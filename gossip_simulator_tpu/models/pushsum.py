"""PushSum numeric gossip: mass averaging as a second model family.

SI infection spreads one bit; this model spreads MASS (Kempe-Dobra-Gehrke
PushSum).  Each node carries a value vector x (length ``-pushsum-dim``)
plus a scalar PushSum weight w (init 1).  Every poll window a live node
keeps ~half its (x, w) mass and pushes the other half, split evenly over
its eligible friend edges, through the SAME mail ring / sharded
all_to_all the SI family uses; delivery folds an associative SUM combine
(ops/mailbox.deposit_sum) instead of first-touch-wins OR.  x_i / w_i
then converges to the network mean of the initial values -- churn-
tolerant averaging, the actor-learner-architectures claim (PAPERS.md).

Fixed-point limb representation -- the load-bearing design choice:
  The repo runs with x64 disabled, and float scatter-adds are not
  associative, so float mass would make trajectories depend on delivery
  order (= shard count).  Mass is therefore 64-bit fixed point
  (FRAC_BITS fractional bits) stored as LIMBS x 16-bit limbs in int32
  columns: integer scatter-adds commute, so S=1 and S=8 produce
  BIT-IDENTICAL states and window sums conserve Sigma x, Sigma w exactly
  (the mass-conservation invariant tests/test_pushsum.py pins).  Limbs
  are kept normalized (< 2^16) between windows; deposits may carry each
  limb up to ~2^16 per arrival, so _normalize's fixed carry sweep is
  safe below ~2^15 arrivals per node per window (slot caps sit far
  under that).

Conservation contract: config.validate rejects -droprate/-crashrate for
pushsum (both destroy mass silently).  Scenario faults are fine: a
crashed node PARKS mass -- it still receives deposits, it just stops
emitting -- and partition-blocked edges are excluded from the share
divisor BEFORE the split, so blocked mass simply stays with the sender.

Convergence metric: per-node relative error |x/w - mean| / |mean|
(max over dims), computed in f32 from the limbs -- identical per node
on every shard layout, and max-reduced, so it is order-independent.
relerr_ppb (clamped at 2e9) rides telemetry as the live max over rows
that can still be averaged (crashed and weight-starved rows excluded --
see metric_rel); eps_tick stamps the first window whose eps-band
population reaches the coverage target (the ticks-to-epsilon
Stats/JSONL surface).  A kout overlay carries an ~e^-k tail of
in-degree-0 nodes that no protocol can average -- nothing ever pushes
to them, their own halving drains their weight to dust -- so a strict
global max would pin at that tail's O(1) error forever, the same reason
SI runs use coverage_target < 1.

Shard invariance of emissions: every random draw is (tick, GLOBAL
id)-keyed off the UNFOLDED base key (rng.OP_PUSHSUM), the same
convention as scenario fault draws -- a shard's rows draw exactly what
the single-device run draws.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gossip_simulator_tpu import scenario as _scen
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models import event
from gossip_simulator_tpu.models.state import (in_flight, init_exch_counts,
                                               msg64_add, msg64_zero)
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32
U32 = jnp.uint32

LIMBS = 4  # 16-bit limbs per fixed-point scalar: 64-bit range
LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1
FRAC_BITS = 24  # weight 1.0 == 2**24
VALUE_BITS = 20  # init values are 20-bit hashes (integer part)


class PushSumState(NamedTuple):
    """Numeric-gossip phase-2 state.  Mirrors EventState's mail-ring and
    scenario leaves (the steppers, checkpointing and in_flight duck-type
    on those names); the SI rumor leaves are replaced by the mass columns
    and the convergence scalars."""

    flags: jnp.ndarray  # uint8[n]   CRASHED bit; RECEIVED mirrors "converged"
    friends: jnp.ndarray  # int32[n, k]
    friend_cnt: jnp.ndarray  # int32[n]
    mass: jnp.ndarray  # int32[n, C]  C = (dim+1)*LIMBS, weight block last
    mail_ids: jnp.ndarray  # int32[ring] packed dst*B + tick-offset
    mail_mass: jnp.ndarray  # int32[ring, C] mass companion rows
    mail_cnt: jnp.ndarray  # int32[1, dw]
    sup_cnt: jnp.ndarray  # int32[1, dw] always 0 (in_flight duck-compat)
    tick: jnp.ndarray  # int32[]
    total_message: jnp.ndarray  # uint32[2] msg64
    total_received: jnp.ndarray  # int32[]  count(converged | crashed)
    total_crashed: jnp.ndarray  # int32[]  always 0 (crashrate rejected)
    mail_dropped: jnp.ndarray  # int32[]  must stay 0 (mass loss otherwise)
    exchange_overflow: jnp.ndarray  # int32[]
    down_since: jnp.ndarray  # int32[n or 1] crash clock (scenario)
    scen_crashed: jnp.ndarray  # int32[]
    scen_recovered: jnp.ndarray  # int32[]
    part_dropped: jnp.ndarray  # int32[]
    heal_repaired: jnp.ndarray  # int32[]
    relerr_ppb: jnp.ndarray  # int32[]  last window's live max rel-err, ppb
    eps_tick: jnp.ndarray  # int32[]  first tick with eps-band count >= target; -1
    # Spatial-telemetry routed-exchange counters (state.init_exch_counts;
    # 1x1 placeholder unless the panels record under S > 1 shards).
    exch_counts: jnp.ndarray  # int32[1, S+2 | 1x1]


# --- geometry ----------------------------------------------------------------
# Window cadence and ring slot layout are the event engine's (B-tick
# windows, dw slots); only the per-slot capacity differs -- pushsum must
# not drop entries (dropped mail is destroyed mass), so the cap is sized
# for the emission volume, not the SI duplicate volume.

batch_ticks = event.batch_ticks
ring_windows = event.ring_windows


def mass_cols(cfg: Config) -> int:
    """int32 columns per node: dim value blocks + 1 weight block."""
    return (cfg.pushsum_dim + 1) * LIMBS


def _src_windows(cfg: Config) -> int:
    """How many distinct emission windows can land in one ring slot: the
    delay span [max(1, delaylow), delayhigh) mapped to window indices."""
    b = batch_ticks(cfg)
    dlow = max(1, cfg.delaylow)
    return max(1, (cfg.delayhigh - 1) // b - dlow // b + 1)


def slot_cap(cfg: Config, n_local: int | None = None) -> int:
    """Per-slot mail capacity.  Every window each live node emits <= k
    lanes, so `n*k*src_windows` is the adversarial zero-loss bound; it is
    clamped to 2*n*k (~8x the uniform-delay expectation n*k/src_windows)
    because the worst case needs every delay draw to agree -- mail_dropped
    stays the audited safety valve (tests assert it is 0)."""
    n = int(n_local) if n_local is not None else cfg.n
    k = cfg.graph_width
    dw = ring_windows(cfg, n_local)
    if cfg.event_slot_cap > 0:
        cap = int(cfg.event_slot_cap)
    else:
        worst = n * k * _src_windows(cfg)
        cap = max(4096, min(worst, 2 * n * k))
    # Flat int32 indexing bound: dw*cap + tail must stay addressable.
    lim = (2 ** 31 - 1 - event._chunk_want(cfg, n_local)) // max(dw, 1)
    return max(256, min(cap, lim))


def drain_chunk(cfg: Config, n_local: int | None = None) -> int:
    return min(slot_cap(cfg, n_local), event._chunk_want(cfg, n_local))


def ring_tail(cfg: Config, n_local: int | None = None) -> int:
    """Slack past the last slot: covers the drain's final dynamic_slice
    window and the append trash cell at flat index dw*cap."""
    return drain_chunk(cfg, n_local)


def ring_len(cfg: Config, n_local: int | None = None) -> int:
    return (ring_windows(cfg, n_local) * slot_cap(cfg, n_local)
            + ring_tail(cfg, n_local))


# --- fixed-point limb arithmetic --------------------------------------------

def _normalize(m3):
    """Carry sweep on (..., LIMBS) int32 limbs.  LIMBS passes reduce any
    post-deposit accumulation (each limb < 2^31) back below 2^16; the top
    limb's carry-out is unreachable by the headroom argument in the module
    docstring."""
    for _ in range(LIMBS):
        carry = m3 >> LIMB_BITS
        m3 = (m3 & LIMB_MASK) + jnp.concatenate(
            [jnp.zeros_like(carry[..., :1]), carry[..., :-1]], axis=-1)
    return m3


def _halve(m3):
    """floor(v/2) on normalized limbs; returns (half, odd) with
    half + half + odd == v (odd is the dropped low bit, shape (...,))."""
    up = jnp.concatenate(
        [m3[..., 1:] & 1, jnp.zeros_like(m3[..., :1])], axis=-1)
    half = (m3 >> 1) | (up << (LIMB_BITS - 1))
    return half, m3[..., 0] & 1


def _div_rows(m3, m):
    """Long division of normalized limbs (n, G, LIMBS) by per-row divisor
    m (n,), high limb first: returns (quotient limbs, remainder (n, G))
    with q*m + r == v exactly.  Safe for m <= 32767 (r*2^16 + limb fits
    int32); graph widths sit far below that."""
    mm = m[:, None]
    r = jnp.zeros(m3.shape[:-1], I32)
    qs = []
    for i in range(LIMBS - 1, -1, -1):
        cur = r * (LIMB_MASK + 1) + m3[..., i]
        q = cur // mm
        r = cur - q * mm
        qs.append(q)
    qs.reverse()
    return jnp.stack(qs, axis=-1), r


_SCALE = tuple(float(2.0 ** (LIMB_BITS * i - FRAC_BITS))
               for i in range(LIMBS))


def _to_float(m3):
    """f32 value of (..., LIMBS) limbs.  Same fixed 4-term reduction on
    every shard layout, so the metric is shard-invariant."""
    return (m3.astype(jnp.float32)
            * jnp.asarray(_SCALE, jnp.float32)).sum(axis=-1)


# --- init values -------------------------------------------------------------
# Per-(seed, gid, dim) 20-bit hash values, implemented twice with
# identical uint32 wraparound semantics: jnp for device init (shard rows
# draw their slice), numpy for the host-side exact true mean.

def _mix32_np(x):
    x = x.astype(np.uint32).copy()
    x ^= x >> np.uint32(16)
    x = x * np.uint32(0x7FEB352D)
    x ^= x >> np.uint32(15)
    x = x * np.uint32(0x846CA68B)
    x ^= x >> np.uint32(16)
    return x


def _mix32_jnp(x):
    x = x ^ (x >> U32(16))
    x = x * U32(0x7FEB352D)
    x = x ^ (x >> U32(15))
    x = x * U32(0x846CA68B)
    return x ^ (x >> U32(16))


def _values_q_host(seed: int, n: int, dim: int) -> np.ndarray:
    """(n, dim) int64 of 20-bit init values q (fixed-point x = q * 2^24)."""
    gid = np.arange(n, dtype=np.uint32)[:, None]
    d = np.arange(dim, dtype=np.uint32)[None, :]
    h = _mix32_np(np.uint32(seed) ^ (gid * np.uint32(0x9E3779B9)))
    h = _mix32_np(h ^ ((d + np.uint32(1)) * np.uint32(0x85EBCA6B)))
    return (h >> np.uint32(32 - VALUE_BITS)).astype(np.int64)


def _values_q_jnp(seed: int, gid, dim: int):
    """(rows, dim) int32 of the same q values for global ids `gid`."""
    g = gid.astype(U32)[:, None]
    d = jnp.arange(dim, dtype=U32)[None, :]
    h = _mix32_jnp(U32(seed) ^ (g * U32(0x9E3779B9)))
    h = _mix32_jnp(h ^ ((d + U32(1)) * U32(0x85EBCA6B)))
    return (h >> U32(32 - VALUE_BITS)).astype(I32)


@functools.lru_cache(maxsize=None)
def _true_means(n: int, dim: int, seed: int) -> tuple:
    sums = _values_q_host(seed, n, dim).sum(axis=0)  # exact int64
    return tuple(float(s) / float(n) for s in sums)


def true_means(cfg: Config) -> tuple:
    """Exact network means of the init values (integer q units) -- baked
    into the metric as compile-time constants."""
    return _true_means(cfg.n, cfg.pushsum_dim, cfg.seed)


def eps_target(cfg: Config) -> int:
    """Eps-band node count at which eps_tick stamps -- the same formula
    the driver's run loop converges on (backends/base.py)."""
    return int(np.ceil(cfg.coverage_target * cfg.n))


def init_mass(cfg: Config, gid0, rows: int):
    """(rows, C) int32 initial mass for global ids [gid0, gid0+rows):
    value blocks q*2^24, weight block 1.0 = 2^24."""
    gid = jnp.asarray(gid0, I32) + jnp.arange(rows, dtype=I32)
    q = _values_q_jnp(cfg.seed, gid, cfg.pushsum_dim)  # (rows, D)
    # q * 2^24 in 16-bit limbs: bits 24..43 -> limb1 low byte + limb2.
    vl = jnp.stack([jnp.zeros_like(q), (q & 0xFF) << 8,
                    (q >> 8) & LIMB_MASK, jnp.zeros_like(q)], axis=-1)
    wl = jnp.zeros((rows, 1, LIMBS), I32).at[:, :, 1].set(1 << (FRAC_BITS
                                                                - LIMB_BITS))
    return jnp.concatenate([vl, wl], axis=1).reshape(rows, mass_cols(cfg))


# --- state -------------------------------------------------------------------

def init_state(cfg: Config, friends: jnp.ndarray, friend_cnt: jnp.ndarray,
               gid0=0, n_shards: int = 1) -> PushSumState:
    n = friends.shape[0]  # local rows: the shard slice under sharded
    z = lambda: jnp.zeros((), I32)  # noqa: E731
    dw = ring_windows(cfg, n)
    return PushSumState(
        flags=jnp.zeros((n,), jnp.uint8),
        friends=friends,
        friend_cnt=friend_cnt,
        mass=init_mass(cfg, gid0, n),
        mail_ids=jnp.zeros((ring_len(cfg, n),), I32),
        mail_mass=jnp.zeros((ring_len(cfg, n), mass_cols(cfg)), I32),
        mail_cnt=jnp.zeros((1, dw), I32),
        sup_cnt=jnp.zeros((1, dw), I32),
        tick=z(), total_message=msg64_zero(), total_received=z(),
        total_crashed=z(), mail_dropped=z(), exchange_overflow=z(),
        down_since=_scen.init_down_since(cfg.faults_enabled, n),
        scen_crashed=z(), scen_recovered=z(), part_dropped=z(),
        heal_repaired=z(),
        relerr_ppb=jnp.full((), 2_000_000_000, I32),
        eps_tick=jnp.full((), -1, I32),
        exch_counts=init_exch_counts(cfg, n_shards),
    )


# --- shared cores (single-device step and the sharded engine both call) -----

STARVE_BITS = 10  # weight < 2^-10: the node is cut off from the mix


def metric_rel(cfg: Config, m3, crashed):
    """Per-node relative error vs the true mean, f32, max over dims,
    clamped to 2.0; crashed rows report 0 (parked mass is 'done' -- the
    convergence count and the live max both want them excluded).

    Returns ``(rel, rep)``.  ``rel`` drives the converged count: a
    weight-STARVED row (an in-degree-0 node, or one walled off by a
    partition, halves its own weight every window with nothing coming
    back) keeps its honest O(1) error and never counts converged.
    ``rep`` is ``rel`` with starved rows zeroed -- the telemetry max
    tracks the population that CAN still be averaged, so relerr_ppb
    actually descends into the eps band instead of pinning at the
    unreachable tail's error (the same reason SI runs use
    coverage_target < 1 on a kout overlay)."""
    dim = cfg.pushsum_dim
    vals = _to_float(m3[:, :dim, :])  # (n, D)
    w_raw = _to_float(m3[:, dim, :])
    w = jnp.maximum(w_raw, jnp.float32(2.0 ** -FRAC_BITS))
    means = jnp.maximum(jnp.abs(jnp.asarray(true_means(cfg), jnp.float32)),
                        jnp.float32(1e-6))
    est = vals / w[:, None]
    rel = (jnp.abs(est - jnp.asarray(true_means(cfg), jnp.float32)[None, :])
           / means[None, :]).max(axis=1)
    rel = jnp.where(crashed, jnp.float32(0.0),
                    jnp.minimum(rel, jnp.float32(2.0)))
    rep = jnp.where(w_raw < jnp.float32(2.0 ** -STARVE_BITS),
                    jnp.float32(0.0), rel)
    return rel, rep


def emit_shares(cfg: Config, m3, crashed, friends, friend_cnt, tick, gids,
                base_key):
    """The PushSum emission: halve, split over eligible edges, return the
    lanes for the engine glue to deliver (append locally or route).

    Eligible edge = in-range, non-padding, sender live, not partition-
    blocked AT SEND TIME -- blocked/dead lanes are excluded BEFORE the
    divisor so their mass share never leaves the sender.  Crashed
    DESTINATIONS still receive (parked mass).  Returns
    (new_m3, share_lanes (n*k, C), dst (n*k,), wslot (n*k,),
    off (n*k,), lane_valid (n*k,), blocked_count)."""
    n, k = friends.shape
    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    scen = cfg.scenario_resolved
    in_range = jnp.arange(k, dtype=I32)[None, :] < friend_cnt[:, None]
    edge = in_range & (friends >= 0) & ~crashed[:, None]
    blk = jnp.zeros((), I32)
    if scen.has_partitions:
        blocked = _scen.partition_blocked(
            scen, cfg.n, tick, gids[:, None], friends) & edge
        blk = blocked.sum(dtype=I32)
        edge = edge & ~blocked
    mdeg = edge.sum(axis=1, dtype=I32)
    emit = ~crashed & (mdeg > 0)
    half, odd = _halve(m3)
    share, rem = _div_rows(half, jnp.maximum(mdeg, 1))
    # kept = ceil(v/2) + division remainder: v == kept + mdeg*share exactly.
    kept = half.at[..., 0].add(odd + rem)
    new_m3 = jnp.where(emit[:, None, None], kept, m3)
    C = m3.shape[1] * LIMBS
    share_lanes = jnp.broadcast_to(
        jnp.where(emit[:, None, None], share, 0).reshape(n, 1, C),
        (n, k, C)).reshape(n * k, C)
    # One shared delay per sender, (tick, GLOBAL id)-keyed off the
    # UNFOLDED base key: shard-count invariant.  batch_ticks guarantees
    # b <= max(1, delaylow), so arrival always lands in a LATER window
    # than the emitting one (its slot is already drained this window).
    tk = _rng.tick_key(base_key, tick, _rng.OP_PUSHSUM)
    delay = _rng.row_uniform_delay(tk, cfg.delaylow, cfg.delayhigh, gids)
    arrive = tick + delay
    wslot = jnp.broadcast_to(((arrive // b) % dw)[:, None], (n, k))
    off = jnp.broadcast_to((arrive % b)[:, None], (n, k))
    lane_valid = (edge & emit[:, None]).reshape(-1)
    dst = jnp.where(edge, friends, 0).reshape(-1)
    return (new_m3, share_lanes, dst, wslot.reshape(-1), off.reshape(-1),
            lane_valid, blk)


# --- single-device engine ----------------------------------------------------

def make_window_step_fn(cfg: Config, n_local: int | None = None):
    """One B-tick window: scenario faults -> drain this window's slot with
    the SUM combine -> normalize -> convergence metric -> emission."""
    from gossip_simulator_tpu.ops.mailbox import deposit_sum, ring_append

    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    cap = slot_cap(cfg, n_local)
    ccap = drain_chunk(cfg, n_local)
    dim = cfg.pushsum_dim
    C = mass_cols(cfg)
    eps = float(cfg.pushsum_eps)
    tgt = eps_target(cfg)
    dkern = cfg.deliver_kernel_resolved
    p2 = cfg.phase2_kernel_resolved

    def step_fn(st: PushSumState, base_key: jax.Array) -> PushSumState:
        n, k = st.friends.shape
        gids = jnp.arange(n, dtype=I32)
        slot = (st.tick // b) % dw
        flags, down, dsc, dsr = event.apply_fault_window_flags(
            cfg, st.flags, st.down_since, st.tick, gids, base_key, b)
        # Drain: sum-deposit every entry due this window.  The packed
        # tick offset (ent % b) orders SI deliveries within the window;
        # sums commute, so only the destination row matters here.
        m = st.mail_cnt[0, slot]
        if p2 == "pallas":
            # Phase-2 megakernel: the whole slot decodes and
            # scatter-adds in ONE pass -- no dynamic-slice chunk
            # round-trips (integer adds commute, so this is
            # bit-identical to any chunking).
            from gossip_simulator_tpu.ops import pallas_megakernel as mk
            mass = mk.fused_drain_sum(st.mass, st.mail_ids, st.mail_mass,
                                      slot, m, cap=cap, b=b)
        else:
            chunks = (m + ccap - 1) // ccap

            def body(j, acc):
                off0 = slot * cap + j * ccap
                ent = jax.lax.dynamic_slice(st.mail_ids, (off0,), (ccap,))
                rows = jax.lax.dynamic_slice(
                    st.mail_mass, (off0, 0), (ccap, C))
                ok = j * ccap + jnp.arange(ccap, dtype=I32) < m
                return deposit_sum(acc, ent // b, rows, ok, kernel=dkern)

            mass = jax.lax.fori_loop(0, chunks, body, st.mass)
        m3 = _normalize(mass.reshape(n, dim + 1, LIMBS))
        crashed = (flags & event.CRASHED) > 0
        rel, rep = metric_rel(cfg, m3, crashed)
        conv = rel <= jnp.float32(eps)
        # RECEIVED mirrors "currently within eps" so the telemetry
        # received column and SI-shaped probes stay meaningful.
        flags = jnp.where(conv, flags | event.RECEIVED,
                          flags & ~event.RECEIVED)
        maxrel = rep.max()
        recv = conv.sum(dtype=I32)
        new_tick = st.tick + b
        eps_tick = jnp.where(
            (st.eps_tick < 0) & (recv >= tgt), new_tick, st.eps_tick)
        new_m3, share, dst, wslot, off, lane_valid, blk = emit_shares(
            cfg, m3, crashed, st.friends, st.friend_cnt, st.tick, gids,
            base_key)
        (mail, mailm), cnt, dropped = ring_append(
            (st.mail_ids, st.mail_mass), st.mail_cnt, st.mail_dropped,
            (dst * b + off, share), wslot, lane_valid, dw, cap,
            kernel=dkern)
        cnt = cnt.at[0, slot].set(0)
        return st._replace(
            flags=flags, down_since=down,
            mass=new_m3.reshape(n, C),
            mail_ids=mail, mail_mass=mailm, mail_cnt=cnt,
            mail_dropped=dropped, tick=new_tick,
            total_message=msg64_add(st.total_message,
                                    lane_valid.sum(dtype=I32)),
            total_received=recv,
            scen_crashed=st.scen_crashed + dsc,
            scen_recovered=st.scen_recovered + dsr,
            part_dropped=st.part_dropped + blk,
            relerr_ppb=(maxrel * jnp.float32(1e9)).astype(I32),
            eps_tick=eps_tick)

    return step_fn


def make_seed_fn(cfg: Config):
    """No-op: pushsum has no rumor injection -- every node's mass exists
    from init and the first window step starts the exchange."""

    def seed_fn(st: PushSumState, base_key: jax.Array) -> PushSumState:
        return st

    return seed_fn


def make_heal_fn(cfg: Config, n_local: int | None = None):
    """Rejoin bookkeeping only (None when heal is off).  The SI heal's
    three waves are ALL deliberately inert for pushsum:

    - edge REPAIR would rewire in-edges away from a temporarily-down node
      permanently: when it reboots nobody pushes to it any more, its own
      emissions halve its (value, weight) down to integer dust and its
      estimate strands at O(1) error even though conservation holds
      (observed as a growing plateau of never-converged nodes under the
      churn timeline).  Parked mass plus the UNCHANGED topology is the
      averaging model's own healing mechanism: mail keeps depositing into
      a crashed node, and on reboot the node pushes the parked mass back
      through the same edges.
    - RE-SEND/PULL waves would emit extra mass and break conservation.

    What remains is consuming the reboot markers apply_fault_window_flags
    leaves in down_since, so detect-dead clocks restart cleanly across
    repeated churn reboots."""
    if not cfg.overlay_heal_resolved:
        return None

    def heal_fn(st: PushSumState, base_key: jax.Array) -> PushSumState:
        clear = _scen.rejoined_mask(st.down_since)
        return st._replace(down_since=jnp.where(clear, -1, st.down_since))

    return heal_fn


def make_window_fn(cfg: Config, window: int):
    step = make_window_step_fn(cfg)
    heal = make_heal_fn(cfg)
    steps = max(1, -(-window // batch_ticks(cfg)))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window_fn(st: PushSumState, base_key: jax.Array) -> PushSumState:
        st = jax.lax.fori_loop(0, steps, lambda _, s: step(s, base_key), st)
        if heal is not None:
            st = heal(st, base_key)
        return st

    return window_fn


def make_run_to_coverage_fn(cfg: Config, telemetry: bool = False):
    """Bounded device-side while_loop to the convergence target, same
    contract as event.make_run_to_coverage_fn.  total_received counts
    converged-or-crashed nodes, so coverage_target means "fraction of
    nodes within eps"."""
    step = make_window_step_fn(cfg)
    heal = make_heal_fn(cfg)
    max_steps = cfg.max_rounds
    steps = event.poll_window_steps(cfg)
    b = batch_ticks(cfg)
    check_in_flight = not cfg.overlay_heal_resolved

    def cond_live(s: PushSumState, target_count, until):
        recv = s.total_received
        live = ((recv < target_count)
                & (s.tick < max_steps) & (s.tick < until))
        if check_in_flight:
            # The ring is empty BEFORE the first emission (seed is a
            # no-op), so the aliveness term only applies past window 0.
            live = live & ((in_flight(s) > 0) | (s.tick < b))
        return live

    def run_window(s: PushSumState, base_key):
        s = jax.lax.fori_loop(0, steps, lambda _, x: step(x, base_key), s)
        if heal is not None:
            s = heal(s, base_key)
        return s

    if telemetry:
        from gossip_simulator_tpu.utils import telemetry as telem

        spatial = telem.spatial_spec(cfg)

        @functools.partial(jax.jit, donate_argnums=(0, 4))
        def run_fn_t(st: PushSumState, base_key: jax.Array,
                     target_count: jax.Array, until: jax.Array,
                     hist: "telem.History"):
            def cond(carry):
                s, _ = carry
                return cond_live(s, target_count, until)

            def body(carry):
                s, h = carry
                s = run_window(s, base_key)
                row = telem.gossip_probe(s, False, relerr=s.relerr_ppb)
                return s, telem.record_window(h, row, st=s, spec=spatial,
                                              relerr=s.relerr_ppb)

            return jax.lax.while_loop(cond, body, (st, hist))

        return run_fn_t

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_fn(st: PushSumState, base_key: jax.Array,
               target_count: jax.Array, until: jax.Array) -> PushSumState:
        def cond(s: PushSumState):
            return cond_live(s, target_count, until)

        return jax.lax.while_loop(cond, lambda s: run_window(s, base_key),
                                  st)

    return run_fn


# --- host-side reporting -----------------------------------------------------

def report(stepper) -> dict:
    """The pushsum result-record payload (driver JSONL): whether the live
    max relative error reached eps, the tick it first did, and the final
    window's max error in ppb."""
    st = stepper.state
    rp, et = (int(v) for v in np.asarray(
        jax.device_get((st.relerr_ppb, st.eps_tick))))
    return {"converged_eps": et >= 0, "eps_ticks": et, "relerr_ppb": rp}
