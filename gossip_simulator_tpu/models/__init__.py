from gossip_simulator_tpu.models.state import SimState, OverlayState
from gossip_simulator_tpu.models import graphs, overlay, epidemic

__all__ = ["SimState", "OverlayState", "graphs", "overlay", "epidemic"]
