"""Struct-of-arrays state pytrees.

The reference's per-node ``Node`` struct (simulator.go:34-46) becomes one
struct-of-arrays over the node axis; every field shards trivially on that
axis for the sharded backend.  Counters live on device (int32 -- safe to
~350M nodes at fanout 5; the reference's int32 atomics have the same bound,
SURVEY §5.5) and are fetched once per progress window.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class SimState(NamedTuple):
    """Epidemic-phase state (phase 2).  Node axis = leading axis of 1-D/2-D
    fields; `pending`/`rebroadcast` are ring buffers over delay ticks."""

    received: jnp.ndarray  # bool[n]   ever infected (simulator.go:38)
    crashed: jnp.ndarray  # bool[n]    (simulator.go:39)
    removed: jnp.ndarray  # bool[n]    SIR only; removed => stops forwarding
    friends: jnp.ndarray  # int32[n, k]  -1-padded adjacency (simulator.go:45)
    friend_cnt: jnp.ndarray  # int32[n]
    pending: jnp.ndarray  # int32[d, n]  arrival counts, ring over ticks
    rebroadcast: jnp.ndarray  # bool[d, n]  SIR re-broadcast schedule
    tick: jnp.ndarray  # int32[]
    total_message: jnp.ndarray  # int32[]  (simulator.go:31)
    total_received: jnp.ndarray  # int32[]  (simulator.go:29)
    total_crashed: jnp.ndarray  # int32[]  (simulator.go:30)
    # Framework-only: cross-shard all_to_all bucket overflow (0 on one chip;
    # counted, never silently lost -- SURVEY §7.3 hard part #4).
    exchange_overflow: jnp.ndarray  # int32[]


class OverlayState(NamedTuple):
    """Overlay-construction state (phase 1).  Message buffers hold the
    makeups/breakups emitted this round, delivered next round (the vectorized
    stand-in for the reference's delayed channel sends, simulator.go:151-164).
    """

    friends: jnp.ndarray  # int32[n, k]
    friend_cnt: jnp.ndarray  # int32[n]
    mk_dst: jnp.ndarray  # int32[n, em]  makeup emissions (dst per slot; src=row)
    bk_dst: jnp.ndarray  # int32[n, eb]  breakup emissions
    round: jnp.ndarray  # int32[]
    makeups: jnp.ndarray  # int32[]  cumulative processed (MakeUps)
    breakups: jnp.ndarray  # int32[]  (BreakUps)
    win_makeups: jnp.ndarray  # int32[]  this round's count
    win_breakups: jnp.ndarray  # int32[]
    mailbox_dropped: jnp.ndarray  # int32[]  capacity overflow (divergence counter)
