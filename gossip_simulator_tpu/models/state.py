"""Struct-of-arrays state pytrees.

The reference's per-node ``Node`` struct (simulator.go:34-46) becomes one
struct-of-arrays over the node axis; every field shards trivially on that
axis for the sharded backend.  Counters live on device and are fetched once
per progress window.

Counter widths (SURVEY §5.5 prescribes int64 where the reference's int32
atomics can overflow, simulator.go:26-31): ``total_received`` /
``total_crashed`` are bounded by n (int32 is safe to n = 2^31), but
``total_message`` counts every delivery and SIR re-broadcasts indefinitely --
at n = 1e8 it crosses 2^31 within a few hundred simulated seconds.  It is
therefore a 64-bit counter, represented as a uint32 ``[hi, lo]`` pair
(``msg64_*`` helpers below) rather than a jnp.int64 scalar: enabling
jax_enable_x64 globally would flip every unannotated jax.random draw to
float64/int64, changing the bit-exact RNG streams the parity tests pin and
dragging emulated-f64 ops onto the TPU hot path.  The pair costs three
scalar ops per accumulation and nothing else.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax.numpy as jnp


def msg64_zero() -> jnp.ndarray:
    """Device-side 64-bit counter: uint32 [hi, lo] = 0."""
    return jnp.zeros((2,), jnp.uint32)


def msg64_add(c: jnp.ndarray, delta) -> jnp.ndarray:
    """c + delta with carry.  `delta` is a nonnegative int32/uint32 scalar
    (per-tick/per-window deltas are bounded by the mail-ring / delay-ring
    per-slot capacities, all sized below 2^31 entries)."""
    d = delta.astype(jnp.uint32)
    lo = c[1] + d
    carry = (lo < d).astype(jnp.uint32)  # uint32 add wraps iff result < d
    return jnp.stack([c[0] + carry, lo])


def msg64_value(c) -> int:
    """Host-side Python int from a fetched [hi, lo] pair (also accepts a
    legacy scalar from pre-widening checkpoints)."""
    a = np.asarray(c)
    if a.ndim == 0:
        return int(a)
    a = a.astype(np.uint64)
    return int((a[0] << np.uint64(32)) | a[1])


class SimState(NamedTuple):
    """Epidemic-phase state (phase 2).  Node axis = leading axis of 1-D/2-D
    fields; `pending`/`rebroadcast` are ring buffers over delay ticks."""

    received: jnp.ndarray  # bool[n]   ever infected (simulator.go:38)
    crashed: jnp.ndarray  # bool[n]    (simulator.go:39)
    removed: jnp.ndarray  # bool[n]    SIR only; removed => stops forwarding
    friends: jnp.ndarray  # int32[n, k]  -1-padded adjacency (simulator.go:45)
    friend_cnt: jnp.ndarray  # int32[n]
    pending: jnp.ndarray  # int32[d, n]  arrival counts, ring over ticks
    rebroadcast: jnp.ndarray  # bool[d, n]  SIR re-broadcast schedule
    tick: jnp.ndarray  # int32[]
    total_message: jnp.ndarray  # uint32[2] hi/lo 64-bit pair (simulator.go:31)
    total_received: jnp.ndarray  # int32[]  (simulator.go:29)
    total_crashed: jnp.ndarray  # int32[]  (simulator.go:30)
    # Framework-only: cross-shard all_to_all bucket overflow (0 on one chip;
    # counted, never silently lost -- SURVEY §7.3 hard part #4).
    exchange_overflow: jnp.ndarray  # int32[]
    # --- fault-injection scenario (scenario.py) --------------------------
    # Crash tick per node (-1 = live / unknown): the recovery clock and the
    # healer's dead-friend detection window.  Full (n,) only when the
    # fault machinery is on (Config.faults_enabled); a 1-element
    # placeholder otherwise, so fault-free runs pay nothing.
    down_since: jnp.ndarray  # int32[n | 1]
    scen_crashed: jnp.ndarray  # int32[]  scenario-crashed (waves + churn)
    scen_recovered: jnp.ndarray  # int32[]  nodes rebooted after downtime
    part_dropped: jnp.ndarray  # int32[]  sends black-holed by partitions
    heal_repaired: jnp.ndarray  # int32[]  dead friend edges replaced
    # --- multi-rumor traffic (Config.multi_rumor) ------------------------
    # Packed per-rumor infection bits (W = ceil(R/32) uint32 words per
    # node) and per-rumor arrival counts over the delay ring.  1-element
    # placeholders when multi_rumor is off, so the default single-rumor
    # build traces no rumor-axis op (the down_since convention).
    pending_rumors: jnp.ndarray  # int32[d, n, R | 1x1x1]  per-rumor arrivals
    rumor_words: jnp.ndarray  # uint32[n, W | 1x1]  per-node rumor bitmask
    rumor_recv: jnp.ndarray  # int32[W*32 | 1]  per-rumor infected count
    rumor_done: jnp.ndarray  # int32[W*32 | 1]  tick rumor hit target (-1)
    # --- spatial telemetry (Config.telemetry_spatial) --------------------
    # Cumulative routed-exchange counters, int32[1, S+2] when the spatial
    # panels are on under S > 1 shards ([:S] delivered sends per dest
    # shard, [S] deliveries received, [S+1] bucket overflow), a 1x1
    # placeholder otherwise (the down_since convention).  Node-axis
    # leading like mail_cnt so shards stack to (S, S+2) under P(AXIS,).
    exch_counts: jnp.ndarray  # int32[1, S+2 | 1x1]


def init_exch_counts(cfg, n_shards: int = 1) -> jnp.ndarray:
    """Per-shard routed-exchange counter leaf (see SimState.exch_counts).
    Full (1, S+2) only when the spatial panels record under a sharded
    run; every other build keeps the 1x1 placeholder so the default
    program traces no counting op."""
    w = (n_shards + 2
         if (cfg.telemetry_spatial_enabled and n_shards > 1) else 1)
    return jnp.zeros((1, w), jnp.int32)


def in_flight(st) -> jnp.ndarray:
    """int32 0/1: nonzero iff any message is still undelivered --
    engine-agnostic (EventState or SimState; duck-typed on the mail ring so
    this module needs no import of either engine).  An indicator, NOT a
    count: every caller only tests emptiness, and a full count would
    overflow int32 when summed across shards near ring occupancy
    (event.slot_cap clamps each shard to ~2^31 entries).  THE single
    definition of "wave still alive": the host exhaustion check
    (backends/base.run_bounded_to_target) and every engine's device-side
    run cond all call this, so they cannot drift."""
    if hasattr(st, "mail_cnt"):
        # sup_cnt: deferred duplicate-suppression credits (EventState) --
        # pending windows must still drain so total_message is credited at
        # the same tick the unsuppressed path would have counted it.
        live = jnp.any(st.mail_cnt > 0)
        if hasattr(st, "sup_cnt"):
            live = live | jnp.any(st.sup_cnt > 0)
        return live.astype(jnp.int32)
    return (jnp.any(st.pending > 0) | jnp.any(st.rebroadcast)).astype(
        jnp.int32)


class OverlayState(NamedTuple):
    """Overlay-construction state (phase 1).  Message buffers hold the
    makeups/breakups emitted this round, delivered next round (the vectorized
    stand-in for the reference's delayed channel sends, simulator.go:151-164).
    """

    friends: jnp.ndarray  # int32[n, k]
    friend_cnt: jnp.ndarray  # int32[n]
    # Slot-major (slots, n) with slots = the mailbox cap EXACTLY: the node
    # axis is minormost (tile-friendly) and the slot count is a multiple
    # of the T(8,128) sublane tile -- (10, 1e8) padded 1.6x to 5.96 GB and
    # broke the 100M single-chip build (round 4).  Bootstrap emissions
    # (one per node per round) live in their own flat vector, delivered
    # after the reply slots -- the same order the (cap+2)-wide layout
    # produced.
    mk_dst: jnp.ndarray  # int32[cap, n]  makeup emissions (dst per slot; src=lane)
    bk_dst: jnp.ndarray  # int32[cap, n]  breakup emissions
    boot_dst: jnp.ndarray  # int32[n]  bootstrap makeups (src=lane)
    # Mailbox-overflow spill: (src, dst) pairs a full mailbox could not
    # take this round, re-delivered FIRST next round -- the reference's
    # channel-full backpressure delays membership traffic, never loses it
    # (simulator.go:51-54).  -1-padded; beyond-spill-capacity messages
    # still fall through to mailbox_dropped (counted, never silent).
    # Filled only on the single-device column-delivery paths (the
    # flagship-scale regime where overflow was ever observed); the
    # sharded hook path keeps counted drops.
    mk_spill: jnp.ndarray  # int32[2, SPILL_CAP(+1)]  overflowed makeups
    bk_spill: jnp.ndarray  # int32[2, SPILL_CAP(+1)]  overflowed breakups
    round: jnp.ndarray  # int32[]
    makeups: jnp.ndarray  # int32[]  cumulative processed (MakeUps)
    breakups: jnp.ndarray  # int32[]  (BreakUps)
    win_makeups: jnp.ndarray  # int32[]  this round's count
    win_breakups: jnp.ndarray  # int32[]
    mailbox_dropped: jnp.ndarray  # int32[]  capacity overflow (divergence counter)
