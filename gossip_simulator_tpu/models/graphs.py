"""Static overlay generators (device-side, O(n*k) memory, no host loops).

The reference only has the dynamic makeup/breakup overlay (simulator.go:66-106);
BASELINE.json configs 3-4 additionally name Erdos-Renyi and
fanout-random graphs, so these are first-class here.  All generators return
``(friends int32[n, k] -1-padded, friend_cnt int32[n])`` with *global* node
ids, generated shard-locally for any contiguous id range [row0, row0+rows) so
the sharded backend can build its slice without materializing the full graph.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from gossip_simulator_tpu import config as config_mod
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.utils import rng as _rng


def _self_patch(picks: jnp.ndarray, ids: jnp.ndarray, n: int) -> jnp.ndarray:
    """Reference's self-collision patch: (id+1)%N (simulator.go:98-100)."""
    return jnp.where(picks == ids, (picks + 1) % n, picks)


def _row_keys(key: jax.Array, row0: int, rows: int) -> jax.Array:
    """One key per *global* row id, so any row slice of the graph is identical
    no matter how the node axis is sharded (shard-consistent generation)."""
    gids = row0 + jnp.arange(rows, dtype=jnp.int32)
    return jax.vmap(lambda r: jax.random.fold_in(key, r))(gids)


def kout(cfg: Config, key: jax.Array, row0: int = 0, rows: int | None = None):
    """k-out random digraph: each node picks `fanout` uniform peers
    (duplicates allowed, like the reference's bootstrap)."""
    n, k = cfg.n, cfg.fanout
    rows = n if rows is None else rows
    if cfg.pallas and isinstance(row0, int):
        # row0 must be a concrete (static) offset: inside shard_map it is a
        # traced axis_index, where we fall through to the fold_in generator
        # (the Pallas path currently serves the single-device backends).
        from gossip_simulator_tpu.ops.pallas_graph import (
            BLOCK_ROWS, kout_pallas)

        # TPU only: in interpret mode (CPU/GPU) pltpu.prng_random_bits is an
        # all-zero stub, which would silently yield a degenerate star graph
        # -- fall through to the fold_in generator there instead.
        if (k <= 128 and row0 % BLOCK_ROWS == 0
                and jax.default_backend() == "tpu"):
            friends = kout_pallas(n, k, row0, rows, cfg.seed, interpret=False)
            return friends, jnp.full((rows,), k, dtype=jnp.int32)
    if cfg.pallas:
        import warnings

        warnings.warn(
            "-pallas requested but the Pallas kout generator is unavailable "
            "here (needs a real TPU backend, fanout <= 128, block-aligned "
            "static row offset -- the sharded backend's traced row0 does not "
            "qualify); using the fold_in generator instead", stacklevel=2)
    ids = (row0 + jnp.arange(rows, dtype=jnp.int32))[:, None]
    keys = _row_keys(key, row0, rows)
    picks = jax.vmap(
        lambda rk: jax.random.randint(rk, (k,), 0, n, dtype=jnp.int32))(keys)
    friends = _self_patch(picks, ids, n)
    return friends, jnp.full((rows,), k, dtype=jnp.int32)


def erdos(cfg: Config, key: jax.Array, row0: int = 0, rows: int | None = None):
    """Sparse directed Erdos-Renyi approximation: out-degree ~ Poisson(n*p)
    (exact G(n,p) is O(n^2); Poisson out-degrees match its sparse limit).
    Slot capacity covers the Poisson upper tail; overflow is clipped (counted
    in degree only, probability ~1e-9 per node at lambda<=32)."""
    n = cfg.n
    rows = n if rows is None else rows
    lam = cfg.er_p_resolved * n
    cap = config_mod.er_cap(lam)
    if cfg.pallas and isinstance(row0, int):
        # Same routing contract as kout: real TPU only (the interpreter's
        # PRNG is a zero stub), static block-aligned row offset, and the
        # kernel's own lam/cap limits (f32 pmf recurrence, 128-lane tile).
        from gossip_simulator_tpu.ops.pallas_graph import (
            BLOCK_ROWS, LANES, erdos_pallas)

        if (0.0 < lam <= 60.0 and cap <= LANES
                and row0 % BLOCK_ROWS == 0
                and jax.default_backend() == "tpu"):
            return erdos_pallas(n, float(lam), row0, rows, cfg.seed,
                                interpret=False)
    if cfg.pallas:
        import warnings

        warnings.warn(
            "-pallas requested but the Pallas erdos generator is "
            "unavailable here (needs a real TPU backend, lam <= 60, "
            "cap <= 128 lanes, block-aligned static row offset); using the "
            "fold_in generator instead", stacklevel=2)
    keys = _row_keys(key, row0, rows)

    def one_row(rk):
        kd, kp = jax.random.split(rk)
        deg = jnp.minimum(jax.random.poisson(kd, lam, ()).astype(jnp.int32), cap)
        picks = jax.random.randint(kp, (cap,), 0, n, dtype=jnp.int32)
        return deg, picks

    deg, picks = jax.vmap(one_row)(keys)
    ids = (row0 + jnp.arange(rows, dtype=jnp.int32))[:, None]
    picks = _self_patch(picks, ids, n)
    slot = jnp.arange(cap, dtype=jnp.int32)[None, :]
    friends = jnp.where(slot < deg[:, None], picks, -1)
    return friends, deg


def ring(cfg: Config, key: jax.Array, row0: int = 0, rows: int | None = None):
    """Ring lattice: node i -> (i+1..i+fanout) mod n.  Deterministic; handy
    as a worst-case-diameter graph for tests."""
    del key
    n, k = cfg.n, cfg.fanout
    rows = n if rows is None else rows
    ids = (row0 + jnp.arange(rows, dtype=jnp.int32))[:, None]
    friends = (ids + jnp.arange(1, k + 1, dtype=jnp.int32)[None, :]) % n
    return friends.astype(jnp.int32), jnp.full((rows,), k, dtype=jnp.int32)


GENERATORS = {"kout": kout, "erdos": erdos, "ring": ring}


def generate(cfg: Config, key: jax.Array, row0: int = 0, rows: int | None = None):
    if cfg.graph == "overlay":
        raise ValueError("dynamic overlay is built by models/overlay.py")
    if cfg.protocol == "pushpull":
        # Anti-entropy draws FRESH uniform peers every round
        # (epidemic.make_pushpull_fn); the static friends table is never
        # gathered, yet at 5e7 x fanout 26 it alone is 5.2 GB -- enough
        # to push the 50M push-pull row off a 16 GB chip.  A one-column
        # placeholder keeps every shape-derived consumer working.
        # Snapshots written BEFORE this placeholder existed carry the old
        # (n, fanout) table; prepare_restore_tree coerces them to this
        # shape on restore (utils/checkpoint.py, advisor r5).
        rows = cfg.n if rows is None else rows
        return (jnp.full((rows, 1), -1, jnp.int32),
                jnp.zeros((rows,), jnp.int32))
    friends, cnt = GENERATORS[cfg.graph](cfg, key, row0, rows)
    return friends, cnt


def graph_key(cfg: Config) -> jax.Array:
    return _rng.tick_key(_rng.base_key(cfg.seed), 0, _rng.OP_GRAPH)
