"""Vectorized dynamic-overlay construction (phase 1).

The reference's membership protocol is inherently sequential per node: an
event loop multiplexing makeup / breakup / need-new-friend mailboxes
(simulator.go:62-106).  The vectorized analog runs the SAME per-message
decision rules, but batched: one "round" delivers last round's messages into
fixed-capacity mailboxes (ops/mailbox.py), then every node processes its
mailbox slots *sequentially across slots, in parallel across nodes* -- a
`fori_loop` over slot index k in which iteration k applies all nodes' k-th
message.  Message emissions (replacement makeups, eviction breakups,
bootstrap makeups) are buffered and delivered next round, standing in for the
reference's delayed channel sends (simulator.go:151-164).

Decision-rule parity (per message, against simulator.go):
* makeup  (simulator.go:66-75): under fanin -> append sender; else evict a
  uniform-random victim (sending it a breakup) and take its slot.
* breakup (simulator.go:76-94): first-match scan; over fanout -> remove
  (swap-with-last here -- order is immaterial because eviction is uniform);
  else replace in place with a fresh random peer (!= self, != leaver) and
  send it a makeup.
* bootstrap (simulator.go:95-106): while under fanout, add one uniform
  random friend per round (self patched as (id+1)%N, duplicates allowed)
  and send a makeup.

This preserves the stationary degree distribution (friend_cnt in
[fanout, max(fanout, fanin)], in-degree concentrated near fanin) rather than
the reference's exact event interleaving -- verified statistically against
the event-driven oracle in tests/test_overlay.py (SURVEY §7.3 hard part #1).

Quiescence is race-free: a round with zero processed AND zero in-flight
messages (the reference's polled check can terminate early, SURVEY §5.2).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models.state import OverlayState
from gossip_simulator_tpu.ops.mailbox import deliver
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32

# Above this row count the rounds engine delivers per COLUMN
# (ops.mailbox.deliver_columns, column-major arrival order) instead of the
# flattened row-major path; below it the per-column machinery is
# op-floor-bound (measured 4x slower at 1M -- make_round_fn's rationale).
# Module-level so a CPU test can lower it and pin the column-major
# trajectory band with a small-n golden (advisor r3: the band was
# otherwise exercisable only by on-TPU runs).
COLUMN_DELIVERY_MIN_ROWS = 4_000_000

# Mailbox-overflow spill capacity (pairs per message type): overflow is the
# in-degree tail past the mailbox cap -- 257 TOTAL messages over 31 rounds
# at the 100M build's cap 8 (r4), so 64k pairs (512 KB) is ~250x the
# largest observed round.  Spilled messages re-deliver first next round
# (the reference's channel-full backpressure: delayed, never lost,
# simulator.go:51-54); past the spill cap they fall back to counted drops.
SPILL_CAP = 65_536


def spill_enabled(cap: int) -> bool:
    """Spill engages only below the full cap 16 -- i.e. the memory-banded
    cap 8 (the ONLY regime that ever dropped: 257 messages at 1e8, r4)
    and explicit tiny test caps.  At cap 16 overflow needs in-degree > 16
    in one round (~1e-12 per node-round at the protocol's Poisson loads;
    never observed), and threading the spill accumulator through every
    delivery chunk costs real op floors (measured +10.6 s on the 27-round
    10M build, 2026-08-01) -- so cap-16 configs keep the counted-drop
    path."""
    return cap < 16


def _poisson_excess(lam: float, cap: int) -> float:
    """E[(X - cap)+] for X ~ Poisson(lam): the expected per-node mailbox
    overflow when a whole wave of uniform sends lands in ONE round.
    Config-time host float; terms summed to a ~10-sigma tail."""
    import math

    p = math.exp(-lam)
    e = 0.0
    for k in range(1, int(lam + 10.0 * math.sqrt(max(lam, 1.0)) + 20)):
        p = p * lam / k
        if k > cap:
            e += (k - cap) * p
    return e


def spill_cap_for(cfg: Config, n_rows: int) -> int:
    """Spill capacity (pairs) for a single-device rounds surface of
    `n_rows` rows; 0 = disabled (spill_enabled).  The static-bootstrap
    band needs burst sizing: the one-shot n*fanout makeup burst lands in
    ONE round, so in-degree is Poisson(fanout) all at once -- at cap 8 /
    fanout 5 that is E[(X-8)+] ~ 0.122 overflow messages per node
    (~12.2M pairs at 1e8, vs the 257 TOTAL the staggered schedule ever
    overflowed), and the round-2 breakup reply wave is bounded by the
    same lambda.  1.6x covers skew; the SPILL_CAP floor covers the
    settled regime.  Spilled pairs are DELAYED one round, never lost --
    the reference's cap-1024 channels absorb the same burst without
    blocking, so the divergence is arrival order only (the documented
    envelope)."""
    cap = cfg.mailbox_cap_for(n_rows)
    if not spill_enabled(cap):
        return 0
    if static_boot_applies(cfg, None):
        margin = _tuning.value("overlay.spill_margin", cfg)
        return SPILL_CAP + int(margin * n_rows
                               * _poisson_excess(float(cfg.fanout), cap))
    return SPILL_CAP


def static_boot_applies(cfg: Config, n_local: int | None,
                        hooked: bool = False) -> bool:
    """Whether the one-shot static bootstrap (config.overlay_static_boot)
    runs on this surface: single-device rounds engine only (the sharded
    hook path's routed init has no burst delivery and its per-shard
    slices sit below the band), and the burst must fit the mailbox-cap
    emission rows (fanout <= cap; always true for auto caps)."""
    n = n_local if n_local is not None else cfg.n
    return ((n_local is None and not hooked)
            and cfg.static_boot_for(cfg.n)
            and cfg.fanout <= cfg.mailbox_cap_for(n))


def init_state(cfg: Config, n_local: int | None = None,
               base_key: jax.Array | None = None) -> OverlayState:
    n = n_local if n_local is not None else cfg.n
    k = cfg.max_degree
    # Per-LOCAL-rows cap: one shard's slice keeps cap 16 far beyond the
    # single-device flat-addressing boundary (config.mailbox_cap_for).
    cap = cfg.mailbox_cap_for(n)
    z = lambda: jnp.zeros((), I32)
    # Emission buffers are SLOT-major (cap, n): the huge node axis is
    # minormost and the slot count tiles T(8,128) exactly (see the
    # OverlayState field comment -- node-major and off-multiple slot
    # counts both padded catastrophically at n=1e8); bootstrap emissions
    # are their own flat vector.
    # Non-spilling configs (spill_enabled) carry token-sized spill fields:
    # the buffers are loop-invariant pass-throughs there, but full-size
    # ones still measurably regressed the bounded phase-1 while_loop
    # (+4.7 s on the 27-round 10M build).  The static-boot band sizes for
    # the one-shot burst's concentrated overflow (spill_cap_for); the
    # sharded hook path (n_local given) never spills and keeps the flat
    # floor so its replicated token fields stay small.
    sc = (spill_cap_for(cfg, n) if n_local is None
          else (SPILL_CAP if spill_enabled(cap) else 0))
    if static_boot_applies(cfg, n_local):
        if base_key is None:
            # make_round_fn skips the per-round bootstrap under the same
            # gate; silently building a burst-less state here would leave
            # the overlay with no bootstrap at all.
            raise ValueError(
                "overlay.init_state: static bootstrap requires base_key")
        # One-shot static bootstrap (round 7; config.overlay_static_boot):
        # draw the whole initial friends table and stage the n*fanout
        # makeup burst as the first `fanout` emission rows, exactly the
        # way overlay_ticks.init_state always has -- the reference's
        # needNewFriend loop re-arms with no delay (simulator.go:103-105),
        # so every node fills all fanout slots at t~0, and once at fanout
        # it can never drop below it (breakup at/under fanout replaces in
        # place; removal only happens above) -- the per-round bootstrap
        # never fires again, and make_round_fn skips it entirely.  Draws
        # are per-LANE keyed (one (n,) column at a time; no (n, fanout)
        # matrix to tile-pad at 1e8), self patched (id+1)%N like every
        # bootstrap draw (simulator.go:97-100).
        f = cfg.fanout
        ids = jnp.arange(n, dtype=I32)
        kb = _rng.tick_key(base_key, 0, _rng.OP_BOOTSTRAP)
        friends = jnp.full((n, k), -1, I32)
        mk_dst = jnp.full((cap, n), -1, I32)
        colsel = jnp.arange(k, dtype=I32)[None, :]
        for j in range(f):
            wj = _rng.row_randint(kb, n, ids * f + j, 1)[:, 0]
            wj = jnp.where(wj == ids, (wj + 1) % n, wj)
            friends = jnp.where(colsel == j, wj[:, None], friends)
            mk_dst = mk_dst.at[j].set(wj)
        cnt = jnp.full((n,), f, I32)
    else:
        friends = jnp.full((n, k), -1, I32)
        cnt = jnp.zeros((n,), I32)
        mk_dst = jnp.full((cap, n), -1, I32)
    return OverlayState(
        friends=friends,
        friend_cnt=cnt,
        mk_dst=mk_dst,
        bk_dst=jnp.full((cap, n), -1, I32),
        boot_dst=jnp.full((n,), -1, I32),
        mk_spill=jnp.full((2, sc + 1), -1, I32),
        bk_spill=jnp.full((2, sc + 1), -1, I32),
        round=z(), makeups=z(), breakups=z(),
        win_makeups=z(), win_breakups=z(), mailbox_dropped=z(),
    )


def delivery_chunk(cfg: Config, n_rows: int) -> int:
    """Delivery-compaction chunk for the overlay mailbox deliver: 64k
    optimum from the v5e full-construction sweep (chunk n: 17.6s,
    131k: 13.2s, 65k: 9.6s, 32k: 11.4s at n=1e6 -- narrow chunks win
    because per-chunk sort/scatter width dominates the extra
    first_true_indices passes of the bootstrap burst); -compact-chunk
    overrides.  Above the n/128 knee (~8.4M rows) the chunk scales as
    n/128 (to 1M): each chunk pays an n-wide compaction scan, so a fixed
    64k chunk is O(n^2/chunk) on burst rows -- ~1526 full-1e8 scans per
    bootstrap row at the 100M build (measured ~87-215 s/round r4;
    n-scaling cuts the scan count 16x).  Chunk size never changes results (ascending
    ranges + rank continuation are bit-identical at any chunk).  Used by
    the ROUNDS engine (and its sharded variant); the tick-faithful
    engine's slot drain has its own scaling
    (overlay_ticks.ticks_delivery_chunk -- its per-chunk cost is
    scatter-floor-bound at GB-scale targets, favoring fat chunks).
    The 65_536 base and 1M cap are registered tunables (tuning.py):
    an explicit -compact-chunk outranks any table entry."""
    if cfg.compact_chunk > 0:
        return cfg.compact_chunk
    base = _tuning.value("overlay.delivery_chunk_base", cfg)
    cap = _tuning.value("overlay.delivery_chunk_cap", cfg)
    return min(n_rows, max(base, n_rows // 128), cap)


# Fattest rung of the adaptive hosted-chunk ladder (hosted_chunk_widths):
# dense burst rows at n=1e8 drop from 128 base-width chunks to 12, each
# chunk's flat scatter/sort paying its fixed cost once -- the scatter into
# the 3.2 GB rank-major mailbox is ~flat per op at GB-scale targets
# (README device-span finding; scripts/profile_overlay.py measures the
# per-width constants), so fewer, fatter chunks win on dense rows exactly
# as they did for the ticks drain (ticks_delivery_chunk).  Bounded so one
# chunk's sort stays well under the watchdog and its operand pair is
# ~64 MB.  Module-level so tests can lower it.
ADAPTIVE_CHUNK_MAX = 8_388_608


def hosted_chunk_widths(cfg: Config, n_rows: int) -> tuple[int, ...]:
    """Occupancy-adaptive chunk-width ladder for the hosted (split-round)
    delivery: geometric x4 rungs from the swept base width
    (delivery_chunk) up to ADAPTIVE_CHUNK_MAX.  Each row picks the
    narrowest rung covering its live count in one chunk -- settled rows
    keep the swept narrow optimum, burst rows take the fat rungs.  Chunk
    width never changes results (deliver's compact_chunk contract), so
    the gate (config.overlay_adaptive_chunks) is pure perf; "off" pins
    the single pre-round-7 width."""
    base = delivery_chunk(cfg, n_rows)
    if not cfg.overlay_adaptive_chunks_resolved:
        return (base,)
    # The module global stays the monkeypatchable default (tests lower
    # it); a tuning-table entry overrides it per platform/band.
    rung_max = _tuning.value("overlay.adaptive_chunk_max", cfg,
                             default=ADAPTIVE_CHUNK_MAX)
    hi = max(base, min(n_rows, rung_max))
    widths = [base]
    while widths[-1] < hi:
        widths.append(min(widths[-1] * 4, hi))
    return tuple(widths)


def _col_onehot(cols, k: int):
    """bool[n, k]: row r's `cols[r]` column.  The friends width k is tiny
    (~6), so per-row column reads/writes are ONE-HOT ELEMENTWISE ops, not
    2-D index gathers/scatters -- which cost a full per-op floor each on
    this platform (~15x slower; see epidemic.deposit_local NOTE).  This
    one change took the 1M overlay round from ~500 to O(100) ms."""
    return jnp.arange(k, dtype=I32)[None, :] == cols[:, None]


def _col_get(arr, cols):
    """arr[rows, cols] via one-hot select (see _col_onehot)."""
    return jnp.where(_col_onehot(cols, arr.shape[1]), arr, 0).sum(
        axis=1, dtype=arr.dtype)


def _col_set(arr, cols, vals, mask=None):
    """arr[rows, cols] = vals (where mask) via one-hot blend."""
    oh = _col_onehot(cols, arr.shape[1])
    if mask is not None:
        oh = oh & mask[:, None]
    return jnp.where(oh, vals[:, None], arr)


def _masked_set(arr, rows, cols, vals, mask):
    """arr[rows, cols] = vals where mask (one-hot blend; `rows` must be
    the dense arange -- true for every caller)."""
    del rows
    return _col_set(arr, cols, vals, mask)


def process_breakup_slot(n, fanout, friends, cnt, src, has, ids, kk):
    """One mailbox slot of breakup decisions for ALL nodes in parallel
    (simulator.go:76-94): first-match scan; over fanout -> remove
    (swap-with-last); else replace in place with a fresh random peer
    (!= self, != leaver) to whom a makeup must be sent.

    Shared by the round engine and the tick-faithful engine
    (models/overlay_ticks.py) so the decision rules can never diverge.
    Returns (friends, cnt, reply_dst, reply_mask): send makeup to
    reply_dst where reply_mask."""
    k = friends.shape[1]
    in_range = jnp.arange(k, dtype=I32)[None, :] < cnt[:, None]
    match = (friends == src[:, None]) & in_range & has[:, None]
    found = match.any(axis=1)
    pos = jnp.argmax(match, axis=1).astype(I32)  # first match
    over = cnt > fanout
    rm = has & found & over
    rp = has & found & ~over
    nf = _rng.randint_excluding(kk, n, (cnt.shape[0],), src, ids)
    lastpos = jnp.maximum(cnt - 1, 0)
    lastval = _col_get(friends, lastpos)
    posval = jnp.where(rm, lastval,
                       jnp.where(rp, nf, _col_get(friends, pos)))
    friends = _col_set(friends, pos, posval)
    friends = _col_set(friends, lastpos,
                       jnp.full(cnt.shape, -1, I32), rm)
    cnt = cnt - rm.astype(I32)
    return friends, cnt, nf, rp


def process_makeup_slot(fanin, friends, cnt, src, has, kk):
    """One mailbox slot of makeup decisions (simulator.go:66-75): accept
    under fanin, else evict a uniform-random existing friend (to whom a
    breakup must be sent) and take its slot.  Shared like
    process_breakup_slot.  Returns (friends, cnt, victim_dst,
    victim_mask)."""
    k = friends.shape[1]
    under = cnt < fanin
    app = has & under
    appcol = jnp.minimum(cnt, k - 1)
    friends = _col_set(friends, appcol, src, app)
    cnt = cnt + app.astype(I32)
    ev = has & ~under
    vpos = jax.random.randint(kk, cnt.shape, 0, jnp.maximum(cnt, 1),
                              dtype=I32)
    victim = _col_get(friends, vpos)
    friends = _col_set(friends, vpos, src, ev)
    return friends, cnt, victim, ev


def process_breakup_slot_pallas(n, fanout, friends, cnt, src, has, ids, kk):
    """process_breakup_slot via the fused phase-1 kernel
    (ops/pallas_overlay_kernel.fused_negotiate): same signature, same
    draw stream (randint_excluding computed XLA-side on the identical
    key), same return contract.  The kernel's reply is already
    where(rp, nf, -1) and nf >= 0 always, so the returned (nf, rp) pair
    -- (reply, reply >= 0) -- reproduces the callers'
    where(rp, nf, -1) / rp.sum() blends bit-for-bit."""
    from gossip_simulator_tpu.ops import pallas_overlay_kernel as _pok
    nf = _rng.randint_excluding(kk, n, (cnt.shape[0],), src, ids)
    friends, cnt, reply = _pok.fused_negotiate(
        friends, cnt, src, has, nf, kind="breakup", limit=fanout)
    return friends, cnt, reply, reply >= 0


def process_makeup_slot_pallas(fanin, friends, cnt, src, has, kk):
    """process_makeup_slot via the fused phase-1 kernel.  The eviction
    position is drawn with the PRE-append counts -- observably identical
    to the XLA path's post-append draw because accept (has & under) and
    evict (has & ~under) are disjoint per row and non-evicting rows'
    draws never escape the where(ev, ...) blend.  Evicted victims are
    in-range friends (>= 0), so (reply, reply >= 0) reproduces the
    callers' where(ev, victim, -1) / ev.sum() blends bit-for-bit."""
    from gossip_simulator_tpu.ops import pallas_overlay_kernel as _pok
    vpos = jax.random.randint(kk, cnt.shape, 0, jnp.maximum(cnt, 1),
                              dtype=I32)
    friends, cnt, reply = _pok.fused_negotiate(
        friends, cnt, src, has, vpos, kind="makeup", limit=fanin)
    return friends, cnt, reply, reply >= 0


def phase1_slot_fns(cfg: Config):
    """(breakup_slot_fn, makeup_slot_fn) for cfg's -phase1-kernel gate --
    the single seam both engines (make_round_fn here, overlay_ticks'
    make_step_fn) and their sharded wrappers select through, so the gate
    can never fork between them.  Resolving the gate here also surfaces
    the explicit `-phase1-kernel pallas` unavailability error at model
    BUILD time, not mid-trace."""
    if cfg.phase1_kernel_resolved == "pallas":
        return process_breakup_slot_pallas, process_makeup_slot_pallas
    return process_breakup_slot, process_makeup_slot


def heal_dead_friends(n_global: int, friends, friend_cnt, detected_global,
                      healer_ok, ids_global, heal_key):
    """Phase-2 re-entry of the bootstrap/needNewFriend draw
    (simulator.go:95-106): every live node replaces friends its failure
    detector has condemned with a fresh uniform random peer, self patched
    ``(id+1) % N`` exactly like the phase-1 bootstrap.  Vectorized over
    the whole (n, k) table at once -- the overlay's makeup *decision* is
    what re-runs here; the reciprocal fanin-side accept (the target
    adding the healer back, simulator.go:66-75) is not simulated, a
    documented divergence (README "Fault model & scenarios").

    `detected_global` is the full-axis bool[n_global] detector verdict
    (the sharded caller all_gathers its local verdicts first); draws are
    row-keyed on GLOBAL ids, so a shard's slice heals bit-identically to
    the single-device run.  The fresh draw is uniform and may itself land
    on a dead node (the reference's draws have no global liveness oracle
    either); a dead pick is condemned again next detection window.
    Returns (friends', dead_mask, repaired_count_local)."""
    k = friends.shape[1]
    in_range = jnp.arange(k, dtype=I32)[None, :] < friend_cnt[:, None]
    valid = in_range & (friends >= 0)
    dead = detected_global.at[jnp.maximum(friends, 0)].get() \
        & valid & healer_ok[:, None]
    w = _rng.row_randint(heal_key, n_global, ids_global, k)
    w = jnp.where(w == ids_global[:, None], (w + 1) % n_global, w)
    friends = jnp.where(dead, w, friends)
    return friends, dead, dead.sum(dtype=I32)


def make_round_fn(cfg: Config,
                  deliver_fn=None,
                  ids_fn=None,
                  sum_fn=None, n_rows: int | None = None,
                  ) -> Callable[[OverlayState, jax.Array], OverlayState]:
    """Build the per-round transition.

    The three hooks make the same body run single-device or per-shard inside
    shard_map (parallel/sharded_step.py):
      deliver_fn(src, dst, valid, cap) -> (mbox int32[n_local, cap], dropped)
          -- plain local mailbox delivery by default; routed all_to_all
             delivery when sharded.
      ids_fn() -> global ids of the local rows (arange(n) by default).
      sum_fn(x) -> global scalar reduction (identity by default; psum sharded).
    `n_rows` (local rows; defaults to cfg.n) sizes the mailbox cap -- it
    must match init_state's n_local so the emission widths agree.
    """
    n = cfg.n
    k = cfg.max_degree
    fanout, fanin = cfg.fanout, cfg.fanin_resolved
    cap = cfg.mailbox_cap_for(n_rows if n_rows is not None else n)
    # Phase-1 megakernel gate: swap the shared slot closures (and the
    # bootstrap block below) for their fused forms.  Sharded callers pass
    # the same cfg, so shard_map bodies inherit the gate automatically.
    bk_slot_fn, mk_slot_fn = phase1_slot_fns(cfg)
    p1_pallas = bk_slot_fn is process_breakup_slot_pallas
    # One-shot bootstrap (round 7): init_state staged the burst, so the
    # per-round bootstrap block is skipped -- must agree with init_state's
    # gate or the overlay would never bootstrap at all.
    static_boot = static_boot_applies(cfg, n_rows,
                                      hooked=deliver_fn is not None)
    # Mailboxes come back either 2-D (n, cap) or FLAT rank-major
    # (cap*n + 1; slot r contiguous at [r*n, (r+1)*n)) -- the large-n
    # path never materializes the (n, cap) shape, whose narrow minor dim
    # TPU tile layouts pad to 128 lanes (s32[1e8, 8] -> 51 GB, the
    # round-4 compile OOM).  `_slot(mbox, r)` reads slot r either way.
    flat_mbox = False
    if deliver_fn is None:
        # Emission lists are mostly empty once membership settles: compact
        # before the delivery sort (chunk sweep: see delivery_chunk).
        dchunk = delivery_chunk(cfg, n)
        from gossip_simulator_tpu.ops.mailbox import (deliver_columns,
                                                      flat_addressing_fits)

        dkern = cfg.deliver_kernel_resolved
        sc_band = spill_cap_for(cfg, n)
        if n > COLUMN_DELIVERY_MIN_ROWS and flat_addressing_fits(n, cap):
            # Per-SLOT delivery: same entries at ~1/slots the compaction
            # scan width (deliver_columns' rationale; the flattened form
            # was 84% of the round at 10M nodes: 42.5 -> 25.3 s there).
            # Arrival order becomes slot-major.  Below ~4M rows the
            # per-slot machinery is op-floor-bound (34 slots x
            # ceil-per-slot chunks measured 4x SLOWER at 1M) and the
            # flattened node-major path stays -- the canonical arrival
            # order is size-banded, deterministic per config, and pinned
            # by the goldens at small n.  This path SPILLS overflow into
            # (src, dst) pairs re-delivered first next round instead of
            # dropping (SPILL_CAP; lossless membership delivery).
            flat_mbox = True

            def deliver_matrix_fn(mats, cap, dep=None, spill_in=None):
                carry = None
                if dep is not None:
                    # Sequence this delivery's buffer allocations after
                    # `dep` so they reuse the previous delivery's dead
                    # buffers (see _dep_full).
                    carry = (_dep_full((n * cap + 1,), -1, dep),
                             _dep_full((n + 1,), 0, dep),
                             jnp.zeros((), I32))
                if sc_band == 0:
                    out = deliver_columns(mats, n, cap, dchunk, flat=True,
                                          carry=carry, kernel=dkern)
                    return out + (None,)
                acc = (jnp.full((2, sc_band + 1), -1, I32),
                       jnp.zeros((), I32))
                mbox, load, dropped, (pairs, _) = deliver_columns(
                    mats, n, cap, dchunk, flat=True, carry=carry,
                    spill_in=spill_in, spill=acc, kernel=dkern)
                return mbox, load, dropped, pairs
        else:
            # Small-n path, and past the flat-addressing boundary the
            # flattened path's dense 2-D fallback + one-time warning.
            # Slot-major flatten, matching the per-slot path's arrival
            # order exactly (sender = flat_idx % n) -- the canonical
            # order no longer changes across the size band.  No spill:
            # at cap 16 (every n in this band) overflow needs in-degree
            # > 16 in one round -- never observed; drops stay counted.
            def deliver_matrix_fn(mats, cap, dep=None, spill_in=None):
                flat = jnp.concatenate(mats, axis=0).reshape(-1)
                mbox, cnt, dropped = deliver(None, flat, flat >= 0, n, cap,
                                             compact_chunk=dchunk,
                                             src_mod=n, kernel=dkern)
                return mbox, cnt.max(initial=0), dropped, None
    else:
        # Hook supplied (the sharded backend's routed delivery): keep its
        # flattened (src, dst, valid) contract; the ids broadcast is only
        # materialized on this path.  Slot-major flatten (the emission
        # buffers' native layout; transposing at shard scale would
        # materialize the padded node-major shape).
        def deliver_matrix_fn(mats, cap, dep=None, spill_in=None):
            matc = jnp.concatenate(mats, axis=0)
            flat = matc.reshape(-1)
            ids_b = jnp.broadcast_to(ids_fn()[None, :],
                                     matc.shape).reshape(-1)
            mbox, dropped = deliver_fn(ids_b, flat, flat >= 0, cap)
            return mbox, (mbox >= 0).sum(axis=1, dtype=I32).max(initial=0), \
                dropped, None
    if ids_fn is None:
        ids_fn = lambda: jnp.arange(n, dtype=I32)
    if sum_fn is None:
        sum_fn = lambda x: x

    def _slot(mbox, r):
        """Mailbox slot r for every node: contiguous dynamic_slice on the
        flat rank-major layout, column read on the 2-D one.  Keyed on the
        array itself (ndim), not the size band: the split round's hosted
        delivery hands the pieces a flat mailbox at ANY n."""
        if mbox.ndim == 1:
            return jax.lax.dynamic_slice(mbox, (r * n,), (n,))
        return mbox[:, r]

    def _dep_full(shape, fill, dep):
        """Constant fill whose ALLOCATION is sequenced after `dep`: a
        plain jnp.full lowers to broadcast(constant), which XLA hoists to
        program start -- at n=1e8 that made every multi-GB buffer of the
        round co-live (both mailboxes + both emission buffers, 19.5 GB on
        a 15.75 GB chip).  Mixing a computed scalar in keeps the buffer's
        live range where the dataflow says it starts, letting it reuse a
        dead predecessor's allocation."""
        return jnp.broadcast_to(jnp.int32(fill) + dep * jnp.int32(0), shape)

    # --- the four round pieces -------------------------------------------
    # Factored so the fused round_fn and the memory-scale split variant
    # (make_split_round_fn: one jitted call PER PIECE) run the exact same
    # closures -- only the jit boundary moves.

    def p_bk_deliver(bk_dst, bk_spill):
        """Deliver last round's BREAKUP emissions (the overflow spill
        pairs first -- delayed messages arrive before this round's)."""
        return deliver_matrix_fn((bk_dst,), cap, spill_in=bk_spill)

    def p_bk_process(friends, cnt, bk_mbox, n_bk, drop2, round_, base_key):
        """Process the breakup mailbox (simulator.go:76-94), emitting
        replacement makeups into mk_em.  Also returns mk_cnt int32[cap]:
        each emission slot's entry count, recorded AT WRITE TIME (one
        scalar reduction per processed slot) -- the round-7 dead-row mask
        the hosted delivery consumes next round instead of popcounting
        every n-wide row (dead in the fused round; XLA drops it)."""
        ids = ids_fn()  # GLOBAL ids of local rows (identity comparisons)
        rkey = jax.random.fold_in(base_key, round_)
        # mk_em allocates after the bk delivery (see _dep_full).
        mk_em = _dep_full((cap, ids.shape[0]), -1, drop2)
        win_bk = jnp.zeros((), I32)
        mk_cnt = jnp.zeros((cap,), I32)

        def bk_body(slot, carry):
            friends, cnt, mk_em, win_bk, mk_cnt = carry
            src = _slot(bk_mbox, slot)
            has = src >= 0
            kk = jax.random.fold_in(
                jax.random.fold_in(rkey, _rng.OP_REPLACE), slot)
            friends, cnt, nf, rp = bk_slot_fn(
                n, fanout, friends, cnt, src, has, ids, kk)
            mk_em = mk_em.at[slot].set(jnp.where(rp, nf, -1))
            mk_cnt = mk_cnt.at[slot].set(rp.sum(dtype=I32))
            return friends, cnt, mk_em, win_bk + has.sum(dtype=I32), mk_cnt

        # Slot loops run to the MAX mailbox load this round (n_mk/n_bk from
        # the delivery), not the fixed capacity: slots are rank-contiguous,
        # so everything past a node's count is -1 (a no-op slot), and
        # typical max load is ~ln n/ln ln n << cap.  Local data-dependent
        # trip counts are fine under shard_map: the bodies contain no
        # collectives.
        return jax.lax.fori_loop(
            0, n_bk, bk_body, (friends, cnt, mk_em, win_bk, mk_cnt))

    def p_mk_deliver(mk_dst, boot_dst, mk_spill, friends, cnt, win_bk):
        """Deliver the MAKEUP emissions (the breakup mailbox is dead by
        now -- holding both ~3 GB mailboxes alive broke the 16 GB chip at
        n=1e8; sequencing is bit-identical since the deliveries are
        data-independent).  Spilled makeups first, then replies, then
        bootstrap makeups as one extra slot row AFTER the replies -- the
        same order the old (cap+2)-wide buffer delivered.  The
        optimization_barrier keeps XLA from hoisting this above the
        breakup processing in the fused form."""
        mk_src, boot_src, friends, cnt = jax.lax.optimization_barrier(
            (mk_dst, boot_dst, friends, cnt))
        mk_mbox, n_mk, drop1, mk_sp = deliver_matrix_fn(
            (mk_src, boot_src[None, :]), cap, dep=win_bk,
            spill_in=mk_spill)
        return mk_mbox, n_mk, drop1, friends, cnt, mk_sp

    def p_mk_process(mk_mbox, n_mk, drop1, drop2, friends, cnt, mk_em,
                     win_bk, round_, makeups0, breakups0, dropped0,
                     base_key, mk_sp=None, bk_sp=None,
                     spill0=None, mk_cnt=None, aux=False):
        """Process the makeup mailbox (simulator.go:66-75), bootstrap
        (simulator.go:95-106) and assemble the next state.

        With `aux` (the split round), also returns the round-7 dead-row
        bookkeeping: (mk_cnt, bk_cnt, boot_cnt, quiesced) -- per-slot
        emission counts recorded at write time (exactly the sums
        pending_emissions would reduce out of the (cap, n) buffers) plus
        the quiescence flag computed from them, so the split loop's
        per-round eager quiesced() never touches the multi-GB masks."""
        ids = ids_fn()
        n_local = ids.shape[0]
        rows = jnp.arange(n_local, dtype=I32)  # LOCAL row indices
        rkey = jax.random.fold_in(base_key, round_)
        bk_em = _dep_full((cap, n_local), -1, win_bk)
        dropped = dropped0 + sum_fn(drop1 + drop2)
        win_mk = jnp.zeros((), I32)
        bk_cnt = jnp.zeros((cap,), I32)

        def mk_body(slot, carry):
            friends, cnt, bk_em, win_mk, bk_cnt = carry
            src = _slot(mk_mbox, slot)
            has = src >= 0
            kk = jax.random.fold_in(
                jax.random.fold_in(rkey, _rng.OP_EVICT), slot)
            friends, cnt, victim, ev = mk_slot_fn(
                fanin, friends, cnt, src, has, kk)
            bk_em = bk_em.at[slot].set(jnp.where(ev, victim, -1))
            bk_cnt = bk_cnt.at[slot].set(ev.sum(dtype=I32))
            return friends, cnt, bk_em, win_mk + has.sum(dtype=I32), bk_cnt

        friends, cnt, bk_em, win_mk, bk_cnt = jax.lax.fori_loop(
            0, n_mk, mk_body, (friends, cnt, bk_em, win_mk, bk_cnt))

        if static_boot:
            # One-shot bootstrap at init (init_state's burst): cnt >=
            # fanout is invariant from round 0 -- breakup at/under fanout
            # replaces in place and removal only happens above it -- so
            # the per-round `under` mask is all-False forever and the
            # whole draw/append/emit block is dead weight (an n-wide
            # randint + 4 elementwise passes per round at 1e8).  Skipping
            # it is EXACTLY identical, not just statistically.
            boot_em = jnp.full((n_local,), -1, I32)
            boot_cnt = jnp.zeros((), I32)
        else:
            # --- bootstrap: one friend per round while under fanout --------
            kb = jax.random.fold_in(rkey, _rng.OP_BOOTSTRAP)
            w = jax.random.randint(kb, (n_local,), 0, n, dtype=I32)
            w = jnp.where(w == ids, (w + 1) % n, w)
            if p1_pallas:
                # Fused needNewFriend pass: append + emission + the
                # write-time count in one traversal (the draw stays
                # XLA-side above, so the stream is untouched).
                from gossip_simulator_tpu.ops import \
                    pallas_overlay_kernel as _pok
                friends, cnt, boot_em, boot_cnt = _pok.fused_request_round(
                    friends, cnt, w, fanout=fanout)
            else:
                under = cnt < fanout
                appcol = jnp.minimum(cnt, k - 1)
                friends = _masked_set(friends, rows, appcol, w, under)
                cnt = cnt + under.astype(I32)
                boot_em = jnp.where(under, w, -1)
                boot_cnt = under.sum(dtype=I32)

        # Global reductions (psum when sharded): window counts feed both the
        # progress lines and the quiescence predicate, so they must be the
        # global sums the reference's atomics would show (simulator.go:224-230).
        win_mk = sum_fn(win_mk)
        win_bk = sum_fn(win_bk)
        # Spill pass-through: non-spilling delivery paths return None and
        # the state keeps its (always-empty) buffers; `spill0` supplies
        # them as an (mk, bk) tuple.
        mk_spill = mk_sp if mk_sp is not None else spill0[0]
        bk_spill = bk_sp if bk_sp is not None else spill0[1]
        st = OverlayState(
            friends=friends, friend_cnt=cnt, mk_dst=mk_em, bk_dst=bk_em,
            boot_dst=boot_em, mk_spill=mk_spill, bk_spill=bk_spill,
            round=round_ + 1,
            makeups=makeups0 + win_mk, breakups=breakups0 + win_bk,
            win_makeups=win_mk, win_breakups=win_bk,
            mailbox_dropped=dropped,
        )
        if not aux:
            return st
        # Counts == the emission-mask sums by construction (every slot row
        # is where(mask, value>=0, -1), so entries == mask trues; rows past
        # the trip count keep their zero), making this EXACTLY
        # overlay.quiesced(st) without the (cap, n) reductions.
        mk_sp_live = (mk_spill[1] >= 0).sum(dtype=I32)
        bk_sp_live = (bk_spill[1] >= 0).sum(dtype=I32)
        pending = (mk_cnt.sum(dtype=I32) + bk_cnt.sum(dtype=I32)
                   + boot_cnt + mk_sp_live + bk_sp_live)
        q = ((win_mk == 0) & (win_bk == 0) & (pending == 0)
             & (st.round > 0))
        return st, (mk_cnt, bk_cnt, boot_cnt, mk_sp_live, bk_sp_live, q)

    def round_fn(st: OverlayState, base_key: jax.Array) -> OverlayState:
        bk_mbox, n_bk, drop2, bk_sp = p_bk_deliver(st.bk_dst, st.bk_spill)
        friends, cnt, mk_em, win_bk, _mk_cnt = p_bk_process(
            st.friends, st.friend_cnt, bk_mbox, n_bk, drop2, st.round,
            base_key)
        mk_mbox, n_mk, drop1, friends, cnt, mk_sp = p_mk_deliver(
            st.mk_dst, st.boot_dst, st.mk_spill, friends, cnt, win_bk)
        return p_mk_process(
            mk_mbox, n_mk, drop1, drop2, friends, cnt, mk_em, win_bk,
            st.round, st.makeups, st.breakups, st.mailbox_dropped, base_key,
            mk_sp=mk_sp, bk_sp=bk_sp,
            spill0=(st.mk_spill, st.bk_spill))

    # make_split_round_fn's seam.
    round_fn.pieces = (p_bk_deliver, p_bk_process, p_mk_deliver,
                       p_mk_process)
    return round_fn


# Above this many rows the single-device rounds engine runs each round as
# FOUR jitted calls (make_split_round_fn): one fused round holds ~19.5 GB
# at n=1e8 (donated state is reserved for the whole call, so the delivery
# temps cannot reuse it, and the co-live temp set fragments badly) while
# the split pieces each hold one multi-GB temp and free dead buffers at
# every call boundary via donation (~13 GB peaks).  Module-level so a CPU
# test can lower it and pin split == fused.
SPLIT_ROUND_MIN_ROWS = 32_000_000


def make_split_round_fn(cfg: Config):
    """One overlay round as a HOST-driven sequence of bounded device
    calls (see SPLIT_ROUND_MIN_ROWS).  Bit-identical to the fused
    round_fn: the two slot-processing phases jit the SAME piece closures,
    and the two deliveries run ops.mailbox.make_hosted_column_delivery --
    the same chunk body as deliver_columns, with the chunk loop split
    across watchdog-bounded calls (one fused burst delivery is > the
    ~10 s axon kill line at n=1e8).  Every call donates its array
    arguments and the driver drops dead references + fences between
    calls, so each phase's multi-GB buffers are retired before the next
    arena is allocated (a fused round reserved everything at once and
    peaked at 19.5 GB on the 15.75 GB chip)."""
    from gossip_simulator_tpu.ops.mailbox import make_hosted_column_delivery

    fused = make_round_fn(cfg)
    _, p_bk_process, _, p_mk_process = fused.pieces
    n = cfg.n
    cap = cfg.mailbox_cap_for(n)
    dead_skip = cfg.overlay_dead_skip_resolved
    sc_split = spill_cap_for(cfg, n)
    hosted_deliver = make_hosted_column_delivery(
        n, cap, hosted_chunk_widths(cfg, n), spill_cap=sc_split,
        kernel=cfg.deliver_kernel_resolved,
        occupancy=cfg.phase1_kernel_resolved)

    # bk_mbox is not donated for the same reason as b2_fn's mk_mbox (no
    # same-shaped output to alias; liveness frees it after the slot loop).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def a2_fn(friends, cnt, bk_mbox, n_bk, drop2, round_, base_key):
        return p_bk_process(friends, cnt, bk_mbox, n_bk, drop2, round_,
                            base_key)

    # mk_mbox is NOT donated (advisor r4: the flat (n*cap+1) mailbox has
    # no same-shaped output to alias, so donating it only produced the
    # "donated buffers were not usable" warning -- at n=1e8 it is freed
    # by liveness right after the slot loop either way); friends/cnt/
    # mk_em/spills all alias same-shaped state outputs.
    @functools.partial(jax.jit, donate_argnums=(4, 5, 6, 13, 14),
                       static_argnums=(16,))
    def b2_fn(mk_mbox, n_mk, drop1, drop2, friends, cnt, mk_em, win_bk,
              round_, makeups0, breakups0, dropped0, base_key, mk_sp,
              bk_sp, mk_cnt, aux):
        return p_mk_process(mk_mbox, n_mk, drop1, drop2, friends, cnt,
                            mk_em, win_bk, round_, makeups0, breakups0,
                            dropped0, base_key, mk_sp=mk_sp, bk_sp=bk_sp,
                            mk_cnt=mk_cnt, aux=aux)

    fence_jit = jax.jit(lambda x: x + 1)
    reshape_boot = jax.jit(lambda b: b[None, :])

    def fence():
        """Full host<->worker round trip.  On the axon platform,
        block_until_ready alone does not reliably get the previous call's
        donated/dead buffers retired before the next call's arena is
        allocated -- probed at n=1e8 (2026-07-31): the identical call
        sequence wedges the worker with RESOURCE_EXHAUSTED without this
        fence and passes with it, repeatably.  Cost: one tiny cached jit
        + scalar transfer per phase, noise against seconds of device
        work at split scale."""
        jax.device_get(fence_jit(jnp.int32(1)))

    # Round-7 dead-row bookkeeping carried ACROSS rounds on the host: the
    # totals describe the state's emission buffers (counted at write time
    # inside b2/a2), so round r's deliveries skip last round's dead rows
    # without popcounting them, and the quiescence flag arrives as one
    # scalar instead of an eager multi-GB mask reduction.  None until the
    # first full round (and after a checkpoint restore, which builds a
    # fresh round fn): those rounds pay the popcount fallback once.
    carry = {"mk": None, "bk": None, "boot": None, "round": None,
             "mk_sp": None, "bk_sp": None}

    def round4(st: OverlayState | list, base_key) -> OverlayState:
        # Drop every dead reference before the next call: buffers whose
        # Python refs linger stay allocated on this platform, and the
        # arenas pile up into the OOM the split exists to avoid.  Callers
        # pass the state in a one-element list ("box"): popping it leaves
        # NO outer frame holding the old state through the round (a
        # caller's `self.ostate = round(self.ostate, ...)` binding
        # otherwise keeps all 9.6 GB alive).
        if isinstance(st, list):
            st = st.pop()
        friends, cnt = st.friends, st.friend_cnt
        mk_dst, boot_dst = st.mk_dst, st.boot_dst
        bk_dst = st.bk_dst
        mk_spill0, bk_spill0 = st.mk_spill, st.bk_spill
        round_, mk0, bk0, d0 = (st.round, st.makeups, st.breakups,
                                st.mailbox_dropped)
        del st
        if dead_skip and carry["mk"] is not None and (
                carry["round"] != int(jax.device_get(round_))):
            # The incoming state is not the one the totals describe (a
            # restored snapshot fed to a live round fn): stale totals
            # would silently skip live rows -- fall back to popcounts.
            carry["mk"] = carry["bk"] = carry["boot"] = None
        known = dead_skip and carry["mk"] is not None
        bk_totals = carry["bk"] if dead_skip else None
        mk_totals = carry["mk"] + [carry["boot"]] if known else None
        if sc_split > 0:
            # An empty spill's re-delivery is a no-op that still pays one
            # full-spill-width sort (at the static-boot band the buffer
            # is burst-sized, ~19M pairs at 1e8) -- skip it when the
            # carried EXACT live count says there is nothing in flight.
            bk_spin = None if (known and carry["bk_sp"] == 0) else bk_spill0
            bk_mbox, n_bk, drop2, bk_sp = hosted_deliver(
                (bk_dst,), spill_in=bk_spin, row_totals=bk_totals)
        else:
            bk_mbox, n_bk, drop2 = hosted_deliver((bk_dst,),
                                                  row_totals=bk_totals)
            bk_sp = bk_spill0  # always-empty pass-through
        del bk_dst, bk_spill0
        fence()
        friends, cnt, mk_em, win_bk, mk_cnt = a2_fn(
            friends, cnt, bk_mbox, n_bk, drop2, round_, base_key)
        del bk_mbox
        jax.block_until_ready(friends)
        fence()
        if sc_split > 0:
            mk_spin = None if (known and carry["mk_sp"] == 0) else mk_spill0
            mk_mbox, n_mk, drop1, mk_sp = hosted_deliver(
                (mk_dst, reshape_boot(boot_dst)), spill_in=mk_spin,
                row_totals=mk_totals)
        else:
            mk_mbox, n_mk, drop1 = hosted_deliver(
                (mk_dst, reshape_boot(boot_dst)), row_totals=mk_totals)
            mk_sp = mk_spill0
        del mk_dst, boot_dst, mk_spill0
        fence()
        out = b2_fn(mk_mbox, n_mk, drop1, drop2, friends, cnt, mk_em,
                    win_bk, round_, mk0, bk0, d0, base_key, mk_sp, bk_sp,
                    mk_cnt, dead_skip)
        del mk_mbox, friends, cnt, mk_em, mk_sp, bk_sp, mk_cnt
        if dead_skip:
            out, (a_mk, a_bk, a_boot, a_msp, a_bsp, q) = out
            jax.block_until_ready(out.friends)
            # One small transfer per round (cap-sized vectors + scalars),
            # riding the sync the split already pays.
            a_mk, a_bk, a_boot, a_msp, a_bsp, q, rnd = jax.device_get(
                (a_mk, a_bk, a_boot, a_msp, a_bsp, q, out.round))
            carry["mk"] = [int(v) for v in a_mk]
            carry["bk"] = [int(v) for v in a_bk]
            carry["boot"] = int(a_boot)
            carry["mk_sp"] = int(a_msp)
            carry["bk_sp"] = int(a_bsp)
            carry["round"] = int(rnd)
            round4.last_quiesced = bool(q)
        else:
            jax.block_until_ready(out.friends)
        fence()
        return out

    round4.last_quiesced = None
    return round4


def use_split_round(cfg: Config, n_rows: int | None = None) -> bool:
    """Single-device rounds engine at memory scale (the sharded hook path
    keeps the fused round: its per-shard slices sit far below the band).
    Bounded above by flat int32 mailbox addressing (the hosted delivery
    is rank-major flat with no dense fallback); past that (~2.7e8 rows
    at cap 8) the state alone exceeds a single chip's HBM anyway --
    shard the node axis."""
    from gossip_simulator_tpu.ops.mailbox import flat_addressing_fits

    rows = n_rows if n_rows is not None else cfg.n
    return (rows >= SPLIT_ROUND_MIN_ROWS
            and flat_addressing_fits(rows, cfg.mailbox_cap_for(rows)))


class OverlayResult(NamedTuple):
    friends: jnp.ndarray
    friend_cnt: jnp.ndarray
    rounds: int
    makeups: int
    breakups: int
    mailbox_dropped: int


def pending_emissions(st: OverlayState) -> jnp.ndarray:
    # Spilled overflow pairs are in-flight messages (delivered next
    # round): quiescing while any remain would lose them.
    return ((st.mk_dst >= 0).sum(dtype=I32) + (st.bk_dst >= 0).sum(dtype=I32)
            + (st.boot_dst >= 0).sum(dtype=I32)
            + (st.mk_spill[1] >= 0).sum(dtype=I32)
            + (st.bk_spill[1] >= 0).sum(dtype=I32))


def quiesced(st: OverlayState) -> jnp.ndarray:
    """Zero processed this round AND zero in flight (race-free version of
    simulator.go:221-234).  The round counter guards round 0 (nothing has
    happened yet)."""
    return ((st.win_makeups == 0) & (st.win_breakups == 0)
            & (pending_emissions(st) == 0) & (st.round > 0))


def run_call_budget(cfg: Config, shards: int = 1) -> int:
    """Rounds per bounded overlay_run_to_quiescence device call (see
    overlay_ticks.run_call_budget for the watchdog calibration); a round
    here costs ~0.2 us/node, half the ticks-mode window.  `shards`
    scales the budget for a mesh backend (per-call device work tracks
    the per-SHARD slice) -- it multiplies BEFORE the >=1 clamp so large
    n keeps the ratio instead of collapsing to 1*shards."""
    return max(1, min(1024, int(4e7 * shards // max(cfg.n, 1))))


def make_bounded_run(round_fn, quiesced_fn, telemetry: bool = False):
    """Bounded phase-1 device loop: up to `max_polls` windows per call,
    early exit at quiescence, returning (st, polls_run, quiesced) -- the
    flag rides the loop carry so callers need no eager host-side
    quiesced() recompute (pending_emissions reduces the full (n, cap)-
    sized emission buffers; at large n that is an un-jitted multi-kernel
    dispatch).  THE one harness behind overlay.make_run_fn,
    overlay_ticks.make_run_fn and the sharded backend's fast path
    (whose round_fn is the shard_map'd poll -- its quiescence counters
    are psum-replicated on the outer state, so the condition is
    mesh-uniform).  Trajectory-identical to the windowed host loop:
    round keys are state-indexed (st.round / st.tick), not
    call-indexed.

    With `telemetry` the loop carries a device-resident per-window History
    (utils/telemetry.py), recording (clock, win_makeups, win_breakups,
    dropped) after every poll -- one probe works for every overlay engine
    because the sharded poll psum-replicates its window counters.  The
    signature gains a trailing `hist` argument and the return becomes
    (st, polls, quiesced, hist)."""
    import functools

    if telemetry:
        from gossip_simulator_tpu.utils import telemetry as telem

        @functools.partial(jax.jit, donate_argnums=(0, 3))
        def run_fn_t(st, base_key, max_polls, hist):
            def body(carry):
                st, polls, _, h = carry
                st = round_fn(st, base_key)
                h = telem.record(h, telem.overlay_probe(st))
                return st, polls + 1, quiesced_fn(st), h

            def cond(carry):
                st, polls, q, _ = carry
                return (polls < max_polls) & ~q

            return jax.lax.while_loop(
                cond, body, (st, jnp.zeros((), I32), quiesced_fn(st), hist))

        return run_fn_t

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_fn(st, base_key, max_polls):
        def body(carry):
            st, polls, _ = carry
            st = round_fn(st, base_key)
            return st, polls + 1, quiesced_fn(st)

        def cond(carry):
            st, polls, q = carry
            return (polls < max_polls) & ~q

        return jax.lax.while_loop(
            cond, body, (st, jnp.zeros((), I32), quiesced_fn(st)))

    return run_fn


def make_run_fn(cfg: Config, telemetry: bool = False):
    """Bounded device-side run for the rounds engine (make_bounded_run)."""
    return make_bounded_run(make_round_fn(cfg), quiesced, telemetry=telemetry)
