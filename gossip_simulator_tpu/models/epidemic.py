"""Epidemic-phase step kernels (the measured hot path).

The reference's per-message scalar receive path (simulator.go:107-123) and
goroutine broadcast (simulator.go:140-149) become one fused array program per
simulated tick:

    drain ring slot -> count deliveries -> crash draw -> infect (idempotent)
    -> gather friends of new infections -> drop mask -> scatter-add future
    arrivals into the delay ring.

Time semantics ("ticks" mode): 1 tick == 1 simulated ms.  Every broadcast
draws ONE shared delay uniform in [delaylow, delayhigh) ticks -- exactly the
reference's RandomNetworkDelay applied once per broadcast goroutine
(simulator.go:141-142) -- and each link send has an independent drop draw
(simulator.go:144).  Messages sit in a ``pending[d, n]`` ring buffer of
arrival *counts* so duplicate deliveries are counted like the reference's
TotalMessage (simulator.go:111) while infection stays an idempotent OR.

"rounds" mode is the classic synchronous-epidemic accounting: every hop takes
exactly one round (ring depth 2).

Documented divergence: when c messages reach a node in the same tick, the
crash draw fires with p = 1-(1-p)^c and all c messages are counted; the
reference processes the channel serially, so messages queued behind an
earlier crash-triggering one go uncounted (simulator.go:108-116).
Distributionally negligible for small p; exact for c=1.
"""

from __future__ import annotations

import functools
import math
from typing import Callable

import jax
import jax.numpy as jnp

from gossip_simulator_tpu import scenario as _scen
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models.state import (SimState, in_flight,
                                               init_exch_counts,
                                               msg64_add, msg64_zero)
from gossip_simulator_tpu.ops.select import first_true_indices  # noqa: F401  (re-export: compaction callers import it from here)
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32
SEED_TICK = 0x7FFFFFFF  # reserved "tick" for the one-off seed draws (fold_in needs uint32)


def ring_depth(cfg: Config) -> int:
    """Delay ring slots: delays are clamped to [1, delayhigh) so `delayhigh`
    slots suffice; rounds mode needs only {this, next}."""
    return cfg.delayhigh if cfg.effective_time_mode == "ticks" else 2


def p_eff(cfg: Config, p: float) -> float:
    """Reference's 1%-resolution truncation under compat (simulator.go:172,180)."""
    return int(p * 100) / 100.0 if cfg.compat_reference else p


def init_rumor_leaves(cfg: Config, n: int):
    """(pending_rumors, rumor_words, rumor_recv, rumor_done) -- full-size
    under Config.multi_rumor, placeholders otherwise (the down_since
    convention).  The ring engine stores per-rumor arrival counts over the
    R axis directly (int32[d, n, R]) -- a scatter-ADD exists where a
    scatter-OR does not, and R <= 1024 (validate) bounds the ring."""
    if not cfg.multi_rumor:
        return (jnp.zeros((1, 1, 1), I32), jnp.zeros((1, 1), jnp.uint32),
                jnp.zeros((1,), I32), jnp.full((1,), -1, I32))
    w = cfg.rumor_word_count
    return (jnp.zeros((ring_depth(cfg), n, cfg.rumors), I32),
            jnp.zeros((n, w), jnp.uint32),
            jnp.zeros((w * 32,), I32), jnp.full((w * 32,), -1, I32))


def unpack_rumor_bits(words: jnp.ndarray, r: int) -> jnp.ndarray:
    """uint32 (n, W) word ladder -> bool (n, r) per-rumor bits."""
    n, w = words.shape
    bits = ((words[:, :, None]
             >> jnp.arange(32, dtype=jnp.uint32)[None, None, :])
            & jnp.uint32(1)).astype(bool).reshape(n, w * 32)
    return bits[:, :r]


def pack_rumor_bits(bits: jnp.ndarray, w: int) -> jnp.ndarray:
    """bool (n, r) per-rumor bits -> uint32 (n, W) word ladder."""
    n, r = bits.shape
    padded = jnp.pad(bits, ((0, 0), (0, w * 32 - r)))
    return (padded.reshape(n, w, 32).astype(jnp.uint32)
            << jnp.arange(32, dtype=jnp.uint32)[None, None, :]).sum(
        axis=2, dtype=jnp.uint32)


def init_state(cfg: Config, friends: jnp.ndarray, friend_cnt: jnp.ndarray,
               n_local: int | None = None, n_shards: int = 1) -> SimState:
    n = n_local if n_local is not None else cfg.n
    d = ring_depth(cfg)
    d_rb = d if cfg.protocol == "sir" else 1
    z = lambda: jnp.zeros((), I32)
    pending_rumors, rumor_words, rumor_recv, rumor_done = init_rumor_leaves(
        cfg, n)
    return SimState(
        received=jnp.zeros((n,), bool),
        crashed=jnp.zeros((n,), bool),
        removed=jnp.zeros((n,), bool),
        friends=friends,
        friend_cnt=friend_cnt,
        pending=jnp.zeros((d, n), I32),
        rebroadcast=jnp.zeros((d_rb, n), bool),
        tick=z(), total_message=msg64_zero(), total_received=z(),
        total_crashed=z(),
        exchange_overflow=z(),
        down_since=_scen.init_down_since(cfg.faults_enabled, n),
        scen_crashed=z(), scen_recovered=z(), part_dropped=z(),
        heal_repaired=z(),
        pending_rumors=pending_rumors, rumor_words=rumor_words,
        rumor_recv=rumor_recv, rumor_done=rumor_done,
        exch_counts=init_exch_counts(cfg, n_shards),
    )


def row_slot(cfg: Config, delay_key, tick, rows):
    """Delay-ring slot for each row's broadcast this tick.  Row-keyed
    (utils/rng.row_keys): row r's shared per-broadcast delay
    (simulator.go:141-142) depends only on (delay_key, r), so the compaction
    path can draw delays for just the gathered sender rows and land on
    exactly the dense path's values."""
    d = ring_depth(cfg)
    if cfg.effective_time_mode == "rounds":
        return jnp.broadcast_to((tick + 1) % d, rows.shape).astype(I32)
    delay = _rng.row_uniform_delay(delay_key, cfg.delaylow, cfg.delayhigh,
                                   rows)
    return ((tick + delay) % d).astype(I32)


def tick_keys(base_key: jax.Array, tick, shard: jax.Array | int | None = None):
    """Per-tick op keys; `shard` (axis index) decorrelates shards in the
    sharded backend."""
    if shard is not None:
        base_key = jax.random.fold_in(base_key, shard)
    return {
        "crash": _rng.tick_key(base_key, tick, _rng.OP_CRASH),
        "delay": _rng.tick_key(base_key, tick, _rng.OP_DELAY),
        "drop": _rng.tick_key(base_key, tick, _rng.OP_DROP),
        "remove": _rng.tick_key(base_key, tick, _rng.OP_REMOVE),
    }


def tick_core(cfg: Config, st: SimState, keys: dict):
    """The node-local physics of one tick -- everything except delivering the
    outgoing wave: drain ring slot, count, crash draw, infect, SIR removal /
    re-broadcast scheduling, shared-delay draw.

    Shard-agnostic: arrays may be the full node axis or one shard of it.
    Returns ``(st_partial, senders, dslot, deltas)`` where `st_partial` has
    everything updated except `pending` additions from the new wave, `senders`
    marks local rows broadcasting this tick, `dslot` is their target ring slot
    and `deltas = (d_message, d_received, d_crashed)` are LOCAL sums (callers
    psum them across shards before adding to the replicated totals).

    Multi-rumor (Config.multi_rumor; SI, single-device only -- validate):
    per-rumor arrivals drain from ``pending_rumors`` alongside the total
    counts, a node's NEW bits (arrived, not crashed, not yet held) fold
    into its rumor_words, `senders` becomes any-new-bit (an
    already-infected node gaining a new rumor re-broadcasts), and the
    return gains a trailing ``newbits`` bool (n, R) -- the payload the
    caller deposits (deposit_rumors).  The 4-tuple return is unchanged
    when multi is off.
    """
    sir = cfg.protocol == "sir"
    multi = cfg.multi_rumor
    crash_p = p_eff(cfg, cfg.crashrate)
    d = ring_depth(cfg)
    n = st.received.shape[0]
    ids = jnp.arange(n, dtype=I32)

    slot = st.tick % d
    arrivals = st.pending[slot]
    pending = st.pending.at[slot].set(0)
    counted = jnp.where(st.crashed, 0, arrivals)  # black-hole, uncounted
    d_message = counted.sum(dtype=I32)
    has = counted > 0

    if crash_p > 0.0:
        pc = 1.0 - jnp.power(1.0 - crash_p, counted.astype(jnp.float32))
        new_crash = (jax.random.uniform(keys["crash"], (n,)) < pc) & has
    else:
        new_crash = jnp.zeros((n,), bool)
    crashed = st.crashed | new_crash
    d_crashed = new_crash.sum(dtype=I32)
    if cfg.faults_enabled and crash_p > 0.0:
        # Reception crashes stamp the crash clock too: under a scenario
        # every crash is subject to the reboot timeline (scenario.py's
        # "machines reboot" model) and to the healer's detection window.
        st = st._replace(down_since=jnp.where(
            new_crash, st.tick.astype(I32), st.down_since))

    newly = has & ~crashed & ~st.received
    received = st.received | newly
    d_received = newly.sum(dtype=I32)

    newbits = None
    if multi:
        arr_r = st.pending_rumors[slot]  # (n, R) per-rumor arrival counts
        pending_r = st.pending_rumors.at[slot].set(0)
        rbits = unpack_rumor_bits(st.rumor_words, cfg.rumors)
        newbits = (arr_r > 0) & ~crashed[:, None] & ~rbits
        rumor_words = st.rumor_words | pack_rumor_bits(
            newbits, cfg.rumor_word_count)
        rumor_recv = st.rumor_recv + jnp.pad(
            newbits.sum(axis=0, dtype=I32),
            (0, st.rumor_recv.shape[0] - cfg.rumors))
        st = st._replace(pending_rumors=pending_r, rumor_words=rumor_words,
                         rumor_recv=rumor_recv)

    # Dense per-row delay slots are only materialized when something consumes
    # them for all n rows (SIR's re-broadcast scheduling, the dense
    # delivery path, or the always-dense multi-rumor deposit); the compact
    # SI path draws slots per gathered row.
    if sir or multi or not cfg.compact_resolved:
        dslot = row_slot(cfg, keys["delay"], st.tick, ids)
    else:
        dslot = None

    if sir:
        due = st.rebroadcast[slot] & ~crashed & ~st.removed
        rb = st.rebroadcast.at[slot].set(False)
        senders = newly | due
        removal = _rng.bernoulli(keys["remove"], p_eff(cfg, cfg.removal_rate),
                                 (n,)) & senders
        removed = st.removed | removal
        rb = rb.at[dslot, ids].max(senders & ~removal)
    else:
        rb = st.rebroadcast
        senders = newbits.any(axis=1) if multi else newly
        removed = st.removed

    st_partial = st._replace(
        received=received, crashed=crashed, removed=removed, pending=pending,
        rebroadcast=rb, tick=st.tick + 1)
    if multi:
        return st_partial, senders, dslot, (d_message, d_received,
                                            d_crashed), newbits
    return st_partial, senders, dslot, (d_message, d_received, d_crashed)


def apply_fault_window(cfg: Config, st: SimState, ids_global, base_key,
                       nticks: int = 1):
    """Apply the scenario's crash/churn/recovery timeline to a SimState for
    the window [st.tick, st.tick + nticks) (scenario.fault_window; the ring
    engine steps per tick, nticks=1).  Returns ``(st, d_crash, d_recover)``
    with the masks applied but the replicated counters NOT yet updated --
    the sharded caller psums the deltas first.  A no-op (st unchanged,
    Python zeros) when the scenario has no fault events, so the traced
    program is untouched at ``-scenario off``."""
    scen = cfg.scenario_resolved
    if not scen.has_faults:
        return st, 0, 0
    new_crash, recover, down, dc, drc = _scen.fault_window(
        scen, cfg.n, st.tick, nticks, ids_global, st.crashed,
        st.down_since, base_key)
    crashed = (st.crashed & ~recover) | new_crash
    return st._replace(crashed=crashed, down_since=down), dc, drc


def edges_from_senders(cfg: Config, friends, friend_cnt, senders, dslot,
                       drop_key, tick=None, gid0=0):
    """Flatten this tick's outgoing wave into (dst_global, dslot, valid) flat
    arrays -- the message list the delivery layer (local scatter or
    cross-shard all_to_all route) consumes.  Per-link drop draw happens here
    (simulator.go:144), row-keyed so the compact path samples identically;
    the shared per-broadcast delay came in via dslot.

    `tick`/`gid0` feed the scenario partition mask (send-time semantics:
    scenario.partition_blocked); the fourth return is the count of edges it
    black-holed (a Python 0 when no partitions are configured, so the
    -scenario off trace is unchanged).  `gid0` is the global id of local
    row 0 (nonzero on the sharded backend's shards)."""
    n, k = friends.shape
    rows = jnp.arange(n, dtype=I32)
    drop = _rng.row_bernoulli(drop_key, p_eff(cfg, cfg.droprate), rows, k)
    edge = (jnp.arange(k, dtype=I32)[None, :] < friend_cnt[:, None]) \
        & senders[:, None] & ~drop & (friends >= 0)
    scen = cfg.scenario_resolved
    blocked_n = 0
    if scen.has_partitions and tick is not None:
        blocked = _scen.partition_blocked(
            scen, cfg.n, tick, (gid0 + rows)[:, None], friends) & edge
        blocked_n = blocked.sum(dtype=I32)
        edge = edge & ~blocked
    dst = jnp.where(edge, friends, -1).reshape(-1)
    slots = jnp.broadcast_to(dslot[:, None], (n, k)).reshape(-1)
    return dst, slots, edge.reshape(-1), blocked_n


def compact_chunk_cap(cfg: Config, n_local: int) -> int:
    """Static sender-compaction chunk size.  In ticks mode the per-tick wave
    is spread over the delay window; n/128 keeps the per-chunk gather small
    (first_true_indices touches cap x blk elements) with the chunked loop
    absorbing peak ticks; rounds mode processes everything at once."""
    if cfg.compact_chunk > 0:
        return min(n_local, cfg.compact_chunk)
    if cfg.effective_time_mode == "rounds":
        return n_local
    return min(n_local, max(4096, n_local // 128))


def compact_gather(cfg: Config, friends, friend_cnt, dslot, delay_key,
                   drop_key, tick, remaining, cap, gid0=0):
    """Pull the next <=cap sender rows out of `remaining` and return their
    edge list (dst, slot, valid) plus the updated remaining mask and the
    scenario-partition block count (Python 0 with no partitions -- see
    edges_from_senders).  Fill rows (index n) gather as invalid.  Drop
    masks and delay slots are row-keyed (utils/rng.row_keys), drawn here
    for just the gathered rows -- bit-identical to the dense path's draws
    for the same rows (tested)."""
    n, k = friends.shape
    idx = first_true_indices(remaining, cap)
    hit = jnp.zeros((n,), bool).at[idx].set(True, mode="drop")
    remaining = remaining & ~hit
    sf = friends.at[idx].get(mode="fill", fill_value=-1)
    scnt = friend_cnt.at[idx].get(mode="fill", fill_value=0)
    # Fill rows draw junk (row id n) but their edges are already invalid.
    sdrop = _rng.row_bernoulli(drop_key, p_eff(cfg, cfg.droprate), idx, k)
    if dslot is not None:
        sslot = dslot.at[idx].get(mode="fill", fill_value=0)
    else:
        sslot = row_slot(cfg, delay_key, tick, idx)
    edge = (jnp.arange(k, dtype=I32)[None, :] < scnt[:, None]) \
        & ~sdrop & (sf >= 0)
    scen = cfg.scenario_resolved
    blocked_n = 0
    if scen.has_partitions:
        # Same send-time predicate as the dense path, on just the gathered
        # rows (fill rows' edges are already invalid).
        blocked = _scen.partition_blocked(
            scen, cfg.n, tick, (gid0 + idx)[:, None], sf) & edge
        blocked_n = blocked.sum(dtype=I32)
        edge = edge & ~blocked
    dst = jnp.where(edge, sf, -1).reshape(-1)
    slots = jnp.broadcast_to(sslot[:, None], (cap, k)).reshape(-1)
    return dst, slots, edge.reshape(-1), remaining, blocked_n


def deposit_compact(cfg: Config, pending, friends, friend_cnt,
                    senders, dslot, delay_key, drop_key, tick):
    """Compacted equivalent of edges_from_senders + deposit_local: only
    actual sender rows reach the RNG, gather and scatter.  Row-keyed draws
    keep the trajectory bit-identical to the dense path (tested).  Returns
    ``(pending, partition_blocked_count)`` -- the count is a Python 0 (and
    the loop carry is untouched) when no partitions are configured."""
    n, k = friends.shape
    cap = compact_chunk_cap(cfg, n)
    dkern = cfg.deliver_kernel_resolved
    count = senders.sum(dtype=I32)
    chunks = (count + cap - 1) // cap
    if cfg.scenario_resolved.has_partitions:
        def body_p(_, carry):
            pending, remaining, blk = carry
            dst, slots, valid, remaining, b = compact_gather(
                cfg, friends, friend_cnt, dslot, delay_key, drop_key,
                tick, remaining, cap)
            return deposit_local(pending, dst, slots, valid,
                                 kernel=dkern), remaining, blk + b

        pending, _, blk = jax.lax.fori_loop(
            0, chunks, body_p, (pending, senders, jnp.zeros((), I32)))
        return pending, blk

    def body(_, carry):
        pending, remaining = carry
        dst, slots, valid, remaining, _ = compact_gather(
            cfg, friends, friend_cnt, dslot, delay_key, drop_key, tick,
            remaining, cap)
        return deposit_local(pending, dst, slots, valid,
                             kernel=dkern), remaining

    pending, _ = jax.lax.fori_loop(0, chunks, body, (pending, senders))
    return pending, 0


def deposit_local(pending, dst_local, slots, valid, kernel="xla"):
    """Scatter arrivals into the pending ring (idempotent counting add;
    duplicates accumulate like the reference's per-message channel sends).

    NOTE: keep the 2-D scatter with per-axis OOB drop.  A flat 1-D variant
    (index = slot * n + dst, invalid -> d*n) is ~5x faster in isolation but
    on the axon TPU stack the OOB-drop of the flattened index was observed
    being ignored inside the jitted tick (every edge delivered, drops
    bypassed -- TPU canary in the verify skill catches it); the 2-D form is
    the one proven correct there.  kernel="pallas" routes to the fused
    serial add (ops/pallas_deliver.fused_deposit_add) whose in-range check
    replaces the scatter's OOB-drop explicitly -- integer adds commute, so
    it is bit-identical (and immune to that miscompile class by
    construction)."""
    n = pending.shape[1]
    dst = jnp.where(valid, dst_local, n)  # out of bounds -> mode="drop"
    if kernel == "pallas":
        from gossip_simulator_tpu.ops import pallas_deliver
        return pallas_deliver.fused_deposit_add(pending, slots, dst)
    return pending.at[slots, dst].add(1, mode="drop")


def deposit_rumors(pending_rumors, dst_local, slots, valid, newbits,
                   kernel="xla"):
    """Multi-rumor companion to deposit_local: each kept edge adds its
    sender's NEW rumor bits (one-hot int rows) into the destination's
    (slot, dst) per-rumor lane.  Same 2-D leading-index scatter form as
    deposit_local (see the axon NOTE there); the R axis rides as the
    scatter's trailing window dimension.  kernel="pallas" applies the
    whole R-row add in-register at the shared (slot, dst) cell
    (fused_deposit_rows) -- the multi-rumor combine rides the fused pass
    for free."""
    n, r = newbits.shape
    k = dst_local.shape[0] // n
    vals = jnp.broadcast_to(newbits[:, None, :].astype(I32),
                            (n, k, r)).reshape(n * k, r)
    dst = jnp.where(valid, dst_local, pending_rumors.shape[1])
    if kernel == "pallas":
        from gossip_simulator_tpu.ops import pallas_deliver
        return pallas_deliver.fused_deposit_rows(
            pending_rumors, slots, dst, vals)
    return pending_rumors.at[slots, dst].add(vals, mode="drop")


def make_tick_fn(cfg: Config) -> Callable[[SimState, jax.Array], SimState]:
    """Single-device per-tick transition for SI / SIR push gossip."""

    # NOTE: do NOT wrap this in a lax.cond "skip empty ticks" fast path.
    # On the axon TPU platform, lax.cond whose taken branch contains the
    # dynamic-trip-count chunk fori_loop, nested inside the window fori_loop,
    # miscompiles: every gathered chunk row scatters regardless of validity
    # (observed at n=2e5: pending gained cap*k counts per tick and the
    # epidemic stalled).  Root-caused 2026-07-30; the skip also measured no
    # wall-clock win (empty slots are rare once delays spread the wave).
    multi = cfg.multi_rumor
    dkern = cfg.deliver_kernel_resolved
    p2 = cfg.phase2_kernel_resolved if multi else "xla"
    if multi:
        target = int(math.ceil(cfg.coverage_target * cfg.n))

    def tick_fn(st: SimState, base_key: jax.Array) -> SimState:
        st, dsc, dsr = apply_fault_window(
            cfg, st, jnp.arange(st.received.shape[0], dtype=I32), base_key)
        keys = tick_keys(base_key, st.tick)
        if multi:
            # Always-dense delivery: the compact gather has no per-rumor
            # payload channel, and the multi configs are single-device
            # (validate) where the dense path is the proven form.
            stp, senders, dslot, (dm, dr, dc), newbits = tick_core(
                cfg, st, keys)
            dst, slots, valid, blk = edges_from_senders(
                cfg, stp.friends, stp.friend_cnt, senders, dslot,
                keys["drop"], tick=st.tick)
            if p2 == "pallas":
                # Phase-2 megakernel: the counting add and the R-row
                # rumor add land at the shared (slot, dst) cell in ONE
                # joint pass (integer adds commute -> bit-identical to
                # the sequential pair below).
                from gossip_simulator_tpu.ops import pallas_megakernel \
                    as mk
                pending, prum = mk.fused_deposit_both(
                    stp.pending, stp.pending_rumors, dst, slots, valid,
                    newbits)
                stp = stp._replace(pending_rumors=prum)
            else:
                pending = deposit_local(stp.pending, dst, slots, valid,
                                        kernel=dkern)
                stp = stp._replace(pending_rumors=deposit_rumors(
                    stp.pending_rumors, dst, slots, valid, newbits,
                    kernel=dkern))
            hit = (stp.rumor_recv >= target) & (stp.rumor_done < 0)
            stp = stp._replace(rumor_done=jnp.where(
                hit, stp.tick, stp.rumor_done))
        else:
            stp, senders, dslot, (dm, dr, dc) = tick_core(cfg, st, keys)
            if cfg.compact_resolved:
                pending, blk = deposit_compact(
                    cfg, stp.pending, stp.friends, stp.friend_cnt, senders,
                    dslot, keys["delay"], keys["drop"], st.tick)
            else:
                dst, slots, valid, blk = edges_from_senders(
                    cfg, stp.friends, stp.friend_cnt, senders, dslot,
                    keys["drop"], tick=st.tick)
                pending = deposit_local(stp.pending, dst, slots, valid,
                                        kernel=dkern)
        stp = stp._replace(
            pending=pending,
            total_message=msg64_add(stp.total_message, dm),
            total_received=stp.total_received + dr,
            total_crashed=stp.total_crashed + dc)
        if cfg.scenario_resolved.active:
            stp = stp._replace(
                scen_crashed=stp.scen_crashed + dsc,
                scen_recovered=stp.scen_recovered + dsr,
                part_dropped=stp.part_dropped + blk)
        return stp

    return tick_fn


def make_seed_fn(cfg: Config) -> Callable[[SimState, jax.Array], SimState]:
    """Uniform-random sender's initial broadcast (simulator.go:240-241).
    Unless compat_reference, the seed itself is marked received (the reference
    never marks it -- SURVEY §5.4 quirk).

    Multi-rumor (oneshot only here -- stream requires the event engine):
    all R sources draw from the shard-invariant OP_INJECT-by-rumor-index
    streams (the event engine's injection_batch keying), their bits set
    immediately, and every source broadcasts in ONE dense deposit."""
    if cfg.multi_rumor:
        r_total, w = cfg.rumors, cfg.rumor_word_count

        def seed_multi(st: SimState, base_key: jax.Array) -> SimState:
            n = st.received.shape[0]
            rr = jnp.arange(r_total, dtype=I32)
            ik = jax.random.fold_in(base_key, _rng.OP_INJECT)
            srcs = jax.vmap(lambda q: jax.random.randint(
                jax.random.fold_in(ik, q), (), 0, n, dtype=I32))(rr)
            masks = jnp.where(
                (rr[:, None] // 32) == jnp.arange(w, dtype=I32)[None, :],
                (jnp.uint32(1) << (rr % 32).astype(jnp.uint32))[:, None],
                jnp.uint32(0))
            # Distinct bits never collide, so the scatter-ADD of colliding
            # source rows IS their OR.
            delta = jnp.zeros((n, w), jnp.uint32).at[srcs].add(masks)
            is_src = (delta != jnp.uint32(0)).any(axis=1)
            received = st.received | is_src
            total_received = st.total_received + is_src.sum(dtype=I32)
            rumor_recv = st.rumor_recv + (
                jnp.arange(st.rumor_recv.shape[0], dtype=I32)
                < r_total).astype(I32)
            kd = _rng.tick_key(base_key, SEED_TICK, _rng.OP_DELAY)
            kp = _rng.tick_key(base_key, SEED_TICK, _rng.OP_DROP)
            dslot = row_slot(cfg, kd, st.tick, jnp.arange(n, dtype=I32))
            dst, slots, valid, blk = edges_from_senders(
                cfg, st.friends, st.friend_cnt, is_src, dslot, kp,
                tick=st.tick)
            pending = deposit_local(st.pending, dst, slots, valid)
            pending_r = deposit_rumors(
                st.pending_rumors, dst, slots, valid,
                unpack_rumor_bits(delta, r_total))
            if cfg.scenario_resolved.has_partitions:
                st = st._replace(part_dropped=st.part_dropped + blk)
            return st._replace(
                received=received, total_received=total_received,
                pending=pending, pending_rumors=pending_r,
                rumor_words=st.rumor_words | delta, rumor_recv=rumor_recv)

        return seed_multi

    def seed_fn(st: SimState, base_key: jax.Array) -> SimState:
        n = st.received.shape[0]
        ks = _rng.tick_key(base_key, SEED_TICK, _rng.OP_SEED_NODE)
        kd = _rng.tick_key(base_key, SEED_TICK, _rng.OP_DELAY)
        kp = _rng.tick_key(base_key, SEED_TICK, _rng.OP_DROP)
        sender = jax.random.randint(ks, (), 0, n, dtype=I32)
        is_sender = jnp.arange(n, dtype=I32) == sender
        received, total_received = st.received, st.total_received
        if cfg.protocol != "si" or not cfg.compat_reference:
            # The seed-never-received quirk (SURVEY §5.4) is an SI compat
            # surface only: pushpull/SIR have no referent in the reference,
            # and the event engine needs the received bit for trigger
            # firing, so both engines mark+count the seed there.
            received = received | is_sender
            total_received = total_received + 1
        if cfg.protocol == "pushpull":
            return st._replace(received=received, total_received=total_received)
        dslot = row_slot(cfg, kd, st.tick, jnp.arange(n, dtype=I32))
        dst, slots, valid, blk = edges_from_senders(
            cfg, st.friends, st.friend_cnt, is_sender, dslot, kp,
            tick=st.tick)
        if cfg.scenario_resolved.has_partitions:
            st = st._replace(part_dropped=st.part_dropped + blk)
        pending = deposit_local(st.pending, dst, slots, valid)
        rb = st.rebroadcast
        if cfg.protocol == "sir":
            # The seed is a sender like any other: removal draw decides
            # whether it keeps re-broadcasting.
            kr = _rng.tick_key(base_key, SEED_TICK, _rng.OP_REMOVE)
            keep = ~_rng.bernoulli(kr, p_eff(cfg, cfg.removal_rate), ())
            rb = rb.at[dslot, jnp.arange(n, dtype=I32)].max(is_sender & keep)
        return st._replace(received=received, total_received=total_received,
                           pending=pending, rebroadcast=rb)

    return seed_fn


def packed_peer_state(received, crashed) -> jnp.ndarray:
    """uint8[n]: 0 susceptible, 1 infected, 2/3 crashed -- ONE random-access
    gather answers both "live?" (< 2) and "live and infected?" (== 1) for the
    pull side of anti-entropy; random access on (n, fanout) peer indices is
    the round's dominant cost at 10M x 23 peers."""
    return received.astype(jnp.uint8) + crashed.astype(jnp.uint8) * 2


def pushpull_chunk_cap(cfg: Config, n_local: int) -> int:
    """Wave-compaction chunk for the push-pull round: rows per gathered
    batch.  n/8 keeps the per-chunk (cap, f) draw+gather bounded (~29M
    lanes at 10M x f=23) while early/late rounds with small active sets
    run a single near-empty chunk; -compact-chunk overrides."""
    if cfg.compact_chunk > 0:
        return min(n_local, cfg.compact_chunk)
    return min(n_local, max(4096, n_local // 8))


def make_pushpull_fn(cfg: Config) -> Callable[[SimState, jax.Array], SimState]:
    """One synchronous push-pull anti-entropy round over uniform random peers
    (BASELINE.json config 3; no referent in the reference).  Push receptions
    are counted and can crash the receiver; pull responses from live peers are
    counted; infection crosses any surviving contact.

    Round 4: the peer and drop draws are ROW-KEYED (utils/rng.row_keys),
    so the wave-compacted path -- push over only the infected-live rows,
    pull over only the susceptible rows, the SI engines' compaction
    applied here -- draws exactly the dense path's values and stays
    bit-identical to it (tested; `-compact off` forces the dense form).
    The two active sets partition the live nodes, so compaction halves
    the per-round gather/draw volume on top of skipping dead rows.
    (Re-keying from the pre-r4 full-matrix draws changed this config's
    trajectory once -- same distribution, new sample; bench totals moved
    accordingly.)"""
    drop_p = p_eff(cfg, cfg.droprate)
    crash_p = p_eff(cfg, cfg.crashrate)
    f = cfg.fanout
    compact = cfg.compact != "off"

    def round_fn(st: SimState, base_key: jax.Array) -> SimState:
        n = st.received.shape[0]
        k1 = _rng.tick_key(base_key, st.tick, _rng.OP_BOOTSTRAP)
        k2 = _rng.tick_key(base_key, st.tick, _rng.OP_PULL)
        kd1 = _rng.tick_key(base_key, st.tick, _rng.OP_DROP)
        kd2 = _rng.tick_key(base_key, st.tick, _rng.OP_DELAY)
        kc = _rng.tick_key(base_key, st.tick, _rng.OP_CRASH)

        live = ~st.crashed
        inf = st.received & live
        sus = ~st.received & live
        packed = packed_peer_state(st.received, st.crashed)

        def compact_rows(mask, body, init):
            """Run `body(idx, valid, carry)` over <=cap-row batches of
            mask's True rows (the SI deposit_compact pattern)."""
            cap = pushpull_chunk_cap(cfg, n)
            chunks = (mask.sum(dtype=I32) + cap - 1) // cap

            def step(_, carry):
                state, remaining = carry
                idx = first_true_indices(remaining, cap)
                hit = jnp.zeros((n,), bool).at[idx].set(True, mode="drop")
                return body(idx, idx < n, state), remaining & ~hit

            out, _ = jax.lax.fori_loop(0, chunks, step, (init, mask))
            return out

        # --- push: infected -> fanout random peers --------------------------
        if compact:
            def push_body(idx, v, arriving):
                peers = _rng.row_randint(k1, n, idx, f)
                kept = ~_rng.row_bernoulli(kd1, drop_p, idx, f)
                edge = v[:, None] & kept
                # Explicit trash cell (index n, in bounds): flat OOB-drop
                # scatters have been miscompiled on this platform inside
                # chunked fori loops (see deposit_local NOTE).
                dst = jnp.where(edge, peers, n).reshape(-1)
                return arriving.at[dst].add(1, mode="promise_in_bounds")

            arriving = compact_rows(
                inf, push_body, jnp.zeros((n + 1,), I32))[:n]
        else:
            rows = jnp.arange(n, dtype=I32)
            peers = _rng.row_randint(k1, n, rows, f)
            kept = ~_rng.row_bernoulli(kd1, drop_p, rows, f)
            edge = inf[:, None] & kept
            dst = jnp.where(edge, peers, n).reshape(-1)
            arriving = jnp.zeros((n + 1,), I32).at[dst].add(
                1, mode="promise_in_bounds")[:n]

        counted = jnp.where(live, arriving, 0)
        total_message = msg64_add(st.total_message, counted.sum(dtype=I32))
        if crash_p > 0.0:
            pc = 1.0 - jnp.power(1.0 - crash_p, counted.astype(jnp.float32))
            new_crash = (jax.random.uniform(kc, (n,)) < pc) & (counted > 0)
        else:
            new_crash = jnp.zeros((n,), bool)
        crashed = st.crashed | new_crash
        total_crashed = st.total_crashed + new_crash.sum(dtype=I32)
        newly_push = (counted > 0) & ~crashed & ~st.received

        # --- pull: surviving susceptible <- fanout random peers' state ------
        # A requester crashed by THIS round's push does not pull (its
        # requests go uncounted) -- the pre-r4 ordering, preserved; peer
        # state stays the pre-round snapshot.
        puller = sus & ~new_crash
        if compact:
            def pull_body(idx, v, carry):
                hit, msgs = carry
                peers2 = _rng.row_randint(k2, n, idx, f)
                kept2 = ~_rng.row_bernoulli(kd2, drop_p, idx, f)
                req = v[:, None] & kept2
                pstate = packed.at[peers2].get(mode="fill", fill_value=2)
                rowhit = (req & (pstate == 1)).any(axis=1)
                msgs = msgs + (req & (pstate < 2)).sum(dtype=I32)
                hit = hit.at[jnp.where(v, idx, n)].max(
                    rowhit, mode="promise_in_bounds")
                return hit, msgs

            pull_hit, pull_msgs = compact_rows(
                puller, pull_body,
                (jnp.zeros((n + 1,), bool), jnp.zeros((), I32)))
            pull_hit = pull_hit[:n]
        else:
            peers2 = _rng.row_randint(k2, n, rows, f)
            kept2 = ~_rng.row_bernoulli(kd2, drop_p, rows, f)
            req = puller[:, None] & kept2
            pstate = packed[peers2]
            pull_hit = (req & (pstate == 1)).any(axis=1)
            pull_msgs = (req & (pstate < 2)).sum(dtype=I32)
        total_message = msg64_add(total_message, pull_msgs)

        newly = (newly_push | pull_hit) & ~crashed & ~st.received
        received = st.received | newly
        total_received = st.total_received + newly.sum(dtype=I32)
        return st._replace(received=received, crashed=crashed,
                           tick=st.tick + 1, total_message=total_message,
                           total_received=total_received,
                           total_crashed=total_crashed)

    return round_fn


def make_step_fn(cfg: Config) -> Callable[[SimState, jax.Array], SimState]:
    if cfg.protocol == "pushpull":
        return make_pushpull_fn(cfg)
    return make_tick_fn(cfg)


def make_heal_fn(cfg: Config):
    """Single-device ring-engine overlay healing (None when -overlay-heal
    is off, keeping the traced window untouched): once per poll window,
    condemn dead friends (scenario.detect_dead), replace them via the
    phase-1 makeup draw and deposit the infected healers' re-sends into
    the delay ring like any broadcast (scenario.heal_and_wave)."""
    if not cfg.overlay_heal_resolved:
        return None
    detect = cfg.heal_detect_ms
    d = ring_depth(cfg)

    def heal_fn(st: SimState, base_key: jax.Array) -> SimState:
        n, k = st.friends.shape
        ids = jnp.arange(n, dtype=I32)
        detected = _scen.detect_dead(st.crashed, st.down_since, st.tick,
                                     detect)
        healer_ok = ~st.crashed
        sender_inf = st.received & ~st.crashed & ~st.removed
        bits = _scen.heal_peer_bits(detected, sender_inf)
        friends, resend, pull, delay, clear, rep, blk = _scen.heal_and_wave(
            cfg, st.friends, st.friend_cnt, bits, healer_ok, sender_inf,
            _scen.rejoined_mask(st.down_since), ids, st.tick, base_key)
        if cfg.effective_time_mode == "rounds":
            dslot = jnp.broadcast_to((st.tick + 1) % d, (n,)).astype(I32)
        else:
            dslot = ((st.tick + delay) % d).astype(I32)
        slots = jnp.broadcast_to(dslot[:, None], (n, k)).reshape(-1)
        dst = jnp.where(resend, friends, -1).reshape(-1)
        pending = deposit_local(st.pending, dst, slots, resend.reshape(-1))
        # Rejoin pull responses deliver to the puller's OWN row.
        pdst = jnp.broadcast_to(ids[:, None], (n, k)).reshape(-1)
        pending = deposit_local(pending, pdst, slots, pull.reshape(-1))
        return st._replace(
            friends=friends, pending=pending,
            down_since=jnp.where(clear, -1, st.down_since),
            heal_repaired=st.heal_repaired + rep,
            part_dropped=st.part_dropped + blk)

    return heal_fn


def make_window_fn(cfg: Config, window: int):
    """`window` consecutive steps as one device call (one progress window).
    The state is donated: the pending ring mutates in place instead of
    costing a fresh HBM allocation + copy per window (essential at 100M,
    where two ring copies would not fit).  With -overlay-heal on, the
    healing pass runs once at the end of every window -- the same cadence
    (and tick keys) the fast-path loop heals at, so both paths walk one
    trajectory."""
    step = make_step_fn(cfg)
    heal = make_heal_fn(cfg)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window_fn(st: SimState, base_key: jax.Array) -> SimState:
        st = jax.lax.fori_loop(0, window, lambda _, s: step(s, base_key), st)
        if heal is not None:
            st = heal(st, base_key)
        return st

    return window_fn


def run_call_budget(cfg: Config) -> int:
    """Ticks per run_to_coverage device call.  One giant while_loop call can
    run for minutes at large n, long enough to trip device-runtime watchdogs
    (observed as UNAVAILABLE faults at n=1e7 on v5e through the remote
    tunnel), so the host loop re-enters a bounded call until done -- same
    compiled executable, same trajectory (keys depend only on tick).  The
    1024 cap bounds how long a dead wave can spin before the host-side
    exhaustion check sees it (the single-device event engine also exits on
    its device-side in-flight term; the ring and sharded engines rely on
    this granularity).

    Push-pull budgets by LANES, not ticks: one anti-entropy round touches
    n * 2f peer draws (every node pushes f and pulls f, no wavefront to
    compact down to), so a round at 5e7 x fanout 26 is ~6 s of device
    work by itself -- the SI-shaped 3.3e9/n budget (66 rounds) blew the
    ~10 s axon watchdog (worker UNAVAILABLE, observed 2026-08-01).
    1.5e9 lanes/call keeps calls in the 2-6 s band across the measured
    sizes."""
    if cfg.protocol == "pushpull":
        return max(1, min(cfg.max_rounds, 1024,
                          int(1.5e9 // max(1, 2 * cfg.fanout * cfg.n))))
    return max(64, min(cfg.max_rounds, 1024, int(3.3e9 // max(cfg.n, 1))))


def make_run_to_coverage_fn(cfg: Config, telemetry: bool = False):
    """Device-side while_loop toward the coverage target: zero host syncs in
    the hot loop (the reference's 10 ms polling becomes one device-side
    predicate, simulator.go:243-251).  Runs until target/max_rounds/`until`
    ticks; callers loop over bounded calls (run_call_budget).

    With `telemetry` the loop additionally carries a device-resident
    per-window History (utils/telemetry.py) and records one counters row
    after every poll window -- the trajectory the windowed driver loop
    observes, without its per-window host round-trip; the signature becomes
    `run_fn(st, key, target, until, hist) -> (st, hist)`."""
    step = make_step_fn(cfg)
    heal = make_heal_fn(cfg)
    window = 1 if cfg.effective_time_mode == "rounds" else 10
    max_steps = cfg.max_rounds
    # Push-pull draws fresh random peers each round -- there is no ring
    # occupancy to test, and the wave never "dies in flight".  Healing can
    # REVIVE an empty ring (a pending dead-friend detection re-sends from
    # an already-infected healer), so heal-on runs drop the early-death
    # exit and run to target/max_rounds (same gate in the host exhaustion
    # checks -- backends set `exhausted` only with healing off).
    check_in_flight = (cfg.protocol != "pushpull"
                       and not cfg.overlay_heal_resolved)
    multi = cfg.multi_rumor
    rumors = cfg.rumors

    def cond_live(s: SimState, target_count, until):
        if multi:
            # Every rumor must hit the target; lanes >= R are padding.
            recv = jnp.min(s.rumor_recv[:rumors])
        else:
            recv = s.total_received
        live = ((recv < target_count)
                & (s.tick < max_steps) & (s.tick < until))
        if check_in_flight:
            # In-flight term (an O(d*n) emptiness test per window, not
            # per tick): exit the device loop the moment the wave dies
            # instead of spinning empty windows until the bounded-call
            # budget lets the host notice -- parity with the event
            # engine's cond (event.make_run_to_coverage_fn).
            live = live & (in_flight(s) > 0)
        return live

    def run_window(s: SimState, base_key):
        # One window per iteration keeps the predicate check off the
        # per-tick critical path.
        s = jax.lax.fori_loop(0, window, lambda _, x: step(x, base_key), s)
        if heal is not None:
            s = heal(s, base_key)
        return s

    if telemetry:
        from gossip_simulator_tpu.utils import telemetry as telem

        sir = cfg.protocol == "sir"
        spatial = telem.spatial_spec(cfg)

        @functools.partial(jax.jit, donate_argnums=(0, 4))
        def run_fn_t(st: SimState, base_key: jax.Array,
                     target_count: jax.Array, until: jax.Array,
                     hist: telem.History):
            def cond(carry):
                s, _ = carry
                return cond_live(s, target_count, until)

            def body(carry):
                s, h = carry
                s = run_window(s, base_key)
                row = telem.gossip_probe(
                    s, sir, rumors=rumors if multi else 0)
                return s, telem.record_window(h, row, st=s, spec=spatial)

            return jax.lax.while_loop(cond, body, (st, hist))

        return run_fn_t

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_fn(st: SimState, base_key: jax.Array, target_count: jax.Array,
               until: jax.Array) -> SimState:
        def cond(s: SimState):
            return cond_live(s, target_count, until)

        return jax.lax.while_loop(cond, lambda s: run_window(s, base_key), st)

    return run_fn
