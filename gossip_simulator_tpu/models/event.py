"""Event-list epidemic engine: cost O(arrivals), not O(n x ticks).

The ring engine (models/epidemic.py) stores arrival *counts* per (slot, node)
-- every tick drains an n-length row, so a 280-tick run at n=1e7 pays 280
O(n) passes even though only ~24M messages ever exist.  This engine stores
the messages themselves -- the TPU-native analog of the reference's per-node
mailbox channels (simulator.go:51-54), batched by delivery time.

Batching granularity is a WINDOW of B = min(10, delaylow) ticks: every
network delay is >= delaylow >= B, so a message delivered inside a window
cannot cause another delivery in the same window -- the whole window drains
as one chunked batch with zero intra-batch causality.  (B collapses to 1 for
sub-window delays, recovering per-tick processing.)  Per-op dispatch
overhead dominates on this platform (each fusion-breaking op costs ~2-5ms
regardless of 16k-256k size), so one batch per window instead of ten is the
difference between the event engine winning and losing to the ring engine.

Mail ring: `mail_ids[dw, cap]` holds PACKED entries `dst * B + tick_off`
(delivery tick within the window), `mail_cnt[dw]` the counts.
Reservations are exact-size, so every entry within a slot's count is a
live message (or SIR trigger) -- the `n * B` sentinel appears only as the
drain's fill for positions beyond the count.  Draining sorts each chunk by
(id, crash-fired-first, tick_off): a node's entries become one contiguous
run whose FIRST element answers everything -- did any crash draw fire, and
(if not) the earliest delivery tick, which seeds the re-broadcast delay
draw.  Infection dedupe across chunks rides the packed `flags` array
(bit0 received, bit1 crashed, bit2 removed under SIR -- one uint8 per
node, so the drain's random-access flag traffic is one gather + one
scatter per chunk).

RNG parity with the ring engine: drop masks and delay slots are drawn from
the identical (seed, delivery-tick, op, sender-row) streams, so with
crashrate=0 the wave trajectory -- totals and window-resolution timing --
is bit-identical to the ring engine (tested).  Documented divergences, all
crash-path only:
* Crash draws are per *message* (keyed by mailbox position), like the
  reference's per-reception draw (simulator.go:112-116), instead of the
  ring engine's aggregated 1-(1-p)^c per node-tick.
* Within one window, a crash does not black-hole the node's other
  deliveries of that window (the reference's channel would, for messages
  queued behind the crash; the margin is ~crashrate x multi-delivery rate).
* A node that would be infected at tick t1 and crashed at t2 > t1 in the
  SAME window is treated as crashed-before-infected (no broadcast).
* When a window drains in multiple chunks, a node whose entries span a
  chunk boundary re-broadcasts from its first-ENCOUNTERED delivery tick
  rather than its globally earliest one (dedupe itself stays exact via the
  received array).
* SIR only: a re-broadcast trigger firing in the same CHUNK as a data
  reception whose crash draw fires still fires (trigger eligibility reads
  chunk-start state); the ring engine's same-tick `due & ~crashed` blocks
  it.  Margin ~crashrate x (trigger co-arrival rate), crash-path only.

Control-flow note: built strictly from constructs proven on the axon TPU
platform -- outer fori windows, inner dynamic-trip fori chunks, gathers,
flat 1-D mode="drop" scatters (2-D index scatters are ~15x slower here),
lax.sort.  Deliberately NO lax.cond (see the miscompile NOTE in
epidemic.make_tick_fn).

Capacity: slot_cap(cfg) packed entries per window slot; appends beyond it
are dropped and counted in `mail_dropped` (Stats.mailbox_dropped), never
silent.  Reservations are exact-size, so SI in-flight is ~n * mean_degree
spread over the delay span; the default covers peak skew ~1.5x over.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gossip_simulator_tpu import scenario as _scen
from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models import epidemic
# in_flight: canonical engine-agnostic definition in models/state.py,
# re-exported here for the backends that import event.in_flight.
from gossip_simulator_tpu.models.state import (in_flight,  # noqa: F401
                                               init_exch_counts,
                                               msg64_add, msg64_zero)
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32


# flags bit layout: one uint8 per node instead of separate received/crashed
# bool arrays -- the drain's random-access traffic on n-sized arrays halves
# (one gather + one scatter per chunk instead of two of each; on this
# platform op count, not element count, sets the floor).
RECEIVED = jnp.uint8(1)
CRASHED = jnp.uint8(2)
REMOVED = jnp.uint8(4)  # SIR: stopped re-broadcasting (still counts coverage)


class EventState(NamedTuple):
    """SI epidemic state with packed message lists instead of count rings."""

    flags: jnp.ndarray  # uint8[n]: bit0 received, bit1 crashed, bit2 removed (SIR)
    friends: jnp.ndarray  # int32[n, k]
    friend_cnt: jnp.ndarray  # int32[n]
    # Flat (dw * cap + ring_tail,) packed ring: slot s occupies
    # [s*cap, (s+1)*cap).  Stored flat (not (dw, cap)) so the append scatter
    # updates it in place -- a reshape round-trip defeats XLA's donation
    # aliasing and copies the multi-GB ring once per chunk (measured
    # 6s/window at n=5e7).  The tail slack (ring_tail) holds the diverted
    # trash writes at UNIQUE positions (letting the append scatter claim
    # unique_indices -- explicit in-bounds cells also dodge the axon
    # mode="drop" OOB miscompile seen in epidemic.deposit_local) and keeps
    # the last drain dynamic_slice of a full slot from clamping (a clamped
    # slice would misalign entry validity).
    mail_ids: jnp.ndarray  # int32[dw * cap + ring_tail]
    # (1, dw): node-axis-leading so the sharded backend stacks shards'
    # counts to (S, dw) under a P('nodes', None) spec.
    mail_cnt: jnp.ndarray  # int32[1, dw]
    # Deferred total_message credits from duplicate suppression, bucketed
    # by arrival window slot (append_messages docstring); credited into
    # the window's dm when it drains and zeroed with mail_cnt.  Bound: one
    # window's suppressed edges <= n * k < 2^31 at every reachable config
    # (n is already bounded tighter by flat mailbox addressing).
    sup_cnt: jnp.ndarray  # int32[1, dw]
    tick: jnp.ndarray  # int32[]
    total_message: jnp.ndarray  # uint32[2] hi/lo 64-bit pair (state.msg64_*)
    total_received: jnp.ndarray  # int32[]
    total_crashed: jnp.ndarray  # int32[]
    mail_dropped: jnp.ndarray  # int32[]  slot-capacity overflow (counted)
    # Cross-shard all_to_all bucket overflow (always 0 on one device).
    exchange_overflow: jnp.ndarray  # int32[]
    # --- fault-injection scenario (scenario.py; see SimState) ------------
    down_since: jnp.ndarray  # int32[n | 1]  crash tick, -1 = live/unknown
    scen_crashed: jnp.ndarray  # int32[]
    scen_recovered: jnp.ndarray  # int32[]
    part_dropped: jnp.ndarray  # int32[]
    heal_repaired: jnp.ndarray  # int32[]
    # --- multi-rumor traffic (Config.multi_rumor; placeholders otherwise,
    # the down_since convention -- the single-rumor program never traces a
    # rumor-axis op) ------------------------------------------------------
    # Per-entry payload words, same flat ring layout/length as mail_ids:
    # the entry at flat position p carries the W = ceil(R/32) uint32 rumor
    # bits mail_words[p] (the sender's NEW bits at send time).
    mail_words: jnp.ndarray  # uint32[dw * cap + ring_tail, W | 1x1]
    rumor_words: jnp.ndarray  # uint32[n, W | 1x1]  per-node infection bits
    # Per-rumor infected counts / completion tick, padded to W*32 lanes
    # (lanes >= R stay 0 / -1).  Replicated across shards (psum'd deltas).
    rumor_recv: jnp.ndarray  # int32[W * 32 | 1]
    rumor_done: jnp.ndarray  # int32[W * 32 | 1]  tick coverage hit, -1 else
    # Spatial-telemetry routed-exchange counters (state.init_exch_counts;
    # 1x1 placeholder unless the panels record under S > 1 shards).
    exch_counts: jnp.ndarray  # int32[1, S+2 | 1x1]


def batch_ticks(cfg: Config, n_local: int | None = None) -> int:
    """Window size B: delays >= delaylow >= B guarantee no intra-window
    causality.  Also bounded so the packed id*B+tick_off fits int32 --
    SIR additionally packs re-broadcast triggers at (n+1+id)*B+off
    (see trigger_base), doubling the range."""
    n = n_local if n_local is not None else cfg.n
    b = max(1, min(10, cfg.delaylow))
    span = 2 * n + 3 if cfg.protocol == "sir" else n + 1
    while b > 1 and span * b >= 2**31:
        b //= 2
    return b


def trigger_base(n: int, b: int) -> int:
    """SIR re-broadcast triggers are tagged self-messages packed as
    trigger_base + id*b + off: they sort after every data entry (and after
    the data padding sentinel n*b), so a node's data run stays contiguous
    and triggers form their own runs."""
    return (n + 1) * b


def ring_windows(cfg: Config, n_local: int | None = None) -> int:
    """Window-slot ring depth: max arrival offset in windows, plus current."""
    b = batch_ticks(cfg, n_local)
    return (b - 1 + cfg.delayhigh - 1) // b + 1


def slot_cap(cfg: Config, n_local: int | None = None) -> int:
    """Packed entries per window slot.  Reservations are exact-size, so SI
    total in-flight is ~n * mean_degree spread over delay_span ticks; a
    window aggregates B ticks of it, 1.5x covers skew (overflow is counted,
    never silent).  Clamped so the flat scatter index dw * cap stays in
    int32."""
    n = n_local if n_local is not None else cfg.n
    b = batch_ticks(cfg, n_local)
    dw = ring_windows(cfg, n_local)
    # Reservations are exact-size (no padding reaches the ring), so the
    # aggregate budget is the MEAN out-degree (for erdos ~3x smaller than
    # the padded column width), plus one for SIR's re-broadcast trigger.
    deg = cfg.mean_degree + (1 if cfg.protocol == "sir" else 0)
    # 1.5x skew headroom is a registered tunable (tuning.py); an explicit
    # -event-slot-cap outranks it entirely.
    headroom = _tuning.value("event.slot_headroom", cfg)
    cap = cfg.event_slot_cap if cfg.event_slot_cap > 0 else max(
        4096, int(math.ceil(headroom * n * deg * b
                            / max(cfg.delay_span, 1))))
    # One slot can never hold more than every SI message plus padding
    # (SIR re-broadcasts indefinitely, so the bound only applies to SI).
    if cfg.protocol != "sir":
        cap = min(cap, n * cfg.graph_width + cfg.graph_width)
    if cfg.event_slot_cap <= 0:
        # Auto sizing also respects HBM: bound the whole ring to ~3 GB
        # (validated headroom for the 100M single-chip run on a 16 GB v5e;
        # overflow past the cap is counted in mail_dropped, never silent).
        # An explicit -event-slot-cap overrides this.  Under duplicate
        # suppression (SI) the band halves: append-side filtering cut the
        # measured peak slot occupancy 1.86x (94.8M vs 176.4M at 1e8
        # fanout 6, 2026-07-31), and the scatter/gather cost of every
        # append batch scales with the RING size on this platform --
        # cap 1.34e8 (1.6 GB ring) ran the 100M/99% row 2.2s faster than
        # cap 2.68e8 (3.2 GB) at a 1.41x occupancy margin.  SIR keeps the
        # full band (re-broadcasts break the broadcast-once occupancy
        # argument).
        hbm = (3 * 2**30 if not (cfg.dup_suppress_resolved
                                 and cfg.protocol == "si")
               else 3 * 2**29)
        cap = min(cap, hbm // (4 * max(dw, 1)))
    # Tail-aware int32 clamp (advisor r5): the flat ring extends to
    # dw*cap + ring_tail and append diverts trash lanes to indices
    # dw*cap + lane, so the WHOLE range -- not just dw*cap -- must stay
    # in int32 or a large explicit -event-slot-cap wraps the trash
    # indices negative.  ring_tail needs drain_chunk which needs slot_cap
    # back; the cycle breaks with the PRE-clamp chunk request
    # (_chunk_want), an upper bound on the real chunk and hence -- via
    # the same width rule ring_tail applies -- on the real tail.
    cw = _chunk_want(cfg, n_local)
    scap = sender_compaction_cap(cfg, cw)
    width = scap if scap else cw
    tail_ub = max(cw, width * (cfg.graph_width
                               + (1 if cfg.protocol == "sir" else 0)))
    lim = (2**31 - 1 - tail_ub) // max(dw, 1)
    if lim <= 0:
        raise ValueError(
            f"-event-chunk {cfg.event_chunk} implies a ring tail of "
            f"{tail_ub} lanes, past int32 flat addressing; lower it")
    return min(cap, lim)


def ring_tail(cfg: Config, n_local: int | None = None) -> int:
    """Slack lanes past the last window slot.  Serves three purposes: an
    explicit trash region for diverted scatter lanes, drain-slice clamp
    protection (>= drain_chunk so the last dynamic_slice of a full slot
    never clamps), and -- sized to one full append batch's lane count --
    UNIQUE trash positions, which lets the mail scatter claim
    unique_indices=True and skip XLA's sort-based duplicate combining
    (profiled at 8.6 ms per batch at scap=1M x k=6 on v5e, plus combine
    overhead inside the scatter fusion itself).  graph_width bounds the
    per-sender lane count from above (kout tables are fanout wide;
    overlay tables max_degree); +1 is the SIR trigger column."""
    ccap = drain_chunk(cfg, n_local)
    scap = sender_compaction_cap(cfg, ccap)
    width = scap if scap else ccap
    lanes = width * (cfg.graph_width + (1 if cfg.protocol == "sir" else 0))
    return max(ccap, lanes)


def drain_geometry(cfg: Config, n_local: int | None = None) -> tuple:
    """(slot_cap, drain_chunk, ring_tail): every jit-time ring/drain
    constant the event-engine tunables (drain_chunk_*, slot_headroom)
    feed.  This is the autotuner's effect probe (tuning.effective_value):
    a candidate that leaves this tuple unchanged compiles the identical
    program, so its sweep row is unexercised noise."""
    return (slot_cap(cfg, n_local), drain_chunk(cfg, n_local),
            ring_tail(cfg, n_local))


def drain_chunk(cfg: Config, n_local: int | None = None) -> int:
    """Drain chunk size: auto = a degree-scaled n/128 ramp with
    r = mean_degree / 4 (the fanout-3 kout calibration; max_degree 4
    there): clamp(n/128 * r^3, 131k, hi) where hi = 1M for r >= 1.5 else
    512k, rounded UP to a power of two (the sort pads to one internally).

    Swept empirically on v5e.  Fanout 3 kout: n=1e7: 64k:752,
    128k:769->922 (post friend_cnt removal), 156k:882, 256k:718->794,
    512k:623, 1M:487 M node-updates/s -- op cost grows superlinearly past
    ~128k entries (sort passes, scatter contention), favoring small
    chunks; n=1e8: 128k:303, 256k:782, 512k:903, 1M:880 -- the n-sized
    flag gather/scatter per chunk grows with n, so fewer/larger chunks
    win.  Fanout 6 kout (the 99%-coverage north-star config, ~5x the
    entries per window; swept 2026-07-31): n=1e7: 131k:7.08s, 262k:6.53,
    512k:6.18, 1M:6.27 time-to-99%; n=1e8: 512k:57.8, 1M:49.5, 2M:55.6 --
    higher message volume pushes the optimum up roughly with degree^3
    over this range.  The scaled ramp lands within ~3% of all six
    measured optima; the cap keeps low-degree configs (incl. the proven
    1e8 fanout-3 headline at 512k) exactly where their sweeps put them."""
    return min(slot_cap(cfg, n_local), _chunk_want(cfg, n_local))


def _chunk_want(cfg: Config, n_local: int | None = None) -> int:
    """drain_chunk before its slot_cap clamp (the auto ramp / explicit
    -event-chunk, >= 256).  Split out so slot_cap's tail-aware int32
    clamp can bound the ring tail without calling drain_chunk back
    (which would recurse into slot_cap)."""
    n = n_local if n_local is not None else cfg.n
    if cfg.event_chunk > 0:
        want = cfg.event_chunk
    else:
        # The ramp's floor and per-branch ceilings are registered
        # tunables (tuning.py "chunk_ladder" space -- the ladder the
        # deleted scripts/chunk_sweep*.py swept by hand); an explicit
        # -event-chunk outranks any table entry via the branch above.
        r = max(1.0, cfg.mean_degree / 4.0)
        hi = (_tuning.value("event.drain_chunk_hi", cfg) if r >= 1.5
              else _tuning.value("event.drain_chunk_hi_lowdeg", cfg))
        if cfg.dup_suppress_resolved and r >= 1.5:
            # Suppression shrinks the drained entry volume ~1.4x and the
            # ring itself (slot_cap band), moving the optimum up again:
            # 1e8 fanout 6 @99% swept 2026-07-31 (cap 1.34e8): 1M:27.6,
            # 2M:24.9, 4M:24.3, 8M:26.6 s -- per-batch op floors beat
            # element growth until ~4M.
            hi = _tuning.value("event.drain_chunk_hi_suppress", cfg)
        floor = _tuning.value("event.drain_chunk_floor", cfg)
        want = min(hi, max(floor, int(n // 128 * r ** 3)))
        # Round up to a power of two: the sort pads to one internally, so
        # a 918k chunk costs a 1M sort but drains only 918k entries
        # (measured 55.6s vs 49.5s at the 1e8 fanout-6 config).
        want = 1 << (want - 1).bit_length()
    return max(256, want)


def init_rumor_leaves(cfg: Config, n: int, ring_len: int | None = None):
    """(mail_words, rumor_words, rumor_recv, rumor_done) -- full-size under
    Config.multi_rumor, 1-element placeholders otherwise (the down_since
    convention).  Shared by the single-device and sharded init paths and by
    the checkpoint loader's legacy-snapshot backfill."""
    if not cfg.multi_rumor:
        return (jnp.zeros((1, 1), jnp.uint32), jnp.zeros((1, 1), jnp.uint32),
                jnp.zeros((1,), I32), jnp.full((1,), -1, I32))
    w = cfg.rumor_word_count
    if ring_len is None:
        ring_len = ring_windows(cfg) * slot_cap(cfg, n) + ring_tail(cfg, n)
    return (jnp.zeros((ring_len, w), jnp.uint32),
            jnp.zeros((n, w), jnp.uint32),
            jnp.zeros((w * 32,), I32), jnp.full((w * 32,), -1, I32))


def injection_lanes(cfg: Config) -> int:
    """Static injection lane count per B-tick window: all R for oneshot
    (only window 0's lanes validate), else the max rumors whose schedule
    tick can land inside one window."""
    if not cfg.multi_rumor:
        return 0
    if cfg.traffic != "stream":
        return cfg.rumors
    b = batch_ticks(cfg)
    from gossip_simulator_tpu import arrivals as _arrivals

    table = _arrivals.table_or_none(cfg)
    if table is not None:
        # Windows are b-aligned (tick advances 0, b, 2b, ...), so the max
        # rumors per aligned bucket is the exact lane requirement.
        counts = np.unique(np.asarray(table, np.int64) // b,
                           return_counts=True)[1]
        return int(counts.max()) if len(counts) else 1
    return min(cfg.rumors, (b * cfg.stream_rate + 999) // 1000 + 1)


def injection_batch(cfg: Config, tick, base_key, b: int, dw: int,
                    n_local: int | None = None, shard=None):
    """Rumor injections whose schedule tick lands in the window
    [tick, tick+b): self-addressed mail entries (dst = source row,
    delivered at the rumor's inject tick) so injected rumors enter through
    the SAME ring/drain machinery as every relayed message -- the source
    is counted infected, and broadcasts, at its entry's drain.  Rumor r's
    tick is 0 (oneshot: every rumor at window 0) or r * 1000 //
    stream_rate (stream).  Source draws are keyed by rumor index ONLY
    (OP_INJECT -- no tick, no shard), so the schedule is shard-count
    invariant; `shard` non-None keeps only lanes the shard owns and
    localizes the destination row.  Returns (payload, words, wslot,
    valid) with injection_lanes(cfg) static lanes."""
    m = injection_lanes(cfg)
    r_total = cfg.rumors
    w = cfg.rumor_word_count
    stream = cfg.traffic == "stream"
    if stream:
        from gossip_simulator_tpu import arrivals as _arrivals

        table = _arrivals.table_or_none(cfg)
    else:
        table = None
    if table is not None:
        # Precomputed arrival schedule (non-fixed -arrivals, or a serve
        # admission-deferral override): the sorted table is a compile-time
        # constant (R <= 1024 int32s), so the window's first candidate
        # rumor is a searchsorted lookup and its tick a gather.  Same lane
        # validity/payload math as the arithmetic branch below.
        tab = jnp.asarray(table, I32)
        r0 = jnp.searchsorted(tab, tick, side="left").astype(I32)
        rr = r0 + jnp.arange(m, dtype=I32)
        t_r = tab[jnp.minimum(rr, r_total - 1)]
    elif stream:
        rate = cfg.stream_rate
        # Clamp before the multiply so tick * rate stays in int32 at any
        # max_rounds (past last_inject_tick every lane invalidates anyway;
        # validate() bounds stream_rate so the clamped product fits).
        tickc = jnp.minimum(tick, cfg.last_inject_tick + 1)
        r0 = (tickc * rate + 999) // 1000
        rr = r0 + jnp.arange(m, dtype=I32)
        t_r = rr * 1000 // rate
    else:
        rr = jnp.arange(m, dtype=I32)
        t_r = jnp.zeros((m,), I32)
    valid = (rr < r_total) & (t_r >= tick) & (t_r < tick + b)
    ik = jax.random.fold_in(base_key, _rng.OP_INJECT)
    src = jax.vmap(lambda r: jax.random.randint(
        jax.random.fold_in(ik, r), (), 0, cfg.n, dtype=I32))(rr)
    if shard is not None:
        valid = valid & (src // n_local == shard)
        src = src % n_local
    payload = src * b + t_r % b
    wslot = (t_r // b) % dw
    words = jnp.where(
        (rr[:, None] // 32) == jnp.arange(w, dtype=I32)[None, :],
        (jnp.uint32(1) << (rr % 32).astype(jnp.uint32))[:, None],
        jnp.uint32(0))
    return payload, words, wslot, valid


def stamp_rumor_done(cfg: Config, rumor_recv, rumor_done, tick):
    """Per-window completion stamping (metrics only -- the run cond keys on
    rumor_recv): rumor r is done at the first window-end tick where its
    infected count reaches the static ceil(coverage_target * n)."""
    target = int(math.ceil(cfg.coverage_target * cfg.n))
    hit = (rumor_recv >= target) & (rumor_done < 0)
    return jnp.where(hit, tick, rumor_done)


def init_state(cfg: Config, friends: jnp.ndarray,
               friend_cnt: jnp.ndarray, n_shards: int = 1) -> EventState:
    n = friends.shape[0]  # local rows: the shard slice under the sharded backend
    z = lambda: jnp.zeros((), I32)
    mail_words, rumor_words, rumor_recv, rumor_done = init_rumor_leaves(
        cfg, n)
    return EventState(
        flags=jnp.zeros((n,), jnp.uint8),
        friends=friends,
        friend_cnt=friend_cnt,
        mail_ids=jnp.zeros(
            (ring_windows(cfg) * slot_cap(cfg, n) + ring_tail(cfg, n),),
            I32),
        mail_cnt=jnp.zeros((1, ring_windows(cfg)), I32),
        sup_cnt=jnp.zeros((1, ring_windows(cfg)), I32),
        tick=z(), total_message=msg64_zero(), total_received=z(),
        total_crashed=z(),
        mail_dropped=z(), exchange_overflow=z(),
        down_since=_scen.init_down_since(cfg.faults_enabled, n),
        scen_crashed=z(), scen_recovered=z(), part_dropped=z(),
        heal_repaired=z(),
        mail_words=mail_words, rumor_words=rumor_words,
        rumor_recv=rumor_recv, rumor_done=rumor_done,
        exch_counts=init_exch_counts(cfg, n_shards),
    )


def _sender_keys(base_key, op: int, ticks, rows):
    """Per-sender key fold_in(fold_in(fold_in(base, tick), op), row) -- the
    exact stream epidemic.row_slot / row_bernoulli draw from for a sender
    broadcasting at `tick`, vectorized over per-sender delivery ticks."""
    def one(t, r):
        return jax.random.fold_in(_rng.tick_key(base_key, t, op), r)

    return jax.vmap(one)(ticks, rows)


def append_messages(cfg: Config, mail_ids, mail_cnt, dropped, sender_ids,
                    svalid, sticks, friends, friend_cnt, base_key,
                    strig=None, flags=None, gid0=0, swords=None,
                    mail_words=None, kernel: str = "xla",
                    phase2: str = "xla"):
    """Emit each sender's broadcast (k sends, ONE shared delay drawn at its
    delivery tick -- simulator.go:141-142) into the packed mail ring.

    `flags` non-None enables guaranteed-duplicate suppression (sound only
    at crash_p == 0 -- Config.dup_suppress_resolved gates): a kept edge
    whose destination already has the received bit never enters the ring
    -- its delivery could only have incremented total_message
    (simulator.go:111,117-119; received is monotone, and at crash_p == 0
    there is no per-reception draw to preserve).  Suppressed edges are
    returned as per-ARRIVAL-WINDOW counts `sup_adds[dw]` that the caller
    banks in EventState.sup_cnt and credits to total_message when that
    window drains -- the exact step its deliveries would have counted --
    so every poll-cadence observable (per-window totals, stdout, JSONL,
    death tick) is bit-identical to the unsuppressed path, not just the
    final totals (A/B-tested).  Delay and drop draws are (tick,
    sender-row)-keyed, so filtering edges shifts no stream.  Remaining
    divergence envelope: under slot overflow a suppressed edge counts as
    delivered where the unsuppressed path might have counted it into
    mail_dropped (zero-overflow regimes -- every measured config -- are
    unaffected).

    A sender's messages share one arrival tick, hence one window slot.
    Reservations are EXACT-size: each sender takes as many contiguous
    positions as it has kept (non-dropped, real) edges -- a per-slot
    weighted exclusive prefix sum over (senders, dw) -- so the ring holds
    no padding and the drain touches only live entries.  (Erdos friends
    tables are ~72% tail padding at the default p; fixed-width
    reservations made the drain pay for all of it.)  The write is one
    flat 1-D scatter with non-edges diverted to the trash cell.

    SIR (`strig` mask set): senders also schedule their next re-broadcast as
    a tagged self-message (trigger_base + id*b + off) arriving with the SAME
    shared delay -- the event analog of the ring engine's
    `rebroadcast.at[dslot, ids]` (models/epidemic.py tick_core); it sits
    right after the sender's kept edges.

    Multi-rumor (`swords` (m, W) + `mail_words` set): every kept edge also
    writes the sender's delta words through the SAME flat positions --
    entry alignment is by construction, not by a second rank pass -- and
    the return gains the updated mail_words.  Mutually exclusive with
    `strig` (multi-rumor is SI-only, config.validate)."""
    n, k = friends.shape
    dw = ring_windows(cfg)
    cap = (mail_ids.shape[0] - ring_tail(cfg, n)) // dw
    b = batch_ticks(cfg)
    rows = jnp.where(svalid, sender_ids, n)
    sidx = jnp.where(svalid, sender_ids, 0)
    sf = friends.at[sidx].get()
    del friend_cnt  # not gathered: rows are prefix-compact, (sf >= 0) is the
    # edge mask (every generator -1-pads the tail; overlay.py appends at cnt
    # and swap-fills holes) -- profiled at ~1 ms/chunk, ~8% of the drain.
    dk = _sender_keys(base_key, _rng.OP_DELAY, sticks, rows)
    pk = _sender_keys(base_key, _rng.OP_DROP, sticks, rows)
    delay = jnp.maximum(jax.vmap(
        lambda kk: jax.random.randint(kk, (), cfg.delaylow, cfg.delayhigh,
                                      dtype=I32))(dk), 1)
    drop_p = epidemic.p_eff(cfg, cfg.droprate)
    if drop_p <= 0.0:
        drop = jnp.zeros(rows.shape + (k,), bool)
    elif drop_p >= 1.0:
        drop = jnp.ones(rows.shape + (k,), bool)
    else:
        drop = jax.vmap(
            lambda kk: jax.random.bernoulli(kk, drop_p, (k,)))(pk)
    arrive = sticks + delay
    wslot = (arrive // b) % dw
    off = arrive % b
    if phase2 == "pallas":
        # Phase-2 megakernel: everything from the edge masks down --
        # partition block, duplicate filter, reservation prefix and the
        # dual-ring scatter -- as ONE serial pass
        # (ops/pallas_megakernel.fused_emit; bit-identical, see its
        # module docstring).  The RNG draws above stay on the XLA side
        # so streams are untouched; the raw partition predicate is
        # evaluated here (vectorized trig-free mask math) and ANDed
        # in-register.
        from gossip_simulator_tpu.ops import pallas_megakernel as mk
        scen = cfg.scenario_resolved
        pmask = None
        if scen.has_partitions:
            pmask = _scen.partition_blocked(
                scen, cfg.n, sticks[:, None], (gid0 + rows)[:, None], sf)
        out = mk.fused_emit(
            mail_ids, mail_cnt, sf, drop, svalid, wslot, off,
            dw=dw, cap=cap, b=b,
            tb=(trigger_base(n, b) if strig is not None else None),
            strig=strig, sender_ids=sender_ids, pmask=pmask,
            flags=flags, received_bit=int(RECEIVED),
            swords=swords, mail_words=mail_words)
        if swords is not None:
            mail_ids, adds, sup_adds, lost, blk, mail_words = out
        else:
            mail_ids, adds, sup_adds, lost, blk = out
        blocked_n = blk if scen.has_partitions else 0
        new_cnt = mail_cnt + adds[None, :]
        if swords is not None:
            return (mail_ids, new_cnt, dropped + lost, sup_adds,
                    blocked_n, mail_words)
        return mail_ids, new_cnt, dropped + lost, sup_adds, blocked_n
    edge = svalid[:, None] & ~drop & (sf >= 0)
    scen = cfg.scenario_resolved
    blocked_n = 0
    if scen.has_partitions:
        # Send-time partition mask (scenario.partition_blocked): an edge
        # whose broadcast leaves inside an active partition never enters
        # the ring -- before the duplicate filter, so a blocked edge is
        # never credited as a delivered duplicate.  `gid0` globalizes the
        # sharded caller's local rows; sf destinations are global already.
        blocked = _scen.partition_blocked(
            scen, cfg.n, sticks[:, None], (gid0 + rows)[:, None], sf) & edge
        blocked_n = blocked.sum(dtype=I32)
        edge = edge & ~blocked
    dcnt = None
    if flags is not None:
        dstf = flags.at[jnp.where(sf >= 0, sf, 0)].get()
        dup = edge & ((dstf & RECEIVED) > 0)
        dcnt = dup.sum(axis=1, dtype=I32)  # suppressed edges per sender
        edge = edge & ~dup
    cols = jnp.cumsum(edge, axis=1, dtype=I32) - 1  # kept-edge rank in row
    ec = edge.sum(axis=1, dtype=I32)  # kept edges per sender
    payload = sf * b + off[:, None]
    if strig is not None:
        tb = trigger_base(n, b)
        # The trigger occupies the slot right after the kept edges.
        cols = jnp.concatenate([cols, ec[:, None]], axis=1)
        edge = jnp.concatenate([edge, strig[:, None]], axis=1)
        payload = jnp.concatenate(
            [payload, (tb + sender_ids * b + off)[:, None]], axis=1)
        ec = ec + strig.astype(I32)
    # Per-slot exclusive prefix of reservation sizes (emission order).
    # Row r's slot column is picked by ONE-HOT ARITHMETIC, not a gather:
    # dw is tiny (~3), so (x * oh).sum(axis=1) fuses into the cumsum while
    # take_along_axis / mail_cnt[0, wslot] each lower to a ccap-sized
    # random gather costing a full per-op floor (profiled at ~24 ms per
    # window combined at n=1e7, ~17% of the drain).  Rows with svalid
    # False get seg = base = 0 instead of the old column-0 values; both
    # versions are don't-cares there (every consumer masks with ok, a
    # subset of svalid) and live rows are bit-identical.
    oh = ((wslot[:, None] == jnp.arange(dw, dtype=I32)[None, :])
          & svalid[:, None]).astype(I32)
    # Suppressed-edge counts bucketed by arrival window via the SAME
    # one-hot (fused reduction, no dw-gather/scatter); independent of the
    # overflow check -- suppressed edges never consume ring capacity.
    sup_adds = ((oh * dcnt[:, None]).sum(axis=0) if dcnt is not None
                else jnp.zeros((dw,), I32))
    w = oh * ec[:, None]
    seg = ((jnp.cumsum(w, axis=0) - w) * oh).sum(axis=1)
    base = (mail_cnt[0][None, :] * oh).sum(axis=1)
    start = base + seg
    ok = svalid & (start + ec <= cap)
    # Dead lanes divert to UNIQUE trash positions (ring_tail sizes the
    # slack to one batch's lane count); live reservations are disjoint by
    # construction, so the scatter can claim unique_indices and skip XLA's
    # sort-based duplicate combining (profiled 8.6 ms/batch at 6.3M lanes).
    nlanes = edge.shape[0] * edge.shape[1]
    lane = jnp.arange(nlanes, dtype=I32).reshape(edge.shape)
    flat = jnp.where(edge & ok[:, None],
                     wslot[:, None] * cap + start[:, None] + cols,
                     dw * cap + lane)
    ivals = jnp.where(edge, payload, 0).reshape(-1)
    if swords is not None:
        wvals = jnp.where(edge[:, :, None],
                          jnp.broadcast_to(swords[:, None, :],
                                           edge.shape + swords.shape[-1:]),
                          jnp.uint32(0)).reshape(-1, swords.shape[-1])
    if kernel == "pallas":
        # Fused dual-ring write: id ring and word ring share their unique
        # reservation positions, so one serial pass writes both (order
        # immaterial -- bit-identical to the unique_indices scatters).
        from gossip_simulator_tpu.ops import pallas_deliver
        if swords is not None:
            mail_ids, mail_words = pallas_deliver.fused_unique_set(
                (mail_ids, mail_words), flat.reshape(-1), (ivals, wvals))
        else:
            (mail_ids,) = pallas_deliver.fused_unique_set(
                (mail_ids,), flat.reshape(-1), (ivals,))
    else:
        mail_ids = mail_ids.at[flat.reshape(-1)].set(
            ivals, unique_indices=True)
        if swords is not None:
            mail_words = mail_words.at[flat.reshape(-1)].set(
                wvals, unique_indices=True)
    # Overflowed senders are a per-slot suffix (start grows monotonically
    # within a slot), so counting only written reservations keeps
    # positions contiguous.
    adds = (w * ok[:, None]).sum(axis=0)
    new_cnt = mail_cnt + adds[None, :]
    lost = (edge & ~ok[:, None]).sum(dtype=I32)
    # CAVEAT (SIR): an overflowed reservation loses the sender's
    # re-broadcast trigger along with its data messages, permanently muting
    # that node's re-broadcast chain -- a qualitatively larger distortion
    # than the per-message count suggests.  slot_cap budgets mean_degree+1
    # per sender precisely so this stays at zero; a nonzero mail_dropped
    # under SIR should be treated as an undersized -event-slot-cap, not as
    # ordinary message loss (see README divergence table).  blocked_n is
    # the partition-masked edge count (a Python 0 without partitions).
    if swords is not None:
        return mail_ids, new_cnt, dropped + lost, sup_adds, blocked_n, \
            mail_words
    return mail_ids, new_cnt, dropped + lost, sup_adds, blocked_n


# Pre-drain compaction engages only once received-fraction crosses this
# (measured 2026-07-31, 1e8 fanout 6 v5e: at 42%/78% received the filter's
# RANDOM flags gather costs more than the sorted drain it shrinks -- +1.05s
# and +0.56s per window -- while at 96% it wins -1.0s; the sort's ascending
# locality is what the platform rewards, same lesson as the scatter-min
# dead end).  Below the threshold the filter runs zero chunks.
PREDRAIN_MIN_RECV_FRAC = 0.9


def predrain_compact(b: int, n_rows: int, dw: int, cap: int, ccap: int,
                     sir: bool, flags, mail_ids, slot, m):
    """Filter the due window's slot against the CURRENT flags before the
    chunked drain (crash_p == 0 gate -- same soundness as append-side
    suppression, shared by the single-device and sharded steps): a data
    entry whose destination's received bit is set can only increment
    total_message in this very window, so it is counted here and compacted
    away instead of paying the sorted drain.  Catches the duplicates the
    append-side filter structurally cannot -- those appended BEFORE their
    destination flipped received (the exponential-phase majority: measured
    ~80% of endgame ring traffic at 1e8 fanout 6).  The slot's content is
    frozen once its window starts (delay >= B), so filtering at drain
    start sees final content.  Stable compaction (rank = running kept
    count) preserves entry order, so retained entries keep the exact
    first-encountered semantics; chunk boundaries shift with occupancy,
    the same envelope as any event_chunk change.  SIR: triggers
    (ent >= n*b) are never data and always kept.

    In-place safety: chunk j's scatter writes land strictly below
    position (j+1)*ccap (kept <= j*ccap), so no later chunk reads a
    written lane.  `m` may be a traced scalar and the caller may pass 0
    chunks' worth (m=0 disables); returns
    (mail_ids, kept_total, filtered_data)."""
    nf = (m + ccap - 1) // ccap

    def fbody(j, carry):
        mail, kept, fdat = carry
        off0 = j * ccap
        pos = off0 + jnp.arange(ccap, dtype=I32)
        valid = pos < m
        ent = jax.lax.dynamic_slice(mail, (slot * cap + off0,), (ccap,))
        is_data = valid & (ent < n_rows * b) if sir else valid
        idx = jnp.where(is_data, jnp.minimum(ent // b, n_rows - 1), 0)
        f = flags.at[idx].get()
        drop = is_data & ((f & RECEIVED) > 0)
        keep = valid & ~drop
        rank = kept + jnp.cumsum(keep.astype(I32)) - 1
        lane = jnp.arange(ccap, dtype=I32)  # unique trash (ccap <= tail)
        tgt = jnp.where(keep, slot * cap + rank, dw * cap + lane)
        mail = mail.at[tgt].set(jnp.where(keep, ent, 0),
                                unique_indices=True)
        return mail, kept + keep.sum(dtype=I32), fdat + drop.sum(dtype=I32)

    return jax.lax.fori_loop(
        0, nf, fbody,
        (mail_ids, jnp.zeros((), I32), jnp.zeros((), I32)))


def drain_chunk_core(crash_p: float, b: int, n_rows: int, flags, packed,
                     evalid, entry_pos, ckey, sir: bool = False,
                     track_crashed: bool = False, down_since=None,
                     win_tick=None, words=None, rumor_words=None):
    """Crash/infect/dedupe one drained chunk of packed entries (shared by the
    single-device and sharded engines; `n_rows` is the local row count).

    Sorts by (id, crash-fired-first, tick_off): a node's entries become one
    contiguous run whose FIRST element answers whether any per-message crash
    draw fired (keyed by mailbox position -- append order is deterministic --
    like the reference's per-reception draw, simulator.go:112-116) and, if
    not, its earliest delivery tick.  The sort also turns the flags
    gather/scatter into ascending-id access (better HBM locality than the
    raw mailbox order).

    `flags` packs received (bit0) and crashed (bit1) per node; within a
    chunk a node's winning entry sets at most one new bit, so the update is
    a single duplicate-free scatter-add.

    With `sir` (static -- compiles to the identical SI program when False):
    trigger entries (trigger_base + id*b + off) sort after all data into
    their own per-node runs.  Data entries infect exactly as in SI; a
    trigger FIRES -- the node re-broadcasts at its tick -- iff the node is
    infected and neither crashed nor removed as of the chunk start (the
    ring engine's `due & ~crashed & ~removed`; same-chunk crash-vs-trigger
    ordering divergence is documented in the module docstring).  Crash
    draws fire on data receptions only; removal draws happen in the caller
    (per sender, at send time, matching tick_core's removal-after-send).

    `track_crashed` forces the pre-crash flag read even at crash_p == 0:
    under a fault scenario, nodes crash OUTSIDE the per-reception draw
    (crash waves / churn), and deliveries to them must still black-hole
    (counted like the ring engine's `where(crashed, 0, arrivals)`).
    `down_since`/`win_tick` non-None stamp the crash clock on reception
    crashes (the scenario reboot/detection timeline; window-start
    granularity -- the crash draw itself is window-batched already).

    Multi-rumor (`words` (ccap, W) + `rumor_words` set; SI only): the
    entry payload words ride the sort as extra operands, a reversed
    segmented OR-scan folds each node-run's words into its FIRST lane
    (suffix-OR over the run -- read only at run starts), and the winner's
    NEW bits (run OR minus the node's current words) update rumor_words,
    per-rumor counts, and become the node's forwarded payload.  The
    winner gate drops `~pre_recv`: an already-infected node gaining new
    bits still delivers and re-forwards (first-touch-wins is per RUMOR,
    not per node).  `dm` still counts every delivered entry -- a delivery
    bringing no new bits walks the channel like any reference duplicate.
    A crash draw firing at the run's first lane voids the whole run's
    delivery (crashed-before-infected, the single-rumor rule, now
    per-run).  Returns three extra values (rumor_words, delta_words,
    drecv) and `senders` becomes win & (delta != 0).

    Returns (flags, dm, dr, dc, ids_s, toff_s, senders, down_since);
    senders is newly-infected for SI, newly | firing for SIR (disjoint: a
    trigger implies the node was already infected)."""
    ccap = packed.shape[0]
    tb = trigger_base(n_rows, b)
    sentinel = tb + n_rows * b if sir else n_rows * b
    packed = jnp.where(evalid, packed, sentinel)  # sentinel sorts last
    wcols = ()
    if words is not None:
        # Stale ring lanes past the count carry garbage words: zero them
        # (their sentinel keys sort them into non-data runs anyway).
        words = jnp.where(evalid[:, None], words, jnp.uint32(0))
        wcols = tuple(words[:, i] for i in range(words.shape[1]))
    if crash_p > 0.0:
        ck = _rng.row_keys(ckey, entry_pos)
        draw = jax.vmap(lambda kk: jax.random.bernoulli(kk, crash_p))(ck)
        crash_e = draw & evalid
        if sir:
            crash_e = crash_e & (packed < n_rows * b)  # not on triggers
        sub = (1 - crash_e.astype(I32)) * b + packed % b
        # Single-key sort of id*2b + sub (sub < 2b): the same order -- and
        # the same tie-stability -- as the 2-key (id*b, sub) sort, at half
        # the sorted bytes and a simpler compare.  uint32 range: batch_ticks
        # guarantees span*b < 2^31, hence span*2b < 2^32 exactly.
        comb = (packed // b).astype(jnp.uint32) * jnp.uint32(2 * b) \
            + sub.astype(jnp.uint32)
        if words is not None:
            comb_s, *wcols_s = jax.lax.sort((comb,) + wcols, num_keys=1)
        else:
            comb_s = jax.lax.sort(comb)
        key1_s = (comb_s // jnp.uint32(2 * b)).astype(I32) * b
        sub_s = (comb_s % jnp.uint32(2 * b)).astype(I32)
        toff_s = sub_s % b
        crash_s = sub_s < b
    else:
        if words is not None:
            packed_s, *wcols_s = jax.lax.sort((packed,) + wcols,
                                              num_keys=1)
        else:
            packed_s = jnp.sort(packed)
        key1_s = packed_s // b * b
        toff_s = packed_s % b
        crash_s = jnp.zeros((ccap,), bool)
    is_data = key1_s < n_rows * b
    if sir:
        is_trig = (key1_s >= tb) & (key1_s < sentinel)
        ids_s = jnp.where(is_trig, (key1_s - tb) // b, key1_s // b)
        touched = is_data | is_trig
    else:
        ids_s = key1_s // b
        touched = is_data
    # Touched lanes are a PREFIX (sentinels sort last) with ascending ids,
    # so for SI the gather/scatter below can claim sorted indices (trash
    # lanes ride at n_rows, clamped by the gather / dropped by the
    # scatter).  SIR cannot: trigger ids restart below the data run's
    # tail.
    srt = not sir
    idx = jnp.where(touched, ids_s, n_rows)
    pre = flags.at[idx].get(indices_are_sorted=srt, mode="clip")
    pre_recv = (pre & RECEIVED) > 0
    if crash_p > 0.0 or track_crashed:
        pre_crash = ((pre & CRASHED) > 0) & touched
    else:
        pre_crash = jnp.zeros((ccap,), bool)
    counted = is_data & ~pre_crash
    dm = counted.sum(dtype=I32)
    prev = jnp.concatenate([jnp.full((1,), -1, I32), key1_s[:-1]])
    first = (key1_s != prev) & is_data
    dc = jnp.zeros((), I32)
    newly = first & counted & ~pre_recv & ~crash_s
    dr = newly.sum(dtype=I32)
    delta = newly.astype(jnp.uint8) * RECEIVED
    if crash_p > 0.0:
        run_crash = first & crash_s & ~pre_crash
        dc = run_crash.sum(dtype=I32)
        delta = delta + run_crash.astype(jnp.uint8) * CRASHED
        if down_since is not None:
            down_since = down_since.at[
                jnp.where(run_crash, ids_s, n_rows)].set(
                win_tick, mode="drop")
    # (No sorted claim here: non-winning lanes divert to n_rows BETWEEN
    # the ascending winners, breaking monotonicity.)
    flags = flags.at[jnp.where(delta > 0, ids_s, n_rows)].add(
        delta, mode="drop")
    senders = newly
    if sir:
        fire = is_trig & pre_recv & ~pre_crash & ~((pre & REMOVED) > 0)
        senders = newly | fire
    if words is not None:
        words_s = jnp.stack(wcols_s, axis=1)
        # Reversed segmented OR-scan: reversing keeps runs contiguous and
        # turns each run's LAST lane into its segment start, so the
        # inclusive scan leaves the whole-run OR at the run's original
        # FIRST lane (the winner; other lanes hold suffix-ORs, unread).
        last = jnp.concatenate([key1_s[:-1] != key1_s[1:],
                                jnp.ones((1,), bool)])

        def _seg_or(a, c):
            af, av = a
            cf, cv = c
            return af | cf, jnp.where(cf[..., None], cv, av | cv)

        _, rv = jax.lax.associative_scan(
            _seg_or, (last[::-1], words_s[::-1]))
        run_or = rv[::-1]
        win = first & counted & ~crash_s  # newly minus the ~pre_recv gate
        idxw = jnp.where(win, ids_s, n_rows)
        old = rumor_words.at[jnp.minimum(idxw, n_rows - 1)].get()
        delta_w = jnp.where(win[:, None], run_or & ~old, jnp.uint32(0))
        rumor_words = rumor_words.at[idxw].set(
            jnp.where(win[:, None], old | delta_w, jnp.uint32(0)),
            mode="drop")
        drecv = jnp.concatenate([
            ((delta_w[:, wi][:, None]
              >> jnp.arange(32, dtype=jnp.uint32)[None, :])
             & jnp.uint32(1)).astype(I32).sum(axis=0)
            for wi in range(words.shape[1])])
        senders = win & (delta_w != 0).any(axis=1)
        return (flags, dm, dr, dc, ids_s, toff_s, senders, down_since,
                rumor_words, delta_w, drecv)
    return flags, dm, dr, dc, ids_s, toff_s, senders, down_since


def sender_compaction_cap(cfg: Config, ccap: int) -> int:
    """Sender-compaction batch width (0 = dense append), shared by the
    single-device and sharded window steps so the two engines cannot
    drift.

    At mean degree d only ~1/(0.9 d) of drained entries are NEW senders,
    yet the dense append pays friends-gather + mail-scatter at full
    ccap x k width -- profiled at 65% of the fanout-6 window (mail
    scatter 33% incl. its internal 3M-lane sort, friends gather 26%),
    both element-bound at these widths.  Compacting senders via ONE
    cumsum-rank + ONE packed scatter (not the 5-op first_true_indices
    selection that measured 6-10% slower at fanout 3 in r2) shrinks
    those widths 2-4x; the reservation order -- hence the mail layout,
    hence every position-keyed crash draw -- is bit-identical on the
    single-device path (ranks ascend in chunk order, batches continue
    sequentially), verified against the exact pre-compaction totals at
    1e7/1e8 fanout 3 and 6, and pinned by a dense-vs-compacted A/B test.
    CAVEAT: the identity holds while mail_dropped stays 0 (auto slot_cap
    budgets for exactly that).  Under slot-cap overflow the paths
    diverge at the margin: an overflowed sender in an early batch
    reserves nothing, so later batches start at lower offsets and may
    fit entries the dense single-call append -- whose per-chunk prefix
    counts overflowed senders' reservations -- would also have
    overflowed.  Measured 2026-07-31 (warm, v5e): 1e7
    fanout 6: 6.29 -> 3.61s; 1e8 fanout 6: 49.5 -> 37.3s; 1e7 fanout 3
    headline: 2.61 -> 2.36s (1.19B node-updates/s).  The batch width
    tracks the typical sender fraction (ccap/2 covers the ~38% of
    actual degree 3; ccap/4 the ~20% of degree >= 5; the >= 3.0 bound
    admits erdos lambda=3, whose sender fraction matches kout fanout 3
    -- kout mean_degree is the column width fanout+1); actual degree
    <= 2 keeps the dense path -- nearly every entry is a new sender
    there, so batching would only add ops."""
    if cfg.mean_degree >= 5.0:
        return ccap // 4
    if cfg.mean_degree >= 3.0:
        return ccap // 2
    return 0


def narrow_tail_cap(scap: int) -> int:
    """Width of the narrow TAIL batches (0 = no narrow path).

    The append's per-batch cost has two regimes: the mail scatter and
    friends gather are element-bound at full scap width (profiled 6.3 +
    2.6 ms at scap=262k, fanout 6, v5e) but drop toward the ~1-2 ms
    per-op floor at ~1/8 width.  Near the coverage target most chunks
    produce only a few thousand NEW senders, yet each paid one
    full-width batch -- at the 1e7 fanout-6 endgame that was 27
    batches/window for near-empty sender sets (~45% of the window).
    Remainders <= 2*narrow widths run as 1-2 narrow batches instead;
    larger remainders keep the full-width batch (3+ narrow trips would
    cost more than the one element-bound batch they replace).
    Bit-identicality: reservation layout depends only on the sender
    ORDER (per-slot starts ride mail_cnt across batches) and every draw
    is (tick, row)-keyed, so batch-boundary placement cannot change the
    trajectory in the zero-overflow regime (same envelope as
    sender_compaction_cap's caveat; pinned by the narrow-tail A/B
    test)."""
    if scap <= 0:
        return 0
    # Strictly scap//8 (no floor-clamp): a clamped width in [scap/2, scap)
    # would make `tail` always true and split every remainder into two
    # near-half-width batches -- same elements, double the op floor.  Below
    # scap=8192 the batches are op-floor-bound at EITHER width, so the
    # narrow path is disabled rather than widened.
    ns = scap // 8
    return ns if ns >= 1024 else 0


def narrow_tail_trips(count, scap: int, nscap: int):
    """Trip counts (nfull, nnarrow) covering `count` senders: full-width
    batches, then -- when the remainder fits 1-2 narrow batches -- the
    narrow tail; larger remainders keep one more full-width batch.  The
    ONE scheduling rule shared by the single-device and sharded steps
    (sharded passes the pmax-agreed count so collective counts stay
    uniform across shards); `count` is a traced scalar."""
    rem = count % scap
    tail = rem <= 2 * nscap
    nfull = count // scap + jnp.where(tail, 0, 1)
    nnarrow = jnp.where(tail, (rem + nscap - 1) // nscap, 0)
    return nfull, nnarrow


def run_narrow_tail(make_abody, carry, count, scap: int, between=None):
    """Drive the batched append schedule: full scap-wide batches, then --
    when narrow_tail_cap engages -- the 1-2 narrow tail batches.  The ONE
    driver shared by the single-device and sharded steps; `make_abody`
    builds a fori body for a (width, lo_of) pair, `count` is the (traced)
    sender count -- pmax-agreed by the sharded caller so collective
    counts stay uniform.  `between`, when given, transforms the carry
    after the full-width loop and before the narrow tail (and is applied
    unconditionally even when the narrow loop runs zero trips): the
    pipelined sharded append uses it to flush the last full batch's
    staged drain, so the homogeneous-shape pend carry never crosses into
    the differently-shaped narrow batches."""
    nscap = narrow_tail_cap(scap)
    if nscap:
        nfull, nnarrow = narrow_tail_trips(count, scap, nscap)
    else:
        nfull = (count + scap - 1) // scap
    carry = jax.lax.fori_loop(
        0, nfull, make_abody(scap, lambda jb: jb * scap), carry)
    if between is not None:
        carry = between(carry)
    if nscap:
        full_end = nfull * scap
        carry = jax.lax.fori_loop(
            0, nnarrow,
            make_abody(nscap, lambda jb: full_end + jb * nscap), carry)
    return carry


def sender_batch(senders, srank, scnt, spacked, b: int, scap: int, jb,
                 lo=None, sdelta=None):
    """Extract compacted sender batch `jb`: rows with rank in
    [lo, lo+scap) land at rank-relative positions via one packed
    scatter (in-bounds trash cell at scap, sliced off).  `lo` defaults
    to jb*scap (uniform batches); the narrow-tail path passes the
    absolute start rank.  Returns (sids, stoff, svalid) of static width
    scap; with `sdelta` (multi-rumor per-lane payload words, (ccap, W))
    a fourth value carries each compacted sender's word row."""
    if lo is None:
        lo = jb * scap
    pos = srank - lo
    sel = senders & (pos >= 0) & (pos < scap)
    idx = jnp.where(sel, pos, scap)
    buf = jnp.zeros((scap + 1,), I32).at[idx].set(
        jnp.where(sel, spacked, 0))[:scap]
    sids = buf // b
    stoff = buf - sids * b
    svalid = jnp.arange(scap, dtype=I32) < (scnt - lo)
    if sdelta is not None:
        bufw = jnp.zeros((scap + 1, sdelta.shape[1]), jnp.uint32).at[
            idx].set(jnp.where(sel[:, None], sdelta, jnp.uint32(0)))[:scap]
        return sids, stoff, svalid, bufw
    return sids, stoff, svalid


def apply_fault_window_flags(cfg: Config, flags, down_since, tick,
                             ids_global, base_key, nticks: int):
    """Event-engine adapter for scenario.fault_window: the crashed mask
    lives in flags bit1.  Applied at window start (the window's drain then
    black-holes deliveries to freshly crashed nodes, the event analog of
    the ring engine's per-tick `where(crashed, 0, arrivals)`).  Recovery
    clears ONLY the crashed bit: a recovered node keeps its received (and
    SIR removed) history.  Returns (flags, down_since, d_crash,
    d_recover); a no-op with Python-zero deltas when the scenario has no
    fault events."""
    scen = cfg.scenario_resolved
    if not scen.has_faults:
        return flags, down_since, 0, 0
    crashed = (flags & CRASHED) > 0
    new_crash, recover, down, dc, drc = _scen.fault_window(
        scen, cfg.n, tick, nticks, ids_global, crashed, down_since,
        base_key)
    flags = jnp.where(recover, flags & ~CRASHED, flags)
    flags = jnp.where(new_crash, flags | CRASHED, flags)
    return flags, down, dc, drc


def make_window_step_fn(cfg: Config, n_local: int | None = None):
    """One B-tick window transition: drain this window's packed list in
    chunks (drain_chunk_core), and emit the newly infected nodes' broadcasts
    at their actual delivery ticks.  SIR adds re-broadcast triggers and
    per-sender removal draws (drain_chunk_core with sir=True).

    Scenario faults (crash waves / churn / recovery) apply at window
    start; partition masks filter every append at send time.  With
    -scenario off and -overlay-heal off every gate below is Python-False
    and the traced program is the pre-scenario one, byte for byte."""
    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    ccap = drain_chunk(cfg, n_local)
    tail = ring_tail(cfg, n_local)
    crash_p = epidemic.p_eff(cfg, cfg.crashrate)
    sir = cfg.protocol == "sir"
    removal_p = epidemic.p_eff(cfg, cfg.removal_rate) if sir else 0.0
    scap = sender_compaction_cap(cfg, ccap)
    # Guaranteed-duplicate suppression (append_messages docstring); the
    # resolved gate implies crash_p == 0 (config.validate rejects "on"
    # otherwise), so the per-reception draw stream it would shift is empty.
    suppress = cfg.dup_suppress_resolved
    scen = cfg.scenario_resolved
    faults = cfg.faults_enabled
    # Scenario gates: the drain must honor crashed bits even at
    # crash_p == 0 once faults can set them; the crash clock is carried
    # only when reception crashes can stamp it; the partition counter is
    # carried only when partitions exist.
    track_crashed = faults or scen.has_faults
    track_down = faults and crash_p > 0.0
    track_part = scen.has_partitions
    # Multi-rumor (static): entry payload words ride the carry alongside
    # mail_ids; injection replaces the seed.  Off => every gate below is
    # Python-False and the traced program is the single-rumor one.
    multi = cfg.multi_rumor
    if multi:
        from gossip_simulator_tpu.ops.mailbox import ring_append
    # Resolved at BUILD time: the pallas capability probes must run eagerly
    # (ops/pallas_deliver._probe_case and the megakernel twin), never
    # inside the trace below.
    dkern = cfg.deliver_kernel_resolved
    p2 = cfg.phase2_kernel_resolved

    def step_fn(st: EventState, base_key: jax.Array) -> EventState:
        n = st.flags.shape[0]
        w = st.tick // b
        slot = w % dw
        if multi:
            # Streaming/oneshot injection: self-addressed source entries
            # appended BEFORE the slot count is read, so a rumor due this
            # window drains -- and its source starts forwarding -- this
            # window.  make_seed_fn is an identity under multi.
            ipay, iwords, iwslot, ivalid = injection_batch(
                cfg, st.tick, base_key, b, dw)
            icap = (st.mail_ids.shape[0] - tail) // dw
            (mi, mw), icnt, idrop = ring_append(
                (st.mail_ids, st.mail_words), st.mail_cnt,
                st.mail_dropped, (ipay, iwords), iwslot, ivalid, dw,
                icap, kernel=dkern)
            st = st._replace(mail_ids=mi, mail_words=mw, mail_cnt=icnt,
                             mail_dropped=idrop)
        m = st.mail_cnt[0, slot]
        dm0 = st.sup_cnt[0, slot]
        mail0 = st.mail_ids
        flags1, down1, dsc, dsr = apply_fault_window_flags(
            cfg, st.flags, st.down_since, st.tick,
            jnp.arange(n, dtype=I32), base_key, b)
        st = st._replace(flags=flags1, down_since=down1)
        if suppress:
            # Pre-drain compaction: duplicates that slipped past the
            # append-side filter die here, before the sorted drain pays
            # for them -- but only in the endgame regime where the
            # filter's random gather beats the drain it removes
            # (PREDRAIN_MIN_RECV_FRAC).  Zero filter chunks otherwise.
            cap0 = (mail0.shape[0] - tail) // dw
            go = st.total_received >= I32(
                int(PREDRAIN_MIN_RECV_FRAC * n))
            mail0, kept, fdat = predrain_compact(
                b, n, dw, cap0, ccap, sir, st.flags, mail0, slot,
                jnp.where(go, m, 0))
            m = jnp.where(go, kept, m)
            dm0 = dm0 + fdat
        chunks = (m + ccap - 1) // ccap
        ckey = _rng.tick_key(base_key, w, _rng.OP_CRASH)

        # Conditional loop-carry tail: the crash clock rides the chunk
        # loop only when reception crashes can stamp it, the partition
        # counter only when partitions exist -- the scenario-off carry is
        # the pre-scenario tuple exactly.
        def pack(core, down, part, mt=()):
            c = list(core)
            if track_down:
                c.append(down)
            if track_part:
                c.append(part)
            return tuple(c) + tuple(mt)

        def unpack(c):
            core, i = c[:8], 8
            down = part = None
            if track_down:
                down, i = c[i], i + 1
            if track_part:
                part, i = c[i], i + 1
            return core, down, part, c[i:]

        def body(j, carry):
            (flags, mail_ids, mail_cnt, sup_cnt, dm, dr, dc,
             dropped), down, part, mt = unpack(carry)
            mail_words = rumor_words = rrecv = delta_w = None
            if multi:
                mail_words, rumor_words, rrecv = mt
            off0 = j * ccap
            entry_pos = off0 + jnp.arange(ccap, dtype=I32)
            evalid = entry_pos < m
            cap = (mail_ids.shape[0] - tail) // dw
            packed = jax.lax.dynamic_slice(
                mail_ids, (slot * cap + off0,), (ccap,))
            if multi:
                wchunk = jax.lax.dynamic_slice(
                    mail_words, (slot * cap + off0, 0),
                    (ccap, mail_words.shape[1]))
                (flags, cdm, cdr, cdc, ids_s, toff_s, senders, down,
                 rumor_words, delta_w, drecv) = drain_chunk_core(
                    crash_p, b, n, flags, packed, evalid, entry_pos,
                    ckey, sir=sir, track_crashed=track_crashed,
                    down_since=down, win_tick=st.tick, words=wchunk,
                    rumor_words=rumor_words)
                rrecv = rrecv + drecv
            else:
                flags, cdm, cdr, cdc, ids_s, toff_s, senders, down = \
                    drain_chunk_core(crash_p, b, n, flags, packed, evalid,
                                     entry_pos, ckey, sir=sir,
                                     track_crashed=track_crashed,
                                     down_since=down, win_tick=st.tick)
            dm, dr, dc = dm + cdm, dr + cdr, dc + cdc
            if scap:
                # Compact senders to <=scap-row batches (sender_batch),
                # then append at reduced width.  Same (tick, row)-keyed
                # RNG streams, same reservation order => bit-identical
                # mail layout and totals (canary-checked).
                srank = jnp.cumsum(senders.astype(I32)) - 1
                scnt = senders.sum(dtype=I32)
                spacked = ids_s * b + toff_s

                def make_abody(width, lo_of):
                    def abody(jb, acarry):
                        (aflags, amail_ids, amail_cnt, asup,
                         adropped) = acarry[:5]
                        i = 5
                        apart = awords = sw = None
                        if track_part:
                            apart, i = acarry[i], i + 1
                        if multi:
                            awords = acarry[i]
                            sids, stoff, svalid, sw = sender_batch(
                                senders, srank, scnt, spacked, b, width,
                                jb, lo=lo_of(jb), sdelta=delta_w)
                        else:
                            sids, stoff, svalid = sender_batch(
                                senders, srank, scnt, spacked, b, width,
                                jb, lo=lo_of(jb))
                        stick2 = w * b + stoff
                        strig = None
                        if sir:
                            # Removal draw per sender at its send tick
                            # (the ring engine's removal-after-send,
                            # tick_core); removed senders still broadcast
                            # this once but schedule no next trigger.
                            rows = jnp.where(svalid, sids, n)
                            rk = _sender_keys(base_key, _rng.OP_REMOVE,
                                              stick2, rows)
                            rem = (jax.vmap(
                                lambda kk: jax.random.bernoulli(
                                    kk, removal_p))(rk) & svalid) \
                                if removal_p > 0.0 \
                                else jnp.zeros((width,), bool)
                            aflags = aflags.at[
                                jnp.where(rem, sids, n)].add(
                                REMOVED, mode="drop")
                            strig = svalid & ~rem
                        if multi:
                            (amail_ids, amail_cnt, adropped, sa, ablk,
                             awords) = append_messages(
                                cfg, amail_ids, amail_cnt, adropped,
                                sids, svalid, stick2, st.friends,
                                st.friend_cnt, base_key, swords=sw,
                                mail_words=awords, kernel=dkern,
                                phase2=p2)
                        else:
                            (amail_ids, amail_cnt, adropped, sa,
                             ablk) = append_messages(
                                cfg, amail_ids, amail_cnt, adropped,
                                sids, svalid, stick2, st.friends,
                                st.friend_cnt, base_key, strig=strig,
                                flags=aflags if suppress else None,
                                kernel=dkern, phase2=p2)
                        out = (aflags, amail_ids, amail_cnt,
                               asup + sa[None, :], adropped)
                        if track_part:
                            out = out + (apart + ablk,)
                        if multi:
                            out = out + (awords,)
                        return out
                    return abody

                # Small remainders run as 1-2 narrow batches at ~op-floor
                # cost instead of one element-bound full-width batch
                # (narrow_tail_cap's rationale; run_narrow_tail drives).
                acarry0 = (flags, mail_ids, mail_cnt, sup_cnt, dropped)
                if track_part:
                    acarry0 = acarry0 + (part,)
                if multi:
                    acarry0 = acarry0 + (mail_words,)
                out = run_narrow_tail(make_abody, acarry0, scnt, scap)
                (flags, mail_ids, mail_cnt, sup_cnt, dropped) = out[:5]
                if track_part:
                    part = out[5]
                if multi:
                    mail_words = out[-1]
                return pack((flags, mail_ids, mail_cnt, sup_cnt, dm, dr,
                             dc, dropped), down, part,
                            (mail_words, rumor_words, rrecv)
                            if multi else ())
            sticks = w * b + toff_s
            strig = None
            if sir:
                # Removal draw per sender at its send tick (the ring
                # engine's removal-after-send, tick_core); removed senders
                # still broadcast this once but schedule no next trigger.
                rows = jnp.where(senders, ids_s, n)
                rk = _sender_keys(base_key, _rng.OP_REMOVE, sticks, rows)
                rem = jax.vmap(lambda kk: jax.random.bernoulli(
                    kk, removal_p))(rk) & senders if removal_p > 0.0 \
                    else jnp.zeros(senders.shape, bool)
                flags = flags.at[jnp.where(rem, ids_s, n)].add(
                    REMOVED, mode="drop")
                strig = senders & ~rem
            # Dense append (low-degree configs): the mask feeds
            # append_messages directly -- senders appear in the same
            # ascending-id order a nonzero() compaction would produce, so
            # reservation ranks and the mail layout are bit-identical.
            # (Measured 2026-07-30: compacting senders to ccap/2 via
            # first_true_indices before the append was bit-identical but
            # ~6-10% SLOWER at n=1e7/1e8 fanout 3 -- the 5-op selection
            # cost more than the 2.4x width saving; the 2-op rank-scatter
            # compaction above pays only at higher degree.)
            if multi:
                (mail_ids, mail_cnt, dropped, sa, blk,
                 mail_words) = append_messages(
                    cfg, mail_ids, mail_cnt, dropped,
                    jnp.where(senders, ids_s, 0), senders, sticks,
                    st.friends, st.friend_cnt, base_key,
                    swords=delta_w, mail_words=mail_words, kernel=dkern,
                    phase2=p2)
            else:
                mail_ids, mail_cnt, dropped, sa, blk = append_messages(
                    cfg, mail_ids, mail_cnt, dropped,
                    jnp.where(senders, ids_s, 0), senders, sticks,
                    st.friends, st.friend_cnt, base_key, strig=strig,
                    flags=flags if suppress else None, kernel=dkern,
                    phase2=p2)
            if track_part:
                part = part + blk
            return pack((flags, mail_ids, mail_cnt, sup_cnt + sa[None, :],
                         dm, dr, dc, dropped), down, part,
                        (mail_words, rumor_words, rrecv)
                        if multi else ())

        z = jnp.zeros((), I32)
        # Credit this window's deferred duplicate counts (banked by
        # append_messages at append time) exactly where their deliveries
        # would have counted; appends during this drain only target later
        # windows (delay >= B), so the slot accrues nothing new before the
        # zeroing below.
        mt0 = ()
        if multi:
            mt0 = (st.mail_words, st.rumor_words,
                   jnp.zeros_like(st.rumor_recv))
        out = jax.lax.fori_loop(
            0, chunks, body,
            pack((st.flags, mail0, st.mail_cnt, st.sup_cnt,
                  dm0, z, z, st.mail_dropped), st.down_since, z, mt0))
        (flags, mail_ids, mail_cnt, sup_cnt, dm, dr, dc,
         dropped), down, part, mt = unpack(out)
        mail_cnt = mail_cnt.at[0, slot].set(0)
        sup_cnt = sup_cnt.at[0, slot].set(0)
        st = st._replace(
            flags=flags, mail_ids=mail_ids,
            mail_cnt=mail_cnt, sup_cnt=sup_cnt, tick=st.tick + b,
            total_message=msg64_add(st.total_message, dm),
            total_received=st.total_received + dr,
            total_crashed=st.total_crashed + dc,
            mail_dropped=dropped)
        if multi:
            # The drained slot's stale words are never zeroed: the next
            # cycle's appends rewrite the [0, count) prefix and the drain
            # zeroes words past the count (evalid gate in
            # drain_chunk_core), so no stale word is ever read.
            mail_words, rumor_words, rrecv = mt
            rumor_recv = st.rumor_recv + rrecv
            rumor_done = stamp_rumor_done(cfg, rumor_recv, st.rumor_done,
                                          st.tick)
            st = st._replace(mail_words=mail_words,
                             rumor_words=rumor_words,
                             rumor_recv=rumor_recv,
                             rumor_done=rumor_done)
        if track_down:
            st = st._replace(down_since=down)
        if scen.active:
            st = st._replace(
                scen_crashed=st.scen_crashed + dsc,
                scen_recovered=st.scen_recovered + dsr)
        if track_part:
            st = st._replace(part_dropped=st.part_dropped + part)
        return st

    return step_fn


def make_seed_fn(cfg: Config):
    """Uniform-random sender's initial broadcast (simulator.go:240-241),
    through the same append path as every later wave.  Uses the ring
    engine's SEED_TICK-keyed streams: a dedicated one-sender append so the
    seed's delay/drop draws do not depend on tick-0 window state.

    Multi-rumor: an identity -- sources are injected by the window step
    itself (injection_batch appends self-addressed entries, so a source
    counts as infected when its entry DRAINS, and oneshot lanes only
    validate in window 0).  Backends still call seed() unconditionally."""
    if cfg.multi_rumor:
        def seed_noop(st: EventState, base_key: jax.Array) -> EventState:
            return st

        return seed_noop

    def seed_fn(st: EventState, base_key: jax.Array) -> EventState:
        n = st.flags.shape[0]
        b = batch_ticks(cfg)
        dw = ring_windows(cfg)
        cap = (st.mail_ids.shape[0] - ring_tail(cfg, n)) // dw
        ks = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_SEED_NODE)
        kd = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_DELAY)
        kp = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_DROP)
        sender = jax.random.randint(ks, (), 0, n, dtype=I32)
        flags, total_received = st.flags, st.total_received
        if cfg.protocol == "sir" or not cfg.compat_reference:
            # Reference quirk: the seed itself is never marked received
            # (SURVEY §5.4); we count it unless compat is requested.  SIR
            # always marks it: trigger firing requires the received bit (the
            # reference has no SIR, so there is no compat surface to match).
            flags = flags.at[sender].set(RECEIVED)
            total_received = total_received + 1
        k = st.friends.shape[1]
        sf = st.friends[sender]
        scnt = st.friend_cnt[sender]
        delay = jnp.maximum(
            jax.random.randint(jax.random.fold_in(kd, sender), (),
                               cfg.delaylow, cfg.delayhigh, dtype=I32), 1)
        drop = _rng.bernoulli(jax.random.fold_in(kp, sender),
                              epidemic.p_eff(cfg, cfg.droprate), (k,))
        arrive = st.tick + delay
        wslot = (arrive // b) % dw
        edge = (jnp.arange(k, dtype=I32) < scnt) & ~drop & (sf >= 0)
        scen = cfg.scenario_resolved
        if scen.has_partitions:
            blocked = _scen.partition_blocked(
                scen, cfg.n, st.tick, sender, sf) & edge
            st = st._replace(
                part_dropped=st.part_dropped + blocked.sum(dtype=I32))
            edge = edge & ~blocked
        payload = sf * b + arrive % b
        cols = jnp.cumsum(edge, dtype=I32) - 1  # exact-size, like append
        ec = edge.sum(dtype=I32)
        if cfg.protocol == "sir":
            # The seed is a sender like any other: a removal draw decides
            # whether it schedules a re-broadcast trigger (the ring
            # engine's SEED_TICK OP_REMOVE draw).
            kr = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_REMOVE)
            keep = ~_rng.bernoulli(kr, epidemic.p_eff(cfg, cfg.removal_rate),
                                   ())
            tb = trigger_base(n, b)
            cols = jnp.concatenate([cols, ec[None]])
            edge = jnp.concatenate([edge, keep[None]])
            payload = jnp.concatenate(
                [payload, (tb + sender * b + arrive % b)[None]])
            ec = ec + keep.astype(I32)
        base = st.mail_cnt[0, wslot]
        ok = base + ec <= cap
        flat = jnp.where(edge & ok, wslot * cap + base + cols,
                         dw * cap + jnp.arange(edge.shape[0], dtype=I32))
        mail_ids = st.mail_ids.at[flat].set(
            jnp.where(edge, payload, 0))  # trash cell if !ok / non-edge
        mail_cnt = st.mail_cnt.at[0, wslot].add(jnp.where(ok, ec, 0))
        dropped = st.mail_dropped + jnp.where(ok, 0, ec)
        return st._replace(flags=flags, total_received=total_received,
                           mail_ids=mail_ids, mail_cnt=mail_cnt,
                           mail_dropped=dropped)

    return seed_fn


def make_heal_fn(cfg: Config, n_local: int | None = None):
    """Single-device event-engine overlay healing (None when off): condemn
    dead friends (scenario.detect_dead), replace them via the phase-1
    makeup draw, and append the infected healers' re-sends into the mail
    ring at their drawn arrival ticks (scenario.heal_and_wave)."""
    if not cfg.overlay_heal_resolved:
        return None
    from gossip_simulator_tpu.ops.mailbox import ring_append

    b = batch_ticks(cfg, n_local)
    dw = ring_windows(cfg, n_local)
    detect = cfg.heal_detect_ms
    multi = cfg.multi_rumor

    dkern = cfg.deliver_kernel_resolved

    def heal_fn(st: EventState, base_key: jax.Array) -> EventState:
        n, k = st.friends.shape
        ids = jnp.arange(n, dtype=I32)
        crashed = (st.flags & CRASHED) > 0
        detected = _scen.detect_dead(crashed, st.down_since, st.tick,
                                     detect)
        healer_ok = ~crashed
        sender_inf = ((st.flags & RECEIVED) > 0) & ~crashed \
            & ~((st.flags & REMOVED) > 0)
        bits = _scen.heal_peer_bits(detected, sender_inf)
        friends, resend, pull, delay, clear, rep, blk = _scen.heal_and_wave(
            cfg, st.friends, st.friend_cnt, bits, healer_ok, sender_inf,
            _scen.rejoined_mask(st.down_since), ids, st.tick, base_key)
        arrive = st.tick + delay  # per healer row (shared across its lanes)
        wslot = jnp.broadcast_to(((arrive // b) % dw)[:, None],
                                 (n, k)).reshape(-1)
        off = (arrive % b)[:, None]
        payload = (friends * b + off).reshape(-1)
        cap = (st.mail_ids.shape[0] - ring_tail(cfg, n_local)) // dw
        if multi:
            wc = st.rumor_words.shape[1]
            # Resends carry the healer's FULL rumor set; a churned node
            # rejoin-pulls ALL of its friend's rumors (the per-rumor
            # generalization of the single "infected" bit).
            rw = jnp.broadcast_to(st.rumor_words[:, None, :],
                                  (n, k, wc)).reshape(-1, wc)
            (mail, mailw), cnt, dropped = ring_append(
                (st.mail_ids, st.mail_words), st.mail_cnt,
                st.mail_dropped, (payload, rw), wslot,
                resend.reshape(-1), dw, cap, kernel=dkern)
            ppay = jnp.broadcast_to((ids * b)[:, None] + off,
                                    (n, k)).reshape(-1)
            fw = st.rumor_words[jnp.where(friends >= 0, friends,
                                          0)].reshape(-1, wc)
            (mail, mailw), cnt, dropped = ring_append(
                (mail, mailw), cnt, dropped, (ppay, fw), wslot,
                pull.reshape(-1), dw, cap, kernel=dkern)
            st = st._replace(mail_words=mailw)
        else:
            (mail,), cnt, dropped = ring_append(
                (st.mail_ids,), st.mail_cnt, st.mail_dropped, (payload,),
                wslot, resend.reshape(-1), dw, cap, kernel=dkern)
            # Rejoin pull responses deliver to the puller's OWN row.
            ppay = jnp.broadcast_to((ids * b)[:, None] + off,
                                    (n, k)).reshape(-1)
            (mail,), cnt, dropped = ring_append(
                (mail,), cnt, dropped, (ppay,), wslot, pull.reshape(-1),
                dw, cap, kernel=dkern)
        return st._replace(
            friends=friends, mail_ids=mail, mail_cnt=cnt,
            mail_dropped=dropped,
            down_since=jnp.where(clear, -1, st.down_since),
            heal_repaired=st.heal_repaired + rep,
            part_dropped=st.part_dropped + blk)

    return heal_fn


def make_window_fn(cfg: Config, window: int):
    """Advance ~`window` simulated ms as one device call (the driver's poll
    cadence): ceil(window / B) batched window steps, then -- with
    -overlay-heal on -- one healing pass (the same cadence and tick keys
    the fast-path loop heals at)."""
    step = make_window_step_fn(cfg)
    heal = make_heal_fn(cfg)
    steps = max(1, -(-window // batch_ticks(cfg)))

    @functools.partial(jax.jit, donate_argnums=(0,))
    def window_fn(st: EventState, base_key: jax.Array) -> EventState:
        st = jax.lax.fori_loop(0, steps, lambda _, s: step(s, base_key), st)
        if heal is not None:
            st = heal(st, base_key)
        return st

    return window_fn


def poll_window_steps(cfg: Config) -> int:
    """B-tick steps per 10 ms poll window: the cadence every fast-path run
    cond must check at so it reports the same death tick / totals as the
    windowed driver loop (with B < 10 a per-step check stops earlier).  The
    10 is base.WINDOW_MS, hardcoded to keep models/ free of backends/
    imports.  Shared by this engine's run fn and the sharded one
    (parallel/event_sharded.make_run_to_coverage_fn)."""
    return max(1, -(-10 // batch_ticks(cfg)))


def make_run_to_coverage_fn(cfg: Config, telemetry: bool = False):
    """Bounded device-side while_loop, same contract as the ring engine's
    (epidemic.make_run_to_coverage_fn / base.run_bounded_to_target).  With
    `telemetry`, carries the device-resident per-window History and records
    one counters row per poll window (signature gains a `hist` argument and
    the return becomes `(st, hist)`)."""
    step = make_window_step_fn(cfg)
    heal = make_heal_fn(cfg)
    max_steps = cfg.max_rounds
    steps = poll_window_steps(cfg)
    # Healing can revive an empty ring (pending dead-friend detections
    # re-send from infected healers), so heal-on runs drop the early-death
    # exit (see epidemic.make_run_to_coverage_fn).
    check_in_flight = not cfg.overlay_heal_resolved
    multi = cfg.multi_rumor
    rumors = cfg.rumors
    stream = cfg.traffic == "stream"
    last_inj = cfg.last_inject_tick

    def cond_live(s: EventState, target_count, until):
        # The in-flight term (a dw-element emptiness test -- free) stops
        # the loop the moment the wave dies instead of spinning empty
        # windows to max_rounds (the host-side exhaustion check only
        # runs between bounded calls).
        if multi:
            # Every rumor must hit the target; lanes >= R are padding
            # (always 0), so the static [:R] slice is load-bearing.
            recv = jnp.min(s.rumor_recv[:rumors])
        else:
            recv = s.total_received
        live = ((recv < target_count)
                & (s.tick < max_steps) & (s.tick < until))
        if check_in_flight:
            alive = in_flight(s) > 0
            if multi:
                # An empty ring is not death while the injection
                # schedule still has rumors to start -- including tick 0
                # of a oneshot run (last_inj = 0), where seeding happens
                # INSIDE the first window step rather than before the
                # loop (seed() is a no-op under the rumor axis).
                alive = alive | (s.tick <= last_inj)
            live = live & alive
        return live

    def run_window(s: EventState, base_key):
        s = jax.lax.fori_loop(0, steps, lambda _, x: step(x, base_key), s)
        if heal is not None:
            s = heal(s, base_key)
        return s

    if telemetry:
        from gossip_simulator_tpu.utils import telemetry as telem

        sir = cfg.protocol == "sir"
        spatial = telem.spatial_spec(cfg)

        @functools.partial(jax.jit, donate_argnums=(0, 4))
        def run_fn_t(st: EventState, base_key: jax.Array,
                     target_count: jax.Array, until: jax.Array,
                     hist: "telem.History"):
            def cond(carry):
                s, _ = carry
                return cond_live(s, target_count, until)

            def body(carry):
                s, h = carry
                s = run_window(s, base_key)
                row = telem.gossip_probe(
                    s, sir, rumors=rumors if multi else 0)
                return s, telem.record_window(h, row, st=s, spec=spatial)

            return jax.lax.while_loop(cond, body, (st, hist))

        return run_fn_t

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_fn(st: EventState, base_key: jax.Array, target_count: jax.Array,
               until: jax.Array) -> EventState:
        def cond(s: EventState):
            return cond_live(s, target_count, until)

        return jax.lax.while_loop(cond, lambda s: run_window(s, base_key), st)

    return run_fn


def removed_count(st) -> jnp.ndarray:
    """SIR removed-node count, engine-agnostic: no counter is threaded
    through the hot loop -- the removed set lives in the state (flags bit2 /
    SimState.removed), one O(n) reduction per host poll."""
    if hasattr(st, "flags"):
        return ((st.flags & REMOVED) > 0).sum(dtype=I32)
    return st.removed.sum(dtype=I32)
