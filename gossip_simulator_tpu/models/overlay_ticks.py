"""Tick-faithful dynamic-overlay construction (phase 1, `-overlay-mode ticks`).

The round-based engine (models/overlay.py) quantizes time: every emission is
delivered exactly one round later, and stabilization time is estimated as
rounds x mean_delay.  This engine keeps the reference's timing model instead:
every makeup/breakup send draws its OWN uniform delay in
[delaylow, delayhigh) ms (simulator.go:151-164, RandomNetworkDelay at
166-168), messages sit in a packed window-slot ring (the same layout as the
phase-2 event engine, models/event.py), and the stabilization clock is true
simulated milliseconds -- upgrading phase 1 to the same "option (b) faithful
ticks" story phase 2 already has (SURVEY §5.8).

Sequencing per B-tick window (B = min(10, delaylow), so a message emitted in
one window always arrives in a later one):
  1. drain this window's ring slot; stable-sort entries by arrival tick so
     per-node mailbox order is arrival order;
  2. deliver breakups / makeups into fixed-capacity mailboxes
     (ops/mailbox.deliver_pair) and process them slot-sequentially,
     node-parallel with the SAME per-message decision rules as the round
     engine (accept-under-fanin / evict-random / replace-on-breakup,
     simulator.go:66-94);
  3. every emission (replacement makeup, eviction breakup) is appended to
     the ring at its trigger's arrival tick plus a fresh per-message delay.

Bootstrap is a window-0 burst: the reference's needNewFriend loop re-arms
with no delay (simulator.go:103-105), so a node fills all `fanout` slots
at t~0, each makeup carrying an independent delay -- and once a node
reaches fanout it can never drop below it (breakup under/at fanout
replaces in place; removal only happens above fanout), so the loop never
re-fires.  init_state therefore draws the whole initial friends table and
appends the n*fanout makeup burst directly.

Quiescence is race-free and in the reference's own terms: a full 10 ms poll
window with zero processed membership messages AND an empty ring
(simulator.go:221-234 without the read-reset race, SURVEY §5.2).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models.overlay import (phase1_slot_fns,
                                                 spill_enabled)
from gossip_simulator_tpu.ops.mailbox import deliver_pair
from gossip_simulator_tpu.ops.select import first_true_indices
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32

MK = 0  # payload type bits: makeup
BK = 1  # breakup

# Mailbox-overflow spill capacity for THIS engine's cap-8 memory band
# (round 7, VERDICT r5 #4 -- the last counted-drop surface): overflowed
# (pay, typ*n+dst) pairs re-deliver FIRST next window instead of dropping,
# the reference's channel-full backpressure (senders block; membership
# traffic is delayed, never lost -- simulator.go:51-54).  Sizing rationale
# matches overlay.SPILL_CAP (the rounds engine observed 257 overflow
# messages TOTAL at 1e8/cap 8); past the spill cap messages still fall
# through to counted drops.  Module-level so tests can zero it (the
# control run of the spill suite).
SPILL_CAP = 65_536

# Prefix-dense drain delivery (round 7): after the drain's stable toff
# sort, the live entries are a packed PREFIX of known length (the ring
# count), so the chunked delivery runs plain ascending ranges with no
# per-chunk compaction scans (ops.mailbox deliver_pair prefix_len) --
# bit-identical, and the scans were the dominant term of the 10M chunk
# sweep (the justification for raising config.OVERLAY_TICKS_AUTO_MAX to
# 10M).  Module-level so the A/B test can pin prefix == masked.
PREFIX_DRAIN = True


def ticks_spill_cap(cfg: Config, n_rows: int | None = None) -> int:
    """Spill capacity for a single-device ticks surface (0 = disabled):
    engages exactly where drops were ever possible -- the slot-major
    memory band's shrunken stacked mailbox cap (spill_enabled mirrors the
    rounds engine: cap 16 overflow needs in-degree > 16 in one window,
    never observed, and threading the accumulator costs real op floors).
    The sharded hook path keeps counted drops (its routed delivery has no
    spill, like the sharded rounds overlay)."""
    n = n_rows if n_rows is not None else cfg.n
    cap_mb = cfg.mailbox_cap_for(n, stacked=True)
    return SPILL_CAP if (slotmajor(n) and spill_enabled(cap_mb)) else 0


# Narrowest occupancy-adaptive drain width (make_step_fn): windows with
# fewer live entries still sort this many lanes -- one sort's fixed cost
# is flat below ~262k on v5e, so narrower buys nothing in production.
# Module-level so a CPU test can lower it and drive the multi-branch
# switch at test n.
_DRAIN_WIDTH_FLOOR = 262_144


def batch_ticks(cfg: Config) -> int:
    """Window size B: delays >= delaylow >= B guarantee no intra-window
    causality; also bounded so pay = (src*2+type)*b + toff fits int32."""
    b = max(1, min(10, cfg.delaylow))
    while b > 1 and (2 * cfg.n + 2) * b >= 2**31:
        b //= 2
    return b


def ring_windows(cfg: Config) -> int:
    b = batch_ticks(cfg)
    return (b - 1 + cfg.delayhigh - 1) // b + 1


def slot_cap(cfg: Config, n_local: int | None = None) -> int:
    """Packed entries per window slot (per shard: destinations are uniform,
    so a shard's share of the traffic scales with its row count).  Peak
    traffic is the bootstrap burst (n*fanout makeups) spread over the delay
    span, plus a comparable response wave; 2x covers skew.  Overflow is
    counted, never silent."""
    n = n_local if n_local is not None else cfg.n
    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    cap = max(4096, int(math.ceil(
        2.0 * n * cfg.fanout * b / max(cfg.delay_span, 1))))
    cap = min(cap, (3 * 2**30) // (8 * max(dw, 1)))  # ~3 GB for both arrays
    return min(cap, (2**31 - 2) // max(dw, 1))


def emit_chunk(cfg: Config, n_local: int | None = None) -> int:
    """Emission-compaction chunk (the drain_chunk analog)."""
    n = n_local if n_local is not None else cfg.n
    return min(slot_cap(cfg, n_local), max(4096, min(262_144, n // 8)))


# Slot-major layout band (single-device; module-level so CPU tests can
# lower it and pin the band trajectory at test n).  Memory scale ONLY:
# a 4M band was tried 2026-08-01 to chase the 10M <=70s target and LOST
# (80.0 vs 72.7 s -- per-row emission scans move the same lane volume at
# settled windows, and the flat-mailbox dynamic-slice reads cost more
# than the 2-D column reads at 10M); above ~3.2e7 the node-major
# layouts are outright compile bombs and the band is mandatory.
TICKS_SLOTMAJOR_MIN_ROWS = 32_000_000


def slotmajor(n_rows: int) -> bool:
    """Memory/perf layout band for THIS engine (single-device only; the
    sharded hooks' per-shard slices stay node-major): above the band
    every (n_rows, small) node-major array is a TPU tiling liability --
    T(8,128) pads the narrow minor dim 16-25x, and the 100M ticks build
    died at compile on a 51 GB s32[1e8, 5] copy.  The band switches to
    the layouts the rounds engine adopted in round 4: slot-major
    (cap, n) emission buffers with per-ROW compaction scans (the full
    slots*n flat scan was the dominant settled-window cost), a
    rank-major FLAT stacked mailbox (ops.mailbox.deliver_pair flat=True)
    whose slots are contiguous dynamic_slices, and per-LANE-keyed
    bootstrap/emission draws (no (n, fanout) draw matrix).  Emission
    order becomes slot-major and the draw streams are lane-keyed -- a
    deterministic re-choice of arrival order and sample, the same move
    (and the same honesty argument: the reference's own arrival order is
    goroutine-racy) as the rounds engine's column band; every n below
    the band is bit-identical to round 4."""
    return n_rows >= TICKS_SLOTMAJOR_MIN_ROWS


def ticks_delivery_chunk(cfg: Config, n_rows: int) -> int:
    """Delivery chunk for THIS engine's slot drain (deliver_pair): its
    per-chunk cost is dominated by the scatters into the stacked
    [2n, cap] mailbox, which are ~10-20 ms FLAT per op at GB-scale
    targets regardless of lane count (README roadmap's device-span
    finding) -- so fewer, fatter chunks win at large n, unlike the
    rounds engine's n-wide deliver_columns where the 64k optimum stands
    (re-swept 2026-07-31 at 10M: 64k 3.40 s/window, 262k 2.60, 1M 2.26,
    2M 2.18; rounds mode with 1M chunks LOSES 733 -> 1134 ms/window).
    n/8 rounded up to a power of two (the sort pads internally), floor
    64k (<= 512k rows keeps the swept small-n optimum), cap 2M.
    Chunking is trajectory-neutral (rank continuation), so this is pure
    perf; -compact-chunk overrides.  The 2M cap is a registered tunable
    (tuning.py: overlay_ticks.delivery_chunk_cap)."""
    if cfg.compact_chunk > 0:
        return cfg.compact_chunk
    from gossip_simulator_tpu import tuning as _tuning

    cap = _tuning.value("overlay_ticks.delivery_chunk_cap", cfg)
    want = min(max(65_536, n_rows // 8), cap)
    return 1 << (want - 1).bit_length()


class OverlayTickState(NamedTuple):
    friends: jnp.ndarray  # int32[n, k]  -1 padded
    friend_cnt: jnp.ndarray  # int32[n]
    # Packed ring, slot s at [s*cap, (s+1)*cap); last element = trash cell.
    ring_dst: jnp.ndarray  # int32[dw*cap + 1]
    ring_pay: jnp.ndarray  # int32[dw*cap + 1]  (src*2 + type)*b + toff
    ring_cnt: jnp.ndarray  # int32[1, dw]
    # Mailbox-overflow spill pairs (pay, typ*n + dst), -1-padded key row;
    # re-delivered first next window (ticks_spill_cap; token (2, 1) where
    # the spill is disabled).  In-flight messages: quiescence requires an
    # empty spill.
    spill: jnp.ndarray  # int32[2, sc + 1]
    tick: jnp.ndarray  # int32[]  window-aligned simulated ms
    makeups: jnp.ndarray  # int32[]  cumulative processed (MakeUps)
    breakups: jnp.ndarray  # int32[]
    win_makeups: jnp.ndarray  # int32[]  this POLL window's counts
    win_breakups: jnp.ndarray  # int32[]
    mailbox_dropped: jnp.ndarray  # int32[]  mailbox + ring overflow


def _append(cfg: Config, ring_dst, ring_pay, ring_cnt, dropped,
            dst, pay, wslot, valid):
    """Append one (dst, pay) entry per True in `valid` into its window
    slot (shared one-hot reservation: ops.mailbox.ring_append)."""
    from gossip_simulator_tpu.ops.mailbox import ring_append

    dw = ring_windows(cfg)
    cap = (ring_dst.shape[0] - 1) // dw
    (ring_dst, ring_pay), ring_cnt, dropped = ring_append(
        (ring_dst, ring_pay), ring_cnt, dropped, (dst, pay), wslot, valid,
        dw, cap, kernel=cfg.deliver_kernel_resolved)
    return ring_dst, ring_pay, ring_cnt, dropped


def init_state(cfg: Config, base_key: jax.Array) -> OverlayTickState:
    """Initial friends table + the window-0 bootstrap makeup burst."""
    n, k, f = cfg.n, cfg.max_degree, cfg.fanout
    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    cap = slot_cap(cfg)
    ids = jnp.arange(n, dtype=I32)
    kb = _rng.tick_key(base_key, 0, _rng.OP_BOOTSTRAP)
    sm = slotmajor(n)
    if sm:
        # Memory band: per-LANE keyed draws, one (n,) column at a time --
        # never materializing the (n, fanout) draw matrix whose tiled
        # copy OOM'd the 100M compile (see slotmajor).  friends columns
        # land via one-hot blends (elementwise on (n, k), the layout the
        # rounds engine already proves at 1e8).
        friends = jnp.full((n, k), -1, I32)
        colsel = jnp.arange(k, dtype=I32)[None, :]
        for j in range(f):
            wj = _rng.row_randint(kb, n, ids * f + j, 1)[:, 0]
            wj = jnp.where(wj == ids, (wj + 1) % n, wj)
            friends = jnp.where(colsel == j, wj[:, None], friends)
        w = None
    else:
        # One independent draw per (node, slot), self patched (id+1)%n
        # (simulator.go:97-100); duplicates allowed, like the reference.
        w = jax.vmap(
            lambda kk: jax.random.randint(kk, (f,), 0, n, dtype=I32))(
            _rng.row_keys(kb, ids))
        w = jnp.where(w == ids[:, None], (w + 1) % n, w)
        friends = jnp.full((n, k), -1, I32).at[:, :f].set(w)
    cnt = jnp.full((n,), f, I32)

    ring_dst = jnp.zeros((dw * cap + 1,), I32)
    ring_pay = jnp.zeros((dw * cap + 1,), I32)
    ring_cnt = jnp.zeros((1, dw), I32)
    z = lambda: jnp.zeros((), I32)
    st = OverlayTickState(
        friends=friends, friend_cnt=cnt,
        ring_dst=ring_dst, ring_pay=ring_pay, ring_cnt=ring_cnt,
        spill=jnp.full((2, ticks_spill_cap(cfg) + 1), -1, I32),
        tick=z(), makeups=z(), breakups=z(),
        win_makeups=z(), win_breakups=z(), mailbox_dropped=z())
    # The burst: n*f makeups at t=0, each with its own delay.  Appended in
    # chunks through the same path as every later emission.
    kd = _rng.tick_key(base_key, 0, _rng.OP_DELAY)
    flat_n = n * f
    chunk = emit_chunk(cfg)

    def append_chunk(i, carry):
        ring_dst, ring_pay, ring_cnt, dropped = carry
        idx = i * chunk + jnp.arange(chunk, dtype=I32)
        valid = idx < flat_n
        src = jnp.where(valid, idx // f, 0)
        if sm:
            # Re-derive the lane's draw from its key (identical to the
            # friends column built above) instead of gathering from a
            # materialized matrix.
            dst = _rng.row_randint(kb, n, idx, 1)[:, 0]
            dst = jnp.where(dst == src, (dst + 1) % n, dst)
        else:
            dst = w.reshape(-1).at[jnp.where(valid, idx, 0)].get()
        delay = _rng.row_uniform_delay(kd, cfg.delaylow, cfg.delayhigh, idx)
        arrive = delay  # emitted at t=0
        return _append(cfg, ring_dst, ring_pay, ring_cnt, dropped,
                       dst, (src * 2 + MK) * b + arrive % b,
                       (arrive // b) % dw, valid)

    ring_dst, ring_pay, ring_cnt, dropped = jax.lax.fori_loop(
        0, -(-flat_n // chunk), append_chunk,
        (ring_dst, ring_pay, ring_cnt, st.mailbox_dropped))
    return st._replace(ring_dst=ring_dst, ring_pay=ring_pay,
                       ring_cnt=ring_cnt, mailbox_dropped=dropped)


def _emit_all(cfg: Config, st_ring, base_key, w, em_dst, em_toff, typ, op,
              lanes_major: bool = False):
    """Compact an (n, cap_mb) emission buffer and append every entry with a
    fresh per-message delay drawn at its trigger's arrival tick.

    `lanes_major` is the memory band's SLOT-major (cap_mb, n) buffer
    layout (see slotmajor): the flat scan order becomes slot-major and
    the sender id is idx % n -- a band-internal re-choice of emission
    order, like the rounds engine's column path."""
    ring_dst, ring_pay, ring_cnt, dropped = st_ring
    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    cols = em_dst.shape[1]
    flat_n = em_dst.shape[0] * cols
    dflat = em_dst.reshape(-1)
    tflat = em_toff.reshape(-1)
    valid_all = dflat >= 0
    total = valid_all.sum(dtype=I32)
    chunk = min(emit_chunk(cfg), flat_n)
    kd = _rng.tick_key(base_key, w, op)

    def make_body(base_lane, width):
        def body(_, carry):
            ring_dst, ring_pay, ring_cnt, dropped, remaining = carry
            ridx = first_true_indices(remaining, chunk)
            hit = jnp.zeros((width,), bool).at[ridx].set(True, mode="drop")
            remaining = remaining & ~hit
            # first_true_indices pads exhausted lanes to the MASK length
            # (`width`); in per-row mode base_lane + width is the next
            # row's first lane, so padding must be masked by ridx, not by
            # the global bound (a padded lane would otherwise read a real
            # NEXT-row emission and double-emit it).
            ok = ridx < width
            idx = base_lane + ridx  # global lane id (keys the delay draw)
            idx_g = jnp.where(ok, idx, flat_n)
            src = jnp.where(ok, idx % cols if lanes_major else idx // cols,
                            0)
            dst = dflat.at[idx_g].get(mode="fill", fill_value=-1)
            toff = tflat.at[idx_g].get(mode="fill", fill_value=0)
            valid = dst >= 0
            # Row-keyed by flat emission index: deterministic and
            # independent regardless of chunking.
            delay = _rng.row_uniform_delay(kd, cfg.delaylow, cfg.delayhigh,
                                           idx)
            arrive = w * b + toff + delay
            ring_dst, ring_pay, ring_cnt, dropped = _append(
                cfg, ring_dst, ring_pay, ring_cnt, dropped,
                jnp.where(valid, dst, 0),
                (src * 2 + typ) * b + arrive % b,
                (arrive // b) % dw, valid)
            return ring_dst, ring_pay, ring_cnt, dropped, remaining
        return body

    if lanes_major:
        # Per-ROW compaction (the deliver_columns move): each slot row is
        # a contiguous n-lane slice, so the scan pays n lanes per chunk
        # instead of slots*n -- the full flat scan was the dominant
        # settled-window cost at 10M.  Same entries, same slot-major
        # order, same lane-keyed draws; rows with zero emissions cost one
        # n-wide popcount.
        carry = (ring_dst, ring_pay, ring_cnt, dropped)
        for r in range(em_dst.shape[0]):
            rowv = valid_all[r * cols:(r + 1) * cols]
            rtotal = rowv.sum(dtype=I32)
            rchunk = min(chunk, cols)
            carry = jax.lax.fori_loop(
                0, (rtotal + rchunk - 1) // rchunk,
                make_body(r * cols, cols), carry + (rowv,))[:4]
        return carry

    out = jax.lax.fori_loop(0, (total + chunk - 1) // chunk,
                            make_body(0, flat_n),
                            (ring_dst, ring_pay, ring_cnt, dropped,
                             valid_all))
    return out[:4]


def make_step_fn(cfg: Config, n_local: int | None = None, ids_fn=None,
                 key_fn=None, sum_fn=None, emit_fn=None):
    """One B-tick window transition (drain -> deliver -> process -> emit).

    The four hooks make the SAME body run single-device or per-shard inside
    shard_map (parallel/overlay_ticks_sharded.py), mirroring
    overlay.make_round_fn's hook pattern so the two modes cannot diverge:
      ids_fn() -> global ids of the local rows (arange by default).
      key_fn(base_key, w, op) -> per-window op key (the sharded variant
          folds the shard index in first to decorrelate draws).
      sum_fn(x) -> global scalar reduction (identity / psum).
      emit_fn(ring, base_key, w, em_dst, em_toff, typ, op) -> ring, with
          `ring = (ring_dst, ring_pay, ring_cnt, local_dropped)`: local
          append by default, route-then-append when sharded.
    """
    n, k = cfg.n, cfg.max_degree
    n_rows = n_local if n_local is not None else cfg.n
    fanout, fanin = cfg.fanout, cfg.fanin_resolved
    b = batch_ticks(cfg)
    dw = ring_windows(cfg)
    cap = slot_cap(cfg, n_local)
    # Memory-band layouts (single-device only: the sharded hooks keep
    # node-major per-shard slices -- see slotmajor's docstring).
    sm = slotmajor(n_rows) and emit_fn is None
    # Per-LOCAL-rows cap, matching the sharded caller's emit_routed
    # (overlay_ticks_sharded uses the same stacked cap -- a mixed pair
    # would shape-mismatch the emission buffers past n ~ 1.34e8).
    # stacked=True: delivery here is deliver_pair's [2n, cap] addressing.
    cap_mb = cfg.mailbox_cap_for(n_rows, stacked=True)
    dchunk = ticks_delivery_chunk(cfg, n_rows)
    if ids_fn is None:
        ids_fn = lambda: jnp.arange(n_rows, dtype=I32)
    if key_fn is None:
        key_fn = _rng.tick_key
    if sum_fn is None:
        sum_fn = lambda x: x
    if emit_fn is None:
        def emit_fn(ring, base_key, w, em_dst, em_toff, typ, op):
            return _emit_all(cfg, ring, base_key, w, em_dst, em_toff,
                             typ, op)

    # Spill engages exactly where drops were ever possible (the slot-major
    # band's cap-8 stacked mailbox; `sm` is false on the sharded hook
    # path); everywhere else the token (2, 1) buffer passes through
    # untouched.
    sc = ticks_spill_cap(cfg, n_rows) if sm else 0
    prefix = PREFIX_DRAIN
    # Phase-1 megakernel gate: the SHARED slot closures, swapped for
    # their fused forms exactly like overlay.make_round_fn -- both
    # engines select through the one phase1_slot_fns seam.
    bk_slot_fn, mk_slot_fn = phase1_slot_fns(cfg)

    def _deliver_both(src_pay, dst, typ, evalid, m_live, spill_in):
        # Both message types in ONE sorted pass (ops.mailbox.deliver_pair;
        # bit-identical to two deliver() calls at ~half the op count).
        # Memory band: rank-major flat stacked buffer + per-type loads.
        # The drain sorts live entries into a packed prefix of length
        # `m_live`, so the chunked path skips its compaction scans
        # (prefix_len; PREFIX_DRAIN pins the A/B).  At the spill band the
        # last window's overflow pairs re-deliver first and this window's
        # overflow accumulates instead of dropping.
        plen = m_live if prefix else None
        dkern = cfg.deliver_kernel_resolved
        if sc > 0:
            acc = (jnp.full((2, sc + 1), -1, I32), jnp.zeros((), I32))
            return deliver_pair(src_pay, dst, typ, evalid, n_rows, cap_mb,
                                compact_chunk=dchunk, flat=sm,
                                prefix_len=plen, spill_in=spill_in,
                                spill=acc, kernel=dkern)
        return deliver_pair(src_pay, dst, typ, evalid, n_rows, cap_mb,
                            compact_chunk=dchunk, flat=sm,
                            prefix_len=plen, kernel=dkern) + (None,)

    def _drain_at_width(width, ring_dst, ring_pay, slot, m, spill_in):
        """Drain one window slot through a `width`-lane sort + delivery.
        Entries are rank-packed at [slot*cap, slot*cap + m), so any
        width >= m sees the whole live prefix; lanes past m hold stale
        cells masked exactly like the full-width form (sentinel toff key,
        stable sort) -- bit-identical mailboxes at any sufficient width."""
        dst_e = jax.lax.dynamic_slice(ring_dst, (slot * cap,), (width,))
        pay_e = jax.lax.dynamic_slice(ring_pay, (slot * cap,), (width,))
        evalid = jnp.arange(width, dtype=I32) < m
        # Arrival order within the window: stable sort by tick offset.
        toff_key = jnp.where(evalid, pay_e % b, b)
        toff_key, dst_e, pay_e = jax.lax.sort(
            (toff_key, dst_e, pay_e), num_keys=1, is_stable=True)
        evalid = toff_key < b
        typ = (pay_e // b) % 2
        mbox_pay = (pay_e // (2 * b)) * b + pay_e % b  # src*b + toff
        return _deliver_both(mbox_pay, dst_e, typ, evalid, m, spill_in)

    # Occupancy-adaptive drain widths (VERDICT r3 #5): slot_cap budgets
    # the worst-case window -- a 100M-lane 4-operand sort at 10M nodes --
    # but only the bootstrap-burst windows come anywhere near it; once
    # membership settles a window carries orders of magnitude fewer
    # entries.  lax.switch picks the narrowest power-of-4 width covering
    # the live count, so quiet windows sort thousands of lanes, not cap.
    widths = [cap]
    while widths[-1] > _DRAIN_WIDTH_FLOOR and len(widths) < 6:
        widths.append(max(_DRAIN_WIDTH_FLOOR, widths[-1] // 4))

    def step_fn(st: OverlayTickState, base_key: jax.Array) -> OverlayTickState:
        w = st.tick // b
        slot = w % dw
        m = st.ring_cnt[0, slot]
        spill_in = st.spill if sc > 0 else None
        if len(widths) == 1:
            drained = _drain_at_width(cap, st.ring_dst, st.ring_pay, slot,
                                      m, spill_in)
        else:
            # widths descend; ws[0] = cap >= m always, so the last
            # fitting index is count_of_fits - 1.
            sel = (jnp.asarray(widths, dtype=I32) >= m).sum(dtype=I32) - 1
            drained = jax.lax.switch(
                sel,
                [lambda rd, rp, sl, mm, w_=w_: _drain_at_width(
                    w_, rd, rp, sl, mm, spill_in)
                 for w_ in widths],
                st.ring_dst, st.ring_pay, slot, m)
        if sm:
            # Rank-major flat stacked mailbox: slot r of type t is the
            # contiguous range [r*2n + t*n, r*2n + (t+1)*n).
            pair_mbox, n_mk, n_bk, local_drops, spill_out = drained

            def mk_slot(sl):
                return jax.lax.dynamic_slice(pair_mbox,
                                             (sl * 2 * n_rows,), (n_rows,))

            def bk_slot(sl):
                return jax.lax.dynamic_slice(
                    pair_mbox, (sl * 2 * n_rows + n_rows,), (n_rows,))
        else:
            mk_mbox, bk_mbox, local_drops, spill_out = drained
            n_bk = (bk_mbox >= 0).sum(axis=1, dtype=I32).max(initial=0)
            n_mk = (mk_mbox >= 0).sum(axis=1, dtype=I32).max(initial=0)
            mk_slot = lambda sl: mk_mbox[:, sl]
            bk_slot = lambda sl: bk_mbox[:, sl]
        spill = spill_out[0] if spill_out is not None else st.spill
        ring_cnt = st.ring_cnt.at[0, slot].set(0)

        rkey = key_fn(base_key, w, _rng.OP_REPLACE)
        ekey = key_fn(base_key, w, _rng.OP_EVICT)
        ids = ids_fn()

        friends, cnt = st.friends, st.friend_cnt
        # Memory band: SLOT-major emission buffers (node axis minormost;
        # the node-major form tile-pads 16x at 1e8 -- see slotmajor).
        em_shape = (cap_mb, n_rows) if sm else (n_rows, cap_mb)
        mk_em_dst = jnp.full(em_shape, -1, I32)
        mk_em_toff = jnp.zeros(em_shape, I32)
        bk_em_dst = jnp.full(em_shape, -1, I32)
        bk_em_toff = jnp.zeros(em_shape, I32)

        def em_set(em, sl, vals):
            if sm:
                return em.at[sl].set(vals)
            return em.at[:, sl].set(vals)

        win_mk = jnp.zeros((), I32)
        win_bk = jnp.zeros((), I32)

        # --- breakups (simulator.go:76-94), slot-sequential ---------------
        # Decision rules are the SHARED kernels (overlay.process_*_slot);
        # this engine only threads the trigger's arrival tick through to
        # the emission so the reply's delay starts at the right time.
        def bk_body(sl, carry):
            friends, cnt, mk_em_dst, mk_em_toff, win_bk = carry
            pay = bk_slot(sl)
            has = pay >= 0
            src = jnp.where(has, pay // b, 0)
            toff = jnp.where(has, pay % b, 0)
            kk = jax.random.fold_in(rkey, sl)
            friends, cnt, nf, rp = bk_slot_fn(
                n, fanout, friends, cnt, src, has, ids, kk)
            mk_em_dst = em_set(mk_em_dst, sl, jnp.where(rp, nf, -1))
            mk_em_toff = em_set(mk_em_toff, sl, toff)
            return (friends, cnt, mk_em_dst, mk_em_toff,
                    win_bk + has.sum(dtype=I32))

        friends, cnt, mk_em_dst, mk_em_toff, win_bk = jax.lax.fori_loop(
            0, n_bk, bk_body,
            (friends, cnt, mk_em_dst, mk_em_toff, win_bk))

        # --- makeups (simulator.go:66-75) ----------------------------------
        def mk_body(sl, carry):
            friends, cnt, bk_em_dst, bk_em_toff, win_mk = carry
            pay = mk_slot(sl)
            has = pay >= 0
            src = jnp.where(has, pay // b, 0)
            toff = jnp.where(has, pay % b, 0)
            kk = jax.random.fold_in(ekey, sl)
            friends, cnt, victim, ev = mk_slot_fn(
                fanin, friends, cnt, src, has, kk)
            bk_em_dst = em_set(bk_em_dst, sl, jnp.where(ev, victim, -1))
            bk_em_toff = em_set(bk_em_toff, sl, toff)
            return (friends, cnt, bk_em_dst, bk_em_toff,
                    win_mk + has.sum(dtype=I32))

        friends, cnt, bk_em_dst, bk_em_toff, win_mk = jax.lax.fori_loop(
            0, n_mk, mk_body,
            (friends, cnt, bk_em_dst, bk_em_toff, win_mk))

        # --- emissions -> ring, per-message delays -------------------------
        ring = (st.ring_dst, st.ring_pay, ring_cnt, local_drops)
        if sm:
            ring = _emit_all(cfg, ring, base_key, w, mk_em_dst, mk_em_toff,
                             MK, _rng.OP_DELAY, lanes_major=True)
            ring = _emit_all(cfg, ring, base_key, w, bk_em_dst, bk_em_toff,
                             BK, _rng.OP_DELAY_BK, lanes_major=True)
        else:
            ring = emit_fn(ring, base_key, w, mk_em_dst, mk_em_toff,
                           MK, _rng.OP_DELAY)
            ring = emit_fn(ring, base_key, w, bk_em_dst, bk_em_toff,
                           BK, _rng.OP_DELAY_BK)
        ring_dst, ring_pay, ring_cnt, local_drops = ring

        win_mk = sum_fn(win_mk)
        win_bk = sum_fn(win_bk)
        return OverlayTickState(
            friends=friends, friend_cnt=cnt,
            ring_dst=ring_dst, ring_pay=ring_pay, ring_cnt=ring_cnt,
            spill=spill,
            tick=st.tick + b,
            makeups=st.makeups + win_mk, breakups=st.breakups + win_bk,
            win_makeups=st.win_makeups + win_mk,
            win_breakups=st.win_breakups + win_bk,
            mailbox_dropped=st.mailbox_dropped + sum_fn(local_drops))

    return step_fn


def _make_poll_body(cfg: Config):
    """One 10 ms poll window (ceil(10/B) steps), unjitted -- the SINGLE
    poll semantics shared by make_poll_fn (windowed host loop) and
    make_run_fn (bounded device loop), so the two paths cannot drift.
    win_makeups/win_breakups accumulate over the poll window, matching the
    reference's polled-atomics observation cadence (simulator.go:221-234)."""
    step = make_step_fn(cfg)
    steps = max(1, -(-10 // batch_ticks(cfg)))

    def poll(st: OverlayTickState, base_key) -> OverlayTickState:
        st = st._replace(win_makeups=jnp.zeros((), I32),
                         win_breakups=jnp.zeros((), I32))
        return jax.lax.fori_loop(0, steps, lambda _, s: step(s, base_key), st)

    return poll


def make_poll_fn(cfg: Config):
    """One poll window as one jitted device call (_make_poll_body)."""
    import functools

    poll = _make_poll_body(cfg)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def poll_fn(st: OverlayTickState, base_key) -> OverlayTickState:
        return poll(st, base_key)

    return poll_fn


def quiesced(st: OverlayTickState) -> jnp.ndarray:
    """A full poll window with zero processed messages AND an empty ring
    (spilled overflow pairs are in-flight messages: quiescing on them
    would lose them)."""
    return ((st.win_makeups == 0) & (st.win_breakups == 0)
            & ~jnp.any(st.ring_cnt > 0) & ~jnp.any(st.spill[1] >= 0)
            & (st.tick > 0))


def run_call_budget(cfg: Config, shards: int = 1) -> int:
    """Poll windows per bounded overlay_run_to_quiescence device call.
    One call must stay under the device-runtime watchdog (the failure
    mode epidemic.run_call_budget documents; calibrated here 2026-07-31
    at n=1e7 on v5e: 4-window ~16 s calls get the worker killed as
    UNAVAILABLE, 2-window ~8 s calls run clean).  Target <= ~8 s/call at
    the measured ~0.4 us/node/window.  `shards` scales for a mesh
    backend (device work tracks the per-SHARD slice), multiplying
    BEFORE the >=1 clamp so large n keeps the ratio."""
    return max(1, min(1024, int(2e7 * shards // max(cfg.n, 1))))


def make_run_fn(cfg: Config, telemetry: bool = False):
    """Up to `max_polls` poll windows per device call, stopping early at
    quiescence -- the phase-1 analog of the epidemic's bounded
    run-to-coverage while_loop.  The windowed host loop pays one jit
    dispatch + one device_get PER 10 simulated ms through the TPU tunnel
    (profiled ~2.4x the device time at n=1e6); a quiet run has nothing to
    observe per window, so the whole stabilization runs device-side with
    one host sync per bounded call.  Trajectory-identical to the windowed
    path: the same step/key derivation (keys are (base_key, window)-
    indexed, not call-indexed) and the same quiescence predicate on the
    same post-window states."""
    from gossip_simulator_tpu.models.overlay import make_bounded_run

    return make_bounded_run(_make_poll_body(cfg), quiesced,
                            telemetry=telemetry)
