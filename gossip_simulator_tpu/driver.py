"""Two-phase driver: overlay construction, then epidemic broadcast.

Mirrors the reference `main()` (simulator.go:207-255) with the same observable
output surface (§0 of SURVEY.md), plus a max-rounds liveness bound the
reference lacks (it spins forever if 99% is unreachable, simulator.go:243-251)
and optional profiling/checkpointing.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
import uuid
from typing import Optional

from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.backends import make_stepper
from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.utils import lifecycle as _lifecycle
from gossip_simulator_tpu.utils import telemetry as _telemetry
from gossip_simulator_tpu.utils import trace as _trace
from gossip_simulator_tpu.utils.metrics import ProgressPrinter, Stats


@dataclasses.dataclass
class RunResult:
    stats: Stats
    stabilize_ms: float  # simulated ms for overlay construction
    coverage_ms: float  # simulated ms to reach the coverage target
    converged: bool
    overlay_windows: int
    gossip_windows: int
    # Host-loss supervision (ISSUE 20): windows replayed after restoring
    # from the last snapshot, and wall-clock paused during recovery.
    # Zero / 0.0 unless -supervise recovered from a loss.
    recovered_windows: int = 0
    recovery_pause_ms: float = 0.0


def run_simulation(cfg: Config, printer: Optional[ProgressPrinter] = None,
                   stepper: Optional[Stepper] = None,
                   silent: bool = False) -> RunResult:
    """`silent` mutes ALL output (and skips the JSONL log) -- the non-zero
    ranks of a -distributed run, which compute the same replicated totals
    as rank 0."""
    cfg = cfg.validate()
    own_printer = printer is None
    printer = printer or ProgressPrinter(
        enabled=cfg.progress,
        jsonl_path=(cfg.log_jsonl_resolved or None) if not silent else None,
        silent=silent)
    # Flight recorder (utils/trace.py): one tracer per run, activated for
    # the module-level span() sites in backends/checkpoint.  Host-side
    # only -- the traced jitted programs are unchanged -- and skipped on
    # non-primary ranks (they would race on the same file).
    tracer = None
    if (cfg.trace_resolved or cfg.xprof_dir) and not silent:
        tracer = _trace.Tracer(path=cfg.trace_resolved,
                               xprof_dir=cfg.xprof_dir)
    try:
        # Ambient tuning config: cfg-less tunable lookups deeper in the
        # stack (exchange pad/rank path, pallas block rows) resolve this
        # run's tuning table instead of falling back to registry defaults.
        with _trace.activated(tracer), _tuning.ambient(cfg):
            return _run(cfg, printer, stepper)
    finally:
        # Close on ANY exit so a raised run still flushes the JSONL log
        # (and the trace file persists what a crashed run got through).
        if tracer is not None and tracer.path:
            tracer.write(metadata={"n": cfg.n, "backend": cfg.backend,
                                   "seed": cfg.seed})
        if own_printer:
            printer.close()


def _run(cfg: Config, printer: ProgressPrinter,
         stepper: Optional[Stepper]) -> RunResult:
    stepper = stepper or make_stepper(cfg)

    printer.params(cfg.parameter_dump())
    scen = cfg.scenario_resolved
    if scen.active or cfg.overlay_heal_resolved:
        # One-line fault-model banner (progress-only, like every note:
        # quiet runs and non-primary ranks skip it) so a scenario run's
        # transcript is self-describing.
        printer.note(
            f"scenario: {len(scen.crashes)} crash / {len(scen.churns)} "
            f"churn / {len(scen.partitions)} partition events, "
            f"groups={scen.groups}, downtime={scen.downtime}ms; "
            f"overlay-heal {cfg.overlay_heal}"
            + (f" (detect {cfg.heal_detect_ms}ms)"
               if cfg.overlay_heal_resolved else ""))
    for entry in _tuning.entries_for(cfg):
        if any(v != _tuning.REGISTRY[k].default
               for k, v in entry.get("values", {}).items()
               if k in _tuning.REGISTRY):
            # Same self-describing-transcript rationale as the scenario
            # banner: a run whose constants were MOVED by a table entry
            # says which one.  An all-defaults entry stays silent -- it
            # produces the identical program, and the golden transcripts
            # pin that.
            printer.note(f"tuning: table entry {entry['id']} active "
                         f"(table {cfg.tuning_table})")
    if cfg.backend == "sharded":
        # Same self-describing-transcript rationale: "auto" resolves per
        # host (device count), so the transcript records which schedule
        # this run's exchange actually compiled (CI greps this line to
        # confirm both gates were exercised).
        printer.note(f"exchange-pipeline: {cfg.exchange_pipeline_resolved} "
                     f"(requested {cfg.exchange_pipeline})")
    if cfg.backend in ("jax", "sharded") and cfg.phase2_kernel != "auto":
        # Gated on an EXPLICIT request only: the default (auto on a CPU
        # host) resolves to xla silently, keeping the golden transcripts
        # byte-identical.  An explicit -phase2-kernel run's transcript
        # records what actually compiled (and auto-on-TPU runs surface
        # through resolved_gates in the result record).
        try:
            p2r = cfg.phase2_kernel_resolved
        except ValueError:
            p2r = "unavailable"
        printer.note(f"phase2-kernel: {p2r} "
                     f"(requested {cfg.phase2_kernel})")
    if cfg.backend in ("jax", "sharded") and cfg.phase1_kernel != "auto":
        # Same explicit-request gate as phase2-kernel above.
        try:
            p1r = cfg.phase1_kernel_resolved
        except ValueError:
            p1r = "unavailable"
        printer.note(f"phase1-kernel: {p1r} "
                     f"(requested {cfg.phase1_kernel})")
    t_init = time.perf_counter()
    with _trace.span("init", cat="phase"):
        stepper.init()
    # The telemetry session (utils/telemetry.py) lets an observing run --
    # progress lines or JSONL -- take the device-side fast paths anyway:
    # the jitted loops record the full per-window trajectory on device and
    # the driver replays it through the SAME printer calls afterward,
    # producing output byte-identical to the windowed loop's.
    telem = getattr(stepper, "_telem", None)
    if telem is not None:
        telem.add_phase("init_s", time.perf_counter() - t_init)

    # Checkpoint provenance (ISSUE 20 satellite 2): the explicit -run-id,
    # else a generated token under supervision / worker mode.  Stamped into
    # every snapshot sidecar this run writes; empty for plain runs so their
    # sidecars stay byte-identical to pre-provenance builds.
    run_id = cfg.run_id
    if not run_id and (cfg.supervise or cfg.heartbeat_dir):
        run_id = uuid.uuid4().hex[:12]
    # Liveness beacon (distributed/heartbeat.py): a supervised worker
    # stamps its rank's beacon once per poll window in BOTH phases, so the
    # supervisor's staleness monitor sees progress, not just existence.
    beacon = None
    if cfg.heartbeat_dir and not cfg.supervise:
        from gossip_simulator_tpu.distributed import heartbeat as _heartbeat

        beacon = _heartbeat.Beacon.for_cfg(cfg)

    # --- Resume: from a phase-2 snapshot (skip straight into phase 2) or a
    # phase-1 overlay snapshot (continue construction mid-overlay) -------------
    resumed = False
    resume_window = 0
    overlay_windows = 0
    if cfg.resume:
        from gossip_simulator_tpu.utils import checkpoint

        # Under -distributed every rank reads the same snapshot (only rank 0
        # writes them), so the checkpoint dir must be on a filesystem all
        # hosts share -- the standard arrangement for multi-host training.
        # latest() prefers state_* (phase 2) over overlay_* (phase 1), so a
        # run interrupted in either phase resumes from its furthest point.
        path = checkpoint.latest(cfg.checkpoint_dir)
        if path is None:
            raise FileNotFoundError(
                f"-resume: no snapshot found in {cfg.checkpoint_dir}"
                + (" (every process of a -distributed run must see the "
                   "checkpoint dir; put it on a shared filesystem)"
                   if cfg.distributed else ""))
        tree, meta = checkpoint.load(path)
        # Provenance gate on an explicit -run-id: a relaunched survivor
        # (same -run-id as the original incarnation) passes; a snapshot
        # from some OTHER run is refused by name.  Staleness is the
        # supervisor's call (it knows the loss window), not resume's.
        if run_id:
            checkpoint.verify_provenance(meta, path=path, run_id=run_id,
                                         now_window=0, max_stale=0)
        # Phase detection falls back to tree contents (win_makeups exists
        # only on overlay state) so a snapshot whose .json sidecar was
        # lost in a copy still routes to the right restore path.
        phase1 = (int(meta["phase"]) == 1 if "phase" in meta
                  else "win_makeups" in tree)
        if phase1:
            overlay_windows = int(meta.get("window", 0))
            stepper.load_overlay_state_pytree(tree, windows=overlay_windows)
            printer.section(f"Resumed from {os.path.basename(path)} "
                            f"(overlay window {overlay_windows})")
            # resumed stays False: phase 1 continues below, then phase 2
            # runs normally (seed included).
        else:
            stepper.load_state_pytree(tree)
            resume_window = int(meta.get("window", 0))
            printer.section(f"Resumed from {os.path.basename(path)} "
                            f"(window {resume_window})")
            resumed = True

    # --- Phase 1: overlay (simulator.go:219-235) ------------------------------
    # Cooperative-shutdown bookkeeping (utils/lifecycle): a signalled run
    # finishes its current window, saves a final checkpoint and flushes
    # artifacts with reason "interrupted".  `p1_interrupted` marks a run
    # that never reached phase 2 (no epidemic state to report or seed).
    p1_interrupted = False
    if not resumed:
        printer.section("Constructing Overlay")
        if (cfg.graph == "overlay" and cfg.overlay_mode == "auto"
                and cfg.backend in ("jax", "sharded")
                and cfg.effective_time_mode == "ticks"
                and cfg.overlay_mode_resolved == "rounds"):
            # The size-banded default (config.OVERLAY_TICKS_AUTO_MAX,
            # raised to 10M in round 7) uses the estimated clock above
            # the band; say so once.  Gated on tick semantics: when
            # -time-mode rounds forced the rounds overlay, recommending
            # -overlay-mode ticks would point at a config validate()
            # rejects.
            printer.note("overlay clock estimated as rounds x mean delay "
                         "at this n; -overlay-mode ticks gives per-message-"
                         "faithful timing at ~2x the cost")
        max_overlay_windows = max(cfg.max_rounds, 1000)
        ckpt1 = _Checkpointer(cfg, stepper, run_id=run_id)
        # Same observability gate as the phase-2 fast path below: a quiet
        # run has no per-window output, so stabilization can run as bounded
        # device-side while_loops (one host sync per watchdog-bounded call
        # -- overlay_ticks/overlay.run_call_budget windows -- instead of
        # one dispatch + device_get per 10 simulated ms).  With telemetry,
        # an OBSERVING run takes the same fast path: the loop records the
        # per-window membership counts on device and they replay below.
        # Checkpointing observes per-window state the history cannot carry,
        # so it keeps the windowed loop (same rule as phase 2's gate).
        if ((not printer.observing or telem is not None)
                and not cfg.checkpointing_enabled
                and hasattr(stepper, "overlay_run_to_quiescence")):
            with _trace.span("phase1.quiesce", cat="phase") as sp:
                overlay_windows, oq = stepper.overlay_run_to_quiescence(
                    max_overlay_windows)
                if sp is not None:
                    sp["windows"] = int(overlay_windows)
            if not oq:
                raise RuntimeError(
                    f"overlay did not stabilize within {max_overlay_windows} "
                    f"windows")
            # Static graphs quiesce without running a window; the windowed
            # loop still counts its one (immediately-quiesced) poll, so
            # match it -- RunResult.overlay_windows is path-independent.
            overlay_windows = max(overlay_windows, 1)
            if telem is not None and printer.observing:
                _telemetry.replay_overlay(
                    printer, telem.overlay_snapshot(),
                    clock_scale=getattr(stepper, "overlay_clock_scale", 1.0))
        else:
            while True:
                with _trace.span("phase1.window", cat="window") as sp:
                    makeups, breakups, quiesced = stepper.overlay_window()
                    if sp is not None:
                        sp.update(makeups=int(makeups),
                                  breakups=int(breakups))
                overlay_windows += 1
                if quiesced:
                    break
                # Reference prints the window line only when *not* quiescing
                # (simulator.go:227-230).
                printer.overlay_window(breakups, makeups,
                                       stepper.sim_time_ms())
                if beacon is not None:
                    beacon.stamp(overlay_windows)
                ckpt1.maybe_save_overlay(overlay_windows)
                if _lifecycle.shutdown_requested():
                    p1_interrupted = True
                    break
                if overlay_windows >= max_overlay_windows:
                    raise RuntimeError(
                        f"overlay did not stabilize within "
                        f"{max_overlay_windows} windows")
    stabilize_ms = 0.0 if resumed else stepper.sim_time_ms()
    if not resumed and not p1_interrupted:
        printer.stabilized(stabilize_ms)

    # --- Phase 2: broadcast (simulator.go:237-253) ----------------------------
    if not p1_interrupted:
        printer.section("Broadcast one message")
        if not resumed:
            stepper.seed()
    target = cfg.coverage_target
    window_rounds = WINDOW_MS if cfg.effective_time_mode == "ticks" else 1
    # max_rounds caps simulated time at WINDOW granularity (both this loop
    # and the engines' run_to_coverage while_loops advance whole windows
    # between bound checks, so either path may overshoot the cap by up to
    # window_rounds-1 ticks -- consistently).  A resumed run gets only the
    # ceil of its remainder; a snapshot already at the cap runs zero windows.
    elapsed = int(stepper.sim_time_ms()) if resumed else 0
    max_windows = max(0, -(-(cfg.max_rounds - elapsed) // window_rounds))
    gossip_windows = 0
    converged = False
    ckpt = _Checkpointer(cfg, stepper, run_id=run_id)
    # Nothing on a quiet, uncheckpointed, unlogged run observes per-window
    # state, so the whole epidemic runs as bounded device-side while_loops
    # with a handful of host syncs total -- the windowed loop below pays a
    # full device->host stats round-trip per 10 simulated ms (~2x wall-clock
    # at n=1e7 through the TPU tunnel).  With telemetry, an OBSERVING run
    # (progress lines or JSONL) takes the fast path too: the device loop
    # records every poll window's counters and the trajectory replays
    # through the same printer calls right after -- byte-identical output,
    # fast-path wall clock.  Checkpointing still needs the real per-window
    # state, so it keeps the windowed loop.
    fast = (not resumed and not cfg.checkpointing_enabled
            and (not printer.observing or telem is not None)
            and hasattr(stepper, "run_to_target"))
    # Per-window trajectory rows for the run artifact (`-run-dir`): the
    # fast path derives them from the telemetry history afterward; the
    # windowed loop collects them here (artifact.TRAJECTORY_COLS order --
    # Stats.round IS the recorded tick, so the two bases are identical).
    window_rows: list = []
    collect_rows = bool(cfg.run_dir) and not printer.silent
    # Serve mode rebuilds the stepper across reshards, so the live config
    # (admission deferrals mutate the injection schedule) and the stepper
    # the final stats come from both ride the ServeOutcome.
    live_cfg = cfg
    serve_report = None
    hostloss_report = None
    interrupted = p1_interrupted
    with _maybe_profile(cfg):
        if p1_interrupted:
            pass
        elif cfg.supervise:
            from gossip_simulator_tpu.distributed import supervisor as _sup

            outcome = _sup.run_supervised(cfg, stepper, printer,
                                          max_windows,
                                          collect_rows=collect_rows,
                                          run_id=run_id)
            stepper = outcome.stepper
            gossip_windows = outcome.windows
            converged = outcome.converged
            window_rows = outcome.rows
            hostloss_report = outcome.report
            interrupted = interrupted or outcome.interrupted
            # A recovery rebuilds the stepper; device-recorded telemetry
            # histories do not survive that (same rule as serve's
            # reshards), so the artifact trajectory uses the
            # host-collected rows.
            telem = None
        elif cfg.serve:
            from gossip_simulator_tpu import serve as _serve

            outcome = _serve.run_serve(cfg, stepper, printer, max_windows,
                                       resume_window=resume_window,
                                       collect_rows=collect_rows)
            stepper = outcome.stepper
            live_cfg = outcome.cfg
            gossip_windows = outcome.windows
            converged = outcome.converged
            window_rows = outcome.rows
            serve_report = outcome.report
            interrupted = interrupted or outcome.interrupted
            # Device-recorded telemetry histories do not survive a reshard
            # (each incarnation starts its own); the artifact trajectory
            # uses the host-collected rows instead (basis "windows" --
            # row-identical to a twin's telemetry basis).
            telem = None
        elif fast:
            with _trace.span("phase2.run_to_target", cat="phase") as sp:
                stats = stepper.run_to_target()
                if sp is not None:
                    sp.update(rounds=int(stats.round),
                              messages=int(stats.total_message),
                              received=int(stats.total_received))
            hist2 = telem.gossip_snapshot() if telem is not None else None
            if hist2 and printer.observing:
                _telemetry.replay_gossip(printer, hist2, n=cfg.n)
            gossip_windows = (hist2["count"]
                              if hist2 and not hist2["truncated"]
                              else -(-stats.round // window_rounds))
            converged = stats.coverage >= target
            if _lifecycle.shutdown_requested():
                interrupted = True
        else:
            while gossip_windows < max_windows:
                with _trace.span("phase2.window", cat="window") as sp:
                    stats = stepper.gossip_window()
                    if sp is not None:
                        sp.update(round=int(stats.round),
                                  received=int(stats.total_received),
                                  messages=int(stats.total_message),
                                  dropped=int(stats.mailbox_dropped))
                gossip_windows += 1
                if collect_rows:
                    window_rows.append((stats.round, stats.total_received,
                                        stats.total_message,
                                        stats.total_crashed,
                                        stats.total_removed))
                pct = stats.coverage * 100.0
                printer.coverage_window(round(pct, 4), stepper.sim_time_ms())
                if beacon is not None:
                    beacon.stamp(resume_window + gossip_windows)
                # Offset by the restored window so post-resume snapshot
                # numbers continue the sequence (checkpoint.latest is
                # lexicographic).
                ckpt.maybe_save(resume_window + gossip_windows, stats)
                if stats.coverage >= target:
                    converged = True
                    break
                if getattr(stepper, "exhausted", False):
                    break  # no messages in flight and nothing can change
                if _lifecycle.shutdown_requested():
                    interrupted = True
                    break
    # A run interrupted mid-overlay has no epidemic state to read back.
    coverage_ms = 0.0 if p1_interrupted else stepper.sim_time_ms()
    stats = Stats(n=cfg.n) if p1_interrupted else stepper.stats()
    if serve_report is not None:
        stats.shed = serve_report["shed"]
    # A snapshot restored at/after the cap may already be at target.
    converged = converged or stats.coverage >= target
    # The true cause rides Stats now (threaded by every backend), so both
    # paths -- and the replayed fast path -- report "exhausted" whenever
    # the wave died, even in the window the round cap was hit.  A signalled
    # run reports "interrupted" whatever else was true -- the exit is the
    # signal's doing, and the final checkpoint below makes it resumable.
    if interrupted:
        reason = "interrupted"
    else:
        reason = ("exhausted: no messages in flight"
                  if stats.exhausted else "max rounds")
    if interrupted and cfg.checkpoint_dir:
        _final_shutdown_checkpoint(cfg, stepper, stats, p1_interrupted,
                                   resume_window + gossip_windows,
                                   overlay_windows)
    printer.done(coverage_ms, stats, target_pct=target * 100.0,
                 converged=converged, reason=reason)
    result = RunResult(
        stats=stats,
        stabilize_ms=stabilize_ms,
        coverage_ms=coverage_ms,
        converged=converged,
        overlay_windows=overlay_windows,
        gossip_windows=gossip_windows,
    )
    # Terminal machine-consumable record: full RunResult + wall breakdown
    # (JSONL-only; consumers stop scraping the `totals` stdout line).
    payload = {
        "converged": converged,
        "stabilize_ms": stabilize_ms, "coverage_ms": coverage_ms,
        "overlay_windows": overlay_windows,
        "gossip_windows": gossip_windows,
        "reason": None if converged else reason,
        # Attribution without re-parsing argv: where this run's artifact
        # landed (None without -run-dir) and the resolved gate set.
        "run_dir": (os.path.abspath(cfg.run_dir) if cfg.run_dir else None),
        "gates": cfg.resolved_gates(),
        **stats.to_dict(),
    }
    if serve_report is not None:
        payload["reshard_pause_ms"] = serve_report["reshard_pause_ms"]
        payload["serve"] = {k: serve_report[k] for k in
                            ("arrivals", "final_shards", "resizes",
                             "reshard_pause_ms", "shed")}
    if hostloss_report is not None:
        # Replayed-window accounting (ISSUE 20): how many windows the
        # recovery re-ran from the snapshot and what the restore pause
        # cost -- top-level for compare_runs-adjacent tooling, full
        # detail under "hostloss".
        result.recovered_windows = hostloss_report["recovered_windows"]
        result.recovery_pause_ms = hostloss_report["recovery_pause_ms"]
        payload["recovered_windows"] = hostloss_report["recovered_windows"]
        payload["recovery_pause_ms"] = hostloss_report["recovery_pause_ms"]
        payload["hostloss"] = hostloss_report
    if cfg.multi_rumor and not p1_interrupted:
        # live_cfg, not cfg: admission deferrals rewrite the injection
        # schedule, and latency is measured against what actually ran.
        payload.update(_multi_rumor_report(live_cfg, stepper, stats,
                                           coverage_ms))
    if cfg.model == "pushsum" and not p1_interrupted:
        from gossip_simulator_tpu.models import pushsum

        payload.update(pushsum.report(stepper))
    if telem is not None:
        payload["phases_s"] = {k: round(v, 6)
                               for k, v in sorted(telem.phases.items())}
    printer.result(payload)
    if telem is not None:
        report = _telemetry.TelemetryReport(
            n=cfg.n, phases=telem.phases,
            overlay=telem.overlay_snapshot(),
            gossip=telem.gossip_snapshot(),
            overlay_clock_scale=getattr(stepper, "overlay_clock_scale", 1.0))
        printer.telemetry(report.summary())
        if cfg.telemetry_summary:
            printer.block(report.summary_block())
    if cfg.run_dir and not printer.silent:
        _write_run_dir(cfg, telem, window_rows, payload, stats,
                       serve_report, hostloss_report)
    return result


def _final_shutdown_checkpoint(cfg: Config, stepper: Stepper, stats: Stats,
                               phase1: bool, window: int,
                               overlay_windows: int) -> None:
    """The signal path's final atomic save (ISSUE 11 satellite 1): whatever
    phase the run was in, its furthest state lands on disk before the
    "interrupted" result goes out, so `-resume` continues where the signal
    struck.  Collective like every snapshot; pruned like every save."""
    from gossip_simulator_tpu.utils import checkpoint

    if phase1:
        tree = stepper.overlay_state_pytree()
        if tree is not None and stepper.primary_host:
            checkpoint.save(cfg.checkpoint_dir, overlay_windows, tree,
                            Stats(n=cfg.n), prefix="overlay",
                            extra_meta={"phase": 1, "interrupted": True,
                                        "sim_ms": stepper.sim_time_ms()})
            checkpoint.prune(cfg.checkpoint_dir, cfg.ckpt_keep,
                             prefix="overlay")
    else:
        tree = stepper.state_pytree()
        if tree is not None and stepper.primary_host:
            checkpoint.save(cfg.checkpoint_dir, window, tree, stats,
                            extra_meta={"interrupted": True})
            checkpoint.prune(cfg.checkpoint_dir, cfg.ckpt_keep)


def _write_run_dir(cfg: Config, telem, window_rows: list, payload: dict,
                   stats: Stats, serve_report: Optional[dict] = None,
                   hostloss_report: Optional[dict] = None) -> None:
    """Flush the `-run-dir` artifact (utils/artifact.py layout).  The
    trajectory prefers the device-recorded history (fast path), falls
    back to the windowed loop's host-collected rows, and degrades to a
    single final-Stats row only when neither existed (a silent rank or a
    telemetry-off oracle-free fast path) -- the basis is named so
    compare_runs can refuse apples-to-oranges fingerprints."""
    from gossip_simulator_tpu.utils import artifact

    rdir = artifact.RunDir(cfg.run_dir)
    hist_o = telem.overlay_snapshot() if telem is not None else None
    hist_g = telem.gossip_snapshot() if telem is not None else None
    traj = artifact.trajectory_from_history(hist_g)
    basis = "telemetry"
    if traj is None:
        traj = artifact.trajectory_from_rows(window_rows)
        basis = "windows"
    if traj is None:
        traj = artifact.trajectory_from_rows(
            [(stats.round, stats.total_received, stats.total_message,
              stats.total_crashed, stats.total_removed)])
        basis = "final"
    rdir.write_config(cfg)
    rdir.write_env()
    rdir.write_telemetry(hist_o, hist_g, traj)
    if cfg.telemetry_spatial_enabled:
        # Shard-health watchdog over the fetched panels: verdict to
        # health.json, findings to the flight recorder as instants.
        from gossip_simulator_tpu.utils import health as _health

        n_shards = getattr(telem, "n_shards", 1) if telem is not None else 1
        rdir.write_health(_health.report_health(_health.evaluate_health(
            hist_g, cap=_health.ring_slot_cap(cfg, n_shards))))
    if serve_report is not None:
        rdir.write_serve(serve_report)
    if hostloss_report is not None:
        rdir.write_hostloss(hostloss_report)
    rdir.write_result({
        **payload,
        "fingerprint": artifact.fingerprint_rows(traj),
        "fingerprint_windows": int(traj.shape[0]),
        "fingerprint_basis": basis,
    })


def latency_summary(lat) -> dict:
    """Interpolated per-rumor latency summary (the SLO block).  np.percentile
    linear interpolation between order statistics -- NOT histogram-bucket
    upper edges, which overstated p50 by up to a full bucket width at small
    R -- with the exact min/max/mean alongside."""
    import numpy as np

    a = np.asarray(lat, np.int64)
    p50, p90, p99 = np.percentile(a, [50, 90, 99])
    return {
        "min": int(a.min()), "max": int(a.max()),
        "p50": round(float(p50), 2),
        "p90": round(float(p90), 2),
        "p99": round(float(p99), 2),
        "mean": round(float(a.mean()), 2),
    }


def _multi_rumor_report(cfg: Config, stepper: Stepper, stats: Stats,
                        coverage_ms: float) -> dict:
    """Steady-state serving metrics for the terminal `result` record
    (simulated-time domain; wall-clock throughput lives in the telemetry
    report).  Per-rumor latency = rumor_done stamp minus the ANALYTIC
    inject tick (arrivals.arrival_ticks under -traffic stream, tick 0
    under oneshot) -- the schedule is deterministic, so no per-rumor
    start stamp is carried on device."""
    import jax
    import numpy as np

    from gossip_simulator_tpu import arrivals as _arrivals

    R = cfg.rumors
    done = np.asarray(jax.device_get(stepper.state.rumor_done))[:R]
    inject = (np.asarray(_arrivals.arrival_ticks(cfg), np.int64)
              if cfg.traffic == "stream" else np.zeros(R, np.int64))
    out: dict = {"traffic": cfg.traffic}
    secs = coverage_ms / 1000.0
    if secs > 0:
        out["rumors_per_sec"] = round(stats.rumors_done / secs, 4)
        out["deliveries_per_sec"] = round(stats.total_message / secs, 1)
    lat = (done.astype(np.int64) - inject)[done >= 0]
    if lat.size:
        out["rumor_latency_ms"] = latency_summary(lat)
        counts, edges = np.histogram(lat, bins=min(10, max(1, lat.size)))
        out["rumor_latency_hist"] = {
            "edges_ms": [round(float(e), 1) for e in edges],
            "counts": [int(c) for c in counts],
        }
    return out


class _Checkpointer:
    def __init__(self, cfg: Config, stepper: Stepper, run_id: str = ""):
        self.cfg, self.stepper = cfg, stepper
        # Provenance sidecar keys (empty run_id = none, keeping plain
        # runs' sidecars byte-identical to pre-provenance builds).
        self.extra_meta = {"run_id": run_id} if run_id else None

    def _due(self, window: int) -> bool:
        cfg = self.cfg
        return (cfg.checkpointing_enabled
                and window % cfg.checkpoint_every == 0)

    def maybe_save(self, window: int, stats: Stats) -> None:
        if not self._due(window):
            return
        from gossip_simulator_tpu.utils import checkpoint

        # Collective on every rank (the sharded backend host-gathers);
        # only the primary host writes the file.
        tree = self.stepper.state_pytree()
        if tree is not None and self.stepper.primary_host:
            checkpoint.save(self.cfg.checkpoint_dir, window, tree, stats,
                            extra_meta=self.extra_meta)
            checkpoint.prune(self.cfg.checkpoint_dir, self.cfg.ckpt_keep)

    def maybe_save_overlay(self, window: int) -> None:
        """Phase-1 snapshot on the same cadence (VERDICT r3 weak #6: a
        minutes-long 100M overlay build was all-or-nothing).  Written
        under the `overlay_` prefix with phase=1 metadata; the load path
        continues construction mid-overlay."""
        if not self._due(window):
            return
        from gossip_simulator_tpu.utils import checkpoint

        # None from backends without phase-1 snapshots (the native/cpp
        # oracles: base.overlay_state_pytree's default -- phase 1 is
        # seconds at their feasible n).
        tree = self.stepper.overlay_state_pytree()
        if tree is not None and self.stepper.primary_host:
            checkpoint.save(
                self.cfg.checkpoint_dir, window, tree,
                Stats(n=self.cfg.n), prefix="overlay",
                extra_meta={"phase": 1,
                            "sim_ms": self.stepper.sim_time_ms()})
            checkpoint.prune(self.cfg.checkpoint_dir, self.cfg.ckpt_keep,
                             prefix="overlay")


@contextlib.contextmanager
def _maybe_profile(cfg: Config):
    if not cfg.profile:
        yield
        return
    import jax

    with jax.profiler.trace(cfg.profile_dir):
        yield
