"""Per-worker liveness beacons (ISSUE 20).

Each worker stamps one JSON file per poll window --
``<dir>/worker_<rank>.json`` holding the worker's rank, the window it just
finished, its pid and a wall-clock stamp.  The stamp is atomic (tmp +
os.replace, the checkpoint idiom) so the monitor never reads a torn
beacon.  Two detection predicates, one per deployment flavor:

* ``Monitor.lagging(current_window)`` -- DETERMINISTIC window-lag check
  for the single-process supervised loop: a logical worker whose beacon
  is more than ``lag_windows`` poll windows behind the loop is lost.
  Wall-clock-free, so the drill trajectories stay pinned.
* ``Monitor.stale(now)`` -- wall-clock staleness for the real
  multi-process supervisor, where a wedged worker keeps its process alive
  but stops advancing windows.  A worker that never wrote a beacon is
  NOT stale (it may still be compiling); process exit covers that case.

Module stays jax-free: the real supervisor monitors workers before any
jax runtime exists in its own process.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from gossip_simulator_tpu.backends.base import WINDOW_MS


def beacon_path(hb_dir: str, rank: int) -> str:
    return os.path.join(hb_dir, f"worker_{rank:04d}.json")


class Beacon:
    """The worker side: stamp liveness once per poll window."""

    def __init__(self, hb_dir: str, rank: int):
        self.path = beacon_path(hb_dir, rank)
        self.rank = rank
        os.makedirs(hb_dir, exist_ok=True)

    @classmethod
    def for_cfg(cls, cfg) -> Optional["Beacon"]:
        """The driver's hook: a beacon when `-heartbeat-dir` is set (the
        supervisor hands every worker one), else None.  Rank comes from
        the explicit -process-id, falling back to jax's own index for
        auto-detected clusters (lazy import -- non-distributed runs never
        touch jax here)."""
        if not cfg.heartbeat_dir:
            return None
        rank = cfg.process_id
        if rank < 0:
            if cfg.distributed:
                import jax

                rank = jax.process_index()
            else:
                rank = 0
        return cls(cfg.heartbeat_dir, rank)

    def stamp(self, window: int) -> None:
        doc = {"worker": self.rank, "window": int(window),
               "pid": os.getpid(), "time": time.time()}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)


class Monitor:
    """The supervisor side: read every beacon, name the lost."""

    def __init__(self, hb_dir: str, workers: int, timeout_ms: int):
        self.hb_dir = hb_dir
        self.workers = workers
        self.timeout_ms = timeout_ms
        # Window-lag equivalent of the wall-clock timeout: one poll window
        # is WINDOW_MS simulated ms, so a timeout of K*WINDOW_MS ms maps
        # to K windows of allowed lag (floor 1 -- a worker is never lost
        # for being exactly one window behind the loop's own stamp).
        self.lag_windows = max(1, timeout_ms // WINDOW_MS)

    def read(self, rank: int) -> Optional[dict]:
        try:
            with open(beacon_path(self.hb_dir, rank)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def last_window(self, rank: int) -> int:
        doc = self.read(rank)
        return int(doc["window"]) if doc else -1

    def lagging(self, current_window: int, live=None) -> Optional[int]:
        """First live worker whose beacon window trails `current_window`
        by more than lag_windows; None when everyone keeps up.  A worker
        with no beacon yet only counts once the loop itself is past the
        allowed lag (startup grace)."""
        for rank in range(self.workers):
            if live is not None and rank not in live:
                continue
            if current_window - self.last_window(rank) > self.lag_windows:
                return rank
        return None

    def stale(self, now: Optional[float] = None, live=None) -> Optional[int]:
        """First live worker whose beacon EXISTS but is wall-clock staler
        than the timeout; None otherwise (a missing beacon is a worker
        still starting up -- process exit, not staleness, covers a worker
        that died before its first window)."""
        now = time.time() if now is None else now
        for rank in range(self.workers):
            if live is not None and rank not in live:
                continue
            doc = self.read(rank)
            if doc is None:
                continue
            if (now - float(doc["time"])) * 1000.0 > self.timeout_ms:
                return rank
        return None
