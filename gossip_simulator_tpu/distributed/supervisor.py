"""Host-loss supervision (ISSUE 20): detect, restore, reshard, resume.

Two flavors share the same detection + recovery semantics:

``run_supervised`` -- the single-process drillable loop.  The live mesh's
devices are partitioned contiguously into ``-workers`` logical workers;
each stamps a heartbeat beacon every poll window.  A ``-chaos
kill-worker@W:K`` drill (or a beacon lagging past the heartbeat timeout,
the ``stall-worker`` drill path) declares worker W lost at window K: as
on a real pod, a lost host wedges every collective, so the WHOLE live
state is torn down and the last atomic snapshot -- sha256-verified and
provenance-checked (run_id + -recover-max-stale, utils/checkpoint.py) --
is restored onto the survivor mesh through serve.py's checkpoint ->
reshard -> restore sequence (build_stepper + load_state_pytree, whose
reshard_mail_rings re-buckets the in-flight mail onto the narrower shard
count).  The loop then REWINDS its window counter to the snapshot window
and replays: the injection schedule and step keys are pure functions of
(config, window, global id), so the replayed windows reproduce the
pre-loss trajectory exactly and the run ends Stats-exact against an
uninterrupted twin, with the replay accounted separately as
``recovered_windows`` / ``recovery_pause_ms``.

``run_supervisor`` -- the real process-spawning flavor: N CLI workers
joined via the bounded ``jax.distributed`` initialize
(parallel/mesh.py), monitored by process exit + wall-clock beacon
staleness; on loss the surviving process set relaunches with ``-resume``
against the shared checkpoint dir (num_processes - lost), after the same
provenance gate.  Runs behind the capability probe in CI -- two-process
CPU collectives are not universally supported.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import time
import uuid
from typing import Optional

from gossip_simulator_tpu.config import Config, parse_chaos
from gossip_simulator_tpu.distributed import heartbeat, worker as _worker
from gossip_simulator_tpu.utils import lifecycle as _lifecycle
from gossip_simulator_tpu.utils import trace as _trace
from gossip_simulator_tpu.utils.metrics import ProgressPrinter


def fresh_run_id() -> str:
    """The provenance token stamped into every snapshot sidecar this run
    writes; recovery refuses to restore anyone else's (ISSUE 20 sat. 2)."""
    return uuid.uuid4().hex[:12]


def survivor_shard_count(n: int, s_old: int, survivor_devices: int) -> int:
    """Largest shard count the survivors can host: <= their device count,
    never wider than the lost mesh (recovery narrows, it does not
    opportunistically widen), and dividing n (mesh.shard_size's
    contract).  Floor 1 -- a single survivor still restores."""
    s = max(min(s_old, survivor_devices), 1)
    while s > 1 and n % s:
        s -= 1
    return s


@dataclasses.dataclass
class SupervisedOutcome:
    """What the driver needs back from the supervised phase-2 loop --
    the serve.ServeOutcome shape minus the autoscaler fields, plus the
    host-loss report for result.json / the flight recorder."""

    stepper: object
    windows: int
    converged: bool
    interrupted: bool
    rows: list
    report: dict


def _recover(cfg: Config, dead: int, cause: str, loss_window: int,
             run_id: str, epoch: int, workers: int, lost: set,
             s_old: int, printer: ProgressPrinter):
    """Teardown -> provenance-checked restore -> survivor mesh.  Returns
    (stepper, ckpt_window, record); raises with the snapshot named on a
    missing/corrupt/foreign/stale checkpoint (never restores garbage)."""
    import jax

    from gossip_simulator_tpu import serve as _serve
    from gossip_simulator_tpu.utils import checkpoint

    t0 = time.perf_counter()
    devices = len(jax.devices())
    per = max(devices // workers, 1)
    survivors = devices - len(lost | {dead}) * per
    with _trace.span("hostloss.recover", cat="phase", worker=dead,
                     cause=cause, window=loss_window) as sp:
        path = checkpoint.latest(cfg.checkpoint_dir)
        if path is None:
            raise RuntimeError(
                f"host loss at window {loss_window} (worker {dead}, "
                f"{cause}) but no snapshot exists in {cfg.checkpoint_dir} "
                f"yet (first save lands at window {cfg.checkpoint_every}); "
                "nothing to recover from")
        tree, meta = checkpoint.load(path)  # sha256-verified
        checkpoint.verify_provenance(
            meta, path=path, run_id=run_id, now_window=loss_window,
            max_stale=cfg.recover_max_stale)
        ckpt_window = int(meta.get("window", 0))
        s_new = survivor_shard_count(cfg.n, s_old, survivors)
        # serve.py's checkpoint -> reshard -> restore sequence: a fresh
        # ready-to-restore stepper on the survivor mesh, then the wholesale
        # state overwrite (reshard_mail_rings re-buckets in-flight mail
        # when s_new != s_old).
        stepper = _serve.build_stepper(cfg, s_new)
        stepper.load_state_pytree(tree)
        pause_ms = (time.perf_counter() - t0) * 1000.0
        record = {"worker": dead, "cause": cause, "window": loss_window,
                  "ckpt_window": ckpt_window,
                  "recovered_windows": loss_window - ckpt_window,
                  "pause_ms": round(pause_ms, 3),
                  "from_shards": s_old, "to_shards": s_new,
                  "epoch": epoch}
        if sp is not None:
            sp.update(record)
    printer.note(
        f"host loss: worker {dead} ({cause}) at window {loss_window}; "
        f"restored {os.path.basename(path)} onto {s_new} survivor "
        f"shard(s), replaying {record['recovered_windows']} window(s) "
        f"(pause {pause_ms:.0f}ms)")
    return stepper, ckpt_window, record


def run_supervised(cfg: Config, stepper, printer: ProgressPrinter,
                   max_windows: int, collect_rows: bool = False,
                   run_id: str = "") -> SupervisedOutcome:
    """The supervised phase-2 loop (driver dispatch under -supervise with
    no -coordinator).  `stepper` arrives initialized and seeded, exactly
    like serve.run_serve; the outcome's stepper is whichever incarnation
    ran the final window."""
    from gossip_simulator_tpu.utils import checkpoint

    run_id = run_id or fresh_run_id()
    drill = parse_chaos(cfg.chaos)
    workers = cfg.workers
    hb_dir = cfg.heartbeat_dir_resolved
    beacons = [heartbeat.Beacon(hb_dir, i) for i in range(workers)]
    monitor = heartbeat.Monitor(hb_dir, workers, cfg.heartbeat_timeout_ms)
    target = cfg.coverage_target

    rows: list = []
    recoveries: list = []
    lost: set = set()
    stalled: set = set()
    windows = 0
    converged = False
    interrupted = False
    epoch = 0
    drill_fired = False
    stats = stepper.stats()

    while windows < max_windows:
        with _trace.span("supervise.window", cat="window") as sp:
            stats = stepper.gossip_window()
            if sp is not None:
                sp.update(round=int(stats.round),
                          received=int(stats.total_received))
        windows += 1
        if collect_rows:
            rows.append((stats.round, stats.total_received,
                         stats.total_message, stats.total_crashed,
                         stats.total_removed))
        printer.coverage_window(round(stats.coverage * 100.0, 4),
                                stepper.sim_time_ms())
        # Liveness beacons: every live logical worker stamps this window.
        # A stall-worker drill silences its target's beacon from the drill
        # window on, so detection exercises the REAL heartbeat-lag path.
        if (drill is not None and drill.kind == "stall-worker"
                and windows >= drill.window):
            stalled.add(drill.worker)
        for i, b in enumerate(beacons):
            if i not in lost and i not in stalled:
                b.stamp(windows)
        # Checkpoint cadence (validate() guarantees it is on): every
        # snapshot carries the provenance sidecar recovery will demand.
        if windows % cfg.checkpoint_every == 0:
            tree = stepper.state_pytree()
            if tree is not None and stepper.primary_host:
                checkpoint.save(cfg.checkpoint_dir, windows, tree, stats,
                                extra_meta={"run_id": run_id,
                                            "epoch": epoch})
                checkpoint.prune(cfg.checkpoint_dir, cfg.ckpt_keep)
        if stats.coverage >= target:
            converged = True
            break
        if getattr(stepper, "exhausted", False):
            break
        if _lifecycle.shutdown_requested():
            interrupted = True
            break
        # --- loss detection ----------------------------------------------
        dead: Optional[int] = None
        cause = ""
        if (drill is not None and drill.kind == "kill-worker"
                and not drill_fired and windows >= drill.window):
            dead, cause, drill_fired = drill.worker, "drill", True
        else:
            lag = monitor.lagging(windows, live=set(range(workers)) - lost)
            if lag is not None:
                dead, cause = lag, "heartbeat"
        if dead is not None:
            from gossip_simulator_tpu import serve as _serve

            lost.add(dead)
            epoch += 1
            stepper, ckpt_window, record = _recover(
                cfg, dead, cause, windows, run_id, epoch, workers, lost,
                _serve.shard_count(stepper), printer)
            recoveries.append(record)
            # Rewind to the snapshot and replay: the deterministic
            # schedule reproduces the pre-loss windows exactly, so the
            # trajectory rows (and the final Stats) match an
            # uninterrupted twin -- the replayed span is accounted in
            # recovered_windows, not hidden in the window count.
            windows = ckpt_window
            del rows[windows:]
            stats = stepper.stats()

    report = {
        "workers": workers,
        "lost": sorted(lost),
        "recoveries": recoveries,
        "recovered_windows": sum(r["recovered_windows"]
                                 for r in recoveries),
        "recovery_pause_ms": round(sum(r["pause_ms"] for r in recoveries),
                                   3),
        "heartbeat": {"timeout_ms": cfg.heartbeat_timeout_ms,
                      "lag_windows": monitor.lag_windows,
                      "dir": hb_dir},
        "run_id": run_id,
    }
    return SupervisedOutcome(stepper=stepper, windows=windows,
                             converged=converged, interrupted=interrupted,
                             rows=rows, report=report)


# --------------------------------------------------------------------------
# Real process-spawning supervisor (multi-host flavor)
# --------------------------------------------------------------------------

def _read_sidecar(path: str) -> dict:
    try:
        with open(path + ".json") as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _terminate_all(procs: dict, grace_s: float = 5.0) -> None:
    """Teardown: a lost host wedges the collective everywhere, so every
    remaining worker goes down before the survivors relaunch."""
    for p in procs.values():
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_s
    for p in procs.values():
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if p.poll() is None:
            p.kill()
            p.wait()


def run_supervisor(cfg: Config, argv: Optional[list[str]] = None) -> int:
    """Spawn -workers CLI worker processes joined via jax.distributed,
    monitor them (exit codes + wall-clock beacon staleness + the
    kill-worker drill), and on host loss relaunch the survivors with
    -resume on a narrower process set.  Returns the final incarnation's
    exit code; writes a supervisor.json report into -run-dir when set."""
    import sys

    from gossip_simulator_tpu.utils import checkpoint

    argv = list(argv) if argv is not None else sys.argv[1:]
    run_id = cfg.run_id or fresh_run_id()
    hb_dir = cfg.heartbeat_dir_resolved
    os.makedirs(hb_dir, exist_ok=True)
    host, port_s = cfg.coordinator.rsplit(":", 1)
    base_port = int(port_s)
    num = cfg.workers
    drill = parse_chaos(cfg.chaos)
    drill_fired = False
    epoch = 0
    recoveries: list = []
    _lifecycle.install_signal_handlers()

    def _spawn(num_procs: int, resume: bool) -> dict:
        # Fresh beacon slate per incarnation: a leftover beacon from the
        # previous (or a crashed earlier) run carries a stale wall clock,
        # and a relaunch that recompiles for longer than the heartbeat
        # timeout must not read as a second host loss -- a MISSING beacon
        # is "still starting", never stale.
        for rank in range(cfg.workers):
            try:
                os.remove(heartbeat.beacon_path(hb_dir, rank))
            except OSError:
                pass
        # Each incarnation gets its own coordinator port: the previous
        # coordination service died with rank 0 and its port may linger
        # in TIME_WAIT.
        coord = f"{host}:{base_port + epoch}"
        procs = {}
        for rank in range(num_procs):
            cmd = _worker.worker_cmd(argv, rank=rank,
                                     num_processes=num_procs,
                                     coordinator=coord,
                                     heartbeat_dir=hb_dir, run_id=run_id,
                                     resume=resume)
            procs[rank] = subprocess.Popen(cmd, env=dict(os.environ))
        return procs

    procs = _spawn(num, resume=False)
    monitor = heartbeat.Monitor(hb_dir, num, cfg.heartbeat_timeout_ms)
    _lifecycle.register_on_shutdown(lambda: _terminate_all(procs))
    rc = 2
    while True:
        time.sleep(0.2)
        if _lifecycle.shutdown_requested():
            _terminate_all(procs)
            rc = 2
            break
        # Injected drill: SIGKILL the target once its beacon shows it past
        # the drill window (so the kill interrupts REAL mid-run progress,
        # after at least one checkpoint-capable window).
        if (drill is not None and drill.kind == "kill-worker"
                and not drill_fired and drill.worker in procs
                and monitor.last_window(drill.worker) >= drill.window):
            procs[drill.worker].kill()
            drill_fired = True
        codes = {r: p.poll() for r, p in procs.items()}
        if all(c == 0 for c in codes.values()):
            rc = 0
            break
        dead = [r for r, c in codes.items() if c not in (None, 0)]
        if not dead:
            s = monitor.stale(live=set(procs))
            if s is not None:
                dead = [s]
            elif all(c is not None for c in codes.values()):
                # Everyone exited, someone nonzero-but-not-killed: the
                # run itself failed (e.g. not converged) -- propagate.
                rc = max(c for c in codes.values())
                break
        if dead:
            t0 = time.perf_counter()
            loss_window = max((monitor.last_window(r) for r in procs),
                              default=0)
            _terminate_all(procs)
            num -= len(dead)
            if num < 1:
                print("supervisor: no survivors left; giving up",
                      file=sys.stderr)
                rc = 2
                break
            path = checkpoint.latest(cfg.checkpoint_dir)
            if path is None:
                print("supervisor: host loss before the first snapshot; "
                      f"nothing to recover from in {cfg.checkpoint_dir}",
                      file=sys.stderr)
                rc = 2
                break
            # Provenance + staleness gate BEFORE burning a relaunch: the
            # sidecar alone decides (no array load on the supervisor).
            checkpoint.verify_provenance(
                _read_sidecar(path), path=path, run_id=run_id,
                now_window=loss_window, max_stale=cfg.recover_max_stale)
            epoch += 1
            ckpt_window = int(_read_sidecar(path).get("window", 0))
            recoveries.append({
                "workers_lost": sorted(dead), "window": loss_window,
                "ckpt_window": ckpt_window,
                "recovered_windows": loss_window - ckpt_window,
                "epoch": epoch, "num_processes": num,
                "pause_ms": round((time.perf_counter() - t0) * 1000.0, 3)})
            print(f"supervisor: lost worker(s) {sorted(dead)} at window "
                  f"~{loss_window}; relaunching {num} survivor(s) with "
                  f"-resume from {os.path.basename(path)}",
                  file=sys.stderr)
            procs = _spawn(num, resume=True)
            monitor = heartbeat.Monitor(hb_dir, num,
                                        cfg.heartbeat_timeout_ms)
    report = {"run_id": run_id, "workers": cfg.workers,
              "final_processes": num, "exit_code": rc,
              "recoveries": recoveries,
              "recovered_windows": sum(r["recovered_windows"]
                                       for r in recoveries),
              "recovery_pause_ms": round(sum(r["pause_ms"]
                                             for r in recoveries), 3)}
    if cfg.run_dir:
        os.makedirs(cfg.run_dir, exist_ok=True)
        with open(os.path.join(cfg.run_dir, "supervisor.json"), "w") as f:
            json.dump(report, f, indent=1)
    print("supervisor: " + json.dumps(report), file=sys.stderr)
    return rc
