"""Worker-side plumbing for the real multi-process supervisor (ISSUE 20).

A worker is not a new entry point: it is the ordinary CLI run with
``-distributed`` plus the supervisor's wiring flags (-coordinator /
-num-processes / -process-id / -heartbeat-dir / -run-id).  This module is
the argv surgery that builds each worker's command line from the
supervisor's OWN command line -- strip the supervisor-only flags, append
the worker wiring -- so the simulation flags (n, graph, seed, engine,
checkpoint cadence, ...) reach every worker verbatim and the relaunched
survivors resume the same run by construction.
"""

from __future__ import annotations

import sys

# Supervisor-only flags that must never reach a worker: the boolean
# switch, then every valued flag (single- and double-dash spellings both
# parse, and argparse also accepts --flag=value).
_STRIP_BOOL = {"-supervise", "--supervise", "-resume", "--resume"}
_STRIP_VALUED = {"-workers", "--workers", "-chaos", "--chaos",
                 "-coordinator", "--coordinator",
                 "-heartbeat-dir", "--heartbeat-dir",
                 "-heartbeat-timeout-ms", "--heartbeat-timeout-ms",
                 "-recover-max-stale", "--recover-max-stale",
                 "-run-id", "--run-id",
                 "-num-processes", "--num-processes",
                 "-process-id", "--process-id"}


def strip_supervisor_flags(argv: list[str]) -> list[str]:
    """The simulation flags only: supervisor argv minus everything the
    supervisor owns (wiring flags are re-appended per worker)."""
    out: list[str] = []
    skip = False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in _STRIP_BOOL:
            continue
        if tok in _STRIP_VALUED:
            skip = True
            continue
        if "=" in tok and tok.split("=", 1)[0] in (_STRIP_BOOL
                                                   | _STRIP_VALUED):
            continue
        out.append(tok)
    return out


def worker_cmd(argv: list[str], *, rank: int, num_processes: int,
               coordinator: str, heartbeat_dir: str, run_id: str,
               resume: bool = False) -> list[str]:
    """One worker's full command line.  `resume` is the relaunch flavor:
    the survivors restart on the narrower process set and continue from
    the latest shared snapshot (the checkpoint dir rode through argv)."""
    cmd = [sys.executable, "-m", "gossip_simulator_tpu",
           *strip_supervisor_flags(argv),
           "-distributed", "-coordinator", coordinator,
           "-num-processes", str(num_processes), "-process-id", str(rank),
           "-heartbeat-dir", heartbeat_dir, "-run-id", run_id]
    if resume:
        cmd.append("-resume")
    return cmd
