"""Supervisor/worker runtime (ISSUE 20): host death as a recoverable event.

Three layers, one per module:

* ``heartbeat`` -- per-worker liveness beacons (one JSON file per worker,
  stamped each poll window) plus the supervisor-side monitor that turns a
  stale or missing beacon into a *named* loss verdict.
* ``worker``    -- the worker side of the real multi-process deployment:
  argv surgery that turns the supervisor's own command line into each
  worker's ``-distributed`` command line, and the relaunch variant that
  restarts the survivors on a narrower process set with ``-resume``.
* ``supervisor`` -- both supervisor flavors.  ``run_supervised`` is the
  drillable single-process loop (logical workers = device slices of the
  live mesh; a ``-chaos kill-worker@W`` drill or a heartbeat lag tears the
  state down and restores the last provenance-checked snapshot onto the
  survivor mesh through serve.py's checkpoint -> reshard -> restore
  sequence).  ``run_supervisor`` is the real process-spawning flavor
  (workers joined via the bounded ``jax.distributed`` initialize in
  parallel/mesh.py; SIGKILL'd or wedged workers are detected, the
  collective job is torn down, and the survivors relaunch with -resume).

Recovery is Stats-exact against an uninterrupted twin when the trajectory
is shard-count invariant (no randomized legacy faults, single-value delay
draw, or (window, global-id)-keyed scenario faults -- the same recipe the
serve reshard twins pin), because the snapshot replays the deterministic
schedule from the checkpoint window forward.
"""

from gossip_simulator_tpu.distributed.heartbeat import (  # noqa: F401
    Beacon, Monitor)
from gossip_simulator_tpu.distributed.supervisor import (  # noqa: F401
    SupervisedOutcome, run_supervised, run_supervisor, survivor_shard_count)
