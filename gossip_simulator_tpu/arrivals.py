"""Open-loop arrival processes for streaming injection (ISSUE 11).

The stream traffic model injects rumor r at a deterministic tick.  The
pre-serve build computed that tick arithmetically inside the jitted window
step (r * 1000 // stream_rate); this module generalizes the schedule to a
precomputed host-side TABLE so richer arrival processes (Poisson, bursts,
diurnal load curves) and serve-mode admission deferrals ride the same
injection machinery.  Design constraints:

* **Deterministic per rumor index.**  Every schedule is a pure function of
  (arrivals, stream_rate, rumors, seed) -- no wall clock, no device state --
  so it is shard-count invariant and survives reshard-resume bit-for-bit
  (the serve loop rebuilds steppers mid-stream; a schedule that depended on
  runtime state would diverge across the rebuild).
* **`table_or_none` returns None for the legacy case** (fixed arrivals, no
  deferral override).  models/event.injection_batch keeps its original
  arithmetic branch on None, byte-identical to the pre-serve build -- the
  trajectory-fingerprint pins prove the table machinery invisible when off.
* Tables are sorted nondecreasing int32 (validate() enforces the same for
  explicit inject_ticks overrides); injection_batch looks rumors up with a
  searchsorted against the compile-time constant.

Numpy-only: imported by config.last_inject_tick, which must work without
jax (the native/cpp oracles validate configs too).
"""

from __future__ import annotations

import functools
import math

import numpy as np

# Rumors released together by the "burst" process.
BURST_GROUP = 8
# Diurnal modulation depth: rate swings rate*(1 +/- DIURNAL_DEPTH).
DIURNAL_DEPTH = 0.8


@functools.lru_cache(maxsize=64)
def _table(kind: str, rate: int, rumors: int, seed: int,
           override: tuple | None) -> tuple:
    if override is not None:
        return tuple(int(t) for t in override)
    if kind == "fixed":
        return tuple(r * 1000 // rate for r in range(rumors))
    if kind == "poisson":
        # Exponential inter-arrivals, mean 1000/rate ms; the generator is
        # seeded from (seed, rate, rumors) alone so the schedule is a pure
        # config function.
        rng = np.random.default_rng(np.uint64(seed * 1_000_003 + rate))
        gaps = rng.exponential(scale=1000.0 / rate, size=rumors)
        ticks = np.floor(np.cumsum(gaps) - gaps[0]).astype(np.int64)
        return tuple(int(t) for t in ticks)
    if kind == "burst":
        # Groups of BURST_GROUP rumors released together at the tick the
        # fixed schedule would have finished the group: mean rate is
        # preserved, instantaneous rate spikes at each boundary.
        group_span = max(1, BURST_GROUP * 1000 // rate)
        return tuple((r // BURST_GROUP) * group_span for r in range(rumors))
    if kind == "diurnal":
        # Sinusoidal load curve lambda(t) = rate*(1 + depth*sin(2pi t/P))
        # per 1000 ms; inter-arrival r->r+1 is 1000/lambda(t_r), i.e. an
        # Euler inversion of the cumulative intensity.  One full period
        # spans the whole run at the mean rate.
        period = max(1.0, rumors * 1000.0 / rate)
        ticks = []
        t = 0.0
        for _ in range(rumors):
            ticks.append(int(t))
            lam = rate * (1.0 + DIURNAL_DEPTH * math.sin(
                2.0 * math.pi * t / period)) / 1000.0
            t += 1.0 / max(lam, 1e-9)
        return tuple(ticks)
    raise ValueError(f"unknown arrival process {kind!r}")


def arrival_ticks(cfg) -> np.ndarray:
    """The per-rumor injection schedule for `cfg` (stream traffic): sorted
    nondecreasing int32 ticks, length cfg.rumors.  An explicit
    cfg.inject_ticks override (serve admission deferrals) wins over the
    named process."""
    tab = _table(cfg.arrivals, max(cfg.stream_rate, 1), cfg.rumors,
                 cfg.seed, cfg.inject_ticks)
    arr = np.asarray(tab, dtype=np.int32)
    if len(arr) and (np.diff(arr) < 0).any():
        raise ValueError(f"arrival table for {cfg.arrivals!r} not sorted")
    return arr


def table_or_none(cfg):
    """The injection table as a tuple, or None when the legacy arithmetic
    schedule applies (fixed arrivals, no deferral override) -- the None
    path keeps models/event.injection_batch byte-identical to the
    pre-serve build."""
    if getattr(cfg, "traffic", "oneshot") != "stream":
        return None
    if cfg.arrivals == "fixed" and cfg.inject_ticks is None:
        return None
    return _table(cfg.arrivals, max(cfg.stream_rate, 1), cfg.rumors,
                  cfg.seed, cfg.inject_ticks)
