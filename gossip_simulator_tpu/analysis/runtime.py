"""Runtime contract checkers: the compile-budget watcher.

Retrace regressions are the silent perf killer on the sharded engines: a
closure-captured Python scalar, a weak-typed literal, or a shape leak
turns one compile per entrypoint into one per *window*, and nothing
fails -- the run just gets slower.  ``CompileWatch`` captures JAX's own
compile/trace logging so per-entrypoint compile counts can be pinned in
the committed ``COMPILE_BUDGET.json`` (scripts/check_compile_budget.py)
and asserted in CI, with the guilty call site named on regression.

Mechanics (validated on this jax): under ``jax_log_compiles`` the
"Compiling <name> ..." record fires on every tracing-cache miss, BEFORE
the persistent-compilation-cache lookup -- so counts are stable whether
the executable itself comes from the cache or not.  With
``jax_explain_cache_misses`` each miss also logs a "TRACING CACHE MISS
at <file>:<line>" record explaining *why* (new avals vs changed
constants), which is what names the guilty call site.

JAX is imported lazily: importing this module (e.g. via the analysis
package's CLI) stays JAX-free.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
from typing import Optional

BUDGET_VERSION = 1

_COMPILE_RE = re.compile(r"^Compiling ([^\s]+)")
_TRACE_RE = re.compile(r"^Finished tracing \+ transforming ([^\s]+) ")
_MISS_RE = re.compile(r"TRACING CACHE MISS at (.+?) because:\s*(.*)",
                      re.DOTALL)
_AVAL_RE = re.compile(r"ShapedArray\([^)]*\)")


class _CaptureHandler(logging.Handler):
    def __init__(self, watch: "CompileWatch"):
        super().__init__(level=logging.DEBUG)
        self._watch = watch

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._watch._ingest(record.getMessage())
        except Exception:  # a watcher must never break the watched run
            pass


class CompileWatch:
    """Context manager recording per-entrypoint compile events.

    with CompileWatch() as watch:
        run_workload()
    watch.counts()   -> {entrypoint: compile count}
    watch.avals      -> {entrypoint: [[aval, ...] per compile]}
    watch.misses     -> [(call site, reason), ...]
    """

    def __init__(self):
        self.compiles: list[tuple[str, list[str]]] = []
        self.traces: list[str] = []
        self.misses: list[tuple[str, str]] = []
        self._handler: Optional[logging.Handler] = None
        self._saved: dict[str, object] = {}

    # -- capture -----------------------------------------------------------
    def _ingest(self, msg: str) -> None:
        m = _COMPILE_RE.match(msg)
        if m:
            self.compiles.append((m.group(1), _AVAL_RE.findall(msg)))
            return
        m = _TRACE_RE.match(msg)
        if m:
            self.traces.append(m.group(1))
            return
        m = _MISS_RE.search(msg)
        if m:
            self.misses.append((m.group(1).strip(),
                                " ".join(m.group(2).split())))

    # -- context -----------------------------------------------------------
    def __enter__(self) -> "CompileWatch":
        import jax

        for knob in ("jax_log_compiles", "jax_explain_cache_misses"):
            try:
                self._saved[knob] = getattr(jax.config, knob)
                jax.config.update(knob, True)
            except (AttributeError, ValueError):
                pass
        self._handler = _CaptureHandler(self)
        logger = logging.getLogger("jax")
        self._saved["_level"] = logger.level
        # The compile/miss records are WARNING-level under the flags;
        # leave the logger's effective level alone beyond ensuring they
        # propagate to our handler.
        if logger.level > logging.WARNING:
            logger.setLevel(logging.WARNING)
        logger.addHandler(self._handler)
        return self

    def __exit__(self, *exc) -> None:
        import jax

        logger = logging.getLogger("jax")
        if self._handler is not None:
            logger.removeHandler(self._handler)
        logger.setLevel(self._saved.pop("_level", logging.NOTSET))
        for knob, val in self._saved.items():
            try:
                jax.config.update(knob, val)
            except (AttributeError, ValueError):
                pass

    # -- reports -----------------------------------------------------------
    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for name, _ in self.compiles:
            out[name] = out.get(name, 0) + 1
        return out

    @property
    def avals(self) -> dict[str, list[list[str]]]:
        out: dict[str, list[list[str]]] = {}
        for name, av in self.compiles:
            out.setdefault(name, []).append(av)
        return out

    def report(self) -> dict:
        return {"entrypoints": self.counts(), "avals": self.avals,
                "misses": [{"site": s, "reason": r}
                           for s, r in self.misses]}


# --------------------------------------------------------------------------
# Budget file
# --------------------------------------------------------------------------

def default_budget_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "COMPILE_BUDGET.json")


def load_budget(path: Optional[str] = None) -> Optional[dict]:
    path = path or default_budget_path()
    if not os.path.exists(path):
        return None
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BUDGET_VERSION:
        raise ValueError(
            f"compile budget {path}: unsupported version "
            f"{data.get('version')!r}")
    return data


def budget_id(path: Optional[str] = None) -> str:
    """Content id of the active compile budget ("none" when absent) --
    stamped into resolved_gates / run artifacts so compare_runs can name
    a stale budget when fingerprints diverge."""
    path = path or default_budget_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return "none"
    canon = json.dumps(data, sort_keys=True, separators=(",", ":"))
    return "cb-" + hashlib.sha256(canon.encode()).hexdigest()[:12]


def _first_aval_diff(avals: list[list[str]],
                     expected: int) -> Optional[str]:
    """First argument position where consecutive compiles of the same
    entrypoint disagree on avals (None = every compile saw identical
    avals: the retrace was forced by a changed constant or closure
    capture, not by shapes)."""
    for i in range(1, len(avals)):
        prev, cur = avals[i - 1], avals[i]
        for pos in range(max(len(prev), len(cur))):
            a = prev[pos] if pos < len(prev) else "<absent>"
            b = cur[pos] if pos < len(cur) else "<absent>"
            if a != b:
                return f"compile {i}, arg {pos}: {a} -> {b}"
    return None


def compare_budget(expected: dict[str, int], report: dict) -> list[dict]:
    """Violations of a combo's entrypoint budget.

    Over-budget and unknown entrypoints are failures; an under-budget
    entrypoint (fewer compiles than pinned, e.g. after a refactor merges
    two programs) is reported as kind="under" so the caller can warn and
    suggest --update instead of failing."""
    observed = report.get("entrypoints", {})
    avals = report.get("avals", {})
    misses = report.get("misses", [])
    out: list[dict] = []
    for name, got in sorted(observed.items()):
        want = expected.get(name)
        if want is None:
            out.append({
                "kind": "unknown", "entrypoint": name,
                "expected": 0, "observed": got,
                "detail": "entrypoint not in COMPILE_BUDGET.json -- new "
                          "jit program; re-pin with --update if intended",
                "misses": _misses_for(misses, name)})
        elif got > want:
            diff = _first_aval_diff(avals.get(name, [[]]), want)
            detail = (f"first differing avals: {diff}" if diff else
                      "identical avals across compiles: retrace forced "
                      "by a changed constant/closure capture (the "
                      "captured-Python-scalar class)")
            out.append({
                "kind": "over", "entrypoint": name,
                "expected": want, "observed": got, "detail": detail,
                "misses": _misses_for(misses, name)})
    for name, want in sorted(expected.items()):
        got = observed.get(name, 0)
        if got < want:
            out.append({
                "kind": "under", "entrypoint": name,
                "expected": want, "observed": got,
                "detail": "fewer compiles than pinned (merged/removed "
                          "program?) -- re-pin with --update",
                "misses": []})
    return out


def _misses_for(misses: list[dict], name: str) -> list[dict]:
    """Cache-miss explanations plausibly about `name` (jax logs the
    fn name inside the reason text); falls back to all misses so the
    guilty call site is never dropped."""
    short = name.split("(")[-1].rstrip(")")
    mine = [m for m in misses
            if short and (short in m.get("reason", "")
                          or short in m.get("site", ""))]
    return mine or misses


def format_violation(combo: str, v: dict) -> str:
    lines = [f"[{combo}] {v['entrypoint']}: "
             f"expected {v['expected']} compile(s), "
             f"observed {v['observed']} ({v['kind']})",
             f"    {v['detail']}"]
    for m in v.get("misses", [])[:4]:
        lines.append(f"    cache miss at {m['site']}: {m['reason']}")
    return "\n".join(lines)
