"""gossip-lint: repo-specific invariant analyzer (ISSUE 17).

Every perf and scale claim in this repo rests on bit-exact trajectory
fingerprint pins; the invariants that make those pins meaningful are
mechanical, so they are checked mechanically:

    donation-aliasing   copy-in/copy-out discipline around donated buffers
                        (the PR-2 zero-copy snapshot bug class, both the
                        save side and read-after-donate)
    dtype-discipline    SoA columns / mail-ring lanes stay inside the
                        declared integer dtype set; no weak-type floats or
                        implicit int64 entering traced arithmetic
    trace-purity        no host nondeterminism (time.*, random.*,
                        np.random.*, .item(), int(tracer), data-dependent
                        Python branches) inside traced code
    donation-coverage   hot-path jits in ops/ and parallel/ that carry
                        state declare donate_argnums

Static rules are pure-stdlib AST passes (`python -m
gossip_simulator_tpu.analysis` never imports JAX); the runtime half
(`analysis.runtime`, driven by scripts/check_compile_budget.py) watches
`jax.log_compiles` and asserts per-entrypoint compile counts against the
committed COMPILE_BUDGET.json so retrace regressions fail CI with the
guilty call site named.

Inline suppression:  # gossip-lint: allow(<rule>) <reason>
Baseline:            analysis/baseline.json (grandfathered fingerprints;
                     shipped empty -- HEAD is clean)
Exit code:           the number of unsuppressed, unbaselined findings.
"""

from gossip_simulator_tpu.analysis.core import (  # noqa: F401
    Finding, load_baseline, run_analysis, write_baseline)
from gossip_simulator_tpu.analysis.rules import RULES  # noqa: F401
