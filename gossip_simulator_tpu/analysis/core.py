"""gossip-lint driver: findings, suppressions, baseline, file walking.

Pure stdlib -- importing this module (or running the static rules) never
touches JAX, so the CI lint step stays under the 30 s budget cold.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Iterable, Optional

# Default scan scope relative to the repo root.  tests/ is excluded: test
# files intentionally contain rule-firing fixture snippets.
DEFAULT_SCOPE = ("gossip_simulator_tpu", "scripts", "bench.py")
EXCLUDE_PARTS = ("tests", "__pycache__", ".jax_cache")

BASELINE_VERSION = 1

_ALLOW_RE = re.compile(
    r"#\s*gossip-lint:\s*allow\(([\w,\s-]+)\)\s*(.*)$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        """Stable id for baselining: rule + path + the *content* of the
        flagged line (whitespace-normalized), so the baseline survives
        pure line moves but a changed line re-fires."""
        norm = " ".join(self.snippet.split())
        h = hashlib.sha256(
            f"{self.rule}|{self.path}|{norm}".encode()).hexdigest()
        return h[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "message": self.message,
            "snippet": self.snippet, "fingerprint": self.fingerprint,
            "suppressed": self.suppressed, "baselined": self.baselined,
        }

    def format_human(self) -> str:
        mark = ""
        if self.suppressed:
            mark = " [suppressed]"
        elif self.baselined:
            mark = " [baseline]"
        loc = f"{self.path}:{self.line}:{self.col}"
        return (f"{loc}: {self.rule}{mark}\n    {self.message}\n"
                f"    > {self.snippet}")


# --------------------------------------------------------------------------
# Suppressions
# --------------------------------------------------------------------------

def collect_suppressions(source: str) -> dict[int, set[str]]:
    """line -> rules allowed on that line.

    ``# gossip-lint: allow(rule[, rule2]) <reason>`` suppresses matching
    findings on its own line; on a standalone comment line it suppresses
    the next non-comment line.  A missing reason is itself an error the
    caller surfaces (we return it under the pseudo-rule ``__noreason__``).
    """
    out: dict[int, set[str]] = {}
    lines = source.splitlines()
    for i, line in enumerate(lines, start=1):
        m = _ALLOW_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not m.group(2).strip():
            rules = {"__noreason__"}
        target = i
        if line.lstrip().startswith("#"):  # standalone comment line
            j = i + 1
            while j <= len(lines) and lines[j - 1].lstrip().startswith("#"):
                j += 1
            target = j
        out.setdefault(target, set()).update(rules)
    return out


def apply_suppressions(findings: list[Finding],
                       suppressions: dict[int, set[str]],
                       path: str) -> list[Finding]:
    """Mark suppressed findings in place; emit a finding for reasonless
    allow() comments so suppressions stay auditable."""
    extra: list[Finding] = []
    for lineno, rules in suppressions.items():
        if "__noreason__" in rules:
            extra.append(Finding(
                rule="lint-usage", path=path, line=lineno, col=1,
                message="gossip-lint: allow() without a reason -- state "
                        "why the finding is safe",
                snippet=""))
    for f in findings:
        allowed = suppressions.get(f.line, set())
        if f.rule in allowed or "all" in allowed:
            f.suppressed = True
    return findings + extra


# --------------------------------------------------------------------------
# Baseline
# --------------------------------------------------------------------------

def baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, "gossip_simulator_tpu", "analysis",
                        "baseline.json")


def load_baseline(path: str) -> set[str]:
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r}")
    return set(data.get("findings", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    fps = sorted({f.fingerprint for f in findings if not f.suppressed})
    with open(path, "w") as f:
        json.dump({"version": BASELINE_VERSION, "findings": fps}, f,
                  indent=2)
        f.write("\n")


# --------------------------------------------------------------------------
# Result cache (tier1.yml caches this dir across runs)
# --------------------------------------------------------------------------

class ResultCache:
    """Per-file finding cache keyed on content hash: unchanged files skip
    the AST passes entirely.  Safe because the rules are pure functions
    of a single file's source (path policy is part of the key)."""

    def __init__(self, cache_dir: Optional[str]):
        self.dir = cache_dir
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)

    def key(self, relpath: str, source: str) -> str:
        return hashlib.sha256(
            f"{relpath}|{_RULES_DIGEST}|{source}".encode()).hexdigest()

    def get(self, key: str) -> Optional[list[dict]]:
        if not self.dir:
            return None
        p = os.path.join(self.dir, key + ".json")
        if not os.path.exists(p):
            return None
        try:
            with open(p) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def put(self, key: str, findings: list[Finding]) -> None:
        if not self.dir:
            return
        p = os.path.join(self.dir, key + ".json")
        with open(p, "w") as f:
            json.dump([dataclasses.asdict(x) for x in findings], f)


def _rules_digest() -> str:
    """Hash of the rule implementation -- a rule edit invalidates the
    whole cache."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for name in ("rules.py", "core.py"):
        try:
            with open(os.path.join(here, name), "rb") as f:
                h.update(f.read())
        except OSError:
            pass
    return h.hexdigest()[:16]


_RULES_DIGEST = _rules_digest()


# --------------------------------------------------------------------------
# Runner
# --------------------------------------------------------------------------

def iter_python_files(root: str, scope: Iterable[str]) -> Iterable[str]:
    for entry in scope:
        path = os.path.join(root, entry)
        if os.path.isfile(path) and path.endswith(".py"):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in EXCLUDE_PARTS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def analyze_source(relpath: str, source: str, *,
                   rules: Optional[dict] = None,
                   force_in_scope: bool = False) -> list[Finding]:
    """Run the rules over one file's source.  ``force_in_scope`` is how
    test fixtures with synthetic paths opt into every rule."""
    from gossip_simulator_tpu.analysis import rules as rules_mod
    active = rules if rules is not None else rules_mod.RULES
    try:
        module = rules_mod.Module(relpath, source,
                                  force_in_scope=force_in_scope)
    except SyntaxError as e:
        return [Finding(rule="lint-usage", path=relpath,
                        line=e.lineno or 1, col=(e.offset or 0) + 1,
                        message=f"file does not parse: {e.msg}")]
    findings: list[Finding] = []
    for fn in active.values():
        findings.extend(fn(module))
    findings = apply_suppressions(
        findings, collect_suppressions(source), relpath)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def run_analysis(root: str, *, scope: Iterable[str] = DEFAULT_SCOPE,
                 rules: Optional[dict] = None,
                 baseline: Optional[set[str]] = None,
                 cache_dir: Optional[str] = None) -> list[Finding]:
    """Analyze the repo.  Returns every finding (suppressed/baselined ones
    marked); the unsuppressed count drives the exit code."""
    root = os.path.abspath(root)
    cache = ResultCache(cache_dir)
    selected = None
    if rules is not None:
        from gossip_simulator_tpu.analysis import rules as rules_mod
        selected = {k: rules_mod.RULES[k] for k in rules}
    findings: list[Finding] = []
    for path in iter_python_files(root, scope):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            source = f.read()
        key = cache.key(relpath, source) if cache.dir else ""
        cached = cache.get(key) if key else None
        if cached is not None:
            findings.extend(Finding(**d) for d in cached)
            continue
        file_findings = analyze_source(relpath, source, rules=selected)
        if key:
            cache.put(key, file_findings)
        findings.extend(file_findings)
    if baseline:
        for f in findings:
            if not f.suppressed and f.fingerprint in baseline:
                f.baselined = True
    return findings


def unsuppressed(findings: Iterable[Finding]) -> list[Finding]:
    return [f for f in findings if not f.suppressed and not f.baselined]
