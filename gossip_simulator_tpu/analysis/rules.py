"""The four gossip-lint AST rules (pure stdlib -- no JAX import).

Each rule is a function ``rule(module: Module) -> list[Finding]`` registered
in ``RULES``.  ``Module`` carries the parsed AST plus the shared analyses
every rule needs: local function defs, dtype aliases, jit sites, and the
traced-function set (functions reachable from a jax.jit / shard_map /
lax-control-flow entrypoint, the repo's "inside the tracer" surface).

Scoping is repo policy, declared up top: the rules know which modules hold
traced code and which hold the checkpoint/snapshot copy-discipline surface.
A fixture file handed to ``run_analysis`` directly is always in scope for
every rule (tests exercise each rule on synthetic snippets).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Optional

from gossip_simulator_tpu.analysis.core import Finding

# --------------------------------------------------------------------------
# Repo policy: which files hold what invariant surface
# --------------------------------------------------------------------------

# Modules whose functions may run under a tracer: trace-purity and
# dtype-discipline apply to the traced subset of their functions.
TRACED_DIRS = ("gossip_simulator_tpu/ops/", "gossip_simulator_tpu/parallel/",
               "gossip_simulator_tpu/models/")

# exchange.py documents "All functions run INSIDE shard_map": every
# top-level function there is a traced root even without a visible jit.
ALL_TRACED_MODULES = ("gossip_simulator_tpu/parallel/exchange.py",)

# Copy-discipline surface for donation-aliasing scope A: modules whose
# snapshot/save-named functions must copy device buffers before persisting.
COPY_MODULES = ("gossip_simulator_tpu/utils/checkpoint.py",
                "gossip_simulator_tpu/utils/artifact.py",
                "gossip_simulator_tpu/serve.py",
                "gossip_simulator_tpu/backends/")
COPY_FUNC_RE = re.compile(r"(state_pytree$|snapshot|^save$|^_host_gather$)")

# donation-coverage applies to the hot-path jit surface.
DONATION_DIRS = TRACED_DIRS

# Parameter names that mark a jitted callable as carrying donated state.
STATE_PARAM_NAMES = {"state", "st", "ostate", "tree", "carry", "rings"}

# Parameters that are static-by-convention at trace time: config objects,
# meshes, and axis names are Python values the tracer never sees.
STATIC_PARAM_NAMES = {"cfg", "config", "mesh", "axis", "axis_name"}

# Annotations naming a Python scalar mark a parameter as trace-time
# static (`n_shards: int`, `p: float`, `sort_buckets: bool | None`).
_SCALAR_ANNOTATIONS = {"int", "float", "bool", "str", "bytes", "Config"}

# The declared SoA dtype budget (models/state.py: uint8 flags, int32
# ids/counters, a uint32 [hi, lo] pair instead of int64 scalars, uint16
# fixed-point limbs).  float32 is allowed for RNG draws / probabilities;
# float64 would silently retype the bit-exact RNG streams.
ALLOWED_DTYPES = {"int8", "int16", "int32", "int64", "uint8", "uint16",
                  "uint32", "uint64", "bool", "bool_", "float32"}

# Canonical spellings for dtype expressions (resolved through module-level
# aliases like ``I32 = jnp.int32``).
_DTYPE_CANON = {"bool": "bool", "int": "int64", "float": "float64"}

_JIT_NAMES = {"jax.jit", "jit"}
_SHARD_MAP_SUFFIX = "shard_map"
_CTRL_FLOW_BODY_ARGS = {
    # dotted suffix -> positions of traced callables among positional args
    "lax.scan": (0,), "lax.while_loop": (0, 1), "lax.fori_loop": (2,),
    "lax.cond": (1, 2), "lax.switch": (1,), "lax.map": (0,),
    "jax.vmap": (0,), "vmap": (0,), "jax.checkpoint": (0,),
}

_ASARRAY_NAMES = {"np.asarray", "numpy.asarray", "jnp.asarray",
                  "jax.numpy.asarray"}
_CONSTRUCTORS = {  # dotted suffix -> index of the positional dtype argument
    "zeros": 1, "ones": 1, "empty": 1, "arange": None, "full": 2,
}
_CONSTRUCTOR_PREFIXES = ("np.", "numpy.", "jnp.", "jax.numpy.")


def dotted(node: ast.AST) -> Optional[str]:
    """`jnp.zeros` -> "jnp.zeros"; `jax.random.fold_in` ->
    "jax.random.fold_in"; bare names -> the name; else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _int_tuple(node: Optional[ast.AST]) -> Optional[tuple[int, ...]]:
    """Literal ints out of `(0, 4)` / `0` / `[1, 2]`; None if not literal."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


@dataclasses.dataclass
class JitSite:
    """One jax.jit occurrence: the call/decorator node plus what it wraps."""
    node: ast.AST  # the jit Call (or partial Call for decorators)
    subject: Optional[ast.AST]  # FunctionDef or Lambda being jitted
    subject_name: str
    donate: Optional[ast.AST]  # the donate_argnums kwarg value node
    static_argnums: tuple[int, ...]


class Module:
    """Parsed module + the shared analyses the rules consume."""

    def __init__(self, relpath: str, source: str, *,
                 force_in_scope: bool = False):
        self.relpath = relpath.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        # Fixture snippets run every rule regardless of path policy.
        self.force_in_scope = force_in_scope
        self.defs: dict[str, ast.AST] = {}  # bare name -> FunctionDef/Lambda
        self.dtype_aliases: dict[str, str] = {}
        self.jit_sites: list[JitSite] = []
        self.donating_defs: dict[str, tuple[int, ...]] = {}
        self._collect_defs_and_aliases()
        self._collect_jit_sites()
        self.traced_roots = self._collect_traced_roots()
        self.traced = self._reach(self.traced_roots)

    # --- scope predicates -------------------------------------------------
    def in_traced_scope(self) -> bool:
        return self.force_in_scope or any(
            self.relpath.startswith(d) for d in TRACED_DIRS)

    def in_copy_scope(self) -> bool:
        return self.force_in_scope or any(
            self.relpath.startswith(m) for m in COPY_MODULES)

    def in_donation_scope(self) -> bool:
        return self.force_in_scope or any(
            self.relpath.startswith(d) for d in DONATION_DIRS)

    # --- collection -------------------------------------------------------
    def _collect_defs_and_aliases(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, node)
        for stmt in self.tree.body:  # module-level dtype aliases only
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                d = dotted(stmt.value)
                if d is not None:
                    leaf = d.rsplit(".", 1)[-1]
                    if leaf in ALLOWED_DTYPES or leaf in (
                            "float64", "float16", "bfloat16", "complex64",
                            "complex128"):
                        self.dtype_aliases[stmt.targets[0].id] = leaf

    def canon_dtype(self, node: ast.AST) -> Optional[str]:
        """Canonical dtype name for an expression, or None if unknown
        (string dtypes like "int32" count too)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        d = dotted(node)
        if d is None:
            return None
        if d in self.dtype_aliases:
            return self.dtype_aliases[d]
        leaf = d.rsplit(".", 1)[-1]
        if leaf in _DTYPE_CANON and d == leaf:  # bare builtin `bool`/`int`
            return _DTYPE_CANON[leaf]
        known = ALLOWED_DTYPES | {"float64", "float16", "bfloat16",
                                  "complex64", "complex128"}
        return leaf if leaf in known else None

    def _jit_call_parts(self, call: ast.Call):
        """(subject_node, donate_kw, static_argnums) for a `jax.jit(...)`
        call, else None."""
        if dotted(call.func) not in _JIT_NAMES:
            return None
        subject = call.args[0] if call.args else None
        donate = None
        static: tuple[int, ...] = ()
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                donate = kw.value
            elif kw.arg in ("static_argnums", "static_argnames"):
                static = _int_tuple(kw.value) or ()
        return subject, donate, static

    def _resolve_subject(self, node: Optional[ast.AST]):
        """Chase a jit subject expression to a FunctionDef/Lambda:
        names resolve through local defs and `fn = _shard_map(...)`
        assignments; `_shard_map(mesh, fn, ...)` / `shard_map(fn, ...)`
        unwrap to their callable argument."""
        for _ in range(4):  # bounded chase
            if node is None:
                return None, ""
            if isinstance(node, ast.Lambda):
                return node, "<lambda>"
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node, node.name
            if isinstance(node, ast.Name):
                if node.id in self.defs:
                    d = self.defs[node.id]
                    return d, node.id
                node = self._local_assignment(node.id)
                continue
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                if d.endswith(_SHARD_MAP_SUFFIX):
                    # _shard_map(mesh, fn, ...) vs shard_map(fn, ...)
                    idx = 1 if d.lstrip("_").startswith("_") or \
                        d.split(".")[-1] == "_shard_map" else 0
                    node = (node.args[idx]
                            if len(node.args) > idx else None)
                    continue
                return None, d  # factory call -- unresolvable statically
            return None, ""
        return None, ""

    def _local_assignment(self, name: str) -> Optional[ast.AST]:
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == name):
                return node.value
        return None

    def _collect_jit_sites(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    site = self._decorator_jit(dec, node)
                    if site is not None:
                        self.jit_sites.append(site)
                        if site.donate is not None:
                            nums = _int_tuple(site.donate)
                            if nums:
                                self.donating_defs[node.name] = nums
            elif isinstance(node, ast.Call):
                parts = self._jit_call_parts(node)
                if parts is None:
                    continue
                subject, donate, static = parts
                sub, name = self._resolve_subject(subject)
                self.jit_sites.append(JitSite(node, sub, name, donate,
                                              static))

    def _decorator_jit(self, dec: ast.AST,
                       fn: ast.FunctionDef) -> Optional[JitSite]:
        """`@jax.jit` or `@functools.partial(jax.jit, ...)`."""
        if dotted(dec) in _JIT_NAMES:
            return JitSite(dec, fn, fn.name, None, ())
        if (isinstance(dec, ast.Call)
                and (dotted(dec.func) or "").endswith("partial")
                and dec.args and dotted(dec.args[0]) in _JIT_NAMES):
            donate = None
            static: tuple[int, ...] = ()
            for kw in dec.keywords:
                if kw.arg == "donate_argnums":
                    donate = kw.value
                elif kw.arg in ("static_argnums", "static_argnames"):
                    static = _int_tuple(kw.value) or ()
            return JitSite(dec, fn, fn.name, donate, static)
        return None

    def _collect_traced_roots(self) -> set[str]:
        roots: set[str] = set()
        for site in self.jit_sites:
            if site.subject is not None and site.subject_name:
                roots.add(site.subject_name)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            if d.split(".")[-1].lstrip("_") == _SHARD_MAP_SUFFIX:
                idx = 1 if d.split(".")[-1] == "_shard_map" else 0
                if len(node.args) > idx:
                    sub, name = self._resolve_subject(node.args[idx])
                    if sub is not None and name:
                        roots.add(name)
            for suffix, positions in _CTRL_FLOW_BODY_ARGS.items():
                if d == suffix or d.endswith("." + suffix):
                    for pos in positions:
                        if len(node.args) > pos:
                            sub, name = self._resolve_subject(node.args[pos])
                            if sub is not None and name:
                                roots.add(name)
        if self.relpath in ALL_TRACED_MODULES:
            for stmt in self.tree.body:
                if isinstance(stmt, ast.FunctionDef):
                    roots.add(stmt.name)
        return roots

    def _reach(self, roots: set[str]) -> set[str]:
        """Transitive closure over same-module calls by bare name."""
        seen: set[str] = set()
        work = [r for r in roots if r in self.defs]
        while work:
            name = work.pop()
            if name in seen:
                continue
            seen.add(name)
            fn = self.defs[name]
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = dotted(node.func)
                    if callee and "." not in callee and callee in self.defs \
                            and callee not in seen:
                        work.append(callee)
        return seen

    def traced_defs(self) -> list[tuple[str, ast.AST, bool]]:
        """(name, def, is_direct_root) for every traced function."""
        out = []
        for name in sorted(self.traced):
            out.append((name, self.defs[name], name in self.traced_roots))
        return out


def _finding(module: Module, rule: str, node: ast.AST,
             message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    snippet = (module.lines[line - 1].strip()
               if 0 < line <= len(module.lines) else "")
    return Finding(rule=rule, path=module.relpath, line=line,
                   col=getattr(node, "col_offset", 0) + 1,
                   message=message, snippet=snippet)


def _params(fn: ast.AST, static: tuple[int, ...] = ()) -> list[str]:
    """Positional parameter names minus static argnum positions and
    self/cls."""
    args = fn.args
    names = [a.arg for a in list(args.posonlyargs) + list(args.args)]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return [n for i, n in enumerate(names) if i not in static]


def _annotation_is_scalar(node: Optional[ast.AST]) -> bool:
    """True for annotations naming Python scalars (`int`, `float`,
    `bool | None`, `Optional[int]`, `Config`): the parameter is a
    trace-time static, never a tracer."""
    if node is None:
        return False
    if isinstance(node, ast.Constant):
        if node.value is None:
            return True
        if isinstance(node.value, str):  # string annotation
            try:
                return _annotation_is_scalar(
                    ast.parse(node.value, mode="eval").body)
            except SyntaxError:
                return False
        return False
    if isinstance(node, ast.Name):
        return node.id in _SCALAR_ANNOTATIONS
    if isinstance(node, ast.Attribute):
        return node.attr in _SCALAR_ANNOTATIONS
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return (_annotation_is_scalar(node.left)
                and _annotation_is_scalar(node.right))
    if isinstance(node, ast.Subscript):
        d = dotted(node.value) or ""
        if d.rsplit(".", 1)[-1] == "Optional":
            return _annotation_is_scalar(node.slice)
    return False


def _array_params(fn: ast.AST, static: tuple[int, ...] = ()) -> set[str]:
    """Parameters that could plausibly be tracers: positional params
    minus static argnums, static-by-convention names (cfg/mesh/axis),
    scalar-annotated params, and params rebound in the body (a rebound
    name holds a locally computed value; flagging it trades recall for
    precision)."""
    args = fn.args
    all_pos = list(args.posonlyargs) + list(args.args)
    names: set[str] = set()
    for i, a in enumerate(all_pos):
        if i == 0 and a.arg in ("self", "cls"):
            continue
        if i in static or a.arg in STATIC_PARAM_NAMES:
            continue
        if _annotation_is_scalar(a.annotation):
            continue
        names.add(a.arg)
    rebound = {n.id for n in ast.walk(fn)
               if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}
    return names - rebound


def _is_identity_test(test: ast.AST) -> bool:
    """`x is None` / `x is not None`: object-identity checks are static
    structure, never data-dependent."""
    return (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.Is, ast.IsNot)))


# --------------------------------------------------------------------------
# Rule 1: donation-aliasing
# --------------------------------------------------------------------------

def _is_fresh_memory(node: ast.AST) -> bool:
    """asarray of a list/tuple literal (or comprehension) allocates fresh
    host memory -- no aliasing possible."""
    return isinstance(node, (ast.List, ast.Tuple, ast.ListComp))


def _scalar_wrapped(parents: dict, node: ast.AST) -> bool:
    """`float(np.asarray(x))` / `int(...)` reads one scalar out; nothing
    retains the view."""
    p = parents.get(node)
    return (isinstance(p, ast.Call) and dotted(p.func) in ("int", "float")
            and p.args and p.args[0] is node)


def _parent_map(root: ast.AST) -> dict:
    parents: dict = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def rule_donation_aliasing(module: Module) -> list[Finding]:
    """Both directions of the PR-2 bug class.

    Scope A (save side): in snapshot/save functions of the checkpoint /
    artifact / backend copy-discipline surface, a zero-copy
    ``np.asarray`` / ``jnp.asarray`` of anything that could be a device
    buffer silently aliases live donated state (on the CPU platform
    asarray of a device buffer is zero-copy and the donating step fns
    reuse the buffer on the next call).  Required idiom: ``np.array``
    (copy) -- or an explicit allow() naming why the source is host-owned.

    Scope A2 (restore side): ``jax.device_put(np.asarray(...))`` hands
    XLA a buffer it does not own; restored leaves feeding donating jits
    must be device copies (``jnp.array``).

    Scope B (read-after-donate): a variable passed in a donated argnum
    position is dead -- any later read in the same block observes a
    buffer XLA has already reused."""
    out: list[Finding] = []
    if module.in_copy_scope():
        parents = _parent_map(module.tree)
        for name, fn in module.defs.items():
            if not COPY_FUNC_RE.search(name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d in _ASARRAY_NAMES and node.args \
                        and not _is_fresh_memory(node.args[0]) \
                        and not _scalar_wrapped(parents, node):
                    out.append(_finding(
                        module, "donation-aliasing", node,
                        f"zero-copy {d}() in snapshot path {name}(): on "
                        "the CPU platform this aliases a live (possibly "
                        "donated) buffer -- copy with np.array(), or "
                        "allow() with the reason the source is "
                        "host-owned"))
                elif d is not None and d.endswith("array") and any(
                        kw.arg == "copy"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False
                        for kw in node.keywords):
                    out.append(_finding(
                        module, "donation-aliasing", node,
                        f"{d}(copy=False) in snapshot path {name}(): "
                        "explicit no-copy of possibly-donated state"))
    # Scope A2 + B apply everywhere in the package.
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and \
                (dotted(node.func) or "").endswith("device_put") \
                and node.args and isinstance(node.args[0], ast.Call) \
                and dotted(node.args[0].func) in _ASARRAY_NAMES:
            out.append(_finding(
                module, "donation-aliasing", node,
                "device_put(asarray(...)): zero-copy placement feeds "
                "XLA a buffer it does not own; use jnp.array (device "
                "copy) before placement"))
    out.extend(_read_after_donate(module))
    return out


def _read_after_donate(module: Module) -> list[Finding]:
    """Linear scan per block: after `f(x, ...)` where f donates argnum i
    and arg i is a bare Name, a later load of that name (without an
    intervening rebind) reads a buffer XLA already reused."""
    if not module.donating_defs:
        return []
    out: list[Finding] = []
    for fname, fn in module.defs.items():
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for block in _blocks(fn):
            dead: dict[str, tuple[ast.AST, str]] = {}
            for stmt in block:
                # A rebind resurrects the name (typically `x = step(x)`).
                loads, stores, donations = _stmt_accesses(module, stmt)
                for name_node in loads:
                    if name_node.id in dead:
                        callee = dead[name_node.id][1]
                        out.append(_finding(
                            module, "donation-aliasing", name_node,
                            f"read of {name_node.id!r} after it was "
                            f"donated to {callee}() (donate_argnums): "
                            "the buffer may already be reused by XLA"))
                        del dead[name_node.id]  # report once per block
                for var, (node, callee) in donations.items():
                    dead[var] = (node, callee)
                for s in stores:
                    dead.pop(s, None)
    return out


def _blocks(fn: ast.AST):
    """Statement lists to scan linearly (function body + nested block
    bodies, each scanned independently -- loop re-entry is not modeled,
    keeping the rule conservative)."""
    for node in ast.walk(fn):
        for field in ("body", "orelse", "finalbody"):
            block = getattr(node, field, None)
            if isinstance(block, list) and block and \
                    isinstance(block[0], ast.stmt):
                yield block


def _stmt_accesses(module: Module, stmt: ast.stmt):
    """(loads, stores, donations) of one statement.  A call to a known
    donating def with a bare-Name arg in a donated position marks that
    name donated; Name loads *inside* the donating call itself are the
    donation, not a stale read."""
    donations: dict[str, tuple[ast.AST, str]] = {}
    donated_nodes: set[int] = set()
    for node in ast.walk(stmt):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted(node.func)
        if callee in module.donating_defs:
            for i in module.donating_defs[callee]:
                if i < len(node.args) and isinstance(node.args[i], ast.Name):
                    donations[node.args[i].id] = (node, callee)
                    donated_nodes.add(id(node.args[i]))
    loads, stores = [], set()
    for node in ast.walk(stmt):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Store):
                stores.add(node.id)
            elif isinstance(node.ctx, ast.Load) and \
                    id(node) not in donated_nodes:
                loads.append(node)
    return loads, stores, donations


# --------------------------------------------------------------------------
# Rule 2: dtype-discipline
# --------------------------------------------------------------------------

def rule_dtype_discipline(module: Module) -> list[Finding]:
    """SoA state columns and mail-ring lanes stay inside the declared
    dtype budget: array constructors in traced modules must name a dtype
    (the host default is float64/int64 -- the implicit-int64-on-device
    class), the named dtype must be in the allowed set, and bare Python
    float literals must not enter traced arithmetic (weak-type promotion
    retypes the whole expression)."""
    if not module.in_traced_scope():
        return []
    out: list[Finding] = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if d is None:
            continue
        leaf = d.rsplit(".", 1)[-1]
        if leaf in _CONSTRUCTORS and (
                d == leaf or any(d.startswith(p) and d == p + leaf
                                 for p in _CONSTRUCTOR_PREFIXES)):
            dtype_node = _constructor_dtype(node, _CONSTRUCTORS[leaf])
            if dtype_node is None:
                out.append(_finding(
                    module, "dtype-discipline", node,
                    f"{d}() without an explicit dtype: defaults to "
                    "float64/int64 on host (implicit int64 on device) -- "
                    "name a dtype from the declared set"))
            else:
                _check_dtype_value(module, node, dtype_node, out)
        elif leaf == "astype" and node.args:
            _check_dtype_value(module, node, node.args[0], out)
        elif leaf in ("float64", "float16", "bfloat16", "complex64",
                      "complex128") and d != leaf:
            out.append(_finding(
                module, "dtype-discipline", node,
                f"{d}() cast: {leaf} is outside the declared SoA dtype "
                "set (uint8/int32/int64 columns; float32 draws)"))
    out.extend(_float_literal_arith(module))
    return out


def _constructor_dtype(call: ast.Call, pos: Optional[int]):
    for kw in call.keywords:
        if kw.arg == "dtype":
            return kw.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def _check_dtype_value(module: Module, site: ast.AST, dtype_node: ast.AST,
                       out: list[Finding]) -> None:
    canon = module.canon_dtype(dtype_node)
    if canon is None:
        return  # dynamic dtype expression -- not statically checkable
    if canon not in ALLOWED_DTYPES:
        out.append(_finding(
            module, "dtype-discipline", site,
            f"dtype {canon} is outside the declared SoA set "
            f"({', '.join(sorted(ALLOWED_DTYPES))})"))


def _float_literal_arith(module: Module) -> list[Finding]:
    """Bare float literal combined arithmetically with a traced-function
    parameter: the weak f32 promotion silently retypes integer lanes."""
    out: list[Finding] = []
    for name, fn, _ in module.traced_defs():
        params = _array_params(fn)
        if not params:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.BinOp):
                continue
            sides = (node.left, node.right)
            lit = next((s for s in sides
                        if isinstance(s, ast.Constant)
                        and isinstance(s.value, float)), None)
            other = sides[1] if lit is sides[0] else sides[0]
            if lit is not None and isinstance(other, ast.Name) \
                    and other.id in params:
                out.append(_finding(
                    module, "dtype-discipline", node,
                    f"bare Python float {lit.value!r} in traced "
                    f"arithmetic with parameter {other.id!r} of "
                    f"{name}(): weak-type promotion retypes the lane"))
    return out


# --------------------------------------------------------------------------
# Rule 3: trace-purity
# --------------------------------------------------------------------------

_PURITY_CALL_PREFIXES = ("time.", "np.random.", "numpy.random.")


def rule_trace_purity(module: Module) -> list[Finding]:
    """No host nondeterminism inside traced code: wall clocks, host RNG,
    tracer->host coercions (.item(), int(tracer)), and data-dependent
    Python branches all either fail to trace or -- worse -- trace once and
    silently freeze a value the next call won't recompute."""
    if not (module.in_traced_scope() or module.traced):
        return []
    has_stdlib_random = any(
        isinstance(s, ast.Import) and any(a.name == "random"
                                          for a in s.names)
        for s in module.tree.body)
    out: list[Finding] = []
    for name, fn, is_root in module.traced_defs():
        static_idx: tuple[int, ...] = ()
        for site in module.jit_sites:
            if site.subject_name == name and site.static_argnums:
                static_idx = site.static_argnums
        array_params = _array_params(fn, static_idx)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                d = dotted(node.func) or ""
                if any(d.startswith(p) for p in _PURITY_CALL_PREFIXES):
                    out.append(_finding(
                        module, "trace-purity", node,
                        f"{d}() inside traced {name}(): host "
                        "nondeterminism freezes into the trace -- use "
                        "jax.random with a threaded key (utils/rng)"))
                elif has_stdlib_random and d.startswith("random."):
                    out.append(_finding(
                        module, "trace-purity", node,
                        f"stdlib {d}() inside traced {name}(): host RNG "
                        "is invisible to the tracer"))
                elif d.endswith(".item"):
                    out.append(_finding(
                        module, "trace-purity", node,
                        f".item() inside traced {name}(): forces a "
                        "device sync / fails under the tracer"))
                elif d in ("int", "float", "bool") and node.args and \
                        _mentions(node.args[0], array_params):
                    out.append(_finding(
                        module, "trace-purity", node,
                        f"{d}(<traced value>) inside {name}(): coercing "
                        "a tracer to a Python scalar fails to trace (the "
                        "int(tracer) class)"))
            elif is_root and isinstance(node, (ast.If, ast.While)) and \
                    not _is_identity_test(node.test) and \
                    _mentions(node.test, array_params):
                kind = "if" if isinstance(node, ast.If) else "while"
                out.append(_finding(
                    module, "trace-purity", node,
                    f"data-dependent Python `{kind}` on traced "
                    f"parameter(s) of {name}(): branches on tracers "
                    "fail to trace -- use lax.cond/jnp.where"))
    return out


def _mentions(node: ast.AST, names: set[str]) -> bool:
    if not names:
        return False
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))


# --------------------------------------------------------------------------
# Rule 4: donation-coverage
# --------------------------------------------------------------------------

def rule_donation_coverage(module: Module) -> list[Finding]:
    """Hot-path jits carrying state must donate it: without
    donate_argnums every window step holds two copies of the SoA state
    live (the 1e9-node memory budget assumes one) and XLA inserts a
    defensive copy on the update."""
    if not module.in_donation_scope():
        return []
    out: list[Finding] = []
    for site in module.jit_sites:
        if site.donate is not None or site.subject is None:
            continue
        params = _params(site.subject, site.static_argnums)
        stateful = [p for p in params if p in STATE_PARAM_NAMES]
        if stateful:
            out.append(_finding(
                module, "donation-coverage", site.node,
                f"jit of {site.subject_name or '<callable>'}() carries "
                f"state parameter(s) {', '.join(stateful)} but declares "
                "no donate_argnums: the step holds two live copies of "
                "the SoA state and XLA copies on update"))
    return out


RULES = {
    "donation-aliasing": rule_donation_aliasing,
    "dtype-discipline": rule_dtype_discipline,
    "trace-purity": rule_trace_purity,
    "donation-coverage": rule_donation_coverage,
}
