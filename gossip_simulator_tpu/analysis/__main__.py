"""CLI: ``python -m gossip_simulator_tpu.analysis [paths...]``.

Exit code is the number of unsuppressed, unbaselined findings (clamped
to 125 so it never collides with signal exit codes).  Never imports JAX.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from gossip_simulator_tpu.analysis import core
from gossip_simulator_tpu.analysis.rules import RULES


def _repo_root() -> str:
    # analysis/ -> gossip_simulator_tpu/ -> repo root
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gossip_simulator_tpu.analysis",
        description="gossip-lint: donation/dtype/purity invariant "
                    "analyzer (see analysis/__init__.py for the rules)")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to scan (default: "
                         f"{', '.join(core.DEFAULT_SCOPE)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: analysis/baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather current unsuppressed findings into "
                         "the baseline file and exit 0")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--cache", default=None, metavar="DIR",
                    help="per-file result cache directory (CI caches "
                         "this across runs)")
    args = ap.parse_args(argv)

    t0 = time.monotonic()
    root = _repo_root()
    scope = args.paths or core.DEFAULT_SCOPE
    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)} "
                     f"(known: {', '.join(RULES)})")

    bl_path = args.baseline or core.baseline_path(root)
    baseline = set() if args.no_baseline else core.load_baseline(bl_path)

    findings = core.run_analysis(root, scope=scope, rules=rules,
                                 baseline=baseline, cache_dir=args.cache)

    if args.write_baseline:
        core.write_baseline(bl_path, findings)
        n = len([f for f in findings if not f.suppressed])
        print(f"gossip-lint: baselined {n} finding(s) -> {bl_path}")
        return 0

    open_findings = core.unsuppressed(findings)
    elapsed = time.monotonic() - t0

    if args.as_json:
        report = {
            "version": 1,
            "rules": sorted(rules) if rules else sorted(RULES),
            "counts": {
                "total": len(findings),
                "suppressed": sum(f.suppressed for f in findings),
                "baselined": sum(f.baselined for f in findings),
                "unsuppressed": len(open_findings),
            },
            "findings": [f.to_dict() for f in findings],
            "elapsed_s": round(elapsed, 3),
        }
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        for f in findings:
            print(f.format_human())
        n_sup = sum(f.suppressed for f in findings)
        n_bl = sum(f.baselined for f in findings)
        print(f"gossip-lint: {len(open_findings)} finding(s) "
              f"({n_sup} suppressed, {n_bl} baselined) "
              f"in {elapsed:.2f}s")

    return min(len(open_findings), 125)


if __name__ == "__main__":
    sys.exit(main())
