"""Registered tunable constants and per-platform tuning tables (ISSUE 12).

Every hot path in the TPU-native engine is governed by constants picked
from profiling sessions at a handful of scales (the drain-chunk sweeps,
the 64k overlay delivery optimum, the Chernoff pad, ...).  This module
makes that hand-tuned surface a declared, searchable parameter space:

* ``REGISTRY`` -- every tunable, with its home module, bit-identical
  default, legal candidate ladder, provenance artifact and the workload
  shapes it affects.  Call sites read constants through :func:`value`
  instead of a literal; with no table and no override the returned value
  IS the old constant, so a registry-wired build is bit-identical to the
  constants it replaced (pinned by tests/test_autotune.py).
* ``SPACES`` -- named sweep spaces for ``scripts/autotune.py`` (which
  tunables to search together and the workload shape that exercises
  them).  The ``chunk_ladder`` space folds in the deleted
  ``scripts/chunk_sweep.py`` / ``chunk_sweep_f6.py`` candidate ladders.
* Tuning tables -- committed JSON (``TUNING_TABLE.json`` at the repo
  root) keyed by (platform, device_kind, scale band), plus a workload
  shape for entries carrying gate-validated tunables.  ``Config``
  consults EVERY matching entry at build time (entries from different
  spaces coexist in one band without shadowing each other); the
  resolution order per tunable is

      explicit CLI flag (checked at the call site, e.g. -compact-chunk,
          -event-chunk, -event-slot-cap)
    > autotune override context (scripts/autotune.py candidates)
    > active tuning-table entry (-tuning-table auto|off|PATH)
    > registered / module default.

The ``+``-joined ids of every active entry (or ``"defaults"``) are
stamped into ``Config.resolved_gates()`` and hence every run-dir
``config.json`` and terminal ``result`` record, so
``scripts/compare_runs.py`` can name a table mismatch as the first
divergence suspect.

Correctness contract: ``scripts/autotune.py`` rejects ANY candidate
whose run-dir trajectory fingerprint differs from the default-constants
twin (the neutrality gate -- perf search can never change results).
What a passed gate is worth differs per tunable, so each one declares a
``persist`` class:

* ``"contract"`` -- trajectory-neutral at ANY shape by construction
  (chunk widths under the rank-continuation delivery contract, the
  bit-identical rank-path width): a gate pass is confirmation, and a
  winner persists band-wide.
* ``"gated"`` -- trajectory-affecting in principle (the event drain
  chunk: a chunk-boundary re-broadcast uses the first-encountered
  delivery tick, models/event.py), so a gate pass at one shape does NOT
  transfer.  A winner persists only after the gate also passes at extra
  probe shapes (other seeds / other n in the band), and its entry
  carries the swept workload shape (:func:`workload_shape`): the values
  apply only to runs matching that shape, never band-wide.
* ``"never"`` -- capacity or semantics constants (slot_headroom,
  chernoff_pad, spill_margin, the Pallas PRNG block height): sweeps are
  timing evidence only, nothing is ever persisted.

This module imports no jax at import time; platform resolution is lazy
(first table lookup), keeping ``Config.validate()`` jax-free.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import importlib
import json
import os
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMMITTED_TABLE = os.path.join(REPO_ROOT, "TUNING_TABLE.json")
# Schema 2: entries may carry a "shape" key (required for entries whose
# values include persist="gated" tunables) and several entries can be
# active at once (one per space).
TABLE_SCHEMA = 2

# Scale bands keying table entries: a winner measured at one n applies
# to the band it was swept in, not the whole axis (per-op floors vs
# element-count costs cross over with n -- the drain-chunk sweeps put
# the 1e7 and 1e8 optima 2-8x apart).  Bands follow the repo's own
# banded constants (1M ticks-auto / 32M memory bands sit inside them).
SCALE_BANDS = ((1_048_576, "<=1m"), (16_777_216, "1m-16m"),
               (67_108_864, "16m-64m"), (134_217_728, "64m-128m"))


def scale_band(n: int) -> str:
    for lim, name in SCALE_BANDS:
        if n <= lim:
            return name
    return ">128m"


@dataclasses.dataclass(frozen=True)
class Tunable:
    """One registered constant: where it lives, what it may legally be,
    and how a swept winner may persist (see module docstring)."""

    name: str  # "module.constant", the registry key
    module: str  # home module (dotted path, for docs/provenance)
    default: float  # bit-identical to the constant it replaced
    candidates: tuple  # legal sweep ladder (default always included)
    kind: type  # int or float
    persist: str  # "contract" | "gated" | "never" (module docstring)
    provenance: str  # PROFILE_*/BENCH_* artifact the default came from
    shapes: str  # workload shapes the constant affects
    cfg_field: str = ""  # explicit Config field that outranks everything
    # "module:function" computing the DERIVED constant the tunable feeds
    # at a given cfg (e.g. event.drain_chunk).  The autotuner compares it
    # under override vs default and skips candidates that cannot change
    # the compiled program at the swept shape ("unexercised" -- their
    # timing deltas would be pure noise).  Empty = the value itself.
    effect: str = ""


PERSIST_CLASSES = ("contract", "gated", "never")
REGISTRY: dict[str, Tunable] = {}


def _register(name: str, module: str, default, candidates, kind,
              persist: str, provenance: str, shapes: str,
              cfg_field: str = "", effect: str = "") -> None:
    assert persist in PERSIST_CLASSES, (name, persist)
    cands = tuple(sorted(set(tuple(candidates) + (default,))))
    REGISTRY[name] = Tunable(name=name, module=module, default=default,
                             candidates=cands, kind=kind, persist=persist,
                             provenance=provenance, shapes=shapes,
                             cfg_field=cfg_field, effect=effect)


# --- the hand-tuned constant surface (defaults bit-identical) --------------
_register("overlay.delivery_chunk_base", "gossip_simulator_tpu.models.overlay",
          65_536, (32_768, 65_536, 131_072, 262_144), int, "contract",
          "PROFILE_OVERLAY.json",
          "rounds-overlay mailbox delivery (v5e full-construction sweep "
          "optimum at n=1e6)", cfg_field="compact_chunk")
_register("overlay.delivery_chunk_cap", "gossip_simulator_tpu.models.overlay",
          1_048_576, (524_288, 1_048_576, 2_097_152), int, "contract",
          "PROFILE_OVERLAY.json",
          "rounds-overlay delivery n/128 ramp ceiling (>=128M rows)",
          cfg_field="compact_chunk")
_register("overlay.adaptive_chunk_max", "gossip_simulator_tpu.models.overlay",
          8_388_608, (2_097_152, 4_194_304, 8_388_608, 16_777_216), int,
          "contract", "PROFILE_OVERLAY.json",
          "fattest rung of the occupancy-adaptive hosted-chunk ladder "
          "(split-round band, >=32M rows)")
_register("overlay.spill_margin", "gossip_simulator_tpu.models.overlay",
          1.6, (1.2, 1.6, 2.0, 2.5), float, "never",
          "BENCH_SELF_r07.json",
          "static-boot burst spill sizing (cap-8 band); too small drops "
          "messages -- capacity, not chunking, so never table-persisted")
_register("overlay_ticks.delivery_chunk_cap",
          "gossip_simulator_tpu.models.overlay_ticks",
          2_097_152, (1_048_576, 2_097_152, 4_194_304), int, "contract",
          "PROFILE_OVERLAY.json",
          "ticks-overlay slot-drain chunk ceiling (re-swept 2026-07-31 "
          "at 10M)", cfg_field="compact_chunk")
_register("exchange.rank_max_shards",
          "gossip_simulator_tpu.parallel.exchange",
          16, (8, 16, 32, 64), int, "contract",
          "PROFILE_EXCHANGE.json",
          "widest mesh served by the sort-free one-hot bucketing rank "
          "(both paths bit-identical; pinned by test_sharded)")
_register("exchange.chernoff_pad", "gossip_simulator_tpu.parallel.exchange",
          8, (6, 8, 10, 12), int, "never",
          "PROFILE_EXCHANGE.json",
          "wire-cap pad multiplier (pad = max(64, k*sqrt(mean))); smaller "
          "raises overflow odds -- capacity, never table-persisted")
_register("exchange.pipeline_depth",
          "gossip_simulator_tpu.parallel.event_sharded",
          2, (1, 2), int, "contract",
          "PROFILE_EXCHANGE.json",
          "staged exchange buffers under -exchange-pipeline double "
          "(2 = drain one batch behind the all_to_all, 1 = the serial "
          "schedule; only the append is deferred, so both are "
          "bit-identical -- pinned by test_sharded)")
_register("exchange.pipeline_chunk",
          "gossip_simulator_tpu.parallel.event_sharded",
          0, (0, 65_536, 131_072, 262_144), int, "contract",
          "PROFILE_EXCHANGE.json",
          "per-buffer staged emission-batch width cap under the "
          "pipelined exchange (0 = inherit sender_compaction_cap); "
          "batch boundaries are trajectory-free in the zero-overflow "
          "regime (narrow_tail_cap's envelope)")
_register("event.slot_headroom", "gossip_simulator_tpu.models.event",
          1.5, (1.25, 1.5, 2.0), float, "never",
          "BENCH_SELF_r05.json",
          "event mail-ring slot-cap skew headroom; too small overflows "
          "(counted, and the neutrality gate rejects it) -- capacity, "
          "never table-persisted", cfg_field="event_slot_cap",
          effect="gossip_simulator_tpu.models.event:drain_geometry")
# The four drain-chunk knobs are persist="gated", NOT contract-neutral:
# a window draining in multiple chunks re-broadcasts a boundary-spanning
# node from its first-ENCOUNTERED delivery tick (models/event.py module
# docstring), so a different effective chunk can move the trajectory.
# The gate catches that at the swept shape; persistence additionally
# requires cross-shape probe passes and shape-keyed table entries.
_register("event.drain_chunk_floor", "gossip_simulator_tpu.models.event",
          131_072, (32_768, 65_536, 131_072, 262_144, 524_288), int, "gated",
          "BENCH_SELF_r03.json",
          "event drain-chunk auto ramp floor (dominant term below "
          "n ~ 16M)", cfg_field="event_chunk",
          effect="gossip_simulator_tpu.models.event:drain_geometry")
_register("event.drain_chunk_hi", "gossip_simulator_tpu.models.event",
          1_048_576, (262_144, 524_288, 1_048_576, 2_097_152), int, "gated",
          "BENCH_SELF_r05.json",
          "event drain-chunk ceiling, mean_degree/4 >= 1.5 (the fanout-6 "
          "ladder scripts/chunk_sweep_f6.py swept)", cfg_field="event_chunk",
          effect="gossip_simulator_tpu.models.event:drain_geometry")
_register("event.drain_chunk_hi_lowdeg", "gossip_simulator_tpu.models.event",
          524_288, (524_288, 1_048_576, 2_097_152, 4_194_304), int, "gated",
          "BENCH_SELF_r03.json",
          "event drain-chunk ceiling, low-degree branch (the fanout-3 "
          "ladder scripts/chunk_sweep.py swept)", cfg_field="event_chunk",
          effect="gossip_simulator_tpu.models.event:drain_geometry")
_register("event.drain_chunk_hi_suppress",
          "gossip_simulator_tpu.models.event",
          4_194_304, (1_048_576, 2_097_152, 4_194_304, 8_388_608), int,
          "gated", "BENCH_SELF_r06.json",
          "event drain-chunk ceiling under duplicate suppression (1e8 "
          "fanout-6 sweep 2026-07-31)", cfg_field="event_chunk",
          effect="gossip_simulator_tpu.models.event:drain_geometry")
_register("pallas_graph.block_rows", "gossip_simulator_tpu.ops.pallas_graph",
          512, (256, 512, 1024, 2048), int, "never",
          "PALLAS_VALIDATION.json",
          "Pallas graph-generator grid block; NOT neutral: the TPU PRNG "
          "seeds per block (row0 // block + blk), so a different block "
          "height generates a different graph -- the gate always rejects "
          "alternatives")
_register("pallas_megakernel.drain_block",
          "gossip_simulator_tpu.ops.pallas_megakernel",
          8, (4, 8, 16, 32), int, "never",
          "PALLAS_VALIDATION.json",
          "phase-2 megakernel pushsum-drain serial unroll (lanes per fori "
          "iteration); awaiting real TPU evidence -- interpret-mode "
          "timings would persist noise, so never table-persisted")
_register("pallas_megakernel.recv_block",
          "gossip_simulator_tpu.ops.pallas_megakernel",
          8, (4, 8, 16, 32), int, "never",
          "PALLAS_VALIDATION.json",
          "phase-2 megakernel receive-landing serial unroll (routed lanes "
          "per fori iteration); same TPU-evidence gate as drain_block")
_register("pallas_overlay.slot_block",
          "gossip_simulator_tpu.ops.pallas_overlay_kernel",
          512, (128, 256, 512, 1024), int, "never",
          "PALLAS_VALIDATION.json",
          "phase-1 overlay megakernel negotiate/request rows per serial "
          "block; awaiting real TPU evidence -- interpret-mode timings "
          "would persist noise, so never table-persisted")
_register("pallas_overlay.chunk_block",
          "gossip_simulator_tpu.ops.pallas_overlay_kernel",
          1024, (256, 512, 1024, 2048), int, "never",
          "PALLAS_VALIDATION.json",
          "phase-1 hosted-occupancy columns per serial block (the ladder "
          "re-selection pass); same TPU-evidence gate as slot_block")
_register("config.overlay_ticks_auto_max", "gossip_simulator_tpu.config",
          10_000_000, (1_000_000, 10_000_000), int, "never",
          "BENCH_SELF_r07.json",
          "overlay_mode auto band: switches the phase-1 engine (true vs "
          "estimated stabilization clock) -- semantics, never "
          "table-persisted")


@dataclasses.dataclass(frozen=True)
class Space:
    """One named sweep: the tunables searched together and the workload
    shape (a Config-kwargs dict scripts/autotune.py completes with n and
    seed) that exercises them."""

    name: str
    tunables: tuple
    workload: dict
    doc: str
    tpu_only: bool = False


SPACES: dict[str, Space] = {
    "chunk_ladder": Space(
        name="chunk_ladder",
        tunables=("event.drain_chunk_floor", "event.drain_chunk_hi",
                  "event.drain_chunk_hi_lowdeg",
                  "event.drain_chunk_hi_suppress"),
        workload=dict(fanout=6, graph="kout", backend="jax", crashrate=0.0,
                      coverage_target=0.95, max_rounds=3000),
        doc="Event-engine drain chunk (folds the deleted "
            "scripts/chunk_sweep.py fanout-3 and chunk_sweep_f6.py "
            "fanout-6 ladders; only tunables the workload shape actually "
            "reaches are swept)"),
    "overlay_chunk": Space(
        name="overlay_chunk",
        tunables=("overlay.delivery_chunk_base", "overlay.delivery_chunk_cap",
                  "overlay.adaptive_chunk_max",
                  "overlay_ticks.delivery_chunk_cap"),
        workload=dict(graph="overlay", backend="jax", crashrate=0.001,
                      coverage_target=0.95, max_rounds=3000),
        doc="Overlay delivery chunk ladders (rounds engine base/cap, "
            "adaptive rung ceiling, ticks drain cap)"),
    "exchange": Space(
        name="exchange",
        tunables=("exchange.rank_max_shards", "exchange.chernoff_pad"),
        workload=dict(fanout=6, graph="kout", backend="sharded",
                      crashrate=0.0, coverage_target=0.95, max_rounds=3000),
        doc="Sharded exchange rank path and wire-cap pad"),
    "event_caps": Space(
        name="event_caps",
        tunables=("event.slot_headroom",),
        workload=dict(fanout=6, graph="kout", backend="jax", crashrate=0.0,
                      coverage_target=0.95, max_rounds=3000),
        doc="Event mail-ring capacity headroom (timing evidence only; "
            "never table-persisted)"),
    "block_shapes": Space(
        name="block_shapes",
        tunables=("pallas_graph.block_rows",
                  "pallas_megakernel.drain_block",
                  "pallas_megakernel.recv_block",
                  "pallas_overlay.slot_block",
                  "pallas_overlay.chunk_block"),
        workload=dict(fanout=6, graph="kout", backend="jax", crashrate=0.0,
                      coverage_target=0.95, max_rounds=3000, pallas=True),
        doc="Pallas graph-generator block height (TPU only: the gate "
            "rejects every alternative by construction -- the sweep "
            "documents the cost of the 512 default, it cannot move it)",
        tpu_only=True),
}


# --- resolution ------------------------------------------------------------
# Autotune candidate overrides: process-global so they reach cfg-less
# call sites (route_multi's auto rank path, chernoff_cap, the pallas
# graph wrappers) during a candidate's build+run.
_OVERRIDES: dict[str, float] = {}
# Ambient config stack: driver.run_simulation pushes its cfg so cfg-less
# call sites resolve the active tuning table too.
_AMBIENT: list = []


@contextlib.contextmanager
def override(values: dict):
    """Apply candidate values for the dynamic extent (scripts/autotune.py
    only -- production resolution goes through tables).  Unknown names
    raise; values are coerced to the tunable's kind."""
    coerced = {}
    for name, v in values.items():
        t = REGISTRY.get(name)
        if t is None:
            raise KeyError(f"unknown tunable {name!r} "
                           f"(registered: {sorted(REGISTRY)})")
        coerced[name] = t.kind(v)
    saved = dict(_OVERRIDES)
    _OVERRIDES.update(coerced)
    try:
        yield
    finally:
        _OVERRIDES.clear()
        _OVERRIDES.update(saved)


@contextlib.contextmanager
def ambient(cfg):
    """Make `cfg` the table-resolution context for cfg-less call sites
    (driver.run_simulation wraps each run in this)."""
    _AMBIENT.append(cfg)
    try:
        yield
    finally:
        _AMBIENT.pop()


# The Config fields that pin a table entry's workload shape (raw field
# values, not resolved properties: deterministic, jax-free, and JSON
# round-trip stable).  n and seed are deliberately absent -- the scale
# band covers n, and the cross-shape probe gate in scripts/autotune.py
# varies exactly those two axes before a gated winner may persist.
SHAPE_FIELDS = ("backend", "engine", "graph", "protocol", "fanout", "fanin",
                "delaylow", "delayhigh", "crashrate", "rumors",
                "dup_suppress")


def workload_shape(cfg) -> dict:
    """The shape key stamped into (and matched against) table entries
    carrying persist="gated" tunables."""
    return {f: getattr(cfg, f) for f in SHAPE_FIELDS}


def shape_digest(shape: dict) -> str:
    """Short stable digest of a shape key (entry-id component, so two
    sweeps of the same space at different workloads coexist)."""
    raw = json.dumps(shape, sort_keys=True).encode()
    return hashlib.sha256(raw).hexdigest()[:8]


def effective_value(name: str, cfg):
    """The derived constant the tunable actually feeds at `cfg` (the
    registered ``effect`` function, e.g. event.drain_chunk), or the
    resolved value itself when no effect is declared.  The autotuner
    compares this under override vs default: a candidate that cannot
    change it at the swept shape ran the identical program, so its
    timing delta is noise and its neutrality verdict vacuous."""
    t = REGISTRY[name]
    if not t.effect:
        return value(name, cfg)
    mod_name, _, fn_name = t.effect.partition(":")
    return getattr(importlib.import_module(mod_name), fn_name)(cfg)


def table_path(cfg) -> Optional[str]:
    """Resolve -tuning-table: "off" -> None, "auto" -> the committed
    table when present, else the explicit path."""
    sel = getattr(cfg, "tuning_table", "auto")
    if sel == "off":
        return None
    if sel == "auto":
        return COMMITTED_TABLE if os.path.exists(COMMITTED_TABLE) else None
    return sel


_TABLE_CACHE: dict = {}


def load_table(path: str) -> dict:
    """Read + sanity-check a tuning table (cached per (path, mtime))."""
    key = (path, os.stat(path).st_mtime_ns)
    if key in _TABLE_CACHE:
        return _TABLE_CACHE[key]
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != TABLE_SCHEMA:
        raise ValueError(f"{path}: tuning-table schema "
                         f"{doc.get('schema')!r} != {TABLE_SCHEMA}")
    for e in doc.get("entries", ()):
        for field in ("id", "platform", "scale_band", "values"):
            if field not in e:
                raise ValueError(f"{path}: entry missing {field!r}: {e}")
        gated = [k for k in e["values"]
                 if k in REGISTRY and REGISTRY[k].persist == "gated"]
        if gated and "shape" not in e:
            # A gated value with no shape key would apply band-wide --
            # exactly the transfer the persist taxonomy forbids.  Failing
            # the load degrades every consumer to defaults (entries_for
            # swallows the error), never to a mis-applied constant.
            raise ValueError(f"{path}: entry {e['id']!r} carries gated "
                             f"tunables {gated} without a workload shape")
    _TABLE_CACHE.clear()  # one live table per path in practice
    _TABLE_CACHE[key] = doc
    return doc


_PLATFORM_CACHE: Optional[tuple[str, str]] = None


def _platform() -> tuple[str, str]:
    """(backend_platform, device_kind) -- the env.json fingerprint's
    fields a table entry keys on.  Lazy jax import (post-setup paths
    only; Config.validate() never reaches here); cached, since every
    tunable read resolves it."""
    global _PLATFORM_CACHE
    if _PLATFORM_CACHE is None:
        import jax

        devs = jax.devices()
        kind = getattr(devs[0], "device_kind", "") if devs else ""
        _PLATFORM_CACHE = (jax.default_backend(), str(kind))
    return _PLATFORM_CACHE


def entries_for(cfg) -> list[dict]:
    """ALL table entries matching this config's platform + scale band
    (+ workload shape, for entries carrying one), sorted by id.  Entries
    from different spaces coexist -- values are merged across them, not
    shadowed by whichever happens to match first.  Any resolution error
    returns [] (a tuning table must never be able to fail a run that
    would run on defaults)."""
    try:
        path = table_path(cfg)
        if path is None:
            return []
        doc = load_table(path)
        platform, kind = _platform()
        band = scale_band(cfg.n)
        shape = None
        out = []
        for e in doc.get("entries", ()):
            if e["platform"] != platform or e["scale_band"] != band:
                continue
            want_kind = e.get("device_kind", "")
            if want_kind and want_kind != kind:
                continue
            if "shape" in e:
                if shape is None:
                    shape = workload_shape(cfg)
                if e["shape"] != shape:
                    continue
            out.append(e)
        return sorted(out, key=lambda e: e["id"])
    except Exception:
        return []


def entry_for(cfg) -> Optional[dict]:
    """First matching entry or None (driver banner convenience; value()
    and entry_id() merge across entries_for)."""
    es = entries_for(cfg)
    return es[0] if es else None


def entry_id(cfg) -> str:
    """The "+"-joined ids of every active tuning-table entry, or
    "defaults".  Never raises -- stamped by Config.resolved_gates() into
    every artifact, so compare_runs attributes the full constant set."""
    es = entries_for(cfg)
    return "+".join(e["id"] for e in es) if es else "defaults"


def value(name: str, cfg=None, default=None):
    """Resolve one tunable (see module docstring for the order).  The
    explicit-CLI-flag rung lives at the call site (e.g. delivery_chunk
    checks cfg.compact_chunk first), mirroring how those overrides
    already short-circuit the constants.  `default`, when given, stands
    in for the registered default so monkeypatched module globals (the
    SPILL_CAP/ADAPTIVE_CHUNK_MAX test pattern) keep working; cfg=None
    call sites fall back to the ambient config pushed by the driver."""
    t = REGISTRY[name]
    if name in _OVERRIDES:
        return _OVERRIDES[name]
    c = cfg if cfg is not None else (_AMBIENT[-1] if _AMBIENT else None)
    if c is not None:
        for e in entries_for(c):
            if name not in e.get("values", {}):
                continue
            if t.persist == "gated" and "shape" not in e:
                # Belt under the load_table check: a gated value only
                # ever applies from a shape-matched entry.
                continue
            return t.kind(e["values"][name])
    return t.default if default is None else default


def registry_rows() -> list[dict]:
    """Registry as plain dicts (README generator / tests)."""
    return [dataclasses.asdict(t) for t in REGISTRY.values()]
