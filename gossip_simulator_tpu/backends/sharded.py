"""Mesh-sharded backend: the 100M-node path (BASELINE.json config 5).

Same Stepper surface as the single-chip jax backend; state lives sharded
across the mesh from birth (graph generation happens per shard -- nothing
is ever materialized on one device), and every window is one jitted
shard_map call whose collectives ride ICI.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS
from gossip_simulator_tpu.models import epidemic, overlay
from gossip_simulator_tpu.parallel import sharded_step
from gossip_simulator_tpu.parallel.mesh import AXIS, node_mesh, shard_size
from gossip_simulator_tpu.utils import rng as _rng
from gossip_simulator_tpu.models.state import msg64_value
from gossip_simulator_tpu.utils.metrics import Stats


def _host_gather(x) -> np.ndarray:
    """Leaf -> host array.  Under -distributed a node-sharded array is not
    fully addressable from one process; process_allgather (a collective --
    every process must traverse the same leaves in the same order, which
    NamedTuple._asdict guarantees) assembles the global value on every
    host.  Replicated scalars and single-process runs take the plain path.
    np.array (COPY), not np.asarray: on the CPU platform asarray of a
    device buffer is zero-copy and the donating window fns reuse the
    buffer on the next call, silently mutating the 'snapshot' (see
    JaxStepper.overlay_state_pytree's note)."""
    if getattr(x, "is_fully_addressable", True):
        return np.array(x)
    from jax.experimental import multihost_utils

    # gossip-lint: allow(donation-aliasing) process_allgather materializes
    # a fresh global array from the collective -- it never aliases the
    # donated per-shard state buffers, so the zero-copy view is safe.
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


class ShardedStepper(Stepper):
    name = "sharded"

    @property
    def primary_host(self) -> bool:
        return jax.process_index() == 0

    def __init__(self, cfg, n_devices: int | None = None):
        super().__init__(cfg)
        self.mesh = node_mesh(n_devices)
        shard_size(cfg.n, self.mesh)  # validate divisibility early

    def init(self) -> None:
        cfg = self.cfg
        self.key = _rng.base_key(cfg.seed)
        self._mean_delay = (
            (cfg.delaylow + cfg.delayhigh) / 2.0
            if cfg.effective_time_mode == "ticks" else 1.0)
        self._overlay_rounds = 0
        self.exhausted = False
        self._mailbox_dropped = 0
        self._window = 1 if cfg.effective_time_mode == "rounds" else WINDOW_MS
        if cfg.telemetry_enabled:
            from gossip_simulator_tpu.utils.telemetry import TelemetrySession

            self._telem = TelemetrySession(
                cfg, n_shards=int(self.mesh.shape[AXIS]))
        else:
            self._telem = None
        telem_on = self._telem is not None
        if cfg.model == "pushsum":
            from gossip_simulator_tpu.parallel import pushsum_sharded

            self._window_fn = pushsum_sharded.make_window_fn(
                cfg, self.mesh, self._window)
            self._seed_fn = pushsum_sharded.make_seed_fn(cfg, self.mesh)
            self._run_fn = pushsum_sharded.make_run_to_coverage_fn(
                cfg, self.mesh, telemetry=telem_on)
            init_fn = pushsum_sharded.make_sharded_pushsum_init
        elif cfg.engine_resolved == "event":
            from gossip_simulator_tpu.parallel import event_sharded

            self._window_fn = event_sharded.make_window_fn(
                cfg, self.mesh, self._window)
            self._seed_fn = event_sharded.make_seed_fn(cfg, self.mesh)
            self._run_fn = event_sharded.make_run_to_coverage_fn(
                cfg, self.mesh, telemetry=telem_on)
            init_fn = event_sharded.make_sharded_event_init
        else:
            self._window_fn = sharded_step.make_window_fn(cfg, self.mesh,
                                                          self._window)
            self._seed_fn = sharded_step.make_seed_fn(cfg, self.mesh)
            self._run_fn = sharded_step.make_run_to_coverage_fn(
                cfg, self.mesh, telemetry=telem_on)
            init_fn = sharded_step.make_sharded_init
        if cfg.resume:
            # State arrives via load_state_pytree; building a sharded graph
            # here would be thrown away (see JaxStepper.init).
            self.state = None
            self._overlay_done = True
        elif cfg.graph == "overlay":
            self._setup_overlay(build_state=True)
        else:
            self._init_fn = init_fn(cfg, self.mesh)
            self.state = self._init_fn()
            self._overlay_done = True

    # --- phase 1 ---------------------------------------------------------------
    def _setup_overlay(self, build_state: bool) -> None:
        """Overlay machinery over the mesh; `build_state=False` is the
        phase-1 resume path (see JaxStepper._setup_overlay)."""
        cfg = self.cfg
        self._faithful_overlay = cfg.overlay_mode_resolved == "ticks"
        if self._faithful_overlay:
            from gossip_simulator_tpu.parallel import \
                overlay_ticks_sharded as ots

            self._oround = ots.make_poll_fn(cfg, self.mesh)
            self.ostate = (ots.make_sharded_init(cfg, self.mesh)(self.key)
                           if build_state else None)
        else:
            n_local = shard_size(cfg.n, self.mesh)
            if n_local >= overlay.SPLIT_ROUND_MIN_ROWS:
                # The sharded rounds engine always runs the FUSED round
                # inside shard_map (the split round's host-driven call
                # sequence cannot run per shard); per-shard slices at
                # memory scale can hit the fused-round OOM class the
                # single-device split exists to avoid (advisor r4).
                # -phase1-kernel threads through cfg into the per-shard
                # round body (overlay.phase1_slot_fns), so the fused
                # negotiate passes shrink the slot loop's temp set here
                # too -- but they do not change the mailbox allocations
                # this band is about.
                import warnings

                warnings.warn(
                    f"sharded overlay: {n_local} rows/shard is at the "
                    f"fused-round memory band (>= "
                    f"{overlay.SPLIT_ROUND_MIN_ROWS}); the sharded engine "
                    "has no split-round fallback -- use at least "
                    f"{-(-cfg.n // overlay.SPLIT_ROUND_MIN_ROWS)} devices "
                    "for this n, or expect HBM exhaustion on 16 GB chips",
                    stacklevel=2)
            self._oround = sharded_step.make_overlay_round_fn(
                cfg, self.mesh)
            self.ostate = (sharded_step.make_sharded_overlay_init(
                cfg, self.mesh)() if build_state else None)
        self._overlay_done = False
        self.state = None

    def _overlay_mod(self):
        if getattr(self, "_faithful_overlay", False):
            from gossip_simulator_tpu.models import overlay_ticks

            return overlay_ticks
        return overlay

    def overlay_window(self) -> tuple[int, int, bool]:
        if self._overlay_done:
            return 0, 0, True
        self.ostate = self._oround(self.ostate, self.key)
        self._overlay_rounds += 1
        faithful = getattr(self, "_faithful_overlay", False)
        quiesced = self._overlay_mod().quiesced(self.ostate)
        tick = self.ostate.tick if faithful else 0
        mk, bk, q, tick = jax.device_get(
            (self.ostate.win_makeups, self.ostate.win_breakups,
             quiesced, tick))
        self._phase1_ms = (float(tick) if faithful
                           else self._overlay_rounds * self._mean_delay)
        if bool(q):
            self._finish_overlay()
        return int(mk), int(bk), bool(q)

    def overlay_run_to_quiescence(self, max_windows: int,
                                  budget: int | None = None
                                  ) -> tuple[int, bool]:
        """Phase-1 fast path for quiet runs (see JaxStepper's method --
        same contract, same driver gate).  The bounded while_loop wraps
        the jitted shard_map'd poll OUTSIDE shard_map: the quiescence
        counters are replicated on the outer state (psum'd inside the
        poll), so the loop condition is mesh-uniform by construction and
        every shard runs the same trip count."""
        if self._overlay_done:
            return 0, True
        import time

        telem = self._telem
        omod = self._overlay_mod()
        if getattr(self, "_orun", None) is None:
            self._orun = overlay.make_bounded_run(
                self._oround, omod.quiesced, telemetry=telem is not None)
        if budget is None:
            # Per-call device work scales with the SHARD slice, so the
            # single-chip watchdog budget stretches by the shard count
            # (scaled inside run_call_budget, before its >=1 clamp).
            budget = omod.run_call_budget(self.cfg,
                                          shards=self.mesh.shape[AXIS])
        from gossip_simulator_tpu.utils import trace as _trace

        faithful = getattr(self, "_faithful_overlay", False)
        hist = telem.begin_overlay(max_windows) if telem is not None else None
        q = False
        calls = 0
        while True:
            lim = min(budget, max_windows - self._overlay_rounds)
            if lim <= 0:
                break
            t0 = time.perf_counter()
            # Each bounded call dispatches the shard_map'd poll: the
            # cross-shard all_to_all exchange lives inside it, so this
            # span IS the host-visible "sharded exchange" cost envelope.
            with _trace.span("phase1.compile+run" if calls == 0
                             else "phase1.sharded_call",
                             cat="device") as sp:
                if hist is not None:
                    self.ostate, polls, q, hist = self._orun(
                        self.ostate, self.key, np.int32(lim), hist)
                else:
                    self.ostate, polls, q = self._orun(
                        self.ostate, self.key, np.int32(lim))
                tick = self.ostate.tick if faithful else 0
                polls, q, tick = jax.device_get((polls, q, tick))
                if sp is not None:
                    sp.update(windows=int(polls),
                              shards=int(self.mesh.shape[AXIS]))
            calls += 1
            if telem is not None:
                telem.tally_overlay_call(time.perf_counter() - t0)
            self._overlay_rounds += int(polls)
            self._phase1_ms = (float(tick) if faithful
                               else self._overlay_rounds * self._mean_delay)
            if bool(q):
                break
        if hist is not None:
            telem.end_overlay(hist)
        if bool(q):
            self._finish_overlay()
        return self._overlay_rounds, bool(q)

    def _finish_overlay(self) -> None:
        self._overlay_done = True
        # Freeze phase-1 elapsed time (see JaxStepper.overlay_window).
        self._stabilize_ms = self._phase1_ms
        self._mailbox_dropped = int(
            jax.device_get(self.ostate.mailbox_dropped))
        self.state = self._epidemic_from_overlay()
        self.ostate = None

    def _epidemic_from_overlay(self):
        cfg, mesh = self.cfg, self.mesh
        n_local = shard_size(cfg.n, mesh)
        from jax.sharding import PartitionSpec as P

        n_shards = int(mesh.shape[AXIS])
        if cfg.engine_resolved == "event":
            from gossip_simulator_tpu.models import event as _event
            from gossip_simulator_tpu.parallel import event_sharded

            def build(c, friends, cnt):
                return _event.init_state(c, friends, cnt, n_shards=n_shards)
            out_specs = event_sharded.event_state_specs(cfg)
        else:
            def build(c, friends, cnt):
                return epidemic.init_state(c, friends, cnt, n_local=n_local,
                                           n_shards=n_shards)
            out_specs = sharded_step.sim_state_specs(cfg)

        from gossip_simulator_tpu.parallel.mesh import shard_map

        fn = shard_map(lambda f, c: build(cfg, f, c), mesh=mesh,
                       in_specs=(P("nodes", None), P("nodes")),
                       out_specs=out_specs)
        return jax.jit(fn)(self.ostate.friends, self.ostate.friend_cnt)

    # --- phase 2 ---------------------------------------------------------------
    def seed(self) -> None:
        self._seeded = True
        self.state = self._seed_fn(self.state, self.key)

    def gossip_window(self) -> Stats:
        from gossip_simulator_tpu.models.event import in_flight as _inflight
        from gossip_simulator_tpu.utils import trace as _trace

        # The per-window sharded dispatch (all_to_all exchange inside).
        with _trace.span("phase2.sharded_window", cat="device",
                         shards=int(self.mesh.shape[AXIS])):
            self.state = self._window_fn(self.state, self.key)
        stats = self.stats()
        in_flight = int(jax.device_get(_inflight(self.state)))
        # Heal-on runs never report exhaustion mid-run (see
        # base.run_bounded_to_target).
        self.exhausted = (in_flight == 0
                          and self.cfg.protocol != "pushpull"
                          and not self.cfg.overlay_heal_resolved)
        stats.exhausted = self.exhausted
        return stats

    def reset_state(self) -> None:
        """Rebuild phase-2 state (same seed => same trajectory) without
        re-tracing; the hot fns donate their inputs (see JaxStepper)."""
        if self.cfg.graph == "overlay":
            raise ValueError("reset_state requires a static graph")
        self.state = self._init_fn()
        self.exhausted = False
        if self._telem is not None:
            self._telem.reset_gossip()

    def run_to_target(self) -> Stats:
        """Bounded device-side while_loop (base.run_bounded_to_target)."""
        from gossip_simulator_tpu.backends.base import run_bounded_to_target

        return run_bounded_to_target(self)

    @property
    def overlay_clock_scale(self) -> float:
        """See JaxStepper.overlay_clock_scale."""
        if getattr(self, "_faithful_overlay", False):
            return 1.0
        return getattr(self, "_mean_delay", 1.0)

    def stats(self) -> Stats:
        from gossip_simulator_tpu.models import event as event_mod

        st = self.state
        extra = st.mail_dropped if hasattr(st, "mail_dropped") else 0
        rem = (event_mod.removed_count(st)
               if self.cfg.protocol == "sir" else 0)
        R = self.cfg.rumors
        multi = self.cfg.multi_rumor
        rmin = st.rumor_recv[:R].min() if multi else -1
        rdone = (st.rumor_done[:R] >= 0).sum() if multi else 0
        (tm, tr, tc, trm, xo, tick, dropped, sc, sr, pd,
         hr, rmin, rdone) = jax.device_get(
            (st.total_message, st.total_received, st.total_crashed,
             rem, st.exchange_overflow, st.tick, extra,
             st.scen_crashed, st.scen_recovered, st.part_dropped,
             st.heal_repaired, rmin, rdone))
        return Stats(
            n=self.cfg.n, round=int(tick),
            total_received=int(tr), total_message=msg64_value(tm),
            total_crashed=int(tc), total_removed=int(trm),
            mailbox_dropped=self._mailbox_dropped + int(dropped),
            exchange_overflow=int(xo),
            scen_crashed=int(sc), scen_recovered=int(sr),
            part_dropped=int(pd), heal_repaired=int(hr),
            exhausted=self.exhausted,
            rumors=R, rumor_min_recv=int(rmin), rumors_done=int(rdone),
        )

    def sim_time_ms(self) -> float:
        if self.state is None or not self._overlay_done:
            return getattr(self, "_phase1_ms",
                           self._overlay_rounds * self._mean_delay)
        if not getattr(self, "_seeded", False):
            # Between quiescence and the broadcast: phase-1 elapsed time.
            return getattr(self, "_stabilize_ms", 0.0)
        return float(jax.device_get(self.state.tick))

    def overlay_state_pytree(self):
        """Host-gathered mid-construction phase-1 snapshot (None once the
        overlay is done).  Sharded leaves gather to global arrays; the
        ticks engine's packed ring gathers as S per-shard rings
        concatenated (spec P(AXIS)), so it restores onto the same shard
        count only -- prepare_overlay_restore_tree checks the geometry."""
        if self._overlay_done or self.ostate is None:
            return None
        return {k: _host_gather(v) for k, v in self.ostate._asdict().items()}

    def load_overlay_state_pytree(self, tree, windows: int = 0) -> None:
        """Resume INTO phase 1 on the mesh (see JaxStepper's method)."""
        from jax.sharding import NamedSharding

        from gossip_simulator_tpu.utils.checkpoint import \
            prepare_overlay_restore_tree

        cfg, mesh = self.cfg, self.mesh
        tree = prepare_overlay_restore_tree(tree, cfg,
                                            n_shards=mesh.shape[AXIS])
        self._setup_overlay(build_state=False)
        if self._faithful_overlay:
            from gossip_simulator_tpu.models.overlay_ticks import \
                OverlayTickState
            from gossip_simulator_tpu.parallel.overlay_ticks_sharded import \
                overlay_tick_state_specs

            cls, specs = OverlayTickState, overlay_tick_state_specs()
        else:
            from gossip_simulator_tpu.models.state import OverlayState

            cls, specs = OverlayState, sharded_step.overlay_state_specs()
        # jnp.array (device COPY) before placement: see load_state_pytree's
        # zero-copy + donation note.
        self.ostate = cls(**{
            k: jax.device_put(jnp.array(v),
                              NamedSharding(mesh, getattr(specs, k)))
            for k, v in tree.items()})
        self._overlay_rounds = int(windows)
        self._phase1_ms = (
            float(np.asarray(tree["tick"])) if self._faithful_overlay
            else self._overlay_rounds * self._mean_delay)

    def state_pytree(self):
        """Host-gathered snapshot (np.asarray collects all shards).  The
        event mail ring is S per-shard rings concatenated, so mail_geom
        records the PER-SHARD slot geometry plus the shard count -- a
        snapshot restores onto the same device count only (the ring engine's
        state is layout-independent and restores onto any mesh)."""
        if self.state is None:
            return None
        tree = {k: _host_gather(v) for k, v in self.state._asdict().items()}
        if "mail_ids" in tree:
            cfg = self.cfg
            if cfg.model == "pushsum":
                from gossip_simulator_tpu.models import pushsum as geo
            else:
                from gossip_simulator_tpu.models import event as geo

            n_local = shard_size(cfg.n, self.mesh)
            tree["mail_geom"] = np.asarray(
                [geo.slot_cap(cfg, n_local), geo.drain_chunk(cfg, n_local),
                 self.mesh.shape[AXIS]], dtype=np.int64)
        # Phase-1 overlay drops live host-side, not in the device state --
        # persist them or a resumed run under-reports mailbox_dropped.
        tree["host_mailbox_dropped"] = np.int64(self._mailbox_dropped)
        return tree

    def load_state_pytree(self, tree) -> None:
        """Restore a snapshot onto the mesh (validation, legacy coercion
        and per-shard mail-ring repack shared with the single-device
        backend: utils/checkpoint.prepare_restore_tree), then device_put
        every leaf with its partition spec -- the restored run's trajectory
        is identical to the uninterrupted one (step keys depend only on
        (seed, tick, shard))."""
        from jax.sharding import NamedSharding

        from gossip_simulator_tpu.models.event import EventState
        from gossip_simulator_tpu.models.state import SimState
        from gossip_simulator_tpu.parallel import event_sharded
        from gossip_simulator_tpu.utils.checkpoint import prepare_restore_tree

        cfg, mesh = self.cfg, self.mesh
        tree = prepare_restore_tree(tree, cfg, n_shards=mesh.shape[AXIS])
        self._mailbox_dropped = int(tree.pop("host_mailbox_dropped", 0))
        if cfg.model == "pushsum":
            from gossip_simulator_tpu.models.pushsum import PushSumState
            from gossip_simulator_tpu.parallel import pushsum_sharded

            cls = PushSumState
            specs = pushsum_sharded.pushsum_state_specs(cfg)
        elif cfg.engine_resolved == "event":
            cls, specs = EventState, event_sharded.event_state_specs(cfg)
        else:
            cls, specs = SimState, sharded_step.sim_state_specs(cfg)
        # jnp.array (device COPY) before placement: on the CPU platform
        # device_put of a host array can be zero-copy, and the restored
        # leaves feed straight into DONATING jitted fns -- XLA then reuses
        # a buffer it does not own, corrupting the restored state
        # (observed as nondeterministic quiet-resume totals on the CPU
        # mesh; the save-side twin of _host_gather's copy note.  TPU
        # device_put always copies to HBM, masking this on hardware).
        self.state = cls(**{
            k: jax.device_put(jnp.array(v),
                              NamedSharding(mesh, getattr(specs, k)))
            for k, v in tree.items()})
        self._overlay_done = True
        self._seeded = True  # snapshots are taken mid-phase-2
