// Event-driven gossip simulator, C API for ctypes.
//
// The fast native tier of the framework's oracle/baseline path: the same
// discrete-event semantics as backends/native.py (itself a reimplementation
// of /root/reference/simulator.go's behavioral contract -- makeup/breakup
// membership at simulator.go:66-106, SI receive path at simulator.go:107-123,
// delayed broadcast at simulator.go:140-168) in C++ with a binary heap, so
// the CPU baseline for bench.py runs at native speed like the reference's Go
// loop (the Go toolchain is absent in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC gossip_sim.cpp -o libgossip_sim.so
// (done lazily by backends/cpp.py).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

enum Kind : int32_t { BOOT = 0, MAKEUP = 1, BREAKUP = 2, MSG = 3, REBROADCAST = 4 };
enum Protocol : int32_t { SI = 0, PUSHPULL = 1, SIR = 2 };
enum Graph : int32_t { OVERLAY = 0, KOUT = 1, ERDOS = 2, RING = 3 };

struct Event {
  double t;
  uint64_t seq;
  int32_t kind;
  int32_t dst;
  int32_t src;
};
struct EventCmp {
  bool operator()(const Event& a, const Event& b) const {
    if (a.t != b.t) return a.t > b.t;  // min-heap
    return a.seq > b.seq;
  }
};

struct Params {
  int64_t n;
  int32_t fanout, fanin;
  int32_t delaylow, delayhigh;
  double droprate, crashrate, removal_rate;
  double er_lambda;
  int32_t protocol, graph, rounds_mode, compat, seed;
};

struct Sim {
  Params p;
  std::mt19937_64 rng;
  std::vector<std::vector<int32_t>> friends;
  std::vector<uint8_t> received, crashed, removed;
  std::priority_queue<Event, std::vector<Event>, EventCmp> heap;
  uint64_t seq = 0;
  int64_t pending_membership = 0;
  double now = 0.0, phase_start = 0.0;
  int64_t total_message = 0, total_received = 0, total_crashed = 0;
  int64_t makeups = 0, breakups = 0;
  int64_t win_makeups = 0, win_breakups = 0;
  bool overlay_done = false;
  bool exhausted = false;

  double urand() { return std::uniform_real_distribution<double>(0.0, 1.0)(rng); }
  int64_t irand(int64_t hi) {  // [0, hi)
    return std::uniform_int_distribution<int64_t>(0, hi - 1)(rng);
  }
  double p_eff(double p) const {
    // simulator.go:172,180: rand.Intn(100) < int(p*100) truncation.
    return p_compat ? std::trunc(p * 100.0) / 100.0 : p;
  }
  bool p_compat = false;
  bool bern(double p) {
    double q = p_eff(p);
    return q > 0.0 && urand() < q;
  }
  double delay() {
    if (p.rounds_mode) return 1.0;
    int64_t d = p.delaylow + irand(p.delayhigh - p.delaylow);
    return d < 1 ? 1.0 : double(d);
  }

  void push(double t, int32_t kind, int32_t dst, int32_t src) {
    if (kind == BOOT || kind == MAKEUP || kind == BREAKUP) pending_membership++;
    heap.push({t, ++seq, kind, dst, src});
  }

  void init() {
    rng.seed(uint64_t(p.seed));
    p_compat = p.compat != 0;
    friends.assign(p.n, {});
    received.assign(p.n, 0);
    crashed.assign(p.n, 0);
    removed.assign(p.n, 0);
    if (p.graph == OVERLAY) {
      for (int64_t i = 0; i < p.n; ++i) push(0.0, BOOT, int32_t(i), -1);
      overlay_done = false;
    } else {
      gen_static();
      overlay_done = true;
    }
  }

  void gen_static() {
    if (p.graph == KOUT) {
      for (int64_t i = 0; i < p.n; ++i) {
        friends[i].reserve(p.fanout);
        for (int32_t j = 0; j < p.fanout; ++j) {
          int64_t x = irand(p.n);
          if (x == i) x = (x + 1) % p.n;  // simulator.go:98-100 patch
          friends[i].push_back(int32_t(x));
        }
      }
    } else if (p.graph == ERDOS) {
      std::poisson_distribution<int32_t> pois(p.er_lambda);
      for (int64_t i = 0; i < p.n; ++i) {
        int32_t d = pois(rng);
        friends[i].reserve(d);
        for (int32_t j = 0; j < d; ++j) {
          int64_t x = irand(p.n);
          if (x == i) x = (x + 1) % p.n;
          friends[i].push_back(int32_t(x));
        }
      }
    } else {  // RING
      for (int64_t i = 0; i < p.n; ++i)
        for (int32_t j = 1; j <= p.fanout; ++j)
          friends[i].push_back(int32_t((i + j) % p.n));
    }
  }

  void broadcast(double t, int32_t node) {
    // One shared delay per broadcast; per-link drop (simulator.go:140-149).
    double d = delay();
    for (int32_t f : friends[node])
      if (!bern(p.droprate)) push(t + d, MSG, f, node);
    if (p.protocol == SIR) {
      if (bern(p.removal_rate)) removed[node] = 1;
      else push(t + d, REBROADCAST, node, node);
    }
  }

  void receive(double t, int32_t dst) {
    if (crashed[dst]) return;  // black-hole, uncounted (simulator.go:108-110)
    total_message++;
    if (bern(p.crashrate)) { crashed[dst] = 1; total_crashed++; return; }
    if (received[dst]) return;  // duplicate (simulator.go:117-119)
    received[dst] = 1;
    total_received++;
    broadcast(t, dst);
  }

  void handle(const Event& e) {
    if (e.kind == BOOT || e.kind == MAKEUP || e.kind == BREAKUP)
      pending_membership--;
    auto& f = friends[e.dst];
    switch (e.kind) {
      case BOOT: {  // simulator.go:95-106
        if (int32_t(f.size()) < p.fanout) {
          int64_t x = irand(p.n);
          if (x == e.dst) x = (x + 1) % p.n;
          f.push_back(int32_t(x));
          push(e.t + delay(), MAKEUP, int32_t(x), e.dst);
          if (int32_t(f.size()) < p.fanout) push(e.t, BOOT, e.dst, -1);
        }
        break;
      }
      case MAKEUP: {  // simulator.go:66-75
        makeups++; win_makeups++;
        if (int32_t(f.size()) < p.fanin) {
          f.push_back(e.src);
        } else {
          int64_t vp = irand(f.size());
          push(e.t + delay(), BREAKUP, f[vp], e.dst);
          f[vp] = e.src;
        }
        break;
      }
      case BREAKUP: {  // simulator.go:76-94
        breakups++; win_breakups++;
        for (size_t i = 0; i < f.size(); ++i) {
          if (f[i] == e.src) {
            if (int32_t(f.size()) > p.fanout) {
              f.erase(f.begin() + i);  // order-preserving (simulator.go:127-138)
            } else {
              int64_t x;
              do { x = irand(p.n); } while (x == e.src || x == e.dst);
              f[i] = int32_t(x);
              push(e.t + delay(), MAKEUP, int32_t(x), e.dst);
            }
            break;
          }
        }
        break;
      }
      case MSG:
        receive(e.t, e.dst);
        break;
      case REBROADCAST:
        if (!crashed[e.dst] && !removed[e.dst]) broadcast(e.t, e.dst);
        break;
    }
  }

  void drain(double end) {
    while (!heap.empty() && heap.top().t < end) {
      Event e = heap.top();
      heap.pop();
      handle(e);
    }
  }

  void overlay_window(double win, int64_t* mk, int64_t* bk, int32_t* quiesced) {
    if (overlay_done) { *mk = *bk = 0; *quiesced = 1; return; }
    win_makeups = win_breakups = 0;
    drain(now + win);
    now += win;
    *mk = win_makeups;
    *bk = win_breakups;
    bool q = win_makeups == 0 && win_breakups == 0 && pending_membership == 0;
    if (q) overlay_done = true;
    *quiesced = q ? 1 : 0;
  }

  void seed() {
    phase_start = now;
    int32_t sender = int32_t(irand(p.n));
    if (p.protocol == PUSHPULL) {
      received[sender] = 1;
      total_received++;
      return;
    }
    if (!p_compat) { received[sender] = 1; total_received++; }
    broadcast(now, sender);
  }

  void pushpull_round() {
    // Mirrors backends/native.py::_pushpull_round (round-synchronous).
    std::vector<int32_t> newly;
    std::vector<uint8_t> rcv0 = received, crs0 = crashed;
    // push
    for (int64_t i = 0; i < p.n; ++i) {
      if (!rcv0[i] || crs0[i]) continue;
      for (int32_t j = 0; j < p.fanout; ++j) {
        int64_t tgt = irand(p.n);
        if (bern(p.droprate)) continue;
        if (crashed[tgt]) continue;
        total_message++;
        if (bern(p.crashrate)) {
          if (!crashed[tgt]) { crashed[tgt] = 1; total_crashed++; }
          continue;
        }
        if (!received[tgt] && !crashed[tgt]) { received[tgt] = 1; total_received++; }
      }
    }
    // pull (susceptible by the round-start snapshot)
    for (int64_t i = 0; i < p.n; ++i) {
      if (rcv0[i] || crashed[i]) continue;
      bool hit = false;
      for (int32_t j = 0; j < p.fanout; ++j) {
        int64_t tgt = irand(p.n);
        if (bern(p.droprate)) continue;
        if (crs0[tgt]) continue;
        total_message++;
        if (rcv0[tgt]) hit = true;
      }
      if (hit && !received[i]) { received[i] = 1; total_received++; }
    }
  }

  void gossip_window(double win) {
    if (p.protocol == PUSHPULL) {
      pushpull_round();
      now += 1.0;
      return;
    }
    drain(now + win);
    now += win;
    exhausted = heap.empty();
  }
};

// ---------------------------------------------------------------------------
// Multithreaded SI baseline (windowed bulk-synchronous parallel DES).
//
// The strongest native tier the TPU headline is compared against should use
// the whole host, not one core (VERDICT r3 stretch #8).  Because every
// network delay is >= delaylow, events inside one delaylow-wide window are
// causally independent -- the same insight the TPU event engine batches on
// (models/event.py) -- so T threads each own a contiguous node shard,
// process their shard's arrivals for the window, bucket the generated sends
// by destination-owner thread, and exchange them at a barrier.  Same-window
// arrival order is thread-interleaved rather than strictly time-ordered:
// the batched-envelope divergence the framework already documents for its
// own engines (README divergence table, "Same-tick crash ordering"); totals
// are statistically identical (each message still gets its own drop draw,
// each reception its own crash draw).  Scope: SI push on a static graph in
// ticks mode -- exactly the bench headline's shape.
// ---------------------------------------------------------------------------

struct MtSim {
  Params p;
  int nthreads;
  int64_t B;           // window width (ticks) = max(1, delaylow)
  int dw;              // future-window ring depth
  int64_t n_per;       // nodes per shard (ceil)
  int64_t now = 0;     // ticks (window-aligned)
  int64_t phase_start = 0;
  std::vector<std::vector<int32_t>> friends;  // shared read-only after init
  std::vector<uint8_t> received, crashed;     // owner-thread writes only
  // buckets[t][w]: packed (arrival_tick << 32 | node) arrivals for thread
  // t in absolute window (arrival_tick / B) (mod dw; dw covers the whole
  // in-flight horizon, (B-1) + delayhigh).
  std::vector<std::vector<std::vector<int64_t>>> buckets;
  // out[src][dst]: staged sends, exchanged at the barrier.
  std::vector<std::vector<std::vector<int64_t>>> out;
  std::vector<std::mt19937_64> rngs;
  std::vector<int64_t> t_message, t_received, t_crashed;
  std::mt19937_64 rng0;

  int owner(int64_t node) const { return int(node / n_per); }

  void init() {
    B = p.delaylow < 1 ? 1 : p.delaylow;
    dw = int((B - 1 + p.delayhigh + B - 1) / B) + 1;
    n_per = (p.n + nthreads - 1) / nthreads;
    rng0.seed(uint64_t(p.seed));
    received.assign(p.n, 0);
    crashed.assign(p.n, 0);
    friends.assign(p.n, {});
    // Same kout generator discipline as Sim::gen_static (single-threaded:
    // graph build is not the benchmarked phase).
    for (int64_t i = 0; i < p.n; ++i) {
      friends[i].reserve(p.fanout);
      for (int32_t j = 0; j < p.fanout; ++j) {
        int64_t x = std::uniform_int_distribution<int64_t>(0, p.n - 1)(rng0);
        if (x == i) x = (x + 1) % p.n;
        friends[i].push_back(int32_t(x));
      }
    }
    buckets.assign(nthreads, std::vector<std::vector<int64_t>>(dw));
    out.assign(nthreads, std::vector<std::vector<int64_t>>(nthreads));
    rngs.resize(nthreads);
    for (int t = 0; t < nthreads; ++t)
      rngs[t].seed(uint64_t(p.seed) * 0x9E3779B97F4A7C15ull + t + 1);
    t_message.assign(nthreads, 0);
    t_received.assign(nthreads, 0);
    t_crashed.assign(nthreads, 0);
  }

  // Stage node's broadcast from thread t at tick `send`: one shared delay
  // per broadcast, per-link drop (simulator.go:140-149).
  void stage_broadcast(int t, int64_t node, int64_t send) {
    auto& rng = rngs[t];
    int64_t d =
        p.delaylow +
        std::uniform_int_distribution<int64_t>(0, p.delayhigh - p.delaylow - 1)(
            rng);
    if (d < 1) d = 1;
    int64_t arr = send + d;
    for (int32_t f : friends[node]) {
      double q = p.droprate;
      if (q > 0.0 &&
          std::uniform_real_distribution<double>(0.0, 1.0)(rng) < q)
        continue;
      out[t][owner(f)].push_back((arr << 32) | uint32_t(f));
    }
  }

  // Move staged sends addressed to `owner_t` into its future buckets --
  // called with one task per OWNER, so each bucket has exactly one writer.
  void ingest_and_clear(int owner_t) {
    for (int s = 0; s < nthreads; ++s) {
      auto& v = out[s][owner_t];
      for (int64_t packed : v) {
        int64_t arr = packed >> 32;
        buckets[owner_t][(arr / B) % dw].push_back(packed);
      }
      v.clear();
    }
  }

  void seed() {
    phase_start = now;
    int64_t sender = std::uniform_int_distribution<int64_t>(0, p.n - 1)(rng0);
    received[sender] = 1;
    t_received[0]++;
    stage_broadcast(0, sender, now);
    for (int t = 0; t < nthreads; ++t) ingest_and_clear(t);
  }

  void process_bucket(int t, int64_t wslot) {
    auto& rng = rngs[t];
    auto& bucket = buckets[t][wslot];
    for (int64_t packed : bucket) {
      int32_t dst = int32_t(packed & 0xFFFFFFFF);
      if (crashed[dst]) continue;  // black-hole, uncounted
      t_message[t]++;
      if (p.crashrate > 0.0 &&
          std::uniform_real_distribution<double>(0.0, 1.0)(rng) <
              p.crashrate) {
        crashed[dst] = 1;
        t_crashed[t]++;
        continue;
      }
      if (received[dst]) continue;
      received[dst] = 1;
      t_received[t]++;
      stage_broadcast(t, dst, packed >> 32);
    }
    bucket.clear();
  }

  // Persistent worker pool: one thread per shard for the whole run (a
  // spawn-per-window variant costs 2*nthreads create/join cycles per
  // B-tick window, deflating the measured rate on many-core hosts --
  // exactly the bias this baseline exists to avoid).  Phases alternate
  // process (own bucket) and ingest (own inbound staging), separated by
  // the generation barrier.
  std::vector<std::thread> pool;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  int64_t generation = 0;
  int phase = 0;  // 1 = process, 2 = ingest
  int pending = 0;
  bool stopping = false;
  int64_t cur_wslot = 0;

  void pool_worker(int t) {
    int64_t seen = 0;
    while (true) {
      int ph;
      int64_t ws;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [&] { return stopping || generation > seen; });
        if (stopping) return;
        seen = generation;
        ph = phase;
        ws = cur_wslot;
      }
      if (ph == 1) process_bucket(t, ws);
      else ingest_and_clear(t);
      {
        std::lock_guard<std::mutex> lk(mu);
        if (--pending == 0) cv_done.notify_one();
      }
    }
  }

  void run_phase(int ph, int64_t wslot) {
    if (pool.empty()) {
      pool.reserve(nthreads);
      for (int t = 0; t < nthreads; ++t)
        pool.emplace_back(&MtSim::pool_worker, this, t);
    }
    std::unique_lock<std::mutex> lk(mu);
    phase = ph;
    cur_wslot = wslot;
    pending = nthreads;
    ++generation;
    cv_work.notify_all();
    cv_done.wait(lk, [&] { return pending == 0; });
  }

  ~MtSim() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stopping = true;
    }
    cv_work.notify_all();
    for (auto& th : pool) th.join();
  }

  // One B-tick window: threads process their own bucket (same-window
  // arrival order is thread-local push order -- the batched envelope),
  // stage sends, barrier, then ingest in parallel per owner.
  void run_window() {
    int64_t wslot = (now / B) % dw;
    run_phase(1, wslot);
    run_phase(2, wslot);
    now += B;
  }

  void gossip_window(double win) {
    int64_t steps = int64_t((win + double(B) - 1) / double(B));
    for (int64_t i = 0; i < steps; ++i) run_window();
  }

  bool exhausted() const {
    for (int t = 0; t < nthreads; ++t)
      for (const auto& b : buckets[t])
        if (!b.empty()) return false;
    return true;
  }
};

}  // namespace

extern "C" {

// Bump when the C ABI changes (slots in sim_stats etc.); cpp.py checks it
// so a stale prebuilt library cannot silently misreport new fields.
// v2: sim_stats gained out[6] = SIR removed count.
// v3: sim_stats takes n_slots (caller buffer length) and writes at most
//     min(n_slots, 7) entries, so future slot growth is skew-safe.
// v4: mt_* multithreaded SI baseline API added.
int32_t sim_abi_version() { return 4; }

void* sim_create(int64_t n, int32_t fanout, int32_t fanin, int32_t delaylow,
                 int32_t delayhigh, double droprate, double crashrate,
                 double removal_rate, double er_lambda, int32_t protocol,
                 int32_t graph, int32_t rounds_mode, int32_t compat,
                 int32_t seed) {
  Sim* s = new Sim();
  s->p = {n, fanout, fanin, delaylow, delayhigh, droprate, crashrate,
          removal_rate, er_lambda, protocol, graph, rounds_mode, compat, seed};
  s->init();
  return s;
}

void sim_destroy(void* h) { delete static_cast<Sim*>(h); }

void sim_overlay_window(void* h, double win, int64_t* mk, int64_t* bk,
                        int32_t* quiesced) {
  static_cast<Sim*>(h)->overlay_window(win, mk, bk, quiesced);
}

void sim_seed(void* h) { static_cast<Sim*>(h)->seed(); }

void sim_gossip_window(void* h, double win) {
  static_cast<Sim*>(h)->gossip_window(win);
}

void sim_stats(void* h, int64_t* out, int32_t n_slots) {
  // The caller passes its buffer length so ABI growth is safe in both
  // skew directions: an old caller's short buffer is never overrun, and a
  // new caller of an old library fails the version gate instead.
  Sim* s = static_cast<Sim*>(h);
  int64_t vals[7];
  vals[0] = s->total_received;
  vals[1] = s->total_message;
  vals[2] = s->total_crashed;
  vals[3] = s->makeups;
  vals[4] = s->breakups;
  vals[5] = s->exhausted ? 1 : 0;
  // SIR only: removed[] is provably all-zero otherwise and this scan is
  // inside the benchmarked polling path.
  int64_t rem = 0;
  if (s->p.protocol == SIR) {
    for (uint8_t r : s->removed) rem += r;
  }
  vals[6] = rem;
  int32_t k = n_slots < 7 ? n_slots : 7;
  for (int32_t i = 0; i < k; ++i) out[i] = vals[i];
}

double sim_now(void* h) { return static_cast<Sim*>(h)->now; }
double sim_phase_start(void* h) { return static_cast<Sim*>(h)->phase_start; }

void sim_degrees(void* h, int32_t* out) {
  Sim* s = static_cast<Sim*>(h);
  for (int64_t i = 0; i < s->p.n; ++i) out[i] = int32_t(s->friends[i].size());
}

// --- multithreaded SI baseline (MtSim) -------------------------------------

void* mt_create(int64_t n, int32_t fanout, int32_t delaylow, int32_t delayhigh,
                double droprate, double crashrate, int32_t seed,
                int32_t nthreads) {
  // The bucket wire packs (arrival_tick << 32 | uint32(node)): both the
  // node id and the arrival tick must fit 32/31 bits or the packing
  // silently corrupts (see stage_broadcast).  SI arrival ticks are
  // bounded by the run length (~hundreds of ms), far inside 2^31; the
  // node bound is enforced here at the API boundary.
  if (n <= 0 || n >= (int64_t(1) << 31)) return nullptr;
  MtSim* s = new MtSim();
  s->p = {n, fanout, fanout + 1, delaylow, delayhigh, droprate, crashrate,
          0.0,  0.0, SI, KOUT, 0, 0, seed};
  s->nthreads = nthreads < 1 ? 1 : nthreads;
  s->init();
  return s;
}

void mt_destroy(void* h) { delete static_cast<MtSim*>(h); }
void mt_seed(void* h) { static_cast<MtSim*>(h)->seed(); }
void mt_gossip_window(void* h, double win) {
  static_cast<MtSim*>(h)->gossip_window(win);
}
void mt_stats(void* h, int64_t* out, int32_t n_slots) {
  MtSim* s = static_cast<MtSim*>(h);
  int64_t vals[4] = {0, 0, 0, s->exhausted() ? 1 : 0};
  for (int t = 0; t < s->nthreads; ++t) {
    vals[0] += s->t_received[t];
    vals[1] += s->t_message[t];
    vals[2] += s->t_crashed[t];
  }
  int32_t k = n_slots < 4 ? n_slots : 4;
  for (int32_t i = 0; i < k; ++i) out[i] = vals[i];
}
double mt_now(void* h) { return double(static_cast<MtSim*>(h)->now); }
double mt_phase_start(void* h) {
  return double(static_cast<MtSim*>(h)->phase_start);
}

}  // extern "C"
