"""ctypes wrapper for the C++ event-driven backend (native_cpp/gossip_sim.cpp).

Same Stepper surface and semantics as backends/native.py, at native speed --
the CPU baseline standing in for the reference's Go loop in bench.py.
The shared library is built lazily with g++ on first use and cached next to
the source (pybind11 is not available in this image; the C API + ctypes
keeps the binding dependency-free).
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import warnings

from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS

from gossip_simulator_tpu.utils.metrics import Stats

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native_cpp")
_SRC = os.path.join(_DIR, "gossip_sim.cpp")
_LIB = os.path.join(_DIR, "libgossip_sim.so")

_PROTO = {"si": 0, "pushpull": 1, "sir": 2}
_GRAPH = {"overlay": 0, "kout": 1, "erdos": 2, "ring": 3}


def _build_lib() -> str:
    if (not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
        if os.path.exists(_LIB) and shutil.which("g++") is None:
            # A prebuilt library with a stale mtime (e.g. a fresh checkout
            # touching the source) is still usable when no toolchain exists
            # to rebuild it; warn rather than crash mid-run.
            warnings.warn(
                f"{_LIB} is older than {_SRC} and g++ is unavailable; "
                "using the stale prebuilt library", stacklevel=2)
            return _LIB
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
             _SRC, "-o", _LIB + ".tmp"],
            check=True, capture_output=True)
        os.replace(_LIB + ".tmp", _LIB)
    return _LIB


_lib = None


ABI_VERSION = 4  # must match sim_abi_version() in gossip_sim.cpp


def load_lib():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(_build_lib())
        try:
            got = lib.sim_abi_version()
        except AttributeError:
            got = 1
        if got != ABI_VERSION:
            # Reachable only via the stale-prebuilt-library fallback in
            # _build_lib (no g++ to rebuild); newer fields (e.g. the SIR
            # removed count in sim_stats[6]) would read as silent zeros.
            raise RuntimeError(
                f"{_LIB} implements C ABI v{got}, this build needs "
                f"v{ABI_VERSION}; rebuild it (g++ required) or remove the "
                "stale library")
        lib.sim_create.restype = ctypes.c_void_p
        lib.sim_create.argtypes = [
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_double, ctypes.c_double, ctypes.c_double,
            ctypes.c_double, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32]
        lib.sim_destroy.argtypes = [ctypes.c_void_p]
        lib.sim_overlay_window.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32)]
        lib.sim_seed.argtypes = [ctypes.c_void_p]
        lib.sim_gossip_window.argtypes = [ctypes.c_void_p, ctypes.c_double]
        # v3: the caller passes its buffer length, so ABI growth can never
        # overrun an older caller's buffer (nor an older library a newer's).
        lib.sim_stats.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int32]
        lib.sim_now.restype = ctypes.c_double
        lib.sim_now.argtypes = [ctypes.c_void_p]
        lib.sim_phase_start.restype = ctypes.c_double
        lib.sim_phase_start.argtypes = [ctypes.c_void_p]
        lib.mt_create.restype = ctypes.c_void_p
        lib.mt_create.argtypes = [
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_double, ctypes.c_double, ctypes.c_int32, ctypes.c_int32]
        lib.mt_destroy.argtypes = [ctypes.c_void_p]
        lib.mt_seed.argtypes = [ctypes.c_void_p]
        lib.mt_gossip_window.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.mt_stats.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.c_int32]
        lib.mt_now.restype = ctypes.c_double
        lib.mt_now.argtypes = [ctypes.c_void_p]
        lib.mt_phase_start.restype = ctypes.c_double
        lib.mt_phase_start.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class CppStepper(Stepper):
    name = "cpp"

    def init(self) -> None:
        cfg = self.cfg
        self._lib = load_lib()
        er_lambda = cfg.er_p_resolved * cfg.n
        self._h = self._lib.sim_create(
            cfg.n, cfg.fanout, cfg.fanin_resolved, cfg.delaylow, cfg.delayhigh,
            cfg.droprate, cfg.crashrate, cfg.removal_rate, er_lambda,
            _PROTO[cfg.protocol], _GRAPH[cfg.graph],
            1 if cfg.effective_time_mode == "rounds" else 0,
            1 if cfg.compat_reference else 0, cfg.seed)
        self._win = (WINDOW_MS if cfg.effective_time_mode == "ticks" else 1)
        self.exhausted = False

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.sim_destroy(h)
            self._h = None

    def overlay_window(self) -> tuple[int, int, bool]:
        mk = ctypes.c_int64()
        bk = ctypes.c_int64()
        q = ctypes.c_int32()
        self._lib.sim_overlay_window(self._h, float(self._win),
                                     ctypes.byref(mk), ctypes.byref(bk),
                                     ctypes.byref(q))
        return mk.value, bk.value, bool(q.value)

    def seed(self) -> None:
        self._lib.sim_seed(self._h)

    def gossip_window(self) -> Stats:
        self._lib.sim_gossip_window(self._h, float(self._win))
        st = self.stats()
        self.exhausted = self._exhausted
        return st

    def stats(self) -> Stats:
        buf = (ctypes.c_int64 * 7)()
        self._lib.sim_stats(self._h, buf, 7)
        self._exhausted = bool(buf[5]) and self.cfg.protocol != "pushpull"
        return Stats(
            n=self.cfg.n,
            round=int(self.sim_time_ms()),
            total_received=int(buf[0]), total_message=int(buf[1]),
            total_crashed=int(buf[2]), makeups=int(buf[3]),
            breakups=int(buf[4]), total_removed=int(buf[6]),
            exhausted=self._exhausted,
        )

    def sim_time_ms(self) -> float:
        return (self._lib.sim_now(self._h)
                - self._lib.sim_phase_start(self._h))


class CppMtStepper(Stepper):
    """Multithreaded C++ SI baseline (MtSim in gossip_sim.cpp): the
    whole-host native perf bar for bench.py's vs_cpp_mt (VERDICT r3
    stretch #8).  Windowed bulk-synchronous parallel DES -- same
    behavioral contract, batched same-window envelope (see the C++
    header comment); scope is the bench headline's exact shape: SI push,
    static kout graph, ticks mode."""

    name = "cpp_mt"

    def __init__(self, cfg, nthreads: int | None = None):
        super().__init__(cfg)
        self.nthreads = nthreads or (os.cpu_count() or 1)

    def init(self) -> None:
        cfg = self.cfg
        if (cfg.protocol != "si" or cfg.graph != "kout"
                or cfg.effective_time_mode != "ticks"):
            raise ValueError(
                "cpp_mt supports SI push on a kout graph in ticks mode "
                "(the bench headline shape) only")
        self._lib = load_lib()
        self._h = self._lib.mt_create(
            cfg.n, cfg.fanout, cfg.delaylow, cfg.delayhigh,
            cfg.droprate, cfg.crashrate, cfg.seed, self.nthreads)
        if not self._h:
            # mt_create range-checks n against its (tick << 32 | node)
            # bucket packing (advisor r4) and returns NULL past 2^31.
            raise ValueError(
                f"cpp_mt: n={cfg.n} outside the packed-wire range "
                "(n must be < 2^31)")
        self.exhausted = False

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.mt_destroy(h)
            self._h = None

    def overlay_window(self) -> tuple[int, int, bool]:
        return 0, 0, True  # static graph: phase 1 is a no-op

    def seed(self) -> None:
        self._lib.mt_seed(self._h)

    def gossip_window(self) -> Stats:
        self._lib.mt_gossip_window(self._h, float(WINDOW_MS))
        st = self.stats()
        self.exhausted = self._exhausted
        return st

    def stats(self) -> Stats:
        buf = (ctypes.c_int64 * 4)()
        self._lib.mt_stats(self._h, buf, 4)
        self._exhausted = bool(buf[3])
        return Stats(
            n=self.cfg.n, round=int(self.sim_time_ms()),
            total_received=int(buf[0]), total_message=int(buf[1]),
            total_crashed=int(buf[2]), exhausted=self._exhausted,
        )

    def sim_time_ms(self) -> float:
        return (self._lib.mt_now(self._h)
                - self._lib.mt_phase_start(self._h))
