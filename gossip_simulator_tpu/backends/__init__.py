"""Backend registry (the Stepper seam -- see base.py)."""

from __future__ import annotations

from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS
from gossip_simulator_tpu.config import Config


def make_stepper(cfg: Config) -> Stepper:
    """Factory: `-backend` flag -> Stepper implementation (lazy imports keep
    e.g. the native oracle importable without touching jax)."""
    if cfg.backend == "native":
        from gossip_simulator_tpu.backends.native import NativeStepper

        return NativeStepper(cfg)
    if cfg.backend == "cpp":
        from gossip_simulator_tpu.backends.cpp import CppStepper

        return CppStepper(cfg)
    if cfg.backend == "jax":
        from gossip_simulator_tpu.backends.jax_backend import JaxStepper

        return JaxStepper(cfg)
    if cfg.backend == "sharded":
        from gossip_simulator_tpu.backends.sharded import ShardedStepper

        return ShardedStepper(cfg)
    raise ValueError(f"unknown backend {cfg.backend!r}")


__all__ = ["Stepper", "make_stepper", "WINDOW_MS"]
