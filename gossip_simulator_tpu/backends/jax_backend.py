"""Single-device JAX/XLA backend -- the product path.

One device call per progress window (10 ticks or 1 round); counters stay
device-resident and come to the host once per window (the reference instead
polls global atomics every 10 ms of wall time, simulator.go:221-253).
`run_to_target` exposes the zero-host-sync while_loop path used by bench.py.

First call per config compiles (~seconds); all subsequent windows reuse the
executable.  The same model code runs on TPU and CPU unchanged.
"""

from __future__ import annotations

import jax
import numpy as np

from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS
from gossip_simulator_tpu.models import epidemic, event, graphs, overlay
from gossip_simulator_tpu.models.state import msg64_value
from gossip_simulator_tpu.utils import rng as _rng
from gossip_simulator_tpu.utils.metrics import Stats


class JaxStepper(Stepper):
    name = "jax"

    def init(self) -> None:
        cfg = self.cfg
        self.key = _rng.base_key(cfg.seed)
        if cfg.model == "pushsum":
            from gossip_simulator_tpu.models import pushsum

            self._engine = pushsum
        else:
            self._engine = event if cfg.engine_resolved == "event" \
                else epidemic
        self._mean_delay = (
            (cfg.delaylow + cfg.delayhigh) / 2.0
            if cfg.effective_time_mode == "ticks" else 1.0)
        self._overlay_rounds = 0
        self.exhausted = False
        if cfg.telemetry_enabled:
            from gossip_simulator_tpu.utils.telemetry import TelemetrySession

            self._telem = TelemetrySession(cfg)
        else:
            self._telem = None
        if cfg.resume:
            # State arrives via load_state_pytree; building a graph (or the
            # phase-1 overlay buffers) here would be thrown away -- minutes
            # and GBs at 1e8 nodes.
            self.state = None
            self._overlay_done = True
        elif cfg.graph == "overlay":
            self._setup_overlay(build_state=True)
        else:
            friends, cnt = graphs.generate(cfg, graphs.graph_key(cfg))
            self.state = self._engine.init_state(cfg, friends, cnt)
            self._overlay_done = True
        self._seed_fn = jax.jit(self._engine.make_seed_fn(cfg))
        self._window = 1 if cfg.effective_time_mode == "rounds" else WINDOW_MS
        self._window_fn = self._engine.make_window_fn(cfg, self._window)
        self._run_fn = self._engine.make_run_to_coverage_fn(
            cfg, telemetry=self._telem is not None)
        self._mailbox_dropped = 0

    # --- phase 1 ---------------------------------------------------------------
    def _setup_overlay(self, build_state: bool) -> None:
        """Overlay-engine machinery (round fn, module, optional initial
        state).  `build_state=False` is the phase-1 RESUME path: the
        restored snapshot replaces the initial state, so building the
        bootstrap burst here would be thrown away."""
        cfg = self.cfg
        self._faithful_overlay = cfg.overlay_mode_resolved == "ticks"
        self._osplit = False
        if self._faithful_overlay:
            from gossip_simulator_tpu.models import overlay_ticks

            self._omod = overlay_ticks
            self._oround = overlay_ticks.make_poll_fn(cfg)
            self.ostate = (overlay_ticks.init_state(cfg, self.key)
                           if build_state else None)
        else:
            self._omod = overlay
            self._osplit = overlay.use_split_round(cfg)
            if self._osplit:
                # Memory scale: one round as two jitted calls so donation
                # can alias dead buffers across the boundary (the fused
                # round held ~19.5 GB at n=1e8 -- overlay.make_split_
                # round_fn).  Host pays two dispatches per round; a round
                # is seconds of device work at this n.
                self._oround = overlay.make_split_round_fn(cfg)
            else:
                self._oround = jax.jit(overlay.make_round_fn(cfg))
            # base_key: the static-bootstrap band draws the initial
            # friends table + burst at init (overlay.init_state).
            self.ostate = (overlay.init_state(cfg, base_key=self.key)
                           if build_state else None)
        self._overlay_done = False
        self._orun = None  # lazy: compiled only on the fast path
        self.state = None

    def _quiesced_jit(self):
        """Jitted quiescence predicate: the eager form materializes the
        (cap, n) >= 0 emission masks (~1.7 GB at n=1e8) before reducing;
        fused, the reductions never allocate them."""
        if getattr(self, "_oq", None) is None:
            self._oq = jax.jit(self._omod.quiesced)
        return self._oq

    def _advance_overlay(self) -> None:
        """One overlay round.  In split mode the state is handed over in
        a popped box so no frame here retains the old state while the
        round's serialized calls run (overlay.make_split_round_fn's
        memory contract)."""
        if getattr(self, "_osplit", False):
            box = [self.ostate]
            self.ostate = None
            self.ostate = self._oround(box, self.key)
        else:
            self.ostate = self._oround(self.ostate, self.key)

    def overlay_window(self) -> tuple[int, int, bool]:
        if self._overlay_done:
            return 0, 0, True
        self._advance_overlay()
        self._overlay_rounds += 1
        faithful = self._faithful_overlay
        tick = self.ostate.tick if faithful else 0
        # Split rounds with the dead-row skip already computed quiescence
        # from the emission counts (overlay.make_split_round_fn); the
        # eager predicate reduces multi-GB masks at memory scale.
        q_fast = getattr(self._oround, "last_quiesced", None)
        mk, bk, tick = jax.device_get(
            (self.ostate.win_makeups, self.ostate.win_breakups, tick))
        q = (q_fast if q_fast is not None
             else jax.device_get(self._quiesced_jit()(self.ostate)))
        # True simulated ms from the tick clock in faithful mode; the
        # rounds engine only estimates rounds x mean_delay.
        self._phase1_ms = (float(tick) if faithful
                           else self._overlay_rounds * self._mean_delay)
        if bool(q):
            self._finish_overlay()
        return int(mk), int(bk), bool(q)

    def overlay_run_to_quiescence(self, max_windows: int,
                                  budget: int | None = None
                                  ) -> tuple[int, bool]:
        """Phase-1 fast path: bounded device-side while_loop to quiescence
        (the overlay analog of run_to_target) -- one host sync per bounded
        call instead of one jit dispatch + device_get per window, which
        profiled at ~2.4x the device time through the TPU tunnel.
        Trajectory-identical to the windowed loop (window-indexed keys,
        same quiescence predicate); only for runs with nothing observing
        per-window state (driver gates on printer.observing).  Returns
        (windows_run, quiesced)."""
        if self._overlay_done:
            return 0, True
        import time

        telem = self._telem
        if getattr(self, "_osplit", False):
            # Split-round mode (memory scale): the bounded device-side
            # while_loop would re-fuse the round into one program and
            # re-create the OOM; run the host loop instead -- a round is
            # seconds of device work at this n, so the per-round
            # dispatch + quiescence sync is noise.  Telemetry records
            # host-side here, riding the per-round device_get the split
            # already pays.
            from gossip_simulator_tpu.utils import trace as _trace

            oq = self._quiesced_jit()
            q = False
            while self._overlay_rounds < max_windows:
                t0 = time.perf_counter()
                with _trace.span("phase1.split_round", cat="device"):
                    self._advance_overlay()
                self._overlay_rounds += 1
                self._phase1_ms = self._overlay_rounds * self._mean_delay
                # Round 7: with the dead-row skip on, the split round
                # computes quiescence from the emission counts INSIDE the
                # jitted b2 call (one scalar) -- the eager quiesced()
                # otherwise reduces the (cap, n) emission masks every
                # round (~6.4 GB of reads at n=1e8).
                q_fast = getattr(self._oround, "last_quiesced", None)
                if telem is not None:
                    st = self.ostate
                    mk, bk, dr = jax.device_get(
                        (st.win_makeups, st.win_breakups,
                         st.mailbox_dropped))
                    telem.overlay_host_row(
                        [self._overlay_rounds, int(mk), int(bk), int(dr)])
                    telem.tally_overlay_call(time.perf_counter() - t0)
                    q = (bool(q_fast) if q_fast is not None
                         else bool(jax.device_get(oq(self.ostate))))
                else:
                    q = (bool(q_fast) if q_fast is not None
                         else bool(jax.device_get(oq(self.ostate))))
                if q:
                    break
            if q:
                self._finish_overlay()
            return self._overlay_rounds, q
        if self._orun is None:
            self._orun = self._omod.make_run_fn(
                self.cfg, telemetry=telem is not None)
        if budget is None:
            # Watchdog-bounded windows per device call; the calibration
            # lives with each overlay module's cost model.
            budget = self._omod.run_call_budget(self.cfg)
        from gossip_simulator_tpu.utils import trace as _trace

        hist = telem.begin_overlay(max_windows) if telem is not None else None
        q = False
        calls = 0
        while True:
            lim = min(budget, max_windows - self._overlay_rounds)
            if lim <= 0:
                break
            t0 = time.perf_counter()
            with _trace.span("phase1.compile+run" if calls == 0
                             else "phase1.bounded_call",
                             cat="device") as sp:
                if hist is not None:
                    self.ostate, polls, q, hist = self._orun(
                        self.ostate, self.key, np.int32(lim), hist)
                else:
                    self.ostate, polls, q = self._orun(
                        self.ostate, self.key, np.int32(lim))
                faithful = self._faithful_overlay
                tick = self.ostate.tick if faithful else 0
                polls, q, tick = jax.device_get((polls, q, tick))
                if sp is not None:
                    sp.update(windows=int(polls))
            calls += 1
            if telem is not None:
                telem.tally_overlay_call(time.perf_counter() - t0)
            self._overlay_rounds += int(polls)
            self._phase1_ms = (float(tick) if faithful
                               else self._overlay_rounds * self._mean_delay)
            if bool(q):
                break
        if hist is not None:
            telem.end_overlay(hist)
        if bool(q):
            self._finish_overlay()
        return self._overlay_rounds, bool(q)

    def _finish_overlay(self) -> None:
        self._overlay_done = True
        # Freeze phase-1 elapsed time: once the epidemic state exists,
        # sim_time_ms switches to its tick (which starts at 0), so the
        # driver's "Took Xms to stabilize" needs this snapshot.
        self._stabilize_ms = self._phase1_ms
        self._mailbox_dropped = int(jax.device_get(
            self.ostate.mailbox_dropped))
        self.state = self._engine.init_state(
            self.cfg, self.ostate.friends, self.ostate.friend_cnt)
        self.ostate = None  # free phase-1 buffers

    # --- phase 2 ---------------------------------------------------------------
    def seed(self) -> None:
        self._seeded = True
        self.state = self._seed_fn(self.state, self.key)

    def gossip_window(self) -> Stats:
        self.state = self._window_fn(self.state, self.key)
        stats, in_flight = self._stats_and_inflight()
        # Heal-on runs never report exhaustion mid-run: a pending dead-
        # friend detection can re-send from an infected healer and revive
        # an empty ring (see base.run_bounded_to_target).
        self.exhausted = (in_flight == 0
                          and self.cfg.protocol != "pushpull"
                          and not self.cfg.overlay_heal_resolved)
        stats.exhausted = self.exhausted
        return stats

    def reset_state(self) -> None:
        """Rebuild phase-2 state from scratch (same seed => same trajectory)
        without re-tracing the jitted step functions.  Needed after a run:
        the hot fns donate their input buffers, so the old state is gone."""
        cfg = self.cfg
        if cfg.graph == "overlay":
            raise ValueError("reset_state requires a static graph")
        # Free the old state FIRST: regenerating while the previous
        # friends table + mail ring are still referenced doubles the HBM
        # footprint (~12 GB transient at 1e8 x fanout 6 -- enough to crash
        # a 16 GB v5e worker, observed r2).
        self.state = None
        friends, cnt = graphs.generate(cfg, graphs.graph_key(cfg))
        self.state = self._engine.init_state(cfg, friends, cnt)
        self.exhausted = False
        if self._telem is not None:
            self._telem.reset_gossip()

    def run_to_target(self) -> Stats:
        """Bench fast path: bounded device-side while_loop toward the
        coverage target (base.run_bounded_to_target)."""
        from gossip_simulator_tpu.backends.base import run_bounded_to_target

        return run_bounded_to_target(self)

    @property
    def overlay_clock_scale(self) -> float:
        """Simulated-ms per recorded overlay clock unit: the tick-faithful
        engine records true ticks; the rounds engine records round counts
        estimated at mean_delay ms each (the windowed loop's clock)."""
        if getattr(self, "_faithful_overlay", False):
            return 1.0
        return getattr(self, "_mean_delay", 1.0)

    def _stats_and_inflight(self) -> tuple[Stats, int]:
        """All progress-window scalars in ONE host round-trip (each
        device_get is a synchronous hop through the TPU tunnel)."""
        st = self.state
        extra = st.mail_dropped if hasattr(st, "mail_dropped") else 0
        rem = (event.removed_count(st)
               if self.cfg.protocol == "sir" else 0)
        R = self.cfg.rumors
        multi = self.cfg.multi_rumor
        rmin = st.rumor_recv[:R].min() if multi else -1
        rdone = (st.rumor_done[:R] >= 0).sum() if multi else 0
        (tm, tr, tc, trm, tick, dropped, in_flight, sc, sr, pd,
         hr, rmin, rdone) = jax.device_get(
            (st.total_message, st.total_received, st.total_crashed,
             rem, st.tick, extra, event.in_flight(st),
             st.scen_crashed, st.scen_recovered, st.part_dropped,
             st.heal_repaired, rmin, rdone))
        return Stats(
            n=self.cfg.n, round=int(tick),
            total_received=int(tr), total_message=msg64_value(tm),
            total_crashed=int(tc), total_removed=int(trm),
            mailbox_dropped=self._mailbox_dropped + int(dropped),
            scen_crashed=int(sc), scen_recovered=int(sr),
            part_dropped=int(pd), heal_repaired=int(hr),
            exhausted=self.exhausted,
            rumors=R, rumor_min_recv=int(rmin), rumors_done=int(rdone),
        ), int(in_flight)

    def stats(self) -> Stats:
        return self._stats_and_inflight()[0]

    def sim_time_ms(self) -> float:
        if self.state is None or not self._overlay_done:
            return getattr(self, "_phase1_ms",
                           self._overlay_rounds * self._mean_delay)
        if not getattr(self, "_seeded", False):
            # Between quiescence and the broadcast: phase-1 elapsed time
            # (the epidemic tick is 0 and would misreport stabilization).
            return getattr(self, "_stabilize_ms", 0.0)
        return float(jax.device_get(self.state.tick))

    # --- checkpoint ------------------------------------------------------------
    def overlay_state_pytree(self):
        """Mid-construction phase-1 snapshot (None once the overlay is
        done -- phase-2 snapshots take over then)."""
        if self._overlay_done or self.ostate is None:
            return None
        # np.array (COPY), not np.asarray: on the CPU platform asarray of
        # a device buffer is zero-copy, and the donating round fns reuse
        # that buffer on the next call -- the "snapshot" would silently
        # track the live state (observed as resumed-trajectory drift in
        # the checkpoint tests; on TPU the device->host transfer always
        # copied, which is why hardware never showed it).
        return {k: np.array(v) for k, v in self.ostate._asdict().items()}

    def load_overlay_state_pytree(self, tree, windows: int = 0) -> None:
        """Resume INTO phase 1: validate the overlay snapshot
        (utils/checkpoint.prepare_overlay_restore_tree), rebuild the
        engine machinery without the bootstrap burst, and continue
        construction from the restored state.  `windows` is the snapshot's
        overlay-window count (drives the rounds engine's estimated
        clock; the ticks engine's clock rides the restored tick)."""
        from gossip_simulator_tpu.utils.checkpoint import \
            prepare_overlay_restore_tree

        cfg = self.cfg
        tree = prepare_overlay_restore_tree(tree, cfg, n_shards=1)
        self._setup_overlay(build_state=False)
        cls = (self._omod.OverlayTickState if self._faithful_overlay
               else self._omod.OverlayState)
        # jax.numpy.array (device COPY), not asarray: a zero-copy restore
        # feeding the donating round fns lets XLA reuse a buffer it does
        # not own (see load_state_pytree's note).
        self.ostate = cls(**{k: jax.numpy.array(v)
                             for k, v in tree.items()})
        self._overlay_rounds = int(windows)
        self._phase1_ms = (
            float(np.asarray(tree["tick"])) if self._faithful_overlay
            else self._overlay_rounds * self._mean_delay)

    def state_pytree(self):
        if self.state is None:
            return None
        # COPY (np.array), never view: see overlay_state_pytree's note on
        # the CPU zero-copy + donated-buffer-reuse aliasing.
        tree = {k: np.array(v) for k, v in self.state._asdict().items()}
        if "mail_ids" in tree:
            # Record the mail-ring geometry so a future build whose AUTO
            # slot-cap/chunk sizing differs can repack instead of rejecting
            # the snapshot (see load_state_pytree).  Pushsum sizes its
            # slots for emission volume, so its own module is the
            # geometry authority there.
            cfg, n = self.cfg, self.cfg.n
            if cfg.model == "pushsum":
                from gossip_simulator_tpu.models import pushsum as geo
            else:
                geo = event
            tree["mail_geom"] = np.asarray(
                [geo.slot_cap(cfg, n), geo.drain_chunk(cfg, n)],
                dtype=np.int64)
        # Phase-1 overlay drops live host-side, not in the device state --
        # persist them or a resumed run under-reports mailbox_dropped.
        tree["host_mailbox_dropped"] = np.int64(self._mailbox_dropped)
        return tree

    def load_state_pytree(self, tree) -> None:
        """Restore a snapshot (validation, legacy coercion and mail-ring
        geometry repack shared with the sharded backend:
        utils/checkpoint.prepare_restore_tree)."""
        from gossip_simulator_tpu.models.event import EventState
        from gossip_simulator_tpu.models.state import SimState
        from gossip_simulator_tpu.utils.checkpoint import prepare_restore_tree

        cfg = self.cfg
        tree = prepare_restore_tree(tree, cfg, n_shards=1)
        self._mailbox_dropped = int(tree.pop("host_mailbox_dropped", 0))
        if cfg.model == "pushsum":
            from gossip_simulator_tpu.models.pushsum import PushSumState

            cls = PushSumState
        else:
            cls = EventState if cfg.engine_resolved == "event" else SimState
        # jax.numpy.array (device COPY), not asarray: on the CPU platform
        # asarray of a host array can be zero-copy, and these leaves feed
        # straight into DONATING jitted fns -- XLA then reuses a buffer it
        # does not own, corrupting the restored state (the load-side twin
        # of state_pytree's copy note; TPU transfers always copy).
        self.state = cls(**{k: jax.numpy.array(v)
                            for k, v in tree.items()})
        self._overlay_done = True
        self._seeded = True  # snapshots are taken mid-phase-2
