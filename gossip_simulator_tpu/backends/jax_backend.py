"""Single-device JAX/XLA backend -- the product path.

One device call per progress window (10 ticks or 1 round); counters stay
device-resident and come to the host once per window (the reference instead
polls global atomics every 10 ms of wall time, simulator.go:221-253).
`run_to_target` exposes the zero-host-sync while_loop path used by bench.py.

First call per config compiles (~seconds); all subsequent windows reuse the
executable.  The same model code runs on TPU and CPU unchanged.
"""

from __future__ import annotations

import jax
import numpy as np

from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS
from gossip_simulator_tpu.models import epidemic, event, graphs, overlay
from gossip_simulator_tpu.models.state import msg64_value
from gossip_simulator_tpu.utils import rng as _rng
from gossip_simulator_tpu.utils.metrics import Stats


class JaxStepper(Stepper):
    name = "jax"

    def init(self) -> None:
        cfg = self.cfg
        self.key = _rng.base_key(cfg.seed)
        self._engine = event if cfg.engine_resolved == "event" else epidemic
        self._mean_delay = (
            (cfg.delaylow + cfg.delayhigh) / 2.0
            if cfg.effective_time_mode == "ticks" else 1.0)
        self._overlay_rounds = 0
        self.exhausted = False
        if cfg.resume:
            # State arrives via load_state_pytree; building a graph (or the
            # phase-1 overlay buffers) here would be thrown away -- minutes
            # and GBs at 1e8 nodes.
            self.state = None
            self._overlay_done = True
        elif cfg.graph == "overlay":
            self._oround = jax.jit(overlay.make_round_fn(cfg))
            self.ostate = overlay.init_state(cfg)
            self._overlay_done = False
            self.state = None
        else:
            friends, cnt = graphs.generate(cfg, graphs.graph_key(cfg))
            self.state = self._engine.init_state(cfg, friends, cnt)
            self._overlay_done = True
        self._seed_fn = jax.jit(self._engine.make_seed_fn(cfg))
        self._window = 1 if cfg.effective_time_mode == "rounds" else WINDOW_MS
        self._window_fn = self._engine.make_window_fn(cfg, self._window)
        self._run_fn = self._engine.make_run_to_coverage_fn(cfg)
        self._mailbox_dropped = 0

    # --- phase 1 ---------------------------------------------------------------
    def overlay_window(self) -> tuple[int, int, bool]:
        if self._overlay_done:
            return 0, 0, True
        self.ostate = self._oround(self.ostate, self.key)
        self._overlay_rounds += 1
        mk, bk, q = jax.device_get(
            (self.ostate.win_makeups, self.ostate.win_breakups,
             overlay.quiesced(self.ostate)))
        if bool(q):
            self._overlay_done = True
            # Freeze phase-1 elapsed time: once the epidemic state exists,
            # sim_time_ms switches to its tick (which starts at 0), so the
            # driver's "Took Xms to stabilize" needs this snapshot.
            self._stabilize_ms = self._overlay_rounds * self._mean_delay
            self._mailbox_dropped = int(jax.device_get(
                self.ostate.mailbox_dropped))
            self.state = self._engine.init_state(
                self.cfg, self.ostate.friends, self.ostate.friend_cnt)
            self.ostate = None  # free phase-1 buffers
        return int(mk), int(bk), bool(q)

    # --- phase 2 ---------------------------------------------------------------
    def seed(self) -> None:
        self._seeded = True
        self.state = self._seed_fn(self.state, self.key)

    def gossip_window(self) -> Stats:
        self.state = self._window_fn(self.state, self.key)
        stats, in_flight = self._stats_and_inflight()
        self.exhausted = in_flight == 0 and self.cfg.protocol != "pushpull"
        return stats

    def reset_state(self) -> None:
        """Rebuild phase-2 state from scratch (same seed => same trajectory)
        without re-tracing the jitted step functions.  Needed after a run:
        the hot fns donate their input buffers, so the old state is gone."""
        cfg = self.cfg
        if cfg.graph == "overlay":
            raise ValueError("reset_state requires a static graph")
        friends, cnt = graphs.generate(cfg, graphs.graph_key(cfg))
        self.state = self._engine.init_state(cfg, friends, cnt)
        self.exhausted = False

    def run_to_target(self) -> Stats:
        """Bench fast path: bounded device-side while_loop toward the
        coverage target (base.run_bounded_to_target)."""
        from gossip_simulator_tpu.backends.base import run_bounded_to_target

        return run_bounded_to_target(self)

    def _stats_and_inflight(self) -> tuple[Stats, int]:
        """All progress-window scalars in ONE host round-trip (each
        device_get is a synchronous hop through the TPU tunnel)."""
        st = self.state
        extra = st.mail_dropped if hasattr(st, "mail_dropped") else 0
        rem = (event.removed_count(st)
               if self.cfg.protocol == "sir" else 0)
        tm, tr, tc, trm, tick, dropped, in_flight = jax.device_get(
            (st.total_message, st.total_received, st.total_crashed,
             rem, st.tick, extra, event.in_flight(st)))
        return Stats(
            n=self.cfg.n, round=int(tick),
            total_received=int(tr), total_message=msg64_value(tm),
            total_crashed=int(tc), total_removed=int(trm),
            mailbox_dropped=self._mailbox_dropped + int(dropped),
        ), int(in_flight)

    def stats(self) -> Stats:
        return self._stats_and_inflight()[0]

    def sim_time_ms(self) -> float:
        if self.state is None or not self._overlay_done:
            return self._overlay_rounds * self._mean_delay
        if not getattr(self, "_seeded", False):
            # Between quiescence and the broadcast: phase-1 elapsed time
            # (the epidemic tick is 0 and would misreport stabilization).
            return getattr(self, "_stabilize_ms", 0.0)
        return float(jax.device_get(self.state.tick))

    # --- checkpoint ------------------------------------------------------------
    def state_pytree(self):
        if self.state is None:
            return None
        tree = {k: np.asarray(v) for k, v in self.state._asdict().items()}
        if "mail_ids" in tree:
            # Record the mail-ring geometry so a future build whose AUTO
            # slot-cap/chunk sizing differs can repack instead of rejecting
            # the snapshot (see load_state_pytree).
            cfg, n = self.cfg, self.cfg.n
            tree["mail_geom"] = np.asarray(
                [event.slot_cap(cfg, n), event.drain_chunk(cfg, n)],
                dtype=np.int64)
        return tree

    def load_state_pytree(self, tree) -> None:
        from gossip_simulator_tpu.models.event import EventState
        from gossip_simulator_tpu.models.state import SimState

        cfg = self.cfg
        ckpt_engine = "event" if "mail_ids" in tree else "ring"
        if ckpt_engine != cfg.engine_resolved:
            raise ValueError(
                f"checkpoint was written by the {ckpt_engine} engine but "
                f"this run resolves to {cfg.engine_resolved}; pass "
                f"-engine {ckpt_engine} to restore it")
        if ckpt_engine == "event" and "received" in tree:
            # Pre-packed-flags event snapshot: fold the two bool arrays into
            # the uint8 flags layout (bit0 received, bit1 crashed).
            tree = dict(tree)
            tree["flags"] = (
                tree.pop("received").astype(np.uint8)
                + tree.pop("crashed").astype(np.uint8) * 2)
        # Geometry check: ring layouts are decoded from cfg-derived constants
        # (cap, dw, delay depth), so a snapshot written under different
        # -n/-delayhigh/-event-* flags would silently mis-index.
        n = int(tree["flags" if ckpt_engine == "event"
                     else "received"].shape[0])
        if n != cfg.n:
            raise ValueError(
                f"checkpoint has n={n} but this run has n={cfg.n}")
        if ckpt_engine == "event":
            dw = event.ring_windows(cfg)
            ncap = event.slot_cap(cfg, n)
            nchunk = event.drain_chunk(cfg, n)
            want_mail = (dw * ncap + nchunk,)
            tree = dict(tree)
            geom = tree.pop("mail_geom", None)
            if tuple(tree["mail_cnt"].shape) != (1, dw):
                raise ValueError(
                    "checkpoint window-ring depth "
                    f"{tuple(tree['mail_cnt'].shape)} does not match this "
                    f"config's (1, {dw}); restore with the snapshot's "
                    "-delaylow/-delayhigh")
            # Compare the STORED geometry, not just array length: distinct
            # (cap, chunk) pairs can have equal dw*cap+chunk totals, which
            # would mis-index every slot base if accepted as-is.
            drifted = ((int(geom[0]), int(geom[1])) != (ncap, nchunk)
                       if geom is not None
                       else tuple(tree["mail_ids"].shape) != want_mail)
            if drifted:
                # Geometry drifted (different -event-* flags, or a build
                # whose auto sizing changed).  Repack slot-by-slot using the
                # stored geometry; legacy snapshots without mail_geom can't
                # be repacked safely, so keep the strict error there.
                if geom is None:
                    raise ValueError(
                        "checkpoint mail-ring geometry "
                        f"{tuple(tree['mail_ids'].shape)} does not match "
                        f"this config's {want_mail} and the snapshot "
                        "predates geometry metadata; restore with the same "
                        "-delaylow/-delayhigh/-event-slot-cap/-event-chunk "
                        "it was written with")
                ocap, ochunk = int(geom[0]), int(geom[1])
                if tree["mail_ids"].shape[0] != dw * ocap + ochunk:
                    raise ValueError(
                        f"checkpoint mail_ids length "
                        f"{tree['mail_ids'].shape[0]} contradicts its "
                        f"stored geometry (cap={ocap}, chunk={ochunk})")
                old = np.asarray(tree["mail_ids"])
                cnt = np.asarray(tree["mail_cnt"])[0]
                new = np.zeros(want_mail, old.dtype)
                lost = 0
                for s in range(dw):
                    take = min(int(cnt[s]), ncap)
                    lost += int(cnt[s]) - take
                    new[s * ncap:s * ncap + take] = \
                        old[s * ocap:s * ocap + take]
                tree["mail_ids"] = new
                tree["mail_cnt"] = np.minimum(
                    np.asarray(tree["mail_cnt"]), ncap)
                tree["mail_dropped"] = np.asarray(
                    tree["mail_dropped"]) + np.int32(lost)
            elif tuple(tree["mail_ids"].shape) != want_mail:
                # Geometry matches the config but the array itself is
                # truncated/corrupt: fail here with a clear error instead of
                # letting the drain's dynamic_slice mis-index at runtime.
                raise ValueError(
                    f"checkpoint mail_ids length "
                    f"{tree['mail_ids'].shape[0]} contradicts its geometry "
                    f"(cap={ncap}, chunk={nchunk} => {want_mail[0]}); the "
                    "snapshot is truncated or corrupt")
        else:
            d = epidemic.ring_depth(cfg)
            if tuple(tree["pending"].shape) != (d, n):
                raise ValueError(
                    f"checkpoint delay ring {tuple(tree['pending'].shape)} "
                    f"does not match this config's ({d}, {n}); restore with "
                    "the snapshot's -delaylow/-delayhigh/-time-mode")
        tm = np.asarray(tree["total_message"])
        if tm.ndim == 0:
            # Pre-widening snapshot: scalar int32 counter -> [hi, lo] pair.
            # & 0xFFFFFFFF also recovers a counter that had already wrapped
            # negative (one int32 wrap reinterprets to the correct low word).
            tree = dict(tree)
            tree["total_message"] = np.asarray(
                [0, int(tm) & 0xFFFFFFFF], dtype=np.uint32)
        cls = EventState if ckpt_engine == "event" else SimState
        self.state = cls(**{k: jax.numpy.asarray(v)
                            for k, v in tree.items()})
        self._overlay_done = True
        self._seeded = True  # snapshots are taken mid-phase-2
