"""Event-driven oracle backend (pure Python/NumPy, small N).

This is the framework's ground truth: a discrete-event reimplementation of the
reference's behavioral contract in *simulated* time.  Where the reference
interleaves goroutines sleeping real wall-clock delays (simulator.go:140-168),
this backend processes a time-ordered event heap -- same protocol decisions,
same distributions, but deterministic, seedable, and free of the Go
scheduler's overhead and races.

Protocol fidelity notes (all against /root/reference/simulator.go):
* makeup handling  -- accept under fanin else evict uniform-random victim and
  send it a breakup (simulator.go:66-75).
* breakup handling -- first-match scan; over fanout -> plain remove, else
  in-place replace with a fresh random peer (!= self, != leaver) plus a makeup
  (simulator.go:76-94, 127-138).
* bootstrap        -- one friend per needNewFriend event, self-collision
  patched as (id+1)%N, duplicate edges allowed, immediate re-arm
  (simulator.go:95-106).
* receive path     -- crashed black-hole (uncounted), count, crash draw,
  duplicate drop, infect + re-broadcast with ONE shared delay for all fanout
  sends (simulator.go:107-123, 140-149).
* crashed nodes keep processing membership traffic; only data messages are
  black-holed (the crashed check exists only in the recvMsgCh case,
  simulator.go:108-110).

Documented divergences (config-gated where meaningful, see config.py):
* Quiescence is race-free: stabilization requires an idle window AND an empty
  membership event queue, fixing the reference's read-reset race (§5.2 of
  SURVEY.md) in which in-flight delayed makeups could be missed.
* Unless ``compat_reference``, the seed node is itself marked received
  (the reference never marks it, simulator.go:240-241) and Bernoulli draws
  use exact float probabilities rather than 1% truncation.
"""

from __future__ import annotations

import heapq
from typing import List

import numpy as np

from gossip_simulator_tpu.backends.base import Stepper, WINDOW_MS

from gossip_simulator_tpu.utils.metrics import Stats

# Event kinds.
BOOT, MAKEUP, BREAKUP, MSG, REBROADCAST = 0, 1, 2, 3, 4
_MEMBERSHIP = (BOOT, MAKEUP, BREAKUP)


class NativeStepper(Stepper):
    name = "native"

    def init(self) -> None:
        cfg = self.cfg
        self.rng = np.random.default_rng(cfg.seed)
        self.n = cfg.n
        self.friends: List[List[int]] = [[] for _ in range(self.n)]
        self.received = np.zeros(self.n, dtype=bool)
        self.crashed = np.zeros(self.n, dtype=bool)
        self.heap: list = []
        self._seq = 0
        self._pending_membership = 0
        self.now = 0.0
        self.phase_start = 0.0
        self.total_message = 0
        self.total_received = 0
        self.total_crashed = 0
        self.makeups = 0
        self.breakups = 0
        self._win_makeups = 0
        self._win_breakups = 0
        self.exhausted = False
        # SIR state: a removed node stops forwarding but stays "received".
        self.removed = np.zeros(self.n, dtype=bool)

        if cfg.graph == "overlay":
            for i in range(self.n):
                self._push(0.0, BOOT, i, -1)
            self._overlay_done = False
        else:
            self._generate_static_graph()
            self._overlay_done = True

    # --- static graphs ---------------------------------------------------------
    def _generate_static_graph(self) -> None:
        cfg, rng, n = self.cfg, self.rng, self.n
        if cfg.graph == "kout":
            # k-out random digraph: each node picks `fanout` uniform peers
            # (duplicates allowed, self patched away like simulator.go:98-100).
            for i in range(n):
                picks = rng.integers(0, n, size=cfg.fanout)
                self.friends[i] = [int((p + 1) % n) if p == i else int(p) for p in picks]
        elif cfg.graph == "erdos":
            # Sparse directed ER approximation: out-degree ~ Poisson(n*p).
            lam = cfg.er_p_resolved * n
            degs = rng.poisson(lam, size=n)
            for i in range(n):
                picks = rng.integers(0, n, size=int(degs[i]))
                self.friends[i] = [int((p + 1) % n) if p == i else int(p) for p in picks]
        elif cfg.graph == "ring":
            for i in range(n):
                self.friends[i] = [(i + j + 1) % n for j in range(cfg.fanout)]
        else:  # pragma: no cover
            raise ValueError(cfg.graph)

    # --- event plumbing --------------------------------------------------------
    def _push(self, t: float, kind: int, dst: int, src: int) -> None:
        self._seq += 1
        if kind in _MEMBERSHIP:
            self._pending_membership += 1
        heapq.heappush(self.heap, (t, self._seq, kind, dst, src))

    def _delay(self) -> float:
        if self.cfg.effective_time_mode == "rounds":
            return 1.0
        d = int(self.rng.integers(self.cfg.delaylow, self.cfg.delayhigh))
        return float(max(d, 1))

    def _bern(self, p: float) -> bool:
        if self.cfg.compat_reference:
            p = int(p * 100) / 100.0  # simulator.go:172,180 truncation
        return bool(self.rng.random() < p)

    def _rand_peer_excluding(self, *exclude: int) -> int:
        while True:
            r = int(self.rng.integers(0, self.n))
            if r not in exclude:
                return r

    # --- protocol handlers -----------------------------------------------------
    def _handle(self, t: float, kind: int, dst: int, src: int) -> None:
        if kind in _MEMBERSHIP:
            self._pending_membership -= 1
        f = self.friends[dst]
        if kind == BOOT:
            if len(f) < self.cfg.fanout:
                nf = int(self.rng.integers(0, self.n))
                if nf == dst:
                    nf = (nf + 1) % self.n
                f.append(nf)
                self._push(t + self._delay(), MAKEUP, nf, dst)
                if len(f) < self.cfg.fanout:
                    self._push(t, BOOT, dst, -1)
        elif kind == MAKEUP:
            self.makeups += 1
            self._win_makeups += 1
            if len(f) < self.cfg.fanin_resolved:
                f.append(src)
            else:
                victim_pos = int(self.rng.integers(0, len(f)))
                self._push(t + self._delay(), BREAKUP, f[victim_pos], dst)
                f[victim_pos] = src
        elif kind == BREAKUP:
            self.breakups += 1
            self._win_breakups += 1
            for i, fid in enumerate(f):
                if fid == src:
                    if len(f) > self.cfg.fanout:
                        del f[i]
                    else:
                        nf = self._rand_peer_excluding(src, dst)
                        f[i] = nf
                        self._push(t + self._delay(), MAKEUP, nf, dst)
                    break
        elif kind == MSG:
            self._receive(t, dst)
        elif kind == REBROADCAST:
            # SIR: an infected node keeps spreading every delay interval until
            # its per-broadcast removal draw fires (no referent in the
            # reference; BASELINE.json config 4's added capability).
            if not self.crashed[dst] and not self.removed[dst]:
                self._broadcast(t, dst)

    def _receive(self, t: float, dst: int) -> None:
        cfg = self.cfg
        if self.crashed[dst]:
            return  # black-hole, uncounted (simulator.go:108-110)
        self.total_message += 1
        if self._bern(cfg.crashrate):
            self.crashed[dst] = True
            self.total_crashed += 1
            return
        if self.received[dst]:
            return  # duplicate (simulator.go:117-119)
        self.received[dst] = True
        self.total_received += 1
        self._broadcast(t, dst)

    def _broadcast(self, t: float, node: int) -> None:
        """One shared delay for the whole fan-out; per-link drop draw
        (simulator.go:140-149)."""
        d = self._delay()
        for fid in self.friends[node]:
            if not self._bern(self.cfg.droprate):
                self._push(t + d, MSG, fid, node)
        if self.cfg.protocol == "sir":
            if self._bern(self.cfg.removal_rate):
                self.removed[node] = True
            else:
                self._push(t + d, REBROADCAST, node, node)

    # --- Stepper API -----------------------------------------------------------
    def overlay_window(self) -> tuple[int, int, bool]:
        if self._overlay_done:
            return 0, 0, True
        win = WINDOW_MS if self.cfg.effective_time_mode == "ticks" else 1
        self._win_makeups = self._win_breakups = 0
        end = self.now + win
        self._drain(end)
        self.now = end
        quiesced = (
            self._win_makeups == 0
            and self._win_breakups == 0
            and self._pending_membership == 0
        )
        if quiesced:
            self._overlay_done = True
        return self._win_makeups, self._win_breakups, quiesced

    def seed(self) -> None:
        self.phase_start = self.now
        sender = int(self.rng.integers(0, self.n))
        self.seed_node = sender
        if self.cfg.protocol == "pushpull":
            # Anti-entropy needs an infected seed; the broadcast machinery is
            # unused (peers are sampled fresh each round).
            self.received[sender] = True
            self.total_received += 1
            return
        if not self.cfg.compat_reference:
            self.received[sender] = True
            self.total_received += 1
        self._broadcast(self.now, sender)

    def gossip_window(self) -> Stats:
        if self.cfg.protocol == "pushpull":
            self._pushpull_round()
            self.now += 1
            return self.stats()
        win = WINDOW_MS if self.cfg.effective_time_mode == "ticks" else 1
        end = self.now + win
        self._drain(end)
        self.now = end
        self.exhausted = not self.heap
        return self.stats()

    def _drain(self, end: float) -> None:
        heap = self.heap
        while heap and heap[0][0] < end:
            t, _, kind, dst, src = heapq.heappop(heap)
            self._handle(t, kind, dst, src)

    def _pushpull_round(self) -> None:
        """One synchronous push-pull anti-entropy round: every live node
        contacts `fanout` uniform random peers; infection crosses each
        surviving contact in both directions.  (No referent in the reference --
        BASELINE.json config 3's added capability.)  Per-contact drop draw;
        crash draw on push receptions only."""
        cfg, rng = self.cfg, self.rng
        live = ~self.crashed
        inf = self.received & live
        sus = ~self.received & live
        # Push: infected -> random peers.
        pushers = np.flatnonzero(inf)
        if pushers.size:
            peers = rng.integers(0, self.n, size=(pushers.size, cfg.fanout))
            kept = rng.random(peers.shape) >= self._p_eff(cfg.droprate)
            tgt = peers[kept]
            alive_tgt = tgt[~self.crashed[tgt]]
            self.total_message += int(alive_tgt.size)
            crash = rng.random(alive_tgt.size) < self._p_eff(cfg.crashrate)
            newly_crashed = np.unique(alive_tgt[crash])
            newly_crashed = newly_crashed[~self.crashed[newly_crashed]]
            self.crashed[newly_crashed] = True
            self.total_crashed += int(newly_crashed.size)
            ok = alive_tgt[~crash]
            ok = ok[~self.crashed[ok] & ~self.received[ok]]
            newly = np.unique(ok)
            self.received[newly] = True
            self.total_received += int(newly.size)
        # Pull: susceptible <- random peers' state.
        pullers = np.flatnonzero(sus & ~self.received)
        if pullers.size:
            peers = rng.integers(0, self.n, size=(pullers.size, cfg.fanout))
            kept = rng.random(peers.shape) >= self._p_eff(cfg.droprate)
            live_contact = kept & ~self.crashed[peers]
            hit = (self.received[peers] & live_contact).any(axis=1)
            newly = pullers[hit]
            self.received[newly] = True
            self.total_received += int(newly.size)
            # Count only responses from live peers (a crashed peer black-holes
            # the request, matching the push path's accounting).
            self.total_message += int(live_contact.sum())

    def _p_eff(self, p: float) -> float:
        return int(p * 100) / 100.0 if self.cfg.compat_reference else p

    def stats(self) -> Stats:
        return Stats(
            n=self.n,
            round=int(self.now - self.phase_start),
            total_received=self.total_received,
            total_message=self.total_message,
            total_crashed=self.total_crashed,
            total_removed=int(self.removed.sum()),
            makeups=self.makeups,
            breakups=self.breakups,
            exhausted=self.exhausted,
        )

    def sim_time_ms(self) -> float:
        return self.now - self.phase_start

    # --- checkpointing ---------------------------------------------------------
    def state_pytree(self):
        deg = np.array([len(f) for f in self.friends], dtype=np.int32)
        cap = max(int(deg.max(initial=0)), 1)
        fr = np.full((self.n, cap), -1, dtype=np.int32)
        for i, f in enumerate(self.friends):
            fr[i, : len(f)] = f
        return {
            "received": self.received.copy(),
            "crashed": self.crashed.copy(),
            "removed": self.removed.copy(),
            "friends": fr,
            "friend_cnt": deg,
        }
