"""The Stepper backend seam.

BASELINE.json's north star asks for the per-round node-update loop behind a
``Stepper`` interface (Init/Step/Stats) so backends are swappable:

* ``native``  -- event-driven Python oracle, faithful to the reference's
                 goroutine/channel semantics in *simulated* time (small N).
* ``cpp``     -- the same discrete-event algorithm in C++ (ctypes), the fast
                 CPU baseline standing in for the reference's Go loop.
* ``jax``     -- vectorized single-device XLA program (the product).
* ``sharded`` -- jax over a `jax.sharding.Mesh`, cross-shard all_to_all.

One ``gossip_window()`` call advances 10 simulated milliseconds -- the
reference driver's poll cadence (simulator.go:223,244) -- or one round in
rounds mode, so the driver's printing loop is backend-agnostic.
"""

from __future__ import annotations

import abc

from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.utils.metrics import Stats

WINDOW_MS = 10  # reference poll interval (simulator.go:223, 244)


class Stepper(abc.ABC):
    name: str = "abstract"

    def __init__(self, cfg: Config):
        self.cfg = cfg

    # --- lifecycle ------------------------------------------------------------
    @abc.abstractmethod
    def init(self) -> None:
        """Allocate node state (mirrors simulator.go:207-217)."""

    @abc.abstractmethod
    def overlay_window(self) -> tuple[int, int, bool]:
        """Advance overlay construction by one poll window.

        Returns ``(makeups, breakups, quiesced)`` -- the membership events
        observed during the window and whether the system has stabilized
        (no makeup/breakup activity for a full window, simulator.go:221-234).
        For static graphs ("kout", "erdos", "ring") the first call generates
        the graph and returns quiesced immediately.
        """

    @abc.abstractmethod
    def seed(self) -> None:
        """Pick a uniform-random node and inject its initial broadcast
        (simulator.go:240-241)."""

    @abc.abstractmethod
    def gossip_window(self) -> Stats:
        """Advance the epidemic by one poll window (10 simulated ms in ticks
        mode; one round in rounds mode) and return a counters snapshot."""

    @abc.abstractmethod
    def stats(self) -> Stats:
        """Current counters snapshot (host-side)."""

    @abc.abstractmethod
    def sim_time_ms(self) -> float:
        """Simulated milliseconds elapsed in the current phase."""

    # --- optional -------------------------------------------------------------
    @property
    def primary_host(self) -> bool:
        """False on the non-zero ranks of a multi-process run: they
        participate in collective snapshot gathers but must not write files
        (every rank holds the same replicated/gathered values)."""
        return True

    def state_pytree(self):
        """Backend state as arrays for checkpointing; None if unsupported.
        Under -distributed this is a COLLECTIVE call: every process must
        make it, even though only the primary host writes the result."""
        return None

    def load_state_pytree(self, tree) -> None:
        raise NotImplementedError(f"{self.name} does not support checkpoint restore")

    def overlay_state_pytree(self):
        """Mid-construction phase-1 state for checkpointing; None if
        unsupported (the discrete-event oracles run phase 1 in seconds at
        their feasible n).  Collective under -distributed, like
        state_pytree."""
        return None

    def load_overlay_state_pytree(self, tree, windows: int = 0) -> None:
        raise NotImplementedError(
            f"{self.name} does not support phase-1 checkpoint restore")


def run_bounded_to_target(stepper) -> Stats:
    """Shared host loop for the JAX backends' run_to_target fast path.

    Re-enters the backend's bounded device-side while_loop (`_run_fn`, see
    epidemic.run_call_budget) until the coverage target, max_rounds, or
    exhaustion (nothing in flight -- the liveness bound the reference lacks,
    simulator.go:243-251).  Requires `stepper._run_fn(state, key, target,
    until) -> state` with donated state, plus `.state/.key/.exhausted`.

    With a TelemetrySession on the stepper (`stepper._telem`, see
    utils/telemetry.py) the run fn additionally threads the device-resident
    per-window History through the loop -- `_run_fn(state, key, target,
    until, hist) -> (state, hist)` -- and the per-call wall clock lands in
    the session's phase ledger (first call = compile, rest = execute).
    """
    import time

    import jax
    import numpy as np

    cfg = stepper.cfg
    from gossip_simulator_tpu.models import epidemic

    from gossip_simulator_tpu.utils import trace as _trace

    target = int(np.ceil(cfg.coverage_target * cfg.n))
    budget = epidemic.run_call_budget(cfg)
    tick = int(jax.device_get(stepper.state.tick))
    telem = getattr(stepper, "_telem", None)
    hist = telem.begin_gossip() if telem is not None else None
    calls = 0
    while True:
        until = min(cfg.max_rounds, tick + budget)
        t0 = time.perf_counter()
        # Span per bounded device call: the first one is dominated by
        # trace+compile (the telemetry ledger's compile_s), later ones are
        # pure execution -- the name says which, so the trace separates
        # compile cost from steady-state throughput at a glance.
        with _trace.span("phase2.compile+run" if calls == 0
                         else "phase2.bounded_call", cat="device") as sp:
            if hist is not None:
                stepper.state, hist = stepper._run_fn(
                    stepper.state, stepper.key, np.int32(target),
                    np.int32(until), hist)
            else:
                stepper.state = stepper._run_fn(
                    stepper.state, stepper.key,
                    np.int32(target), np.int32(until))
            st = stepper.state
            from gossip_simulator_tpu.models.event import \
                in_flight as _inflight

            import jax.numpy as jnp

            # Multi-rumor convergence is the WORST rumor: the loop runs
            # until every rumor's per-rumor count reaches the target.
            recv_metric = (jnp.min(st.rumor_recv[:cfg.rumors])
                           if cfg.multi_rumor else st.total_received)
            tick, recv, in_flight = (int(x) for x in jax.device_get(
                (st.tick, recv_metric, _inflight(st))))
            if sp is not None:
                sp.update(until=int(until), tick=tick, received=recv,
                          in_flight=in_flight)
        calls += 1
        if telem is not None:
            telem.tally_gossip_call(time.perf_counter() - t0)
        # Exhaustion is recorded whatever ends the run (the windowed loop's
        # per-window flag ends up reflecting the LAST window too), so a wave
        # that dies in the same window the round cap is hit still reports
        # "exhausted" -- reason parity with the windowed path.  Healing can
        # revive an empty ring (a pending dead-friend detection re-sends
        # from an already-infected healer), so heal-on runs never exit on
        # emptiness -- they run to target or max_rounds.  A streaming run
        # with an empty ring is not dead while the injection schedule has
        # rumors still to start.
        if (in_flight == 0 and cfg.protocol != "pushpull"
                and not cfg.overlay_heal_resolved
                and (not cfg.multi_rumor
                     or tick > cfg.last_inject_tick)):
            stepper.exhausted = True
        if (recv >= target or tick >= cfg.max_rounds
                or stepper.exhausted):
            break
        # Cooperative shutdown (utils/lifecycle): a signalled run stops at
        # the next bounded-call boundary; the driver then writes the final
        # checkpoint and flushes artifacts with reason "interrupted".
        from gossip_simulator_tpu.utils import lifecycle as _lifecycle

        if _lifecycle.shutdown_requested():
            break
    if telem is not None:
        telem.end_gossip(hist)
    return stepper.stats()
