"""Vectorized mailbox delivery: the array-program replacement for the
reference's per-node buffered channels (simulator.go:51-54).

The reference gives every node four mailboxes (buffered Go channels) and
delivers each message with a goroutine.  Here a whole round's messages are
three flat arrays ``(src, dst, valid)``; delivery is a sort by destination,
a per-destination rank computation, and one scatter into a fixed-capacity
``[n, cap]`` mailbox -- O(M log M) total, entirely on device, no dynamic
shapes.  Rank-overflow beyond `cap` is counted and dropped (the channel-full
backpressure case; with cap=16 and uniform destinations the probability is
negligible -- see Config.mailbox_cap_resolved).

All functions are jit-safe and shard-agnostic: for the sharded backend the
same `deliver` runs per shard after messages are routed with all_to_all
(parallel/exchange.py).
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp

from gossip_simulator_tpu.ops.select import first_true_indices

_warned_dense_fallback = False


def flat_addressing_fits(n: int, cap: int) -> bool:
    """True iff the [n, cap] mailbox can use flat int32 addressing (the fast
    sort + 1-D-scatter delivery paths; index n*cap is the trash cell).  The
    auto mailbox cap (Config.mailbox_cap_for) shrinks 16 -> 8 right where
    its CONSUMER's gate stops fitting -- past n ~ 1.34e8 for plain
    deliver() surfaces (single [n, cap] arrays, flat to n ~ 2.7e8 at
    cap 8), past n ~ 6.7e7 for stacked=True consumers (the ticks
    overlay's deliver_pair [2n, cap] buffer, one-pass to n ~ 1.34e8 at
    cap 8)."""
    return (n + 1) * cap < 2**31


def ring_append(rings, cnt, dropped, payloads, wslot, valid, dw: int,
                cap: int, kernel: str = "xla"):
    """Append one entry per True in `valid` into its `wslot` window slot of
    the packed ring(s): one-hot reservation ranks (emission order, no
    gathers -- dw is tiny), bounds-checked against the slot capacity, with
    overflow counted in `dropped` and overflowed writes diverted to the
    dw*cap trash cell (this platform miscompiled flat OOB-drop scatters;
    see epidemic.deposit_local).  The same masked-cumsum rank pattern now
    also buckets the cross-shard exchange (round 6:
    parallel/exchange.route_multi ranks over the <= RANK_MAX_SHARDS
    destination columns instead of paying a stable sort per batch).

    `rings`/`payloads` are equal-length tuples -- every ring gets the same
    flat positions, so multi-array entries (e.g. the overlay's (dst, pay)
    pair) stay aligned.  A ring may carry a trailing payload axis (the
    multi-rumor (L, W) word ladder next to an (L,) id ring): its payload is
    (M, W) and the shared flat positions scatter whole rows.  Shared by
    parallel/event_sharded._ring_append and models/overlay_ticks;
    models/event.append_messages keeps its own multi-entry-per-row
    reservation variant.

    `kernel="pallas"` routes to the fused single-pass form
    (ops/pallas_deliver.fused_ring_append) -- bit-identical slot writes,
    counts, and drop totals (the -deliver-kernel gate; see the module
    docstring there for the equivalence argument)."""
    if kernel == "pallas":
        from gossip_simulator_tpu.ops import pallas_deliver
        return pallas_deliver.fused_ring_append(
            rings, cnt, dropped, payloads, wslot, valid, dw, cap)
    oh = ((wslot[:, None] == jnp.arange(dw, dtype=jnp.int32)[None, :])
          & valid[:, None]).astype(jnp.int32)
    rank = (jnp.cumsum(oh, axis=0) * oh).sum(axis=1) - 1
    base = (cnt[0][None, :] * oh).sum(axis=1)
    pos = base + rank
    ok = valid & (pos < cap)
    flat = jnp.where(ok, wslot * cap + pos, dw * cap)  # in-bounds trash cell
    rings = tuple(
        r.at[flat].set(jnp.where(ok[:, None] if p.ndim == 2 else ok, p, 0))
        for r, p in zip(rings, payloads))
    cnt = cnt + (oh * ok[:, None]).sum(axis=0)[None, :]
    dropped = dropped + (valid & ~ok).sum(dtype=jnp.int32)
    return rings, cnt, dropped


def deposit_sum(acc, dst, rows, valid, kernel: str = "xla"):
    """Sum-combine delivery for the numeric gossip family (models/pushsum):
    acc[dst[i]] += rows[i] for every True in `valid` -- the associative
    scatter-ADD sibling of the SI drain's first-touch-wins OR.  Integer adds
    commute, so arrival order (routing, chunking, shard count) never moves
    the result -- the property the pushsum S=1 == S=8 bit-identity pin rests
    on.  `acc` is (n, C) int32 fixed-point limbs; `rows` is (m, C).

    `kernel="pallas"` routes through the fused deposit
    (ops/pallas_deliver.fused_deposit_rows, the multi-rumor deposit's
    in-register combine, here with a 1-deep slot axis) -- same combine mode
    table as the OR path, gated by -deliver-kernel."""
    n = acc.shape[0]
    d = jnp.where(valid, dst, n)
    if kernel == "pallas":
        from gossip_simulator_tpu.ops import pallas_deliver
        return pallas_deliver.fused_deposit_rows(
            acc[None], jnp.zeros_like(d), d, rows)[0]
    return acc.at[d].add(jnp.where(valid[:, None], rows, 0), mode="drop")


def segment_ranks(sorted_keys: jnp.ndarray) -> jnp.ndarray:
    """Rank of each element within its run of equal values (input sorted).

    One cummax pass: each element's run start is the latest index where a
    new run began.  (A searchsorted(self, self) binary search does the same
    job but costs ~25 random-access probes per element -- measured seconds
    at 18M entries on v5e.)"""
    m = sorted_keys.shape[0]
    if m == 0:
        return jnp.zeros((0,), jnp.int32)
    idx = jnp.arange(m, dtype=jnp.int32)
    newseg = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]])
    segstart = jax.lax.cummax(jnp.where(newseg, idx, 0))
    return idx - segstart


def deliver(src: jnp.ndarray | None, dst: jnp.ndarray, valid: jnp.ndarray,
            n: int, cap: int, compact_chunk: int | None = None,
            src_cols: int | None = None, src_mod: int | None = None,
            kernel: str = "xla"):
    """Deliver messages into per-destination mailboxes.

    Args:
        src, dst: int32[M] message source/destination node ids (dst in [0,n)).
        valid: bool[M] mask of real messages.
        n: number of (local) nodes.
        cap: mailbox capacity per node.
        src_cols: if set, `src` may be None and sender ids are DERIVED as
            flat_index // src_cols -- for callers delivering a flattened
            (n, src_cols) emission matrix whose sender id is the row.
            The chunked path then skips both the caller's n*src_cols-wide
            broadcast materialization (4*n*src_cols bytes; 720 MB at the
            10M-node overlay) and the per-chunk gather from it.
        src_mod: like src_cols but for SLOT-major flattened (slots, n)
            matrices -- sender ids derive as flat_index % src_mod.
        compact_chunk: if set (and flat int32 addressing fits,
            (n+1)*cap < 2^31 -- past that the dense 2-D path runs and this
            is silently ignored), compact the valid messages (two-level
            first_true_indices) into <=chunk-sized batches before sorting --
            the overlay's emission lists are (n, ~18) arrays that are ~99%
            empty once membership settles, and the delivery sort otherwise
            pays for every empty slot.  Bit-identical to the single-pass
            form: chunks are ascending index ranges, so the global stable
            order is preserved, and per-node ranks continue across chunks
            via a total-arrivals counter.
        kernel: "xla" (the sort + rank + scatter chain below) or "pallas"
            (the fused single-pass kernel, ops/pallas_deliver) -- the
            -deliver-kernel gate, bit-identical mailboxes/counts/drops.
            The dense 2-D fallback (flat addressing overflow) always runs
            the XLA form.

    Returns:
        mbox: int32[n, cap] -- sender ids, -1 padded.  Slot order is arrival
            order after a stable sort, i.e. deterministic.
        count: int32[n] -- messages delivered per node (<= cap).
        dropped: int32[] -- messages beyond capacity (counted, not delivered).

    The sort carries the payload directly (one stable 2-operand lax.sort)
    instead of argsort+gather, and the mailbox scatter is flat 1-D with an
    explicit in-bounds trash cell -- 2-D index scatters are ~15x slower on
    this platform (see the NOTE in epidemic.deposit_local; the trash cell
    avoids relying on the OOB-drop semantics that were miscompiled there).
    """
    m = dst.shape[0]
    if src is None and src_cols is None and src_mod is None:
        # Caught here rather than as `int // None` in the derivation below
        # (advisor r3: the non-compact path otherwise raised an opaque
        # TypeError).
        raise ValueError("deliver: src=None requires src_cols or src_mod")
    if compact_chunk is not None and compact_chunk < m:
        if flat_addressing_fits(n, cap):
            return _deliver_compact(src, dst, valid, n, cap, compact_chunk,
                                    src_cols=src_cols, src_mod=src_mod,
                                    kernel=kernel)
        # Flat int32 addressing no longer fits: the requested compaction is
        # ignored and the full-length sort + 2-D scatter path below runs
        # (~15x slower per the NOTE).  Without a signal this reads as an
        # unexplained performance cliff at n >= ~1.35e8, so say it once.
        global _warned_dense_fallback
        if not _warned_dense_fallback:
            _warned_dense_fallback = True
            warnings.warn(
                f"mailbox.deliver: (n+1)*cap = {(n + 1) * cap} >= 2^31 -- "
                "compact_chunk is ignored and overlay delivery falls back "
                "to the dense sort + 2-D scatter path (~15x slower); "
                "reduce -mailbox-cap or shard the node axis",
                stacklevel=2)
    if src is None:
        src = (jnp.arange(m, dtype=jnp.int32) % src_mod
               if src_cols is None
               else jnp.arange(m, dtype=jnp.int32) // src_cols)
    key = jnp.where(valid, dst, n).astype(jnp.int32)
    if kernel == "pallas" and flat_addressing_fits(n, cap):
        # One full-width fused chunk with an empty carry reproduces the
        # single-pass result exactly: the fused step's count is TOTAL
        # arrivals (can exceed cap), so clamp to match the ok-only count
        # below -- both equal min(arrivals, cap) per destination.
        mbox, cnt, dropped = _compact_chunk_step(
            jnp.full((n * cap + 1,), -1, dtype=jnp.int32),
            jnp.zeros((n + 1,), dtype=jnp.int32),
            jnp.zeros((), jnp.int32), key, src.astype(jnp.int32), n, cap,
            rank_major=False, kernel=kernel)
        return (mbox[:n * cap].reshape(n, cap),
                jnp.minimum(cnt[:n], cap), dropped)
    sd, ss = jax.lax.sort((key, src.astype(jnp.int32)), num_keys=1,
                          is_stable=True)
    rank = segment_ranks(sd)
    ok = (sd < n) & (rank < cap)
    if flat_addressing_fits(n, cap):
        flat = jnp.where(ok, sd * cap + rank, n * cap)  # in-bounds trash cell
        mbox = jnp.full((n * cap + 1,), -1, dtype=jnp.int32)
        mbox = mbox.at[flat].set(
            jnp.where(ok, ss, -1))[:n * cap].reshape(n, cap)
    else:
        # Flat addressing would overflow int32 (n*cap >= 2^31, e.g. the
        # overlay phase at n >= ~1.35e8 with the default cap 16): fall back
        # to the 2-D scatter -- slower, but these sizes hit it rarely.
        rows = jnp.where(ok, sd, n)
        cols = jnp.where(ok, rank, 0)
        mbox = jnp.full((n, cap), -1, dtype=jnp.int32)
        mbox = mbox.at[rows, cols].set(jnp.where(ok, ss, -1), mode="drop")
    count = jnp.zeros((n + 1,), dtype=jnp.int32).at[
        jnp.where(ok, sd, n)].add(1)[:n]
    dropped = ((sd < n) & (rank >= cap)).sum(dtype=jnp.int32)
    return mbox, count, dropped


def _deliver_prefix_keyed(src, key_full, live, nk, cap, chunk,
                          carry=None, rank_major=False, spill=None,
                          kernel="xla"):
    """Chunked delivery of a prepacked-key stream whose valid entries are a
    known-length PREFIX (`live`, an int32 scalar): chunks are plain
    ascending index ranges with NO per-chunk compaction scan --
    first_true_indices of a prefix mask IS the ascending range, so this is
    bit-identical to _deliver_compact_keyed on that mask (lanes at or past
    `live` carry the caller's nk sentinel and land in the trash cell
    either way) at zero scan cost.  The ticks overlay's drain is the
    consumer: its stable toff sort packs every live entry into a prefix of
    known length (the ring count), and the per-chunk scans were the
    dominant term of the 10M delivery sweep (ticks_delivery_chunk's 64k
    3.40 -> 2M 2.18 s/window gradient was scan amortization).  Returns
    like _deliver_compact_keyed."""
    chunks = (live + chunk - 1) // chunk

    def body(i, bcarry):
        if spill is not None:
            mbox, count, dropped, pairs, scnt = bcarry
        else:
            mbox, count, dropped = bcarry
        idx = i * chunk + jnp.arange(chunk, dtype=jnp.int32)
        s = src.at[idx].get(mode="fill", fill_value=-1)
        key = key_full.at[idx].get(mode="fill", fill_value=nk)
        if spill is not None:
            mbox, count, dropped, (pairs, scnt) = _compact_chunk_step(
                mbox, count, dropped, key, s, nk, cap, rank_major,
                spill=(pairs, scnt), kernel=kernel)
            return mbox, count, dropped, pairs, scnt
        return _compact_chunk_step(mbox, count, dropped, key, s, nk, cap,
                                   rank_major, kernel=kernel)

    if carry is None:
        carry = (jnp.full((nk * cap + 1,), -1, dtype=jnp.int32),
                 jnp.zeros((nk + 1,), dtype=jnp.int32),
                 jnp.zeros((), jnp.int32))
    if spill is not None:
        out = jax.lax.fori_loop(0, chunks, body, carry + spill)
        return out[0], out[1], out[2], (out[3], out[4])
    return jax.lax.fori_loop(0, chunks, body, carry)


def deliver_pair(src, dst, typ, evalid, n: int, cap: int,
                 compact_chunk: int | None = None, flat: bool = False,
                 prefix_len=None, spill_in=None, spill=None,
                 kernel: str = "xla"):
    """Deliver a two-TYPE message stream into two mailbox sets in ONE
    sorted pass: key (typ, dst) packed as typ*n + dst, shared compaction,
    one stable sort, one scatter into a stacked [2n, cap] buffer split
    afterwards.  Bit-identical mailboxes to two deliver() calls with
    valid = evalid & (typ == t): the stable sort keeps within-(typ, dst)
    arrival order, and removing the other type's entries from a stably
    ordered stream does not reorder the survivors -- at roughly half the
    per-chunk op count (ONE full-width compaction scan / sort / scatter /
    count-add where two delivers each paid their own).

    Requires flat addressing for the stacked buffer, (2n+1)*cap < 2^31;
    past that it falls back to two deliver() calls (which carry their own
    dense-fallback warning).  Returns (mbox_t0, mbox_t1, dropped).

    With `flat` (the ticks engine's memory band): never materializes the
    (n, cap) 2-D shapes, whose narrow minor dim TPU tiling pads 16-25x
    (the round-4/5 compile-OOM class) -- returns the RANK-MAJOR stacked
    buffer instead: (mbox int32[2n*cap + 1], load_t0, load_t1, dropped),
    where mailbox slot r of type t is the CONTIGUOUS range
    [r*2n + t*n, r*2n + (t+1)*n) and load_t* are the max per-node counts
    (clamped to cap).  Cell contents are identical to the 2-D form.

    `prefix_len` (int32 scalar) asserts the valid entries are a packed
    prefix of that length (the ticks drain's post-sort layout): the
    chunked path then runs plain ascending ranges with no compaction
    scans (_deliver_prefix_keyed; bit-identical to the masked form).

    `spill_in` / `spill` mirror deliver_columns' overflow spill (round 7,
    the ticks overlay's lossless-membership band): `spill_in` is a
    (2, S(+1)) (pay, packed-key) pair list re-delivered FIRST through the
    same carry (delayed messages arrive before this window's); `spill` is
    a (pairs, cnt) accumulator collecting THIS delivery's capacity
    overflow as (pay, typ*n + dst) pairs instead of dropping -- the
    return gains the final pairs array.  Requires the chunked path (the
    single-pass branch routes through it with one full-width chunk)."""
    m = src.shape[0]
    n2 = 2 * n
    if not flat_addressing_fits(2 * n + 1, cap):
        assert not flat, "flat deliver_pair requires stacked addressing"
        assert spill is None and spill_in is None, \
            "deliver_pair spill requires stacked flat addressing"
        m0, _, d0 = deliver(src, dst, evalid & (typ == 0), n, cap,
                            compact_chunk, kernel=kernel)
        m1, _, d1 = deliver(src, dst, evalid & (typ == 1), n, cap,
                            compact_chunk, kernel=kernel)
        return m0, m1, d0 + d1
    key_full = jnp.where(evalid, typ * n + dst, n2).astype(jnp.int32)
    spilling = spill is not None or spill_in is not None
    if spilling:
        # Spill needs the carry-chained chunk machinery; a chunk covering
        # the whole stream reproduces the single-pass result exactly.
        chunk = min(compact_chunk or m, m)
        carry = None
        if spill_in is not None:
            carry = (jnp.full((n2 * cap + 1,), -1, dtype=jnp.int32),
                     jnp.zeros((n2 + 1,), dtype=jnp.int32),
                     jnp.zeros((), jnp.int32))
            carry, spill = deliver_spill_pairs(carry, spill_in, n2, cap,
                                               rank_major=flat, spill=spill,
                                               kernel=kernel)
        if prefix_len is not None:
            out = _deliver_prefix_keyed(src, key_full, prefix_len, n2, cap,
                                        chunk, carry=carry, rank_major=flat,
                                        spill=spill, kernel=kernel)
        else:
            out = _deliver_compact_keyed(src, key_full, evalid, n2, cap,
                                         chunk, carry=carry,
                                         rank_major=flat, spill=spill,
                                         kernel=kernel)
        if spill is not None:
            mbox, count, dropped, spill_out = out
        else:
            mbox, count, dropped = out
            spill_out = None
        res = ((mbox,
                jnp.minimum(count[:n].max(initial=0), cap),
                jnp.minimum(count[n:n2].max(initial=0), cap), dropped)
               if flat else
               (mbox[:n * cap].reshape(n, cap),
                mbox[n * cap:n2 * cap].reshape(n, cap), dropped))
        return res + (spill_out,) if spill_out is not None else res
    if compact_chunk is not None and compact_chunk < m:
        if prefix_len is not None:
            mbox, count, dropped = _deliver_prefix_keyed(
                src, key_full, prefix_len, n2, cap, compact_chunk,
                rank_major=flat, kernel=kernel)
        else:
            mbox, count, dropped = _deliver_compact_keyed(
                src, key_full, evalid, n2, cap, compact_chunk,
                rank_major=flat, kernel=kernel)
    elif kernel == "pallas":
        # One full-width fused chunk with an empty carry == the
        # single-pass sort form (same count semantics: every lane adds,
        # sentinel included).
        mbox, count, dropped = _compact_chunk_step(
            jnp.full((n2 * cap + 1,), -1, dtype=jnp.int32),
            jnp.zeros((n2 + 1,), dtype=jnp.int32), jnp.zeros((), jnp.int32),
            key_full, src.astype(jnp.int32), n2, cap, rank_major=flat,
            kernel=kernel)
    else:
        sd, ss = jax.lax.sort((key_full, src.astype(jnp.int32)),
                              num_keys=1, is_stable=True)
        rank = segment_ranks(sd)
        ok = (sd < n2) & (rank < cap)
        if flat:
            fidx = jnp.where(ok, rank * n2 + sd, n2 * cap)
        else:
            fidx = jnp.where(ok, sd * cap + rank, n2 * cap)
        mbox = jnp.full((n2 * cap + 1,), -1, dtype=jnp.int32)
        mbox = mbox.at[fidx].set(jnp.where(ok, ss, -1))
        count = jnp.zeros((n2 + 1,), dtype=jnp.int32).at[
            jnp.where(sd < n2, sd, n2)].add(1)
        dropped = ((sd < n2) & (rank >= cap)).sum(dtype=jnp.int32)
    if flat:
        return (mbox,
                jnp.minimum(count[:n].max(initial=0), cap),
                jnp.minimum(count[n:n2].max(initial=0), cap), dropped)
    mbox = mbox[:n2 * cap]
    return (mbox[:n * cap].reshape(n, cap),
            mbox[n * cap:n2 * cap].reshape(n, cap), dropped)


def _compact_chunk_step(mbox, count, dropped, key, s, nk, cap,
                        rank_major, spill=None, kernel="xla"):
    """ONE compaction chunk's delivery: stable sort by key, rank
    continuation via the total-arrivals counter, capacity-checked flat
    scatter (trash cell at nk*cap), count/drop updates.  THE shared body
    behind _deliver_compact_keyed and make_hosted_column_delivery -- the
    split round's bit-identity with the fused delivery is structural,
    not a maintained copy.  `key` must already be nk-sentineled for
    invalid lanes; `s` is the payload (sender ids).

    `spill`, when given as `(pairs int32[2, scap + 1], cnt int32[])`,
    collects capacity-overflowed messages as (src, dst) pairs instead of
    dropping them -- the caller re-delivers them next round, reproducing
    the reference's channel-full backpressure (senders block; membership
    traffic is delayed, never lost -- simulator.go:51-54).  Only messages
    past the SPILL capacity fall through to `dropped` (counted, never
    silent).  Returns (mbox, count, dropped[, spill]).

    `kernel="pallas"` replaces the whole sort -> segment_ranks -> scatter
    chain with the fused single-pass kernel (ops/pallas_deliver.
    fused_chunk_step): every chunked delivery path in the repo funnels
    through this one body, so the -deliver-kernel gate lives HERE and the
    fused/XLA bit-identity is structural for all of them.  Mailboxes,
    counts, and drop totals are bit-identical; the only at-rest divergence
    is the spill pair buffer's internal order (arrival vs sorted -- a
    within-destination-order-preserving permutation, so re-delivery
    produces identical mailboxes; see README divergence table)."""
    if kernel == "pallas":
        from gossip_simulator_tpu.ops import pallas_deliver
        return pallas_deliver.fused_chunk_step(
            mbox, count, dropped, key, s, nk, cap, rank_major, spill=spill)
    sd, ss = jax.lax.sort((key, s.astype(jnp.int32)), num_keys=1,
                          is_stable=True)
    rank = segment_ranks(sd) + count[jnp.minimum(sd, nk)]
    ok = (sd < nk) & (rank < cap)
    if rank_major:
        flat = jnp.where(ok, rank * nk + sd, nk * cap)
    else:
        flat = jnp.where(ok, sd * cap + rank, nk * cap)
    mbox = mbox.at[flat].set(jnp.where(ok, ss, -1))
    count = count.at[jnp.where(sd < nk, sd, nk)].add(1)
    ovf = (sd < nk) & (rank >= cap)
    if spill is None:
        return mbox, count, dropped + ovf.sum(dtype=jnp.int32)
    pairs, scnt = spill
    scap = pairs.shape[1] - 1
    pos = scnt + jnp.cumsum(ovf.astype(jnp.int32)) - 1
    fit = ovf & (pos < scap)
    tgt = jnp.where(fit, pos, scap)  # trash column
    pairs = pairs.at[0, tgt].set(jnp.where(fit, ss, -1))
    pairs = pairs.at[1, tgt].set(jnp.where(fit, sd, -1))
    dropped = dropped + (ovf & ~fit).sum(dtype=jnp.int32)
    return mbox, count, dropped, (pairs, scnt + fit.sum(dtype=jnp.int32))


def _deliver_compact_keyed(src, key_full, valid, nk, cap, chunk,
                           src_cols=None, src_mod=None, carry=None,
                           rank_major=False, spill=None, kernel="xla"):
    """Chunked-compacted delivery on a prepacked key in [0, nk) with nk
    the invalid sentinel -- the ONE chunked work-horse behind
    _deliver_compact (key = dst), deliver_pair (key = typ*n + dst) and
    deliver_columns (per column, src_cols=1).  With `src_cols`, sender
    ids derive as idx // src_cols (deliver's matrix-row contract; 1
    makes the sender the lane index itself) instead of gathering `src`.
    `carry`, when given, is a previous call's (mbox, count, dropped) so
    chained calls continue per-node ranks exactly like the chunk
    continuation within one call.  Returns the flat (nk*cap + 1) mailbox
    incl. trash cell, the TOTAL-arrivals count array (nk + 1), and the
    drop count.

    `rank_major` packs cell (key, rank) at rank*nk + key instead of
    key*cap + rank: mailbox slot r is then the CONTIGUOUS range
    [r*nk, (r+1)*nk) -- consumers can dynamic_slice a whole slot without
    ever materializing an (nk, cap) 2-D array, whose narrow minor dim
    TPU tile layouts pad to 128 lanes (observed 16x: s32[1e8, 8] tiled
    T(8,128) would be a 51 GB allocation -- the round-4 100M overlay
    compile OOM).  Same cells, same values, different addressing."""
    m = valid.shape[0]
    total = valid.sum(dtype=jnp.int32)
    chunks = (total + chunk - 1) // chunk

    def body(i, bcarry):
        if spill is not None:
            mbox, count, dropped, pairs, scnt, remaining = bcarry
        else:
            mbox, count, dropped, remaining = bcarry
        idx = first_true_indices(remaining, chunk)
        hit = jnp.zeros((m,), bool).at[idx].set(True, mode="drop")
        remaining = remaining & ~hit
        v = idx < m
        if src_cols is not None:
            s = jnp.where(v, idx // src_cols, -1)
        elif src_mod is not None:
            s = jnp.where(v, idx % src_mod, -1)
        else:
            s = src.at[idx].get(mode="fill", fill_value=-1)
        key = key_full.at[idx].get(mode="fill", fill_value=nk)
        key = jnp.where(v, key, nk)
        if spill is not None:
            mbox, count, dropped, (pairs, scnt) = _compact_chunk_step(
                mbox, count, dropped, key, s, nk, cap, rank_major,
                spill=(pairs, scnt), kernel=kernel)
            return mbox, count, dropped, pairs, scnt, remaining
        mbox, count, dropped = _compact_chunk_step(
            mbox, count, dropped, key, s, nk, cap, rank_major,
            kernel=kernel)
        return mbox, count, dropped, remaining

    if carry is None:
        carry = (jnp.full((nk * cap + 1,), -1, dtype=jnp.int32),
                 jnp.zeros((nk + 1,), dtype=jnp.int32),
                 jnp.zeros((), jnp.int32))
    if spill is not None:
        out = jax.lax.fori_loop(0, chunks, body, carry + spill + (valid,))
        return out[0], out[1], out[2], (out[3], out[4])
    mbox, count, dropped, _ = jax.lax.fori_loop(
        0, chunks, body, carry + (valid,))
    return mbox, count, dropped


def deliver_spill_pairs(carry, pairs, n: int, cap: int, rank_major: bool,
                        spill=None, kernel="xla"):
    """Deliver an explicit (src, dst) pair list -- last round's
    capacity-overflow spill -- as ONE sorted chunk step, chained BEFORE
    the round's emission matrices through the same carry (delayed
    messages arrive first, a deterministic order).  `pairs` is
    int32[2, S(+1)] with -1-padded dst; an all-empty spill costs one
    S-wide sort.  Re-overflowed messages go into `spill` again (or are
    counted dropped when spill is None)."""
    mbox, count, dropped = carry
    dst = pairs[1]
    key = jnp.where(dst >= 0, dst, n).astype(jnp.int32)
    out = _compact_chunk_step(mbox, count, dropped, key, pairs[0], n, cap,
                              rank_major, spill=spill, kernel=kernel)
    if spill is None:
        return out, None
    return out[:3], out[3]


def deliver_columns(dst_mat: jnp.ndarray, n: int, cap: int, chunk: int,
                    flat: bool = False, carry=None, spill_in=None,
                    spill=None, kernel: str = "xla"):
    """Per-SLOT chunked delivery of a (slots, n) emission matrix whose
    sender id is the lane (column) index.

    The flattened form scans the full slots*n mask per compaction chunk
    (~76 ms/chunk at the 10M-node overlay's 180M lanes, 84% of the
    round); scanning per SLOT row costs n lanes per chunk instead -- the
    same entries at ~1/slots the scan width -- and the sender id is the
    lane index itself (no src gather, no broadcast).  Arrival order is
    therefore SLOT-major (slot, then node): a deterministic re-choice of
    the engine's canonical mailbox order, not a fidelity change -- the
    reference's own arrival order is goroutine-racy (simulator.go:51-54),
    so any fixed order is equally faithful; the golden trajectory pins
    the one chosen here.  Per-node ranks continue across slots and
    chunks via the total-arrivals counter, and slots with zero emissions
    cost one n-wide popcount.

    With `flat` (the large-n path), returns the RANK-MAJOR flat mailbox
    (see _deliver_compact_keyed: mailbox slot r is the contiguous range
    [r*n, (r+1)*n)) plus the max per-node load, never materializing the
    16x-padded (n, cap) tile layout: (mbox_flat int32[n*cap + 1],
    max_load int32[], dropped).  Otherwise (mbox int32[n, cap], dropped).
    Cell contents are identical either way.

    `dst_mat` may be a tuple of matrices: their slot rows chain in order
    through the same carry (the overlay's reply buffers followed by the
    bootstrap vector reshaped (1, n)).  `carry` optionally supplies the
    initial (mbox, count, dropped) -- the overlay passes allocation-
    sequenced buffers so consecutive deliveries can share memory.

    `spill_in` (int32[2, S] pairs) delivers last round's overflow spill
    FIRST through the same carry; `spill` (a (pairs, cnt) accumulator)
    collects THIS delivery's overflow instead of dropping it (see
    _compact_chunk_step) -- the return gains the final accumulator."""
    mats = dst_mat if isinstance(dst_mat, (tuple, list)) else (dst_mat,)
    return _deliver_columns_impl(mats, n, cap, chunk, flat, carry,
                                 spill_in=spill_in, spill=spill,
                                 kernel=kernel)


def _deliver_columns_impl(mats, n, cap, chunk, flat, carry, spill_in=None,
                          spill=None, kernel="xla"):
    if carry is None:
        carry = (jnp.full((n * cap + 1,), -1, dtype=jnp.int32),
                 jnp.zeros((n + 1,), dtype=jnp.int32),
                 jnp.zeros((), jnp.int32))
    if spill_in is not None:
        carry, spill = deliver_spill_pairs(carry, spill_in, n, cap,
                                           rank_major=flat, spill=spill,
                                           kernel=kernel)
    for mat in mats:
        for c in range(mat.shape[0]):
            dcol = mat[c]
            # src_cols=1: the sender id is the lane index itself; the
            # chained carry continues per-node ranks across slots exactly
            # like the chunk continuation within one call.
            out = _deliver_compact_keyed(None, dcol, dcol >= 0, n, cap,
                                         chunk, src_cols=1, carry=carry,
                                         rank_major=flat, spill=spill,
                                         kernel=kernel)
            if spill is not None:
                carry, spill = out[:3], out[3]
            else:
                carry = out
    mbox, count, dropped = carry
    if flat:
        res = (mbox, jnp.minimum(count[:n].max(initial=0), cap), dropped)
    else:
        res = (mbox[:n * cap].reshape(n, cap), dropped)
    return res + (spill,) if spill is not None else res


def make_hosted_column_delivery(n: int, cap: int, chunk,
                                per_call_chunks: int = 256,
                                spill_cap: int = 0, kernel: str = "xla",
                                occupancy: str = "xla"):
    """deliver_columns(flat=True) as a HOST-driven sequence of bounded
    device calls -- the memory-scale overlay's delivery (overlay.
    make_split_round_fn).  One fused delivery of a full emission row is
    minutes of chunks at n=1e8 (the bootstrap burst is ~1526 64k-chunks)
    and a single device call past ~10 s gets the axon worker killed
    (UNAVAILABLE; the calibration note in overlay_ticks.run_call_budget),
    so the chunk loop runs a bounded number of trips per jitted call with
    the carry donated across calls.  Rows with zero emissions cost one
    jitted popcount -- CHEAPER than the fused form's full scan -- or
    NOTHING when the caller already knows the row's total (run's
    `row_totals`, the round-7 dead-row skip: the overlay pieces count
    each slot's emissions at write time, so settled rounds never touch
    the ~16 dead n-wide rows at all).

    `chunk` is an int or an ascending WIDTH LADDER (round 7,
    overlay.hosted_chunk_widths): each row picks the narrowest ladder
    width that covers its live total in one chunk, falling back to the
    fattest for burst rows -- fat chunks amortize the per-chunk flat
    scatter floors that dominate dense rows (profile_overlay.py measures
    the per-width constants), narrow ones keep settled rows at the swept
    small-chunk optimum.  Chunk width never changes results (ascending
    ranges + rank continuation -- deliver's compact_chunk contract), so
    the schedule is pure perf; each width's kernels compile lazily on
    first use.  The per-call trip budget scales inversely with width
    (constant lanes per call), keeping every call inside the watchdog
    calibration done at the base width.

    Bit-identical to deliver_columns(..., flat=True): same chunk body,
    same ascending-index order, same rank continuation (pinned by the
    split==fused trajectory test).  Returns fn(mats) ->
    (mbox_flat int32[n*cap + 1] rank-major, max_load, dropped).

    With `spill_cap` > 0: run(mats, spill_in) first re-delivers last
    round's overflow pairs, every chunk collects overflow into a
    (2, spill_cap + 1) accumulator instead of dropping (see
    _compact_chunk_step), and the return gains the final pairs array --
    the memory-scale overlay's lossless-membership path.

    `occupancy="pallas"` (the -phase1-kernel gate) replaces the
    per-row jitted popcount round-trips with ONE fused pass + transfer
    per emission matrix (ops.pallas_overlay_kernel.fused_hosted_chunk)
    when the caller has no write-time totals -- the first round after a
    checkpoint restore, and every round with -overlay-dead-skip off.
    Integer block sums, so the ladder re-selects exactly the same widths
    (and callers passing `row_totals` are untouched either way)."""
    widths = tuple(sorted({int(w) for w in
                           (chunk if isinstance(chunk, (tuple, list))
                            else (chunk,))}))
    base_chunk = widths[0]
    per_call_lanes = per_call_chunks * base_chunk
    count_valid = jax.jit(lambda d: (d >= 0).sum(dtype=jnp.int32))
    finish = jax.jit(
        lambda count: jnp.minimum(count[:n].max(initial=0), cap))
    spilling = spill_cap > 0

    def _chunk_body(mbox, count, dropped, idx, dcol, spill=None):
        v = idx < n
        s = jnp.where(v, idx, -1)  # sender = lane index (src_cols=1)
        key = dcol.at[idx].get(mode="fill", fill_value=n)
        key = jnp.where(v, key, n)
        return _compact_chunk_step(mbox, count, dropped, key, s, n, cap,
                                   rank_major=True, spill=spill,
                                   kernel=kernel)

    def _make_ksteps(chunk_w: int):
        @functools.partial(jax.jit,
                           donate_argnums=(0, 1, 2, 3, 4, 5) if spilling
                           else (0, 1, 2, 3))
        def kstep(mbox, count, dropped, *rest):
            if spilling:
                pairs, scnt, remaining, dcol, trips = rest
            else:
                remaining, dcol, trips = rest

            def body(i, carry):
                if spilling:
                    mbox, count, dropped, pairs, scnt, remaining = carry
                else:
                    mbox, count, dropped, remaining = carry
                idx = first_true_indices(remaining, chunk_w)
                hit = jnp.zeros((n,), bool).at[idx].set(True, mode="drop")
                remaining = remaining & ~hit
                if spilling:
                    mbox, count, dropped, (pairs, scnt) = _chunk_body(
                        mbox, count, dropped, idx, dcol,
                        spill=(pairs, scnt))
                    return mbox, count, dropped, pairs, scnt, remaining
                mbox, count, dropped = _chunk_body(mbox, count, dropped,
                                                   idx, dcol)
                return mbox, count, dropped, remaining

            init = ((mbox, count, dropped, pairs, scnt, remaining)
                    if spilling else (mbox, count, dropped, remaining))
            return jax.lax.fori_loop(0, trips, body, init)

        @functools.partial(jax.jit,
                           donate_argnums=(0, 1, 2, 3, 4) if spilling
                           else (0, 1, 2))
        def kstep_dense(mbox, count, dropped, *rest):
            """Fully-valid row (every lane emits -- the bootstrap burst):
            chunks are plain ascending ranges, no compaction scan at all.
            Bit-identical to kstep on an all-true mask (first_true_indices
            of all-true IS the ascending range)."""
            if spilling:
                pairs, scnt, dcol, start, trips = rest
            else:
                dcol, start, trips = rest

            def body(i, carry):
                if spilling:
                    mbox, count, dropped, pairs, scnt = carry
                else:
                    mbox, count, dropped = carry
                idx = start + i * chunk_w + jnp.arange(chunk_w,
                                                       dtype=jnp.int32)
                idx = jnp.minimum(idx, n)  # tail: clamp to the n sentinel
                if spilling:
                    mbox, count, dropped, (pairs, scnt) = _chunk_body(
                        mbox, count, dropped, idx, dcol,
                        spill=(pairs, scnt))
                    return mbox, count, dropped, pairs, scnt
                return _chunk_body(mbox, count, dropped, idx, dcol)

            init = ((mbox, count, dropped, pairs, scnt) if spilling
                    else (mbox, count, dropped))
            return jax.lax.fori_loop(0, trips, body, init)

        return kstep, kstep_dense

    ksteps: dict = {}  # width -> (kstep, kstep_dense), compiled lazily

    def _fns(chunk_w: int):
        if chunk_w not in ksteps:
            ksteps[chunk_w] = _make_ksteps(chunk_w)
        return ksteps[chunk_w]

    def _pick_width(total: int) -> int:
        """Narrowest ladder width covering `total` in ONE chunk, else the
        fattest (burst rows: fat chunks amortize the flat scatter floor)."""
        for w in widths:
            if total <= w:
                return w
        return widths[-1]

    remaining_jit = jax.jit(lambda d: d >= 0)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
    def kspill_in(mbox, count, dropped, pairs, scnt, spill_pairs):
        carry, sp = deliver_spill_pairs((mbox, count, dropped),
                                        spill_pairs, n, cap,
                                        rank_major=True,
                                        spill=(pairs, scnt), kernel=kernel)
        return carry + sp

    def run(mats, spill_in=None, row_totals=None):
        mbox = jnp.full((n * cap + 1,), -1, dtype=jnp.int32)
        count = jnp.zeros((n + 1,), dtype=jnp.int32)
        dropped = jnp.zeros((), jnp.int32)
        if spilling:
            pairs = jnp.full((2, spill_cap + 1), -1, dtype=jnp.int32)
            scnt = jnp.zeros((), jnp.int32)
            if spill_in is not None:
                mbox, count, dropped, pairs, scnt = kspill_in(
                    mbox, count, dropped, pairs, scnt, spill_in)
                jax.block_until_ready(mbox)
        if row_totals is None and occupancy == "pallas":
            from gossip_simulator_tpu.ops.pallas_overlay_kernel import \
                fused_hosted_chunk
            occs = jax.device_get([fused_hosted_chunk(mat) for mat in mats])
            row_totals = [int(v) for occ in occs for v in occ]
        ri = 0
        for mat in mats:
            for c in range(mat.shape[0]):
                dcol = mat[c]
                if row_totals is not None:
                    # Caller-supplied exact total (counted at emission
                    # time): zero rows skip without touching the array.
                    total = int(row_totals[ri])
                else:
                    total = int(jax.device_get(count_valid(dcol)))
                ri += 1
                if total == 0:
                    continue
                cw = _pick_width(total)
                kstep, kstep_dense = _fns(cw)
                chunks = -(-total // cw)
                per_call = max(1, per_call_lanes // cw)
                if total == int(dcol.shape[0]):
                    # Fully-valid row (the bootstrap burst): ascending
                    # ranges, no compaction scans.
                    done = 0
                    while done < chunks:
                        t = min(per_call, chunks - done)
                        if spilling:
                            mbox, count, dropped, pairs, scnt = kstep_dense(
                                mbox, count, dropped, pairs, scnt, dcol,
                                jnp.int32(done * cw), jnp.int32(t))
                        else:
                            mbox, count, dropped = kstep_dense(
                                mbox, count, dropped, dcol,
                                jnp.int32(done * cw), jnp.int32(t))
                        jax.block_until_ready(mbox)
                        done += t
                    continue
                remaining = remaining_jit(dcol)
                done = 0
                while done < chunks:
                    t = min(per_call, chunks - done)
                    if spilling:
                        (mbox, count, dropped, pairs, scnt,
                         remaining) = kstep(mbox, count, dropped, pairs,
                                            scnt, remaining, dcol,
                                            jnp.int32(t))
                    else:
                        mbox, count, dropped, remaining = kstep(
                            mbox, count, dropped, remaining, dcol,
                            jnp.int32(t))
                    jax.block_until_ready(mbox)
                    done += t
                del remaining
        if spilling:
            return mbox, finish(count), dropped, pairs
        return mbox, finish(count), dropped

    return run


def _deliver_compact(src, dst, valid, n, cap, chunk, src_cols=None,
                     src_mod=None, kernel="xla"):
    """Chunked-compacted deliver (see deliver's compact_chunk)."""
    key_full = jnp.where(valid, dst, n).astype(jnp.int32)
    mbox, count, dropped = _deliver_compact_keyed(
        src, key_full, valid, n, cap, chunk, src_cols=src_cols,
        src_mod=src_mod, kernel=kernel)
    return (mbox[:n * cap].reshape(n, cap),
            jnp.minimum(count[:n], cap), dropped)
