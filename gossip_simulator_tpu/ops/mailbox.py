"""Vectorized mailbox delivery: the array-program replacement for the
reference's per-node buffered channels (simulator.go:51-54).

The reference gives every node four mailboxes (buffered Go channels) and
delivers each message with a goroutine.  Here a whole round's messages are
three flat arrays ``(src, dst, valid)``; delivery is a sort by destination,
a per-destination rank computation, and one scatter into a fixed-capacity
``[n, cap]`` mailbox -- O(M log M) total, entirely on device, no dynamic
shapes.  Rank-overflow beyond `cap` is counted and dropped (the channel-full
backpressure case; with cap=16 and uniform destinations the probability is
negligible -- see Config.mailbox_cap_resolved).

All functions are jit-safe and shard-agnostic: for the sharded backend the
same `deliver` runs per shard after messages are routed with all_to_all
(parallel/exchange.py).
"""

from __future__ import annotations

import jax.numpy as jnp


def segment_ranks(sorted_keys: jnp.ndarray) -> jnp.ndarray:
    """Rank of each element within its run of equal values (input sorted)."""
    idx = jnp.arange(sorted_keys.shape[0], dtype=jnp.int32)
    first = jnp.searchsorted(sorted_keys, sorted_keys, side="left").astype(jnp.int32)
    return idx - first


def deliver(src: jnp.ndarray, dst: jnp.ndarray, valid: jnp.ndarray, n: int,
            cap: int):
    """Deliver messages into per-destination mailboxes.

    Args:
        src, dst: int32[M] message source/destination node ids (dst in [0,n)).
        valid: bool[M] mask of real messages.
        n: number of (local) nodes.
        cap: mailbox capacity per node.

    Returns:
        mbox: int32[n, cap] -- sender ids, -1 padded.  Slot order is arrival
            order after a stable sort, i.e. deterministic.
        count: int32[n] -- messages delivered per node (<= cap).
        dropped: int32[] -- messages beyond capacity (counted, not delivered).
    """
    m = src.shape[0]
    key = jnp.where(valid, dst, n).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    sd = key[order]
    ss = src[order]
    rank = segment_ranks(sd)
    ok = (sd < n) & (rank < cap)
    rows = jnp.where(ok, sd, n)  # n -> out of bounds -> mode="drop"
    cols = jnp.where(ok, rank, 0)
    mbox = jnp.full((n, cap), -1, dtype=jnp.int32)
    mbox = mbox.at[rows, cols].set(ss, mode="drop")
    count = jnp.zeros((n,), dtype=jnp.int32).at[rows].add(
        ok.astype(jnp.int32), mode="drop")
    dropped = ((sd < n) & (rank >= cap)).sum(dtype=jnp.int32)
    return mbox, count, dropped
