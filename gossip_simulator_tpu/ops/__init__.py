from gossip_simulator_tpu.ops.mailbox import deliver, segment_ranks

__all__ = ["deliver", "segment_ranks"]
