"""Phase-1 overlay megakernel: the request->negotiate->reply chain fused.

PR 3 cut the overlay's round count and PR 6/18 fused the *delivery* chunk
step, but the slot negotiation itself still runs as ~10 separate XLA
passes per mailbox slot: the makeup side builds its under-fanin mask,
one-hot append, eviction draw gather and reply blend as distinct
full-(n, k) ops, the breakup side adds the first-match scan and the
swap-with-last pair, and the bootstrap block pays another four n-wide
passes every round.  Each pass round-trips `friends`/`friend_cnt`
through HBM; ROOFLINE.json's phase-1 terms price what ONE traversal
would cost (scripts/profile_window.py --roofline).  The kernels here
collapse each link so a slot column touches the state once.

Three fused passes, one per gate point the -phase1-kernel flag threads
(config.phase1_kernel_resolved -- same policy as PR 6/18's gates):

* ``fused_negotiate``     -- process_makeup_slot / process_breakup_slot
                             plus the accept-under-fanin / random-evict /
                             replace decisions and the reply emission
                             in-register per slot column (kind="makeup" /
                             "breakup").
* ``fused_request_round`` -- the needNewFriend bootstrap append with its
                             write-time dead-skip count (PR 3's counted
                             emissions) in the same pass; composes with
                             -overlay-static-boot, which skips the block
                             entirely.
* ``fused_hosted_chunk``  -- per-rung occupancy for the adaptive
                             hosted_chunk_widths ladder: ALL emission
                             rows popcounted in one pass / one transfer
                             instead of a host round-trip per row
                             (ops.mailbox.make_hosted_column_delivery's
                             `occupancy` hook).

Why the fused forms are bit-identical to the XLA chain they replace: RNG
stays on the XLA side, so the draw streams are untouched -- the breakup
replacement draw (randint_excluding) depends only on (key, shape, src,
ids) and is computed before the kernel exactly as inside
process_breakup_slot; the makeup eviction position is drawn with the
PRE-append counts, which equals the XLA path's post-append draw on every
row where it is observable (append and evict are disjoint: a row either
accepts under fanin or evicts at/above it, and non-evicting rows' draws
never escape the where(ev, ...) blend).  Inside the kernel every decision
is the same one-hot elementwise form overlay._col_get/_col_set lower to
(first-match via a masked iota minimum, not argmax -- same index), the
inert-row replacement write stays an identity write like the XLA
unmasked _col_set, and the emission counts are integer mask sums, which
commute across the serial row blocks.

Layout note: the kernels keep the engines' natural node-major (n, k)
state -- the row axis is what the serial block loop walks, so the k<=16
minor axis rides along per block instead of forcing the transposed
layout pallas_graph needs for its (rows-on-lanes) PRNG streams.

Gate policy mirrors pallas_megakernel verbatim: interpret=True is the
CPU CI parity surface, ``auto`` resolves to pallas only on a real TPU
backend after the one-shot probe below passes on-device parity, explicit
``xla`` never probes, explicit ``pallas`` raises the named reason when
unavailable.  Block sizes resolve through tuning.py
(pallas_overlay.slot_block / chunk_block, "never"-persist until real TPU
evidence lands -- same class as pallas_megakernel.drain_block).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.ops.pallas_deliver import (_default_interpret,
                                                     _interpret_param)

I32 = jnp.int32

# Rows per serial block of the negotiate/request passes and columns per
# serial block of the occupancy pass.  Defaults are deliberate
# placeholders pending TPU evidence -- resolve via tuning.value so the
# block_shapes sweep space can move them without code edits.
SLOT_BLOCK = 512
CHUNK_BLOCK = 1024


def _slot_block() -> int:
    return int(_tuning.value("pallas_overlay.slot_block", None,
                             default=SLOT_BLOCK))


def _chunk_block() -> int:
    return int(_tuning.value("pallas_overlay.chunk_block", None,
                             default=CHUNK_BLOCK))


# ---------------------------------------------------------------------------
# Fused negotiation: one mailbox slot's makeup or breakup decisions.
# ---------------------------------------------------------------------------


def _row_blocks(n: int, blk: int):
    """Serial row-block schedule over n rows: full blocks of width
    blk_eff, then (when n is ragged) ONE overlapping block at n - blk_eff
    whose already-processed rows are masked inert.  The overlap trick
    keeps every device op at the static block width -- no unrolled
    scalar tail -- and is safe because every state write below is masked
    by the same validity row mask (masked rows write back their current,
    already-updated values)."""
    blk_eff = min(blk, n)
    nfull = n // blk_eff
    tail_start = n - blk_eff  # first masked row = nfull * blk_eff
    return blk_eff, nfull, (n % blk_eff != 0), tail_start


@functools.lru_cache(maxsize=None)
def _negotiate_kernel(kind: str, n: int, k: int, limit: int, blk: int):
    """One serial pass over row blocks.  Statics: kind ("makeup" /
    "breakup"), n rows, k friends columns, limit (= fanin for makeup,
    fanout for breakup), blk rows per block.  Ref layout: aliased inputs
    (friends, cnt), read-only inputs (src, has, draw), aliased outputs
    (friends, cnt -- read for the in-place update), fresh outputs
    (reply)."""
    blk_eff, nfull, ragged, tail_start = _row_blocks(n, blk)

    def block(start, first_valid, fr_ref, cnt_ref, src_ref, has_ref,
              draw_ref, reply_ref):
        rows = start + jax.lax.broadcasted_iota(I32, (blk_eff,), 0)
        valid = rows >= first_valid
        fr = fr_ref[pl.ds(start, blk_eff), :]
        cnt = cnt_ref[pl.ds(start, blk_eff)]
        src = src_ref[pl.ds(start, blk_eff)]
        has = (has_ref[pl.ds(start, blk_eff)] > 0) & valid
        draw = draw_ref[pl.ds(start, blk_eff)]
        iok = jax.lax.broadcasted_iota(I32, (blk_eff, k), 1)
        if kind == "makeup":
            # simulator.go:66-75: accept under fanin, else evict the
            # pre-drawn uniform victim and take its slot.
            under = cnt < limit
            app = has & under
            oh_app = iok == jnp.minimum(cnt, k - 1)[:, None]
            fr = jnp.where(oh_app & app[:, None], src[:, None], fr)
            cnt = cnt + app.astype(I32)
            ev = has & ~under
            oh_v = iok == draw[:, None]
            victim = jnp.where(oh_v, fr, 0).sum(axis=1, dtype=I32)
            fr = jnp.where(oh_v & ev[:, None], src[:, None], fr)
            reply = jnp.where(ev, victim, -1)
        else:
            # simulator.go:76-94: first-match scan; over fanout ->
            # swap-with-last removal, else replace in place with the
            # pre-drawn fresh peer (the reply's makeup target).
            in_range = iok < cnt[:, None]
            match = (fr == src[:, None]) & in_range & has[:, None]
            found = match.astype(I32).max(axis=1) > 0
            first = jnp.min(jnp.where(match, iok, k), axis=1)
            pos = jnp.where(found, first, 0)  # == argmax(match) per row
            over = cnt > limit
            rm = has & found & over
            rp = has & found & ~over
            lastpos = jnp.maximum(cnt - 1, 0)
            oh_last = iok == lastpos[:, None]
            lastval = jnp.where(oh_last, fr, 0).sum(axis=1, dtype=I32)
            oh_pos = iok == pos[:, None]
            posat = jnp.where(oh_pos, fr, 0).sum(axis=1, dtype=I32)
            posval = jnp.where(rm, lastval, jnp.where(rp, draw, posat))
            # The XLA form's UNMASKED in-place write (identity on inert
            # rows); `valid` only shields the ragged overlap rows.
            fr = jnp.where(oh_pos & valid[:, None], posval[:, None], fr)
            fr = jnp.where(oh_last & rm[:, None], -1, fr)
            cnt = cnt - rm.astype(I32)
            reply = jnp.where(rp, draw, -1)
        fr_ref[pl.ds(start, blk_eff), :] = fr
        cnt_ref[pl.ds(start, blk_eff)] = cnt
        old = reply_ref[pl.ds(start, blk_eff)]
        reply_ref[pl.ds(start, blk_eff)] = jnp.where(valid, reply, old)

    def kernel(_, __, src_ref, has_ref, draw_ref, fr_ref, cnt_ref,
               reply_ref):
        args = (fr_ref, cnt_ref, src_ref, has_ref, draw_ref, reply_ref)
        jax.lax.fori_loop(
            0, nfull,
            lambda i, _: (block(i * blk_eff, jnp.int32(0), *args), 0)[1],
            0)
        if ragged:
            block(jnp.int32(tail_start), jnp.int32(nfull * blk_eff),
                  *args)

    return kernel


def fused_negotiate(friends, cnt, src, has, draw, *, kind: str,
                    limit: int, interpret=None):
    """One mailbox slot of membership decisions for ALL nodes as a single
    pass over the (n, k) state: the decision masks, one-hot column
    read/write pair and the reply emission that overlay.process_*_slot
    runs as separate full-array ops.  `draw` carries the slot's
    pre-computed XLA-side randomness (makeup: the eviction position drawn
    with the pre-append counts; breakup: the randint_excluding fresh
    peer), `limit` is fanin (makeup) or fanout (breakup).  Returns
    (friends, cnt, reply) with reply = dst where a message must be sent,
    -1 elsewhere -- exactly where(mask, value, -1), so callers recover
    the decision mask as reply >= 0 and the write-time count as its
    sum."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    n, k = int(friends.shape[0]), int(friends.shape[1])
    kern = _negotiate_kernel(kind, n, k, int(limit),
                             max(1, _slot_block()))
    friends, cnt, reply = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(friends.shape, friends.dtype),
                   jax.ShapeDtypeStruct(cnt.shape, cnt.dtype),
                   jax.ShapeDtypeStruct(cnt.shape, I32)],
        input_output_aliases={0: 0, 1: 1},
        interpret=ip,
    )(friends, cnt, src.astype(I32), has.astype(I32), draw.astype(I32))
    return friends, cnt, reply


# ---------------------------------------------------------------------------
# Fused bootstrap request round: needNewFriend append + write-time count.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _request_kernel(n: int, k: int, fanout: int, blk: int):
    blk_eff, nfull, ragged, tail_start = _row_blocks(n, blk)

    def block(start, first_valid, fr_ref, cnt_ref, w_ref, em_ref, c_ref):
        rows = start + jax.lax.broadcasted_iota(I32, (blk_eff,), 0)
        valid = rows >= first_valid
        fr = fr_ref[pl.ds(start, blk_eff), :]
        cnt = cnt_ref[pl.ds(start, blk_eff)]
        w = w_ref[pl.ds(start, blk_eff)]
        under = (cnt < fanout) & valid
        iok = jax.lax.broadcasted_iota(I32, (blk_eff, k), 1)
        oh_app = iok == jnp.minimum(cnt, k - 1)[:, None]
        fr_ref[pl.ds(start, blk_eff), :] = jnp.where(
            oh_app & under[:, None], w[:, None], fr)
        cnt_ref[pl.ds(start, blk_eff)] = cnt + under.astype(I32)
        em = jnp.where(under, w, -1)
        old = em_ref[pl.ds(start, blk_eff)]
        em_ref[pl.ds(start, blk_eff)] = jnp.where(valid, em, old)
        c_ref[0] = c_ref[0] + under.sum(dtype=I32)

    def kernel(_, __, w_ref, fr_ref, cnt_ref, em_ref, c_ref):
        c_ref[0] = jnp.int32(0)
        args = (fr_ref, cnt_ref, w_ref, em_ref, c_ref)
        jax.lax.fori_loop(
            0, nfull,
            lambda i, _: (block(i * blk_eff, jnp.int32(0), *args), 0)[1],
            0)
        if ragged:
            block(jnp.int32(tail_start), jnp.int32(nfull * blk_eff),
                  *args)

    return kernel


def fused_request_round(friends, cnt, w, *, fanout: int, interpret=None):
    """The per-round bootstrap block (simulator.go:95-106) as one pass:
    every row still under fanout appends its pre-drawn self-patched
    needNewFriend target `w` and emits the request, with the write-time
    emission count (the PR-3 dead-skip bookkeeping) accumulated
    in-register instead of a separate n-wide reduction.  Returns
    (friends, cnt, boot_em, boot_cnt) -- boot_cnt a scalar int32, the
    integer mask sum (commutes across blocks, bit-identical to
    under.sum())."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    n, k = int(friends.shape[0]), int(friends.shape[1])
    kern = _request_kernel(n, k, int(fanout), max(1, _slot_block()))
    friends, cnt, boot_em, c = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(friends.shape, friends.dtype),
                   jax.ShapeDtypeStruct(cnt.shape, cnt.dtype),
                   jax.ShapeDtypeStruct(cnt.shape, I32),
                   jax.ShapeDtypeStruct((1,), I32)],
        input_output_aliases={0: 0, 1: 1},
        interpret=ip,
    )(friends, cnt, w.astype(I32))
    return friends, cnt, boot_em, c[0]


# ---------------------------------------------------------------------------
# Fused hosted-chunk occupancy: every emission row popcounted in one pass.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _occupancy_kernel(r: int, m: int, blk: int):
    blk_eff = min(blk, m)
    nfull = m // blk_eff
    tail_start = m - blk_eff

    def part(start, first_valid, mat_ref):
        cols = start + jax.lax.broadcasted_iota(I32, (r, blk_eff), 1)
        live = (mat_ref[:, pl.ds(start, blk_eff)] >= 0) \
            & (cols >= first_valid)
        return live.sum(axis=1, dtype=I32)

    def kernel(mat_ref, occ_ref):
        acc = jax.lax.fori_loop(
            0, nfull,
            lambda j, a: a + part(j * blk_eff, jnp.int32(0), mat_ref),
            jnp.zeros((r,), I32))
        if m % blk_eff:
            acc = acc + part(jnp.int32(tail_start),
                             jnp.int32(nfull * blk_eff), mat_ref)
        occ_ref[...] = acc

    return kernel


@functools.lru_cache(maxsize=None)
def _occupancy_call(r: int, m: int, blk: int, interpret: bool):
    """Jitted per-shape wrapper: run() calls this from the host loop, so
    the pallas_call must not re-trace per round."""
    kern = _occupancy_kernel(r, m, blk)
    call = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((r,), I32),
        interpret=_interpret_param(interpret),
    )
    return jax.jit(call)


def fused_hosted_chunk(mat, *, interpret=None):
    """Per-rung occupancy for the adaptive hosted delivery ladder: the
    live-entry total of EVERY row of an emission matrix int32[r, m] in
    one fused pass -- one device call + one transfer where the host
    ladder paid a jitted popcount round-trip per row.  Per-row integer
    block sums, so the totals are bit-identical to (row >= 0).sum() and
    the ladder re-selects exactly the same widths.  Returns occupancy
    int32[r]."""
    if interpret is None:
        interpret = _default_interpret()
    r, m = int(mat.shape[0]), int(mat.shape[1])
    return _occupancy_call(r, m, max(1, _chunk_block()),
                           bool(interpret))(mat)


# ---------------------------------------------------------------------------
# Capability probes (one-shot, threaded out of ambient traces -- the PR-6
# pattern: config.phase1_kernel_resolved is read at model-build time).
# ---------------------------------------------------------------------------


def _probe_case(interpret: bool) -> str:
    """Tiny concrete parity cases for every fused pass vs its XLA form;
    '' on bit-identical results, else a named reason.  Runs on a fresh
    thread: trace contexts are thread-local, so the comparisons stay
    eager even when the (lru_cached) gate fires mid-trace."""
    import threading

    out: list = []

    def run():
        try:
            out.append(_probe_case_impl(interpret))
        except Exception as e:  # noqa: BLE001 - reported as the reason
            out.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=run)
    t.start()
    t.join()
    return out[0]


def _probe_case_impl(interpret: bool) -> str:
    from gossip_simulator_tpu.models import overlay as ov
    from gossip_simulator_tpu.utils import rng as _rng

    # A small state with every row class: empty, under-fanin, at-fanout,
    # over-fanout, and src hits both present and absent friends.  n=37 is
    # deliberately ragged against every slot_block candidate.
    n, k, fanout, fanin = 37, 5, 3, 3
    key = jax.random.PRNGKey(7)
    kc, kf, ks, kk = jax.random.split(key, 4)
    cnt = jax.random.randint(kc, (n,), 0, k + 1, dtype=I32)
    fr = jax.random.randint(kf, (n, k), 0, n, dtype=I32)
    iok = jnp.arange(k, dtype=I32)[None, :]
    fr = jnp.where(iok < cnt[:, None], fr, -1)
    src = jax.random.randint(ks, (n,), -2, n, dtype=I32)
    has = src >= 0
    src = jnp.where(has, src, 0)
    ids = jnp.arange(n, dtype=I32)

    # --- breakup: fused vs process_breakup_slot -------------------------
    xf, xc, xnf, xrp = ov.process_breakup_slot(n, fanout, fr, cnt, src,
                                               has, ids, kk)
    nf = _rng.randint_excluding(kk, n, (n,), src, ids)
    ff, fc, rep = fused_negotiate(fr, cnt, src, has, nf, kind="breakup",
                                  limit=fanout, interpret=interpret)
    if not (bool((ff == xf).all()) and bool((fc == xc).all())
            and bool((rep == jnp.where(xrp, xnf, -1)).all())):
        return "fused breakup negotiation diverged from the XLA reference"

    # --- makeup: fused vs process_makeup_slot ---------------------------
    xf, xc, xv, xev = ov.process_makeup_slot(fanin, fr, cnt, src, has, kk)
    vpos = jax.random.randint(kk, cnt.shape, 0, jnp.maximum(cnt, 1),
                              dtype=I32)
    ff, fc, rep = fused_negotiate(fr, cnt, src, has, vpos, kind="makeup",
                                  limit=fanin, interpret=interpret)
    if not (bool((ff == xf).all()) and bool((fc == xc).all())
            and bool((rep == jnp.where(xev, xv, -1)).all())):
        return "fused makeup negotiation diverged from the XLA reference"

    # --- bootstrap request: fused vs the masked-append block ------------
    kb = jax.random.fold_in(kk, _rng.OP_BOOTSTRAP)
    w = jax.random.randint(kb, (n,), 0, n, dtype=I32)
    w = jnp.where(w == ids, (w + 1) % n, w)
    under = cnt < fanout
    xf = ov._col_set(fr, jnp.minimum(cnt, k - 1), w, under)
    xc = cnt + under.astype(I32)
    xem = jnp.where(under, w, -1)
    ff, fc, fem, fbc = fused_request_round(fr, cnt, w, fanout=fanout,
                                           interpret=interpret)
    if not (bool((ff == xf).all()) and bool((fc == xc).all())
            and bool((fem == xem).all())
            and int(fbc) == int(under.sum())):
        return "fused bootstrap request diverged from the XLA reference"

    # --- hosted occupancy vs the per-row popcount -----------------------
    mat = jnp.where(jax.random.uniform(kf, (4, 133)) < 0.4,
                    jax.random.randint(ks, (4, 133), 0, n, dtype=I32), -1)
    occ = fused_hosted_chunk(mat, interpret=interpret)
    if not bool((occ == (mat >= 0).sum(axis=1, dtype=I32)).all()):
        return "fused hosted occupancy diverged from the XLA popcount"
    return ""


@functools.lru_cache(maxsize=1)
def interpret_unsupported() -> str:
    """'' when every fused phase-1 pass runs (and matches XLA) in
    interpret mode on this jax build; else the reason (the CPU-CI
    gate)."""
    try:
        return _probe_case(interpret=True)
    except Exception as e:  # noqa: BLE001 - probe must never raise
        return f"{type(e).__name__}: {e}"


@functools.lru_cache(maxsize=1)
def tpu_unsupported() -> str:
    """'' when the fused passes lower AND pass on-device parity on a real
    TPU backend; else the named reason (the auto gate policy)."""
    if jax.default_backend() != "tpu":
        return ("no TPU backend "
                f"(jax.default_backend()={jax.default_backend()!r})")
    try:
        return _probe_case(interpret=False)
    except Exception as e:  # noqa: BLE001 - probe must never raise
        return f"{type(e).__name__}: {e}"


def kernel_unavailable_reason() -> str:
    """'' when `-phase1-kernel pallas` can run on THIS host (natively on
    TPU, interpret mode elsewhere); else the named reason."""
    if jax.default_backend() == "tpu":
        return tpu_unsupported()
    return interpret_unsupported()
