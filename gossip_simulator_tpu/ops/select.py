"""Shared selection ops: two-level compaction index extraction.

Used by the ring engine's wavefront compaction (models/epidemic.py) and the
mailbox delivery compaction (ops/mailbox.py)."""

from __future__ import annotations

import jax.numpy as jnp

I32 = jnp.int32


def first_true_indices(mask: jnp.ndarray, cap: int,
                       blk: int | None = None) -> jnp.ndarray:
    """First <=cap indices of True in `mask`, ascending, padded with n.

    Drop-in for ``jnp.nonzero(mask, size=cap, fill_value=n)[0]``, which XLA
    lowers to a full-length cumsum + scatter (~150 ms at n=1e7 on TPU v5e --
    the measured hot op of the compact tick).  Two-level version: one O(n)
    block-count pass, a nonzero over the n/blk block counts, then gather +
    in-block scan of only the first `cap` nonempty blocks.

    Yield contract (what deposit_compact's fixed chunk count relies on):
    if cap blocks are selected each holds >=1 True, and if every nonempty
    block is selected (nb <= cap) all Trues are seen -- either way the call
    yields min(cap, count) indices.

    `blk` balances the two scans: the block-count nonzero touches n/blk
    elements, the candidate gather touches min(nb, cap) * blk; blk ~
    sqrt(n/cap) equalizes them (both ~sqrt(n*cap)), clamped to [8, 256].
    """
    n = mask.shape[0]
    if n <= 4096 or cap >= n:
        return jnp.nonzero(mask, size=cap, fill_value=n)[0].astype(I32)
    if blk is None:
        blk = 8
        while blk * blk * cap < n and blk < 256:
            blk *= 2
    nb = -(-n // blk)
    pad = nb * blk - n
    m = jnp.pad(mask, (0, pad)) if pad else mask
    m = m.reshape(nb, blk)
    bc = m.sum(axis=1, dtype=I32)
    capb = min(nb, cap)
    bidx = jnp.nonzero(bc > 0, size=capb, fill_value=nb)[0].astype(I32)
    rows = m.at[bidx].get(mode="fill", fill_value=False)
    bcnt = bc.at[bidx].get(mode="fill", fill_value=0)
    off = jnp.cumsum(bcnt) - bcnt  # exclusive: output offset of each block
    local = jnp.cumsum(rows.astype(I32), axis=1) - 1
    pos = off[:, None] + local
    gidx = bidx[:, None] * blk + jnp.arange(blk, dtype=I32)[None, :]
    take = rows & (pos < cap)
    out = jnp.full((cap,), n, I32)
    return out.at[jnp.where(take, pos, cap)].set(
        jnp.where(take, gidx, n), mode="drop")
