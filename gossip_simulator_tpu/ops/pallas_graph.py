"""Pallas TPU kernel: k-out random-graph generation with the hardware PRNG.

The default generator (models/graphs.py) derives one counter-based key per row
(`vmap(fold_in)`) -- exactly reproducible anywhere, but at 100M nodes that is
10^8 threefry hashes before the simulation starts.  This kernel instead seeds
the per-core TPU PRNG once per row-block and materializes the friends table
tile by tile in VMEM (`pltpu.prng_random_bits`), which is bandwidth-bound
rather than hash-bound.

Properties:
* Shard-consistent at block granularity: blocks are addressed by GLOBAL row
  block index, so any shard whose row range is block-aligned generates
  exactly the rows it owns (same values as a single-device run).
* Different stream than the default generator -- same seed gives a different
  (equally random) graph; selected explicitly via `-pallas`.
* Peer draw is `bits mod n`: modulo bias <= n / 2^32 (< 2.5% at n=100M,
  uniform over peers to ~1e-9 relative -- irrelevant for the simulation's
  statistics).  Self-collisions get the reference's (id+1) % n patch
  (simulator.go:98-100).

Off-TPU, interpret=True runs the kernels in pallas interpret mode for
STRUCTURAL checks only: the TPU PRNG is replaced by an explicit all-zero
stub (jax 0.4.37's interpreter raises NotImplementedError on
pltpu.prng_random_bits, so the stub is ours, statically selected), and the
"graph" degenerates to everyone-befriends-node-0.  models/graphs.py
therefore routes to this kernel only on a real TPU backend; never validate
distributional properties in interpret mode.  The interpret argument to
pallas_call goes through ops.pallas_deliver._interpret_param, which papers
over the pltpu.InterpretParams availability drift across jax versions.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.ops.pallas_deliver import _interpret_param

BLOCK_ROWS = 512
LANES = 128  # minimum last-dim tile; k columns are sliced out afterwards


def _kout_kernel(n: int, k: int, row0: int, br: int, interpret: bool,
                 seed_ref, out_ref):
    blk = pl.program_id(0)
    # The output is TRANSPOSED (k, rows): a (rows, k) pallas output gets the
    # forced T(8,128) tiled layout, padding k<=6 lanes out to 128 -- 51 GB
    # of HBM at rows=1e8.  With rows on the lane axis the padding is only
    # k -> 8 sublanes; the caller transposes back to the natural compact
    # (rows, k) on the XLA side.
    if interpret:
        # The interpreter has no TPU PRNG (NotImplementedError on 0.4.37):
        # keep the documented all-zero-stub semantics explicitly.
        bits = jnp.zeros((k, br), jnp.int32)
    else:
        # Seed by GLOBAL block index so a row0>0 slice reproduces exactly
        # the same rows as the corresponding blocks of a full generation.
        # NOTE the seed stream depends on br: pallas_graph.block_rows is a
        # sweepable-but-NEVER-persisted tunable (neutral=False in tuning.py).
        pltpu.prng_seed(seed_ref[0], row0 // br + blk)
        bits = pltpu.prng_random_bits((k, br))
    peers = (bits.astype(jnp.uint32) % jnp.uint32(n)).astype(jnp.int32)
    gid = (row0 + blk * br
           + jax.lax.broadcasted_iota(jnp.int32, (k, br), 1))
    out_ref[:] = jnp.where(peers == gid, (peers + 1) % n, peers)


_ER_STREAM = 0x4552D14D  # XOR'd into the seed: decorrelates ER from kout


def _erdos_kernel(n: int, lam: float, cap: int, row0: int, br: int,
                  interpret: bool, seed_ref, out_ref):
    blk = pl.program_id(0)
    if interpret:
        # Same zero-bit stub as _kout_kernel: degree 0 everywhere.
        bits = jnp.zeros((cap + 1, br), jnp.int32)
    else:
        # The platform caps prng_seed at 2 values, so the stream tag folds
        # into the seed word instead of riding as a third argument.
        pltpu.prng_seed(seed_ref[0] ^ _ER_STREAM, row0 // br + blk)
        bits = pltpu.prng_random_bits((cap + 1, br))
    # Row 0 -> the Poisson uniform; rows 1.. -> peer picks.  The top 24 bits
    # shift into int32 range first (Mosaic has no uint32->f32 cast).
    u = (bits[0:1].astype(jnp.uint32) >> jnp.uint32(8)).astype(
        jnp.int32).astype(jnp.float32) * (2.0 ** -24)

    # Degree ~ Poisson(lam) by inverse CDF: X = #{j : u > P(X <= j)}.  The
    # pmf recurrence runs in f32 scalars; exp(-lam) stays normal for
    # lam <= 60 (the wrapper rejects larger).
    def body(j, carry):
        pmf, cdf, deg = carry
        cdf = cdf + pmf
        deg = deg + (u > cdf).astype(jnp.int32)
        pmf = pmf * (jnp.float32(lam) / (j + 1).astype(jnp.float32))
        return pmf, cdf, deg

    import math as _math

    _, _, deg = jax.lax.fori_loop(
        0, cap, body,
        (jnp.float32(_math.exp(-lam)), jnp.float32(0.0),
         jnp.zeros((1, br), jnp.int32)))
    peers = (bits[1:].astype(jnp.uint32) % jnp.uint32(n)).astype(jnp.int32)
    gid = (row0 + blk * br
           + jax.lax.broadcasted_iota(jnp.int32, (cap, br), 1))
    peers = jnp.where(peers == gid, (peers + 1) % n, peers)
    out_ref[:] = jnp.concatenate([deg, peers], axis=0)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 6))
def _erdos_pallas_jit(n: int, lam: float, row0: int, rows: int, br: int,
                      seed, interpret: bool = False):
    from gossip_simulator_tpu.config import er_cap

    cap = er_cap(lam)
    if cap > LANES:
        raise ValueError(f"erdos_pallas cap {cap} exceeds {LANES}")
    nblocks = -(-rows // br)
    seed_arr = jnp.asarray(seed, dtype=jnp.int32).reshape((1,))
    out = pl.pallas_call(
        functools.partial(_erdos_kernel, n, lam, cap, row0, br, interpret),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((cap + 1, br), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((cap + 1, nblocks * br),
                                       jnp.int32),
        interpret=_interpret_param(interpret),
    )(seed_arr)
    deg = jnp.minimum(out[0, :rows], cap)
    slot = jnp.arange(cap, dtype=jnp.int32)[:, None]
    friends = jnp.where(slot < deg[None, :], out[1:, :rows], -1)
    return friends.T, deg


def erdos_pallas(n: int, lam: float, row0: int, rows: int, seed,
                 interpret: bool = False):
    """Sparse directed Erdos-Renyi slice via the TPU PRNG: out-degree ~
    Poisson(lam = n*p) like models/graphs.erdos (different, equally random
    stream -- same contract as kout_pallas), peers uniform with the (id+1)%n
    self-patch.  Returns (friends int32[rows, cap] -1-padded, deg
    int32[rows]).  Requires lam <= 60 (f32 pmf recurrence) and
    block-rows-aligned row0.

    Block rows resolve through the tuning registry (pallas_graph.block_rows,
    default BLOCK_ROWS) OUTSIDE the jit so a sweep override actually
    retraces; the tunable changes the PRNG block stream, so it is
    neutral=False and never table-persisted.
    """
    if not 0.0 < lam <= 60.0:
        raise ValueError(f"erdos_pallas requires 0 < lam <= 60, got {lam}")
    br = int(_tuning.value("pallas_graph.block_rows", None,
                           default=BLOCK_ROWS))
    if row0 % br:
        raise ValueError(f"row0 must be {br}-aligned, got {row0}")
    return _erdos_pallas_jit(n, lam, row0, rows, br, seed, interpret)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 6))
def _kout_pallas_jit(n: int, k: int, row0: int, rows: int, br: int, seed,
                     interpret: bool = False):
    nblocks = -(-rows // br)
    seed_arr = jnp.asarray(seed, dtype=jnp.int32).reshape((1,))
    out = pl.pallas_call(
        functools.partial(_kout_kernel, n, k, row0, br, interpret),
        grid=(nblocks,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec((k, br), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, nblocks * br),
                                       jnp.int32),
        interpret=_interpret_param(interpret),
    )(seed_arr)
    return out[:, :rows].T


def kout_pallas(n: int, k: int, row0: int, rows: int, seed,
                interpret: bool = False):
    """friends int32[rows, k]: each of rows nodes picks k uniform peers != self.

    Requires k <= 128 and row0 aligned to the resolved block rows (shard
    alignment); `rows` is padded up to a block multiple internally.  Block
    rows resolve via tuning (pallas_graph.block_rows, default BLOCK_ROWS)
    outside the jit -- see erdos_pallas.
    """
    if k > LANES:
        raise ValueError(f"kout_pallas supports k <= {LANES}, got {k}")
    br = int(_tuning.value("pallas_graph.block_rows", None,
                           default=BLOCK_ROWS))
    if row0 % br:
        raise ValueError(f"row0 must be {br}-aligned, got {row0}")
    return _kout_pallas_jit(n, k, row0, rows, br, seed, interpret)
