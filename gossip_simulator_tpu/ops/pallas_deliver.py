"""Fused Pallas delivery kernels: the sort/rank/scatter chain as ONE pass.

Every delivery in both engines decomposes, on the XLA path, into a stable
sort by destination key, a segment-rank pass, and a flat scatter with a
trash cell -- three full-array ops whose per-op floors PROFILE_OVERLAY.json
pins at ~450-490 ns/lane (flat-scatter chunk) and PROFILE_EXCHANGE.json at
~2747 ns/lane (drain-side sort).  The kernels here replace that chain with
one serial pass per mailbox chunk that computes each lane's destination
bucket rank, writes its ring slot, and applies the combine in-register --
the fusion move of ROADMAP item 5.

Why a SERIAL pass is bit-identical to sort+rank+scatter: the XLA chain's
stable sort only ever reorders lanes BETWEEN destinations; within one
destination the sorted order IS arrival (lane) order.  A single pass that
keeps a per-destination arrival counter therefore assigns every lane the
same rank, the same flat cell, and the same overflow verdict as the sorted
form -- including the count array's junk-sentinel increments and the
trash-cell -1 writes -- so mailboxes, counts, and drop counters match the
XLA path bit for bit (pinned by tests/test_pallas_deliver.py).  The one
at-rest divergence is the spill PAIR BUFFER's internal order (arrival
order here vs sorted order on the XLA path): a within-destination
order-preserving permutation, so re-delivery next round produces identical
mailboxes under either kernel (see README divergence table).

Combine semantics ride the same pass: mailbox/ring payloads are
first-touch slot writes (rank < cap wins, exactly the SI bits' semantics),
multi-rumor word rows (the PR-5 (L, W) ladder next to an (L,) id ring)
scatter whole rows at the shared flat position, and the epidemic deposit
kernels accumulate their integer adds in-register -- R-rumor runs get the
fusion for free.

Gate policy (config.deliver_kernel_resolved): kernels trace with
``interpret=True`` on non-TPU backends -- that is the CPU CI parity
mechanism, not a stub -- and lower natively on TPU only when the one-shot
capability probe below passes on-device parity.  ``auto`` falls back to
``xla`` with a named reason on hosts without Pallas lowering.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

I32 = jnp.int32


def _interpret_param(interpret: bool):
    """Pallas interpret flag across jax builds: newer builds want
    pltpu.InterpretParams(), older ones (this container's 0.4.37) only
    accept the boolean -- the AttributeError that used to skip the
    pallas_graph structural tests wholesale (PR-4 probe)."""
    if not interpret:
        return False
    try:  # pragma: no cover - version-dependent
        from jax.experimental.pallas import tpu as pltpu
        ip = getattr(pltpu, "InterpretParams", None)
        if ip is not None:
            return ip()
    except ImportError:
        pass
    return True


def _default_interpret() -> bool:
    """Interpret unless we are actually on TPU (decided at trace time)."""
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Fused chunk step: mailbox._compact_chunk_step as one serial pass.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _chunk_kernel(nk: int, cap: int, rank_major: bool, scap):
    """Kernel body for one delivery chunk (cached per static shape so
    repeated pallas_call tracing reuses one closure).  `scap` is the spill
    pair capacity or None for the drop-counting form."""

    def kernel(*refs):
        if scap is None:
            (_, _, _, key_ref, s_ref,
             mbox_ref, count_ref, drop_ref) = refs
        else:
            (_, _, _, _, _, key_ref, s_ref,
             mbox_ref, count_ref, drop_ref, pr_ref, scnt_ref) = refs
        m = key_ref.shape[0]

        def body(i, _):
            k = key_ref[i]
            ss = s_ref[i]
            # Per-destination arrival counter == sorted-stream rank (the
            # stable sort never reorders within a destination).  count is
            # TOTAL arrivals -- incremented for every lane, sentinel nk
            # included, exactly like the XLA chain's count.at[...].add(1).
            kc = jnp.clip(k, 0, nk)
            pos = count_ref[kc]
            ok = (k >= 0) & (k < nk) & (pos < cap)
            if rank_major:
                cell = pos * nk + kc
            else:
                cell = kc * cap + pos
            flat = jnp.where(ok, cell, nk * cap)
            mbox_ref[flat] = jnp.where(ok, ss, -1)
            count_ref[kc] = pos + 1
            ovf = (k >= 0) & (k < nk) & (pos >= cap)
            if scap is None:
                drop_ref[0] = drop_ref[0] + ovf.astype(I32)
            else:
                # Spill collects overflow as (src, key) pairs in ARRIVAL
                # order (the XLA path collects the same multiset in sorted
                # order -- see module docstring); non-fitting lanes write
                # -1 at the trash column scap, like the XLA form.
                sp = scnt_ref[0]
                fit = ovf & (sp < scap)
                tgt = jnp.where(fit, sp, scap)
                pr_ref[tgt] = jnp.where(fit, ss, -1)
                pr_ref[scap + 1 + tgt] = jnp.where(fit, k, -1)
                scnt_ref[0] = sp + fit.astype(I32)
                drop_ref[0] = drop_ref[0] + (ovf & ~fit).astype(I32)
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    return kernel


def fused_chunk_step(mbox, count, dropped, key, s, nk: int, cap: int,
                     rank_major: bool, spill=None, interpret=None):
    """Drop-in fused form of mailbox._compact_chunk_step: same carry
    contract (flat mailbox incl. trash cell, total-arrivals count, drop
    counter), same return shape.  `key` must be nk-sentineled for invalid
    lanes, exactly like the XLA form."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    key = key.astype(I32)
    s = s.astype(I32)
    d1 = dropped.reshape(1)
    if spill is None:
        kern = _chunk_kernel(nk, cap, bool(rank_major), None)
        mbox, count, d1 = pl.pallas_call(
            kern,
            out_shape=[jax.ShapeDtypeStruct(mbox.shape, mbox.dtype),
                       jax.ShapeDtypeStruct(count.shape, count.dtype),
                       jax.ShapeDtypeStruct(d1.shape, d1.dtype)],
            input_output_aliases={0: 0, 1: 1, 2: 2},
            interpret=ip,
        )(mbox, count, d1, key, s)
        return mbox, count, d1[0]
    pairs, scnt = spill
    scap = pairs.shape[1] - 1
    pf = pairs.reshape(-1)
    s1 = scnt.reshape(1)
    kern = _chunk_kernel(nk, cap, bool(rank_major), scap)
    mbox, count, d1, pf, s1 = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(mbox.shape, mbox.dtype),
                   jax.ShapeDtypeStruct(count.shape, count.dtype),
                   jax.ShapeDtypeStruct(d1.shape, d1.dtype),
                   jax.ShapeDtypeStruct(pf.shape, pf.dtype),
                   jax.ShapeDtypeStruct(s1.shape, s1.dtype)],
        input_output_aliases={0: 0, 1: 1, 2: 2, 3: 3, 4: 4},
        interpret=ip,
    )(mbox, count, d1, pf, s1, key, s)
    return mbox, count, d1[0], (pf.reshape(2, scap + 1), s1[0])


# ---------------------------------------------------------------------------
# Fused ring append: mailbox.ring_append's one-hot rank chain as one pass.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _ring_kernel(dw: int, cap: int, widths):
    """widths: per-ring trailing word width, None for flat (L,) rings."""
    nr = len(widths)

    def kernel(*refs):
        # Inputs: cnt, drop, rings*nr, wslot, valid, payloads*nr; outputs
        # (aliased): cnt, drop, rings*nr.
        n_in = 4 + 2 * nr
        wslot_ref = refs[2 + nr]
        valid_ref = refs[2 + nr + 1]
        pay_refs = refs[2 + nr + 2:n_in]
        cnt_ref = refs[n_in]
        drop_ref = refs[n_in + 1]
        ring_refs = refs[n_in + 2:]
        m = wslot_ref.shape[0]

        def body(i, _):
            w = wslot_ref[i]
            v = valid_ref[i] != 0
            wc = jnp.clip(w, 0, dw - 1)
            pos = cnt_ref[wc]
            ok = v & (pos < cap)
            flat = jnp.where(ok, wc * cap + pos, dw * cap)
            for j, ww in enumerate(widths):
                if ww is None:
                    val = pay_refs[j][i]
                    ring_refs[j][flat] = jnp.where(ok, val,
                                                   jnp.zeros_like(val))
                else:
                    # Whole-row write at the shared flat position: the
                    # multi-rumor word ladder fuses for free (static
                    # unroll; W is the packed word count, tiny).
                    for c in range(ww):
                        val = pay_refs[j][i, c]
                        ring_refs[j][flat, c] = jnp.where(
                            ok, val, jnp.zeros_like(val))
            # ok-only increments reproduce the one-hot form: pos is
            # monotone per slot, so once it reaches cap every later lane
            # fails the bound under both schemes.
            cnt_ref[wc] = pos + ok.astype(I32)
            drop_ref[0] = drop_ref[0] + (v & ~ok).astype(I32)
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    return kernel


def fused_ring_append(rings, cnt, dropped, payloads, wslot, valid, dw: int,
                      cap: int, interpret=None):
    """Drop-in fused form of mailbox.ring_append (same contract: rings /
    payloads are aligned tuples, cnt is int32[1, dw], overflow diverts to
    the dw*cap trash cell)."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    widths = tuple(None if p.ndim == 1 else int(p.shape[1])
                   for p in payloads)
    kern = _ring_kernel(dw, cap, widths)
    cf = cnt.reshape(-1)
    d1 = dropped.reshape(1)
    outs = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(cf.shape, cf.dtype),
                   jax.ShapeDtypeStruct(d1.shape, d1.dtype)]
        + [jax.ShapeDtypeStruct(r.shape, r.dtype) for r in rings],
        input_output_aliases={i: i for i in range(2 + len(rings))},
        interpret=ip,
    )(cf, d1, *rings, wslot.astype(I32), valid.astype(I32),
      *[p for p in payloads])
    cf, d1 = outs[0], outs[1]
    return tuple(outs[2:]), cf.reshape(cnt.shape), d1[0]


# ---------------------------------------------------------------------------
# Fused deposit: epidemic.deposit_local / deposit_rumors scatter-adds.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _deposit_kernel(b: int, n: int, width):
    """width None: +1 count adds (deposit_local); else whole-row adds of a
    (m, width) value matrix (deposit_rumors' broadcast newbits rows)."""

    def kernel(*refs):
        if width is None:
            _, slot_ref, dst_ref, p_ref = refs
        else:
            _, slot_ref, dst_ref, val_ref, p_ref = refs
        m = slot_ref.shape[0]

        def body(i, _):
            sl = slot_ref[i]
            d = dst_ref[i]
            # mode="drop" equivalence: out-of-range lanes add zero at cell
            # 0 (a read-modify-write of an unchanged value); integer adds
            # commute, so lane order never matters.
            ok = (sl >= 0) & (sl < b) & (d >= 0) & (d < n)
            idx = jnp.where(ok, sl * n + d, 0)
            if width is None:
                p_ref[idx] = p_ref[idx] + ok.astype(p_ref.dtype)
            else:
                for c in range(width):
                    val = val_ref[i, c]
                    p_ref[idx, c] = p_ref[idx, c] + jnp.where(
                        ok, val, jnp.zeros_like(val))
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    return kernel


def fused_deposit_add(pending, slots, dst, interpret=None):
    """pending.at[slots, dst].add(1, mode="drop") as one fused pass;
    `dst` already carries the caller's n sentinel for invalid lanes."""
    if interpret is None:
        interpret = _default_interpret()
    b, n = int(pending.shape[0]), int(pending.shape[1])
    kern = _deposit_kernel(b, n, None)
    pf = pending.reshape(-1)
    (pf,) = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(pf.shape, pf.dtype)],
        input_output_aliases={0: 0},
        interpret=_interpret_param(interpret),
    )(pf, slots.astype(I32), dst.astype(I32))
    return pf.reshape(pending.shape)


def fused_deposit_rows(pending, slots, dst, vals, interpret=None):
    """pending.at[slots, dst].add(vals, mode="drop") with a trailing word
    axis: pending is (b, n, W), vals is (m, W) -- the multi-rumor deposit's
    in-register combine."""
    if interpret is None:
        interpret = _default_interpret()
    b, n, w = (int(pending.shape[0]), int(pending.shape[1]),
               int(pending.shape[2]))
    kern = _deposit_kernel(b, n, w)
    pf = pending.reshape(b * n, w)
    (pf,) = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(pf.shape, pf.dtype)],
        input_output_aliases={0: 0},
        interpret=_interpret_param(interpret),
    )(pf, slots.astype(I32), dst.astype(I32), vals)
    return pf.reshape(pending.shape)


# ---------------------------------------------------------------------------
# Fused unique-index scatter: event.append_messages' dual-ring write.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _unique_set_kernel(widths):
    nr = len(widths)

    def kernel(*refs):
        flat_ref = refs[nr]
        val_refs = refs[nr + 1:nr + 1 + nr]
        ring_refs = refs[nr + 1 + nr:]
        m = flat_ref.shape[0]

        def body(i, _):
            f = flat_ref[i]
            for j, ww in enumerate(widths):
                if ww is None:
                    ring_refs[j][f] = val_refs[j][i]
                else:
                    for c in range(ww):
                        ring_refs[j][f, c] = val_refs[j][i, c]
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    return kernel


def fused_unique_set(rings, flat, vals, interpret=None):
    """ring.at[flat].set(vals, unique_indices=True) over aligned ring/value
    tuples in ONE pass (the append path's id ring and word ring share their
    reservation positions).  Indices must be unique and in bounds -- the
    caller's per-lane trash-slot construction guarantees both -- so the
    serial write order is immaterial and the result is bit-identical to the
    XLA scatters."""
    if interpret is None:
        interpret = _default_interpret()
    widths = tuple(None if v.ndim == 1 else int(v.shape[1]) for v in vals)
    kern = _unique_set_kernel(widths)
    nr = len(rings)
    outs = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(r.shape, r.dtype) for r in rings],
        input_output_aliases={i: i for i in range(nr)},
        interpret=_interpret_param(interpret),
    )(*rings, flat.astype(I32), *vals)
    return tuple(outs)


# ---------------------------------------------------------------------------
# Capability probes (PR-4 pattern, split per satellite 1: interpret-mode
# availability is a different question from TPU lowering).
# ---------------------------------------------------------------------------


def _probe_case(interpret: bool) -> str:
    """Run a tiny fused chunk step + ring append and compare against the
    XLA forms; returns '' on bit-identical results, else a named reason.

    The probe compares CONCRETE outputs, but its (lru_cached) callers can
    fire mid-trace -- Config.deliver_kernel_resolved is read inside
    shard_map/jit closures that only exist at trace time.  JAX trace
    contexts are thread-local, so running the probe body on a fresh thread
    escapes any ambient trace and keeps the comparisons eager; the result
    is a host string, which is trace-safe to branch on."""
    import threading

    out: list = []

    def run():
        try:
            out.append(_probe_case_impl(interpret))
        except Exception as e:  # noqa: BLE001 - reported as the reason
            out.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=run)
    t.start()
    t.join()
    return out[0]


def _probe_case_impl(interpret: bool) -> str:
    from gossip_simulator_tpu.ops import mailbox as mb

    nk, cap = 5, 2
    key = jnp.array([0, 3, 0, 0, nk, 2, 3, 3, 3, 1], I32)
    s = jnp.arange(10, dtype=I32) + 100
    init = lambda: (jnp.full((nk * cap + 1,), -1, I32),
                    jnp.zeros((nk + 1,), I32), jnp.zeros((), I32))
    fm, fc, fd = fused_chunk_step(*init(), key, s, nk, cap, False,
                                  interpret=interpret)
    xm, xc, xd = mb._compact_chunk_step(*init(), key, s, nk, cap, False)
    if not (bool((fm == xm).all()) and bool((fc == xc).all())
            and int(fd) == int(xd)):
        return "fused chunk step diverged from the XLA reference"

    dw, rcap = 3, 2
    rings = (jnp.zeros((dw * rcap + 1,), I32),
             jnp.zeros((dw * rcap + 1, 2), jnp.uint32))
    cnt = jnp.zeros((1, dw), I32)
    pay = (jnp.arange(7, dtype=I32) + 1,
           jnp.arange(14, dtype=jnp.uint32).reshape(7, 2) + 1)
    wslot = jnp.array([0, 1, 0, 2, 0, 1, 0], I32)
    valid = jnp.array([1, 1, 1, 0, 1, 1, 1], bool)
    fr, fcn, fdr = fused_ring_append(rings, cnt, jnp.zeros((), I32), pay,
                                     wslot, valid, dw, rcap,
                                     interpret=interpret)
    xr, xcn, xdr = mb.ring_append(rings, cnt, jnp.zeros((), I32), pay,
                                  wslot, valid, dw, rcap)
    if not (all(bool((a == b).all()) for a, b in zip(fr, xr))
            and bool((fcn == xcn).all()) and int(fdr) == int(xdr)):
        return "fused ring append diverged from the XLA reference"
    return ""


@functools.lru_cache(maxsize=1)
def interpret_unsupported() -> str:
    """'' when the fused kernels run (and match XLA) in interpret mode on
    this jax build; else the reason.  This is the CPU-CI gate: interpret
    mode needs no TPU, so a non-empty value means the jax build itself
    cannot trace these kernels."""
    try:
        return _probe_case(interpret=True)
    except Exception as e:  # noqa: BLE001 - probe must never raise
        return f"{type(e).__name__}: {e}"


@functools.lru_cache(maxsize=1)
def tpu_unsupported() -> str:
    """'' when the fused kernels lower AND pass on-device parity on a real
    TPU backend; else the named reason (used by the auto gate policy)."""
    if jax.default_backend() != "tpu":
        return f"no TPU backend (jax.default_backend()={jax.default_backend()!r})"
    try:
        return _probe_case(interpret=False)
    except Exception as e:  # noqa: BLE001 - probe must never raise
        return f"{type(e).__name__}: {e}"


def kernel_unavailable_reason() -> str:
    """'' when `-deliver-kernel pallas` can run on THIS host (natively on
    TPU, interpret mode elsewhere); else the named reason."""
    if jax.default_backend() == "tpu":
        return tpu_unsupported()
    return interpret_unsupported()
