"""Phase-2 megakernel: the emit->route->deliver window as fused passes.

PR 6 (ops/pallas_deliver) fused the sort/rank/scatter DELIVERY chain; a
phase-2 window still round-trips the mail ring through HBM between the
three remaining links: the emission builds its edge/partition/duplicate
masks and reservation prefix as ~10 separate full-array ops, the sharded
receive side re-decodes and re-filters routed arrivals before a separate
ring append, and the pushsum drain walks the slot in dynamic-slice chunks.
ROOFLINE.json prices each link from the SoA column layout (see
scripts/profile_window.py --roofline); the kernels here collapse each link
to ONE serial pass so the bytes actually touched approach that floor.

Four fused passes, one per gate point the -phase2-kernel flag threads
(config.phase2_kernel_resolved -- same policy as PR 6's -deliver-kernel):

* ``fused_emit``       -- event.append_messages' mask/prefix/scatter chain:
                          edge masks, partition block, duplicate
                          suppression, per-slot reservation prefix and the
                          dual-ring payload scatter in-register.  Draws its
                          targets from the gathered friends rows and lands
                          locally-owned deliveries directly into the ring.
* ``fused_recv_land``  -- the sharded receive side: wire-word decode,
                          receiving-side duplicate filter and the ring
                          append as one pass over routed arrivals (what
                          "lands cross-shard traffic" means at S > 1 --
                          see the bit-identity note below).
* ``fused_drain_sum``  -- the pushsum whole-slot drain: entry decode and
                          integer scatter-add over every due lane, no
                          chunk round-trips.
* ``fused_deposit_both`` -- the ring engine's multi-rumor deposit pair
                          (+1 counting add AND the R-row rumor add) at the
                          shared (slot, dst) cell in one pass.

Why the fused forms are bit-identical to the XLA chain they replace:
``fused_emit`` keeps a per-slot VIRTUAL counter incremented by every valid
sender's reservation size -- exactly the weighted exclusive prefix sum the
XLA path computes with cumsum -- so every sender sees the same start, the
same overflow verdict and the same trash-lane diversions (non-edges write
0 at their unique ``dw*cap + lane`` position, overflowed edges write their
payload there, matching the unique_indices scatter lane for lane).
``fused_recv_land`` reproduces mailbox.ring_append's monotone per-slot
position argument plus the pre-append flags gather, which no append
mutates.  The two ADD passes commute lane-for-lane (integer adds), the
same property the pushsum S=1 == S=8 pin rests on.

What the megakernel deliberately does NOT fuse: at S > 1 the drain's
crash draws are keyed by ring POSITION (ckey + entry slot), and an
entry's position depends on how the all_to_all interleaved every source
shard's segments -- unknowable shard-locally.  Landing locally-owned
deliveries around the collective would therefore shift crash draws at
crashrate > 0; the S=1 path (where the route is the identity) gets the
direct landing via ``fused_emit``, and S > 1 gets the fused receive side
instead.  The pipelined exchange path (-exchange-pipeline double) keeps
its PR-6 kernels: its route/flush split already owns the overlap win.

Gate policy mirrors pallas_deliver verbatim: interpret=True is the CPU CI
parity surface, ``auto`` resolves to pallas only on a real TPU backend
after the one-shot probe below passes on-device parity, explicit ``xla``
never probes, explicit ``pallas`` raises the named reason when
unavailable.  Block sizes for the drain/receive passes resolve through
tuning.py (pallas_megakernel.drain_block / recv_block, "never"-persist
until real TPU evidence lands -- same class as pallas_graph.block_rows).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.ops.pallas_deliver import (_default_interpret,
                                                     _interpret_param)

I32 = jnp.int32

# Serial-loop unroll factors for the bounded passes (fori trip count drops
# by the factor; lanes inside a block still apply in order).  The emit
# kernel takes NO unroll: its trash-lane uniqueness argument is per-lane
# and row trip counts are already small.  Defaults are deliberate
# placeholders pending TPU evidence -- resolve via tuning.value so the
# block_shapes sweep space can move them without code edits.
DRAIN_BLOCK = 8
RECV_BLOCK = 8


def _drain_block() -> int:
    return int(_tuning.value("pallas_megakernel.drain_block", None,
                             default=DRAIN_BLOCK))


def _recv_block() -> int:
    return int(_tuning.value("pallas_megakernel.recv_block", None,
                             default=RECV_BLOCK))


# ---------------------------------------------------------------------------
# Fused emission: event.append_messages' mask -> prefix -> scatter chain.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _emit_kernel(k: int, dw: int, cap: int, b: int, tb, has_part: bool,
                 has_dup: bool, rbit: int, width):
    """One serial pass over sender rows.  Statics: k friends columns, dw
    arrival windows, cap per-slot capacity, b batch ticks, tb the SIR
    trigger base (None = no trigger column), rbit the RECEIVED flag bit,
    width the packed rumor word count (None = id ring only).  Lanes per
    row KW = k (+1 with the trigger column)."""
    kw = k + (1 if tb is not None else 0)

    def kernel(*refs):
        # Ref layout: aliased inputs (ids[, words], vcnt, adds, sup,
        # lost, blk), read-only inputs, then the aliased outputs in the
        # same order (the pallas_call convention -- see _chunk_kernel).
        na = 6 + (1 if width is not None else 0)
        nro = (6 + (1 if has_part else 0) + (1 if has_dup else 0)
               + (2 if tb is not None else 0)
               + (1 if width is not None else 0))
        ro = list(refs[na:na + nro])
        base_ref, sf_ref, drop_ref = ro.pop(0), ro.pop(0), ro.pop(0)
        sv_ref, ws_ref, off_ref = ro.pop(0), ro.pop(0), ro.pop(0)
        pm_ref = ro.pop(0) if has_part else None
        fl_ref = ro.pop(0) if has_dup else None
        st_ref = ro.pop(0) if tb is not None else None
        sid_ref = ro.pop(0) if tb is not None else None
        sw_ref = ro.pop(0) if width is not None else None
        out = list(refs[na + nro:])
        ids_ref = out.pop(0)
        words_ref = out.pop(0) if width is not None else None
        vcnt_ref, adds_ref, sup_ref, lost_ref, blk_ref = out
        m = sv_ref.shape[0]

        def body(i, _):
            v = sv_ref[i] != 0
            o = off_ref[i]
            sc = ws_ref[i]
            evs = []
            pays = []
            ec = jnp.zeros((), I32)
            dcnt = jnp.zeros((), I32)
            blkn = jnp.zeros((), I32)
            # Lane pass 1 (static unroll over the tiny friends axis):
            # edge verdicts, partition block, duplicate filter, kept count.
            for kk in range(k):
                f = sf_ref[i, kk]
                e = v & (drop_ref[i, kk] == 0) & (f >= 0)
                if has_part:
                    bl = (pm_ref[i, kk] != 0) & e
                    blkn = blkn + bl.astype(I32)
                    e = e & ~bl
                if has_dup:
                    df = fl_ref[jnp.maximum(f, 0)]
                    du = e & ((df.astype(I32) & rbit) > 0)
                    dcnt = dcnt + du.astype(I32)
                    e = e & ~du
                evs.append(e)
                pays.append(f * b + o)
                ec = ec + e.astype(I32)
            if tb is not None:
                # SIR trigger lane right after the kept edges (NOT gated
                # on svalid -- mirror the XLA concat exactly; dead rows'
                # triggers only ever reach a trash lane / lost count).
                et = st_ref[i] != 0
                evs.append(et)
                pays.append(tb + sid_ref[i] * b + o)
                ec = ec + et.astype(I32)
            # Reservation: virtual per-slot counter over ALL valid senders
            # == the XLA weighted exclusive prefix (overflowed senders
            # still advance it; their writes divert, keeping later
            # senders' verdicts identical).
            start = base_ref[sc] + vcnt_ref[sc]
            okr = v & (start + ec <= cap)
            vcnt_ref[sc] = vcnt_ref[sc] + jnp.where(v, ec, 0)
            adds_ref[sc] = adds_ref[sc] + jnp.where(okr, ec, 0)
            sup_ref[sc] = sup_ref[sc] + dcnt
            lost_ref[0] = lost_ref[0] + jnp.where(okr, 0, ec)
            if has_part:
                blk_ref[0] = blk_ref[0] + blkn
            # Lane pass 2: running kept-edge rank -> flat cell; every lane
            # writes (non-edges 0 at their UNIQUE trash lane, overflowed
            # edges their payload there) -- lane-for-lane the
            # unique_indices scatter's ivals.
            col = jnp.zeros((), I32)
            for kk in range(kw):
                e = evs[kk]
                wr = e & okr
                flat = jnp.where(wr, sc * cap + start + col,
                                 dw * cap + i * kw + kk)
                ids_ref[flat] = jnp.where(e, pays[kk], 0)
                if width is not None:
                    for c in range(width):
                        wv = sw_ref[i, c]
                        words_ref[flat, c] = jnp.where(
                            e, wv, jnp.zeros_like(wv))
                col = col + e.astype(I32)
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    return kernel


def fused_emit(mail_ids, mail_cnt, sf, drop, svalid, wslot, off, *,
               dw: int, cap: int, b: int, tb=None, strig=None,
               sender_ids=None, pmask=None, flags=None, received_bit=1,
               swords=None, mail_words=None, interpret=None):
    """Fused form of event.append_messages from the gathered friends rows
    down: consumes the XLA-computed per-sender draws (sf gather, drop
    mask, arrival wslot/off -- RNG stays on the XLA side so streams are
    untouched) and performs masks, reservation and the dual-ring write in
    one pass.  `pmask` is the RAW partition_blocked matrix (un-ANDed),
    `flags` the uint8 node flags for duplicate suppression.  Returns
    (mail_ids, adds[dw], sup_adds[dw], lost, blocked[, mail_words]);
    blocked is only meaningful when pmask is given."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    m, k = sf.shape
    width = None if swords is None else int(swords.shape[1])
    kern = _emit_kernel(k, dw, cap, b,
                        None if tb is None else int(tb),
                        pmask is not None, flags is not None,
                        int(received_bit), width)
    z = jnp.zeros((dw,), I32)
    z1 = jnp.zeros((1,), I32)
    aliased = [mail_ids] + ([mail_words] if width is not None else []) \
        + [z, z, z, z1, z1]
    ro = [mail_cnt[0], sf.astype(I32), drop.astype(I32),
          svalid.astype(I32), wslot.astype(I32), off.astype(I32)]
    if pmask is not None:
        ro.append(pmask.astype(I32))
    if flags is not None:
        ro.append(flags)
    if tb is not None:
        ro.append(strig.astype(I32))
        ro.append(sender_ids.astype(I32))
    if width is not None:
        ro.append(swords)
    outs = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in aliased],
        input_output_aliases={i: i for i in range(len(aliased))},
        interpret=ip,
    )(*aliased, *ro)
    mail_ids = outs[0]
    j = 1
    if width is not None:
        mail_words = outs[1]
        j = 2
    # outs[j] is the virtual counter (internal); the observables follow.
    adds, sup = outs[j + 1], outs[j + 2]
    lost, blk = outs[j + 3], outs[j + 4]
    if width is not None:
        return mail_ids, adds, sup, lost[0], blk[0], mail_words
    return mail_ids, adds, sup, lost[0], blk[0]


# ---------------------------------------------------------------------------
# Fused receive-side landing: decode + duplicate filter + ring append.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _recv_kernel(dw: int, cap: int, b: int, has_dup: bool, rbit: int,
                 width, m: int, blk: int):
    """One pass over M routed wire words: -1-sentinel validity, positional
    decode, receiving-side duplicate gather and the mailbox ring-append
    convention (ok-only count increments, single dw*cap trash cell)."""

    def kernel(*refs):
        # Aliased: ids[, words], cnt, drop[, sup]; read-only: recv
        # [, flags][, word matrix]; then the aliased outputs.
        nal = (3 + (1 if has_dup else 0)
               + (1 if width is not None else 0))
        nro = (1 + (1 if has_dup else 0)
               + (1 if width is not None else 0))
        ro = list(refs[nal:nal + nro])
        recv_ref = ro.pop(0)
        fl_ref = ro.pop(0) if has_dup else None
        wv_ref = ro.pop(0) if width is not None else None
        out = list(refs[nal + nro:])
        ids_ref = out.pop(0)
        words_ref = out.pop(0) if width is not None else None
        cnt_ref, drop_ref = out.pop(0), out.pop(0)
        sup_ref = out.pop(0) if has_dup else None

        def lane(i):
            rw = recv_ref[i]
            rv = rw >= 0
            r = jnp.maximum(rw, 0)
            d = r // (dw * b)
            w = (r // b) % dw
            o = r % b
            if has_dup:
                df = fl_ref[d]
                du = rv & ((df.astype(I32) & rbit) > 0)
                sup_ref[w] = sup_ref[w] + du.astype(I32)
                rv = rv & ~du
            pos = cnt_ref[w]
            ok = rv & (pos < cap)
            flat = jnp.where(ok, w * cap + pos, dw * cap)
            ids_ref[flat] = jnp.where(ok, d * b + o, 0)
            if width is not None:
                for c in range(width):
                    wv = wv_ref[i, c]
                    words_ref[flat, c] = jnp.where(
                        ok, wv, jnp.zeros_like(wv))
            cnt_ref[w] = pos + ok.astype(I32)
            drop_ref[0] = drop_ref[0] + (rv & ~ok).astype(I32)

        nfull = m // blk

        def body(jb, _):
            for t in range(blk):
                lane(jb * blk + t)
            return 0

        jax.lax.fori_loop(0, nfull, body, 0)
        for i in range(nfull * blk, m):
            lane(i)

    return kernel


def fused_recv_land(mail_ids, mail_cnt, dropped, recv, *, dw: int,
                    cap: int, b: int, words=None, mail_words=None,
                    flags=None, received_bit=1, interpret=None):
    """Fused sharded receive side: for each routed wire word (-1 =
    empty slot) decode (dst_local, wslot, off), optionally apply the
    receiving-side duplicate filter against `flags`, and append into the
    local mail ring -- the decode/filter/rank/scatter chain of
    event_sharded._route_and_append's post-exchange half as ONE pass.
    `words` is the (M, W) word matrix (garbage in empty slots is fine:
    nothing invalid is ever written).  Returns
    (mail_ids, mail_cnt, dropped, sup_adds[, mail_words]); sup_adds is
    zeros when `flags` is None."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    m = int(recv.shape[0])
    width = None if words is None else int(words.shape[1])
    has_dup = flags is not None
    kern = _recv_kernel(dw, cap, b, has_dup, int(received_bit), width,
                        m, max(1, _recv_block()))
    cf = mail_cnt.reshape(-1)
    d1 = dropped.reshape(1)
    aliased = [mail_ids] + ([mail_words] if width is not None else []) \
        + [cf, d1] + ([jnp.zeros((dw,), I32)] if has_dup else [])
    ro = [recv.astype(I32)]
    if has_dup:
        ro.append(flags)
    if width is not None:
        ro.append(words)
    outs = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in aliased],
        input_output_aliases={i: i for i in range(len(aliased))},
        interpret=ip,
    )(*aliased, *ro)
    mail_ids = outs[0]
    j = 1
    if width is not None:
        mail_words = outs[1]
        j = 2
    cf, d1 = outs[j], outs[j + 1]
    sup = outs[j + 2] if has_dup else jnp.zeros((dw,), I32)
    cnt = cf.reshape(mail_cnt.shape)
    if width is not None:
        return mail_ids, cnt, d1[0], sup, mail_words
    return mail_ids, cnt, d1[0], sup


# ---------------------------------------------------------------------------
# Fused pushsum drain: whole-slot decode + integer scatter-add.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _drain_kernel(n: int, cols: int, cap: int, b: int, blk: int):
    def kernel(_, slot_ref, m_ref, ids_ref, mass_ref, acc_ref):
        s0 = slot_ref[0] * cap
        m = m_ref[0]

        def lane(idx):
            ok = idx < m
            ent = ids_ref[s0 + idx]
            row = ent // b
            # mode="drop" equivalence: masked / out-of-range lanes add
            # zero at row 0 (integer adds commute, order immaterial).
            inb = ok & (row >= 0) & (row < n)
            ix = jnp.where(inb, row, 0)
            for c in range(cols):
                v = mass_ref[s0 + idx, c]
                acc_ref[ix, c] = acc_ref[ix, c] + jnp.where(
                    inb, v, jnp.zeros_like(v))

        nfull = cap // blk

        def body(jb, _):
            for t in range(blk):
                lane(jb * blk + t)
            return 0

        jax.lax.fori_loop(0, nfull, body, 0)
        for i in range(nfull * blk, cap):
            lane(i)

    return kernel


def fused_drain_sum(acc, mail_ids, mail_mass, slot, m, *, cap: int,
                    b: int, interpret=None):
    """The pushsum drain as one whole-slot pass: every lane of window
    `slot` decodes its destination row (entry // b) and scatter-adds its
    mass limbs into `acc` -- replacing the dynamic-slice chunk loop over
    deposit_sum.  `m` is the live entry count (lanes past it are masked);
    the full static `cap` is scanned, which subsumes the sharded engine's
    pmax-agreed chunk count.  Integer adds commute, so the result is
    bit-identical to any chunking.  Returns the updated acc."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    n, cols = int(acc.shape[0]), int(acc.shape[1])
    kern = _drain_kernel(n, cols, cap, b, max(1, _drain_block()))
    (acc,) = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(acc.shape, acc.dtype)],
        input_output_aliases={0: 0},
        interpret=ip,
    )(acc, jnp.reshape(slot, (1,)).astype(I32),
      jnp.reshape(m, (1,)).astype(I32), mail_ids, mail_mass)
    return acc


# ---------------------------------------------------------------------------
# Fused multi-rumor deposit: the +1 count add and the R-row rumor add.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _deposit_both_kernel(bslots: int, n: int, r: int, k: int):
    def kernel(_, __, slot_ref, dst_ref, nb_ref, p_ref, pr_ref):
        m = slot_ref.shape[0]

        def body(i, _):
            sl = slot_ref[i]
            d = dst_ref[i]
            ok = (sl >= 0) & (sl < bslots) & (d >= 0) & (d < n)
            idx = jnp.where(ok, sl * n + d, 0)
            p_ref[idx] = p_ref[idx] + ok.astype(p_ref.dtype)
            for c in range(r):
                v = nb_ref[i // k, c]
                pr_ref[idx, c] = pr_ref[idx, c] + jnp.where(
                    ok, v, jnp.zeros_like(v))
            return 0

        jax.lax.fori_loop(0, m, body, 0)

    return kernel


def fused_deposit_both(pending, pending_rumors, dst, slots, valid,
                       newbits, interpret=None):
    """epidemic.deposit_local AND deposit_rumors as one joint pass: each
    kept edge lands its +1 counting add and its sender's R new-rumor-bit
    row at the shared (slot, dst) cell.  `dst` carries the caller's edge
    layout ((n*k,) local ids, row-major by sender); the sender's newbits
    row is gathered in-register (i // k) instead of materializing the
    (n*k, R) broadcast.  Integer adds commute -> bit-identical to the
    sequential pair.  Returns (pending, pending_rumors)."""
    if interpret is None:
        interpret = _default_interpret()
    ip = _interpret_param(interpret)
    bslots, n = int(pending.shape[0]), int(pending.shape[1])
    r = int(newbits.shape[1])
    k = int(dst.shape[0]) // int(newbits.shape[0])
    d = jnp.where(valid, dst, n)
    kern = _deposit_both_kernel(bslots, n, r, k)
    pf = pending.reshape(-1)
    prf = pending_rumors.reshape(bslots * n, r)
    pf, prf = pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct(pf.shape, pf.dtype),
                   jax.ShapeDtypeStruct(prf.shape, prf.dtype)],
        input_output_aliases={0: 0, 1: 1},
        interpret=ip,
    )(pf, prf, slots.astype(I32), d.astype(I32),
      newbits.astype(prf.dtype))
    return pf.reshape(pending.shape), prf.reshape(pending_rumors.shape)


# ---------------------------------------------------------------------------
# Capability probes (one-shot, threaded out of ambient traces -- the PR-6
# pattern: config.phase2_kernel_resolved is read inside jit closures).
# ---------------------------------------------------------------------------


def _probe_case(interpret: bool) -> str:
    """Tiny concrete parity cases for every fused pass vs its XLA form;
    '' on bit-identical results, else a named reason.  Runs on a fresh
    thread: trace contexts are thread-local, so the comparisons stay
    eager even when the (lru_cached) gate fires mid-trace."""
    import threading

    out: list = []

    def run():
        try:
            out.append(_probe_case_impl(interpret))
        except Exception as e:  # noqa: BLE001 - reported as the reason
            out.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=run)
    t.start()
    t.join()
    return out[0]


def _probe_case_impl(interpret: bool) -> str:
    from gossip_simulator_tpu.models import epidemic
    from gossip_simulator_tpu.ops import mailbox as mb

    # --- drain: fused whole-slot scan vs chunked deposit_sum ------------
    n, cols, cap, b = 5, 3, 8, 4
    ids = jnp.arange(2 * cap, dtype=I32) * 3 % (n * b)
    mass = (jnp.arange(2 * cap * cols, dtype=I32).reshape(2 * cap, cols)
            + 1)
    acc0 = jnp.ones((n, cols), I32)
    m = jnp.asarray(5, I32)
    fa = fused_drain_sum(acc0, ids, mass, jnp.asarray(1, I32), m,
                         cap=cap, b=b, interpret=interpret)
    ok = jnp.arange(cap, dtype=I32) < m
    xa = mb.deposit_sum(acc0, ids[cap:] // b, mass[cap:], ok)
    if not bool((fa == xa).all()):
        return "fused drain sum diverged from the XLA reference"

    # --- receive landing vs decode + filter + ring_append ---------------
    dw, rcap, b2 = 3, 2, 4
    nl = 4
    flags = jnp.array([0, 1, 0, 1], jnp.uint8)
    wire = []
    for d, w, o, v in ((0, 1, 2, 1), (1, 1, 0, 1), (2, 0, 3, 1),
                       (0, 0, 0, 0), (3, 1, 1, 1), (2, 1, 2, 1),
                       (1, 2, 1, 1)):
        wire.append(d * (dw * b2) + w * b2 + o if v else -1)
    recv = jnp.array(wire, I32)
    wv = (jnp.arange(recv.shape[0] * 2, dtype=jnp.uint32)
          .reshape(-1, 2) + 7)
    ring0 = jnp.zeros((dw * rcap + 1,), I32)
    wring0 = jnp.zeros((dw * rcap + 1, 2), jnp.uint32)
    cnt0 = jnp.zeros((1, dw), I32)
    fi, fc, fd, fs, fw = fused_recv_land(
        ring0, cnt0, jnp.zeros((), I32), recv, dw=dw, cap=rcap, b=b2,
        words=wv, mail_words=wring0, flags=flags, interpret=interpret)
    rv = recv >= 0
    r = jnp.maximum(recv, 0)
    rd, rw_, ro = r // (dw * b2), (r // b2) % dw, r % b2
    dup = rv & ((flags.at[rd].get() & jnp.uint8(1)) > 0)
    xs = ((rw_[:, None] == jnp.arange(dw, dtype=I32)[None, :])
          & dup[:, None]).sum(axis=0, dtype=I32)
    rv = rv & ~dup
    wvx = jnp.where(rv[:, None], wv, jnp.uint32(0))
    (xi, xw), xc, xd = mb.ring_append(
        (ring0, wring0), cnt0, jnp.zeros((), I32),
        (rd * b2 + ro, wvx), rw_, rv, dw, rcap)
    if not (bool((fi == xi).all()) and bool((fw == xw).all())
            and bool((fc == xc).all()) and int(fd) == int(xd)
            and bool((fs == xs).all())):
        return "fused receive landing diverged from the XLA reference"

    # --- joint deposit vs deposit_local + deposit_rumors ----------------
    bs, nn, rr, kk = 3, 4, 2, 2
    dst = jnp.array([0, 1, 3, 3, 2, 0, 1, 2], I32)
    slots = jnp.array([0, 1, 2, 0, 1, 2, 0, 1], I32)
    valid = jnp.array([1, 1, 0, 1, 1, 1, 0, 1], bool)
    nb = (jnp.arange(nn * rr, dtype=I32).reshape(nn, rr) % 2)
    p0 = jnp.zeros((bs, nn), I32)
    pr0 = jnp.zeros((bs, nn, rr), I32)
    fp, fpr = fused_deposit_both(p0, pr0, dst, slots, valid, nb,
                                 interpret=interpret)
    xp = epidemic.deposit_local(p0, dst, slots, valid)
    xpr = epidemic.deposit_rumors(pr0, dst, slots, valid, nb)
    if not (bool((fp == xp).all()) and bool((fpr == xpr).all())):
        return "fused joint deposit diverged from the XLA reference"

    # --- emission vs an inline replica of the reservation chain ---------
    # (full-system parity against event.append_messages itself is pinned
    # by tests/test_megakernel.py; the probe checks the kernel contract
    # on a case with overflow, duplicates and a dead row.)
    me, ke, dwe, cape, be = 4, 3, 2, 3, 4
    sf = jnp.array([[1, 2, -1], [0, 3, 1], [2, -1, -1], [3, 0, 1]], I32)
    drop = jnp.zeros((me, ke), bool).at[1, 1].set(True)
    sv = jnp.array([1, 1, 0, 1], bool)
    ws = jnp.array([0, 1, 0, 0], I32)
    off = jnp.array([2, 1, 0, 3], I32)
    fl = jnp.array([1, 0, 0, 1], jnp.uint8)
    ring0 = jnp.zeros((dwe * cape + me * ke,), I32)
    cnt0 = jnp.array([[1, 0]], I32)
    fi2, fad, fsu, flo, _ = fused_emit(
        ring0, cnt0, sf, drop, sv, ws, off, dw=dwe, cap=cape, b=be,
        flags=fl, interpret=interpret)
    edge = sv[:, None] & ~drop & (sf >= 0)
    dstf = fl.at[jnp.where(sf >= 0, sf, 0)].get()
    dup = edge & ((dstf & jnp.uint8(1)) > 0)
    dc = dup.sum(axis=1, dtype=I32)
    edge = edge & ~dup
    colsx = jnp.cumsum(edge, axis=1, dtype=I32) - 1
    ec = edge.sum(axis=1, dtype=I32)
    pay = sf * be + off[:, None]
    oh = ((ws[:, None] == jnp.arange(dwe, dtype=I32)[None, :])
          & sv[:, None]).astype(I32)
    xsu = (oh * dc[:, None]).sum(axis=0)
    w = oh * ec[:, None]
    seg = ((jnp.cumsum(w, axis=0) - w) * oh).sum(axis=1)
    base = (cnt0[0][None, :] * oh).sum(axis=1)
    okx = sv & (base + seg + ec <= cape)
    lanes = jnp.arange(me * ke, dtype=I32).reshape(me, ke)
    flat = jnp.where(edge & okx[:, None],
                     ws[:, None] * cape + (base + seg)[:, None] + colsx,
                     dwe * cape + lanes)
    xi2 = ring0.at[flat.reshape(-1)].set(
        jnp.where(edge, pay, 0).reshape(-1), unique_indices=True)
    xad = (w * okx[:, None]).sum(axis=0)
    xlo = (edge & ~okx[:, None]).sum(dtype=I32)
    if not (bool((fi2 == xi2).all()) and bool((fad == xad).all())
            and bool((fsu == xsu).all()) and int(flo) == int(xlo)):
        return "fused emission diverged from the XLA reference"
    return ""


@functools.lru_cache(maxsize=1)
def interpret_unsupported() -> str:
    """'' when every fused megakernel pass runs (and matches XLA) in
    interpret mode on this jax build; else the reason (the CPU-CI
    gate)."""
    try:
        return _probe_case(interpret=True)
    except Exception as e:  # noqa: BLE001 - probe must never raise
        return f"{type(e).__name__}: {e}"


@functools.lru_cache(maxsize=1)
def tpu_unsupported() -> str:
    """'' when the fused passes lower AND pass on-device parity on a real
    TPU backend; else the named reason (the auto gate policy)."""
    if jax.default_backend() != "tpu":
        return ("no TPU backend "
                f"(jax.default_backend()={jax.default_backend()!r})")
    try:
        return _probe_case(interpret=False)
    except Exception as e:  # noqa: BLE001 - probe must never raise
        return f"{type(e).__name__}: {e}"


def kernel_unavailable_reason() -> str:
    """'' when `-phase2-kernel pallas` can run on THIS host (natively on
    TPU, interpret mode elsewhere); else the named reason."""
    if jax.default_backend() == "tpu":
        return tpu_unsupported()
    return interpret_unsupported()
