"""Typed configuration for the TPU-native gossip simulator.

Mirrors the reference CLI flag-for-flag (reference: simulator.go:186-205) and adds
the knobs the TPU framework needs (`backend`, `protocol`, `graph`, `seed`,
`max_rounds`, ...).  Documented divergences from the reference:

* ``fanin`` defaults to *resolved* ``fanout + 1``.  The reference evaluates
  ``Fanout+1`` at flag-registration time, so its fanin default is the constant 6
  regardless of ``-fanout`` (simulator.go:189).  ``compat_reference=True``
  restores the constant-6 behaviour.
* Drop/crash probabilities are exact float Bernoulli draws.  The reference
  truncates to 1% resolution via ``rand.Intn(100) < int(rate*100)``
  (simulator.go:172,180), so its default ``crashrate=0.001`` can never crash.
  ``compat_reference=True`` restores the truncation.
* ``DelayHigh <= DelayLow`` is a validation error here; the reference panics in
  ``rand.Intn`` (simulator.go:167).
* ``max_rounds`` bounds the epidemic phase; the reference spins forever if 99%
  is unreachable (simulator.go:243-251).
"""

from __future__ import annotations

import argparse
import dataclasses
import math
import os
from typing import Optional


def er_cap(lam: float) -> int:
    """Erdős–Rényi friends-column capacity: covers the Poisson(lam) upper
    tail to ~6 sigma (overflow is clipped in degree, probability ~1e-9 per
    node at lam <= 32).  The single source of truth for every ER generator
    (models/graphs.erdos, ops/pallas_graph.erdos_pallas)."""
    return max(1, int(math.ceil(lam + 6.0 * math.sqrt(max(lam, 1.0)) + 4)))

BACKENDS = ("native", "cpp", "jax", "sharded")
PROTOCOLS = ("si", "pushpull", "sir")
GRAPHS = ("overlay", "kout", "erdos", "ring")
TIME_MODES = ("ticks", "rounds")
ENGINES = ("auto", "ring", "event")
# overlay_mode="auto" picks the tick-faithful phase-1 engine up to this n.
# Round 7 raised the band 1M -> 10M: the prefix-dense drain delivery
# (ops.mailbox deliver_pair prefix path -- the drained ring's live
# entries are a sorted PREFIX, so the per-chunk compaction scans that
# dominated the 10M chunk sweep are pure waste) brought the 10M ticks
# build inside the <=2x-rounds-mode budget the fidelity default demands
# (README "Overlay mode at scale"; scripts/profile_overlay.py measures
# the per-chunk scan/sort/scatter constants the raise cites).  Above
# 10M the estimated clock (within ~1 window of true at 1M/10M, r3)
# remains the default and the driver prints the notice.
OVERLAY_TICKS_AUTO_MAX = 10_000_000
# overlay_static_boot="auto" band: at and above this many rows the
# single-device ROUNDS overlay draws the whole initial friends table and
# emits the n*fanout makeup burst at round 0 (the way overlay_ticks.
# init_state always has -- the reference's needNewFriend loop re-arms
# with no delay, simulator.go:103-105, so a node fills all fanout slots
# at t~0 and, once at fanout, can never drop below it again).  A
# deterministic re-choice of the bootstrap schedule, same as the column
# band's arrival-order re-choice: every n below the band is bit-identical
# to round 6; the band sits at the split-round boundary
# (overlay.SPLIT_ROUND_MIN_ROWS) where the staggered per-round burst was
# the measured dominant phase-1 cost.  Module-level so CPU tests can
# lower it and pin both trajectories.
OVERLAY_STATIC_BOOT_MIN_ROWS = 32_000_000
# The auto mailbox cap drops 16 -> 8 at this many local rows (see
# Config.mailbox_cap_for: emission-buffer memory, not overflow risk,
# is what the cap costs at scale).
MAILBOX_CAP_MEMORY_BAND = 32_000_000


@dataclasses.dataclass(frozen=True)
class Config:
    """Full simulation configuration.

    The first seven fields correspond 1:1 to the reference flags
    (simulator.go:187-193, defaults preserved).
    """

    n: int = 50_000
    fanout: int = 5
    fanin: int = -1  # -1 -> resolved fanout + 1 (see module docstring)
    delaylow: int = 10  # ms (one simulated tick == 1 ms)
    delayhigh: int = 20  # ms, exclusive upper bound like rand.Intn
    droprate: float = 0.1
    crashrate: float = 0.001

    # --- framework extensions -------------------------------------------------
    backend: str = "jax"
    protocol: str = "si"
    graph: str = "overlay"
    seed: int = 0
    max_rounds: int = 100_000
    coverage_target: float = 0.99  # reference stops at >=99% (simulator.go:248)
    # "ticks": 1 round == 1 simulated ms; messages carry uniform[delaylow,
    # delayhigh) delivery delays through a ring buffer (faithful to the
    # reference's time-to-99% semantics).  "rounds": synchronous rounds, one
    # hop per round (classic epidemic-rounds accounting; faster).
    time_mode: str = "ticks"
    # SIR removal probability (config 4 in BASELINE.json); ignored otherwise.
    removal_rate: float = 0.1
    # Erdos-Renyi edge probability; -1 -> fanout/n (expected degree == fanout).
    er_p: float = -1.0
    # Reproduce reference quirks (1%-resolution Bernoulli, constant fanin
    # default, seed node never counted as received: simulator.go:240-241).
    compat_reference: bool = False
    # Mailbox / exchange capacities (see ops/mailbox.py).  -1 -> auto.
    mailbox_cap: int = -1
    # Use the Pallas TPU-PRNG graph generator (ops/pallas_graph.py) for the
    # kout graph: same distribution, different stream, much faster at 100M.
    pallas: bool = False
    # Wavefront compaction: gather only actual senders' edges before the
    # scatter/route (identical results while no exchange overflow occurs --
    # overflow is counted, never silent; big win in ticks mode where the
    # per-tick wave is a small fraction of n).  "auto" = on for ticks mode.
    compact: str = "auto"
    # Compaction chunk size override (-1 = auto: n_local//128, min 4096; see
    # epidemic.compact_chunk_cap).  Exposed mainly so tests can force the
    # multi-chunk path at small n.
    compact_chunk: int = -1
    # Epidemic engine (jax + sharded backends): "ring" keeps per-(slot,
    # node) arrival counts (O(n) per tick); "event" keeps per-slot message
    # id-lists (O(arrivals) per tick -- models/event.py and
    # parallel/event_sharded.py).  "auto" = event for SI and (round 5)
    # SIR in ticks mode on the jax/sharded backends (unless compact is
    # explicitly set, a ring-engine request), ring otherwise.
    engine: str = "auto"
    # Event engine per-WINDOW-slot message capacity (-1 = auto: see
    # event.slot_cap -- 1.5*n*mean_degree*B/delay_span, bounded by the SI
    # message total and int32 flat addressing; overflow is counted in
    # Stats.mailbox_dropped, never silent).
    event_slot_cap: int = -1
    # Event engine drain chunk size (-1 = auto: 524288; see
    # event.drain_chunk).
    event_chunk: int = -1
    # Guaranteed-duplicate suppression at append (event engine): an edge
    # whose destination already has the received bit -- monotone, so it is
    # STILL set at delivery -- can only increment total_message there
    # (simulator.go:111,117-119); with an effective crash rate of 0 there
    # is not even a crash draw.  Suppression never writes such edges into
    # the mail ring (~4.8x of endgame traffic at fanout 6); their counts
    # are BANKED per arrival window in EventState.sup_cnt at append time
    # and credited into total_message when that window drains -- the
    # exact step their deliveries would have counted -- so every
    # per-window observable (stdout, JSONL, death tick), not just the
    # final totals, is bit-identical to the unsuppressed path
    # (A/B-tested).  On the sharded backend the filter runs pre-exchange
    # for locally-owned destinations and on the receiving shard for
    # routed ones (parallel/event_sharded._route_and_append), with the
    # same deferred crediting.  "auto" = on iff the EFFECTIVE crash rate
    # is 0: that is
    # crashrate 0, or any crashrate < 0.01 under -compat-reference
    # (whose 1%-resolution Bernoulli truncates the reference's own
    # 0.001 default to 0, simulator.go:180).  Plain crashrate 0.001
    # WITHOUT compat is an exact-float 0.1% crash rate here and keeps
    # suppression off -- pass -crashrate 0 (or -compat-reference) to
    # engage it.  "on" errors when crash_p > 0: per-reception crash
    # draws are keyed by mailbox position, so removing entries would
    # shift every later draw.
    dup_suppress: str = "auto"
    # Phase-1 overlay timing (graph=overlay): "rounds" batches membership
    # into synchronous rounds, delivering every emission exactly one round
    # later and ESTIMATING stabilization time as rounds x mean_delay;
    # "ticks" keeps the reference's per-message uniform delays through a
    # packed window-slot ring (models/overlay_ticks.py, sharded variant
    # parallel/overlay_ticks_sharded.py) so the stabilization clock is
    # true simulated ms (simulator.go:151-168).  "auto" (default)
    # size-bands: ticks at n <= 1e6 -- the reference's default n=50000
    # lands there and the faithful engine costs little at that scale --
    # rounds above, where ticks costs 3-4x more and the estimated clock
    # measured within ~1 window of true (r3: 380 true vs 390 estimated ms
    # at 1M, 400 vs 405 at 10M); a one-line notice marks the estimate.
    # native/cpp are inherently faithful (discrete-event) and ignore the
    # flag.
    overlay_mode: str = "auto"
    # --- phase-1 speed-round gates (round 7; rounds overlay engine) ----------
    # Occupancy-adaptive hosted-delivery chunk schedule (split-round band
    # only): the hosted column delivery picks its per-row chunk width from
    # a ladder sized to the row's live emission count -- one narrow chunk
    # for settled rows, few fat chunks for the dense burst rows whose
    # per-chunk flat-scatter floors dominated the 100M build (chunking is
    # trajectory-neutral: ascending ranges + rank continuation are
    # bit-identical at ANY chunk, ops/mailbox.deliver).  "auto" = on.
    overlay_adaptive_chunks: str = "auto"
    # Dead-emission-row skip (split-round band only): the round pieces
    # count each emission slot's entries AT EMISSION TIME (a scalar per
    # processed slot) and the hosted delivery skips zero rows without the
    # n-wide popcount each row otherwise costs -- once membership settles,
    # ~16 of 17 rows are dead every round at 100M.  The same counts feed a
    # scalar quiescence check, replacing the per-round (cap, n) emission-
    # mask reductions.  Trajectory-neutral (the counts equal the masks'
    # sums exactly; A/B-pinned).  "auto" = on.
    overlay_dead_skip: str = "auto"
    # One-shot static bootstrap (see OVERLAY_STATIC_BOOT_MIN_ROWS): "auto"
    # size-bands (on at >= the band, off below -- bit-identical to round 6
    # below it); "on"/"off" force either schedule at any n.  Changes the
    # membership trajectory above the band (a deterministic re-choice of
    # the bootstrap schedule, strictly CLOSER to the reference's burst);
    # "off" reproduces the pre-round-7 staggered schedule exactly.
    overlay_static_boot: str = "auto"
    # Delivery kernel for the mailbox sort/rank/scatter chain (ROADMAP
    # item 5): "pallas" runs the fused single-pass kernels
    # (ops/pallas_deliver -- natively on TPU, interpret mode elsewhere;
    # bit-identical mailboxes/counts/drops, A/B-pinned by trajectory
    # fingerprints); "xla" is the recorded sort + segment-rank + scatter
    # chain and reproduces every prior trajectory bit-for-bit; "auto"
    # picks pallas only when the one-shot TPU capability probe passes
    # on-device parity, else xla with a named reason
    # (deliver_kernel_fallback_reason).
    deliver_kernel: str = "auto"
    # Phase-2 megakernel for the emit->route->deliver window (ROADMAP
    # item 5, against the committed ROOFLINE.json floors): "pallas" runs
    # the fused single-pass kernels (ops/pallas_megakernel -- emission
    # mask/prefix/scatter, sharded receive landing, pushsum whole-slot
    # drain, joint multi-rumor deposit; natively on TPU, interpret mode
    # elsewhere; bit-identical, A/B-pinned by trajectory fingerprints);
    # "xla" is the recorded multi-op chain and reproduces every prior
    # trajectory bit-for-bit; "auto" picks pallas only when the one-shot
    # TPU capability probe passes on-device parity, else xla with a
    # named reason (phase2_kernel_fallback_reason).  Orthogonal to
    # -deliver-kernel: where the megakernel engages it subsumes that
    # gate's fused ops; everywhere else -deliver-kernel still applies.
    phase2_kernel: str = "auto"
    # Phase-1 overlay megakernel for the request->negotiate->reply chain
    # (against ROOFLINE.json's phase-1 terms): "pallas" runs the fused
    # single-pass kernels (ops/pallas_overlay_kernel -- slot negotiation
    # with its decision masks/draw blends/reply emission in-register,
    # bootstrap request append with write-time dead-skip counts, hosted
    # ladder occupancy; natively on TPU, interpret mode elsewhere;
    # bit-identical, A/B-pinned by trajectory fingerprints); "xla" is the
    # recorded one-hot op chain and reproduces every prior trajectory
    # bit-for-bit; "auto" picks pallas only when the one-shot TPU
    # capability probe passes on-device parity, else xla with a named
    # reason (phase1_kernel_fallback_reason).  Orthogonal to
    # -deliver-kernel: the delivery chain keeps its own gate; this one
    # owns the negotiation passes around it.
    phase1_kernel: str = "auto"
    # Exchange pipelining for the sharded backend (ROADMAP item 1):
    # "double" software-pipelines the per-chunk all_to_all at chunk
    # granularity -- the ring_append drain of batch j is deferred one
    # batch behind the route, so XLA's async collective scheduler can
    # hoist batch j+1's all_to_all dispatch above batch j's drain.
    # Trajectory-preserving by construction (the dup verdict is still
    # computed at the serial program point; only the append is staged,
    # and in-window appends always target later windows), so "double"
    # is bit-identical to "off".  "off" runs the serial route->drain
    # chunk loop and reproduces pre-pipeline trajectories bit-for-bit;
    # "auto" picks double on multi-device meshes and off elsewhere
    # (S=1 skips the collective entirely, nothing to overlap).
    exchange_pipeline: str = "auto"
    # Emit a TensorBoard trace of the epidemic phase.
    profile: bool = False
    profile_dir: str = "/tmp/gossip-trace"
    # --- flight recorder (utils/trace.py, utils/artifact.py) -----------------
    # Host-side span trace (compile, phase-1 rounds, phase-2 windows,
    # checkpoint save/load, sharded exchange) as Chrome trace-event JSON
    # to this path.  Pure host-side observability: the traced jitted
    # programs are unchanged, so trajectories stay bit-identical.
    trace: str = ""
    # jax.profiler device profile wrapping the whole run, with a
    # TraceAnnotation per host span so the TensorBoard device timeline
    # lines up with the -trace spans (unlike -profile, which wraps only
    # phase 2 and carries no span names).
    xprof_dir: str = ""
    # Write a self-describing run artifact here: config snapshot +
    # resolved gates, platform/env fingerprint, JSONL metrics, telemetry
    # histories (npz), trace file, final Stats and the trajectory
    # fingerprint.  scripts/compare_runs.py diffs two of these.
    run_dir: str = ""
    # Append structured JSONL records (params, per-window progress, totals,
    # wall-clock) to this path, alongside the reference-format stdout.
    log_jsonl: str = ""
    # Checkpoint every k rounds to this directory (0 = off).
    checkpoint_every: int = 0
    checkpoint_dir: str = ""
    # Resume the epidemic phase from the latest snapshot in checkpoint_dir
    # (jax backend; skips overlay construction and seeding).
    resume: bool = False
    progress: bool = True  # print reference-format progress lines
    # Multi-host SPMD (backend=sharded): every participating process runs
    # the same CLI with its own -process-id; jax.distributed wires them
    # into one global device mesh (ICI within a slice, DCN across), the
    # node axis shards over ALL processes' devices, and only process 0
    # prints.  Empty coordinator/counts fall back to jax's automatic
    # detection (TPU pod environments set them via the runtime).
    distributed: bool = False
    coordinator: str = ""  # e.g. "host0:1234"
    num_processes: int = -1  # -1 = auto-detect
    process_id: int = -1  # -1 = auto-detect
    # --- host-loss supervision (ISSUE 20; gossip_simulator_tpu/distributed/) --
    # -supervise turns host death into a recoverable event.  Without
    # -coordinator it is the single-process drillable mode: the live mesh's
    # devices are partitioned into -workers logical workers, heartbeat
    # beacons are stamped per window, and a detected loss tears the state
    # down and restores the last provenance-checked snapshot onto the
    # survivors (distributed/supervisor.py run_supervised).  With
    # -coordinator it is the real flavor: the supervisor process spawns
    # -workers CLI worker processes joined via jax.distributed, monitors
    # exits + wall-clock beacon staleness, and relaunches survivors with
    # -resume.  Requires checkpointing (recovery restores the last atomic
    # snapshot).
    supervise: bool = False
    workers: int = 2
    # Liveness beacon directory ("" = <checkpoint_dir>/heartbeats).  Each
    # worker stamps worker_<rank>.json atomically once per poll window.
    heartbeat_dir: str = ""
    # Loss verdict threshold: a worker whose beacon lags this far behind
    # (wall-clock in the real supervisor; the WINDOW_MS-scaled window lag
    # in the drillable mode) is declared lost.
    heartbeat_timeout_ms: int = 30000
    # Injected host-loss drill: "kill-worker@W[:K]" / "stall-worker@W[:K]"
    # kills (or silences the beacon of) worker W at gossip window K
    # (default 6).  Requires -supervise; parse_chaos() below.
    chaos: str = ""
    # Recovery staleness gate: refuse to restore a snapshot more than this
    # many windows behind the loss point (0 = no limit).  See
    # utils/checkpoint.py verify_provenance.
    recover_max_stale: int = 0
    # Provenance token stamped into snapshot sidecars; recovery refuses a
    # snapshot from a different run.  "" = generate one per run (the
    # supervisor passes its own to every worker so relaunched survivors
    # adopt the original run's snapshots).
    run_id: str = ""
    # Bounded jax.distributed.initialize: per-attempt timeout in seconds
    # (3 exponential-backoff attempts; parallel/mesh.py bounded_initialize).
    init_timeout_s: int = 60
    # Device-resident per-window telemetry (utils/telemetry.py): the fast-
    # path while_loops record the full per-window trajectory on device and
    # the driver replays it through the printer afterward -- so a progress-
    # printing or JSONL-logging run takes the fast path whenever
    # checkpointing is off.  "off" restores the old gating (observing runs
    # pay the windowed host loop).  jax/sharded backends only; the
    # discrete-event oracles have no device loop to instrument.
    telemetry: str = "on"
    # Spatial telemetry panels (utils/telemetry.py spatial_*): per-group /
    # per-shard per-window panels plus the exchange traffic matrix,
    # recorded on device next to the scalar history and fetched in the
    # same single transfer.  npz-only (run dirs / utils/health.py): the
    # stdout/JSONL surface is byte-identical on and off, and "off" traces
    # the pre-spatial program (trajectory pins in tests/test_spatial.py).
    # Requires -telemetry on; jax/sharded backends only.
    telemetry_spatial: str = "off"
    # --- fault-injection scenario (gossip_simulator_tpu/scenario.py) --------
    # "off" (default: traced programs identical to a scenario-less build),
    # a path to a JSON timeline, or the JSON inline.  Schedules crash
    # waves, steady churn, node recovery after `downtime` ms, and
    # partition masks over simulated time; jax/sharded backends, SI/SIR
    # ticks-and-rounds epidemics.  Draws are (window, GLOBAL-id)-keyed so
    # trajectories are shard-count invariant and survive reshard-resume.
    scenario: str = "off"
    # Overlay self-healing during phase 2: every poll window, live nodes
    # replace friends that have been dead >= heal_detect_ms (the windowed
    # failed-delivery detection -- a dead friend black-holes every send,
    # so detect_ms models how long the sender's delivery accounting takes
    # to condemn it) with a fresh uniform peer, re-entering the phase-1
    # makeup draw (overlay.heal_dead_friends); infected healers re-send
    # the rumor over the repaired edge (the rejoin anti-entropy that lets
    # recovered nodes catch up).  Works on any friends-table graph.
    overlay_heal: str = "off"
    heal_detect_ms: int = 30
    # Print the end-of-run telemetry block (phase breakdown, throughput).
    telemetry_summary: bool = False
    # --- multi-rumor traffic (ISSUE 8) ---------------------------------------
    # Number of concurrent rumors sharing the dissemination substrate.  R=1
    # (default) keeps every legacy single-rumor code path byte-for-byte: the
    # rumor axis only materializes when multi_rumor resolves True.  R>1 adds
    # a packed uint32 word ladder (W = ceil(R/32) words per node / per mail
    # entry) and first-touch-wins becomes a per-rumor bitwise OR fold over
    # the SAME mailbox/sort/rank/flat-scatter machinery -- no per-rumor loop.
    rumors: int = 1
    # "oneshot": all R rumors injected at tick 0 at R random sources, the run
    # ends when every rumor reaches coverage_target (the classic wall-time
    # measurement, now R-wide).  "stream": rumors are injected continuously
    # from a jitted schedule at `stream_rate` rumors per 1000 simulated ms
    # until all `rumors` are in flight; Stats/telemetry report steady-state
    # throughput (rumors/s reaching the target, deliveries/s) instead of a
    # single one-shot wall time.
    traffic: str = "oneshot"
    # Streaming injection rate: rumors per 1000 simulated ms (>= 1).  Rumor
    # r is injected at tick r * 1000 // stream_rate at a derived-key uniform
    # source, shard-count invariantly.
    stream_rate: int = 100
    # --- elastic serving mode (ISSUE 11; gossip_simulator_tpu/serve.py) ------
    # Long-lived serving loop: watches mail-ring occupancy against the
    # watermarks below and reshards (checkpoint -> wider/narrower mesh ->
    # resume) without dropping in-flight rumors.  Requires -traffic stream
    # on the event engine (jax/sharded backends).
    serve: bool = False
    # Arrival process shaping the per-rumor injection schedule (stream
    # traffic): "fixed" keeps the analytic r * 1000 // stream_rate ladder
    # (bit-identical to the pre-serve build); "poisson" draws exponential
    # inter-arrivals with mean 1000/stream_rate; "burst" releases groups
    # of 8 rumors at group boundaries; "diurnal" modulates the rate with a
    # sinusoidal load curve.  All schedules are precomputed host-side from
    # (arrivals, stream_rate, rumors, seed) alone -- keyed by rumor index,
    # so they are shard-count invariant and reshard-resume safe.
    arrivals: str = "fixed"
    # Autoscaler watermarks: mail-ring occupancy fractions (high-water
    # entries / slot capacity).  serve_window consecutive windows above
    # serve_high trip a widen; the same below serve_low trip a narrow.
    serve_high: float = 0.85
    serve_low: float = 0.10
    serve_window: int = 3
    # Shard-count band for the autoscaler.  -1 = all visible devices.
    serve_min_shards: int = 1
    serve_max_shards: int = -1
    # Deterministic transition override for CI: "S@W[,S@W...]" reshards to
    # S shards at serve window W regardless of occupancy (e.g. "8@4,1@10"
    # forces one widen and one narrow).  Empty = telemetry-driven.
    serve_force: str = ""
    # Admission-control backoff cap (simulated ms): when the widest mesh
    # is still saturated, pending injections are deferred by a doubling
    # backoff capped here, counted in Stats.shed, and never dropped.
    serve_max_defer: int = 2000
    # Checkpoint retention: after each successful save keep only the
    # newest K snapshots (sha256 sidecars pruned with them).  0 = keep all.
    ckpt_keep: int = 0
    # Internal: explicit per-rumor injection-tick override (sorted tuple,
    # len == rumors).  Set by serve's admission control when it defers
    # pending injections; not a CLI flag.
    inject_ticks: Optional[tuple] = None
    # --- tuning table (ISSUE 12; gossip_simulator_tpu/tuning.py) -------------
    # Per-platform tuned-constant table produced by scripts/autotune.py:
    # "auto" consults the committed TUNING_TABLE.json when present, "off"
    # forces registered defaults, a path loads that table.  Resolution
    # order per tunable: explicit CLI flag (-compact-chunk, -event-chunk,
    # -event-slot-cap) > table entry > registered default; the active
    # entry id (or "defaults") is stamped into resolved_gates().
    tuning_table: str = "auto"
    # --- numeric gossip (ISSUE 14; models/pushsum.py) -------------------------
    # Model family: "si" is the reference's 1-bit infection; "pushsum" runs
    # Kempe-style PushSum averaging -- every node carries a (value, weight)
    # mass vector in 64-bit fixed point (exact integer limbs, so shard-count
    # invariance and conservation hold bit-exactly), keeps ceil(half) each
    # window and pushes the rest split over its friends through the same
    # mail ring / all_to_all; delivery is a commutative scatter-ADD instead
    # of the SI first-touch-wins OR.  Convergence: max over live nodes of
    # the relative error of value/weight vs the true network mean.
    model: str = "si"
    # PushSum payload dimensionality (value vector length, 1..8).
    pushsum_dim: int = 2
    # Convergence threshold: the run completes when every live node's
    # estimate is within this relative error of the true mean.
    pushsum_eps: float = 1e-3

    # --- derived --------------------------------------------------------------
    @property
    def fanin_resolved(self) -> int:
        if self.fanin >= 0:
            return self.fanin
        return 6 if self.compat_reference else self.fanout + 1

    @property
    def max_degree(self) -> int:
        """Friend-list capacity.

        A node's list grows by its own bootstrap (up to fanout,
        simulator.go:95-106) and by accepted makeups (up to fanin,
        simulator.go:66-75); eviction keeps it at fanin once saturated.
        """
        return max(self.fanout, self.fanin_resolved)

    @property
    def delay_span(self) -> int:
        return self.delayhigh - self.delaylow

    @property
    def er_p_resolved(self) -> float:
        return self.er_p if self.er_p > 0 else self.fanout / max(self.n, 1)

    @property
    def graph_width(self) -> int:
        """Actual friends-table column count for this config's graph: the
        Erdős–Rényi generators pad to the Poisson tail cap (er_cap), which
        can be ~3x max_degree.  This bounds a single sender's reservation;
        aggregate in-flight sizing uses mean_degree (reservations are
        exact-size, so padding never reaches the mail ring)."""
        if self.graph == "erdos":
            return er_cap(self.er_p_resolved * self.n)
        return self.max_degree

    @property
    def mean_degree(self) -> float:
        """Expected out-degree -- the right per-node in-flight budget for
        the event engine's exact-size mail reservations (event.slot_cap)."""
        if self.graph == "erdos":
            return self.er_p_resolved * self.n
        return float(self.max_degree)

    @property
    def crashrate_eff(self) -> float:
        """Effective per-reception crash probability (the compat gate's
        1%-resolution truncation applied -- simulator.go:180; mirrors
        epidemic.p_eff, which models/ keep for jit-time constants)."""
        if self.compat_reference:
            return int(self.crashrate * 100) / 100.0
        return self.crashrate

    @property
    def scenario_resolved(self):
        """Parsed fault-injection Scenario (scenario.OFF when "off") --
        module-cached, so the jitted closures all see one object."""
        from gossip_simulator_tpu import scenario as _scen

        return _scen.parse(self.scenario)

    @property
    def overlay_heal_resolved(self) -> bool:
        return self.overlay_heal == "on"

    @property
    def faults_enabled(self) -> bool:
        """Whether the phase-2 steps carry the fault machinery (the
        per-node down_since crash clock and the scenario tick): scenario
        crash/churn/recovery events, or healing -- whose dead-friend
        detection reads the same clock."""
        return (self.scenario_resolved.has_faults
                or self.overlay_heal_resolved)

    @property
    def dup_suppress_resolved(self) -> bool:
        """Whether the event engine suppresses guaranteed-duplicate edges
        at append (see the `dup_suppress` field comment).  Only sound at
        crash_p == 0; validate() rejects an explicit "on" otherwise.
        Scenario faults also force it off: a suppressed edge's count is
        credited assuming delivery to a live node, but a scenario crash
        can black-hole the destination between append and delivery --
        the unsuppressed path would then NOT count it."""
        if self.dup_suppress == "off":
            return False
        if self.scenario_resolved.has_faults:
            return False
        if self.multi_rumor:
            # The "guaranteed duplicate" predicate (destination's received
            # bit already set -- monotone) no longer implies zero-information
            # delivery: an infected node can still gain NEW rumor bits from
            # the entry's payload word.  validate() rejects an explicit "on".
            return False
        return self.crashrate_eff == 0.0

    @property
    def multi_rumor(self) -> bool:
        """Whether the rumor axis materializes (R > 1, or stream traffic --
        a stream of 1 still needs per-rumor accounting).  Python-static: the
        default single-rumor configuration never traces a rumor-axis op."""
        return self.rumors > 1 or self.traffic == "stream"

    @property
    def rumor_word_count(self) -> int:
        """uint32 words in the packed rumor ladder (W = ceil(R/32))."""
        return (self.rumors + 31) // 32 if self.multi_rumor else 1

    @property
    def last_inject_tick(self) -> int:
        """Tick of the final rumor's injection under stream traffic
        (rumor r enters at r * 1000 // stream_rate on the fixed schedule;
        non-fixed arrivals and serve deferrals consult the precomputed
        arrival table); 0 for oneshot."""
        if self.traffic != "stream":
            return 0
        from gossip_simulator_tpu import arrivals as _arrivals

        table = _arrivals.table_or_none(self)
        if table is not None:
            return int(table[-1])
        return (self.rumors - 1) * 1000 // max(self.stream_rate, 1)

    @property
    def effective_time_mode(self) -> str:
        """Push-pull anti-entropy is a synchronous per-round protocol; it always
        runs (and is budgeted) in rounds mode regardless of `time_mode`."""
        return "rounds" if self.protocol == "pushpull" else self.time_mode

    @property
    def checkpointing_enabled(self) -> bool:
        """Snapshots can actually be written: BOTH -checkpoint-every and
        -checkpoint-dir are set.  THE predicate for every gate that trades
        the fast paths for per-window observability (driver phase-1/2
        gates, _Checkpointer._due) -- they drifted when each spelled it
        out (advisor r4)."""
        return bool(self.checkpoint_every and self.checkpoint_dir)

    @property
    def heartbeat_dir_resolved(self) -> str:
        """Where liveness beacons live: the explicit -heartbeat-dir, else a
        heartbeats/ subdir of the checkpoint dir (supervision requires
        checkpointing, so the fallback always resolves under -supervise)."""
        if self.heartbeat_dir:
            return self.heartbeat_dir
        if self.checkpoint_dir:
            return os.path.join(self.checkpoint_dir, "heartbeats")
        return ""

    @property
    def telemetry_enabled(self) -> bool:
        """Whether the device-side loops record per-window history (see the
        `telemetry` field): jax/sharded only -- the oracles' windowed loop
        IS their only loop."""
        return self.telemetry != "off" and self.backend in ("jax", "sharded")

    @property
    def telemetry_spatial_enabled(self) -> bool:
        """Whether the device-side loops also record the spatial panels
        (per-group / per-shard / traffic-matrix histories).  Rides the
        telemetry fast path, so it inherits telemetry_enabled's gating."""
        return self.telemetry_spatial == "on" and self.telemetry_enabled

    @property
    def overlay_mode_resolved(self) -> str:
        """Size-banded 'auto' resolution (see the field comment): ticks at
        n <= OVERLAY_TICKS_AUTO_MAX on tick-semantics runs, rounds
        otherwise (the ticks overlay engine needs -time-mode ticks)."""
        if self.overlay_mode != "auto":
            return self.overlay_mode
        if (self.backend in ("jax", "sharded")
                and self.effective_time_mode != "ticks"):
            return "rounds"
        from gossip_simulator_tpu import tuning as _tuning

        band = _tuning.value("config.overlay_ticks_auto_max", self,
                             default=OVERLAY_TICKS_AUTO_MAX)
        return "ticks" if self.n <= band else "rounds"

    @property
    def overlay_adaptive_chunks_resolved(self) -> bool:
        return self.overlay_adaptive_chunks != "off"

    @property
    def overlay_dead_skip_resolved(self) -> bool:
        return self.overlay_dead_skip != "off"

    @property
    def deliver_kernel_resolved(self) -> str:
        """"xla" or "pallas" -- resolved LAZILY (first model-build time,
        after jaxsetup.setup(); validate() must not import jax).  Explicit
        "pallas" raises with the probe's named reason when this host
        cannot run the kernels at all (broken interpret build, or TPU
        lowering/parity failure on a TPU host); "auto" enables pallas
        only on TPU hosts that pass the on-device parity probe -- CPU
        hosts stay on xla because the interpret-mode kernels are a
        correctness/CI surface, not a fast path."""
        if self.deliver_kernel == "xla":
            return "xla"
        from gossip_simulator_tpu.ops import pallas_deliver
        if self.deliver_kernel == "pallas":
            why = pallas_deliver.kernel_unavailable_reason()
            if why:
                raise ValueError(
                    f"-deliver-kernel pallas is unavailable on this host: "
                    f"{why} (use -deliver-kernel xla or auto)")
            return "pallas"
        return "xla" if pallas_deliver.tpu_unsupported() else "pallas"

    @property
    def deliver_kernel_fallback_reason(self) -> str:
        """Non-empty iff `-deliver-kernel auto` resolved to xla: the
        probe's named reason (e.g. 'no TPU backend (...)'), surfaced by
        the driver so the fallback is never silent."""
        if self.deliver_kernel != "auto":
            return ""
        from gossip_simulator_tpu.ops import pallas_deliver
        return pallas_deliver.tpu_unsupported()

    @property
    def phase2_kernel_resolved(self) -> str:
        """"xla" or "pallas" -- the megakernel twin of
        deliver_kernel_resolved (same lazy policy: explicit "pallas"
        raises the probe's named reason when this host cannot run the
        fused passes, "auto" enables pallas only on TPU hosts that pass
        the on-device parity probe; CPU interpret mode is a CI
        correctness surface, not a fast path)."""
        if self.phase2_kernel == "xla":
            return "xla"
        from gossip_simulator_tpu.ops import pallas_megakernel
        if self.phase2_kernel == "pallas":
            why = pallas_megakernel.kernel_unavailable_reason()
            if why:
                raise ValueError(
                    f"-phase2-kernel pallas is unavailable on this host: "
                    f"{why} (use -phase2-kernel xla or auto)")
            return "pallas"
        return "xla" if pallas_megakernel.tpu_unsupported() else "pallas"

    @property
    def phase2_kernel_fallback_reason(self) -> str:
        """Non-empty iff `-phase2-kernel auto` resolved to xla: the
        probe's named reason, surfaced by the driver so the fallback is
        never silent."""
        if self.phase2_kernel != "auto":
            return ""
        from gossip_simulator_tpu.ops import pallas_megakernel
        return pallas_megakernel.tpu_unsupported()

    @property
    def phase1_kernel_resolved(self) -> str:
        """"xla" or "pallas" -- the phase-1 overlay twin of
        phase2_kernel_resolved (same lazy policy: explicit "pallas"
        raises the probe's named reason when this host cannot run the
        fused passes, "auto" enables pallas only on TPU hosts that pass
        the on-device parity probe; CPU interpret mode is a CI
        correctness surface, not a fast path)."""
        if self.phase1_kernel == "xla":
            return "xla"
        from gossip_simulator_tpu.ops import pallas_overlay_kernel
        if self.phase1_kernel == "pallas":
            why = pallas_overlay_kernel.kernel_unavailable_reason()
            if why:
                raise ValueError(
                    f"-phase1-kernel pallas is unavailable on this host: "
                    f"{why} (use -phase1-kernel xla or auto)")
            return "pallas"
        return "xla" if pallas_overlay_kernel.tpu_unsupported() else "pallas"

    @property
    def phase1_kernel_fallback_reason(self) -> str:
        """Non-empty iff `-phase1-kernel auto` resolved to xla: the
        probe's named reason, surfaced by the driver so the fallback is
        never silent."""
        if self.phase1_kernel != "auto":
            return ""
        from gossip_simulator_tpu.ops import pallas_overlay_kernel
        return pallas_overlay_kernel.tpu_unsupported()

    @property
    def exchange_pipeline_resolved(self) -> str:
        """"off" or "double" -- resolved LAZILY (first model-build time,
        after jaxsetup.setup(); validate() must not import jax).
        Explicit off/double pass through; "auto" picks double only on a
        multi-device mesh -- at S=1 the exchange is an identity (no
        collective in the program), so there is nothing to overlap and
        the serial loop is already optimal.  The engines additionally
        run serial at S=1 even under a forced "double" (trivially
        identical: the pipelined loop with no collective is the serial
        loop plus a no-op staging buffer)."""
        if self.exchange_pipeline in ("off", "double"):
            return self.exchange_pipeline
        import jax

        return "double" if len(jax.devices()) > 1 else "off"

    @property
    def tuning_entry_resolved(self) -> str:
        """Active tuning-table entry id(s, "+"-joined when several
        spaces match), or "defaults" -- resolved LAZILY
        (table matching keys on the jax platform fingerprint, so the
        lookup happens post-setup like deliver_kernel_resolved; validate()
        must not import jax).  Never raises: any table-resolution error
        degrades to "defaults", the values the run would use anyway."""
        from gossip_simulator_tpu import tuning

        return tuning.entry_id(self)

    def resolved_gates(self) -> dict:
        """The resolved gate set, stamped into run artifacts and the
        terminal `result` record so a trajectory is attributable without
        re-deriving auto resolutions from argv.  deliver_kernel resolves
        lazily via the jax capability probe, so it is only consulted on
        the jax/sharded backends (post-setup); the oracles report None.
        Safe to call on any validated config -- an unavailable explicit
        `-deliver-kernel pallas` reports "unavailable" rather than
        raising (the run itself raises at model-build time).  Only
        TRAJECTORY-affecting gates belong here: observability toggles
        (telemetry, checkpointing) are excluded on purpose so a
        telemetry-on/off twin pair's `result` records stay
        field-identical (the fast-path replay parity tests compare
        them)."""
        gates = {
            "engine": self.engine_resolved,
            "overlay_mode": self.overlay_mode_resolved,
            "compact": self.compact_resolved,
            "overlay_adaptive_chunks": self.overlay_adaptive_chunks_resolved,
            "overlay_dead_skip": self.overlay_dead_skip_resolved,
            "overlay_heal": self.overlay_heal_resolved,
            "dup_suppress": self.dup_suppress_resolved,
            "multi_rumor": self.multi_rumor,
            "time_mode": self.effective_time_mode,
            "model": self.model,
        }
        if self.backend in ("jax", "sharded"):
            try:
                gates["deliver_kernel"] = self.deliver_kernel_resolved
            except ValueError:
                gates["deliver_kernel"] = "unavailable"
            try:
                gates["phase2_kernel"] = self.phase2_kernel_resolved
            except ValueError:
                gates["phase2_kernel"] = "unavailable"
            try:
                gates["phase1_kernel"] = self.phase1_kernel_resolved
            except ValueError:
                gates["phase1_kernel"] = "unavailable"
        else:
            gates["deliver_kernel"] = None
            gates["phase2_kernel"] = None
            gates["phase1_kernel"] = None
        # Exchange pipelining only exists on the sharded backend's
        # routed path; everywhere else there is no exchange to overlap.
        gates["exchange_pipeline"] = (
            self.exchange_pipeline_resolved
            if self.backend == "sharded" else "off")
        # The active tuning-table entry ids ("defaults" when no table
        # matches): a table CAN carry trajectory-affecting values (it is
        # reviewed, committed data -- autotune itself persists only
        # contract-neutral tunables band-wide, and gate-validated ones
        # behind a matching workload-shape key), so compare_runs names a
        # mismatch here as the first divergence suspect.
        gates["tuning_table"] = self.tuning_entry_resolved
        # The active compile-budget pin ("none" when absent): the budget
        # cannot move a trajectory, but a RETRACE regression it would
        # have caught can hide behind one -- so compare_runs names a
        # stale budget id right next to the tuning-table id when
        # fingerprints diverge.  Never raises (budget_id degrades to
        # "none"); pure-stdlib, so validate()'s no-jax rule holds.
        from gossip_simulator_tpu.analysis import runtime as _rt

        gates["compile_budget"] = _rt.budget_id()
        return gates

    @property
    def log_jsonl_resolved(self) -> str:
        """JSONL destination: an explicit -log-jsonl wins; otherwise a
        -run-dir run logs into its own artifact (metrics.jsonl) so the
        dir is complete without extra flags."""
        if self.log_jsonl:
            return self.log_jsonl
        if self.run_dir:
            import os

            return os.path.join(self.run_dir, "metrics.jsonl")
        return ""

    @property
    def trace_resolved(self) -> str:
        """Trace destination: explicit -trace wins; a -run-dir run traces
        into its artifact by default (host-side only -- the traced jitted
        programs are unchanged either way)."""
        if self.trace:
            return self.trace
        if self.run_dir:
            import os

            return os.path.join(self.run_dir, "trace.json")
        return ""

    def static_boot_for(self, n_rows: int) -> bool:
        """One-shot static bootstrap for a ROUNDS-overlay surface of
        `n_rows` rows (single-device engine only; the sharded hook path
        keeps the per-round schedule -- its routed init has no burst
        delivery and its per-shard slices sit below the band anyway)."""
        if self.overlay_static_boot != "auto":
            return self.overlay_static_boot == "on"
        return n_rows >= OVERLAY_STATIC_BOOT_MIN_ROWS

    @property
    def compact_resolved(self) -> bool:
        if self.compact == "auto":
            return (self.effective_time_mode == "ticks"
                    and self.protocol != "pushpull")
        return self.compact == "on"

    @property
    def engine_resolved(self) -> str:
        """Event engine requires SI/SIR + ticks semantics on the jax or
        sharded backend; everything else uses the ring engine.  Auto picks
        event for BOTH SI and SIR (round 5: event SIR runs the BASELINE
        config-4 shape ~8x faster than ring -- 5.1 vs 42 s at 10M ER --
        with crash-path-only divergences enumerated in models/event.py and
        pinned by the vs-ring/determinism/dieout/removal-1==SI tests plus
        the sir_event golden).  An explicit `-compact on/off` is a
        ring-engine request (the event engine has no dense path to
        compact), so auto honors it."""
        if self.engine == "event":
            return "event"
        if (self.engine == "auto" and self.backend in ("jax", "sharded")
                and self.protocol in ("si", "sir")
                and self.effective_time_mode == "ticks"
                and self.compact == "auto"):
            return "event"
        return "ring"

    def mailbox_cap_for(self, n_rows: int, *, stacked: bool = False) -> int:
        """Mailbox capacity for a delivery surface of `n_rows` local rows
        (the full node axis single-device; one shard's slice sharded --
        flat int32 addressing is per-LOCAL-array, so a sharded run keeps
        cap 16 well past the single-device boundary).

        `stacked=True` is for consumers that deliver through
        ops.mailbox.deliver_pair's stacked [2n, cap] flat addressing (the
        phase-1 ticks engines); only they shrink at the half boundary.
        Plain deliver() surfaces -- the rounds overlay and the phase-2
        ring delivery, in any overlay mode -- keep the full-boundary cap
        (advisor r3: a mode-keyed shrink halved phase-2 overflow headroom
        in ticks runs for n_local in (~6.7e7, 1.34e8] for no reason)."""
        if self.mailbox_cap > 0:
            return self.mailbox_cap
        # Balls-in-bins: with <=N uniform messages into N bins the max load
        # is ~ln N/ln ln N w.h.p. (~6.3 at N=1e8), so BOTH 16 and 8 put
        # overflow in the negligible band (and overflow is counted, never
        # silent).  Two size-banded shrinks to 8:
        # * MEMORY (round 4): the rounds overlay holds (n, cap+2) makeup
        #   + (n, cap) breakup emission buffers -- at cap 16 that is
        #   13.6 GB for n=1e8, over the 16 GB v5e HBM by itself.  Cap 8
        #   halves it and makes the reference-default 100M two-phase
        #   pipeline fit a single chip.  The band sits above every
        #   measured/golden-pinned config (<= 10M rows keep cap 16).
        # * ADDRESSING: past n_rows ~ 1.34e8, (n_rows+1)*16 overflows the
        #   flat int32 mailbox addressing and delivery would silently
        #   take the ~15x dense 2-D-scatter path (ops/mailbox.deliver);
        #   cap 8 keeps flat addressing to n_rows ~ 2.7e8.  Beyond THAT
        #   the dense fallback engages and deliver's one-time warning
        #   names it.  deliver_pair's STACKED [2n, cap] addressing
        #   (stacked=True consumers) stops fitting at ~1.34e8 even at
        #   cap 8; its fallback is two deliver() passes, not the dense
        #   path.  The memory band (3.2e7) sits below both addressing
        #   boundaries, so the fits() checks are a backstop kept EXACTLY
        #   as the delivery paths consult them (deliver_pair checks
        #   fits(2n+1, cap); deliver checks fits(n, cap)).
        from gossip_simulator_tpu.ops.mailbox import flat_addressing_fits

        if n_rows >= MAILBOX_CAP_MEMORY_BAND:
            return 8
        rows = 2 * n_rows + 1 if stacked else n_rows
        if not flat_addressing_fits(rows, 16):
            return 8
        return 16

    @property
    def mailbox_cap_resolved(self) -> int:
        return self.mailbox_cap_for(self.n)

    def validate(self) -> "Config":
        if self.n < 2:
            raise ValueError(f"n must be >= 2, got {self.n}")
        if self.graph == "overlay" and self.n < 3:
            # Breakup replacement excludes two ids (self + leaver,
            # simulator.go:87-89); with n=2 the reference's retry loop would
            # spin forever -- reject the config instead.
            raise ValueError("overlay graph requires n >= 3")
        if self.fanout < 1:
            raise ValueError(f"fanout must be >= 1, got {self.fanout}")
        if self.fanin != -1 and self.fanin < 1:
            raise ValueError(f"fanin must be >= 1 (or -1=auto), got {self.fanin}")
        if self.delayhigh <= self.delaylow:
            # The reference panics inside rand.Intn here (simulator.go:167).
            raise ValueError(
                f"delayhigh ({self.delayhigh}) must be > delaylow ({self.delaylow})"
            )
        if self.delaylow < 0:
            raise ValueError(f"delaylow must be >= 0, got {self.delaylow}")
        if (self.delaylow < 1 and self.backend in ("jax", "sharded")
                and self.effective_time_mode == "ticks"):
            # The delay-ring engines batch B = min(10, delaylow) ticks per
            # step and clamp drawn delays to >= 1 (a zero-delay message
            # would land in the ring slot already drained this step); with
            # delaylow=0 the clamp silently reshapes the delay distribution
            # instead.  Reject it -- zero-delay networks run faithfully on
            # the discrete-event backends (native/cpp) or in rounds mode.
            raise ValueError(
                "delaylow must be >= 1 in ticks mode on the jax/sharded "
                "backends (drawn delays are clamped to >= 1 tick); use "
                "-time-mode rounds or -backend native/cpp for delaylow=0")
        for name in ("droprate", "crashrate", "removal_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0,1], got {v}")
        if self.backend not in BACKENDS:
            raise ValueError(f"backend must be one of {BACKENDS}, got {self.backend!r}")
        if self.protocol not in PROTOCOLS:
            raise ValueError(
                f"protocol must be one of {PROTOCOLS}, got {self.protocol!r}"
            )
        if self.graph not in GRAPHS:
            raise ValueError(f"graph must be one of {GRAPHS}, got {self.graph!r}")
        if self.compact not in ("auto", "on", "off"):
            raise ValueError(
                f"compact must be auto|on|off, got {self.compact!r}")
        if self.engine not in ENGINES:
            raise ValueError(f"engine must be one of {ENGINES}, got {self.engine!r}")
        if self.dup_suppress not in ("auto", "on", "off"):
            raise ValueError(
                f"dup_suppress must be auto|on|off, got {self.dup_suppress!r}")
        if self.telemetry not in ("on", "off"):
            raise ValueError(
                f"telemetry must be on|off, got {self.telemetry!r}")
        if self.telemetry_spatial not in ("on", "off"):
            raise ValueError(f"telemetry_spatial must be on|off, got "
                             f"{self.telemetry_spatial!r}")
        if self.telemetry_spatial == "on" and self.telemetry == "off":
            raise ValueError(
                "-telemetry-spatial on records panels on the telemetry "
                "fast path; it cannot run with -telemetry off")
        if (self.telemetry_spatial == "on"
                and self.backend not in ("jax", "sharded")):
            raise ValueError(
                "-telemetry-spatial needs a device-side loop to record "
                f"panels; backend {self.backend!r} has none")
        for name in ("overlay_adaptive_chunks", "overlay_dead_skip",
                     "overlay_static_boot"):
            v = getattr(self, name)
            if v not in ("auto", "on", "off"):
                raise ValueError(f"{name} must be auto|on|off, got {v!r}")
        if self.deliver_kernel not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"deliver_kernel must be auto|xla|pallas, "
                f"got {self.deliver_kernel!r}")
        if self.phase2_kernel not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"phase2_kernel must be auto|xla|pallas, "
                f"got {self.phase2_kernel!r}")
        if self.phase1_kernel not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"phase1_kernel must be auto|xla|pallas, "
                f"got {self.phase1_kernel!r}")
        if self.exchange_pipeline not in ("auto", "off", "double"):
            raise ValueError(
                f"exchange_pipeline must be auto|off|double, "
                f"got {self.exchange_pipeline!r}")
        if self.dup_suppress == "on" and self.crashrate_eff > 0.0:
            raise ValueError(
                "-dup-suppress on requires an effective crash rate of 0 "
                "(crash draws are keyed by mailbox position; suppressing "
                "entries would shift every later draw).  Note the "
                "reference's own default crashrate 0.001 IS 0 under "
                "-compat-reference (1%-resolution truncation).")
        # --- multi-rumor traffic -----------------------------------------
        if not 1 <= self.rumors <= 1024:
            raise ValueError(
                f"rumors must be in [1, 1024], got {self.rumors}")
        if self.traffic not in ("oneshot", "stream"):
            raise ValueError(
                f"traffic must be oneshot|stream, got {self.traffic!r}")
        if self.multi_rumor:
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "-rumors > 1 / -traffic stream require backend=jax or "
                    "sharded (the discrete-event oracles are single-rumor)")
            if self.protocol != "si":
                raise ValueError(
                    "-rumors > 1 / -traffic stream support protocol=si only "
                    "(SIR removal and push-pull digests are single-rumor)")
            if self.effective_time_mode != "ticks":
                raise ValueError(
                    "-rumors > 1 / -traffic stream require -time-mode ticks")
            if self.compat_reference:
                raise ValueError(
                    "-compat-reference is strictly single-rumor (the "
                    "reference broadcasts exactly one rumor per run)")
            if self.dup_suppress == "on":
                raise ValueError(
                    "-dup-suppress on is unsound with a rumor axis: an "
                    "already-infected destination can still gain new rumor "
                    "bits, so 'guaranteed duplicate' edges carry information")
            if self.engine_resolved == "ring":
                if self.backend == "sharded":
                    raise ValueError(
                        "-rumors > 1 on the ring engine is single-device "
                        "only (use -engine event for -backend sharded)")
                if self.overlay_heal_resolved:
                    raise ValueError(
                        "-overlay-heal with -rumors > 1 requires the event "
                        "engine (ring-engine heal re-sends are single-rumor)")
        if self.traffic == "stream":
            if not 1 <= self.stream_rate <= 1_000_000:
                # The upper bound keeps the injection schedule's clamped
                # tick * rate product in int32 (event.injection_batch).
                raise ValueError(
                    f"stream_rate must be in [1, 1000000], got "
                    f"{self.stream_rate}")
            if self.engine_resolved != "event":
                raise ValueError(
                    "-traffic stream requires the event engine (the jitted "
                    "injection schedule rides the event window step)")
        # --- numeric gossip (-model pushsum) ------------------------------
        if self.model not in ("si", "pushsum"):
            raise ValueError(
                f"model must be si|pushsum, got {self.model!r}")
        if self.model == "pushsum":
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "-model pushsum requires backend=jax or sharded (the "
                    "discrete-event oracles are 1-bit SI only)")
            if self.graph not in ("kout", "erdos"):
                raise ValueError(
                    "-model pushsum supports -graph kout|erdos (the rounds "
                    "overlay build has no numeric state to average)")
            if self.protocol != "si":
                raise ValueError(
                    "-model pushsum replaces the infection protocol; use "
                    "the default -protocol si")
            if self.effective_time_mode != "ticks":
                raise ValueError("-model pushsum requires -time-mode ticks")
            if self.engine_resolved != "event":
                raise ValueError(
                    "-model pushsum rides the event-engine mail ring; "
                    "leave -engine auto/event")
            if self.multi_rumor or self.traffic != "oneshot":
                raise ValueError(
                    "-model pushsum is incompatible with -rumors > 1 / "
                    "-traffic stream (mass columns replace the rumor words)")
            if self.compat_reference:
                raise ValueError(
                    "-compat-reference is strictly 1-bit SI; it has no "
                    "PushSum surface")
            if self.dup_suppress == "on":
                raise ValueError(
                    "-dup-suppress on is meaningless under -model pushsum: "
                    "every delivery carries fresh mass, nothing is a "
                    "guaranteed duplicate")
            if self.droprate != 0.0:
                raise ValueError(
                    "-model pushsum requires droprate 0 (a dropped message "
                    "destroys mass and breaks the conservation invariant; "
                    "model lossy links with -scenario partitions instead, "
                    "which block at send time)")
            if self.crashrate != 0.0:
                raise ValueError(
                    "-model pushsum requires crashrate 0 (per-reception "
                    "crashes black-hole in-flight mass; use -scenario "
                    "crash/churn events -- crashed nodes park mass and "
                    "rejoin with it)")
            if self.serve:
                raise ValueError("-serve streams rumors; it has no "
                                 "pushsum surface")
            if not 1 <= self.pushsum_dim <= 8:
                raise ValueError(
                    f"pushsum_dim must be in [1, 8], got {self.pushsum_dim}")
            if not self.pushsum_eps > 0.0:
                raise ValueError(
                    f"pushsum_eps must be > 0, got {self.pushsum_eps}")
        # --- elastic serving / arrival processes --------------------------
        if self.arrivals not in ("fixed", "poisson", "burst", "diurnal"):
            raise ValueError(
                f"arrivals must be fixed|poisson|burst|diurnal, "
                f"got {self.arrivals!r}")
        if self.arrivals != "fixed" and self.traffic != "stream":
            raise ValueError(
                "-arrivals shapes the streaming injection schedule; it "
                "requires -traffic stream")
        if self.inject_ticks is not None:
            if self.traffic != "stream":
                raise ValueError("inject_ticks requires -traffic stream")
            ticks = self.inject_ticks
            if len(ticks) != self.rumors:
                raise ValueError(
                    f"inject_ticks length ({len(ticks)}) must equal rumors "
                    f"({self.rumors})")
            if any(t < 0 or t >= 2**31 - 1 for t in ticks):
                raise ValueError("inject_ticks entries must be int32 ticks")
            if any(b < a for a, b in zip(ticks, ticks[1:])):
                raise ValueError("inject_ticks must be nondecreasing")
        if self.serve:
            if self.traffic != "stream":
                raise ValueError(
                    "-serve is the streaming service loop; it requires "
                    "-traffic stream")
            if self.backend not in ("jax", "sharded"):
                raise ValueError("-serve requires backend=jax or sharded")
            if self.resume:
                raise ValueError(
                    "-serve manages its own reshard-resume cycle; -resume "
                    "is not supported with it")
            if not 0.0 <= self.serve_low < self.serve_high <= 1.0:
                raise ValueError(
                    f"need 0 <= serve_low < serve_high <= 1, got "
                    f"low={self.serve_low} high={self.serve_high}")
            if self.serve_window < 1:
                raise ValueError(
                    f"serve_window must be >= 1, got {self.serve_window}")
            if self.serve_min_shards < 1:
                raise ValueError(
                    f"serve_min_shards must be >= 1, "
                    f"got {self.serve_min_shards}")
            if (self.serve_max_shards != -1
                    and self.serve_max_shards < self.serve_min_shards):
                raise ValueError(
                    "serve_max_shards must be -1 (all devices) or >= "
                    "serve_min_shards")
            if self.serve_max_defer < 0:
                raise ValueError(
                    f"serve_max_defer must be >= 0, got {self.serve_max_defer}")
            parse_serve_force(self.serve_force)  # raises on a bad spec
        if self.ckpt_keep < 0:
            raise ValueError(f"ckpt_keep must be >= 0, got {self.ckpt_keep}")
        if self.tuning_table not in ("auto", "off"):
            import os

            if not os.path.exists(self.tuning_table):
                raise ValueError(
                    f"-tuning-table: no such file {self.tuning_table!r} "
                    "(use 'auto', 'off', or a tuning-table JSON path)")
        # --- fault-injection scenario ------------------------------------
        scen = self.scenario_resolved  # raises ValueError on a bad spec
        if scen.active:
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "-scenario requires backend=jax or sharded (the "
                    "discrete-event oracles have no fault timeline)")
            if self.protocol == "pushpull":
                raise ValueError(
                    "-scenario supports protocol=si|sir (push-pull has "
                    "no send-time wave for the partition mask to filter)")
            if scen.groups > self.n:
                raise ValueError(
                    f"scenario groups ({scen.groups}) cannot exceed n "
                    f"({self.n})")
            if self.dup_suppress == "on" and scen.has_faults:
                raise ValueError(
                    "-dup-suppress on is unsound under scenario faults: "
                    "a banked duplicate credit assumes delivery to a "
                    "live node, but a scenario crash can black-hole the "
                    "destination before its window drains")
        if self.overlay_heal not in ("on", "off"):
            raise ValueError(
                f"overlay_heal must be on|off, got {self.overlay_heal!r}")
        if self.overlay_heal_resolved:
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "-overlay-heal requires backend=jax or sharded")
            if self.protocol == "pushpull":
                raise ValueError(
                    "-overlay-heal is meaningless for push-pull (fresh "
                    "random peers every round; there is no friends table "
                    "to repair)")
        if self.heal_detect_ms < 0:
            raise ValueError(
                f"heal_detect_ms must be >= 0, got {self.heal_detect_ms}")
        if self.engine == "event":
            if (self.protocol not in ("si", "sir")
                    or self.effective_time_mode != "ticks"):
                raise ValueError(
                    "engine=event supports protocol=si|sir in ticks mode only")
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "engine=event requires backend=jax or sharded")
        if self.time_mode not in TIME_MODES:
            raise ValueError(
                f"time_mode must be one of {TIME_MODES}, got {self.time_mode!r}"
            )
        if self.overlay_mode not in ("auto", "rounds", "ticks"):
            raise ValueError(
                f"overlay_mode must be 'auto', 'rounds' or 'ticks', "
                f"got {self.overlay_mode!r}")
        if self.overlay_mode == "ticks" and self.graph == "overlay":
            # native/cpp are discrete-event and inherently faithful, so the
            # flag is a no-op there; only the vectorized backends gate.
            # (auto resolves to rounds on rounds-semantics runs instead of
            # erroring -- the gate is for the EXPLICIT request.)
            if (self.backend in ("jax", "sharded")
                    and self.effective_time_mode != "ticks"):
                raise ValueError(
                    "-overlay-mode ticks requires -time-mode ticks")
        if self.distributed:
            if self.backend != "sharded":
                raise ValueError("-distributed requires -backend sharded")
            manual = (bool(self.coordinator), self.num_processes != -1,
                      self.process_id != -1)
            if any(manual) and not all(manual):
                raise ValueError(
                    "-coordinator, -num-processes and -process-id must be "
                    "given together (or all omitted for jax's automatic "
                    "cluster detection, e.g. on TPU pods)")
            if all(manual):
                if self.num_processes < 1:
                    raise ValueError(
                        f"-num-processes must be >= 1, got {self.num_processes}")
                if not 0 <= self.process_id < self.num_processes:
                    raise ValueError(
                        f"-process-id must be in [0, {self.num_processes}), "
                        f"got {self.process_id}")
        if self.chaos and not self.supervise:
            raise ValueError(
                "-chaos is a supervision drill; it requires -supervise")
        if self.workers < 1:
            raise ValueError(f"-workers must be >= 1, got {self.workers}")
        if self.heartbeat_timeout_ms < 1:
            raise ValueError(
                f"-heartbeat-timeout-ms must be >= 1, "
                f"got {self.heartbeat_timeout_ms}")
        if self.recover_max_stale < 0:
            raise ValueError(
                f"-recover-max-stale must be >= 0, "
                f"got {self.recover_max_stale}")
        if self.init_timeout_s < 1:
            raise ValueError(
                f"-init-timeout must be >= 1, got {self.init_timeout_s}")
        if self.supervise:
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "-supervise requires backend=jax or sharded (recovery "
                    "rides the checkpoint/restore surface)")
            if self.serve:
                raise ValueError(
                    "-supervise and -serve are exclusive phase-2 loops")
            if self.distributed:
                raise ValueError(
                    "-supervise launches the -distributed workers itself; "
                    "run the supervisor WITHOUT -distributed")
            if self.resume:
                raise ValueError(
                    "-supervise manages resume itself (survivors relaunch "
                    "with -resume); start the supervisor without it")
            if not self.checkpointing_enabled:
                raise ValueError(
                    "-supervise requires -checkpoint-every and "
                    "-checkpoint-dir: recovery restores the last atomic "
                    "snapshot, so there must be one to restore")
            if self.workers < 2:
                raise ValueError(
                    "-supervise needs -workers >= 2 (losing the only "
                    "worker leaves no survivors to recover onto)")
            if self.coordinator and self.backend != "sharded":
                raise ValueError(
                    "-supervise with -coordinator spawns -distributed "
                    "workers, which require -backend sharded")
            if self.coordinator and (self.num_processes != -1
                                     or self.process_id != -1):
                raise ValueError(
                    "-supervise assigns -num-processes/-process-id to the "
                    "workers it spawns; do not set them on the supervisor")
            drill = parse_chaos(self.chaos)
            if drill is not None and drill.worker >= self.workers:
                raise ValueError(
                    f"-chaos targets worker {drill.worker} but only "
                    f"{self.workers} workers exist")
        if not 0.0 < self.coverage_target <= 1.0:
            raise ValueError(
                f"coverage_target must be in (0,1], got {self.coverage_target}"
            )
        if self.max_rounds < 1:
            raise ValueError(f"max_rounds must be >= 1, got {self.max_rounds}")
        if self.resume:
            if not self.checkpoint_dir:
                raise ValueError("-resume requires -checkpoint-dir")
            if self.backend not in ("jax", "sharded"):
                raise ValueError(
                    "-resume requires backend=jax or sharded")
        if self.fanout >= self.n:
            raise ValueError(f"fanout ({self.fanout}) must be < n ({self.n})")
        return self

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw).validate()

    # --- reference-format parameter dump (simulator.go:197-204) ---------------
    def parameter_dump(self) -> str:
        """Reference prints flags alphabetically via flag.VisitAll with an `ms`
        suffix on the delay flags (simulator.go:197-204)."""
        ref = {
            "crashrate": self.crashrate,
            "delayhigh": f"{self.delayhigh}ms",
            "delaylow": f"{self.delaylow}ms",
            "droprate": self.droprate,
            "fanin": self.fanin_resolved,
            "fanout": self.fanout,
            "n": self.n,
        }
        lines = ["=== Parameters ==="]
        lines += [f"{k}={v}" for k, v in sorted(ref.items())]
        return "\n".join(lines)


def parse_serve_force(spec: str) -> dict:
    """Parse a `-serve-force` spec "S@W[,S@W...]" into {window: shards}.
    Raises ValueError on malformed entries."""
    out: dict = {}
    if not spec:
        return out
    for part in spec.split(","):
        try:
            s_str, w_str = part.strip().split("@")
            s, w = int(s_str), int(w_str)
        except ValueError:
            raise ValueError(
                f"bad -serve-force entry {part!r} (expected S@W, e.g. 8@4)")
        if s < 1 or w < 0:
            raise ValueError(
                f"-serve-force entry {part!r}: need shards >= 1, window >= 0")
        if w in out:
            raise ValueError(
                f"-serve-force window {w} given twice")
        out[w] = s
    return out


@dataclasses.dataclass(frozen=True)
class ChaosDrill:
    """A parsed -chaos spec: inject `kind` against `worker` once the run
    reaches gossip window `window`."""

    kind: str  # "kill-worker" | "stall-worker"
    worker: int
    window: int


def parse_chaos(spec: str) -> Optional[ChaosDrill]:
    """Parse a `-chaos` drill spec "kill-worker@W[:K]" /
    "stall-worker@W[:K]" (W = target worker rank, K = gossip window to
    inject at, default 6).  Returns None for the empty spec; raises
    ValueError on malformed ones."""
    if not spec:
        return None
    try:
        kind, rest = spec.strip().split("@")
        if ":" in rest:
            w_str, k_str = rest.split(":")
        else:
            w_str, k_str = rest, "6"
        worker, window = int(w_str), int(k_str)
    except ValueError:
        raise ValueError(
            f"bad -chaos spec {spec!r} (expected kill-worker@W[:K] or "
            "stall-worker@W[:K], e.g. kill-worker@1:6)")
    if kind not in ("kill-worker", "stall-worker"):
        raise ValueError(
            f"-chaos kind must be kill-worker or stall-worker, got {kind!r}")
    if worker < 0:
        raise ValueError(f"-chaos worker must be >= 0, got {worker}")
    if window < 1:
        raise ValueError(
            f"-chaos window must be >= 1 (the drill fires after a full "
            f"gossip window), got {window}")
    return ChaosDrill(kind=kind, worker=worker, window=window)


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gossip-sim-tpu",
        description="TPU-native gossip/epidemic simulator "
        "(capability parity with go-distributed/gossip_simulator).",
    )
    d = Config()
    # Reference flags (single-dash accepted for drop-in parity with Go's flag).
    p.add_argument("-n", "--n", type=int, default=d.n, help="total number of nodes")
    p.add_argument("-fanout", "--fanout", type=int, default=d.fanout, help="fanout")
    p.add_argument(
        "-fanin", "--fanin", type=int, default=-1,
        help="fanin (default: fanout+1; reference defaults to the constant 6)",
    )
    p.add_argument("-delaylow", "--delaylow", type=int, default=d.delaylow,
                   help="delay low (ms)")
    p.add_argument("-delayhigh", "--delayhigh", type=int, default=d.delayhigh,
                   help="delay high (ms)")
    p.add_argument("-droprate", "--droprate", type=float, default=d.droprate,
                   help="message drop rate")
    p.add_argument("-crashrate", "--crashrate", type=float, default=d.crashrate,
                   help="machine crash rate")
    # Framework extensions.
    p.add_argument("-backend", "--backend", choices=BACKENDS, default=d.backend)
    p.add_argument("-protocol", "--protocol", choices=PROTOCOLS, default=d.protocol)
    p.add_argument("-graph", "--graph", choices=GRAPHS, default=d.graph)
    p.add_argument("-seed", "--seed", type=int, default=d.seed)
    p.add_argument("-max-rounds", "--max-rounds", dest="max_rounds", type=int,
                   default=d.max_rounds)
    p.add_argument("-coverage-target", "--coverage-target", dest="coverage_target",
                   type=float, default=d.coverage_target)
    p.add_argument("-time-mode", "--time-mode", dest="time_mode",
                   choices=TIME_MODES, default=d.time_mode)
    p.add_argument("-removal-rate", "--removal-rate", dest="removal_rate",
                   type=float, default=d.removal_rate)
    p.add_argument("-er-p", "--er-p", dest="er_p", type=float, default=d.er_p)
    p.add_argument("-compat-reference", "--compat-reference",
                   dest="compat_reference", action="store_true")
    p.add_argument("-pallas", "--pallas", action="store_true")
    p.add_argument("-compact", "--compact", choices=("auto", "on", "off"),
                   default="auto")
    p.add_argument("-engine", "--engine", choices=ENGINES, default=d.engine)
    p.add_argument("-event-slot-cap", "--event-slot-cap",
                   dest="event_slot_cap", type=int, default=d.event_slot_cap)
    p.add_argument("-event-chunk", "--event-chunk", dest="event_chunk",
                   type=int, default=d.event_chunk)
    p.add_argument("-dup-suppress", "--dup-suppress", dest="dup_suppress",
                   choices=("auto", "on", "off"), default=d.dup_suppress,
                   help="suppress guaranteed-duplicate sends at append "
                        "(event engine, crash rate 0 only; auto = on "
                        "whenever sound)")
    p.add_argument("-overlay-mode", "--overlay-mode", dest="overlay_mode",
                   choices=("auto", "rounds", "ticks"),
                   default=d.overlay_mode)
    p.add_argument("-overlay-adaptive-chunks", "--overlay-adaptive-chunks",
                   dest="overlay_adaptive_chunks",
                   choices=("auto", "on", "off"),
                   default=d.overlay_adaptive_chunks,
                   help="occupancy-adaptive hosted-delivery chunk ladder "
                        "for the split-round overlay (trajectory-neutral; "
                        "auto = on)")
    p.add_argument("-overlay-dead-skip", "--overlay-dead-skip",
                   dest="overlay_dead_skip", choices=("auto", "on", "off"),
                   default=d.overlay_dead_skip,
                   help="skip dead emission rows via counts carried across "
                        "rounds (split-round overlay; trajectory-neutral; "
                        "auto = on)")
    p.add_argument("-overlay-static-boot", "--overlay-static-boot",
                   dest="overlay_static_boot", choices=("auto", "on", "off"),
                   default=d.overlay_static_boot,
                   help="one-shot bootstrap burst for the rounds overlay "
                        "(auto = on at >= 32M rows; off reproduces the "
                        "staggered per-round schedule)")
    p.add_argument("-deliver-kernel", "--deliver-kernel",
                   dest="deliver_kernel", choices=("auto", "xla", "pallas"),
                   default=d.deliver_kernel,
                   help="mailbox delivery kernel: pallas fuses the "
                        "sort/rank/scatter chain into one pass "
                        "(bit-identical, A/B-pinned); xla reproduces "
                        "prior trajectories bit-for-bit; auto = pallas "
                        "only when the TPU capability probe passes, else "
                        "xla with a named reason")
    p.add_argument("-phase2-kernel", "--phase2-kernel",
                   dest="phase2_kernel", choices=("auto", "xla", "pallas"),
                   default=d.phase2_kernel,
                   help="phase-2 megakernel: pallas fuses the "
                        "emit/receive-land/drain chains into single "
                        "passes against the ROOFLINE.json floors "
                        "(bit-identical, A/B-pinned); xla reproduces "
                        "prior trajectories bit-for-bit; auto = pallas "
                        "only when the TPU capability probe passes, else "
                        "xla with a named reason")
    p.add_argument("-phase1-kernel", "--phase1-kernel",
                   dest="phase1_kernel", choices=("auto", "xla", "pallas"),
                   default=d.phase1_kernel,
                   help="phase-1 overlay megakernel: pallas fuses the "
                        "slot-negotiate/bootstrap-request/hosted-occupancy "
                        "chains into single passes against the "
                        "ROOFLINE.json phase-1 floors (bit-identical, "
                        "A/B-pinned); xla reproduces prior trajectories "
                        "bit-for-bit; auto = pallas only when the TPU "
                        "capability probe passes, else xla with a named "
                        "reason")
    p.add_argument("-exchange-pipeline", "--exchange-pipeline",
                   dest="exchange_pipeline",
                   choices=("auto", "off", "double"),
                   default=d.exchange_pipeline,
                   help="sharded exchange pipelining: double defers each "
                        "chunk's drain one batch behind its all_to_all "
                        "so the next dispatch overlaps the drain "
                        "(bit-identical, A/B-pinned); off reproduces the "
                        "serial route->drain loop bit-for-bit; auto = "
                        "double on multi-device meshes, off at S=1")
    p.add_argument("-telemetry", "--telemetry", choices=("on", "off"),
                   default=d.telemetry,
                   help="device-resident per-window telemetry on fast-path "
                        "runs (jax/sharded); off restores the windowed "
                        "host loop for observing runs")
    p.add_argument("-telemetry-spatial", "--telemetry-spatial",
                   dest="telemetry_spatial", choices=("on", "off"),
                   default=d.telemetry_spatial,
                   help="per-group/per-shard panels + exchange traffic "
                        "matrix recorded next to the scalar history "
                        "(npz-only; stdout/JSONL unchanged)")
    p.add_argument("-telemetry-summary", "--telemetry-summary",
                   dest="telemetry_summary", action="store_true",
                   help="print the end-of-run telemetry block (phase "
                        "breakdown, throughput)")
    p.add_argument("-scenario", "--scenario", default=d.scenario,
                   help="fault-injection timeline: 'off', a JSON file "
                        "path, or inline JSON (crash waves, churn, "
                        "recovery downtime, partition masks -- see "
                        "scenario.py)")
    p.add_argument("-overlay-heal", "--overlay-heal", dest="overlay_heal",
                   choices=("on", "off"), default=d.overlay_heal,
                   help="phase-2 overlay self-healing: replace detected-"
                        "dead friends via the phase-1 makeup draw and "
                        "re-send the rumor over repaired edges")
    p.add_argument("-heal-detect-ms", "--heal-detect-ms",
                   dest="heal_detect_ms", type=int, default=d.heal_detect_ms,
                   help="ms of failed deliveries before a dead friend is "
                        "condemned and replaced")
    p.add_argument("-rumors", "--rumors", type=int, default=d.rumors,
                   help="concurrent rumors sharing one dissemination "
                        "substrate (packed uint32 word ladder; 1 = the "
                        "reference's single-rumor broadcast)")
    p.add_argument("-traffic", "--traffic", choices=("oneshot", "stream"),
                   default=d.traffic,
                   help="oneshot: all rumors injected at tick 0; stream: "
                        "continuous injection at -stream-rate with steady-"
                        "state throughput reporting")
    p.add_argument("-stream-rate", "--stream-rate", dest="stream_rate",
                   type=int, default=d.stream_rate,
                   help="stream traffic injection rate, rumors per 1000 "
                        "simulated ms")
    p.add_argument("-serve", "--serve", action="store_true",
                   help="elastic serving loop: autoscale the shard count "
                        "under streaming traffic via checkpoint -> reshard "
                        "-> resume, with admission control when saturated")
    p.add_argument("-arrivals", "--arrivals",
                   choices=("fixed", "poisson", "burst", "diurnal"),
                   default=d.arrivals,
                   help="stream arrival process: fixed analytic ladder, "
                        "poisson inter-arrivals, 8-rumor bursts, or a "
                        "sinusoidal diurnal curve (all deterministic per "
                        "rumor index, shard-count invariant)")
    p.add_argument("-serve-high", "--serve-high", dest="serve_high",
                   type=float, default=d.serve_high,
                   help="widen watermark: mail-ring occupancy fraction")
    p.add_argument("-serve-low", "--serve-low", dest="serve_low",
                   type=float, default=d.serve_low,
                   help="narrow watermark: mail-ring occupancy fraction")
    p.add_argument("-serve-window", "--serve-window", dest="serve_window",
                   type=int, default=d.serve_window,
                   help="consecutive windows beyond a watermark before the "
                        "autoscaler acts")
    p.add_argument("-serve-min-shards", "--serve-min-shards",
                   dest="serve_min_shards", type=int,
                   default=d.serve_min_shards)
    p.add_argument("-serve-max-shards", "--serve-max-shards",
                   dest="serve_max_shards", type=int,
                   default=d.serve_max_shards,
                   help="autoscaler shard-count ceiling (-1 = all devices)")
    p.add_argument("-serve-force", "--serve-force", dest="serve_force",
                   default=d.serve_force,
                   help="deterministic reshard override 'S@W[,S@W...]': "
                        "reshard to S shards at serve window W (CI twins)")
    p.add_argument("-serve-max-defer", "--serve-max-defer",
                   dest="serve_max_defer", type=int, default=d.serve_max_defer,
                   help="admission-control backoff cap in simulated ms")
    p.add_argument("-ckpt-keep", "--ckpt-keep", dest="ckpt_keep", type=int,
                   default=d.ckpt_keep,
                   help="keep only the newest K checkpoint snapshots after "
                        "each successful save (0 = keep all)")
    p.add_argument("-model", "--model", choices=("si", "pushsum"),
                   default=d.model,
                   help="model family: si = the reference's 1-bit "
                        "infection; pushsum = numeric PushSum averaging "
                        "(nodes push half their (value, weight) mass to "
                        "friends each window; delivery is a scatter-add; "
                        "the run converges when every live node's estimate "
                        "is within -pushsum-eps of the true mean)")
    p.add_argument("-pushsum-dim", "--pushsum-dim", dest="pushsum_dim",
                   type=int, default=d.pushsum_dim,
                   help="pushsum value-vector length (1..8)")
    p.add_argument("-pushsum-eps", "--pushsum-eps", dest="pushsum_eps",
                   type=float, default=d.pushsum_eps,
                   help="pushsum convergence threshold: max relative "
                        "error of any live node's estimate vs the true "
                        "network mean")
    p.add_argument("-tuning-table", "--tuning-table", dest="tuning_table",
                   default=d.tuning_table,
                   help="tuned-constant table (scripts/autotune.py): auto "
                        "= the committed TUNING_TABLE.json when present, "
                        "off = registered defaults, or a table path; "
                        "explicit flags like -event-chunk still outrank "
                        "table entries")
    p.add_argument("-profile", "--profile", action="store_true")
    p.add_argument("-profile-dir", "--profile-dir", dest="profile_dir",
                   default=d.profile_dir)
    p.add_argument("-trace", "--trace", default=d.trace,
                   help="write host-side phase/window spans as Chrome "
                        "trace-event JSON to this path")
    p.add_argument("-xprof", "--xprof", dest="xprof_dir",
                   default=d.xprof_dir,
                   help="wrap the run in a jax.profiler device trace "
                        "(TensorBoard dir), with one TraceAnnotation per "
                        "host span so device and host timelines align")
    p.add_argument("-run-dir", "--run-dir", dest="run_dir",
                   default=d.run_dir,
                   help="write a self-describing run artifact (config, "
                        "env fingerprint, JSONL metrics, telemetry npz, "
                        "trace, result + trajectory fingerprint) into "
                        "this directory; see scripts/compare_runs.py")
    p.add_argument("-log-jsonl", "--log-jsonl", dest="log_jsonl",
                   default=d.log_jsonl,
                   help="append structured JSONL progress records here")
    p.add_argument("-checkpoint-every", "--checkpoint-every",
                   dest="checkpoint_every", type=int, default=0)
    p.add_argument("-checkpoint-dir", "--checkpoint-dir", dest="checkpoint_dir",
                   default="")
    p.add_argument("-resume", "--resume", action="store_true",
                   help="resume from the latest snapshot in -checkpoint-dir")
    p.add_argument("-quiet", "--quiet", action="store_true",
                   help="suppress per-window progress lines")
    p.add_argument("-distributed", "--distributed", action="store_true",
                   help="multi-host SPMD: initialize jax.distributed and "
                        "shard the node axis over every process's devices")
    p.add_argument("-coordinator", "--coordinator", default=d.coordinator,
                   help="jax.distributed coordinator address host:port "
                        "(empty = auto-detect)")
    p.add_argument("-num-processes", "--num-processes", dest="num_processes",
                   type=int, default=d.num_processes)
    p.add_argument("-process-id", "--process-id", dest="process_id",
                   type=int, default=d.process_id)
    p.add_argument("-supervise", "--supervise", action="store_true",
                   help="host-loss supervision (distributed/supervisor.py): "
                        "single-process drillable mode, or with "
                        "-coordinator the real process-spawning supervisor")
    p.add_argument("-workers", "--workers", type=int, default=d.workers,
                   help="worker count under -supervise (logical device "
                        "slices, or spawned processes with -coordinator)")
    p.add_argument("-heartbeat-dir", "--heartbeat-dir", dest="heartbeat_dir",
                   default=d.heartbeat_dir,
                   help="liveness beacon directory "
                        "(default: <checkpoint-dir>/heartbeats)")
    p.add_argument("-heartbeat-timeout-ms", "--heartbeat-timeout-ms",
                   dest="heartbeat_timeout_ms", type=int,
                   default=d.heartbeat_timeout_ms,
                   help="beacon lag before a worker is declared lost")
    p.add_argument("-chaos", "--chaos", default=d.chaos,
                   help="host-loss drill: kill-worker@W[:K] or "
                        "stall-worker@W[:K] (worker W at gossip window K)")
    p.add_argument("-recover-max-stale", "--recover-max-stale",
                   dest="recover_max_stale", type=int,
                   default=d.recover_max_stale,
                   help="refuse recovery from a snapshot more than this "
                        "many windows behind the loss point (0 = no limit)")
    p.add_argument("-run-id", "--run-id", dest="run_id", default=d.run_id,
                   help="checkpoint provenance token (default: generated "
                        "per run; recovery refuses foreign snapshots)")
    p.add_argument("-init-timeout", "--init-timeout", dest="init_timeout_s",
                   type=int, default=d.init_timeout_s,
                   help="jax.distributed.initialize per-attempt timeout "
                        "in seconds (3 retried attempts)")
    return p


def parse_args(argv: Optional[list[str]] = None) -> Config:
    ns = _build_parser().parse_args(argv)
    kw = vars(ns)
    kw["progress"] = not kw.pop("quiet")
    return Config(**kw).validate()


def expected_rounds(cfg: Config) -> int:
    """Analytic upper-ish bound on rounds to 99% for SI push (SURVEY §6):
    log_{1+f(1-d)} N + slack.  Used for buffer sizing and test tolerances."""
    growth = 1.0 + cfg.fanout * (1.0 - cfg.droprate)
    if growth <= 1.0:
        return cfg.max_rounds
    return int(math.log(max(cfg.n, 2)) / math.log(growth)) + 12
