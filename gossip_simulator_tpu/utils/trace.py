"""Host-side span tracer: the flight recorder's timing substrate.

Every cross-revision perf claim so far (PR 2's exchange overhead, PR 3's
phase-1 floors, PR 6's kernel parity) was established by hand-run A/B twins
and hand-diffed BENCH_SELF rows.  This module records the same phase
boundaries mechanically: named spans (compile, phase-1 rounds, phase-2
windows, checkpoint save/load, sharded exchange, bench captures) with
counter payloads (messages, mail high-water, drops) as span args, emitted
as Chrome trace-event JSON (`chrome://tracing` / Perfetto "X" complete
events) behind `-trace PATH`.

`-xprof DIR` additionally wraps the run in ``jax.profiler.trace`` and
enters a ``jax.profiler.TraceAnnotation`` per span, so the device timeline
in TensorBoard lines up with the host spans recorded here.

Instrumentation sites use the module-level ``span()`` / ``instant()``
helpers, which are strict no-ops while no tracer is active -- backends,
checkpoint and bench never need cfg plumbing, and a run without `-trace`
executes zero extra work on the hot path (one None check per span site,
all of which sit on host-side per-call/per-window boundaries, never inside
jitted code).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional


class Tracer:
    """Collects Chrome trace-event "X" (complete) events host-side.

    Timestamps are microseconds from the tracer's construction
    (perf_counter based -- monotonic, sub-us resolution); one tracer spans
    one run (or one bench suite).  Thread-safe appends: the sharded
    backend and bench are single-threaded today, but the lock keeps the
    recorder safe if a callback ever fires from a jax runtime thread.
    """

    def __init__(self, path: str = "", xprof_dir: str = ""):
        self.path = path
        self.xprof_dir = xprof_dir
        self.events: list[dict] = []
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._xprof_cm = None

    # --- clock ----------------------------------------------------------
    def now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    # --- recording ------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """One timed region.  `args` (counters: messages, mail high-water,
        drops, ...) land in the event's ``args`` dict; values must be
        JSON-serializable scalars.  Yields the args dict so counters only
        known at span exit (window totals, drop counts) can be added
        before the event is sealed:

            with tracer.span("gossip.window") as sp:
                stats = stepper.gossip_window()
                sp["messages"] = stats.total_message
        """
        ann = self._annotation(name)
        t0 = self.now_us()
        try:
            if ann is not None:
                with ann:
                    yield args
            else:
                yield args
        finally:
            ev = {"name": name, "cat": cat, "ph": "X", "ts": t0,
                  "dur": self.now_us() - t0, "pid": self._pid,
                  "tid": threading.get_ident()}
            if args:
                ev["args"] = dict(args)
            with self._lock:
                self.events.append(ev)

    def instant(self, name: str, cat: str = "host", **args) -> None:
        """Zero-duration marker (trace-event "i")."""
        ev = {"name": name, "cat": cat, "ph": "i", "s": "p",
              "ts": self.now_us(), "pid": self._pid,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        with self._lock:
            self.events.append(ev)

    # --- xprof (device timeline) ----------------------------------------
    def _annotation(self, name: str):
        if not self.xprof_dir:
            return None
        try:
            import jax

            return jax.profiler.TraceAnnotation(name)
        except Exception:
            return None

    def start(self) -> None:
        """Begin the optional device-side profile (`-xprof DIR`)."""
        if self.xprof_dir and self._xprof_cm is None:
            import jax

            self._xprof_cm = jax.profiler.trace(self.xprof_dir)
            self._xprof_cm.__enter__()

    def stop(self) -> None:
        if self._xprof_cm is not None:
            self._xprof_cm.__exit__(None, None, None)
            self._xprof_cm = None

    # --- output ---------------------------------------------------------
    def to_json(self, metadata: Optional[dict] = None) -> dict:
        with self._lock:
            events = list(self.events)
        doc: dict = {"traceEvents": events, "displayTimeUnit": "ms"}
        if metadata:
            doc["metadata"] = metadata
        return doc

    def write(self, path: str = "", metadata: Optional[dict] = None) -> str:
        """Write the trace file (one JSON document, Chrome/Perfetto
        loadable); returns the path written."""
        out = path or self.path
        if not out:
            raise ValueError("Tracer.write: no path configured")
        parent = os.path.dirname(os.path.abspath(out))
        os.makedirs(parent, exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_json(metadata), f)
        os.replace(tmp, out)
        return out


# --- module-level active tracer ---------------------------------------------
#
# The driver (or bench) activates one tracer around a run; every
# instrumentation site in backends/checkpoint/bench reaches it through
# these helpers and costs a single None check when tracing is off.

_ACTIVE: Optional[Tracer] = None


def activate(tracer: Tracer) -> Tracer:
    global _ACTIVE
    _ACTIVE = tracer
    return tracer


def deactivate() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[Tracer]:
    return _ACTIVE


class _NullContext:
    """Inactive-tracer span: yields None (callers guard counter updates
    with `if sp:`) and costs one shared-instance enter/exit."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullContext()


def span(name: str, cat: str = "host", **args):
    """Context manager: a timed span on the active tracer, or a no-op.
    Yields the span's args dict (add counters before exit), or None when
    tracing is off."""
    t = _ACTIVE
    if t is None:
        return _NULL
    return t.span(name, cat=cat, **args)


def instant(name: str, cat: str = "host", **args) -> None:
    t = _ACTIVE
    if t is not None:
        t.instant(name, cat=cat, **args)


@contextlib.contextmanager
def activated(tracer: Optional[Tracer]):
    """Scoped activation (used by the driver and bench): activates on
    entry, starts the optional xprof profile, and always deactivates --
    a raised run never leaves a stale tracer behind for the next run in
    the same process (bench, tests)."""
    if tracer is None:
        yield None
        return
    prev = _ACTIVE
    activate(tracer)
    tracer.start()
    try:
        yield tracer
    finally:
        tracer.stop()
        globals()["_ACTIVE"] = prev
