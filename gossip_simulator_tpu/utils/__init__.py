from gossip_simulator_tpu.utils.metrics import Stats, ProgressPrinter

# NOTE: utils.rng imports jax and is deliberately NOT re-exported here, so the
# native oracle stays importable without jax (lazy-import policy of
# backends/__init__.py).

__all__ = ["Stats", "ProgressPrinter"]
