"""Graceful-shutdown plumbing (ISSUE 11 satellite 1).

A served simulation is a long-lived process; killing it must not lose
work.  `install_signal_handlers()` converts the FIRST SIGTERM/SIGINT into
a cooperative flag the driver's loops poll at window boundaries -- the run
then saves a final atomic checkpoint (when a -checkpoint-dir is set),
flushes the run-dir artifacts with reason "interrupted", and exits through
the normal result path (exit code 2, the standard not-converged code).  A
SECOND signal restores the default disposition and re-raises, so a wedged
run can still be killed hard.

The flag is process-global on purpose: signals are process-global, and
the driver's phase loops all consult the same predicate.  Host-side only
-- nothing here touches traced programs, so trajectories are unchanged
whether or not handlers are installed (an un-signalled run never observes
the flag).  The fast-path device loops poll between bounded dispatches
(backends/base.run_bounded_to_target), so even a non-checkpointing run
reacts within one bounded call.
"""

from __future__ import annotations

import signal

_shutdown_signum: int | None = None
_installed = False
# Shutdown callbacks (ISSUE 20): fired once, from the first signal, before
# the cooperative flag is even polled -- the real supervisor registers its
# worker teardown here so a TERM'd supervisor does not strand N child
# processes behind its own window-boundary polling.
_on_shutdown: list = []


def shutdown_requested() -> bool:
    return _shutdown_signum is not None


def register_on_shutdown(cb) -> None:
    """Run `cb()` when shutdown is first requested (signal or
    programmatic).  Callbacks must be quick and exception-safe in spirit;
    anything they raise is swallowed (a failing callback must not break
    signal delivery).  Cleared by reset()."""
    _on_shutdown.append(cb)


def _fire_callbacks() -> None:
    for cb in list(_on_shutdown):
        try:
            cb()
        except Exception:  # noqa: BLE001 - see register_on_shutdown
            pass


def shutdown_signal() -> int | None:
    return _shutdown_signum


def request_shutdown(signum: int = signal.SIGTERM) -> None:
    """Raise the flag programmatically (tests, embedding hosts)."""
    global _shutdown_signum
    first = _shutdown_signum is None
    _shutdown_signum = signum
    if first:
        _fire_callbacks()


def reset() -> None:
    """Clear the flag and callbacks (tests; a new run in the same
    process)."""
    global _shutdown_signum
    _shutdown_signum = None
    _on_shutdown.clear()


def _handler(signum, frame):
    global _shutdown_signum
    if _shutdown_signum is not None:
        # Second signal: the user means it -- die the default way.
        signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)
        return
    _shutdown_signum = signum
    _fire_callbacks()


def install_signal_handlers() -> bool:
    """Install the SIGTERM/SIGINT handlers (main thread only -- signal
    delivery outside it raises ValueError, in which case shutdown stays
    signal-less and this returns False).  Idempotent."""
    global _installed
    if _installed:
        return True
    try:
        signal.signal(signal.SIGTERM, _handler)
        signal.signal(signal.SIGINT, _handler)
    except ValueError:
        return False
    _installed = True
    return True
