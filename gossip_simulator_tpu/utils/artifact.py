"""Self-describing run artifacts (`-run-dir DIR`) and trajectory fingerprints.

A run dir is the unit `scripts/compare_runs.py` diffs and the substrate
every future hardware claim reports through (ROADMAP item 1): one
directory holding everything needed to attribute, replay and compare a
run without re-parsing argv or git-stashing twins:

    run-dir/
      config.json     flag snapshot + the resolved gate set
      env.json        platform fingerprint (jax/numpy/python versions,
                      backend, device count/kind, hostname, argv)
      metrics.jsonl   the structured JSONL log (schema v3, header first)
      telemetry.npz   fetched per-window histories + canonical trajectory
      trace.json      Chrome trace-event spans (when tracing is on)
      health.json     shard-health watchdog verdict over the spatial
                      panels (utils/health.py; spatial runs only)
      result.json     final Stats / RunResult payload + the trajectory
                      fingerprint

The **trajectory fingerprint** is the per-window
``(round, total_received, total_message, total_crashed, total_removed)``
row list hashed as sha256-of-JSON (first 16 hex chars) -- the same
convention the fingerprint-pin tests use.  The rows come from the
telemetry history on the fast path and from per-window Stats on the
windowed loop; the two bases are identical (`Stats.round` IS the recorded
tick column, and telemetry replay is byte-parity-pinned), so fingerprints
compare across paths.  A run with no per-window record at all (telemetry
off AND nothing observing) falls back to a single final-Stats row and says
so (``fingerprint_basis: "final"``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import sys
from typing import Optional

import numpy as np

# Canonical trajectory column order (one row per poll window).
TRAJECTORY_COLS = ("round", "total_received", "total_message",
                   "total_crashed", "total_removed")


def fingerprint_rows(rows) -> str:
    """sha256-of-JSON over int rows, first 16 hex chars (the repo's
    fingerprint-pin convention, tests/test_multirumor.py)."""
    payload = json.dumps([[int(v) for v in r] for r in rows]).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


def trajectory_from_history(hist: Optional[dict]) -> Optional[np.ndarray]:
    """Canonical int64 [count, 5] trajectory from a fetched gossip history
    (utils/telemetry.fetch_history shape)."""
    if not hist or not hist.get("count"):
        return None
    from gossip_simulator_tpu.utils import telemetry

    count = hist["count"]
    cols = hist["cols"][:count]
    g = telemetry.GCOL
    msg = telemetry._msg64_col(cols).astype(np.int64)
    out = np.empty((count, len(TRAJECTORY_COLS)), np.int64)
    out[:, 0] = cols[:, g["tick"]]
    out[:, 1] = cols[:, g["received"]]
    out[:, 2] = msg
    out[:, 3] = cols[:, g["crashed"]]
    out[:, 4] = cols[:, g["removed"]]
    return out


def trajectory_from_rows(rows: list) -> Optional[np.ndarray]:
    """Same canonical array from host-collected per-window Stats rows."""
    if not rows:
        return None
    return np.asarray(rows, np.int64).reshape(len(rows),
                                              len(TRAJECTORY_COLS))


def env_fingerprint() -> dict:
    """Platform/environment fingerprint: enough to attribute a perf delta
    to a software or hardware change before suspecting the code."""
    import platform

    out = {
        "python": platform.python_version(),
        "platform": platform.platform(),
        "hostname": socket.gethostname(),
        "argv": list(sys.argv),
    }
    try:
        out["numpy"] = np.__version__
    except Exception:
        pass
    try:
        import jax

        out["jax"] = jax.__version__
        devs = jax.devices()
        out["backend_platform"] = devs[0].platform if devs else "none"
        out["device_count"] = len(devs)
        kinds = sorted({getattr(d, "device_kind", "?") for d in devs})
        out["device_kind"] = kinds[0] if len(kinds) == 1 else kinds
    except Exception as e:  # pragma: no cover - jax is baked into the image
        out["jax_error"] = f"{type(e).__name__}: {e}"
    return out


class RunDir:
    """Writer for one run's artifact directory.

    Construction creates the directory; the driver (or bench) then calls
    the ``write_*`` methods as each artifact becomes available.  All
    writes are small JSON/npz files at run boundaries -- nothing here
    touches the hot path.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(self.path, exist_ok=True)

    def file(self, name: str) -> str:
        return os.path.join(self.path, name)

    @property
    def metrics_path(self) -> str:
        return self.file("metrics.jsonl")

    @property
    def trace_path(self) -> str:
        return self.file("trace.json")

    def _write_json(self, name: str, doc: dict) -> str:
        out = self.file(name)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True, default=str)
            f.write("\n")
        os.replace(tmp, out)
        return out

    def write_config(self, cfg) -> str:
        # resolved_gates carries the active tuning-table entry id (or
        # "defaults"), so every archived run names the tuned-constant set
        # it ran under -- compare_runs.py reads it as the first
        # divergence suspect.
        doc = {"flags": dataclasses.asdict(cfg),
               "resolved": cfg.resolved_gates()}
        return self._write_json("config.json", doc)

    def write_env(self, extra: Optional[dict] = None) -> str:
        doc = env_fingerprint()
        if extra:
            doc.update(extra)
        return self._write_json("env.json", doc)

    def write_telemetry(self, overlay: Optional[dict],
                        gossip: Optional[dict],
                        trajectory: Optional[np.ndarray]) -> Optional[str]:
        """One npz holding both fetched histories (named-column layouts
        from utils/telemetry) plus the canonical trajectory."""
        arrays: dict = {}
        from gossip_simulator_tpu.utils import telemetry

        if gossip is not None:
            arrays["gossip_cols"] = gossip["cols"][:gossip["count"]]
            arrays["gossip_count"] = np.int64(gossip["count"])
            arrays["gossip_names"] = np.array(telemetry.GOSSIP_COLS)
            if "spatial_group" in gossip:
                # Spatial panels (telemetry tentpole): already trimmed to
                # the recorded window count by fetch_history.
                arrays["spatial_group"] = gossip["spatial_group"]
                arrays["spatial_group_names"] = np.array(
                    telemetry.SPATIAL_GROUP_COLS)
                arrays["spatial_shard"] = gossip["spatial_shard"]
                arrays["spatial_shard_names"] = np.array(
                    telemetry.SPATIAL_SHARD_COLS)
                arrays["spatial_traffic"] = gossip["spatial_traffic"]
        if overlay is not None:
            arrays["overlay_cols"] = overlay["cols"][:overlay["count"]]
            arrays["overlay_count"] = np.int64(overlay["count"])
            arrays["overlay_names"] = np.array(telemetry.OVERLAY_COLS)
        if trajectory is not None:
            arrays["trajectory"] = trajectory
            arrays["trajectory_names"] = np.array(TRAJECTORY_COLS)
        if not arrays:
            return None
        out = self.file("telemetry.npz")
        tmp = out + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, out)
        return out

    def write_result(self, payload: dict) -> str:
        return self._write_json("result.json", payload)

    def write_serve(self, doc: dict) -> str:
        """Serve-mode sidecar: the autoscaler decision log, per-reshard
        pause spans and SLO summary (gossip_simulator_tpu/serve.py)."""
        return self._write_json("serve.json", doc)

    def write_hostloss(self, doc: dict) -> str:
        """Host-loss supervisor sidecar (distributed/supervisor.py): the
        per-recovery records (cause, snapshot, replayed windows, pause)
        plus detection settings."""
        return self._write_json("hostloss.json", doc)

    def write_health(self, verdict: dict) -> str:
        """Shard-health watchdog verdict (utils/health.py) over the
        spatial panels: status + the findings that fired."""
        return self._write_json("health.json", verdict)


def load_run(path: str) -> dict:
    """Read a run dir back for comparison: the JSON artifacts plus the
    npz arrays (lazily OK -- these are small).  Raises FileNotFoundError
    with a named missing artifact so compare_runs can exit 2 cleanly."""
    out: dict = {"path": os.path.abspath(path)}
    for name in ("config", "env", "result"):
        p = os.path.join(path, name + ".json")
        if not os.path.exists(p):
            raise FileNotFoundError(f"{path}: missing {name}.json "
                                    "(not a run dir?)")
        with open(p) as f:
            out[name] = json.load(f)
    npz = os.path.join(path, "telemetry.npz")
    if os.path.exists(npz):
        with np.load(npz, allow_pickle=False) as z:
            out["telemetry"] = {k: z[k] for k in z.files}
    else:
        out["telemetry"] = {}
    serve = os.path.join(path, "serve.json")
    if os.path.exists(serve):
        with open(serve) as f:
            out["serve"] = json.load(f)
    return out
