"""Process-level JAX knobs (compilation cache).

This environment compiles through a remote relay, so even trivial jits cost
seconds of wall-clock; the persistent compilation cache makes every rerun of
the same (config, shape) free.  Call before the first jit -- cli.py, bench.py
and tests/conftest.py all route through here.
"""

from __future__ import annotations

import os

_DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def setup(cache_dir: str | None = None) -> None:
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          cache_dir or _DEFAULT_CACHE)
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
