"""Process-level JAX knobs (compilation cache).

This environment compiles through a remote relay, so even trivial jits cost
seconds of wall-clock; the persistent compilation cache makes every rerun of
the same (config, shape) free.  Call before the first jit -- cli.py, bench.py
and tests/conftest.py all route through here.
"""

from __future__ import annotations

import os

_DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def setup(cache_dir: str | None = None) -> None:
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          cache_dir or _DEFAULT_CACHE)
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    # Cache every entry regardless of size, and (where this jax exposes
    # it) XLA's own autotuning/kernel caches too: the flagship 100M row's
    # first call is ~52 s of trace + compile (`graph_s` in the bench
    # record, README "cold-start" note) and the persistent cache is what
    # makes every rerun of the same (config, shape) start warm.
    for knob, val in (("jax_persistent_cache_min_entry_size_bytes", -1),
                      ("jax_persistent_cache_enable_xla_caches", "all")):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):  # knob absent on this jax
            pass


def forced_cpu_env(n_devices: int,
                   base: dict[str, str] | None = None) -> dict[str, str]:
    """Child-process env that forces an `n_devices`-way virtual CPU platform.

    This image's sitecustomize registers the axon TPU PJRT plugin at
    interpreter startup unless PALLAS_AXON_POOL_IPS is cleared, and with the
    plugin registered JAX_PLATFORMS / --xla_force_host_platform_device_count
    are no-ops -- so all three knobs must be set together, before the child's
    first jax import.  Single source of truth for tests/conftest.py,
    tests/test_distributed.py and __graft_entry__.dryrun_multichip.

    Appending the device-count flag after any inherited value is safe: XLA
    flag parsing is last-wins.
    """
    env = dict(os.environ if base is None else base)
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip axon PJRT registration
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={int(n_devices)}").strip()
    return env
