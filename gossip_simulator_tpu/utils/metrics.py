"""Counters and reference-format progress output.

The reference keeps six global int32 atomics (simulator.go:26-31) polled every
10 ms by the driver, printing:

    break <B> makeup <M> elasped <t>          (simulator.go:230, typo intact)
    --- Took <t> to stabilize ---             (simulator.go:235)
    <p>% covered, took <t>                    (simulator.go:247)
    --- Took <t> to get 99% ---               (simulator.go:252)
    Total message <M> Total Crashed <C>       (simulator.go:253)

Here the counters are device-resident scalars updated inside the jitted step
and fetched once per progress window; totals are validated against int32
overflow (the reference would silently wrap at ~430M-node scale).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Optional

# JSONL record-format version, stamped on every record so logs are
# machine-consumable without sniffing.  v2 added `schema_version` itself,
# the terminal `event="result"` record (full RunResult + wall-time
# breakdown), `Stats.exhausted`, and the fast-path `event="telemetry"`
# report (utils/telemetry.py).  v3 opens every stream with an
# `event="header"` record naming the telemetry history columns (the named
# schema replacing positional "14th column" indexing) and adds
# `run_dir` + the resolved gate set to the terminal `result` record.
SCHEMA_VERSION = 4


def header_record() -> dict:
    """The v4 stream header: the column schemas every downstream consumer
    needs to read telemetry histories / npz artifacts without hard-coding
    positions.  Deterministic (no wall clock beyond the stamp `_record`
    adds), so twin streams stay comparable.  v4 adds the spatial-panel
    registries (group/shard column names) -- STATIC, not gated on
    -telemetry-spatial, so a spatial-on twin's JSONL stays byte-identical
    to its spatial-off twin."""
    from gossip_simulator_tpu.utils.artifact import TRAJECTORY_COLS
    from gossip_simulator_tpu.utils.telemetry import (GOSSIP_COLS,
                                                      OVERLAY_COLS,
                                                      SPATIAL_GROUP_COLS,
                                                      SPATIAL_SHARD_COLS)

    return {"event": "header",
            "columns": {"gossip": list(GOSSIP_COLS),
                        "overlay": list(OVERLAY_COLS),
                        "trajectory": list(TRAJECTORY_COLS),
                        "spatial_group": list(SPATIAL_GROUP_COLS),
                        "spatial_shard": list(SPATIAL_SHARD_COLS)}}


@dataclasses.dataclass
class Stats:
    """Host-side snapshot of the simulation counters."""

    n: int = 0
    round: int = 0
    total_received: int = 0  # nodes infected (reference: TotalReceived)
    total_message: int = 0  # messages delivered to live nodes (TotalMessage)
    total_crashed: int = 0  # nodes crashed by reception (TotalCrashed)
    total_removed: int = 0  # SIR: nodes that stopped re-broadcasting
    makeups: int = 0  # membership events this run (MakeUps)
    breakups: int = 0  # (BreakUps)
    mailbox_dropped: int = 0  # framework-only: capacity-overflow drops
    exchange_overflow: int = 0  # framework-only: all_to_all bucket overflow
    # --- fault-injection scenario (scenario.py) --------------------------
    scen_crashed: int = 0  # nodes crashed by scenario waves/churn
    scen_recovered: int = 0  # nodes rebooted after scenario downtime
    part_dropped: int = 0  # sends black-holed by partition masks
    heal_repaired: int = 0  # dead friend edges replaced by -overlay-heal
    # True when the run ended with no messages in flight (the wave died) --
    # threaded here so printer.done() reports the true nonconvergence cause
    # on both the windowed and the fast path (reason parity).
    exhausted: bool = False
    # --- multi-rumor traffic (-rumors / -traffic) ------------------------
    rumors: int = 1  # concurrent rumor count R (1 = classic single-rumor)
    rumor_min_recv: int = -1  # min over rumors of per-rumor infected count
    rumors_done: int = 0  # rumors that have reached the coverage target
    # Serve-mode admission control: injections deferred (with capped
    # backoff, never dropped) because the widest mesh was saturated.  A
    # rumor deferred twice counts twice; always 0 outside -serve.
    shed: int = 0

    @property
    def coverage(self) -> float:
        if not self.n:
            return 0.0
        if self.rumors > 1 or self.rumor_min_recv >= 0:
            # Multi-rumor convergence is the WORST rumor's coverage: the
            # run is done when every rumor has reached the target.
            return max(self.rumor_min_recv, 0) / self.n
        return self.total_received / self.n

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["coverage"] = self.coverage
        return d


def fmt_sim_ms(ms: float) -> str:
    """Render simulated milliseconds the way Go renders time.Duration
    (e.g. ``231ms``, ``1.24s``)."""
    if ms < 1000:
        return f"{ms:g}ms"
    return f"{ms / 1000.0:g}s"


class ProgressPrinter:
    """Reference-format progress lines plus optional JSONL structured log."""

    def __init__(self, enabled: bool = True, jsonl_path: Optional[str] = None,
                 out=None, silent: bool = False):
        # enabled=False ("quiet") suppresses only the per-window progress
        # lines; parameters, phase summaries, and final totals always print.
        # silent=True suppresses ALL stdout (non-zero ranks of a
        # -distributed run, where every process would otherwise print the
        # same replicated totals); JSONL records still flow if configured.
        self.enabled = enabled
        self.silent = silent
        self.out = out or sys.stdout
        if jsonl_path:
            # A -run-dir run logs into its (not-yet-created) artifact dir.
            parent = os.path.dirname(os.path.abspath(jsonl_path))
            os.makedirs(parent, exist_ok=True)
        self._jsonl = open(jsonl_path, "a") if jsonl_path else None
        self._header_written = False
        self._t0 = time.perf_counter()

    @property
    def observing(self) -> bool:
        """True when per-window callbacks are visible somewhere (stdout
        progress lines or the JSONL log) -- the driver may skip the
        windowed loop entirely otherwise."""
        return (self.enabled and not self.silent) or self._jsonl is not None

    def _emit(self, line: str, progress_only: bool = False, **record):
        if not self.silent and (self.enabled or not progress_only):
            print(line, file=self.out, flush=True)
        self._record(**record)

    def _record(self, **record):
        """JSONL-only record (no stdout line)."""
        if self._jsonl:
            if not self._header_written:
                # v3: the stream opens with the column-schema header,
                # written lazily so a run that never logs stays empty.
                self._header_written = True
                self._record(**header_record())
            record["schema_version"] = SCHEMA_VERSION
            record["wall_s"] = time.perf_counter() - self._t0
            self._jsonl.write(json.dumps(record) + "\n")
            self._jsonl.flush()

    def params(self, dump: str):
        self._emit(dump, event="params")

    def overlay_window(self, breakups: int, makeups: int, sim_ms: float):
        # simulator.go:230 -- including the `elasped` typo for parity.
        self._emit(
            f"break {breakups} makeup {makeups} elasped {fmt_sim_ms(sim_ms)}",
            progress_only=True,
            event="overlay", breakups=breakups, makeups=makeups, sim_ms=sim_ms,
        )

    def stabilized(self, sim_ms: float):
        self._emit(f"--- Took {fmt_sim_ms(sim_ms)} to stabilize ---\n",
                   event="stabilized", sim_ms=sim_ms)

    def coverage_window(self, pct: float, sim_ms: float):
        # simulator.go:247 prints float32 percent*100 with %v.
        self._emit(f"{pct:g}% covered, took {fmt_sim_ms(sim_ms)}",
                   progress_only=True, event="coverage", pct=pct, sim_ms=sim_ms)

    def done(self, sim_ms: float, stats: Stats, target_pct: float = 99.0,
             converged: bool = True, reason: str = "max rounds"):
        if converged:
            self._emit(f"--- Took {fmt_sim_ms(sim_ms)} to get {target_pct:g}% ---\n",
                       event="done", sim_ms=sim_ms, **stats.to_dict())
        else:
            # Reference has no liveness bound and would spin forever
            # (simulator.go:243-251); we report non-convergence explicitly,
            # with the actual cause (cap hit vs wave died out).
            self._emit(
                f"--- Did NOT reach {target_pct:g}% after {fmt_sim_ms(sim_ms)} "
                f"({reason}) ---\n",
                event="nonconvergence", sim_ms=sim_ms, reason=reason,
                **stats.to_dict())
        self._emit(
            f"Total message {stats.total_message} Total Crashed {stats.total_crashed}",
            event="totals", **stats.to_dict())

    def note(self, text: str):
        """One-line informational notice (progress-only: quiet runs and
        non-primary ranks skip it; it never reaches the totals surface)."""
        self._emit(f"({text})", progress_only=True, event="note", text=text)

    def section(self, title: str):
        self._emit(f"\n=== {title} ===", event="section", title=title)

    def result(self, payload: dict):
        """Terminal machine-consumable record: the full RunResult plus the
        wall-time breakdown, JSONL-only -- downstream consumers no longer
        scrape the `totals` stdout line."""
        self._record(event="result", **payload)

    def telemetry(self, summary: dict):
        """Fast-path telemetry report (utils/telemetry.py): phase ledger,
        throughput and per-window trajectory.  JSONL-only."""
        self._record(event="telemetry", **summary)

    def block(self, text: str):
        """Multi-line end-of-run stdout block (e.g. -telemetry-summary);
        never enters the JSONL stream."""
        if not self.silent:
            print(text, file=self.out, flush=True)

    def close(self):
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None

    def __enter__(self) -> "ProgressPrinter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # Close on ANY exit so the JSONL file is flushed even when the run
        # raises (cli.py / bench.py wrap runs in `with`).
        self.close()
