"""Shard-health watchdog: declared predicates over the spatial panels.

The spatial telemetry tentpole (utils/telemetry.py: group/shard panels +
exchange traffic matrix) records WHERE a run's counters move; this module
is the host-side consumer that turns the fetched panels into a verdict.
Every check is a pure function of the snapshot dict `fetch_history`
returns -- no device access, no config plumbing beyond the optional ring
capacity -- so the same evaluation runs after a simulation (driver writes
`health.json` into the run dir), inside the serve loop (the autoscaler's
decision log carries the findings), and in tests against hand-built
panels.

Checks (each produces zero or more findings):

- ``occupancy_stuck_at_cap``: a shard's mail-ring occupancy high-water
  sat AT the slot capacity for the last K windows.  A full ring means
  the drain is not keeping up with arrivals on that shard -- the
  precursor of `mailbox_dropped` growth.  Needs `cap` (the event/pushsum
  engines' slot capacity); skipped when None (the ring engine's pending
  max is an arrival count with no hard cap).
- ``zero_delivery_shard``: a shard received NO routed lanes over the
  last K windows while its siblings did.  On a healthy mesh the routed
  all_to_all spreads every window's emissions across all shards; one
  silent column of the traffic matrix is a partitioned / wedged shard.
- ``group_coverage_stall``: a group's received gauge stopped growing for
  K windows below saturation while some sibling group still grew -- the
  spatial signature of a crash wave or partition confining the rumor.

The verdict is ``degraded`` when any finding fired, else ``ok`` (or
``no-data`` without panels -- spatial off, or a run too short to judge).
Findings also go to the flight recorder as instant events
(utils/trace.py `instant`, strict no-op without `-trace`).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gossip_simulator_tpu.utils import trace as _trace

# Minimum trailing windows a stall/stuck predicate needs before it may
# fire -- a 2-window run has no trend to judge.
STALL_WINDOWS = 3


def _panel_cols(gossip: dict):
    from gossip_simulator_tpu.utils.telemetry import (SPATIAL_GROUP_COLS,
                                                      SPATIAL_SHARD_COLS)

    return (SPATIAL_GROUP_COLS.index("received"),
            SPATIAL_SHARD_COLS.index("mail_high"),
            SPATIAL_SHARD_COLS.index("exch_rcvd"))


def evaluate_health(gossip: Optional[dict], cap: Optional[int] = None,
                    stall_windows: int = STALL_WINDOWS) -> dict:
    """Evaluate every predicate against one fetched gossip snapshot.

    `gossip` is `TelemetrySession.gossip_snapshot()` output (None or a
    dict without `spatial_group` yields the ``no-data`` verdict).  `cap`
    is the per-(window, node) slot capacity the occupancy column is
    measured against, when the engine has one.  Returns::

        {"status": "ok" | "degraded" | "no-data",
         "windows": <evaluated window count>,
         "checks": [<names run>],
         "findings": [{"check", "subject", "index", "windows", "detail"},
                      ...]}
    """
    if not gossip or "spatial_group" not in gossip:
        return {"status": "no-data", "windows": 0, "checks": [],
                "findings": []}
    i_recv, i_high, i_rcvd = _panel_cols(gossip)
    group = np.asarray(gossip["spatial_group"])
    shard = np.asarray(gossip["spatial_shard"])
    w = int(group.shape[0])
    k = min(int(stall_windows), w)
    findings: list[dict] = []
    checks: list[str] = []

    # --- occupancy stuck at cap (per shard, trailing K windows) ----------
    if cap is not None and w >= stall_windows:
        checks.append("occupancy_stuck_at_cap")
        tail = shard[w - k:, :, i_high]
        for s in np.flatnonzero((tail >= int(cap)).all(axis=0)):
            findings.append({
                "check": "occupancy_stuck_at_cap", "subject": "shard",
                "index": int(s), "windows": k,
                "detail": f"mail-ring high-water pinned at cap {int(cap)} "
                          f"for the last {k} windows"})

    # --- zero-delivery shard (cumulative exch_rcvd deltas) ---------------
    n_shards = int(shard.shape[1])
    if n_shards > 1 and w > stall_windows:
        checks.append("zero_delivery_shard")
        rcvd = shard[:, :, i_rcvd]
        delta = rcvd[w - 1] - rcvd[w - 1 - k]
        if (delta > 0).any():
            for s in np.flatnonzero(delta == 0):
                findings.append({
                    "check": "zero_delivery_shard", "subject": "shard",
                    "index": int(s), "windows": k,
                    "detail": f"no routed lanes delivered in the last {k} "
                              "windows while sibling shards kept "
                              "receiving"})

    # --- group coverage stall (received gauge, vs siblings) --------------
    if w > stall_windows:
        checks.append("group_coverage_stall")
        recv = group[:, :, i_recv]
        delta = recv[w - 1] - recv[w - 1 - k]
        # Saturation guard: a group that already reached its high-water
        # (its receive gauge equals the run's max for that group) is
        # done, not stalled.  Down nodes lower the gauge, so compare
        # against the group's own historical peak.
        peak = recv.max(axis=0)
        stalled = (delta == 0) & (recv[w - 1] < peak) | \
                  ((delta == 0) & (recv[w - 1] == 0))
        if (delta > 0).any():
            for g in np.flatnonzero(stalled):
                findings.append({
                    "check": "group_coverage_stall", "subject": "group",
                    "index": int(g), "windows": k,
                    "detail": f"received gauge flat for the last {k} "
                              "windows below its peak while sibling "
                              "groups kept growing"})

    status = "degraded" if findings else "ok"
    return {"status": status, "windows": w, "checks": checks,
            "findings": findings}


def report_health(verdict: dict) -> dict:
    """Emit one flight-recorder instant per finding plus the verdict
    (no-ops without an active tracer) and return the verdict unchanged,
    so call sites can chain `report_health(evaluate_health(...))`."""
    for f in verdict.get("findings", ()):
        _trace.instant(f"health.{f['check']}", cat="health",
                       subject=f["subject"], index=f["index"],
                       detail=f["detail"])
    if verdict.get("status") != "no-data":
        _trace.instant("health.verdict", cat="health",
                       status=verdict["status"],
                       findings=len(verdict.get("findings", ())))
    return verdict


def ring_slot_cap(cfg, n_shards: int = 1) -> Optional[int]:
    """The occupancy cap the stuck-at-cap check measures against: the
    mail-ring engines' PER-SHARD per-window slot capacity (the shard
    panel's mail_high column is each shard's local `mail_cnt` max).
    None for the ring engine (its pending max is an arrival count with
    no hard cap), matching the check's skip."""
    if cfg.model == "pushsum":
        from gossip_simulator_tpu.models import pushsum as geo
    elif cfg.engine_resolved == "event":
        from gossip_simulator_tpu.models import event as geo
    else:
        return None
    n_local = cfg.n // max(1, int(n_shards))
    return int(geo.slot_cap(cfg, n_local))
