"""Device-resident telemetry: per-window history without leaving the fast path.

The reference's only observability is six polled atomics printed every 10 ms
(simulator.go:26-31); our windowed driver loop reproduces that surface but
pays one jit dispatch + one device->host stats round-trip per 10 simulated ms
(~2x wall-clock at n=1e7 through the TPU tunnel).  This module removes the
observability-vs-speed tradeoff: the bounded device-side while_loops
(epidemic/event `make_run_to_coverage_fn`, overlay `make_bounded_run`) write
one row of counters per poll window into a preallocated device `History`
buffer -- a handful of scalar ops against a window of O(n) work -- and the
host fetches the whole trajectory in ONE transfer at loop exit.

`replay_overlay` / `replay_gossip` then drive the fetched history through the
ordinary ProgressPrinter, producing stdout/JSONL per-window output
byte-identical to the windowed loop's (the golden CLI transcripts enforce
this), so a progress-printing or JSONL-logging run takes the fast path
whenever checkpointing is off.  `TelemetrySession` is the host-side holder a
backend carries: device histories for both phases plus the wall-clock phase
ledger (init / compile / execute / fetch); `TelemetryReport` turns it into
throughput numbers, per-window deltas and the `-telemetry-summary` block.

History rows are int32; the 64-bit total_message pair travels as two
bitcast int32 columns and is reassembled host-side (msg64 convention from
models/state.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import numpy as np

# Column layouts (one int32 matrix per phase keeps the per-window write a
# single row scatter instead of one per counter).  The four scenario
# columns (scenario.py) record the per-window fault trajectory -- crash
# waves, reboots, repaired edges, partition-suppressed sends -- on the
# same device-resident ride as the epidemic counters; they are constant 0
# on scenario-less runs and the replay functions never read them, so the
# replayed stdout/JSONL surface is unchanged.
GOSSIP_COLS = ("tick", "received", "msg_hi", "msg_lo", "crashed", "removed",
               "mail_high", "dropped", "overflow", "scen_crashed",
               "recovered", "repaired", "part_dropped", "rumors_done",
               "exchange_inflight_hwm", "relerr_ppb")
OVERLAY_COLS = ("clock", "makeups", "breakups", "dropped")

# Optional trailing per-window blocks of the JSONL `telemetry` record: a
# group is emitted only when the build carries its source columns AND any
# of them is nonzero (all-zero columns would bloat every record), and it
# is emitted whole -- the scenario quartet travels together.  Each entry
# maps the emitted per_window key to its GOSSIP_COLS source column; this
# registry IS the contract scripts/check_telemetry.py validates the
# stream against (hardcoded per-column name checks drifted once per
# added column).
OPTIONAL_BLOCK_GROUPS = (
    (("scen_crashed", "scen_crashed"), ("scen_recovered", "recovered"),
     ("heal_repaired", "repaired"), ("part_dropped", "part_dropped")),
    (("rumors_done", "rumors_done"),),
    (("exchange_inflight_hwm", "exchange_inflight_hwm"),),
    (("relerr_ppb", "relerr_ppb"),),
)

# --- spatial panels (ISSUE 16) ----------------------------------------------
# Per-window spatial panels recorded next to the scalar history and
# fetched in the SAME single transfer: a (windows, groups, KG) group
# panel over the PR-4 scenario contiguous-id ranges (falling back to the
# sharded backend's shard slices when no scenario declares groups), a
# (windows, shards, KS) shard panel, and a (windows, S, S) exchange
# traffic matrix counted inside the routed all_to_all (parallel/
# exchange.py).  npz-only: the replayed stdout/JSONL surface never reads
# them, so a spatial-on/off twin pair stays byte-identical.
#
# Group columns are probe-time gauges over per-node state, chosen so the
# reconciliation invariant is exact (tests/test_spatial.py): summed over
# groups, `received` equals the global received column every window and
# `removed` equals the removed column; `down` is the currently-crashed
# count (== scen_crashed when faults are scenario waves without
# recovery; a cumulative-crash panel would need per-group accumulators
# in every fault site).
SPATIAL_GROUP_COLS = ("received", "down", "removed")
# Shard columns: probe-time mail-ring occupancy high-water (max over
# shards == the global mail_high column), resident informed count (sums
# to the received column), and the exchange counters accumulated inside
# the routed collective (exch_counts layout below) -- send-side overflow
# and valid lanes received off the wire.  relerr_ppb is the pushsum
# eps-check error, pmax-replicated by the sharded step (every shard
# records the same value; per-shard attribution would need the step to
# defer its pmax).
SPATIAL_SHARD_COLS = ("mail_high", "received", "overflow", "relerr_ppb",
                      "exch_rcvd")

# Layout of the per-shard exchange accumulator state leaf (`exch_counts`,
# int32[1, S + 2] on spatial sharded runs, int32[1, 1] placeholder
# otherwise -- the down_since convention): [0, :S] is this shard's
# traffic-matrix row (routed lanes by destination, counted at dispatch
# inside exchange.route_*), [0, S] the valid lanes received off the
# wire, [0, S + 1] the send-side overflow (lanes ranked past the slot
# cap, which never reach a receiver).
def exch_counts_width(spec) -> int:
    return spec.n_shards + 2 if (spec is not None and spec.n_shards > 1) \
        else 1

# Named column indices -- THE way to address a history column (schema v3
# names these in the JSONL header).  Positional literals ("the 14th
# column") drifted once per added column; every reader below and every
# external consumer (bench.py, utils/artifact.py, scripts) goes through
# these maps instead.
GCOL = {name: i for i, name in enumerate(GOSSIP_COLS)}
OCOL = {name: i for i, name in enumerate(OVERLAY_COLS)}


class History(NamedTuple):
    """Device-resident per-window ring: `idx` rows written (keeps counting
    past the capacity so truncation is detectable; writes saturate at the
    last row), `cols` the int32[cap, F] matrix."""

    idx: object  # int32[]
    cols: object  # int32[cap, F]


def empty_history(cap: int, ncols: int) -> History:
    import jax.numpy as jnp

    return History(idx=jnp.zeros((), jnp.int32),
                   cols=jnp.zeros((max(int(cap), 1), ncols), jnp.int32))


def record(hist: History, row) -> History:
    """Append one window's row (list of int32 scalars) device-side."""
    import jax.numpy as jnp

    cap = hist.cols.shape[0]
    vals = jnp.stack([jnp.asarray(v).astype(jnp.int32) for v in row])
    i = jnp.minimum(hist.idx, cap - 1)
    return History(idx=hist.idx + 1, cols=hist.cols.at[i].set(vals))


class Panels(NamedTuple):
    """Device-resident spatial panels, same ring discipline as History
    (the bundle shares History.idx -- panels and scalars are always
    row-aligned)."""

    group: object  # int32[cap, G, KG]
    shard: object  # int32[cap, S, KS]
    traffic: object  # int32[cap, S, S]  cumulative routed-lane counts


class SpatialBundle(NamedTuple):
    """The telemetry carry on spatial runs: the scalar History plus the
    panels.  Threaded through the same `hist` argument of the six
    run-to-coverage fns (backends/base.py treats it opaquely); a
    spatial-off run carries a plain History, so the off path traces the
    pre-spatial program."""

    hist: History
    panels: Panels


class SpatialSpec(NamedTuple):
    """Static panel geometry, hashable (closed over at trace time).
    groups = scenario groups when a scenario declares > 1, else the
    shard count (shard slices ARE contiguous-id groups -- scenario.py's
    group ranges coincide with the sharded backend's slices when groups
    == device count); group_size is the ceil-division id-range width."""

    groups: int
    group_size: int
    n: int
    n_shards: int


def spatial_spec(cfg, n_shards: int = 1):
    """The engine- and session-side gate: None when spatial panels are
    off (the run fns then trace the exact pre-spatial program)."""
    if not cfg.telemetry_spatial_enabled:
        return None
    scen = cfg.scenario_resolved
    g = scen.groups if (scen.active and scen.groups > 1) \
        else max(1, int(n_shards))
    return SpatialSpec(groups=g, group_size=-(-cfg.n // g), n=cfg.n,
                       n_shards=max(1, int(n_shards)))


def empty_panels(cap: int, spec: SpatialSpec) -> Panels:
    import jax.numpy as jnp

    cap = max(int(cap), 1)
    g, s = spec.groups, spec.n_shards
    return Panels(
        group=jnp.zeros((cap, g, len(SPATIAL_GROUP_COLS)), jnp.int32),
        shard=jnp.zeros((cap, s, len(SPATIAL_SHARD_COLS)), jnp.int32),
        traffic=jnp.zeros((cap, s, s), jnp.int32))


def bundle_specs(spec, P):
    """shard_map in/out specs for the telemetry carry: replicated
    History when spatial is off, replicated bundle when on (every panel
    row is psum/all_gather-replicated before the scatter)."""
    hspecs = History(idx=P(), cols=P(None, None))
    if spec is None:
        return hspecs
    return SpatialBundle(hist=hspecs,
                         panels=Panels(group=P(None, None, None),
                                       shard=P(None, None, None),
                                       traffic=P(None, None, None)))


def spatial_probe(st, spec: SpatialSpec, shard_index=0, gather=None,
                  psum=None, relerr=None):
    """One panel row triple (group (G, KG), shard (S, KS), traffic
    (S, S)) from an engine's local state view.  Duck-typed like
    gossip_probe: event/pushsum states carry `flags` + `mail_cnt`, the
    ring engine boolean node arrays + `pending`.  On sharded engines
    `shard_index` is lax.axis_index, `gather` all-gathers over the mesh
    axis and `psum` sums the per-shard group partials; single-device
    callers leave them None (S == 1)."""
    import jax
    import jax.numpy as jnp

    I32 = jnp.int32
    z = jnp.zeros((), I32)
    if hasattr(st, "flags"):
        from gossip_simulator_tpu.models.event import (CRASHED, RECEIVED,
                                                       REMOVED)

        received = (st.flags & RECEIVED) > 0
        down = (st.flags & CRASHED) > 0
        removed = (st.flags & REMOVED) > 0
        high = st.mail_cnt.max().astype(I32)
    else:
        received, down, removed = st.received, st.crashed, st.removed
        high = st.pending.max().astype(I32)
    n_local = received.shape[0]
    vals = jnp.stack([received, down, removed], axis=1).astype(I32)
    # Per-group sums WITHOUT a length-n scatter (segment_sum lowers to a
    # serial scatter-add on CPU -- measured ~200ms/window at 1M, blowing
    # the <=5% overhead budget).  Groups are contiguous equal-width id
    # ranges, so shift the local block to its within-group offset inside
    # a chunk-aligned buffer and reduce with a reshape -- the only
    # scatters left are two O(groups) dynamic_update_slices.
    gsz = spec.group_size
    kg = vals.shape[1]
    n_chunks = -(-n_local // gsz) + 1
    first = jnp.asarray(shard_index, I32) * n_local
    buf = jax.lax.dynamic_update_slice(
        jnp.zeros((n_chunks * gsz, kg), I32), vals, (first % gsz, 0))
    chunk = buf.reshape(n_chunks, gsz, kg).sum(axis=1, dtype=I32)
    group_rows = jax.lax.dynamic_update_slice(
        jnp.zeros((spec.groups + n_chunks, kg), I32), chunk,
        (first // gsz, 0))[:spec.groups]
    if psum is not None:
        group_rows = psum(group_rows)
    received_loc = vals[:, 0].sum(dtype=I32)
    rel = jnp.asarray(relerr, I32) if relerr is not None else z
    s = spec.n_shards
    if s > 1:
        ex = st.exch_counts[0]
        srow = jnp.stack([high, received_loc, ex[s + 1], rel, ex[s]])
        return group_rows, gather(srow), gather(ex[:s])
    srow = jnp.stack([high, received_loc, z, rel, z])
    return group_rows, srow[None, :], jnp.zeros((1, 1), I32)


def record_spatial(b: SpatialBundle, row, group_rows, shard_rows,
                   traffic) -> SpatialBundle:
    """Append one window's scalar row + panel rows at the shared index."""
    import jax.numpy as jnp

    cap = b.hist.cols.shape[0]
    i = jnp.minimum(b.hist.idx, cap - 1)
    return SpatialBundle(
        hist=record(b.hist, row),
        panels=Panels(group=b.panels.group.at[i].set(group_rows),
                      shard=b.panels.shard.at[i].set(shard_rows),
                      traffic=b.panels.traffic.at[i].set(traffic)))


def record_window(hist, row, st=None, spec=None, shard_index=0,
                  gather=None, psum=None, relerr=None):
    """THE per-window recording entry for the six run-to-coverage fns:
    a plain History append when spatial is off (spec None -- byte-
    identical trace to the pre-spatial build), the bundle append with a
    spatial probe when on."""
    if spec is None:
        return record(hist, row)
    g, s, t = spatial_probe(st, spec, shard_index=shard_index,
                            gather=gather, psum=psum, relerr=relerr)
    return record_spatial(hist, row, g, s, t)


def gossip_probe(st, sir: bool, psum=None, pmax=None, rumors: int = 0,
                 inflight_hwm: int = 0, relerr=None):
    """One GOSSIP_COLS row from either epidemic engine's state (duck-typed
    like models/state.in_flight: EventState has the mail ring, SimState the
    pending ring).  `psum`/`pmax` are the sharded engines' cross-shard
    reductions for the per-shard quantities (removed flags, ring occupancy);
    the totals are already psum-replicated by the step functions.  `rumors`
    (static R; 0 = single-rumor) adds the count of rumors that have hit the
    coverage target -- rumor_done is replicated on every engine, so no
    reduction applies.  `inflight_hwm` (static, per engine build) is the
    high-water mark of exchange buffers alive at once on the sharded
    routed path: 0 = no collective in the program (single device /
    non-sharded), 1 = the serial route->drain loop, 2 = the
    double-buffered pipeline (-exchange-pipeline double -- one staged
    drain in flight behind the dispatched all_to_all).  `relerr` is the
    pushsum engines' per-window max relative error vs the true network
    mean, pre-scaled to int32 parts-per-billion (already pmax-replicated
    by the sharded step); None = not a numeric-gossip run, column 0."""
    import jax
    import jax.numpy as jnp

    I32 = jnp.int32
    z = jnp.zeros((), I32)
    if hasattr(st, "flags"):  # event engine
        from gossip_simulator_tpu.models.event import REMOVED

        removed = ((st.flags & REMOVED) > 0).sum(dtype=I32) if sir else z
        high = st.mail_cnt.max().astype(I32)
        dropped = st.mail_dropped
    else:
        removed = st.removed.sum(dtype=I32) if sir else z
        # Per-(slot, node) arrival-count high-water -- the ring engine's
        # analog of the fullest mailbox.
        high = st.pending.max().astype(I32)
        dropped = z
    if psum is not None:
        removed = psum(removed)
    if pmax is not None:
        high = pmax(high)
    msg = jax.lax.bitcast_convert_type(st.total_message, I32)
    rdone = (st.rumor_done[:rumors] >= 0).sum(dtype=I32) if rumors else z
    return [st.tick, st.total_received, msg[0], msg[1], st.total_crashed,
            removed, high, dropped, st.exchange_overflow,
            st.scen_crashed, st.scen_recovered, st.heal_repaired,
            st.part_dropped, rdone, jnp.asarray(inflight_hwm, I32),
            jnp.asarray(relerr, I32) if relerr is not None else z]


def overlay_probe(st):
    """One OVERLAY_COLS row from either overlay engine's state (the
    tick-faithful engine carries `tick`, the rounds engine `round`; the
    window counters are already global/replicated on both)."""
    clock = st.tick if hasattr(st, "tick") else st.round
    return [clock, st.win_makeups, st.win_breakups, st.mailbox_dropped]


def gossip_history_cap(cfg) -> int:
    """Phase-2 window capacity: every engine's poll window advances at least
    WINDOW_MS ticks in ticks mode (event.poll_window_steps rounds UP) and
    one round in rounds mode, so ceil(max_rounds / window) bounds the rows."""
    window = 1 if cfg.effective_time_mode == "rounds" else 10
    return max(1, -(-cfg.max_rounds // window) + 2)


def fetch_history(hist) -> Optional[dict]:
    """ONE device->host transfer of a whole history buffer.  A spatial
    bundle rides the same single device_get: the snapshot dict gains
    `spatial_group` / `spatial_shard` / `spatial_traffic` arrays trimmed
    to the recorded window count."""
    if hist is None:
        return None
    import jax

    if isinstance(hist, SpatialBundle):
        idx, cols, pg, ps, pt = jax.device_get(
            (hist.hist.idx, hist.hist.cols, hist.panels.group,
             hist.panels.shard, hist.panels.traffic))
    else:
        idx, cols = jax.device_get((hist.idx, hist.cols))
        pg = ps = pt = None
    recorded = int(idx)
    cols = np.asarray(cols)
    out = {"count": min(recorded, cols.shape[0]), "recorded": recorded,
           "truncated": recorded > cols.shape[0], "cols": cols}
    if pg is not None:
        count = out["count"]
        out["spatial_group"] = np.asarray(pg)[:count]
        out["spatial_shard"] = np.asarray(ps)[:count]
        out["spatial_traffic"] = np.asarray(pt)[:count]
    return out


def host_history(rows: list) -> Optional[dict]:
    """Same shape as fetch_history for host-side recorded rows (the split
    overlay round's host loop)."""
    if not rows:
        return None
    cols = np.asarray(rows, np.int32).reshape(len(rows), -1)
    return {"count": len(rows), "recorded": len(rows), "truncated": False,
            "cols": cols}


# --- replay -----------------------------------------------------------------

def replay_overlay(printer, hist: Optional[dict], clock_scale: float,
                   quiesced: bool = True) -> None:
    """Re-emit the phase-1 per-window lines exactly as the windowed loop
    would have: the quiescing window itself is never printed
    (simulator.go:227-230 prints only when *not* stabilizing)."""
    if not hist:
        return
    cols, count = hist["cols"], hist["count"]
    last = count - 1 if quiesced else count
    for i in range(max(0, last)):
        # clock_scale 1.0 (faithful ticks) reproduces float(tick) exactly;
        # the rounds engine's round * mean_delay is the windowed loop's
        # identical float expression.
        printer.overlay_window(int(cols[i, OCOL["breakups"]]),
                               int(cols[i, OCOL["makeups"]]),
                               float(cols[i, OCOL["clock"]]) * clock_scale)


def replay_gossip(printer, hist: Optional[dict], n: int) -> None:
    """Re-emit the phase-2 coverage lines: same float math as the windowed
    driver loop (coverage = int received / int n, pct rounded to 4)."""
    if not hist:
        return
    cols = hist["cols"]
    for i in range(hist["count"]):
        pct = (int(cols[i, GCOL["received"]]) / n if n else 0.0) * 100.0
        printer.coverage_window(round(pct, 4), float(cols[i, GCOL["tick"]]))


def _msg64_col(cols: np.ndarray) -> np.ndarray:
    """Reassemble the bitcast [hi, lo] int32 column pair into uint64."""
    hi = GCOL["msg_hi"]
    pair = cols[:, hi:hi + 2].astype(np.int32).view(np.uint32) \
        .astype(np.uint64)
    return (pair[:, 0] << np.uint64(32)) | pair[:, 1]


# --- host-side session ------------------------------------------------------

class TelemetrySession:
    """Per-stepper holder: device histories for both phases plus the
    wall-clock phase ledger.  The first-ever bounded device call of each
    phase is tallied as `compile_s` (tracing + XLA compile dominate it;
    subsequent calls reuse the executable), the rest as `execute_s`."""

    def __init__(self, cfg, n_shards: int = 1):
        self.cfg = cfg
        self.n_shards = n_shards  # panel geometry on spatial runs
        self.phases: dict[str, float] = {}
        self._gossip: Optional[History] = None
        self._overlay: Optional[History] = None
        self._overlay_host_rows: list = []
        self._gossip_calls = 0
        self._overlay_calls = 0
        self._gossip_fetched: Optional[dict] = None
        self._overlay_fetched: Optional[dict] = None

    # --- phase ledger ---------------------------------------------------
    def add_phase(self, key: str, seconds: float) -> None:
        self.phases[key] = self.phases.get(key, 0.0) + seconds

    def tally_gossip_call(self, seconds: float) -> None:
        self.add_phase("compile_s" if self._gossip_calls == 0 else
                       "execute_s", seconds)
        self._gossip_calls += 1

    def tally_overlay_call(self, seconds: float) -> None:
        self.add_phase("compile_s" if self._overlay_calls == 0 else
                       "execute_s", seconds)
        self._overlay_calls += 1

    # --- phase-2 history ------------------------------------------------
    def begin_gossip(self):
        if self._gossip is None:
            cap = gossip_history_cap(self.cfg)
            hist = empty_history(cap, len(GOSSIP_COLS))
            spec = spatial_spec(self.cfg, self.n_shards)
            self._gossip = hist if spec is None else \
                SpatialBundle(hist=hist, panels=empty_panels(cap, spec))
        return self._gossip

    def end_gossip(self, hist) -> None:
        self._gossip = hist

    def reset_gossip(self) -> None:
        """Drop phase-2 history (a reset_state rerun records afresh)."""
        self._gossip = None
        self._gossip_fetched = None

    def gossip_snapshot(self) -> Optional[dict]:
        if self._gossip_fetched is None and self._gossip is not None:
            import time

            t0 = time.perf_counter()
            self._gossip_fetched = fetch_history(self._gossip)
            self.add_phase("fetch_s", time.perf_counter() - t0)
        return self._gossip_fetched

    # --- phase-1 history ------------------------------------------------
    def begin_overlay(self, cap: int) -> History:
        if self._overlay is None:
            self._overlay = empty_history(cap, len(OVERLAY_COLS))
        return self._overlay

    def end_overlay(self, hist: History) -> None:
        self._overlay = hist

    def overlay_host_row(self, row) -> None:
        """Host-side recording for the split-round overlay (its round is a
        host-driven call sequence; the per-round device_get it already pays
        carries the counters)."""
        self._overlay_host_rows.append([int(v) for v in row])

    def overlay_snapshot(self) -> Optional[dict]:
        if self._overlay_fetched is None:
            if self._overlay is not None:
                import time

                t0 = time.perf_counter()
                self._overlay_fetched = fetch_history(self._overlay)
                self.add_phase("fetch_s", time.perf_counter() - t0)
            elif self._overlay_host_rows:
                self._overlay_fetched = host_history(self._overlay_host_rows)
        return self._overlay_fetched


# --- report -----------------------------------------------------------------

@dataclasses.dataclass
class TelemetryReport:
    """Host-side view of one run's telemetry: phase ledger, throughput and
    the per-window trajectory (what the reference never had)."""

    n: int
    phases: dict
    overlay: Optional[dict] = None
    gossip: Optional[dict] = None
    overlay_clock_scale: float = 1.0

    def summary(self) -> dict:
        out: dict = {"phases_s": {k: round(v, 6)
                                  for k, v in sorted(self.phases.items())}}
        execute = self.phases.get("execute_s", 0.0) \
            + self.phases.get("compile_s", 0.0)
        if self.overlay:
            out["overlay_windows"] = self.overlay["count"]
            if self.overlay["truncated"]:
                out["overlay_truncated"] = True
        if self.gossip:
            cols, count = self.gossip["cols"], self.gossip["count"]
            out["gossip_windows"] = count
            if self.gossip["truncated"]:
                out["gossip_truncated"] = True
            if count:
                c = cols[:count]

                def col(name: str) -> np.ndarray:
                    return c[:, GCOL[name]]

                ticks = int(col("tick")[-1])
                msg = _msg64_col(c)
                out["sim_ticks"] = ticks
                out["total_message"] = int(msg[-1])
                if execute > 0:
                    out["node_updates_per_sec"] = round(
                        self.n * ticks / execute, 1)
                    out["messages_per_sec"] = round(int(msg[-1]) / execute, 1)
                per = {
                    "tick": col("tick").tolist(),
                    "received": col("received").tolist(),
                    "message": [int(v) for v in msg],
                    "crashed": col("crashed").tolist(),
                    "removed": col("removed").tolist(),
                    "mail_high": col("mail_high").tolist(),
                    "dropped": col("dropped").tolist(),
                    "overflow": col("overflow").tolist(),
                }
                # Optional trailing blocks, registry-driven (scenario
                # quartet only when a scenario ran, rumors_done only when
                # rumors completed, inflight depth only when a routed
                # exchange ran, relerr only on pushsum runs): a group is
                # emitted whole when the build carries its columns and
                # any is nonzero.
                for grp in OPTIONAL_BLOCK_GROUPS:
                    srcs = [src for _, src in grp]
                    have = cols.shape[1] > max(GCOL[s] for s in srcs)
                    if have and bool(np.stack([col(s)
                                               for s in srcs]).any()):
                        for key, src in grp:
                            per[key] = col(src).tolist()
                out["per_window"] = per
                out["deltas"] = {
                    "received": np.diff(col("received"),
                                        prepend=0).tolist(),
                    "message": np.diff(msg.astype(np.int64),
                                       prepend=np.int64(0)).tolist(),
                }
        return out

    def summary_block(self) -> str:
        """The `-telemetry-summary` end-of-run stdout block."""
        s = self.summary()
        ph = s.get("phases_s", {})
        lines = ["\n=== Telemetry ==="]
        lines.append("phases: " + " ".join(
            f"{k[:-2]} {v:.3f}s" for k, v in ph.items()) if ph
            else "phases: (none recorded)")
        if "overlay_windows" in s:
            lines.append(f"overlay: {s['overlay_windows']} windows")
        if "gossip_windows" in s:
            g = f"gossip: {s['gossip_windows']} windows"
            if "sim_ticks" in s:
                g += f", {s['sim_ticks']} simulated ms"
            lines.append(g)
        if "node_updates_per_sec" in s:
            lines.append(f"throughput: {s['node_updates_per_sec']:g} "
                         f"node-updates/s, {s['messages_per_sec']:g} "
                         "messages/s")
        return "\n".join(lines)
