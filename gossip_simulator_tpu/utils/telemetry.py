"""Device-resident telemetry: per-window history without leaving the fast path.

The reference's only observability is six polled atomics printed every 10 ms
(simulator.go:26-31); our windowed driver loop reproduces that surface but
pays one jit dispatch + one device->host stats round-trip per 10 simulated ms
(~2x wall-clock at n=1e7 through the TPU tunnel).  This module removes the
observability-vs-speed tradeoff: the bounded device-side while_loops
(epidemic/event `make_run_to_coverage_fn`, overlay `make_bounded_run`) write
one row of counters per poll window into a preallocated device `History`
buffer -- a handful of scalar ops against a window of O(n) work -- and the
host fetches the whole trajectory in ONE transfer at loop exit.

`replay_overlay` / `replay_gossip` then drive the fetched history through the
ordinary ProgressPrinter, producing stdout/JSONL per-window output
byte-identical to the windowed loop's (the golden CLI transcripts enforce
this), so a progress-printing or JSONL-logging run takes the fast path
whenever checkpointing is off.  `TelemetrySession` is the host-side holder a
backend carries: device histories for both phases plus the wall-clock phase
ledger (init / compile / execute / fetch); `TelemetryReport` turns it into
throughput numbers, per-window deltas and the `-telemetry-summary` block.

History rows are int32; the 64-bit total_message pair travels as two
bitcast int32 columns and is reassembled host-side (msg64 convention from
models/state.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import numpy as np

# Column layouts (one int32 matrix per phase keeps the per-window write a
# single row scatter instead of one per counter).  The four scenario
# columns (scenario.py) record the per-window fault trajectory -- crash
# waves, reboots, repaired edges, partition-suppressed sends -- on the
# same device-resident ride as the epidemic counters; they are constant 0
# on scenario-less runs and the replay functions never read them, so the
# replayed stdout/JSONL surface is unchanged.
GOSSIP_COLS = ("tick", "received", "msg_hi", "msg_lo", "crashed", "removed",
               "mail_high", "dropped", "overflow", "scen_crashed",
               "recovered", "repaired", "part_dropped", "rumors_done",
               "exchange_inflight_hwm", "relerr_ppb")
OVERLAY_COLS = ("clock", "makeups", "breakups", "dropped")

# Named column indices -- THE way to address a history column (schema v3
# names these in the JSONL header).  Positional literals ("the 14th
# column") drifted once per added column; every reader below and every
# external consumer (bench.py, utils/artifact.py, scripts) goes through
# these maps instead.
GCOL = {name: i for i, name in enumerate(GOSSIP_COLS)}
OCOL = {name: i for i, name in enumerate(OVERLAY_COLS)}


class History(NamedTuple):
    """Device-resident per-window ring: `idx` rows written (keeps counting
    past the capacity so truncation is detectable; writes saturate at the
    last row), `cols` the int32[cap, F] matrix."""

    idx: object  # int32[]
    cols: object  # int32[cap, F]


def empty_history(cap: int, ncols: int) -> History:
    import jax.numpy as jnp

    return History(idx=jnp.zeros((), jnp.int32),
                   cols=jnp.zeros((max(int(cap), 1), ncols), jnp.int32))


def record(hist: History, row) -> History:
    """Append one window's row (list of int32 scalars) device-side."""
    import jax.numpy as jnp

    cap = hist.cols.shape[0]
    vals = jnp.stack([jnp.asarray(v).astype(jnp.int32) for v in row])
    i = jnp.minimum(hist.idx, cap - 1)
    return History(idx=hist.idx + 1, cols=hist.cols.at[i].set(vals))


def gossip_probe(st, sir: bool, psum=None, pmax=None, rumors: int = 0,
                 inflight_hwm: int = 0, relerr=None):
    """One GOSSIP_COLS row from either epidemic engine's state (duck-typed
    like models/state.in_flight: EventState has the mail ring, SimState the
    pending ring).  `psum`/`pmax` are the sharded engines' cross-shard
    reductions for the per-shard quantities (removed flags, ring occupancy);
    the totals are already psum-replicated by the step functions.  `rumors`
    (static R; 0 = single-rumor) adds the count of rumors that have hit the
    coverage target -- rumor_done is replicated on every engine, so no
    reduction applies.  `inflight_hwm` (static, per engine build) is the
    high-water mark of exchange buffers alive at once on the sharded
    routed path: 0 = no collective in the program (single device /
    non-sharded), 1 = the serial route->drain loop, 2 = the
    double-buffered pipeline (-exchange-pipeline double -- one staged
    drain in flight behind the dispatched all_to_all).  `relerr` is the
    pushsum engines' per-window max relative error vs the true network
    mean, pre-scaled to int32 parts-per-billion (already pmax-replicated
    by the sharded step); None = not a numeric-gossip run, column 0."""
    import jax
    import jax.numpy as jnp

    I32 = jnp.int32
    z = jnp.zeros((), I32)
    if hasattr(st, "flags"):  # event engine
        from gossip_simulator_tpu.models.event import REMOVED

        removed = ((st.flags & REMOVED) > 0).sum(dtype=I32) if sir else z
        high = st.mail_cnt.max().astype(I32)
        dropped = st.mail_dropped
    else:
        removed = st.removed.sum(dtype=I32) if sir else z
        # Per-(slot, node) arrival-count high-water -- the ring engine's
        # analog of the fullest mailbox.
        high = st.pending.max().astype(I32)
        dropped = z
    if psum is not None:
        removed = psum(removed)
    if pmax is not None:
        high = pmax(high)
    msg = jax.lax.bitcast_convert_type(st.total_message, I32)
    rdone = (st.rumor_done[:rumors] >= 0).sum(dtype=I32) if rumors else z
    return [st.tick, st.total_received, msg[0], msg[1], st.total_crashed,
            removed, high, dropped, st.exchange_overflow,
            st.scen_crashed, st.scen_recovered, st.heal_repaired,
            st.part_dropped, rdone, jnp.asarray(inflight_hwm, I32),
            jnp.asarray(relerr, I32) if relerr is not None else z]


def overlay_probe(st):
    """One OVERLAY_COLS row from either overlay engine's state (the
    tick-faithful engine carries `tick`, the rounds engine `round`; the
    window counters are already global/replicated on both)."""
    clock = st.tick if hasattr(st, "tick") else st.round
    return [clock, st.win_makeups, st.win_breakups, st.mailbox_dropped]


def gossip_history_cap(cfg) -> int:
    """Phase-2 window capacity: every engine's poll window advances at least
    WINDOW_MS ticks in ticks mode (event.poll_window_steps rounds UP) and
    one round in rounds mode, so ceil(max_rounds / window) bounds the rows."""
    window = 1 if cfg.effective_time_mode == "rounds" else 10
    return max(1, -(-cfg.max_rounds // window) + 2)


def fetch_history(hist: Optional[History]) -> Optional[dict]:
    """ONE device->host transfer of a whole history buffer."""
    if hist is None:
        return None
    import jax

    idx, cols = jax.device_get((hist.idx, hist.cols))
    recorded = int(idx)
    cols = np.asarray(cols)
    return {"count": min(recorded, cols.shape[0]), "recorded": recorded,
            "truncated": recorded > cols.shape[0], "cols": cols}


def host_history(rows: list) -> Optional[dict]:
    """Same shape as fetch_history for host-side recorded rows (the split
    overlay round's host loop)."""
    if not rows:
        return None
    cols = np.asarray(rows, np.int32).reshape(len(rows), -1)
    return {"count": len(rows), "recorded": len(rows), "truncated": False,
            "cols": cols}


# --- replay -----------------------------------------------------------------

def replay_overlay(printer, hist: Optional[dict], clock_scale: float,
                   quiesced: bool = True) -> None:
    """Re-emit the phase-1 per-window lines exactly as the windowed loop
    would have: the quiescing window itself is never printed
    (simulator.go:227-230 prints only when *not* stabilizing)."""
    if not hist:
        return
    cols, count = hist["cols"], hist["count"]
    last = count - 1 if quiesced else count
    for i in range(max(0, last)):
        # clock_scale 1.0 (faithful ticks) reproduces float(tick) exactly;
        # the rounds engine's round * mean_delay is the windowed loop's
        # identical float expression.
        printer.overlay_window(int(cols[i, OCOL["breakups"]]),
                               int(cols[i, OCOL["makeups"]]),
                               float(cols[i, OCOL["clock"]]) * clock_scale)


def replay_gossip(printer, hist: Optional[dict], n: int) -> None:
    """Re-emit the phase-2 coverage lines: same float math as the windowed
    driver loop (coverage = int received / int n, pct rounded to 4)."""
    if not hist:
        return
    cols = hist["cols"]
    for i in range(hist["count"]):
        pct = (int(cols[i, GCOL["received"]]) / n if n else 0.0) * 100.0
        printer.coverage_window(round(pct, 4), float(cols[i, GCOL["tick"]]))


def _msg64_col(cols: np.ndarray) -> np.ndarray:
    """Reassemble the bitcast [hi, lo] int32 column pair into uint64."""
    hi = GCOL["msg_hi"]
    pair = cols[:, hi:hi + 2].astype(np.int32).view(np.uint32) \
        .astype(np.uint64)
    return (pair[:, 0] << np.uint64(32)) | pair[:, 1]


# --- host-side session ------------------------------------------------------

class TelemetrySession:
    """Per-stepper holder: device histories for both phases plus the
    wall-clock phase ledger.  The first-ever bounded device call of each
    phase is tallied as `compile_s` (tracing + XLA compile dominate it;
    subsequent calls reuse the executable), the rest as `execute_s`."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.phases: dict[str, float] = {}
        self._gossip: Optional[History] = None
        self._overlay: Optional[History] = None
        self._overlay_host_rows: list = []
        self._gossip_calls = 0
        self._overlay_calls = 0
        self._gossip_fetched: Optional[dict] = None
        self._overlay_fetched: Optional[dict] = None

    # --- phase ledger ---------------------------------------------------
    def add_phase(self, key: str, seconds: float) -> None:
        self.phases[key] = self.phases.get(key, 0.0) + seconds

    def tally_gossip_call(self, seconds: float) -> None:
        self.add_phase("compile_s" if self._gossip_calls == 0 else
                       "execute_s", seconds)
        self._gossip_calls += 1

    def tally_overlay_call(self, seconds: float) -> None:
        self.add_phase("compile_s" if self._overlay_calls == 0 else
                       "execute_s", seconds)
        self._overlay_calls += 1

    # --- phase-2 history ------------------------------------------------
    def begin_gossip(self) -> History:
        if self._gossip is None:
            self._gossip = empty_history(gossip_history_cap(self.cfg),
                                         len(GOSSIP_COLS))
        return self._gossip

    def end_gossip(self, hist: History) -> None:
        self._gossip = hist

    def reset_gossip(self) -> None:
        """Drop phase-2 history (a reset_state rerun records afresh)."""
        self._gossip = None
        self._gossip_fetched = None

    def gossip_snapshot(self) -> Optional[dict]:
        if self._gossip_fetched is None and self._gossip is not None:
            import time

            t0 = time.perf_counter()
            self._gossip_fetched = fetch_history(self._gossip)
            self.add_phase("fetch_s", time.perf_counter() - t0)
        return self._gossip_fetched

    # --- phase-1 history ------------------------------------------------
    def begin_overlay(self, cap: int) -> History:
        if self._overlay is None:
            self._overlay = empty_history(cap, len(OVERLAY_COLS))
        return self._overlay

    def end_overlay(self, hist: History) -> None:
        self._overlay = hist

    def overlay_host_row(self, row) -> None:
        """Host-side recording for the split-round overlay (its round is a
        host-driven call sequence; the per-round device_get it already pays
        carries the counters)."""
        self._overlay_host_rows.append([int(v) for v in row])

    def overlay_snapshot(self) -> Optional[dict]:
        if self._overlay_fetched is None:
            if self._overlay is not None:
                import time

                t0 = time.perf_counter()
                self._overlay_fetched = fetch_history(self._overlay)
                self.add_phase("fetch_s", time.perf_counter() - t0)
            elif self._overlay_host_rows:
                self._overlay_fetched = host_history(self._overlay_host_rows)
        return self._overlay_fetched


# --- report -----------------------------------------------------------------

@dataclasses.dataclass
class TelemetryReport:
    """Host-side view of one run's telemetry: phase ledger, throughput and
    the per-window trajectory (what the reference never had)."""

    n: int
    phases: dict
    overlay: Optional[dict] = None
    gossip: Optional[dict] = None
    overlay_clock_scale: float = 1.0

    def summary(self) -> dict:
        out: dict = {"phases_s": {k: round(v, 6)
                                  for k, v in sorted(self.phases.items())}}
        execute = self.phases.get("execute_s", 0.0) \
            + self.phases.get("compile_s", 0.0)
        if self.overlay:
            out["overlay_windows"] = self.overlay["count"]
            if self.overlay["truncated"]:
                out["overlay_truncated"] = True
        if self.gossip:
            cols, count = self.gossip["cols"], self.gossip["count"]
            out["gossip_windows"] = count
            if self.gossip["truncated"]:
                out["gossip_truncated"] = True
            if count:
                c = cols[:count]

                def col(name: str) -> np.ndarray:
                    return c[:, GCOL[name]]

                ticks = int(col("tick")[-1])
                msg = _msg64_col(c)
                out["sim_ticks"] = ticks
                out["total_message"] = int(msg[-1])
                if execute > 0:
                    out["node_updates_per_sec"] = round(
                        self.n * ticks / execute, 1)
                    out["messages_per_sec"] = round(int(msg[-1]) / execute, 1)
                per = {
                    "tick": col("tick").tolist(),
                    "received": col("received").tolist(),
                    "message": [int(v) for v in msg],
                    "crashed": col("crashed").tolist(),
                    "removed": col("removed").tolist(),
                    "mail_high": col("mail_high").tolist(),
                    "dropped": col("dropped").tolist(),
                    "overflow": col("overflow").tolist(),
                }
                scen = ("scen_crashed", "recovered", "repaired",
                        "part_dropped")
                have = cols.shape[1] > max(GCOL[s] for s in scen)
                if have and bool(np.stack([col(s) for s in scen]).any()):
                    # Scenario columns only when a scenario actually ran
                    # (all-zero columns would bloat every record).
                    per["scen_crashed"] = col("scen_crashed").tolist()
                    per["scen_recovered"] = col("recovered").tolist()
                    per["heal_repaired"] = col("repaired").tolist()
                    per["part_dropped"] = col("part_dropped").tolist()
                if (cols.shape[1] > GCOL["rumors_done"]
                        and bool(col("rumors_done").any())):
                    # Multi-rumor column only when rumors completed.
                    per["rumors_done"] = col("rumors_done").tolist()
                if (cols.shape[1] > GCOL["exchange_inflight_hwm"]
                        and bool(col("exchange_inflight_hwm").any())):
                    # Exchange-pipeline depth column only when a routed
                    # exchange ran (single-device builds record 0).
                    per["exchange_inflight_hwm"] = \
                        col("exchange_inflight_hwm").tolist()
                if (cols.shape[1] > GCOL["relerr_ppb"]
                        and bool(col("relerr_ppb").any())):
                    # Numeric-gossip error column only on pushsum runs
                    # (epidemic models record 0).
                    per["relerr_ppb"] = col("relerr_ppb").tolist()
                out["per_window"] = per
                out["deltas"] = {
                    "received": np.diff(col("received"),
                                        prepend=0).tolist(),
                    "message": np.diff(msg.astype(np.int64),
                                       prepend=np.int64(0)).tolist(),
                }
        return out

    def summary_block(self) -> str:
        """The `-telemetry-summary` end-of-run stdout block."""
        s = self.summary()
        ph = s.get("phases_s", {})
        lines = ["\n=== Telemetry ==="]
        lines.append("phases: " + " ".join(
            f"{k[:-2]} {v:.3f}s" for k, v in ph.items()) if ph
            else "phases: (none recorded)")
        if "overlay_windows" in s:
            lines.append(f"overlay: {s['overlay_windows']} windows")
        if "gossip_windows" in s:
            g = f"gossip: {s['gossip_windows']} windows"
            if "sim_ticks" in s:
                g += f", {s['sim_ticks']} simulated ms"
            lines.append(g)
        if "node_updates_per_sec" in s:
            lines.append(f"throughput: {s['node_updates_per_sec']:g} "
                         f"node-updates/s, {s['messages_per_sec']:g} "
                         "messages/s")
        return "\n".join(lines)
