"""Stateless, counter-based randomness for the simulator.

The reference uses Go's global, *unseeded* ``math/rand`` (simulator.go never
calls ``rand.Seed``), so runs are deterministic-per-Go-version by accident.
Here every random draw is derived from ``(seed, round, op)`` via
``jax.random.fold_in``, making runs reproducible by construction and letting
each jitted step be a pure function of ``(state, tick)``.

Op tags keep draws for different purposes independent within a tick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Op tags (arbitrary distinct constants).
OP_CRASH = 1
OP_DROP = 2
OP_DELAY = 3
OP_BOOTSTRAP = 4
OP_EVICT = 5
OP_REPLACE = 6
OP_SEED_NODE = 7
OP_GRAPH = 8
OP_PULL = 9
OP_REMOVE = 10
OP_DELAY_BK = 11  # overlay-ticks breakup-send delays (makeups use OP_DELAY)
# 12-14 are claimed by scenario.py (OP_SCENARIO/OP_HEAL/OP_HEAL_SEND).
OP_INJECT = 15  # multi-rumor source draws, keyed by rumor index (not tick)
OP_PUSHSUM = 16  # pushsum per-window emission delays, (tick, GLOBAL id)-keyed


def base_key(seed: int) -> jax.Array:
    return jax.random.PRNGKey(seed)


def tick_key(key: jax.Array, tick, op: int) -> jax.Array:
    """Key for operation `op` at round/tick `tick`."""
    return jax.random.fold_in(jax.random.fold_in(key, tick), op)


def bernoulli(key: jax.Array, p, shape, compat_reference: bool = False) -> jax.Array:
    """Bernoulli(p) mask.

    With ``compat_reference`` reproduces the reference's 1%-resolution
    truncation ``rand.Intn(100) < int(p*100)`` (simulator.go:172,180) under
    which p=0.001 is exactly 0.
    """
    if compat_reference:
        p = int(float(p) * 100) / 100.0
    if p <= 0.0:
        return jnp.zeros(shape, dtype=bool)
    if p >= 1.0:
        return jnp.ones(shape, dtype=bool)
    return jax.random.bernoulli(key, p, shape)


def row_keys(key: jax.Array, rows: jax.Array) -> jax.Array:
    """One derived key per row id (vmapped fold_in).  Row-keyed draws make a
    gathered subset of rows compute exactly the values the dense computation
    would -- the compaction paths sample only the rows they touch while
    staying bit-identical to the dense fallback."""
    return jax.vmap(jax.random.fold_in, in_axes=(None, 0))(key, rows)


def row_bernoulli(key: jax.Array, p, rows: jax.Array, k: int) -> jax.Array:
    """Bernoulli(p) mask of shape (len(rows), k), row-keyed (see row_keys)."""
    m = rows.shape[0]
    if p <= 0.0:
        return jnp.zeros((m, k), dtype=bool)
    if p >= 1.0:
        return jnp.ones((m, k), dtype=bool)
    ks = row_keys(key, rows)
    return jax.vmap(lambda kk: jax.random.bernoulli(kk, p, (k,)))(ks)


def row_randint(key: jax.Array, n: int, rows: jax.Array, k: int) -> jax.Array:
    """Uniform [0, n) int32 of shape (len(rows), k), row-keyed (see
    row_keys) -- the peer draws of the push-pull round, keyed so the
    wave-compacted path samples exactly the dense path's values."""
    ks = row_keys(key, rows)
    return jax.vmap(
        lambda kk: jax.random.randint(kk, (k,), 0, n, dtype=jnp.int32))(ks)


def row_uniform_delay(key: jax.Array, low: int, high: int,
                      rows: jax.Array) -> jax.Array:
    """Row-keyed integer delay in [low, high) ticks, clamped to >= 1
    (see uniform_delay)."""
    ks = row_keys(key, rows)
    d = jax.vmap(
        lambda kk: jax.random.randint(kk, (), low, high, dtype=jnp.int32))(ks)
    return jnp.maximum(d, 1)


def uniform_delay(key: jax.Array, low: int, high: int, shape) -> jax.Array:
    """Integer ticks uniform in [low, high), matching RandomNetworkDelay
    (simulator.go:166-168); clamped to >= 1 so a message never lands in the
    current tick's already-drained ring slot."""
    d = jax.random.randint(key, shape, low, high, dtype=jnp.int32)
    return jnp.maximum(d, 1)


def randint_excluding(key: jax.Array, n: int, shape, *exclude) -> jax.Array:
    """Uniform draw from [0, n) then deterministically stepped off any of the
    excluded values (per-element arrays).  Mirrors the reference's non-uniform
    collision patches (the ``(id+1)%N`` fix at simulator.go:98-100 and the
    retry loop at simulator.go:87-89) with a bounded, jit-friendly remap:
    after k passes over k excluded values the result avoids all of them."""
    r = jax.random.randint(key, shape, 0, n, dtype=jnp.int32)
    k = len(exclude)
    for _ in range(k + 1):
        for e in exclude:
            r = jnp.where(r == e, (r + 1) % n, r)
    return r
