"""Round-indexed state snapshots (SURVEY §5.4: the reference has none; at
100M-node scale a resumable snapshot is nearly free and worth having).

Format: one ``.npz`` per snapshot holding the state pytree's leaves plus a
JSON sidecar of counters.  Orbax would also work, but npz keeps the native
(non-JAX) backends checkpointable with zero extra deps.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Optional

import numpy as np

from gossip_simulator_tpu.utils.metrics import Stats


def _digest(path: str) -> str:
    """sha256 of the snapshot file's bytes (streamed; snapshots are GBs
    at flagship scale)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save(ckpt_dir: str, window: int, tree: dict[str, Any], stats: Stats,
         prefix: str = "state", extra_meta: Optional[dict] = None) -> str:
    """`prefix` namespaces the two phases: phase-2 snapshots are
    ``state_*``, phase-1 overlay snapshots ``overlay_*``.  ``latest()``
    sorts lexicographically, and "overlay" < "state", so any phase-2
    snapshot outranks every phase-1 one -- resuming always continues from
    the furthest phase.

    Atomic: both files are written to ``.tmp`` names and os.replace'd
    into place -- a crash mid-save leaves either the previous snapshot or
    none, never a torn one (``latest()`` ignores the tmp names).  The
    sidecar carries a sha256 content digest; ``load()`` verifies it, so a
    snapshot corrupted AFTER a clean save (truncation, bit rot, a partial
    copy between filesystems) is rejected with a clear error instead of
    restoring garbage."""
    from gossip_simulator_tpu.utils import trace as _trace

    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"{prefix}_{window:08d}.npz")
    with _trace.span("checkpoint.save", cat="io", prefix=prefix,
                     window=window):
        # np.array (COPY), not np.asarray: on the CPU platform asarray of
        # a device buffer is zero-copy, and the donating window fns reuse
        # the buffer on the next step -- the "snapshot" would silently
        # track live state until savez reads it (the PR-2 aliasing bug).
        arrays = {k: np.array(v) for k, v in tree.items()}
        tmp = path + ".tmp"
        # np.savez appends ".npz" to names without it -- write under the
        # real suffix structure by handing it a file object.
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **arrays)
        meta = {"window": window, **(extra_meta or {}), **stats.to_dict(),
                "sha256": _digest(tmp)}
        with open(path + ".json.tmp", "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        # Sidecar lands first: a crash between the two replaces leaves a
        # (new json, old/no npz) pair, which load() rejects via the digest
        # -- never silently restores a mismatched pair.
        os.replace(path + ".json.tmp", path + ".json")
        os.replace(tmp, path)
    return path


def latest(ckpt_dir: str) -> Optional[str]:
    if not os.path.isdir(ckpt_dir):
        return None
    snaps = sorted(p for p in os.listdir(ckpt_dir) if p.endswith(".npz"))
    return os.path.join(ckpt_dir, snaps[-1]) if snaps else None


def prune(ckpt_dir: str, keep: int, prefix: str = "state") -> list[str]:
    """Retention: remove all but the newest `keep` ``{prefix}_*.npz``
    snapshots, each with its ``.json`` sha256 sidecar, plus any stale
    ``.tmp`` partials a crashed save left behind (``latest()`` already
    ignores them, but a serve loop resharding repeatedly must not fill
    the disk with them either).  Called AFTER a successful save, so the
    newest snapshot is always the one just written; single-writer by
    design (the driver checkpoints from the primary host only).  Returns
    the removed paths.  ``keep <= 0`` means keep everything."""
    removed: list[str] = []
    if keep <= 0 or not os.path.isdir(ckpt_dir):
        return removed
    names = os.listdir(ckpt_dir)
    snaps = sorted(p for p in names
                   if p.startswith(prefix + "_") and p.endswith(".npz"))
    doomed = snaps[:-keep] if keep < len(snaps) else []
    partials = [p for p in names
                if p.startswith(prefix + "_") and p.endswith(".tmp")]
    for name in doomed:
        for f in (name, name + ".json"):
            path = os.path.join(ckpt_dir, f)
            if os.path.exists(path):
                os.remove(path)
                removed.append(path)
    for name in partials:
        path = os.path.join(ckpt_dir, name)
        if os.path.exists(path):
            os.remove(path)
            removed.append(path)
    return removed


def load(path: str) -> tuple[dict[str, np.ndarray], dict]:
    """Load one snapshot, verifying the sidecar's sha256 content digest
    when present (pre-digest snapshots load without the check).  A
    truncated, torn or bit-rotted file raises ValueError naming the
    snapshot instead of feeding garbage to the restore path."""
    from gossip_simulator_tpu.utils import trace as _trace

    with _trace.span("checkpoint.load", cat="io",
                     file=os.path.basename(path)):
        meta = {}
        if os.path.exists(path + ".json"):
            with open(path + ".json") as f:
                meta = json.load(f)
        want = meta.get("sha256")
        if want is not None:
            got = _digest(path)
            if got != want:
                raise ValueError(
                    f"checkpoint {path} is corrupt: content digest "
                    f"{got[:16]}… does not match its sidecar's "
                    f"{want[:16]}… (truncated or torn write?) -- delete "
                    "it and resume from an older snapshot")
        try:
            arrays = dict(np.load(path))
        except Exception as e:
            raise ValueError(
                f"checkpoint {path} is unreadable ({e!r}); delete it and "
                "resume from an older snapshot") from e
        return arrays, meta


def verify_provenance(meta: dict, path: str, *, run_id: str,
                      now_window: int, max_stale: int = 0) -> None:
    """The host-loss recovery gate (ISSUE 20 satellite 2): refuse BY NAME
    to restore a snapshot from a different run or one staler than
    ``-recover-max-stale`` windows behind the loss point.  A survivor that
    silently resurrects a foreign or ancient snapshot would "recover" into
    a different simulation; both refusals are ValueError so the drill
    tests can pin the message.

    `run_id` empty means this run makes no provenance claim (plain
    -resume); pre-provenance snapshots (no run_id in the sidecar) pass the
    run check for backward compatibility but still face the staleness
    gate.  `max_stale <= 0` disables the staleness gate."""
    theirs = meta.get("run_id")
    if run_id and theirs is not None and theirs != run_id:
        raise ValueError(
            f"checkpoint {path} was written by run {theirs} but this "
            f"supervisor run is {run_id}; refusing to restore a foreign "
            "snapshot (pass its -run-id explicitly to adopt it)")
    if max_stale > 0:
        behind = now_window - int(meta.get("window", 0))
        if behind > max_stale:
            raise ValueError(
                f"checkpoint {path} is {behind} window(s) behind the loss "
                f"point (window {now_window}), over the -recover-max-stale "
                f"limit of {max_stale}; refusing to resurrect stale state "
                "-- lower -checkpoint-every or raise -recover-max-stale")


def prepare_restore_tree(tree: dict, cfg, n_shards: int) -> dict:
    """Shared snapshot validation + coercion for the jax and sharded
    backends' ``load_state_pytree``: engine gate, n check, legacy-field
    coercion (pre-packed-flags event snapshots, pre-widening scalar
    total_message), and the event mail-ring geometry check with per-shard
    slot repack on drift.  Returns a new dict of host arrays ready for
    device placement; raises ValueError with a restore-specific message on
    any mismatch.  ``n_shards`` is 1 for the single-device backend; the
    event ring is ``n_shards`` per-shard rings concatenated, so event
    snapshots restore onto the same shard count only."""
    from gossip_simulator_tpu.models import epidemic, event

    ckpt_engine = "event" if "mail_ids" in tree else "ring"
    if ckpt_engine != cfg.engine_resolved:
        raise ValueError(
            f"checkpoint was written by the {ckpt_engine} engine but "
            f"this run resolves to {cfg.engine_resolved}; pass "
            f"-engine {ckpt_engine} to restore it")
    # Model gate (the word-width rejection pattern): pushsum snapshots
    # carry fixed-point mass columns an epidemic run has no slot for, and
    # an epidemic snapshot has no mass to average -- both directions are
    # rejected by name rather than coerced.
    ckpt_pushsum = "mass" in tree
    if ckpt_pushsum and cfg.model != "pushsum":
        raise ValueError(
            "checkpoint was written by the pushsum numeric-gossip model "
            "(it carries fixed-point mass columns) but this run's model "
            f"is {cfg.model}; pass -model pushsum to restore it")
    if cfg.model == "pushsum" and not ckpt_pushsum:
        raise ValueError(
            "checkpoint was written by an epidemic-model run (it has no "
            "mass columns) but this run has -model pushsum; restore it "
            "without -model pushsum, or restart the pushsum run from "
            "scratch")
    tree = dict(tree)
    if ckpt_engine == "event" and "received" in tree:
        # Pre-packed-flags event snapshot: fold the two bool arrays into
        # the uint8 flags layout (bit0 received, bit1 crashed).
        tree["flags"] = (
            tree.pop("received").astype(np.uint8)
            + tree.pop("crashed").astype(np.uint8) * 2)
    n = int(tree["flags" if ckpt_engine == "event"
                 else "received"].shape[0])
    if n != cfg.n:
        raise ValueError(
            f"checkpoint has n={n} but this run has n={cfg.n}")
    if (cfg.protocol == "pushpull" and "friends" in tree
            and tuple(tree["friends"].shape[1:]) != (1,)):
        # Pre-round-5 pushpull snapshot: friends was the full (n, fanout)
        # table.  The protocol never reads it, but graphs.generate now
        # returns a one-column placeholder and every traced step was built
        # on that shape -- coerce instead of silently carrying the old
        # geometry into a shape-mismatched restore (advisor r5).
        tree["friends"] = np.full((n, 1), -1, np.int32)
        tree["friend_cnt"] = np.zeros((n,), np.int32)
    # --- multi-rumor traffic leaves (models/state.py rumor axis) ----------
    ckpt_multi = ("rumor_words" in tree
                  and tuple(np.asarray(tree["rumor_words"]).shape) != (1, 1))
    if cfg.multi_rumor and not ckpt_multi:
        raise ValueError(
            "checkpoint was written by a single-rumor run but this run "
            f"has -rumors {cfg.rumors} -traffic {cfg.traffic}; the "
            "snapshot does not record which rumors were in flight -- "
            "restore it with -rumors 1 -traffic oneshot, or restart the "
            "multi-rumor run from scratch")
    if ckpt_multi and not cfg.multi_rumor:
        raise ValueError(
            "checkpoint carries multi-rumor state "
            f"({int(np.asarray(tree['rumor_recv']).shape[0])} rumor "
            "lanes) but this run is single-rumor; restore with the "
            "snapshot's -rumors / -traffic flags")
    if cfg.model == "pushsum":
        # No rumor axis to backfill -- PushSumState has no rumor leaves.
        want_cols = np.asarray(tree["mass"]).shape[1]
        from gossip_simulator_tpu.models import pushsum as _ps

        if want_cols != _ps.mass_cols(cfg):
            raise ValueError(
                f"checkpoint mass is {want_cols} limb column(s) wide but "
                f"-pushsum-dim {cfg.pushsum_dim} needs "
                f"{_ps.mass_cols(cfg)}; restore with the snapshot's "
                "-pushsum-dim")
    elif cfg.multi_rumor:
        ckpt_w = int(np.asarray(tree["rumor_words"]).shape[1])
        if ckpt_w != cfg.rumor_word_count:
            raise ValueError(
                f"checkpoint rumor bitmask is {ckpt_w} word(s) wide but "
                f"-rumors {cfg.rumors} needs {cfg.rumor_word_count} "
                "(= ceil(R/32)); restore with the snapshot's -rumors")
    else:
        # Legacy (pre-rumor-axis) snapshot into a single-rumor run:
        # backfill the 1-element placeholders (nothing was in flight
        # on an axis that did not exist).
        u1 = np.zeros((1, 1), np.uint32)
        fills = {"rumor_words": u1, "rumor_recv": np.zeros((1,), np.int32),
                 "rumor_done": np.full((1,), -1, np.int32)}
        if ckpt_engine == "event":
            fills["mail_words"] = u1
        else:
            fills["pending_rumors"] = np.zeros((1, 1, 1), np.int32)
        for k, v in fills.items():
            if k not in tree:
                tree[k] = v
    if ckpt_engine == "event":
        if cfg.model == "pushsum":
            # Pushsum sizes its ring for emission volume (every live node
            # emits every window), so its own module is the geometry
            # authority; the mail_mass limb columns ride the repack as the
            # dtype-agnostic `words` companion.
            from gossip_simulator_tpu.models import pushsum as geo
        else:
            geo = event
        n_local = n // n_shards
        dw = geo.ring_windows(cfg)
        ncap = geo.slot_cap(cfg, n_local)
        nchunk = geo.drain_chunk(cfg, n_local)
        ntail = geo.ring_tail(cfg, n_local)
        per_new = dw * ncap + ntail
        geom = tree.pop("mail_geom", None)
        s_ckpt = (int(geom[2]) if geom is not None and len(geom) > 2 else 1)
        if tuple(tree["mail_cnt"].shape) != (s_ckpt, dw):
            raise ValueError(
                "checkpoint window-ring shape "
                f"{tuple(tree['mail_cnt'].shape)} does not match its "
                f"{s_ckpt} shard(s) x this config's {dw} windows; restore "
                "with the snapshot's -delaylow/-delayhigh")
        if "sup_cnt" not in tree:
            # Pre-dup-suppression snapshot (rounds <= 4): no deferred
            # duplicate credits pending.  (Crediting is unconditional in
            # the window step, so restoring a suppress-on snapshot into a
            # suppress-off run -- or vice versa -- stays consistent.)
            tree["sup_cnt"] = np.zeros((s_ckpt, dw), np.int32)
        mail_len = int(tree["mail_ids"].shape[0])
        if geom is None:
            # Legacy snapshot without geometry metadata: accept only an
            # exact-layout match (repacking blind would mis-index slots).
            if n_shards != 1 or mail_len != per_new:
                raise ValueError(
                    f"checkpoint mail-ring geometry ({mail_len},) does not "
                    f"match this config's ({n_shards * per_new},) and the "
                    "snapshot predates geometry metadata; restore with the "
                    "same -delaylow/-delayhigh/-event-slot-cap/-event-chunk "
                    "it was written with, single-device")
        else:
            ocap, ochunk = int(geom[0]), int(geom[1])
            # The tail is derived, not stored: recover it from the actual
            # length (pre-round-5 snapshots have tail == chunk; newer ones
            # ring_tail).  Anything below the chunk contradicts every
            # layout that ever existed.
            per_old = mail_len // s_ckpt
            otail = per_old - dw * ocap
            if mail_len % s_ckpt or otail < ochunk:
                raise ValueError(
                    f"checkpoint mail_ids length {mail_len} contradicts "
                    f"its stored geometry (cap={ocap}, chunk={ochunk}, "
                    f"{s_ckpt} shard(s))")
            comp_key = ("mail_mass" if cfg.model == "pushsum"
                        else ("mail_words" if cfg.multi_rumor else None))
            mw = np.asarray(tree[comp_key]) if comp_key else None
            if s_ckpt != n_shards:
                # Shard-count resharding (round 5): decode every in-flight
                # entry to its GLOBAL destination, re-bucket under the new
                # shard count, and re-pack in the new geometry.  The rumor
                # payload words ride the identical re-bucketing.
                mail2, cnt2, sup2, lost, mw2 = reshard_mail_rings(
                    np.asarray(tree["mail_ids"]),
                    np.asarray(tree["mail_cnt"]),
                    np.asarray(tree["sup_cnt"]), cfg, s_ckpt, n_shards,
                    dw, ocap, otail, words=mw, geom=geo)
                tree["mail_ids"], tree["mail_cnt"] = mail2, cnt2
                tree["sup_cnt"] = sup2
                if mw2 is not None:
                    tree[comp_key] = mw2
                tree["mail_dropped"] = np.asarray(
                    tree["mail_dropped"]) + np.int32(lost)
            elif per_old != per_new or ocap != ncap:
                old = np.asarray(tree["mail_ids"])
                cnt = np.asarray(tree["mail_cnt"])
                mails, cnts, words, lost = [], [], [], 0
                for sh in range(n_shards):
                    m, c, sl, w2 = repack_mail_ring(
                        old[sh * per_old:(sh + 1) * per_old], cnt[sh],
                        ocap, otail, ncap, ntail, dw,
                        words=(mw[sh * per_old:(sh + 1) * per_old]
                               if mw is not None else None))
                    mails.append(m)
                    cnts.append(c)
                    if w2 is not None:
                        words.append(w2)
                    lost += sl
                tree["mail_ids"] = np.concatenate(mails)
                tree["mail_cnt"] = np.stack(cnts)
                if words:
                    tree[comp_key] = np.concatenate(words)
                tree["mail_dropped"] = np.asarray(
                    tree["mail_dropped"]) + np.int32(lost)
    else:
        d = epidemic.ring_depth(cfg)
        if tuple(tree["pending"].shape) != (d, n):
            raise ValueError(
                f"checkpoint delay ring {tuple(tree['pending'].shape)} "
                f"does not match this config's ({d}, {n}); restore with "
                "the snapshot's -delaylow/-delayhigh/-time-mode")
    tm = np.asarray(tree["total_message"])
    if tm.ndim == 0:
        # Pre-widening snapshot: scalar int32 counter -> [hi, lo] pair.
        # & 0xFFFFFFFF also recovers a counter that had already wrapped
        # negative (one int32 wrap reinterprets to the correct low word).
        tree["total_message"] = np.asarray(
            [0, int(tm) & 0xFFFFFFFF], dtype=np.uint32)
    # --- fault-scenario fields (scenario.py) ------------------------------
    from gossip_simulator_tpu import scenario as _scen

    want_down = _scen.down_shape(cfg.faults_enabled, n)
    if "down_since" not in tree:
        # Pre-scenario snapshot: no crash clocks in flight.
        tree["down_since"] = np.full((want_down,), -1, np.int32)
    elif int(np.asarray(tree["down_since"]).shape[0]) != want_down:
        if int(np.asarray(tree["down_since"]).shape[0]) == 1:
            # Fault-free snapshot resuming INTO a scenario run: every
            # crash so far has an unknown crash time (the placeholder
            # held none), which -1 encodes exactly.
            tree["down_since"] = np.full((want_down,), -1, np.int32)
        else:
            raise ValueError(
                "checkpoint carries a full fault-scenario crash clock "
                f"({int(np.asarray(tree['down_since']).shape[0])} rows) "
                "but this run's fault machinery is off; restore with the "
                "snapshot's -scenario/-overlay-heal flags")
    for f in ("scen_crashed", "scen_recovered", "part_dropped",
              "heal_repaired"):
        if f not in tree:
            tree[f] = np.zeros((), np.int32)
    # Spatial-telemetry exchange counters (models/state.init_exch_counts):
    # per-shard diagnostic gauges, not trajectory state.  Their width
    # depends on the RESTORING run's shard count and -telemetry-spatial
    # flag, so rebuild them at zero rather than coercing the snapshot's
    # (a resumed run's traffic matrix restarts at the resume window).
    w = (n_shards + 2
         if (cfg.telemetry_spatial_enabled and n_shards > 1) else 1)
    tree["exch_counts"] = np.zeros((n_shards, w), np.int32)
    return tree


def prepare_overlay_restore_tree(tree: dict, cfg, n_shards: int) -> dict:
    """Phase-1 counterpart of prepare_restore_tree: validate an overlay
    snapshot (rounds OverlayState or ticks OverlayTickState) against this
    run's config before the stepper re-shards it.  Unlike the phase-2
    mail ring there is no repack path -- the packed window ring's slot
    capacity and the emission-buffer widths are derived sizes, so the
    snapshot restores only under geometry-identical settings; every
    mismatch gets a restore-specific error naming the flag to fix."""
    from gossip_simulator_tpu.models import overlay_ticks as ot

    ckpt_mode = "ticks" if "ring_dst" in tree else "rounds"
    if cfg.graph != "overlay":
        raise ValueError(
            "snapshot holds mid-construction overlay state but this run "
            f"has -graph {cfg.graph}; restore with -graph overlay")
    if ckpt_mode != cfg.overlay_mode_resolved:
        raise ValueError(
            f"overlay checkpoint was written by the {ckpt_mode} engine "
            f"but this run resolves to {cfg.overlay_mode_resolved}; pass "
            f"-overlay-mode {ckpt_mode} to restore it")
    tree = dict(tree)
    n, k = (int(d) for d in tree["friends"].shape)
    if n != cfg.n:
        raise ValueError(f"checkpoint has n={n} but this run has n={cfg.n}")
    if ckpt_mode == "rounds":
        from gossip_simulator_tpu.models import overlay as _ov

        # Target spill size = what init_state would build for this run
        # (single-device: burst-sized at the static-boot band, round 7;
        # sharded: the flat floor -- the hook path never spills).
        sc = (_ov.spill_cap_for(cfg, n) if n_shards == 1
              else (_ov.SPILL_CAP
                    if _ov.spill_enabled(cfg.mailbox_cap_for(n // n_shards))
                    else 0))
        if n_shards > 1:
            # The sharded rounds engine's routed delivery has no spill
            # path (overlay_state_specs note): live pairs restored onto a
            # mesh would sit in pending_emissions forever and block
            # quiescence.  Empty buffers restore fine.
            for f in ("mk_spill", "bk_spill"):
                if f in tree and (np.asarray(tree[f])[1] >= 0).any():
                    raise ValueError(
                        f"snapshot holds undelivered {f} overflow pairs; "
                        "the sharded overlay engine cannot deliver them "
                        "-- finish phase 1 (or at least drain the spill) "
                        "single-device before resharding")
        for f in ("mk_spill", "bk_spill"):
            if f not in tree:
                # Pre-round-5 snapshot: no overflow spill in flight.
                tree[f] = np.full((2, sc + 1), -1, np.int32)
            elif tuple(tree[f].shape) != (2, sc + 1):
                # Size drift (e.g. SPILL_CAP change or a cap-band move):
                # re-pad, preserving any in-flight pairs; pairs beyond the
                # new size would be lost -- reject that instead.
                old_arr = np.asarray(tree[f])
                live = old_arr[:, old_arr[1] >= 0]
                if live.shape[1] > sc:
                    raise ValueError(
                        f"checkpoint {f} holds {live.shape[1]} in-flight "
                        f"pairs but this build's spill capacity is {sc}")
                pad = np.full((2, sc + 1), -1, np.int32)
                pad[:, :live.shape[1]] = live
                tree[f] = pad
    if k != cfg.max_degree:
        raise ValueError(
            f"checkpoint friend lists have capacity {k} but this config's "
            f"max degree is {cfg.max_degree}; restore with the snapshot's "
            "-fanout/-fanin")
    n_local = n // n_shards
    if ckpt_mode == "ticks":
        # Round-7 spill coercion, mirroring the rounds branch above: the
        # ticks engine's mailbox-overflow spill (overlay_ticks.spill) is
        # (pay, packed-key) pairs; pre-round-7 snapshots have no overflow
        # in flight, the sharded engine has no spill delivery (live pairs
        # would block quiescence forever), and size drift re-pads
        # preserving in-flight pairs.
        sc = ot.ticks_spill_cap(cfg) if n_shards == 1 else 0
        if n_shards > 1 and "spill" in tree and (
                np.asarray(tree["spill"])[1] >= 0).any():
            raise ValueError(
                "snapshot holds undelivered ticks-overlay spill overflow "
                "pairs; the sharded overlay engine cannot deliver them -- "
                "finish phase 1 (or at least drain the spill) "
                "single-device before resharding")
        if "spill" not in tree:
            tree["spill"] = np.full((2, sc + 1), -1, np.int32)
        elif tuple(tree["spill"].shape) != (2, sc + 1):
            old_arr = np.asarray(tree["spill"])
            live = old_arr[:, old_arr[1] >= 0]
            if live.shape[1] > sc:
                raise ValueError(
                    f"checkpoint spill holds {live.shape[1]} in-flight "
                    f"pairs but this build's spill capacity is {sc}")
            pad = np.full((2, sc + 1), -1, np.int32)
            pad[:, :live.shape[1]] = live
            tree["spill"] = pad
        dw = ot.ring_windows(cfg)
        if tuple(tree["ring_cnt"].shape) != (n_shards, dw):
            raise ValueError(
                f"checkpoint window-ring shape {tuple(tree['ring_cnt'].shape)}"
                f" does not match this config's ({n_shards}, {dw}); restore "
                "on the snapshot's device count with its "
                "-delaylow/-delayhigh")
        cap = ot.slot_cap(cfg, n_local if n_shards > 1 else None)
        want = n_shards * (dw * cap + 1)
        if int(tree["ring_dst"].shape[0]) != want:
            raise ValueError(
                f"checkpoint ring length {int(tree['ring_dst'].shape[0])} "
                f"does not match this config's {want} (slot cap {cap} x "
                f"{dw} windows over {n_shards} shard(s))")
    else:
        cap_mb = cfg.mailbox_cap_for(n_local)
        if int(tree["mk_dst"].shape[0]) != cap_mb:
            raise ValueError(
                f"checkpoint emission buffers are {int(tree['mk_dst'].shape[0])}"
                f" wide but this config's mailbox cap gives {cap_mb}; "
                "restore with the snapshot's -mailbox-cap / device count")
    return tree


def reshard_mail_rings(mail: np.ndarray, cnt: np.ndarray, sup: np.ndarray,
                       cfg, s_old: int, s_new: int, dw: int, ocap: int,
                       otail: int, words: Optional[np.ndarray] = None,
                       geom=None):
    """Re-bucket S_old concatenated per-shard mail rings onto S_new shards
    (models/event.py packing: entry = dst_local * B + off, SIR triggers at
    trigger_base(n_local) + id * B + off -- both depend on the PER-SHARD
    row count, so every in-flight entry is decoded to its global
    destination and re-encoded).  Within a new (shard, slot) entries keep
    old-shard-major order -- a deterministic re-choice of arrival order
    within the window, the same class of re-ordering the sharded engine's
    batch routing already performs.  Deferred duplicate credits (sup_cnt)
    are only ever summed across shards, so the per-slot totals land on
    shard 0.  Entries past the new slot capacity are dropped (counted).
    `words` (multi-rumor payload word rings or pushsum mail_mass limbs,
    same concatenated layout, dtype-agnostic) rides the identical
    re-bucketing.  `geom` overrides the slot-geometry module (default the
    event engine; pushsum snapshots pass their own module, whose ring is
    sized for emission volume).  Returns (mail, cnt, sup, lost, words) in
    the new geometry (words None when not given)."""
    from gossip_simulator_tpu.models import event

    geo = geom if geom is not None else event
    n = cfg.n
    b = geo.batch_ticks(cfg)
    nlo, nln = n // s_old, n // s_new
    ncap = geo.slot_cap(cfg, nln)
    ntail = geo.ring_tail(cfg, nln)
    per_old, per_new = dw * ocap + otail, dw * ncap + ntail
    sir = cfg.protocol == "sir"
    tbo, tbn = event.trigger_base(nlo, b), event.trigger_base(nln, b)
    new_mail = np.zeros((s_new * per_new,), np.int32)
    new_cnt = np.zeros((s_new, dw), np.int32)
    new_words = (np.zeros((s_new * per_new, words.shape[1]), words.dtype)
                 if words is not None else None)
    lost = 0
    for slot in range(dw):
        segs = []
        for sh in range(s_old):
            c = int(cnt[sh, slot])
            at0 = sh * per_old + slot * ocap
            seg = mail[at0:at0 + c].astype(np.int64)
            trig = seg >= tbo if sir else np.zeros(seg.shape, bool)
            base = np.where(trig, seg - tbo, seg)
            gid = base // b + sh * nlo
            off = base % b
            segs.append((gid, off, trig, at0 + np.arange(c)))
        gid = np.concatenate([s[0] for s in segs])
        off = np.concatenate([s[1] for s in segs])
        trig = np.concatenate([s[2] for s in segs])
        pos = np.concatenate([s[3] for s in segs])
        nsh = gid // nln
        ndl = gid % nln
        ent = np.where(trig, tbn + ndl * b + off, ndl * b + off)
        for t in range(s_new):
            sel = nsh == t
            e = ent[sel].astype(np.int32)
            take = min(len(e), ncap)
            lost += len(e) - take
            at = t * per_new + slot * ncap
            new_mail[at:at + take] = e[:take]
            if new_words is not None:
                new_words[at:at + take] = words[pos[sel][:take].astype(
                    np.int64)]
            new_cnt[t, slot] = take
    new_sup = np.zeros((s_new, dw), np.int32)
    new_sup[0] = sup.astype(np.int64).sum(axis=0)
    return new_mail, new_cnt, new_sup, lost, new_words


def repack_mail_ring(mail: np.ndarray, cnt: np.ndarray, ocap: int,
                     otail: int, ncap: int, ntail: int, dw: int,
                     words: Optional[np.ndarray] = None):
    """Repack one packed mail ring (models/event.py layout: slot s occupies
    [s*cap, (s+1)*cap), plus a `tail` slack region) from slot geometry
    (ocap, otail) to (ncap, ntail) -- snapshots written under different
    -event-* flags or an auto sizing that changed.  Entries beyond the new
    capacity are dropped (returned in `lost`, counted like any overflow).

    `cnt` is the per-slot entry count, shape (dw,); `words` (multi-rumor
    payload word ring, same layout) moves with its entries.  Returns
    (new_mail, clamped_cnt, lost, new_words) -- words None when not
    given."""
    if mail.shape[0] != dw * ocap + otail:
        raise ValueError(
            f"mail ring length {mail.shape[0]} contradicts its geometry "
            f"(cap={ocap}, tail={otail}, dw={dw})")
    new = np.zeros((dw * ncap + ntail,), mail.dtype)
    new_words = (np.zeros((dw * ncap + ntail, words.shape[1]), words.dtype)
                 if words is not None else None)
    lost = 0
    for s in range(dw):
        take = min(int(cnt[s]), ncap)
        lost += int(cnt[s]) - take
        new[s * ncap:s * ncap + take] = mail[s * ocap:s * ocap + take]
        if new_words is not None:
            new_words[s * ncap:s * ncap + take] = \
                words[s * ocap:s * ocap + take]
    return new, np.minimum(cnt, ncap), lost, new_words
