"""Round-indexed state snapshots (SURVEY §5.4: the reference has none; at
100M-node scale a resumable snapshot is nearly free and worth having).

Format: one ``.npz`` per snapshot holding the state pytree's leaves plus a
JSON sidecar of counters.  Orbax would also work, but npz keeps the native
(non-JAX) backends checkpointable with zero extra deps.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np

from gossip_simulator_tpu.utils.metrics import Stats


def save(ckpt_dir: str, window: int, tree: dict[str, Any], stats: Stats) -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, f"state_{window:08d}.npz")
    arrays = {k: np.asarray(v) for k, v in tree.items()}
    np.savez_compressed(path, **arrays)
    with open(path + ".json", "w") as f:
        json.dump({"window": window, **stats.to_dict()}, f)
    return path


def latest(ckpt_dir: str) -> Optional[str]:
    if not os.path.isdir(ckpt_dir):
        return None
    snaps = sorted(p for p in os.listdir(ckpt_dir) if p.endswith(".npz"))
    return os.path.join(ckpt_dir, snaps[-1]) if snaps else None


def load(path: str) -> tuple[dict[str, np.ndarray], dict]:
    arrays = dict(np.load(path))
    meta = {}
    if os.path.exists(path + ".json"):
        with open(path + ".json") as f:
            meta = json.load(f)
    return arrays, meta
