"""Elastic serving mode (`-serve`, ISSUE 11): telemetry-driven autoscaling
under live streaming traffic.

The driver hands this module a seeded stepper and the serve loop takes over
phase 2: it advances poll windows like the windowed loop, but between
windows it watches the mail-ring occupancy (high-water entries / slot
capacity -- the device-resident saturation signal) against the configured
watermarks and, when one trips for `serve_window` consecutive windows,
performs **checkpoint -> reshard -> resume**:

  1. snapshot the full state pytree (`state_pytree` -- the PR-4 atomic
     checkpoint surface; written to disk too when -checkpoint-dir is set),
  2. build a fresh stepper on the wider/narrower mesh (S=1 uses the
     single-device jax backend, S>1 the sharded backend over the first S
     devices),
  3. restore (`load_state_pytree` -- the PR-5 mid-stream re-bucketing
     repacks the S_old per-shard mail rings onto S_new shards).

Not a single in-flight rumor is dropped: the snapshot carries the complete
mail ring, and the injection schedule is a pure function of the config
(gossip_simulator_tpu/arrivals.py -- keyed by rumor index, shard-count
invariant), so the rebuilt stepper continues the exact trajectory.  The
S=1<->S=8 Stats-exactness of this transition is pinned by the reshard
tests and the CI serve-smoke twin.

**Admission control** is the graceful-degradation path: when the widest
mesh is still saturated, the not-yet-injected suffix of the arrival table
is shifted by a doubling backoff (capped at -serve-max-defer) -- rumors
are *deferred*, counted in `Stats.shed`, and retried; never silently lost.
The shift rides the same reshard machinery (the schedule is baked into the
traced window step, so a deferral rebuilds the stepper at the same S with
the new `inject_ticks` override).

Every decision lands in the autoscaler log (window, tick, action,
occupancy, pause ms) and the whole transition is a flight-recorder span
("serve.reshard"), so reshard-pause time -- the metric the next perf PR
drives toward zero -- is measured, not estimated.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from gossip_simulator_tpu.backends.base import Stepper
from gossip_simulator_tpu.config import Config, parse_serve_force
from gossip_simulator_tpu.parallel.mesh import AXIS
from gossip_simulator_tpu.utils import lifecycle as _lifecycle
from gossip_simulator_tpu.utils import trace as _trace
from gossip_simulator_tpu.utils.metrics import ProgressPrinter, Stats


@dataclasses.dataclass
class ServeOutcome:
    """What the driver needs back: the (possibly rebuilt) stepper, the live
    config (admission deferrals mutate the injection schedule), the window
    count/rows for artifacts, and the serve report for result.json."""

    stepper: Stepper
    cfg: Config
    windows: int
    converged: bool
    interrupted: bool
    rows: list
    report: dict
    shed: int


def shard_count(stepper) -> int:
    mesh = getattr(stepper, "mesh", None)
    if mesh is None:
        return 1
    return int(mesh.shape[AXIS])


def next_shard_count(s: int, direction: int, lo: int, hi: int,
                     n: int) -> int:
    """The autoscaler's doubling ladder: the nearest power-of-two step in
    `direction` that stays inside [lo, hi] and divides n (shard_size
    requires exact divisibility).  Returns s unchanged when no step fits."""
    nxt = s * 2 if direction > 0 else s // 2
    while lo <= nxt <= hi:
        if n % nxt == 0:
            return nxt
        nxt = nxt * 2 if direction > 0 else nxt // 2
    return s


def build_stepper(cfg: Config, n_shards: int) -> Stepper:
    """A fresh ready-to-restore stepper at `n_shards`: init + overlay drain
    + seed, exactly the reshard-resume pattern the PR-5 tests pin -- the
    subsequent load_state_pytree overwrites graph and state wholesale."""
    if n_shards <= 1:
        from gossip_simulator_tpu.backends.jax_backend import JaxStepper

        stepper: Stepper = JaxStepper(cfg.replace(backend="jax"))
    else:
        from gossip_simulator_tpu.backends.sharded import ShardedStepper

        stepper = ShardedStepper(cfg.replace(backend="sharded"),
                                 n_devices=n_shards)
    stepper.init()
    while not stepper.overlay_window()[2]:
        pass
    stepper.seed()
    return stepper


def _occupancy(stepper, cfg: Config, n_shards: int) -> float:
    """Mail-ring occupancy fraction: the fullest window slot's entry count
    over the per-shard slot capacity -- the backpressure signal (appends
    beyond the cap are counted drops, so occupancy ~1.0 means loss is
    imminent)."""
    state = getattr(stepper, "state", None)
    cnt = getattr(state, "mail_cnt", None)
    if cnt is None:
        return 0.0
    from gossip_simulator_tpu.models.event import slot_cap

    cap = slot_cap(cfg, max(cfg.n // n_shards, 1))
    return float(jax.device_get(jnp.max(cnt))) / float(max(cap, 1))


def _fmt_occ(vec: list) -> str:
    """Compact per-shard occupancy rendering for transcript notes:
    `[0.12 0.31 ...]` (two decimals -- the note is a trend readout, the
    decision-log entry keeps the precise values)."""
    return "[" + " ".join(f"{v:.2f}" for v in vec) + "]"


def _occupancy_vector(stepper, cfg: Config, n_shards: int) -> list:
    """Per-shard occupancy fractions -- the spatial shard panel's live
    analog (serve runs with telemetry off, so the decision log reads the
    ring directly).  mail_cnt is (1, dw) per shard, (S, dw) gathered;
    each shard's fullest window slot over the per-shard capacity."""
    state = getattr(stepper, "state", None)
    cnt = getattr(state, "mail_cnt", None)
    if cnt is None:
        return []
    from gossip_simulator_tpu.models.event import slot_cap

    cap = float(max(slot_cap(cfg, max(cfg.n // n_shards, 1)), 1))
    arr = np.asarray(jax.device_get(cnt)).reshape(n_shards, -1)
    return [round(float(v) / cap, 4) for v in arr.max(axis=1)]


def _pending_mask(cfg: Config, current_tick: int) -> np.ndarray:
    from gossip_simulator_tpu import arrivals as _arrivals

    table = np.asarray(_arrivals.arrival_ticks(cfg), np.int64)
    return table > current_tick


def defer_pending(cfg: Config, current_tick: int, backoff_ms: int
                  ) -> tuple[int, Config, int]:
    """Admission control: shift every not-yet-injected arrival by one
    backoff step (all by the SAME amount -- the table must stay sorted; the
    pending entries form a suffix of the sorted table, so a uniform shift
    preserves order).  Returns (deferred_count, new_cfg, new_backoff_ms);
    (0, cfg, backoff) when nothing is pending or deferral is disabled."""
    from gossip_simulator_tpu import arrivals as _arrivals

    from gossip_simulator_tpu.backends.base import WINDOW_MS

    if cfg.serve_max_defer <= 0:
        return 0, cfg, backoff_ms
    table = np.asarray(_arrivals.arrival_ticks(cfg), np.int64)
    pending = table > current_tick
    count = int(pending.sum())
    if count == 0:
        return 0, cfg, backoff_ms
    step = min(max(backoff_ms * 2, WINDOW_MS), cfg.serve_max_defer)
    shifted = table.copy()
    shifted[pending] += step
    new_cfg = cfg.replace(inject_ticks=tuple(int(t) for t in shifted))
    return count, new_cfg, step


def reshard(cfg: Config, stepper: Stepper, new_shards: int, window: int,
            stats: Stats) -> tuple[Stepper, float]:
    """The zero-loss transition: snapshot -> (optional durable checkpoint)
    -> fresh stepper at `new_shards` -> restore.  Returns the new stepper
    and the pause in wall-clock ms (the serving SLO cost of the resize)."""
    from gossip_simulator_tpu.utils import checkpoint

    t0 = time.perf_counter()
    old = shard_count(stepper)
    with _trace.span("serve.reshard", cat="phase", window=window,
                     from_shards=old, to_shards=new_shards) as sp:
        tree = stepper.state_pytree()
        if tree is not None and cfg.checkpoint_dir and stepper.primary_host:
            checkpoint.save(cfg.checkpoint_dir, window, tree, stats,
                            extra_meta={"reshard_to": new_shards})
            checkpoint.prune(cfg.checkpoint_dir, cfg.ckpt_keep)
        new_stepper = build_stepper(cfg, new_shards)
        new_stepper.load_state_pytree(tree)
        if sp is not None:
            sp["pause_ms"] = round((time.perf_counter() - t0) * 1000.0, 3)
    return new_stepper, (time.perf_counter() - t0) * 1000.0


def run_serve(cfg: Config, stepper: Stepper, printer: ProgressPrinter,
              max_windows: int, resume_window: int = 0,
              collect_rows: bool = False) -> ServeOutcome:
    """The serving loop (driver phase 2 under -serve).  `stepper` arrives
    initialized and seeded; the returned outcome's stepper is whichever
    incarnation served the final window."""
    from gossip_simulator_tpu.utils import checkpoint

    live_cfg = cfg
    s = shard_count(stepper)
    devices = len(jax.devices())
    max_s = devices if cfg.serve_max_shards == -1 else min(
        cfg.serve_max_shards, devices)
    min_s = min(cfg.serve_min_shards, max_s)
    force = parse_serve_force(cfg.serve_force)
    target = cfg.coverage_target

    # Liveness beacon (distributed/heartbeat.py): a serving worker under a
    # supervisor stamps its rank once per window, same as the windowed
    # driver loops -- progress, not just process existence.
    beacon = None
    if cfg.heartbeat_dir:
        from gossip_simulator_tpu.distributed import heartbeat as _heartbeat

        beacon = _heartbeat.Beacon.for_cfg(cfg)

    rows: list = []
    decisions: list = []
    segments: list = []
    windows = 0
    converged = False
    interrupted = False
    shed = 0
    backoff_ms = 0
    hi_run = lo_run = 0
    pause_total = 0.0
    seg_start_tick = 0
    seg_start_msg = 0
    stats = stepper.stats()

    def _close_segment(end_tick: int, end_msg: int) -> None:
        span_ms = end_tick - seg_start_tick
        if span_ms <= 0:
            return
        rate = (end_msg - seg_start_msg) / (span_ms / 1000.0)
        segments.append({
            "shards": s, "start_tick": seg_start_tick, "end_tick": end_tick,
            "deliveries": end_msg - seg_start_msg,
            "deliveries_per_sec_per_shard": round(rate / max(s, 1), 1),
        })

    while windows < max_windows:
        with _trace.span("serve.window", cat="window") as sp:
            stats = stepper.gossip_window()
            if sp is not None:
                sp.update(round=int(stats.round), shards=s,
                          received=int(stats.total_received))
        windows += 1
        if collect_rows:
            rows.append((stats.round, stats.total_received,
                         stats.total_message, stats.total_crashed,
                         stats.total_removed))
        printer.coverage_window(round(stats.coverage * 100.0, 4),
                                stepper.sim_time_ms())
        if beacon is not None:
            beacon.stamp(resume_window + windows)
        if (live_cfg.checkpointing_enabled
                and windows % live_cfg.checkpoint_every == 0):
            tree = stepper.state_pytree()
            if tree is not None and stepper.primary_host:
                checkpoint.save(live_cfg.checkpoint_dir,
                                resume_window + windows, tree, stats)
                checkpoint.prune(live_cfg.checkpoint_dir,
                                 live_cfg.ckpt_keep)
        if stats.coverage >= target:
            converged = True
            break
        # The windowed loop's exhaustion break, with the streaming guard:
        # an empty ring is not a dead run while the (possibly deferred)
        # schedule still has rumors to start.
        if stats.exhausted and stats.round > live_cfg.last_inject_tick:
            break
        if _lifecycle.shutdown_requested():
            interrupted = True
            break

        # --- autoscaler ---------------------------------------------------
        occ = _occupancy(stepper, live_cfg, s)
        occ_v = _occupancy_vector(stepper, live_cfg, s)
        # Shard-health feed (utils/health.py's stuck-at-cap predicate,
        # live): any shard at/over its slot capacity gets flagged in the
        # decision log and the flight recorder before loss shows up in
        # mailbox_dropped.
        at_cap = [i for i, v in enumerate(occ_v) if v >= 1.0]
        if at_cap:
            _trace.instant("health.occupancy_at_cap", cat="health",
                           shards=at_cap)
        if occ < cfg.serve_high:
            backoff_ms = 0
        target_s: Optional[int] = None
        action = ""
        if windows in force:
            t = force[windows]
            if t != s:
                if not (min_s <= t <= max_s) or cfg.n % t or t > devices:
                    raise ValueError(
                        f"-serve-force {t}@{windows}: target must divide n "
                        f"({cfg.n}), fit [{min_s}, {max_s}] and the "
                        f"{devices} visible devices")
                target_s, action = t, ("widen" if t > s else "narrow")
        else:
            hi_run = hi_run + 1 if occ >= cfg.serve_high else 0
            lo_run = lo_run + 1 if occ <= cfg.serve_low else 0
            if hi_run >= cfg.serve_window:
                hi_run = 0
                up = next_shard_count(s, +1, min_s, max_s, cfg.n)
                if up != s:
                    target_s, action = up, "widen"
                else:
                    # Widest mesh still saturated: defer the pending
                    # injections (graceful degradation, never loss).
                    deferred, new_cfg, backoff_ms = defer_pending(
                        live_cfg, stats.round, backoff_ms)
                    if deferred:
                        shed += deferred
                        live_cfg = new_cfg
                        stepper, pause = reshard(live_cfg, stepper, s,
                                                 resume_window + windows,
                                                 stats)
                        pause_total += pause
                        entry = {"window": windows, "tick": stats.round,
                                 "action": "defer", "from": s, "to": s,
                                 "occupancy": round(occ, 4),
                                 "occupancy_shards": occ_v,
                                 "shards_at_cap": at_cap,
                                 "deferred": deferred,
                                 "backoff_ms": backoff_ms,
                                 "pause_ms": round(pause, 3)}
                        decisions.append(entry)
                        _trace.instant("serve.decision", **entry)
                        printer.note(
                            f"serve: deferred {deferred} pending "
                            f"injections by {backoff_ms}ms (occupancy "
                            f"{occ:.2f} at widest mesh S={s}, per-shard "
                            f"{_fmt_occ(occ_v)})")
            elif lo_run >= cfg.serve_window:
                lo_run = 0
                down = next_shard_count(s, -1, min_s, max_s, cfg.n)
                if down != s:
                    target_s, action = down, "narrow"
        if target_s is not None:
            _close_segment(stats.round, stats.total_message)
            stepper, pause = reshard(live_cfg, stepper, target_s,
                                     resume_window + windows, stats)
            pause_total += pause
            entry = {"window": windows, "tick": stats.round,
                     "action": action, "from": s, "to": target_s,
                     "occupancy": round(occ, 4),
                     "occupancy_shards": occ_v,
                     "shards_at_cap": at_cap,
                     "pause_ms": round(pause, 3)}
            decisions.append(entry)
            _trace.instant("serve.decision", **entry)
            printer.note(
                f"serve: {action} S={s}->{target_s} at window {windows} "
                f"(occupancy {occ:.2f}, per-shard {_fmt_occ(occ_v)}, "
                f"pause {pause:.0f}ms)")
            s = target_s
            seg_start_tick = stats.round
            seg_start_msg = stats.total_message
            hi_run = lo_run = 0

    _close_segment(stats.round, stats.total_message)
    report = {
        "arrivals": cfg.arrivals,
        "final_shards": s,
        "resizes": sum(1 for d in decisions
                       if d["action"] in ("widen", "narrow")),
        "reshard_pause_ms": round(pause_total, 3),
        "shed": shed,
        "watermarks": {"high": cfg.serve_high, "low": cfg.serve_low,
                       "window": cfg.serve_window},
        "decisions": decisions,
        "segments": segments,
    }
    return ServeOutcome(stepper=stepper, cfg=live_cfg, windows=windows,
                        converged=converged, interrupted=interrupted,
                        rows=rows, report=report, shed=shed)
