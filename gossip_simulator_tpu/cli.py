"""CLI entry point, flag-compatible with the reference
(``go run simulator.go -n 50000 -fanout 5 ...`` -> ``python -m
gossip_simulator_tpu -n 50000 -fanout 5 ...``; see config.py for the flag
table and divergence notes)."""

from __future__ import annotations

import sys
from typing import Optional

from gossip_simulator_tpu.config import parse_args
from gossip_simulator_tpu.driver import run_simulation


def main(argv: Optional[list[str]] = None) -> int:
    cfg = parse_args(argv)
    result = run_simulation(cfg)
    return 0 if result.converged else 2


if __name__ == "__main__":
    sys.exit(main())
