"""CLI entry point, flag-compatible with the reference
(``go run simulator.go -n 50000 -fanout 5 ...`` -> ``python -m
gossip_simulator_tpu -n 50000 -fanout 5 ...``; see config.py for the flag
table and divergence notes)."""

from __future__ import annotations

import os
import sys
from typing import Optional

from gossip_simulator_tpu.config import parse_args
from gossip_simulator_tpu.driver import run_simulation
from gossip_simulator_tpu.utils.metrics import ProgressPrinter


def _maybe_reexec_for_cpu(argv: Optional[list[str]]) -> None:
    """When the user explicitly requests the CPU platform on a host whose
    sitecustomize registers a TPU PJRT plugin with remote compilation (this
    image's axon relay), re-exec once with the plugin disabled -- otherwise
    even CPU compiles block on the remote relay."""
    if (os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
            and os.environ.get("PALLAS_AXON_POOL_IPS")
            and os.environ.get("_GOSSIP_CLI_REEXEC") != "1"):
        env = dict(os.environ)
        env["_GOSSIP_CLI_REEXEC"] = "1"
        env["PALLAS_AXON_POOL_IPS"] = ""
        args = sys.argv[1:] if argv is None else list(argv)
        os.execve(sys.executable,
                  [sys.executable, "-m", "gossip_simulator_tpu", *args], env)


def main(argv: Optional[list[str]] = None) -> int:
    cfg = parse_args(argv)
    # Graceful shutdown (utils/lifecycle): the first SIGTERM/SIGINT turns
    # into a final atomic checkpoint + artifact flush with reason
    # "interrupted" (exit 2, the standard not-converged code); a second
    # signal kills the process the default way.  Installed for every run,
    # not just -serve -- any long batch run deserves the same exit.
    from gossip_simulator_tpu.utils import lifecycle

    lifecycle.install_signal_handlers()
    if cfg.supervise and cfg.coordinator:
        # Real multi-process supervision (ISSUE 20): this process never
        # touches jax -- it spawns -workers CLI worker processes (the same
        # argv with -distributed wiring, distributed/worker.py), monitors
        # their heartbeats, and on host loss relaunches the survivors with
        # -resume.  Dispatched before any jax setup on purpose.
        from gossip_simulator_tpu.distributed import supervisor

        return supervisor.run_supervisor(
            cfg, sys.argv[1:] if argv is None else list(argv))
    silent = False
    if cfg.backend in ("jax", "sharded"):
        _maybe_reexec_for_cpu(argv)
        from gossip_simulator_tpu.utils import jaxsetup

        jaxsetup.setup()
        # Resolve the delivery-kernel gate once, post-setup (the probe
        # imports jax), and name the auto fallback so it is never silent.
        why = cfg.deliver_kernel_fallback_reason
        if why and cfg.progress:
            print(f"deliver-kernel auto -> xla: {why}", file=sys.stderr)
        if cfg.distributed:
            # Every process runs this same CLI; jax.distributed wires them
            # into one global runtime and the sharded backend's mesh spans
            # ALL processes' devices (SURVEY §5.8 multi-slice path).  Only
            # process 0 prints -- the totals are replicated everywhere.
            # Bounded + retried (parallel/mesh.py): a bad address fails in
            # -init-timeout-scaled seconds WITH the address named, instead
            # of the opaque 60s gRPC hang.
            import jax

            from gossip_simulator_tpu.parallel.mesh import bounded_initialize

            bounded_initialize(
                coordinator_address=cfg.coordinator or None,
                num_processes=(cfg.num_processes
                               if cfg.num_processes > 0 else None),
                process_id=cfg.process_id if cfg.process_id >= 0 else None,
                timeout_s=float(cfg.init_timeout_s))
            silent = jax.process_index() != 0
    # Context-managed printer: the JSONL log is flushed and closed even
    # when the run raises (metrics.ProgressPrinter.__exit__).
    with ProgressPrinter(
            enabled=cfg.progress,
            jsonl_path=((cfg.log_jsonl_resolved or None)
                        if not silent else None),
            silent=silent) as printer:
        result = run_simulation(cfg, printer=printer, silent=silent)
    return 0 if result.converged else 2


if __name__ == "__main__":
    sys.exit(main())
