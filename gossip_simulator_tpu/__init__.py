"""TPU-native gossip/epidemic simulation framework.

Capability parity with the reference `go-distributed/gossip_simulator`
(/root/reference/simulator.go), rebuilt as a single SPMD array program:
node state is a struct-of-arrays pytree sharded on the node axis; one
simulated millisecond (or one gossip round) is a jitted step; the simulated
network is data movement inside the step (gather/scatter in-shard,
all_to_all over ICI across shards).

Public surface:
    Config, parse_args        -- typed config, CLI-compatible with the reference
    make_stepper              -- Stepper factory ("native" | "cpp" | "jax" | "sharded")
    run_simulation            -- the two-phase driver (overlay build + broadcast)
"""

from gossip_simulator_tpu.config import Config, parse_args
from gossip_simulator_tpu.backends import make_stepper
from gossip_simulator_tpu.driver import run_simulation

__version__ = "0.1.0"

__all__ = ["Config", "parse_args", "make_stepper", "run_simulation", "__version__"]
