"""Fault-injection scenarios: dynamic churn, crash waves, recovery and
partition masks over *simulated* time.

The reference's fault model is static -- a fixed per-send drop rate and a
per-reception crash that black-holes a node forever (simulator.go:144,
179-184).  A scenario adds the dynamic dimension: a small timeline of
events, scheduled on the simulated clock, that every engine applies inside
its jitted step functions.

Config surface: ``-scenario off`` (default -- the traced programs are
untouched, bit-identical to a scenario-less build), a path to a JSON
timeline file, or the JSON itself inline.  Schema::

    {
      "groups":   4,        # node groups: contiguous global-id ranges
      "downtime": 500,      # ms until a crashed node reboots (0=permanent)
      "events": [
        {"type": "crash",     "at": 100, "frac": 0.05, "group": 2},
        {"type": "churn",     "start": 0, "end": 2000, "rate": 0.2},
        {"type": "partition", "start": 500, "end": 900, "group": 0}
      ]
    }

* ``crash``: one-shot wave at tick ``at`` -- each live node (in ``group``,
  or everywhere with group omitted/-1) crashes with probability ``frac``.
  Group-targeted waves are the *correlated per-shard failure* primitive:
  groups are contiguous id ranges, exactly the sharded backend's slices
  when ``groups`` equals the device count.
* ``churn``: steady churn over [start, end): each live node crashes with
  probability ``rate`` per 1000 simulated ms (so ``rate`` ~ the expected
  churned fraction per simulated second).
* ``partition``: traffic black-hole over [start, end): a message whose
  SEND tick falls in the window and whose (src, dst) groups are split is
  dropped (counted in ``Stats.partition_dropped``, never silent).  With
  ``group`` set, that group is isolated from the rest; with -1/omitted,
  ALL cross-group traffic is blocked (a full G-way split).

Recovery (``downtime`` > 0) revives EVERY crash -- scenario crashes and
per-reception crashes alike -- ``downtime`` ms after it happened: the
"machines reboot" model.  A recovered node rejoins live and susceptible
(its received bit, if it had one, is kept: counters stay monotone); it
receives again, but nobody re-sends to it unless ``-overlay-heal on``
repairs edges toward it (models/overlay.heal_dead_friends).  This is a
documented divergence from the reference's permanent black-hole.

Determinism: every scenario draw is keyed on (seed, window/tick,
OP_SCENARIO, event-index, GLOBAL node id) -- independent of the shard
count and of the shard-folded step keys -- so a scenario trajectory is
identical between the single-device and S-shard event engines, and a
checkpoint written at S=1 resumes bit-identically at S=8 (tested).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os

import jax
import jax.numpy as jnp

from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32


@dataclasses.dataclass(frozen=True)
class CrashEvent:
    at: int  # tick the wave fires
    frac: float  # per-node crash probability
    group: int = -1  # restrict to one group (-1 = all nodes)


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    start: int  # [start, end) active window, ticks
    end: int
    rate: float  # expected churned fraction per 1000 simulated ms
    group: int = -1


@dataclasses.dataclass(frozen=True)
class PartitionEvent:
    start: int  # [start, end) send-tick window, ticks
    end: int
    group: int = -1  # isolate this group (-1 = block ALL cross-group)


@dataclasses.dataclass(frozen=True)
class Scenario:
    """Parsed, validated fault timeline.  All fields are Python constants:
    the jitted steps close over them, so ``-scenario off`` (the empty
    Scenario) traces exactly the pre-scenario programs."""

    crashes: tuple[CrashEvent, ...] = ()
    churns: tuple[ChurnEvent, ...] = ()
    partitions: tuple[PartitionEvent, ...] = ()
    groups: int = 1
    downtime: int = 0  # ticks until reboot; 0 = crashes stay permanent

    @property
    def has_faults(self) -> bool:
        """Any crash/churn/recovery machinery in the step (the gate for
        the scenario tick and the down_since array)."""
        return bool(self.crashes or self.churns) or self.downtime > 0

    @property
    def has_partitions(self) -> bool:
        return bool(self.partitions)

    @property
    def active(self) -> bool:
        return self.has_faults or self.has_partitions

    def validate(self) -> "Scenario":
        if self.groups < 1:
            raise ValueError(f"scenario groups must be >= 1, got "
                             f"{self.groups}")
        if self.downtime < 0:
            raise ValueError(f"scenario downtime must be >= 0, got "
                             f"{self.downtime}")
        for e in self.crashes:
            if e.at < 0:
                raise ValueError(f"crash event at={e.at} must be >= 0")
            if not 0.0 <= e.frac <= 1.0:
                raise ValueError(f"crash frac must be in [0,1], got "
                                 f"{e.frac}")
        for e in self.churns:
            if e.end <= e.start or e.start < 0:
                raise ValueError(
                    f"churn window [{e.start},{e.end}) must be nonempty "
                    "and nonnegative")
            if not 0.0 <= e.rate <= 1000.0:
                raise ValueError(f"churn rate must be in [0,1000], got "
                                 f"{e.rate}")
        for e in self.partitions:
            if e.end <= e.start or e.start < 0:
                raise ValueError(
                    f"partition window [{e.start},{e.end}) must be "
                    "nonempty and nonnegative")
        for e in (*self.crashes, *self.churns, *self.partitions):
            if e.group != -1 and not 0 <= e.group < self.groups:
                raise ValueError(
                    f"event group {e.group} outside [0, {self.groups})")
        if self.partitions and self.groups < 2:
            raise ValueError(
                "partition events need scenario groups >= 2 (a 1-group "
                "world has no cross-group traffic to block)")
        return self


OFF = Scenario()


@functools.lru_cache(maxsize=32)
def parse(spec: str) -> Scenario:
    """``off``/empty -> the inert Scenario; otherwise inline JSON (starts
    with ``{``) or a path to a JSON timeline file.  Raises ValueError with
    a flag-specific message on anything malformed."""
    if not spec or spec == "off":
        return OFF
    if spec.lstrip().startswith("{"):
        try:
            raw = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError(f"-scenario inline JSON is invalid: {e}")
    else:
        if not os.path.exists(spec):
            raise ValueError(
                f"-scenario {spec!r} is neither 'off', inline JSON, nor "
                "an existing timeline file")
        with open(spec) as f:
            try:
                raw = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"-scenario file {spec} is invalid "
                                 f"JSON: {e}")
    if not isinstance(raw, dict):
        raise ValueError("-scenario JSON must be an object "
                         "{groups, downtime, events}")
    known = {"groups", "downtime", "events"}
    extra = set(raw) - known
    if extra:
        raise ValueError(f"-scenario: unknown keys {sorted(extra)}")
    crashes, churns, partitions = [], [], []
    for i, ev in enumerate(raw.get("events", [])):
        if not isinstance(ev, dict) or "type" not in ev:
            raise ValueError(f"-scenario events[{i}] needs a 'type'")
        t = ev["type"]
        try:
            if t == "crash":
                crashes.append(CrashEvent(
                    at=int(ev["at"]), frac=float(ev["frac"]),
                    group=int(ev.get("group", -1))))
            elif t == "churn":
                churns.append(ChurnEvent(
                    start=int(ev["start"]), end=int(ev["end"]),
                    rate=float(ev["rate"]),
                    group=int(ev.get("group", -1))))
            elif t == "partition":
                partitions.append(PartitionEvent(
                    start=int(ev["start"]), end=int(ev["end"]),
                    group=int(ev.get("group", -1))))
            else:
                raise ValueError(
                    f"-scenario events[{i}]: unknown type {t!r} "
                    "(crash|churn|partition)")
        except KeyError as e:
            raise ValueError(f"-scenario events[{i}] ({t}) is missing "
                             f"field {e}")
    return Scenario(
        crashes=tuple(crashes), churns=tuple(churns),
        partitions=tuple(partitions),
        groups=int(raw.get("groups", 1)),
        downtime=int(raw.get("downtime", 0))).validate()


# --------------------------------------------------------------------------
# Traced helpers.  All take the Scenario as a Python constant and global
# node ids / ticks as (possibly traced) arrays.
# --------------------------------------------------------------------------

# RNG op tag for every scenario draw (crash waves, churn) and the healing
# machinery (replacement draws, repaired-edge re-sends).  Kept here, not in
# utils/rng.py, so the rng module stays a closed reference of the
# pre-scenario streams.
OP_SCENARIO = 12
OP_HEAL = 13
OP_HEAL_SEND = 14


def group_size(scen: Scenario, n: int) -> int:
    """Nodes per group: contiguous global-id ranges (ceil so the last
    group absorbs the remainder)."""
    return -(-n // scen.groups)


def group_of(scen: Scenario, n: int, ids):
    return ids // group_size(scen, n)


def _event_keys(base_key, window_idx, idx: int):
    """Key for scenario event `idx` in window `window_idx`: shard-count
    independent (no shard fold), row keys derived per GLOBAL id by the
    caller."""
    return jax.random.fold_in(
        _rng.tick_key(base_key, window_idx, OP_SCENARIO), idx)


def fault_window(scen: Scenario, n: int, tick0, nticks: int, ids_global,
                 crashed, down_since, base_key):
    """Apply the scenario's crash/churn/recovery timeline over the window
    [tick0, tick0 + nticks).

    `crashed` is the caller's bool[n_local] view (the event engine adapts
    its flags bit); `down_since` is int32[n_local] crash ticks (-1 = not
    crashed / crash time unknown).  Returns
    ``(new_crash, recover, down_since', d_crashed, d_recovered)`` --
    boolean masks plus LOCAL count deltas (sharded callers psum them).

    Order within the window: recovery first (a node whose downtime ends
    this window is live again and exposed to this window's churn draw),
    then the crash draws on live nodes.  Draws are keyed on
    (window-index, event-index, GLOBAL id): shard-count invariant, so
    S=1 and S=8 runs -- and a reshard-resumed checkpoint -- crash the
    same nodes at the same ticks.  The window index is tick0 // nticks
    (every engine advances in fixed nticks strides from 0)."""
    widx = tick0 // nticks
    if scen.downtime > 0:
        recover = crashed & (down_since >= 0) \
            & (tick0 >= down_since + scen.downtime)
        crashed = crashed & ~recover
        # Rejoin marker -(t+2): negative (so the node reads as live to
        # recovery and detection alike) but distinguishable from the
        # never-crashed -1 -- the healing pass's rejoin anti-entropy
        # consumes it (heal_and_wave), letting a freshly rebooted node
        # pull the rumor from its live infected friends.  Inert when
        # healing is off.
        down_since = jnp.where(recover, -(tick0.astype(I32) + 2),
                               down_since)
    else:
        recover = jnp.zeros(crashed.shape, bool)
    hit = jnp.zeros(crashed.shape, bool)
    gid = group_of(scen, n, ids_global) if scen.groups > 1 else None
    t1 = tick0 + nticks
    for i, e in enumerate(scen.crashes):
        fires = (e.at >= tick0) & (e.at < t1)
        u = _row_uniform(_event_keys(base_key, widx, i), ids_global)
        m = (u < e.frac) & fires
        if e.group >= 0:
            m = m & (gid == e.group)
        hit = hit | m
    base = len(scen.crashes)
    for i, e in enumerate(scen.churns):
        # Expected per-tick hazard rate/1000; the window draw uses the
        # overlap-scaled probability (exact for the window-quantized
        # process both engines step at).
        lo = jnp.maximum(tick0, e.start)
        hi = jnp.minimum(t1, e.end)
        overlap = jnp.maximum(hi - lo, 0).astype(jnp.float32)
        p = overlap * (e.rate / 1000.0)
        u = _row_uniform(_event_keys(base_key, widx, base + i), ids_global)
        m = u < p
        if e.group >= 0:
            m = m & (gid == e.group)
        hit = hit | m
    new_crash = hit & ~crashed
    down_since = jnp.where(new_crash, tick0.astype(I32), down_since)
    return (new_crash, recover, down_since,
            new_crash.sum(dtype=I32), recover.sum(dtype=I32))


def _row_uniform(key, rows):
    """One uniform[0,1) per GLOBAL row id (row-keyed like rng.row_keys, so
    a shard's slice draws exactly the values the full axis would)."""
    ks = _rng.row_keys(key, rows)
    return jax.vmap(lambda kk: jax.random.uniform(kk, ()))(ks)


def partition_blocked(scen: Scenario, n: int, send_tick, src_gids,
                      dst_gids):
    """bool mask, True where a send from src to dst at `send_tick` crosses
    an active partition.  `send_tick` broadcasts against the id arrays
    (a scalar for the ring engine's per-tick wave, per-sender ticks for
    the event engine's batched appends).  Semantics: the partition applies
    at SEND time -- a message emitted inside the window is black-holed
    even if its delivery tick falls after the partition heals (the wire
    was down when it left).  dst < 0 lanes (padding) come back False."""
    if not scen.partitions:
        return jnp.zeros(jnp.broadcast_shapes(
            jnp.shape(src_gids), jnp.shape(dst_gids)), bool)
    gs = group_of(scen, n, src_gids)
    gd = group_of(scen, n, jnp.maximum(dst_gids, 0))
    blocked = jnp.zeros(jnp.broadcast_shapes(gs.shape, gd.shape), bool)
    for e in scen.partitions:
        live = (send_tick >= e.start) & (send_tick < e.end)
        if e.group >= 0:
            cross = (gs == e.group) != (gd == e.group)
        else:
            cross = gs != gd
        blocked = blocked | (live & cross)
    return blocked & (dst_gids >= 0)


# Packed per-node bits the healing pass publishes across shards in ONE
# uint8 all_gather: the detector's verdict and "carries the rumor and can
# answer a rejoin pull".
HEAL_DEAD = 1  # detect_dead verdict
HEAL_INFECTIVE = 2  # infected & live (& not SIR-removed)


def heal_peer_bits(detected, infective):
    import jax.numpy as jnp  # noqa: F811

    return detected.astype(jnp.uint8) * jnp.uint8(HEAL_DEAD) \
        + infective.astype(jnp.uint8) * jnp.uint8(HEAL_INFECTIVE)


def heal_and_wave(cfg, friends, friend_cnt, peer_bits_global, healer_ok,
                  sender_inf, rejoined, ids_global, tick, base_key):
    """One healing pass (every poll window when ``-overlay-heal on``),
    three pieces:

    1. REPAIR -- replace detector-condemned friends via the phase-1
       makeup draw (overlay.heal_dead_friends).
    2. RE-SEND -- an INFECTED healer re-broadcasts the rumor over each
       repaired edge (without this, topology repair alone cannot carry
       the rumor across edges that were rewired after the healer's
       one-shot broadcast already happened).
    3. REJOIN PULL -- a node whose reboot marker is set (fault_window's
       -(t+2) encoding in down_since) asks its friends for the rumor;
       each live INFECTED friend's response is a normal delayed delivery
       back to the rejoined node (counted at delivery like any message).
       This is the rejoin anti-entropy: a node that was down while its
       neighbors broadcast has no other path back to coverage.

    Re-sends and pull responses are real network traffic: per-link drop
    draws, a per-node shared delay (the reference's one-delay-per-
    broadcast, simulator.go:141-142), and the partition mask.  All draws
    are (tick, GLOBAL-id)-keyed (OP_HEAL / OP_HEAL_SEND): shard-count
    invariant, reshard-resume safe.

    `peer_bits_global` is the full-axis uint8 heal_peer_bits vector (the
    sharded engines all_gather it -- one byte per node).  Returns
    ``(friends', resend[n, k], pull[n, k], delay[n], down_clear[n],
    repaired_local, partition_blocked_local)``; `pull` marks friend lanes
    whose response delivers to the LANE'S OWN ROW (always shard-local),
    `down_clear` is the consumed-rejoin-marker mask.  The engine glue
    owns delivery (delay ring deposit / mail-ring append / all_to_all
    route) and the psums."""
    from gossip_simulator_tpu.models import overlay as _ov

    n = cfg.n
    k = friends.shape[1]
    detected_global = (peer_bits_global & HEAL_DEAD) > 0
    hk = _rng.tick_key(base_key, tick, OP_HEAL)
    friends, dead, repaired = _ov.heal_dead_friends(
        n, friends, friend_cnt, detected_global, healer_ok, ids_global, hk)
    kd = _rng.tick_key(base_key, tick, OP_HEAL_SEND)
    kp = jax.random.fold_in(kd, 1)
    kq = jax.random.fold_in(kd, 2)
    if cfg.effective_time_mode == "ticks":
        delay = _rng.row_uniform_delay(kd, cfg.delaylow, cfg.delayhigh,
                                       ids_global)
    else:
        delay = jnp.ones(ids_global.shape, I32)
    drop_p = int(cfg.droprate * 100) / 100.0 if cfg.compat_reference \
        else cfg.droprate
    drop = _rng.row_bernoulli(kp, drop_p, ids_global, k)
    resend = dead & sender_inf[:, None] & ~drop
    # Rejoin pull: the rebooted node contacts every current friend; an
    # infective one answers with the rumor (response lane -> own row).
    # Only rumor-bearing responses are materialized (an uninfected
    # friend's reply carries nothing to deliver or count).
    in_range = jnp.arange(k, dtype=I32)[None, :] < friend_cnt[:, None]
    fbits = peer_bits_global.at[jnp.maximum(friends, 0)].get()
    qdrop = _rng.row_bernoulli(kq, drop_p, ids_global, k)
    pull = rejoined[:, None] & healer_ok[:, None] & in_range \
        & (friends >= 0) & ((fbits & HEAL_INFECTIVE) > 0) & ~qdrop
    scen = cfg.scenario_resolved
    blocked_n = jnp.zeros((), I32)
    if scen.has_partitions:
        blocked = partition_blocked(
            scen, n, tick, ids_global[:, None], friends) & resend
        # The pull response travels friend -> rejoined node: same pair,
        # opposite direction -- the partition masks are symmetric (group
        # predicates), so one blocked() evaluation covers both.
        qblocked = partition_blocked(
            scen, n, tick, ids_global[:, None], friends) & pull
        blocked_n = blocked.sum(dtype=I32) + qblocked.sum(dtype=I32)
        resend = resend & ~blocked
        pull = pull & ~qblocked
    return (friends, resend, pull, delay, rejoined, repaired, blocked_n)


def rejoined_mask(down_since):
    """Nodes carrying fault_window's reboot marker (consumed by the next
    healing pass's rejoin pull)."""
    return down_since <= -2


def detect_dead(crashed, down_since, tick, detect_ms: int):
    """The failure detector's verdict on the LOCAL rows: a node is
    condemned once it has been crashed for >= detect_ms -- the windowed
    failed-delivery model (every send to it since the crash black-holed;
    after detect_ms of that, its senders give up on it).  No actor-style
    heartbeats: the crash clock (down_since) IS the accountant."""
    return crashed & (down_since >= 0) & (tick - down_since >= detect_ms)


def down_shape(enabled: bool, n_local: int) -> int:
    """down_since rows: the full local axis when the fault machinery is on
    (scenario faults or healing), a 1-element placeholder otherwise --
    the placeholder keeps the state pytree's structure stable across
    configs without costing n * 4 bytes on every fault-free run."""
    return n_local if enabled else 1


def init_down_since(enabled: bool, n_local: int) -> jnp.ndarray:
    return jnp.full((down_shape(enabled, n_local),), -1, I32)
