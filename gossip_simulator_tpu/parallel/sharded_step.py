"""SPMD step functions over the node mesh (shard_map + all_to_all + psum).

Each device owns a contiguous slice of the node axis; the per-tick physics is
the SAME `tick_core` the single-chip backend uses, the outgoing wave is
routed with one all_to_all (parallel/exchange.py), and the global counters /
termination predicate are psums -- the TPU-native equivalent of the
reference's shared `GlobalView` + atomics (simulator.go:24-31).

Layout (S shards, n = S * n_local):
    received/crashed/removed/friend_cnt: [n]      -> P("nodes")
    friends:                             [n, k]   -> P("nodes", None)
    pending/rebroadcast:                 [d, n]   -> P(None, "nodes")
    tick / totals:                       scalars  -> replicated
Global node id of local row r on shard s: s * n_local + r.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models import epidemic, graphs, overlay
from gossip_simulator_tpu.models import state as state_mod
from gossip_simulator_tpu.models.state import (OverlayState, SimState,
                                               msg64_add)
from gossip_simulator_tpu.ops.mailbox import deliver
from gossip_simulator_tpu.parallel import exchange
from gossip_simulator_tpu.parallel.mesh import AXIS, shard_size
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32


def sim_state_specs(cfg: Config) -> SimState:
    # down_since is node-sharded only when the fault machinery allocates
    # the full axis (Config.faults_enabled); the fault-free 1-element
    # placeholder is replicated.
    return SimState(
        received=P(AXIS), crashed=P(AXIS), removed=P(AXIS),
        friends=P(AXIS, None), friend_cnt=P(AXIS),
        pending=P(None, AXIS), rebroadcast=P(None, AXIS),
        tick=P(), total_message=P(), total_received=P(), total_crashed=P(),
        exchange_overflow=P(),
        down_since=P(AXIS) if cfg.faults_enabled else P(),
        scen_crashed=P(), scen_recovered=P(), part_dropped=P(),
        heal_repaired=P(),
        # Multi-rumor rides the event engine only on meshes (config
        # rejects ring+multi at S > 1), so these stay the 1-element
        # replicated placeholders.
        pending_rumors=P(), rumor_words=P(), rumor_recv=P(),
        rumor_done=P(),
        # Per-shard exchange counters stack to (S, S+2); the 1x1
        # off-path placeholder splits the same way to (S, 1).
        exch_counts=P(AXIS, None),
    )


def overlay_state_specs() -> OverlayState:
    # Spill buffers are per-shard (each shard spills only what ITS routed
    # delivery overflowed; the hook path never fills them -- overlay.py's
    # pass-through keeps them empty, so the axis-sharded spec just splits
    # constant -1 arrays).
    return OverlayState(
        friends=P(AXIS, None), friend_cnt=P(AXIS),
        mk_dst=P(None, AXIS), bk_dst=P(None, AXIS), boot_dst=P(AXIS),
        mk_spill=P(None, None), bk_spill=P(None, None),
        round=P(), makeups=P(), breakups=P(),
        win_makeups=P(), win_breakups=P(), mailbox_dropped=P(),
    )


def _shard_map(mesh, fn, in_specs, out_specs):
    from gossip_simulator_tpu.parallel.mesh import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


# --------------------------------------------------------------------------
# Epidemic phase
# --------------------------------------------------------------------------

def _deposit_routed(cfg: Config, n_local: int, n_shards: int, pending,
                    dst_global, slots, valid, cap: int, exch=None):
    """Route (dst, ring-slot) messages to their owning shards and scatter
    into the local pending ring.  Returns (pending, local overflow).
    `cap` is the per-destination-shard buffer size (exchange.epidemic_cap of
    the wave's row count x row width).  `exch` non-None (the spatial
    panels' exch_counts leaf) accumulates the route's traffic and a 3rd
    value returns the updated leaf."""
    d = epidemic.ring_depth(cfg)
    dest_shard = jnp.where(valid, dst_global // n_local, n_shards)
    dst_local = jnp.where(valid, dst_global % n_local, 0)
    packed = jnp.where(valid, exchange.pack_dst_slot(dst_local, slots, d), -1)
    out = exchange.route_one(packed, dest_shard, valid, n_shards, cap,
                             traffic=exch)
    (recv, overflow), exch = out[:2], out[2] if exch is not None else None
    rvalid = recv >= 0
    rdst, rslot = exchange.unpack_dst_slot(jnp.maximum(recv, 0), d)
    pending = epidemic.deposit_local(pending, rdst, rslot, rvalid,
                                     kernel=cfg.deliver_kernel_resolved)
    if exch is None:
        return pending, overflow
    return pending, overflow, exch


def _route_stage_si(cfg: Config, n_local: int, n_shards: int, dst_global,
                    slots, valid, cap: int, pstage, exch=None):
    """Pipelined twin of _deposit_routed's route half (-exchange-pipeline
    double): the same pack/route/unpack, but the deposit arguments come
    back as the next staged drain instead of being scattered -- the
    caller deposits the barrier-threaded PREVIOUS stage while this
    chunk's all_to_all is in flight.  Deferring the deposit is
    trivially bit-identical here: nothing in the chunk loop reads
    `pending` (compact_gather keys off friends/dslot/remaining only),
    and deposits replay in the serial FIFO order.  Returns
    (stage_new, overflow, pstage_threaded[, exch])."""
    d = epidemic.ring_depth(cfg)
    dest_shard = jnp.where(valid, dst_global // n_local, n_shards)
    dst_local = jnp.where(valid, dst_global % n_local, 0)
    packed = jnp.where(valid, exchange.pack_dst_slot(dst_local, slots, d), -1)
    out = exchange.route_multi_pipelined(
        (packed,), dest_shard, valid, n_shards, cap, pstage, traffic=exch)
    ((recv,), overflow, pstage), exch = out[:3], (out[3]
                                                  if exch is not None
                                                  else None)
    rvalid = recv >= 0
    rdst, rslot = exchange.unpack_dst_slot(jnp.maximum(recv, 0), d)
    if exch is None:
        return (rdst, rslot, rvalid), overflow, pstage
    return (rdst, rslot, rvalid), overflow, pstage, exch


def _flush_deposit(cfg: Config, pending, stage):
    """Apply a staged deposit (the deferred half of _route_stage_si)."""
    rdst, rslot, rvalid = stage
    return epidemic.deposit_local(pending, rdst, rslot, rvalid,
                                  kernel=cfg.deliver_kernel_resolved)


def _empty_deposit_stage(n_lanes: int):
    """All-invalid staged deposit: scattering it is a no-op, seeds the
    pipeline's prologue."""
    z = jnp.zeros((n_lanes,), I32)
    return (z, z, jnp.zeros((n_lanes,), bool))


def make_sharded_tick(cfg: Config, mesh):
    """Per-tick transition as a shard_map body (composable into loops)."""
    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)

    track_part = cfg.scenario_resolved.has_partitions
    # Exchange pipelining (-exchange-pipeline double): the compact chunk
    # loop defers each chunk's pending-ring deposit one chunk behind its
    # all_to_all (see _route_stage_si); the dense path's single route
    # per tick has no loop to pipeline and stays serial.
    pipe = exchange.pipeline_enabled(cfg, s)
    # Spatial panels: the exch_counts leaf rides the ovf carry position
    # as a pair (exchange.ovf_split) through the chunk loops.
    spatial = cfg.telemetry_spatial_enabled and s > 1

    def tick_shard(st: SimState, base_key: jax.Array) -> SimState:
        shard = jax.lax.axis_index(AXIS)
        gid0 = shard * n_local
        # Scenario faults draw on (tick, GLOBAL-id) keys -- shard-count
        # invariant, unlike the shard-folded step keys below -- so a
        # scenario trajectory's crash/recovery schedule is identical on
        # any mesh (and across a reshard-resume).
        st, dsc, dsr = epidemic.apply_fault_window(
            cfg, st, gid0 + jnp.arange(n_local, dtype=I32), base_key)
        keys = epidemic.tick_keys(base_key, st.tick, shard)
        stp, senders, dslot, (dm, dr, dc) = epidemic.tick_core(cfg, st, keys)
        width = stp.friends.shape[1]
        zblk = jnp.zeros((), I32)
        ovf0 = exchange.ovf_join(jnp.zeros((), I32),
                                 st.exch_counts if spatial else None)
        if cfg.compact_resolved:
            # Compacted wave: only sender rows reach the RNG/sort/all_to_all.
            # Chunk count is agreed across shards (pmax) so every shard
            # executes the same number of collectives.
            ccap = epidemic.compact_chunk_cap(cfg, n_local)
            count = jax.lax.pmax(senders.sum(dtype=I32), AXIS)
            chunks = (count + ccap - 1) // ccap
            # Per-chunk route cap: destination-uniform graphs size the
            # wire from the per-pair high-water mark (round 6 --
            # exchange.chernoff_cap, same gate as the event engine's
            # wire_cap; counted overflow, never silent); others keep the
            # round-1 rule -- never below the dense path's cap (so any
            # wave dense delivers losslessly, compact does too, skew
            # included), bounded above by a chunk's absolute max emission.
            if cfg.graph in ("kout", "erdos"):
                rcap = exchange.chernoff_cap(ccap * width, s)
            else:
                rcap = min(exchange.epidemic_cap(n_local, width, s),
                           ccap * width)

            if pipe:
                # Pipelined chunk loop (-exchange-pipeline double): chunk
                # j's deposit flushes behind chunk j+1's in-flight
                # collective (_route_stage_si's identity note); the last
                # stage flushes after the loop.
                def body_pipe(_, carry):
                    pending, remaining, ovf, blk, pend = carry
                    oacc, exch = exchange.ovf_split(ovf)
                    (dstg, slots, valid, remaining,
                     b2) = epidemic.compact_gather(
                        cfg, stp.friends, stp.friend_cnt, dslot,
                        keys["delay"], keys["drop"], st.tick, remaining,
                        ccap, **(dict(gid0=gid0) if track_part else {}))
                    out = _route_stage_si(
                        cfg, n_local, s, dstg, slots, valid, rcap, pend,
                        exch=exch)
                    (nstage, o, pthr), exch = out[:3], (
                        out[3] if exch is not None else None)
                    pending = _flush_deposit(cfg, pending, pthr)
                    return (pending, remaining,
                            exchange.ovf_join(oacc + o, exch),
                            blk + (b2 if track_part else 0), nstage)

                pending, _, ovf, blk, pend = jax.lax.fori_loop(
                    0, chunks, body_pipe,
                    (stp.pending, senders, ovf0, zblk,
                     _empty_deposit_stage(s * rcap)))
                pending = _flush_deposit(cfg, pending, pend)
            elif track_part:
                def body_p(_, carry):
                    pending, remaining, ovf, blk = carry
                    oacc, exch = exchange.ovf_split(ovf)
                    (dstg, slots, valid, remaining,
                     b2) = epidemic.compact_gather(
                        cfg, stp.friends, stp.friend_cnt, dslot,
                        keys["delay"], keys["drop"], st.tick, remaining,
                        ccap, gid0=gid0)
                    out = _deposit_routed(cfg, n_local, s, pending,
                                          dstg, slots, valid, rcap,
                                          exch=exch)
                    (pending, o), exch = out[:2], (
                        out[2] if exch is not None else None)
                    return (pending, remaining,
                            exchange.ovf_join(oacc + o, exch), blk + b2)

                pending, _, ovf, blk = jax.lax.fori_loop(
                    0, chunks, body_p,
                    (stp.pending, senders, ovf0, zblk))
            else:
                def body(_, carry):
                    pending, remaining, ovf = carry
                    oacc, exch = exchange.ovf_split(ovf)
                    (dstg, slots, valid, remaining,
                     _b) = epidemic.compact_gather(
                        cfg, stp.friends, stp.friend_cnt, dslot,
                        keys["delay"], keys["drop"], st.tick, remaining,
                        ccap)
                    out = _deposit_routed(cfg, n_local, s, pending,
                                          dstg, slots, valid, rcap,
                                          exch=exch)
                    (pending, o), exch = out[:2], (
                        out[2] if exch is not None else None)
                    return (pending, remaining,
                            exchange.ovf_join(oacc + o, exch))

                pending, _, ovf = jax.lax.fori_loop(
                    0, chunks, body,
                    (stp.pending, senders, ovf0))
                blk = zblk
        else:
            dst, slots, valid, blk = epidemic.edges_from_senders(
                cfg, stp.friends, stp.friend_cnt, senders, dslot,
                keys["drop"], tick=st.tick, gid0=gid0)
            exch = st.exch_counts if spatial else None
            out = _deposit_routed(
                cfg, n_local, s, stp.pending, dst, slots, valid,
                exchange.epidemic_cap(n_local, width, s), exch=exch)
            (pending, ovf), exch = out[:2], (
                out[2] if exch is not None else None)
            ovf = exchange.ovf_join(ovf, exch)
        # Traffic rows are per-shard gauges: split them off BEFORE the psum.
        ovf, exch = exchange.ovf_split(ovf)
        dm, dr, dc, ovf = jax.lax.psum((dm, dr, dc, ovf), AXIS)
        # NOTE: no lax.cond empty-slot skip here -- see the miscompile note
        # in epidemic.make_tick_fn (axon platform, cond + dynamic fori).
        # The psum'd per-tick delta stays int32 (bounded by the delay-ring
        # capacity); the carry into the 64-bit pair is replicated per shard.
        stp = stp._replace(
            pending=pending,
            total_message=msg64_add(stp.total_message, dm),
            total_received=stp.total_received + dr,
            total_crashed=stp.total_crashed + dc,
            exchange_overflow=stp.exchange_overflow + ovf)
        if exch is not None:
            stp = stp._replace(exch_counts=exch)
        if cfg.scenario_resolved.active:
            dsc, dsr, blk = jax.lax.psum(
                (jnp.asarray(dsc, I32), jnp.asarray(dsr, I32),
                 jnp.asarray(blk, I32)), AXIS)
            stp = stp._replace(
                scen_crashed=stp.scen_crashed + dsc,
                scen_recovered=stp.scen_recovered + dsr,
                part_dropped=stp.part_dropped + blk)
        return stp

    return tick_shard


def make_sharded_pushpull(cfg: Config, mesh):
    """Push-pull anti-entropy round per shard: push deliveries and pull
    request/response both ride the same all_to_all routing."""
    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    f = cfg.fanout
    drop_p = epidemic.p_eff(cfg, cfg.droprate)
    crash_p = epidemic.p_eff(cfg, cfg.crashrate)
    cap = exchange.epidemic_cap(n_local, f, s)
    spatial = cfg.telemetry_spatial_enabled and s > 1

    def round_shard(st: SimState, base_key: jax.Array) -> SimState:
        shard = jax.lax.axis_index(AXIS)
        skey = jax.random.fold_in(base_key, shard)
        k1 = _rng.tick_key(skey, st.tick, _rng.OP_BOOTSTRAP)
        k2 = _rng.tick_key(skey, st.tick, _rng.OP_PULL)
        kd1 = _rng.tick_key(skey, st.tick, _rng.OP_DROP)
        kd2 = _rng.tick_key(skey, st.tick, _rng.OP_DELAY)
        kc = _rng.tick_key(skey, st.tick, _rng.OP_CRASH)

        live = ~st.crashed
        inf = st.received & live
        sus = ~st.received & live
        gids = shard * n_local + jnp.arange(n_local, dtype=I32)

        # --- push ---------------------------------------------------------
        peers = jax.random.randint(k1, (n_local, f), 0, cfg.n, dtype=I32)
        kept = ~_rng.bernoulli(kd1, drop_p, (n_local, f))
        edge = (inf[:, None] & kept).reshape(-1)
        dstg = peers.reshape(-1)
        exch = st.exch_counts if spatial else None
        out = exchange.route_one(
            jnp.where(edge, dstg % n_local, -1),
            jnp.where(edge, dstg // n_local, s), edge, s, cap,
            traffic=exch)
        (recv, ovf1), exch = out[:2], (
            out[2] if exch is not None else None)
        rvalid = recv >= 0
        arriving = jnp.zeros((n_local,), I32).at[
            jnp.where(rvalid, recv, n_local)].add(1, mode="drop")
        counted = jnp.where(live, arriving, 0)
        dm = counted.sum(dtype=I32)
        if crash_p > 0.0:
            pc = 1.0 - jnp.power(1.0 - crash_p, counted.astype(jnp.float32))
            new_crash = (jax.random.uniform(kc, (n_local,)) < pc) & (counted > 0)
        else:
            new_crash = jnp.zeros((n_local,), bool)
        crashed = st.crashed | new_crash
        dc = new_crash.sum(dtype=I32)
        newly_push = (counted > 0) & ~crashed & ~st.received

        # --- pull: request (target, requester) then response (hits) --------
        peers2 = jax.random.randint(k2, (n_local, f), 0, cfg.n, dtype=I32)
        kept2 = ~_rng.bernoulli(kd2, drop_p, (n_local, f))
        req = (sus[:, None] & kept2 & ~crashed[:, None]).reshape(-1)
        tgt = peers2.reshape(-1)
        dest = jnp.where(req, tgt // n_local, s)
        # Target row and requester id share one sort + one all_to_all.
        out = exchange.route_multi(
            (jnp.where(req, tgt % n_local, -1),
             jnp.where(req, jnp.broadcast_to(
                 gids[:, None], (n_local, f)).reshape(-1), -1)),
            dest, req, s, cap, traffic=exch)
        ((rtgt, rreq), ovf2), exch = out[:2], (
            out[2] if exch is not None else None)
        tvalid = rtgt >= 0
        tgt_idx = jnp.where(tvalid, rtgt, 0)
        # A live peer answers any request (counted); an infected live peer's
        # answer infects.  One packed gather answers both (pre-round crashed,
        # like the single-device round; see epidemic.packed_peer_state).
        peer_state = epidemic.packed_peer_state(st.received,
                                                st.crashed)[tgt_idx]
        answered = tvalid & (peer_state < 2)
        dm = dm + answered.sum(dtype=I32)
        hit = answered & (peer_state == 1)
        out = exchange.route_one(
            jnp.where(hit, rreq % n_local, -1),
            jnp.where(hit, rreq // n_local, s), hit, s, cap,
            traffic=exch)
        (back, ovf4), exch = out[:2], (
            out[2] if exch is not None else None)
        bvalid = back >= 0
        pull_hit = jnp.zeros((n_local,), bool).at[
            jnp.where(bvalid, back, n_local)].max(bvalid, mode="drop")

        newly = (newly_push | pull_hit) & ~crashed & ~st.received
        received = st.received | newly
        dr = newly.sum(dtype=I32)
        dm, dr, dc = jax.lax.psum((dm, dr, dc), AXIS)
        ovf = jax.lax.psum(ovf1 + ovf2 + ovf4, AXIS)
        stp = st._replace(
            received=received, crashed=crashed, tick=st.tick + 1,
            total_message=msg64_add(st.total_message, dm),
            total_received=st.total_received + dr,
            total_crashed=st.total_crashed + dc,
            exchange_overflow=st.exchange_overflow + ovf)
        if exch is not None:
            stp = stp._replace(exch_counts=exch)
        return stp

    return round_shard


def make_sharded_step(cfg: Config, mesh):
    if cfg.protocol == "pushpull":
        return make_sharded_pushpull(cfg, mesh)
    return make_sharded_tick(cfg, mesh)


def make_sharded_heal(cfg: Config, mesh):
    """Sharded ring-engine overlay healing (shard_map body; None when
    -overlay-heal is off).  The failure detector's verdicts are per-shard
    (crash clock and crashed bits live with the rows); ONE bool-per-node
    all_gather publishes them so every shard can condemn its remote
    friends, then the repaired-edge re-sends ride the normal all_to_all
    route.  Heal draws are (tick, GLOBAL-id)-keyed (scenario.heal_and_
    wave), so the repair schedule matches the single-device engine
    bit-for-bit."""
    if not cfg.overlay_heal_resolved:
        return None
    from gossip_simulator_tpu import scenario as _scen

    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    detect = cfg.heal_detect_ms
    d = epidemic.ring_depth(cfg)
    spatial = cfg.telemetry_spatial_enabled and s > 1

    def heal_shard(st: SimState, base_key: jax.Array) -> SimState:
        shard = jax.lax.axis_index(AXIS)
        gids = shard * n_local + jnp.arange(n_local, dtype=I32)
        rows = jnp.arange(n_local, dtype=I32)
        k = st.friends.shape[1]
        detected = _scen.detect_dead(st.crashed, st.down_since, st.tick,
                                     detect)
        healer_ok = ~st.crashed
        sender_inf = st.received & ~st.crashed & ~st.removed
        bits_global = jax.lax.all_gather(
            _scen.heal_peer_bits(detected, sender_inf), AXIS, tiled=True)
        friends, resend, pull, delay, clear, rep, blk = _scen.heal_and_wave(
            cfg, st.friends, st.friend_cnt, bits_global, healer_ok,
            sender_inf, _scen.rejoined_mask(st.down_since), gids, st.tick,
            base_key)
        if cfg.effective_time_mode == "rounds":
            dslot = jnp.broadcast_to((st.tick + 1) % d,
                                     (n_local,)).astype(I32)
        else:
            dslot = ((st.tick + delay) % d).astype(I32)
        dst = jnp.where(resend, friends, -1).reshape(-1)
        slots = jnp.broadcast_to(dslot[:, None], (n_local, k)).reshape(-1)
        exch = st.exch_counts if spatial else None
        out = _deposit_routed(
            cfg, n_local, s, st.pending, dst, slots, resend.reshape(-1),
            exchange.epidemic_cap(n_local, k, s), exch=exch)
        (pending, ovf), exch = out[:2], (
            out[2] if exch is not None else None)
        # Rejoin pull responses deliver to the puller's OWN row -- always
        # shard-local, so they skip the route.
        pdst = jnp.broadcast_to(rows[:, None], (n_local, k)).reshape(-1)
        pending = epidemic.deposit_local(pending, pdst, slots,
                                         pull.reshape(-1),
                                         kernel=cfg.deliver_kernel_resolved)
        rep, blk, ovf = jax.lax.psum(
            (rep, jnp.asarray(blk, I32), ovf), AXIS)
        stp = st._replace(
            friends=friends, pending=pending,
            down_since=jnp.where(clear, -1, st.down_since),
            heal_repaired=st.heal_repaired + rep,
            part_dropped=st.part_dropped + blk,
            exchange_overflow=st.exchange_overflow + ovf)
        if exch is not None:
            stp = stp._replace(exch_counts=exch)
        return stp

    return heal_shard


def make_sharded_seed(cfg: Config, mesh):
    """Uniform-random global sender; its broadcast is routed like any wave."""
    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    spatial = cfg.telemetry_spatial_enabled and s > 1

    def seed_shard(st: SimState, base_key: jax.Array) -> SimState:
        shard = jax.lax.axis_index(AXIS)
        ks = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_SEED_NODE)
        kd = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_DELAY)
        kp = _rng.tick_key(jax.random.fold_in(base_key, shard),
                           epidemic.SEED_TICK, _rng.OP_DROP)
        sender = jax.random.randint(ks, (), 0, cfg.n, dtype=I32)
        gids = shard * n_local + jnp.arange(n_local, dtype=I32)
        is_sender = gids == sender
        received, total_received = st.received, st.total_received
        if cfg.protocol == "pushpull" or not cfg.compat_reference:
            received = received | is_sender
            total_received = total_received + 1  # replicated: +1 everywhere
        if cfg.protocol == "pushpull":
            return st._replace(received=received,
                               total_received=total_received)
        dslot = epidemic.row_slot(cfg, kd, st.tick,
                                  jnp.arange(n_local, dtype=I32))
        dst, slots, valid, blk = epidemic.edges_from_senders(
            cfg, st.friends, st.friend_cnt, is_sender, dslot, kp,
            tick=st.tick, gid0=shard * n_local)
        if cfg.scenario_resolved.has_partitions:
            st = st._replace(part_dropped=st.part_dropped
                             + jax.lax.psum(blk, AXIS))
        exch = st.exch_counts if spatial else None
        out = _deposit_routed(
            cfg, n_local, s, st.pending, dst, slots, valid,
            exchange.epidemic_cap(n_local, st.friends.shape[1], s),
            exch=exch)
        (pending, ovf), exch = out[:2], (
            out[2] if exch is not None else None)
        rb = st.rebroadcast
        if cfg.protocol == "sir":
            kr = _rng.tick_key(base_key, epidemic.SEED_TICK, _rng.OP_REMOVE)
            keep = ~_rng.bernoulli(kr, epidemic.p_eff(cfg, cfg.removal_rate),
                                   ())
            rb = rb.at[dslot, jnp.arange(n_local, dtype=I32)].max(
                is_sender & keep)
        ovf = jax.lax.psum(ovf, AXIS)
        stp = st._replace(received=received, total_received=total_received,
                          pending=pending, rebroadcast=rb,
                          exchange_overflow=st.exchange_overflow + ovf)
        if exch is not None:
            stp = stp._replace(exch_counts=exch)
        return stp

    return seed_shard


def make_sharded_init(cfg: Config, mesh):
    """Build the sharded SimState for a static graph directly on the mesh
    (each shard generates its own row slice; the row-keyed generators make
    this bit-identical to slicing a single-device generation)."""
    n_local = shard_size(cfg.n, mesh)
    n_shards = mesh.shape[AXIS]

    def init_shard():
        shard = jax.lax.axis_index(AXIS)
        key = graphs.graph_key(cfg)
        friends, cnt = graphs.generate(cfg, key, row0=shard * n_local,
                                       rows=n_local)
        return epidemic.init_state(cfg, friends, cnt, n_local=n_local,
                                   n_shards=n_shards)

    specs = sim_state_specs(cfg)
    fn = _shard_map(mesh, init_shard, in_specs=(), out_specs=specs)
    return jax.jit(fn)


# --------------------------------------------------------------------------
# Overlay phase (dynamic graph), sharded
# --------------------------------------------------------------------------

def make_sharded_overlay_round(cfg: Config, mesh):
    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    cap = cfg.mailbox_cap_for(n_local)
    # Membership messages per node per round <= em/eb; same capacity logic as
    # the epidemic wave.
    route_cap = exchange.epidemic_cap(n_local, cap + 2, s)

    def routed_deliver(src, dst, valid, mbox_cap):
        """Route (src payload) to dst's shard, then local mailbox deliver.
        One route_multi call: src and the local-destination payload share
        the sort and the all_to_all."""
        dest = jnp.where(valid, dst // n_local, s)
        dstl = jnp.where(valid, dst % n_local, 0)
        (rsrc, rdst), ovf = exchange.route_multi(
            (jnp.where(valid, src, -1), jnp.where(valid, dstl, -1)),
            dest, valid, s, route_cap)
        rvalid = rsrc >= 0
        mbox, _, dropped = deliver(rsrc, jnp.where(rvalid, rdst, 0), rvalid,
                                   n_local, mbox_cap,
                                   kernel=cfg.deliver_kernel_resolved)
        return mbox, dropped + ovf

    def ids_fn():
        shard = jax.lax.axis_index(AXIS)
        return shard * n_local + jnp.arange(n_local, dtype=I32)

    def sum_fn(x):
        return jax.lax.psum(x, AXIS)

    body = overlay.make_round_fn(cfg, deliver_fn=routed_deliver,
                                 ids_fn=ids_fn, sum_fn=sum_fn,
                                 n_rows=n_local)

    def round_shard(st: OverlayState, base_key: jax.Array) -> OverlayState:
        # Decorrelate per-shard draws inside the round body by folding the
        # shard id into the key stream.
        shard = jax.lax.axis_index(AXIS)
        return body(st, jax.random.fold_in(base_key, shard))

    return round_shard


def make_sharded_overlay_init(cfg: Config, mesh):
    n_local = shard_size(cfg.n, mesh)

    def init_shard():
        return overlay.init_state(cfg, n_local=n_local)

    return jax.jit(_shard_map(mesh, init_shard, in_specs=(),
                              out_specs=overlay_state_specs()))


# --------------------------------------------------------------------------
# Jitted drivers (loops live inside one shard_map region)
# --------------------------------------------------------------------------

def make_window_fn(cfg: Config, mesh, window: int):
    step = make_sharded_step(cfg, mesh)
    heal = make_sharded_heal(cfg, mesh)
    specs = sim_state_specs(cfg)

    def window_shard(st: SimState, base_key: jax.Array) -> SimState:
        st = jax.lax.fori_loop(0, window, lambda _, x: step(x, base_key), st)
        if heal is not None:
            st = heal(st, base_key)
        return st

    return jax.jit(_shard_map(mesh, window_shard, in_specs=(specs, P()),
                              out_specs=specs), donate_argnums=(0,))


def make_seed_fn(cfg: Config, mesh):
    specs = sim_state_specs(cfg)
    return jax.jit(_shard_map(mesh, make_sharded_seed(cfg, mesh),
                              in_specs=(specs, P()), out_specs=specs))


def make_overlay_round_fn(cfg: Config, mesh):
    specs = overlay_state_specs()
    return jax.jit(_shard_map(mesh, make_sharded_overlay_round(cfg, mesh),
                              in_specs=(specs, P()), out_specs=specs))


def make_run_to_coverage_fn(cfg: Config, mesh, telemetry: bool = False):
    """Bounded device-side while_loop (see epidemic.run_call_budget): the
    host re-enters until target/max_rounds/exhaustion.  With `telemetry`
    the loop carries the per-window History (utils/telemetry.py) inside
    shard_map with replicated specs -- the recorded totals are already
    psum-replicated by the step; the per-shard occupancy/removed probes
    reduce across shards so every shard writes identical rows."""
    step = make_sharded_step(cfg, mesh)
    heal = make_sharded_heal(cfg, mesh)
    specs = sim_state_specs(cfg)
    window = 1 if cfg.effective_time_mode == "rounds" else 10
    max_steps = cfg.max_rounds
    # Heal-on runs drop the early-death exit: healing can revive an empty
    # ring (see epidemic.make_run_to_coverage_fn).
    check_in_flight = (cfg.protocol != "pushpull"
                       and not cfg.overlay_heal_resolved)

    def cond_live(s, target_count, until):
        live = ((s.total_received < target_count)
                & (s.tick < max_steps) & (s.tick < until))
        if check_in_flight:
            # psum of each shard's ring-occupied indicator
            # (replicated, so every shard agrees): exit at wave
            # death instead of spinning to the bounded-call budget
            # -- same term the sharded event engine's cond has
            # (event_sharded.make_run_to_coverage_fn).
            live = live & (jax.lax.psum(state_mod.in_flight(s),
                                        AXIS) > 0)
        return live

    def advance(s, base_key):
        s = jax.lax.fori_loop(0, window, lambda _, x: step(x, base_key), s)
        if heal is not None:
            s = heal(s, base_key)
        return s

    if telemetry:
        from gossip_simulator_tpu.utils import telemetry as telem

        sir = cfg.protocol == "sir"
        ihwm = exchange.inflight_hwm(cfg, mesh.shape[AXIS])
        spatial = telem.spatial_spec(cfg, int(mesh.shape[AXIS]))
        hspecs = telem.bundle_specs(spatial, P)

        @functools.partial(jax.jit, donate_argnums=(0, 4))
        def run_t(st: SimState, base_key, target_count, until, hist):
            def run_shard(st, base_key, target_count, until, hist):
                def cond(carry):
                    s, _ = carry
                    return cond_live(s, target_count, until)

                def body(carry):
                    s, h = carry
                    s = advance(s, base_key)
                    row = telem.gossip_probe(
                        s, sir, psum=lambda x: jax.lax.psum(x, AXIS),
                        pmax=lambda x: jax.lax.pmax(x, AXIS),
                        inflight_hwm=ihwm)
                    return s, telem.record_window(
                        h, row, st=s, spec=spatial,
                        shard_index=jax.lax.axis_index(AXIS),
                        gather=lambda x: jax.lax.all_gather(x, AXIS),
                        psum=lambda x: jax.lax.psum(x, AXIS))

                return jax.lax.while_loop(cond, body, (st, hist))

            return _shard_map(
                mesh, run_shard,
                in_specs=(specs, P(), P(), P(), hspecs),
                out_specs=(specs, hspecs))(st, base_key, target_count,
                                           until, hist)

        return run_t

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(st: SimState, base_key: jax.Array, target_count: jax.Array,
            until: jax.Array) -> SimState:
        def run_shard(st, base_key, target_count, until):
            return jax.lax.while_loop(
                lambda s: cond_live(s, target_count, until),
                lambda s: advance(s, base_key), st)

        return _shard_map(mesh, run_shard, in_specs=(specs, P(), P(), P()),
                          out_specs=specs)(st, base_key, target_count, until)

    return run
