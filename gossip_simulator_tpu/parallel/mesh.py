"""Device mesh for the node axis.

The reference scales by spawning goroutines inside one process
(simulator.go:214-217); the TPU framework scales by sharding the node axis
over a 1-D mesh (SURVEY §2.2 row 4).  One axis ("nodes") is all the
simulator needs -- collectives ride ICI within a slice; multi-slice DCN works
through the same axis via jax's standard multi-host initialization.
"""

from __future__ import annotations

import inspect
import time

import jax
import numpy as np
from jax.sharding import Mesh

AXIS = "nodes"


class DistributedInitError(RuntimeError):
    """jax.distributed.initialize failed after bounded, retried attempts.
    Carries the coordinator address, attempt count and elapsed seconds in
    the message -- a *named* failure instead of a silent hang (the r6-r9
    TPU pool attempts each burned a full opaque 60s timeout)."""


def bounded_initialize(coordinator_address=None, num_processes=None,
                       process_id=None, timeout_s: float = 60.0,
                       retries: int = 3, base_delay_s: float = 1.0,
                       _sleep=time.sleep) -> float:
    """`jax.distributed.initialize` with a bounded per-attempt timeout and
    exponential-backoff retry.  Passes jax's own `initialization_timeout`
    when this jax version accepts it (0.4.15+); on older jax the attempt
    relies on jax's internal default but the retry/naming contract still
    holds.  Returns elapsed seconds on success; raises DistributedInitError
    naming address, attempts and elapsed on exhaustion.  None kwargs are
    omitted so jax's env autodetection still applies."""
    kw = {}
    if coordinator_address is not None:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    try:
        params = inspect.signature(jax.distributed.initialize).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic builds
        params = {}
    if "initialization_timeout" in params:
        kw["initialization_timeout"] = max(int(timeout_s), 1)
    t0 = time.monotonic()
    last_err: Exception | None = None
    attempts = max(retries, 1)
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(**kw)
            return time.monotonic() - t0
        except Exception as e:  # noqa: BLE001 - grpc raises bare RuntimeError
            last_err = e
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 - nothing to tear down
                pass
            if attempt < attempts - 1:
                _sleep(base_delay_s * (2 ** attempt))
    elapsed = time.monotonic() - t0
    addr = coordinator_address or "<env-autodetected>"
    raise DistributedInitError(
        f"jax.distributed.initialize failed against {addr} after "
        f"{attempts} attempt(s) in {elapsed:.1f}s "
        f"(timeout {timeout_s:.0f}s/attempt): {last_err}") from last_err


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable shard_map: `jax.shard_map(..., check_vma=...)` on
    current jax; `jax.experimental.shard_map.shard_map(..., check_rep=...)`
    on the 0.4.x line.  Replication checking stays off either way (the
    per-shard bodies return psum-replicated scalars the checker cannot
    prove).  THE one entry point for every shard_map in the repo."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def node_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"({jax.default_backend()}); for CPU testing set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def shard_size(n: int, mesh: Mesh) -> int:
    s = mesh.shape[AXIS]
    if n % s:
        raise ValueError(
            f"n ({n}) must be divisible by the mesh size ({s}); "
            f"pad n or change the device count")
    return n // s
