"""Device mesh for the node axis.

The reference scales by spawning goroutines inside one process
(simulator.go:214-217); the TPU framework scales by sharding the node axis
over a 1-D mesh (SURVEY §2.2 row 4).  One axis ("nodes") is all the
simulator needs -- collectives ride ICI within a slice; multi-slice DCN works
through the same axis via jax's standard multi-host initialization.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

AXIS = "nodes"


def shard_map(fn, mesh, in_specs, out_specs):
    """Version-portable shard_map: `jax.shard_map(..., check_vma=...)` on
    current jax; `jax.experimental.shard_map.shard_map(..., check_rep=...)`
    on the 0.4.x line.  Replication checking stays off either way (the
    per-shard bodies return psum-replicated scalars the checker cannot
    prove).  THE one entry point for every shard_map in the repo."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def node_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)} "
                f"({jax.default_backend()}); for CPU testing set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_devices}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def shard_size(n: int, mesh: Mesh) -> int:
    s = mesh.shape[AXIS]
    if n % s:
        raise ValueError(
            f"n ({n}) must be divisible by the mesh size ({s}); "
            f"pad n or change the device count")
    return n // s
