"""Cross-shard message routing: the ICI replacement for the reference's
shared-address-space channel sends (`GlobalView[id].ch <- msg`,
simulator.go:145,154,161).

A shard's outgoing messages (global destination + payload) are bucketed by
destination shard, placed into a fixed-capacity ``[S, cap]`` buffer, and
exchanged with one `lax.all_to_all` over the "nodes" mesh axis.  Capacity
overflow is counted (never silently lost) -- with uniform-random destinations
the per-pair load concentrates at mean/S, so cap = a few x mean/S makes
overflow astronomically rare (SURVEY §7.3 hard part #4).

Bucketing (round 6): for the small meshes this simulator runs (S <= 16,
RANK_MAX_SHARDS) the per-bucket rank is ONE-HOT CUMSUM arithmetic over the
S destination columns -- the same trick the mail ring's append uses over
its ~3 window slots (ops/mailbox.ring_append) -- instead of the round-1
stable sort + segment_ranks pass.  The sort was the single heaviest op in
the routed append (a full lax.sort of width*k lanes PER emission batch;
see scripts/profile_exchange.py for the measured ratio), and the ranks it
produced are exactly reproducible without it: an entry's rank within its
destination bucket is the count of earlier valid entries with the same
destination, which the masked cumsum computes in one pass.  Buffer
contents are bit-identical to the sorted path (positions (dest, rank) are
unique, survivors keep emission order) -- pinned by
tests/test_sharded.py::test_route_multi_rank_matches_sort.  Meshes wider
than RANK_MAX_SHARDS (where the M x S one-hot workspace would outgrow the
sorted form) keep the sort path.

All functions run INSIDE shard_map.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from gossip_simulator_tpu import tuning as _tuning
from gossip_simulator_tpu.ops.mailbox import segment_ranks
from gossip_simulator_tpu.parallel.mesh import AXIS

I32 = jnp.int32

# Widest mesh the one-hot bucketing rank serves; beyond it the M x S
# cumsum workspace grows past what the sort pass costs.  Every mesh this
# repo targets (v5e-8, the 8-fake-device CPU shim) is far inside it.
RANK_MAX_SHARDS = 16


def route_multi(payloads, dest_shard: jnp.ndarray, valid: jnp.ndarray,
                n_shards: int, cap: int, axis: str = AXIS,
                sort_buckets: bool | None = None, traffic=None):
    """Exchange several int32 payload arrays that share one (dest, valid)
    keying: one bucketing-rank pass carries all payloads, the per-payload
    buffers concatenate into a single all_to_all.

    Args:
        payloads: tuple of int32[M] (each >= 0 for valid messages; -1 is
            the wire sentinel for an empty slot).
        dest_shard: int32[M] destination shard per message.
        valid: bool[M].
        n_shards: mesh size S.
        cap: per-destination-shard buffer slots.
        sort_buckets: None (auto: sort only past RANK_MAX_SHARDS), or
            force the sort (True) / one-hot cumsum (False) rank path --
            the two produce bit-identical buffers (module docstring);
            the override exists for the profiler and the parity test.
        traffic: None, or the caller's int32[1, S+2] spatial-telemetry
            counter leaf (models/state.SimState.exch_counts).  When armed
            the route also accumulates [:S] += delivered sends per
            destination shard (overflowed lanes excluded, so column sums
            of the traffic matrix equal receiver-side counts exactly),
            [S] += deliveries received here, [S+1] += local bucket
            overflow, and a 3rd value returns the updated leaf.  The
            delivered payload bits are untouched either way.

    Returns:
        recvs: tuple of int32[S*cap] received payloads (-1 = empty slot),
            slot-aligned across payloads.
        overflow: int32[] messages dropped for capacity locally.
        traffic: updated counter leaf -- ONLY when `traffic` was passed.
    """
    stacked, overflow, sent = _bucket_pack(
        payloads, dest_shard, valid, n_shards, cap, sort_buckets,
        count_sent=traffic is not None)
    if n_shards > 1:
        recv = jax.lax.all_to_all(stacked, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
    else:
        # A tiled 1-device all_to_all is the identity; skip the collective
        # (every S=1 route caller -- the routing-constant bench twins, the
        # ring engine's deliveries, the overlay -- pays it per batch).
        recv = stacked
    recvs = tuple(recv[:, i * cap:(i + 1) * cap].reshape(-1)
                  for i in range(len(payloads)))
    if traffic is None:
        return recvs, overflow
    return recvs, overflow, _traffic_update(traffic, sent, recvs[0],
                                            overflow)


def _traffic_update(traffic, sent, recv0, overflow):
    """Accumulate one route's counts into the int32[1, S+2] leaf: [:S]
    delivered sends per destination, [S] deliveries received (valid slots
    of the first payload -- slot-aligned, one message per slot), [S+1]
    bucket overflow."""
    got = (recv0 >= 0).sum(dtype=I32)
    row = jnp.concatenate([sent, got[None], overflow[None]])
    return traffic + row[None, :]


def _bucket_pack(payloads, dest_shard, valid, n_shards, cap, sort_buckets,
                 count_sent=False):
    """Bucket-by-destination rank + flat scatter into the [S, len(payloads)
    * cap] send buffer -- the pre-collective half of route_multi, split out
    so the pipelined route can order the pack against the previous batch's
    staged drain.  Op-for-op the round-6 pack (bit-identical buffers).

    Returns (stacked, overflow, sent): `sent` is the int32[S] delivered
    (rank < cap) send count per destination shard when `count_sent`, else
    None -- computed from the masks the pack already built, so the armed
    path adds reductions only."""
    if sort_buckets is None:
        sort_buckets = n_shards > _tuning.value(
            "exchange.rank_max_shards", None, default=RANK_MAX_SHARDS)
    key = jnp.where(valid, dest_shard, n_shards).astype(I32)
    if sort_buckets:
        # Stable sort + segment ranks (the round-1 path, kept for wide
        # meshes): flat scatter with an in-bounds trash cell -- 2-D index
        # scatters are ~15x slower here (ops/mailbox.deliver's NOTE).
        srt = jax.lax.sort((key, *[p.astype(I32) for p in payloads]),
                           num_keys=1, is_stable=True)
        sk, sps = srt[0], srt[1:]
        rank = segment_ranks(sk)
        ok = (sk < n_shards) & (rank < cap)
        flat = jnp.where(ok, sk * cap + rank, n_shards * cap)  # trash cell
        vals = [jnp.where(ok, sp, -1) for sp in sps]
        overflow = ((sk < n_shards) & (rank >= cap)).sum(dtype=I32)
        sent = (((sk[:, None] == jnp.arange(n_shards, dtype=I32)[None, :])
                 & ok[:, None]).sum(axis=0, dtype=I32)
                if count_sent else None)
    else:
        # Sort-free: rank within the destination bucket = count of earlier
        # valid entries with the same destination (masked cumsum over the
        # S one-hot columns).  Scatter positions (dest, rank) are unique
        # for valid lanes, so the unsorted scatter lands the identical
        # buffer; overflowed and invalid lanes share the trash cell
        # (all write -1, order-free).
        oh = ((key[:, None] == jnp.arange(n_shards, dtype=I32)[None, :])
              .astype(I32))
        rank = (jnp.cumsum(oh, axis=0) * oh).sum(axis=1) - 1
        ok = (key < n_shards) & (rank < cap)
        flat = jnp.where(ok, key * cap + rank, n_shards * cap)
        vals = [jnp.where(ok, p.astype(I32), -1) for p in payloads]
        overflow = ((key < n_shards) & (rank >= cap)).sum(dtype=I32)
        sent = ((oh * ok[:, None]).sum(axis=0, dtype=I32)
                if count_sent else None)
    bufs = []
    for v in vals:
        buf = jnp.full((n_shards * cap + 1,), -1, I32)
        bufs.append(buf.at[flat].set(v)
                    [:n_shards * cap].reshape(n_shards, cap))
    return jnp.concatenate(bufs, axis=1), overflow, sent


def route_multi_pipelined(payloads, dest_shard: jnp.ndarray,
                          valid: jnp.ndarray, n_shards: int, cap: int,
                          stage, axis: str = AXIS,
                          sort_buckets: bool | None = None, traffic=None):
    """Double-buffered route_multi: pack this batch's send buffer, ORDER
    the pack before the previous batch's staged drain with
    `lax.optimization_barrier`, then dispatch the collective.

    `stage` is the caller's pending-drain carry (any pytree; in
    event_sharded it is the deferred ring_append arguments from the
    previous batch).  The barrier makes every returned stage leaf depend
    on the packed send buffer, so the drain that consumes the returned
    `stage` cannot be scheduled before the pack materializes -- at which
    point the all_to_all's start has no remaining inputs, and XLA's
    async collective scheduler (which splits the op into start/done) is
    free to hoist the dispatch above the whole drain.  The values are
    untouched (optimization_barrier is an identity), so delivered bits
    are exactly route_multi's.

    Returns (recvs, overflow, stage) -- recvs/overflow as route_multi,
    stage the barrier-threaded carry to drain now.  With `traffic`
    (route_multi's spatial counter leaf) a 4th value returns the updated
    leaf.
    """
    stacked, overflow, sent = _bucket_pack(
        payloads, dest_shard, valid, n_shards, cap, sort_buckets,
        count_sent=traffic is not None)
    leaves, treedef = jax.tree_util.tree_flatten(stage)
    if leaves:
        stacked, *leaves = jax.lax.optimization_barrier((stacked, *leaves))
        stage = jax.tree_util.tree_unflatten(treedef, leaves)
    if n_shards > 1:
        recv = jax.lax.all_to_all(stacked, axis, split_axis=0,
                                  concat_axis=0, tiled=True)
    else:
        recv = stacked
    recvs = tuple(recv[:, i * cap:(i + 1) * cap].reshape(-1)
                  for i in range(len(payloads)))
    if traffic is None:
        return recvs, overflow, stage
    return recvs, overflow, stage, _traffic_update(traffic, sent, recvs[0],
                                                   overflow)


def ovf_split(xovf):
    """View a threaded overflow carry as (scalar, traffic-or-None).

    The sharded engines thread one exchange_overflow value positionally
    through deep emission carries (fori bodies, batch loops, the pipeline
    stage).  With the spatial panels armed that value becomes the pair
    (overflow scalar, exch_counts leaf) so the traffic accumulator rides
    the SAME positions untouched -- only the route helpers (which add to
    it) and the window-step boundaries (seed / psum / state writeback)
    ever look inside, via this pair of views."""
    return xovf if isinstance(xovf, tuple) else (xovf, None)


def ovf_join(ovf, traffic):
    """Inverse of ovf_split: rebuild the threaded carry."""
    return ovf if traffic is None else (ovf, traffic)


def pipeline_enabled(cfg, n_shards: int) -> bool:
    """Whether the routed exchange runs the double-buffered schedule
    (-exchange-pipeline, ROADMAP item 1) on an `n_shards` mesh -- the ONE
    gate every sharded engine consults.  S=1 always runs serial: there is
    no collective in the program to overlap, so a forced "double" is
    trivially identical there.  exchange.pipeline_depth < 2 (tuning)
    also falls back to serial -- depth 1 IS the serial schedule."""
    return (n_shards > 1 and cfg.exchange_pipeline_resolved == "double"
            and _tuning.value("exchange.pipeline_depth", cfg) >= 2)


def inflight_hwm(cfg, n_shards: int) -> int:
    """Static high-water mark of exchange buffers alive at once on an
    engine build (the telemetry `exchange_inflight_hwm` column): 0 = no
    collective in the program (S=1 routes are the identity), 1 = serial
    route->drain, 2 = the double-buffered pipeline."""
    if n_shards <= 1:
        return 0
    return 2 if pipeline_enabled(cfg, n_shards) else 1


def route_one(payload: jnp.ndarray, dest_shard: jnp.ndarray,
              valid: jnp.ndarray, n_shards: int, cap: int,
              axis: str = AXIS, sort_buckets: bool | None = None,
              traffic=None):
    """Exchange one int32 payload array (see route_multi)."""
    out = route_multi((payload,), dest_shard, valid, n_shards,
                      cap, axis, sort_buckets=sort_buckets, traffic=traffic)
    if traffic is None:
        (recv,), overflow = out
        return recv, overflow
    (recv,), overflow, traffic = out
    return recv, overflow, traffic


def epidemic_cap(n_local: int, k: int, n_shards: int, safety: int = 4) -> int:
    """Per-pair buffer for the broadcast wave.  A tick's local wave is at most
    n_local*k edges spread over S destination shards; `safety` covers skew.
    Clamped to the zero-loss bound n_local*k (can't exceed the edge count)."""
    mean = max(1, (n_local * k) // max(n_shards, 1))
    return int(min(n_local * k, max(64, safety * mean)))


def chernoff_cap(m_edges: int, n_shards: int) -> int:
    """Per-pair wire cap for a batch of `m_edges` uniform-random-destination
    messages over `n_shards`: the actual per-pair high-water mark
    (mean = m/S) plus a Chernoff pad, instead of the zero-loss worst case
    m_edges.  pad = max(64, 8*sqrt(mean)) puts the per-(pair, batch)
    overflow probability near exp(-32) ~ 1e-14 (multiplicative Chernoff,
    P[X > mean + d] <= exp(-d^2 / (2 mean + 2d/3)) for binomial X) --
    astronomically rare over any run's batch count, and overflow is
    counted in exchange_overflow, never silent.  SOUND ONLY for
    destination-uniform graphs (kout, erdos -- every pick is uniform over
    [0, n)); ring lattices and settled overlays can concentrate a whole
    batch on one pair, so callers gate on graph type and fall back to the
    zero-loss bound (the `min` keeps small batches lossless either way)."""
    if n_shards <= 1:
        return m_edges
    mean = -(-m_edges // n_shards)
    pad = _tuning.value("exchange.chernoff_pad", None)
    return int(min(m_edges, mean + max(64, math.ceil(pad * math.sqrt(mean)))))


def pack_dst_slot(dst_local: jnp.ndarray, dslot: jnp.ndarray, d: int):
    """Pack (local destination, ring slot) into one int32 for the wire:
    value = dst_local * d + dslot.  Valid while n_local * d < 2^31 (e.g.
    67M nodes/shard at d=32)."""
    return dst_local * d + dslot


def unpack_dst_slot(packed: jnp.ndarray, d: int):
    return packed // d, packed % d
