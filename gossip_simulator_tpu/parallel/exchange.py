"""Cross-shard message routing: the ICI replacement for the reference's
shared-address-space channel sends (`GlobalView[id].ch <- msg`,
simulator.go:145,154,161).

A shard's outgoing messages (global destination + payload) are bucketed by
destination shard with the same sort-and-rank machinery as the local mailbox
(ops/mailbox.py), placed into a fixed-capacity ``[S, cap]`` buffer, and
exchanged with one `lax.all_to_all` over the "nodes" mesh axis.  Capacity
overflow is counted (never silently lost) -- with uniform-random destinations
the per-pair load concentrates at mean/S, so cap = a few x mean/S makes
overflow astronomically rare (SURVEY §7.3 hard part #4).

All functions run INSIDE shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gossip_simulator_tpu.ops.mailbox import segment_ranks
from gossip_simulator_tpu.parallel.mesh import AXIS

I32 = jnp.int32


def route_multi(payloads, dest_shard: jnp.ndarray, valid: jnp.ndarray,
                n_shards: int, cap: int, axis: str = AXIS):
    """Exchange several int32 payload arrays that share one (dest, valid)
    keying: ONE stable sort carries all payloads, the per-payload buffers
    concatenate into a single all_to_all.  Same fast pattern as
    ops/mailbox.deliver (payload-carrying sort, flat scatter with an
    in-bounds trash cell -- 2-D index scatters are ~15x slower here).

    Args:
        payloads: tuple of int32[M] (each >= 0 for valid messages; -1 is
            the wire sentinel for an empty slot).
        dest_shard: int32[M] destination shard per message.
        valid: bool[M].
        n_shards: mesh size S.
        cap: per-destination-shard buffer slots.

    Returns:
        recvs: tuple of int32[S*cap] received payloads (-1 = empty slot),
            slot-aligned across payloads.
        overflow: int32[] messages dropped for capacity locally.
    """
    key = jnp.where(valid, dest_shard, n_shards).astype(I32)
    srt = jax.lax.sort((key, *[p.astype(I32) for p in payloads]),
                       num_keys=1, is_stable=True)
    sk, sps = srt[0], srt[1:]
    rank = segment_ranks(sk)
    ok = (sk < n_shards) & (rank < cap)
    flat = jnp.where(ok, sk * cap + rank, n_shards * cap)  # trash cell
    bufs = []
    for sp in sps:
        buf = jnp.full((n_shards * cap + 1,), -1, I32)
        bufs.append(buf.at[flat].set(jnp.where(ok, sp, -1))
                    [:n_shards * cap].reshape(n_shards, cap))
    overflow = ((sk < n_shards) & (rank >= cap)).sum(dtype=I32)
    recv = jax.lax.all_to_all(jnp.concatenate(bufs, axis=1), axis,
                              split_axis=0, concat_axis=0, tiled=True)
    recvs = tuple(recv[:, i * cap:(i + 1) * cap].reshape(-1)
                  for i in range(len(bufs)))
    return recvs, overflow


def route_one(payload: jnp.ndarray, dest_shard: jnp.ndarray,
              valid: jnp.ndarray, n_shards: int, cap: int,
              axis: str = AXIS):
    """Exchange one int32 payload array (see route_multi)."""
    (recv,), overflow = route_multi((payload,), dest_shard, valid, n_shards,
                                    cap, axis)
    return recv, overflow


def epidemic_cap(n_local: int, k: int, n_shards: int, safety: int = 4) -> int:
    """Per-pair buffer for the broadcast wave.  A tick's local wave is at most
    n_local*k edges spread over S destination shards; `safety` covers skew.
    Clamped to the zero-loss bound n_local*k (can't exceed the edge count)."""
    mean = max(1, (n_local * k) // max(n_shards, 1))
    return int(min(n_local * k, max(64, safety * mean)))


def pack_dst_slot(dst_local: jnp.ndarray, dslot: jnp.ndarray, d: int):
    """Pack (local destination, ring slot) into one int32 for the wire:
    value = dst_local * d + dslot.  Valid while n_local * d < 2^31 (e.g.
    67M nodes/shard at d=32)."""
    return dst_local * d + dslot


def unpack_dst_slot(packed: jnp.ndarray, d: int):
    return packed // d, packed % d
