"""Sharded tick-faithful overlay construction (-overlay-mode ticks,
backend=sharded): models/overlay_ticks.py over the node mesh.

Each shard owns a contiguous row slice and its own packed window ring;
emissions are routed to their destination's shard with one all_to_all per
compaction chunk (parallel/exchange.route_multi), window counters are
psum'd (replicated, so the quiescence predicate agrees on every shard), and
the membership decision rules are the SAME shared kernels the single-device
engines use (overlay.process_breakup_slot / process_makeup_slot).

The bootstrap burst and its delays are keyed by GLOBAL row / emission
index, so the initial friends table and the initial in-flight messages are
bit-identical to a single-device run's -- only their placement differs.
Later processing draws are per-shard streams (like the sharded rounds
overlay), so trajectories diverge from single-device statistically, not
structurally; parity is validated by the same degree-distribution and
stabilization-clock tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models import overlay_ticks as ot
from gossip_simulator_tpu.ops.mailbox import ring_append
from gossip_simulator_tpu.ops.select import first_true_indices
from gossip_simulator_tpu.parallel import exchange
from gossip_simulator_tpu.parallel.mesh import AXIS, shard_size
from gossip_simulator_tpu.utils import rng as _rng

I32 = jnp.int32


def overlay_tick_state_specs() -> ot.OverlayTickState:
    # spill: the sharded ticks engine keeps counted drops (no routed spill
    # path, like the sharded rounds overlay), so the field is the token
    # (2, 1) constant -- replicated, never written.
    return ot.OverlayTickState(
        friends=P(AXIS, None), friend_cnt=P(AXIS),
        ring_dst=P(AXIS), ring_pay=P(AXIS), ring_cnt=P(AXIS, None),
        spill=P(None, None),
        tick=P(), makeups=P(), breakups=P(),
        win_makeups=P(), win_breakups=P(), mailbox_dropped=P())


def _shard_map(mesh, fn, in_specs, out_specs):
    from gossip_simulator_tpu.parallel.mesh import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def _route_append(cfg, n_local, s, ring, dst_g, pay, wslot, valid, rcap):
    """Route (global dst, pay, wslot) entries to their owner shards and
    append into the local ring (entries store LOCAL destinations)."""
    ring_dst, ring_pay, ring_cnt, dropped = ring
    dw = ot.ring_windows(cfg)
    cap = (ring_dst.shape[0] - 1) // dw
    (rd, rp, rw), ovf = exchange.route_multi(
        (jnp.where(valid, dst_g % n_local, -1),
         jnp.where(valid, pay, -1),
         jnp.where(valid, wslot, -1)),
        jnp.where(valid, dst_g // n_local, s), valid, s, rcap)
    rvalid = rd >= 0
    (ring_dst, ring_pay), ring_cnt, dropped = ring_append(
        (ring_dst, ring_pay), ring_cnt, dropped + ovf,
        (jnp.where(rvalid, rd, 0), jnp.where(rvalid, rp, 0)),
        jnp.where(rvalid, rw, 0), rvalid, dw, cap,
        kernel=cfg.deliver_kernel_resolved)
    return ring_dst, ring_pay, ring_cnt, dropped


def _route_stage_ot(cfg, n_local, s, dst_g, pay, wslot, valid, rcap,
                    pstage):
    """Pipelined twin of _route_append's route half (-exchange-pipeline
    double): same pack/route, append deferred -- the caller appends the
    barrier-threaded PREVIOUS stage behind this chunk's in-flight
    collective.  Nothing in the emission chunk loop reads the ring
    (first_true_indices keys off `remaining` only), so the deferral is
    bit-identical; stage = ((rd, rp, rw), ovf) with -1 the empty-lane
    sentinel."""
    (rd, rp, rw), ovf, pstage = exchange.route_multi_pipelined(
        (jnp.where(valid, dst_g % n_local, -1),
         jnp.where(valid, pay, -1),
         jnp.where(valid, wslot, -1)),
        jnp.where(valid, dst_g // n_local, s), valid, s, rcap, pstage)
    return (rd, rp, rw), ovf, pstage


def _flush_append_ot(cfg, ring, stage, ovf):
    """Apply a staged append (the deferred half of _route_stage_ot) --
    the exact ring_append _route_append runs, one chunk late."""
    ring_dst, ring_pay, ring_cnt, dropped = ring
    dw = ot.ring_windows(cfg)
    cap = (ring_dst.shape[0] - 1) // dw
    rd, rp, rw = stage
    rvalid = rd >= 0
    (ring_dst, ring_pay), ring_cnt, dropped = ring_append(
        (ring_dst, ring_pay), ring_cnt, dropped + ovf,
        (jnp.where(rvalid, rd, 0), jnp.where(rvalid, rp, 0)),
        jnp.where(rvalid, rw, 0), rvalid, dw, cap,
        kernel=cfg.deliver_kernel_resolved)
    return ring_dst, ring_pay, ring_cnt, dropped


def make_sharded_init(cfg: Config, mesh):
    """Per-shard state + the routed window-0 bootstrap burst."""
    n, f, k = cfg.n, cfg.fanout, cfg.max_degree
    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    b = ot.batch_ticks(cfg)
    dw = ot.ring_windows(cfg)
    cap = ot.slot_cap(cfg, n_local)
    chunk = ot.emit_chunk(cfg, n_local)
    flat_n = n_local * f
    rcap = exchange.epidemic_cap(chunk, 1, s)

    def init_shard(base_key):
        shard = jax.lax.axis_index(AXIS)
        gids = shard * n_local + jnp.arange(n_local, dtype=I32)
        kb = _rng.tick_key(base_key, 0, _rng.OP_BOOTSTRAP)
        # Global row keys: the same friends table a single-device run draws.
        w = jax.vmap(
            lambda kk: jax.random.randint(kk, (f,), 0, n, dtype=I32))(
            _rng.row_keys(kb, gids))
        w = jnp.where(w == gids[:, None], (w + 1) % n, w)
        friends = jnp.full((n_local, k), -1, I32).at[:, :f].set(w)
        cnt = jnp.full((n_local,), f, I32)
        ring_dst = jnp.zeros((dw * cap + 1,), I32)
        ring_pay = jnp.zeros((dw * cap + 1,), I32)
        ring_cnt = jnp.zeros((1, dw), I32)
        kd = _rng.tick_key(base_key, 0, _rng.OP_DELAY)

        def body(i, carry):
            idx = i * chunk + jnp.arange(chunk, dtype=I32)
            valid = idx < flat_n
            src_g = jnp.where(valid, shard * n_local + idx // f, 0)
            dst = w.reshape(-1).at[jnp.where(valid, idx, 0)].get()
            # Global emission index -> the single-device burst's delays.
            delay = _rng.row_uniform_delay(
                kd, cfg.delaylow, cfg.delayhigh,
                jnp.where(valid, shard * flat_n + idx, n * f))
            arrive = delay  # emitted at t=0
            return _route_append(
                cfg, n_local, s, carry, jnp.where(valid, dst, 0),
                (src_g * 2 + ot.MK) * b + arrive % b,
                (arrive // b) % dw, valid, rcap)

        z = jnp.zeros((), I32)
        ring_dst, ring_pay, ring_cnt, dropped = jax.lax.fori_loop(
            0, -(-flat_n // chunk), body,
            (ring_dst, ring_pay, ring_cnt, z))
        return ot.OverlayTickState(
            friends=friends, friend_cnt=cnt,
            ring_dst=ring_dst, ring_pay=ring_pay, ring_cnt=ring_cnt,
            spill=jnp.full((2, 1), -1, I32),
            tick=z, makeups=z, breakups=z,
            win_makeups=z, win_breakups=z,
            mailbox_dropped=jax.lax.psum(dropped, AXIS))

    specs = overlay_tick_state_specs()
    return jax.jit(_shard_map(mesh, init_shard, in_specs=(P(),),
                              out_specs=specs))


def make_poll_fn(cfg: Config, mesh):
    """One 10 ms poll window as one jitted shard_map call.  The step body
    is the single-device engine's (overlay_ticks.make_step_fn) with the
    four backend hooks supplied here -- global row ids, shard-folded key
    streams, psum reductions, and route-then-append emissions -- so the
    two -overlay-mode ticks engines share every line of sequencing and
    decision logic."""
    n = cfg.n
    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    b = ot.batch_ticks(cfg)
    dw = ot.ring_windows(cfg)
    cap_mb = cfg.mailbox_cap_for(n_local, stacked=True)
    echunk = ot.emit_chunk(cfg, n_local)
    rcap = exchange.epidemic_cap(echunk, 1, s)
    steps = max(1, -(-10 // b))
    # Exchange pipelining: emit_routed's chunk loop defers each chunk's
    # ring append one chunk behind its all_to_all, contained inside the
    # emission (prologue seeds an empty stage, epilogue flushes the last
    # one before the step sequencing continues).
    pipe = exchange.pipeline_enabled(cfg, s)

    def emit_routed(ring, base_key, w, em_dst, em_toff, typ, op):
        """Compact a local (n_local, cap_mb) emission buffer, draw
        per-message delays (keyed by global emission index) and route each
        entry to its destination's shard."""
        shard = jax.lax.axis_index(AXIS)
        flat_n = n_local * cap_mb
        dflat = em_dst.reshape(-1)
        tflat = em_toff.reshape(-1)
        valid_all = dflat >= 0
        # Chunk count must agree across shards: the loop body routes.
        total = jax.lax.pmax(valid_all.sum(dtype=I32), AXIS)
        kd = _rng.tick_key(base_key, w, op)

        def chunk_args(remaining):
            idx = first_true_indices(remaining, echunk)
            hit = jnp.zeros((flat_n,), bool).at[idx].set(True, mode="drop")
            remaining = remaining & ~hit
            okx = idx < flat_n
            src_g = jnp.where(okx, shard * n_local + idx // cap_mb, 0)
            dst = dflat.at[idx].get(mode="fill", fill_value=-1)
            toff = tflat.at[idx].get(mode="fill", fill_value=0)
            valid = dst >= 0
            delay = _rng.row_uniform_delay(
                kd, cfg.delaylow, cfg.delayhigh,
                jnp.where(okx, shard * flat_n + idx, s * flat_n))
            arrive = w * b + toff + delay
            return (remaining, jnp.where(valid, dst, 0),
                    (src_g * 2 + typ) * b + arrive % b,
                    (arrive // b) % dw, valid)

        nchunks = (total + echunk - 1) // echunk
        if pipe:
            def body_pipe(_, carry):
                ring, remaining, (pstage, povf) = carry
                remaining, dstv, pay, wsl, valid = chunk_args(remaining)
                nstage, ovf, pthr = _route_stage_ot(
                    cfg, n_local, s, dstv, pay, wsl, valid, rcap, pstage)
                ring = _flush_append_ot(cfg, ring, pthr, povf)
                return ring, remaining, (nstage, ovf)

            empty = ((jnp.full((s * rcap,), -1, I32),) * 3,
                     jnp.zeros((), I32))
            ring, _, (pend, povf) = jax.lax.fori_loop(
                0, nchunks, body_pipe, (ring, valid_all, empty))
            return _flush_append_ot(cfg, ring, pend, povf)

        def body(_, carry):
            ring, remaining = carry
            remaining, dstv, pay, wsl, valid = chunk_args(remaining)
            ring = _route_append(
                cfg, n_local, s, ring, dstv, pay, wsl, valid, rcap)
            return ring, remaining

        (ring, _) = jax.lax.fori_loop(0, nchunks, body, (ring, valid_all))
        return ring

    def ids_fn():
        shard = jax.lax.axis_index(AXIS)
        return shard * n_local + jnp.arange(n_local, dtype=I32)

    def key_fn(base_key, w, op):
        shard = jax.lax.axis_index(AXIS)
        return _rng.tick_key(jax.random.fold_in(base_key, shard), w, op)

    def sum_fn(x):
        return jax.lax.psum(x, AXIS)

    step = ot.make_step_fn(cfg, n_local=n_local, ids_fn=ids_fn,
                           key_fn=key_fn, sum_fn=sum_fn,
                           emit_fn=emit_routed)

    def poll_shard(st: ot.OverlayTickState, base_key):
        st = st._replace(win_makeups=jnp.zeros((), I32),
                         win_breakups=jnp.zeros((), I32))
        return jax.lax.fori_loop(
            0, steps, lambda _, x: step(x, base_key), st)

    specs = overlay_tick_state_specs()
    return jax.jit(_shard_map(mesh, poll_shard, in_specs=(specs, P()),
                              out_specs=specs), donate_argnums=(0,))
