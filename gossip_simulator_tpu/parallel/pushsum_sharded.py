"""Mesh-sharded PushSum engine: numeric mass gossip across shards.

Same scaffolding as the sharded event engine (parallel/event_sharded.py):
each shard drains its own slot of the mail ring locally with the SUM
combine, and the emission routes (value, weight) mass shares to their
destination's owner shard over `lax.all_to_all` -- the mass limbs ride
exchange.route_multi as extra int32 columns next to the packed wire word,
exactly the multi-rumor word-column path.

Shard invariance is STRONGER here than for SI: the event engine shard-
folds its crash/drop/delay streams (trajectories differ by shard count,
distributionally matched), but pushsum draws only (tick, GLOBAL id)-keyed
delays off the UNFOLDED base key (models/pushsum.emit_shares) and its
deposits are integer adds, which commute -- so S=1 and S=8 produce
BIT-IDENTICAL mass states, the property tests/test_pushsum.py pins and
the reshard-resume acceptance criterion rides on.

Collective agreement: drain chunk counts are pmax-agreed; the
convergence count psums and the max relative error pmaxes, so the
replicated scalars (total_received, relerr_ppb, eps_tick) match every
shard.  Zero-loss accounting: route overflow -> exchange_overflow, slot
overflow -> mail_dropped, both psum'd -- either being nonzero means
destroyed mass, and the conservation tests assert both stay 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from gossip_simulator_tpu import scenario as _scen
from gossip_simulator_tpu.config import Config
from gossip_simulator_tpu.models import event, graphs, pushsum
from gossip_simulator_tpu.models.pushsum import LIMBS, PushSumState
from gossip_simulator_tpu.models.state import in_flight, msg64_add
from gossip_simulator_tpu.parallel import event_sharded, exchange
from gossip_simulator_tpu.parallel.mesh import AXIS, shard_size

I32 = jnp.int32


def pushsum_state_specs(cfg: Config) -> PushSumState:
    return PushSumState(
        flags=P(AXIS),
        friends=P(AXIS, None), friend_cnt=P(AXIS),
        mass=P(AXIS, None),
        mail_ids=P(AXIS), mail_mass=P(AXIS, None),
        mail_cnt=P(AXIS, None), sup_cnt=P(AXIS, None),
        tick=P(), total_message=P(), total_received=P(), total_crashed=P(),
        mail_dropped=P(), exchange_overflow=P(),
        down_since=P(AXIS) if cfg.faults_enabled else P(),
        scen_crashed=P(), scen_recovered=P(), part_dropped=P(),
        heal_repaired=P(),
        relerr_ppb=P(), eps_tick=P(),
        # Per-shard exchange counters stack to (S, S+2); the 1x1
        # off-path placeholder splits the same way to (S, 1).
        exch_counts=P(AXIS, None),
    )


def _shard_map(mesh, fn, in_specs, out_specs):
    from gossip_simulator_tpu.parallel.mesh import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def make_sharded_pushsum_init(cfg: Config, mesh):
    """Per-shard graph slice + pushsum state; the row-keyed graph
    generators and the gid-keyed mass hash make this bit-identical to
    slicing a single-device init."""
    n_local = shard_size(cfg.n, mesh)
    n_shards = mesh.shape[AXIS]

    def init_shard():
        shard = jax.lax.axis_index(AXIS)
        key = graphs.graph_key(cfg)
        friends, cnt = graphs.generate(cfg, key, row0=shard * n_local,
                                       rows=n_local)
        return pushsum.init_state(cfg, friends, cnt, gid0=shard * n_local,
                                  n_shards=n_shards)

    return jax.jit(_shard_map(mesh, init_shard, in_specs=(),
                              out_specs=pushsum_state_specs(cfg)))


def _mass_append(cfg: Config, n_local: int, mail, mailm, cnt, dropped,
                 payload, rows, wslot, valid):
    """Local ring append of packed entries + their mass rows (pushsum's
    slot geometry, not the event engine's)."""
    from gossip_simulator_tpu.ops.mailbox import ring_append

    dw = pushsum.ring_windows(cfg)
    cap = (mail.shape[0] - pushsum.ring_tail(cfg, n_local)) // dw
    (mail, mailm), cnt, dropped = ring_append(
        (mail, mailm), cnt, dropped, (payload, rows), wslot, valid, dw,
        cap, kernel=cfg.deliver_kernel_resolved)
    return mail, mailm, cnt, dropped


def _route_append_mass(cfg: Config, s: int, n_local: int, mail, mailm,
                       cnt, dropped, xovf, dst_global, wslot, off, valid,
                       rcap, share, phase2: str = "xla"):
    """Route mass shares to their owner shards and append.  The 1-device
    mesh appends directly (the route is the identity there -- same
    DIRECT_SELF_APPEND argument as the event engine, and what makes the
    S=1 sharded ring bit-identical to the single-device one)."""
    b = pushsum.batch_ticks(cfg)
    dw = pushsum.ring_windows(cfg)
    if s == 1 and event_sharded.DIRECT_SELF_APPEND:
        mail, mailm, cnt, dropped = _mass_append(
            cfg, n_local, mail, mailm, cnt, dropped,
            dst_global * b + off, share, wslot, valid)
        return mail, mailm, cnt, dropped, xovf
    xo, exch = exchange.ovf_split(xovf)
    dest = jnp.where(valid, dst_global // n_local, s)
    wire = jnp.where(
        valid, (dst_global % n_local) * (dw * b) + wslot * b + off, -1)
    payloads = (wire,) + tuple(share[:, i] for i in range(share.shape[1]))
    out = exchange.route_multi(payloads, dest, valid, s, rcap,
                               traffic=exch)
    (recvs, ovf), exch = out[:2], (out[2] if exch is not None else None)
    recv = recvs[0]
    if phase2 == "pallas":
        # Phase-2 megakernel receive side: decode + ring append of the
        # routed mass rows as one pass (garbage -1-fill columns in empty
        # wire slots are never written -- same gate as the stray-add
        # guard below).
        from gossip_simulator_tpu.ops import pallas_megakernel as mk
        cap = (mail.shape[0] - pushsum.ring_tail(cfg, n_local)) // dw
        mail, cnt, dropped, _, mailm = mk.fused_recv_land(
            mail, cnt, dropped, recv, dw=dw, cap=cap, b=b,
            words=jnp.stack(recvs[1:], axis=1), mail_words=mailm)
        return mail, mailm, cnt, dropped, exchange.ovf_join(xo + ovf, exch)
    rvalid = recv >= 0
    r = jnp.maximum(recv, 0)
    rdstl = r // (dw * b)
    rw = (r // b) % dw
    roff = r % b
    # Empty wire slots carry the -1 fill in every column; gate their
    # garbage mass out (a stray add would CREATE mass).
    rrows = jnp.where(rvalid[:, None], jnp.stack(recvs[1:], axis=1), 0)
    mail, mailm, cnt, dropped = _mass_append(
        cfg, n_local, mail, mailm, cnt, dropped, rdstl * b + roff, rrows,
        rw, rvalid)
    return mail, mailm, cnt, dropped, exchange.ovf_join(xo + ovf, exch)


def make_sharded_pushsum_step(cfg: Config, mesh):
    """One B-tick window transition per shard (shard_map body)."""
    from gossip_simulator_tpu.ops.mailbox import deposit_sum

    s = mesh.shape[AXIS]
    n_local = shard_size(cfg.n, mesh)
    b = pushsum.batch_ticks(cfg)
    dw = pushsum.ring_windows(cfg)
    cap = pushsum.slot_cap(cfg, n_local)
    ccap = pushsum.drain_chunk(cfg, n_local)
    dim = cfg.pushsum_dim
    C = pushsum.mass_cols(cfg)
    eps = float(cfg.pushsum_eps)
    tgt = pushsum.eps_target(cfg)
    dkern = cfg.deliver_kernel_resolved
    p2 = cfg.phase2_kernel_resolved
    scen = cfg.scenario_resolved
    k = cfg.graph_width
    if n_local * dw * b >= 2 ** 31:
        raise ValueError(
            f"wire packing overflow: n_local ({n_local}) * dw ({dw}) * B "
            f"({b}) must stay below 2^31; use more shards")
    # Every live node emits <= k lanes per window; the per-pair route
    # buffer uses the event-heal zero-loss-leaning bound (overflow is
    # counted, and the conservation tests assert it stays 0).
    rcap = min(exchange.epidemic_cap(n_local, k, s), n_local * k)
    spatial = cfg.telemetry_spatial_enabled and s > 1

    def step_shard(st: PushSumState, base_key: jax.Array) -> PushSumState:
        shard = jax.lax.axis_index(AXIS)
        gids = shard * n_local + jnp.arange(n_local, dtype=I32)
        # Scenario faults: (window, GLOBAL-id)-keyed off the UNFOLDED
        # base key -- identical schedule at any shard count.
        flags, down, dsc, dsr = event.apply_fault_window_flags(
            cfg, st.flags, st.down_since, st.tick, gids, base_key, b)
        slot = (st.tick // b) % dw
        m = st.mail_cnt[0, slot]
        if p2 == "pallas":
            # Phase-2 megakernel: whole-slot fused drain.  The static
            # full-cap scan subsumes the pmax-agreed chunk count (every
            # shard runs the same trip count by construction; masked
            # lanes add zero, and integer adds commute).
            from gossip_simulator_tpu.ops import pallas_megakernel as mk
            mass = mk.fused_drain_sum(st.mass, st.mail_ids, st.mail_mass,
                                      slot, m, cap=cap, b=b)
        else:
            # pmax-agreed chunk count: every shard runs the same loop
            # trip count (shards with fewer entries deposit masked
            # no-ops).
            chunks = (jax.lax.pmax(m, AXIS) + ccap - 1) // ccap

            def body(j, acc):
                off0 = slot * cap + j * ccap
                ent = jax.lax.dynamic_slice(st.mail_ids, (off0,), (ccap,))
                rows = jax.lax.dynamic_slice(
                    st.mail_mass, (off0, 0), (ccap, C))
                ok = j * ccap + jnp.arange(ccap, dtype=I32) < m
                return deposit_sum(acc, ent // b, rows, ok, kernel=dkern)

            mass = jax.lax.fori_loop(0, chunks, body, st.mass)
        m3 = pushsum._normalize(mass.reshape(n_local, dim + 1, LIMBS))
        crashed = (flags & event.CRASHED) > 0
        rel, rep = pushsum.metric_rel(cfg, m3, crashed)
        conv = rel <= jnp.float32(eps)
        flags = jnp.where(conv, flags | event.RECEIVED,
                          flags & ~event.RECEIVED)
        total_received = jax.lax.psum(conv.sum(dtype=I32), AXIS)
        maxrel = jax.lax.pmax(rep.max(), AXIS)
        new_tick = st.tick + b
        # Eps-band population criterion, same as the single-device step
        # (see the model docstring: the global max need never enter the
        # band on a kout overlay, the coverage target is the contract).
        eps_tick = jnp.where(
            (st.eps_tick < 0) & (total_received >= tgt),
            new_tick, st.eps_tick)
        new_m3, share, dst, wslot, off, lane_valid, blk = \
            pushsum.emit_shares(cfg, m3, crashed, st.friends,
                                st.friend_cnt, st.tick, gids, base_key)
        ddrop = jnp.zeros((), I32)
        xv0 = exchange.ovf_join(jnp.zeros((), I32),
                                st.exch_counts if spatial else None)
        mail, mailm, cnt, ddrop, dxovf = _route_append_mass(
            cfg, s, n_local, st.mail_ids, st.mail_mass, st.mail_cnt,
            ddrop, xv0, dst, wslot, off, lane_valid, rcap,
            share, phase2=p2)
        dxovf, exch_new = exchange.ovf_split(dxovf)
        cnt = cnt.at[0, slot].set(0)
        dm = lane_valid.sum(dtype=I32)
        if scen.has_faults:
            dm, ddrop, dxovf, blk, dsc, dsr = jax.lax.psum(
                (dm, ddrop, dxovf, blk, dsc, dsr), AXIS)
        else:
            dm, ddrop, dxovf, blk = jax.lax.psum(
                (dm, ddrop, dxovf, blk), AXIS)
        if exch_new is not None:
            st = st._replace(exch_counts=exch_new)
        return st._replace(
            flags=flags, down_since=down,
            mass=new_m3.reshape(n_local, C),
            mail_ids=mail, mail_mass=mailm, mail_cnt=cnt,
            mail_dropped=st.mail_dropped + ddrop,
            exchange_overflow=st.exchange_overflow + dxovf,
            tick=new_tick,
            total_message=msg64_add(st.total_message, dm),
            total_received=total_received,
            scen_crashed=st.scen_crashed + dsc,
            scen_recovered=st.scen_recovered + dsr,
            part_dropped=st.part_dropped + blk,
            relerr_ppb=(maxrel * jnp.float32(1e9)).astype(I32),
            eps_tick=eps_tick)

    return step_shard


def make_sharded_pushsum_heal(cfg: Config, mesh):
    """Per-shard rejoin bookkeeping (None when off).  Deliberately no
    edge repair and no waves -- see models/pushsum.make_heal_fn for why
    rewiring strands rebooted nodes' estimates; the shard-local marker
    clear needs no collective, so S=1..S=8 trajectories stay identical
    by construction."""
    if not cfg.overlay_heal_resolved:
        return None

    def heal_shard(st: PushSumState, base_key: jax.Array) -> PushSumState:
        clear = _scen.rejoined_mask(st.down_since)
        return st._replace(down_since=jnp.where(clear, -1, st.down_since))

    return heal_shard


def make_window_fn(cfg: Config, mesh, window: int):
    step = make_sharded_pushsum_step(cfg, mesh)
    heal = make_sharded_pushsum_heal(cfg, mesh)
    steps = max(1, -(-window // pushsum.batch_ticks(cfg)))
    specs = pushsum_state_specs(cfg)

    def window_shard(st: PushSumState, base_key: jax.Array) -> PushSumState:
        st = jax.lax.fori_loop(0, steps, lambda _, x: step(x, base_key), st)
        if heal is not None:
            st = heal(st, base_key)
        return st

    return jax.jit(_shard_map(mesh, window_shard, in_specs=(specs, P()),
                              out_specs=specs), donate_argnums=(0,))


def make_seed_fn(cfg: Config, mesh):
    """No-op (mass exists from init), but still a shard_map identity so
    the stepper's seed call leaves the sharded layout untouched."""
    specs = pushsum_state_specs(cfg)

    def seed_shard(st: PushSumState, base_key: jax.Array) -> PushSumState:
        return st

    return jax.jit(_shard_map(mesh, seed_shard, in_specs=(specs, P()),
                              out_specs=specs), donate_argnums=(0,))


def make_run_to_coverage_fn(cfg: Config, mesh, telemetry: bool = False):
    step = make_sharded_pushsum_step(cfg, mesh)
    heal = make_sharded_pushsum_heal(cfg, mesh)
    specs = pushsum_state_specs(cfg)
    max_steps = cfg.max_rounds
    steps = event.poll_window_steps(cfg)
    b = pushsum.batch_ticks(cfg)
    check_in_flight = not cfg.overlay_heal_resolved

    def cond_live(s, target_count, until):
        live = ((s.total_received < target_count)
                & (s.tick < max_steps) & (s.tick < until))
        if check_in_flight:
            # The ring is empty BEFORE the first emission (seed is a
            # no-op), so the aliveness term only applies past window 0.
            alive = jax.lax.psum(in_flight(s), AXIS) > 0
            live = live & (alive | (s.tick < b))
        return live

    def advance(s, base_key):
        s = jax.lax.fori_loop(0, steps, lambda _, x: step(x, base_key), s)
        if heal is not None:
            s = heal(s, base_key)
        return s

    if telemetry:
        from gossip_simulator_tpu.utils import telemetry as telem

        ihwm = exchange.inflight_hwm(cfg, mesh.shape[AXIS])
        spatial = telem.spatial_spec(cfg, int(mesh.shape[AXIS]))
        hspecs = telem.bundle_specs(spatial, P)

        @functools.partial(jax.jit, donate_argnums=(0, 4))
        def run_t(st: PushSumState, base_key, target_count, until, hist):
            def run_shard(st, base_key, target_count, until, hist):
                def cond(carry):
                    s, _ = carry
                    return cond_live(s, target_count, until)

                def body(carry):
                    s, h = carry
                    s = advance(s, base_key)
                    row = telem.gossip_probe(
                        s, False, psum=lambda x: jax.lax.psum(x, AXIS),
                        pmax=lambda x: jax.lax.pmax(x, AXIS),
                        inflight_hwm=ihwm, relerr=s.relerr_ppb)
                    return s, telem.record_window(
                        h, row, st=s, spec=spatial,
                        shard_index=jax.lax.axis_index(AXIS),
                        gather=lambda x: jax.lax.all_gather(x, AXIS),
                        psum=lambda x: jax.lax.psum(x, AXIS),
                        relerr=s.relerr_ppb)

                return jax.lax.while_loop(cond, body, (st, hist))

            return _shard_map(
                mesh, run_shard,
                in_specs=(specs, P(), P(), P(), hspecs),
                out_specs=(specs, hspecs))(st, base_key, target_count,
                                           until, hist)

        return run_t

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(st: PushSumState, base_key: jax.Array, target_count: jax.Array,
            until: jax.Array) -> PushSumState:
        def run_shard(st, base_key, target_count, until):
            return jax.lax.while_loop(
                lambda s: cond_live(s, target_count, until),
                lambda s: advance(s, base_key), st)

        return _shard_map(mesh, run_shard, in_specs=(specs, P(), P(), P()),
                          out_specs=specs)(st, base_key, target_count, until)

    return run
